"""Benchmark: TPU global balancer vs reference-style stealing heuristics.

Runs the nq and coinop workloads (the BASELINE.md configs) under both
cross-server balancing strategies implemented by this framework:

* steal — the rebuilt reference heuristics (qmstat state broadcast + RFR
  pull stealing), the stand-in for upstream ADLB's behavior;
* tpu — the periodic batched global assignment solve in JAX (the north-star
  architecture from BASELINE.json).

Output contract (round 4): the FULL detail record is printed first for
human auditing, then a COMPACT headline record is printed as the FINAL
stdout line. The driver keeps only the last ~2000 chars of output, so
the final line is guaranteed to fit and parse (round 3's grown detail
line truncated to garbage — BENCH_r03.json "parsed": null). The compact
line carries every headline field plus per-rep spreads so the claims
are auditable from the driver's record alone.

Estimator contract (round 6, VERDICT r5 items 2/5): the BAR metrics —
``vs_baseline`` and the per-workload keys (``nq``/``tsp``/``sudoku``/
``gfmc``/``classic_ratio``) — are the PAIRED per-rep-pair ratio medians
(phase-robust: adjacent interleaved reps share the host's hour-scale
phase, so the per-pair ratio cancels it); the pooled medians remain as
``*_pooled``. The HEADLINE scale rows are the both-modes batch:8
consumer rows ``n64b``/``n128b``; single-fetch scale rows are secondary.
"""

import json
import os
import subprocess
import sys
import time


def _ensure_live_backend(probe_timeout: float = 60.0) -> str:
    """Probe accelerator initialization in a subprocess; fall back to CPU if
    it hangs or fails (a wedged TPU tunnel must degrade, not deadlock the
    benchmark). Returns the platform used."""
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=probe_timeout,
            check=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return os.environ.get("JAX_PLATFORMS", "default")
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu (accelerator unreachable)"


def main() -> None:
    platform = _ensure_live_backend()

    from adlb_tpu.runtime.world import Config
    from adlb_tpu.workloads import coinop, hotspot, nq, trickle

    N = 9
    APPS, SERVERS = 6, 3
    CUTOFF = 3

    def cfg(mode: str) -> Config:
        if mode == "steal":
            # upstream-faithful baseline: the reference's qmstat is a
            # store-and-forward ring token at a fixed 0.1 s interval
            # (reference src/adlb.c:165,806-822,1705-1757); this framework's
            # improved direct-broadcast stealing is reported separately.
            return Config(
                balancer="steal",
                qmstat_mode="ring",
                qmstat_interval=0.1,
                exhaust_check_interval=0.2,
            )
        if mode == "steal_fast":
            return Config(balancer="steal", exhaust_check_interval=0.2)
        return Config(
            balancer="tpu",
            exhaust_check_interval=0.2,
            balancer_max_tasks=256,
            balancer_max_requesters=64,
        )

    # warm the solver (host path) so setup cost stays out of the timing
    from adlb_tpu.balancer.solve import AssignmentSolver

    warm = AssignmentSolver(types=(1,), max_tasks=128, max_requesters=32)
    warm.solve({0: {"tasks": [(1, 1, 1, 1)], "reqs": [(0, 1, None)]}}, None)

    def interleaved(run_one, modes=("steal", "tpu"), reps=3):
        """Alternate modes rep by rep so slow phases of the shared host
        (cron, compiles, co-tenants) hit every mode instead of skewing
        whichever mode ran last; returns {mode: [result, ...]}."""
        out = {m: [] for m in modes}
        for _ in range(reps):
            for m in modes:
                out[m].append(run_one(m))
        return out

    def median_by(rows, key=None):
        """Median-of-reps: robust to one lucky/unlucky draw per mode,
        which best-of is not (a single fast outlier in either mode skews
        the ratio on a noisy shared host)."""
        v = sorted(rows, key=key)
        return v[len(v) // 2]

    # hotspot on the ALL-NATIVE plane: C clients + C++ server daemons, every
    # rank an OS process (no GIL coupling); the Python runtime appears only
    # as the balancer sidecar. 64 app ranks / 16 servers is the scale the
    # one-interpreter harness cannot reach. Work grain 8 ms keeps the
    # single-core host scheduling-bound, not message-bound. Measured FIRST,
    # before half an hour of in-proc worlds accumulates memory pressure
    # that starves 80-process native worlds.
    from adlb_tpu.workloads import hotspot_native

    def native_cfg(mode: str) -> Config:
        if mode == "steal":
            return Config(balancer="steal", qmstat_mode="ring",
                          qmstat_interval=0.1)
        # solver_host_threshold high, matching scripts/scaling_curve.py:
        # the sidecar on THIS host has only the ~90-200 ms tunneled
        # chip, and the default threshold (64 parked requesters) sends
        # exactly the 64-rank row's solves through the tunnel INSIDE
        # the balancer loop — each one stalls the top-up cadence for a
        # tunnel round trip (round 3's 64r tpu wait 29.4% vs the
        # curve's 7.1% was this placement divergence, not noise).
        # On locally attached chips the default adaptive threshold is
        # the right setting; forcing the numpy path here IS the
        # adaptive placement decision for tunnel-attached hardware.
        # (BASELINE.md "Measurement-definition note" records what this
        # means for cross-round comparisons.)
        return Config(balancer="tpu", balancer_max_tasks=2048,
                      balancer_max_requesters=256,
                      solver_host_threshold=10**6)

    # AssertionError included everywhere native worlds are contained: the
    # workload wrappers fail via known-answer asserts, and a single bad
    # rep (lost unit, wrong B&B answer) must burn its own row, not the
    # whole bench record
    _NATIVE_ERRS = (RuntimeError, OSError, TimeoutError, AssertionError)

    def hot_native(mode: str, apps: int, servers: int, n: int,
                   fetch: str = "single", work_us: int = 8000):
        def one():
            r = hotspot_native.run(
                n_tasks=n, work_us=work_us, num_app_ranks=apps,
                nservers=servers, cfg=native_cfg(mode), timeout=300.0,
                fetch=fetch,
            )
            assert r.tasks == n, (
                f"native hotspot {mode}: lost work ({r.tasks})"
            )
            return r

        return one()

    try:
        # task counts follow scripts/scaling_curve.py's sizing formula
        # ((apps-1) consumer-seconds of 8 ms grain ~= 1 s ideal makespan)
        # so these rows and the curve's are the same measurement
        nat16 = interleaved(lambda m: hot_native(m, 16, 4, 1875))
        nat16_steal = median_by(nat16["steal"],
                                key=lambda r: r.tasks_per_sec)
        nat16_tpu = median_by(nat16["tpu"], key=lambda r: r.tasks_per_sec)
        # 5 interleaved reps + medians (round 4, up from 3): an
        # 81-process world on this one-core host has multi-second
        # scheduler slow phases that swing single draws ±30% in BOTH
        # modes, and the wait%% medians this row's scale story rests on
        # need more than a best-of-3 draw
        nat64 = interleaved(lambda m: hot_native(m, 64, 16, 7875),
                            reps=5)
        nat64_steal = median_by(nat64["steal"],
                                key=lambda r: r.tasks_per_sec)
        nat64_tpu = median_by(nat64["tpu"], key=lambda r: r.tasks_per_sec)
        native_rows = {
            "native_16r_steal_tasks_per_sec": round(
                nat16_steal.tasks_per_sec, 1),
            "native_16r_tpu_tasks_per_sec": round(nat16_tpu.tasks_per_sec, 1),
            "native_16r_ratio": round(
                nat16_tpu.tasks_per_sec / nat16_steal.tasks_per_sec, 3),
            "native_16r_steal_idle_pct": round(nat16_steal.idle_pct, 1),
            "native_16r_tpu_idle_pct": round(nat16_tpu.idle_pct, 1),
            "native_64r_steal_tasks_per_sec": round(
                nat64_steal.tasks_per_sec, 1),
            "native_64r_tpu_tasks_per_sec": round(nat64_tpu.tasks_per_sec, 1),
            "native_64r_ratio": round(
                nat64_tpu.tasks_per_sec / nat64_steal.tasks_per_sec, 3),
            "native_64r_steal_idle_pct": round(nat64_steal.idle_pct, 1),
            "native_64r_tpu_idle_pct": round(nat64_tpu.idle_pct, 1),
            # direct measure of time blocked acquiring work (Reserve+Get),
            # reported alongside the utilization-based idle% (see
            # BASELINE.md "Idle accounting" for the definitions)
            "native_16r_steal_wait_pct": round(nat16_steal.wait_pct, 1),
            "native_16r_tpu_wait_pct": round(nat16_tpu.wait_pct, 1),
            "native_64r_steal_wait_pct": round(nat64_steal.wait_pct, 1),
            "native_64r_tpu_wait_pct": round(nat64_tpu.wait_pct, 1),
            # headline consumers use the single-unit fused fetch; the
            # batched fused fetch is measured right below so the choice
            # stays a recorded measurement, not folklore (VERDICT r4
            # item 7; cadence-interaction caveat in BASELINE.md)
            "native_64r_tpu_fetch_mode": "single",
        }
    except _NATIVE_ERRS as e:
        # no C toolchain (or daemon spawn failure): report, don't die
        native_rows = {"native_error": repr(e)}

    # batched fused fetch delta at 64 ranks, interleaved against fresh
    # single-unit reps (not the headline pool above) so the pair shares
    # slow phases. Own try: a failure here must not discard the headline
    # rows already measured above.
    try:
        natb = interleaved(
            lambda m: hot_native("tpu", 64, 16, 7875,
                                 fetch="single" if m == "one" else "batch:8"),
            modes=("one", "batch"),
        )
        nb_one = median_by(natb["one"], key=lambda r: r.tasks_per_sec)
        nb_batch = median_by(natb["batch"], key=lambda r: r.tasks_per_sec)
        native_rows.update({
            "native_64r_tpu_batch8_tasks_per_sec": round(
                nb_batch.tasks_per_sec, 1),
            "native_64r_tpu_single_paired_tasks_per_sec": round(
                nb_one.tasks_per_sec, 1),
            "native_batch_fetch_delta_pct": round(
                100.0 * (nb_batch.tasks_per_sec / nb_one.tasks_per_sec - 1.0),
                1) if nb_one.tasks_per_sec else 0.0,
        })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_batch_error", repr(e))

    # 64 ranks, BOTH modes on the batched fused fetch — the HEADLINE
    # 64-rank scale row since round 6 (VERDICT r5 item 2: the batched
    # consumer is the framework's own best path and the measured scale
    # story; the single-fetch rows above stay as secondary continuity
    # metrics). Identical call in both modes; batching only pays for
    # units the balancer pre-positioned locally — that asymmetry IS the
    # balancing advantage being measured.
    try:
        nb64 = interleaved(
            lambda m: hot_native(m, 64, 16, 7875, fetch="batch:8"),
        )
        nb64_steal = median_by(nb64["steal"],
                               key=lambda r: r.tasks_per_sec)
        nb64_tpu = median_by(nb64["tpu"], key=lambda r: r.tasks_per_sec)
        native_rows.update({
            "native_64r_batch8_steal_tasks_per_sec": round(
                nb64_steal.tasks_per_sec, 1),
            "native_64r_batch8_tpu_tasks_per_sec": round(
                nb64_tpu.tasks_per_sec, 1),
            "native_64r_batch8_ratio": round(
                nb64_tpu.tasks_per_sec / nb64_steal.tasks_per_sec, 3)
            if nb64_steal.tasks_per_sec else 0.0,
            "native_64r_batch8_steal_wait_pct": round(
                nb64_steal.wait_pct, 1),
            "native_64r_batch8_tpu_wait_pct": round(
                nb64_tpu.wait_pct, 1),
            "native_64r_batch8_steal_reps": [
                round(r.tasks_per_sec) for r in nb64["steal"]],
            "native_64r_batch8_tpu_reps": [
                round(r.tasks_per_sec) for r in nb64["tpu"]],
        })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_64r_batch_error", repr(e))

    # 128 ranks on the framework's own best consumer path: BOTH modes on
    # the batched fused fetch (identical call; batching only pays for
    # units the balancer pre-positioned locally — that asymmetry IS the
    # balancing advantage). 24 ms grain as in scripts/scaling_curve.py's
    # 128-rank row (8 ms at 161 processes is kernel-scheduling-bound on
    # this one-core host). Measured 2026-07-31 development run: steal
    # 2486 vs tpu 3732 → 1.501, tpu wait 1.7-12.5%.
    try:
        nb128 = interleaved(
            lambda m: hot_native(m, 128, 32, 5291, fetch="batch:8",
                                 work_us=24000),
        )
        nb128_steal = median_by(nb128["steal"],
                                key=lambda r: r.tasks_per_sec)
        nb128_tpu = median_by(nb128["tpu"], key=lambda r: r.tasks_per_sec)
        native_rows.update({
            "native_128r_batch8_steal_tasks_per_sec": round(
                nb128_steal.tasks_per_sec, 1),
            "native_128r_batch8_tpu_tasks_per_sec": round(
                nb128_tpu.tasks_per_sec, 1),
            "native_128r_batch8_ratio": round(
                nb128_tpu.tasks_per_sec / nb128_steal.tasks_per_sec, 3)
            if nb128_steal.tasks_per_sec else 0.0,
            "native_128r_batch8_steal_wait_pct": round(
                nb128_steal.wait_pct, 1),
            "native_128r_batch8_tpu_wait_pct": round(
                nb128_tpu.wait_pct, 1),
            "native_128r_batch8_steal_reps": [
                round(r.tasks_per_sec) for r in nb128["steal"]],
            "native_128r_batch8_tpu_reps": [
                round(r.tasks_per_sec) for r in nb128["tpu"]],
        })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_128r_batch_error", repr(e))

    # THE north-star workloads at native scale (VERDICT r4 item 1:
    # BASELINE.json names nq and tsp at 256 MPI ranks; 128 ranks is this
    # one-core host's measurable ceiling, scripts/sim_scale.py carries the
    # extrapolation) — real B&B/DFS compute, known-answer validated every
    # rep, 3 interleaved reps with medians.
    try:
        from adlb_tpu.workloads import nq_native, tsp_native

        def nq_scale_one(mode, apps, servers):
            def one():
                r = nq_native.run(
                    n=13, cutoff=3, num_app_ranks=apps, nservers=servers,
                    cfg=native_cfg(mode), timeout=420.0,
                )
                assert r.solutions == r.expected, (
                    f"nq {mode}@{apps}: {r.solutions} != {r.expected}"
                )
                return r

            return one()

        def tsp_scale_one(mode, apps, servers):
            def one():
                r = tsp_native.run(
                    n_cities=9, num_app_ranks=apps, nservers=servers,
                    cfg=native_cfg(mode), timeout=420.0,
                )
                assert r.best == r.optimum, (
                    f"tsp {mode}@{apps}: {r.best} != {r.optimum}"
                )
                return r

            return one()

        for apps, servers, tag in ((64, 16, "64r"), (128, 32, "128r")):
            for name, one in (("nq", nq_scale_one), ("tsp", tsp_scale_one)):
                # tsp@64r gets 5 reps: it is the one row whose ratio has
                # sat below 1.0, and B&B draws swing ±30% — the interval
                # needs more than a best-of-3 median
                nreps = 5 if (name == "tsp" and tag == "64r") else 3
                try:
                    runs = interleaved(lambda m: one(m, apps, servers),
                                       reps=nreps)
                except _NATIVE_ERRS as e:
                    # per-row containment: one bad scale row must not
                    # discard the remaining rows
                    native_rows[f"native_{name}_{tag}_error"] = repr(e)
                    continue
                st = median_by(runs["steal"], key=lambda r: r.tasks_per_sec)
                tp = median_by(runs["tpu"], key=lambda r: r.tasks_per_sec)
                native_rows.update({
                    f"native_{name}_{tag}_steal_tasks_per_sec": round(
                        st.tasks_per_sec, 1),
                    f"native_{name}_{tag}_tpu_tasks_per_sec": round(
                        tp.tasks_per_sec, 1),
                    f"native_{name}_{tag}_ratio": round(
                        tp.tasks_per_sec / st.tasks_per_sec, 3)
                    if st.tasks_per_sec else 0.0,
                    f"native_{name}_{tag}_steal_wait_pct": round(
                        st.wait_pct, 1),
                    f"native_{name}_{tag}_tpu_wait_pct": round(
                        tp.wait_pct, 1),
                    # per-rep spreads (full record only): every scale
                    # claim auditable from the BENCH file alone
                    f"native_{name}_{tag}_steal_reps": [
                        round(r.tasks_per_sec) for r in runs["steal"]],
                    f"native_{name}_{tag}_tpu_reps": [
                        round(r.tasks_per_sec) for r in runs["tpu"]],
                })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_scale_error", repr(e))

    # trickle on the all-native plane: the dispatch-latency story without
    # any GIL coupling (C clients + C++ daemons; the in-proc probe's twin)
    from adlb_tpu.workloads import trickle_native

    def nat_tric_one(mode):
        if mode == "steal":
            c = Config(balancer="steal", qmstat_mode="ring",
                       qmstat_interval=0.1)
        else:
            c = Config(balancer="tpu", balancer_max_tasks=512,
                       balancer_max_requesters=64)
        return trickle_native.run(
            n_tasks=240, num_app_ranks=8, nservers=4, cfg=c, timeout=120.0,
        )

    try:
        nt_runs = interleaved(nat_tric_one)
        nt_steal = median_by(nt_runs["steal"],
                             key=lambda r: r.dispatch_p50_ms)
        nt_tpu = median_by(nt_runs["tpu"], key=lambda r: r.dispatch_p50_ms)
        native_rows.update({
            "native_trickle_p50_ms_steal": round(nt_steal.dispatch_p50_ms, 2),
            "native_trickle_p50_ms_tpu": round(nt_tpu.dispatch_p50_ms, 2),
            "native_trickle_p90_ms_steal": round(nt_steal.dispatch_p90_ms, 2),
            "native_trickle_p90_ms_tpu": round(nt_tpu.dispatch_p90_ms, 2),
            "native_dispatch_speedup": round(
                nt_steal.dispatch_p50_ms / nt_tpu.dispatch_p50_ms, 2)
            if nt_tpu.dispatch_p50_ms else 0.0,
        })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_error", repr(e))

    # coinop on the all-native plane: the fork's own pop-latency probe
    # (reference examples/coinop.cpp) — flooded pool, so p50/p95 measure
    # pure pop service latency through the C client + C++ daemon path
    from adlb_tpu.workloads import coinop_native

    def nat_coin_one(mode):
        return coinop_native.run(
            n_tokens=400, num_app_ranks=8, nservers=4,
            cfg=native_cfg(mode), timeout=120.0,
        )

    try:
        nc_runs = interleaved(nat_coin_one)
        nc_steal = median_by(nc_runs["steal"],
                             key=lambda r: r.latency_p50_ms)
        nc_tpu = median_by(nc_runs["tpu"], key=lambda r: r.latency_p50_ms)
        native_rows.update({
            "native_coinop_p50_ms_steal": round(nc_steal.latency_p50_ms, 3),
            "native_coinop_p50_ms_tpu": round(nc_tpu.latency_p50_ms, 3),
            "native_coinop_p95_ms_steal": round(nc_steal.latency_p95_ms, 3),
            "native_coinop_p95_ms_tpu": round(nc_tpu.latency_p95_ms, 3),
        })
    except _NATIVE_ERRS as e:
        native_rows.setdefault("native_coinop_error", repr(e))

    def nq_one(mode):
        r = nq.run(
            n=N, num_app_ranks=APPS, nservers=SERVERS,
            max_depth_for_puts=CUTOFF, cfg=cfg(mode), timeout=600.0,
        )
        assert r.solutions == nq.KNOWN_SOLUTIONS[N], (
            f"{mode}: wrong answer {r.solutions}"
        )
        return r

    nq_runs = interleaved(nq_one, reps=5)
    steal = median_by(nq_runs["steal"], key=lambda r: r.tasks_per_sec)
    tpu = median_by(nq_runs["tpu"], key=lambda r: r.tasks_per_sec)

    # tsp: the other BASELINE.json-named workload (branch-and-bound with
    # broadcast bound updates; compute-bound like nq at this scale).
    # n_cities=10 so the run is long enough (~3.5 s) that the 0.2 s
    # exhaustion-termination quantum stays noise (<5%); pooled per-rep
    # medians like sudoku/gfmc — B&B node counts are nondeterministic
    # run to run in both modes.
    from adlb_tpu.workloads import tsp

    TSP_N = 10
    tsp_want = tsp.brute_force_optimum(
        tsp.dist_matrix(tsp.make_cities(TSP_N, seed=3))
    )

    def tsp_one(mode):
        r = tsp.run(n_cities=TSP_N, num_app_ranks=APPS, nservers=SERVERS,
                    seed=3, cfg=cfg(mode), timeout=600.0)
        assert r.best == tsp_want, f"tsp {mode}: {r.best} != {tsp_want}"
        return (r.tasks_processed, r.elapsed)

    def pooled(rows):
        """Median of per-rep RATES. Each rep's tasks/elapsed already
        normalizes B&B search-luck node-count swings (both modes); the
        median then drops the one-stuck-rep failure mode that a
        total-tasks/total-time pool has, where a single run caught in a
        host slow phase dominates the denominator (observed: a 5-rep
        sudoku pool swinging 0.83-0.97 on the same code)."""
        return median_by([t / s for t, s in rows])

    # 7 reps (round 4, up from 5): B&B search-luck rates swing ±30% per
    # rep in both modes and recorded draws put the 5-rep pooled median
    # anywhere in 0.86-1.07
    tsp_runs = interleaved(tsp_one, reps=7)
    tsp_steal = pooled(tsp_runs["steal"])
    tsp_tpu = pooled(tsp_runs["tpu"])

    # sudoku + gfmc (the self-checking GFMC mini-app economy, reference
    # examples/c4.c): the remaining reference-named workloads, mode vs mode
    from adlb_tpu.workloads import gfmc, sudoku

    # 17-clue grid: enough search that the run is not over in one burst.
    # First-solution search luck swings node counts per run, so the rate
    # is the median of per-rep rates (see pooled()), not best-of.
    SUDOKU_HARD = (
        "000000010400000000020000000000050407008000300001090000"
        "300400200050100000000806000"
    )

    def sudoku_one(mode):
        r = sudoku.run(puzzle=SUDOKU_HARD, num_app_ranks=APPS,
                       nservers=SERVERS, cfg=cfg(mode), timeout=600.0,
                       n_puzzles=8)
        assert r.valid, f"sudoku {mode}: invalid solution"
        return (r.tasks_processed, r.elapsed)

    # first-solution search luck swings node counts per run, so the rate
    # is the median of per-rep rates (see pooled()); 7 reps (round 4,
    # up from 5): recorded draws swing +-40% per rep in BOTH modes
    # (round-4 dress: steal 4860-8323/s within one run's reps), and a
    # 5-rep median leaves the pooled ratio a two-bad-draw lottery
    sudoku_runs = interleaved(sudoku_one, reps=7)
    sudoku_steal = pooled(sudoku_runs["steal"])
    sudoku_tpu = pooled(sudoku_runs["tpu"])

    def gfmc_one(mode):
        r = gfmc.run(num_a=400, bs_per_a=8, cs_per_b=5,
                     num_app_ranks=APPS, nservers=SERVERS,
                     cfg=cfg(mode), timeout=600.0)
        assert r.ok, f"gfmc {mode}: wrong counts {r.counts}"
        return (r.tasks_processed, r.elapsed)

    # 9 reps (round 4, up from 7): gfmc's pooled ratio swung 0.87-1.00
    # across 5-rep draws on this host's hour-scale slow phases, and a
    # round-4 rehearsal drew 0.934 when one slow phase crushed two
    # adjacent reps in both modes; the wider pool tightens the median
    gfmc_runs = interleaved(gfmc_one, reps=9)
    gfmc_steal = pooled(gfmc_runs["steal"])
    gfmc_tpu = pooled(gfmc_runs["tpu"])

    # hotspot: all work enters one server, consumers everywhere — the
    # balancing scenario ADLB exists for; makespan-based, GIL-free work.
    # 16 ranks / 8 servers: enough ring hops that upstream's gossip
    # staleness shows, while staying under the one-interpreter message cap
    HOT_APPS, HOT_SERVERS, HOT_N = 16, 8, 1200

    def hot_one(mode, fused=True):
        r = hotspot.run(
            n_tasks=HOT_N, work_time=0.004, num_app_ranks=HOT_APPS,
            nservers=HOT_SERVERS, cfg=cfg(mode), timeout=300.0, fused=fused,
        )
        assert r.tasks == HOT_N, f"hotspot {mode}: lost work ({r.tasks})"
        return r

    # the headline row: 7 reps — its median sets vs_baseline, and single
    # draws swing ±5% with the host's hour-scale phases. Consumers use
    # the fused get_work call (one round trip when the unit is local):
    # both modes issue the identical call, so the mode that pre-positions
    # work locally is paid for the locality it created.
    hot_runs = interleaved(hot_one, modes=("steal", "steal_fast", "tpu"),
                           reps=7)
    hot_steal = median_by(hot_runs["steal"], key=lambda r: r.tasks_per_sec)
    hot_fast = median_by(hot_runs["steal_fast"],
                         key=lambda r: r.tasks_per_sec)
    hot_tpu = median_by(hot_runs["tpu"], key=lambda r: r.tasks_per_sec)
    steal_idle_med = median_by([r.idle_pct for r in hot_runs["steal"]])
    tpu_idle_med = median_by([r.idle_pct for r in hot_runs["tpu"]])

    # continuity row: the two-call Reserve+Get consumer loop benchmarked in
    # rounds 1-2 (the reference's only consumer shape), so the fused-loop
    # switch above stays auditable against earlier BENCH_r* files.
    # 7 reps (round 4): ~1 draw in 3 hits a host slow phase and collapses
    # the tpu side 20-25% (a round-4 rehearsal drew two adjacent
    # collapsed reps); the median must survive two bad draws
    hcl_runs = interleaved(lambda m: hot_one(m, fused=False), reps=7)
    hcl_steal = median_by(hcl_runs["steal"], key=lambda r: r.tasks_per_sec)
    hcl_tpu = median_by(hcl_runs["tpu"], key=lambda r: r.tasks_per_sec)
    hcl_steal_idle = median_by([r.idle_pct for r in hcl_runs["steal"]])
    hcl_tpu_idle = median_by([r.idle_pct for r in hcl_runs["tpu"]])

    # trickle: steady arrival at one server, consumers elsewhere — isolates
    # dispatch (discovery) latency, the structural gap between gossip-driven
    # stealing and the event-driven global solve
    def tric_one(mode):
        return trickle.run(
            n_tasks=200, interval=0.01, group=2, work_time=0.002,
            num_app_ranks=8, nservers=4, cfg=cfg(mode), timeout=300.0,
        )

    # plan age = staleness of the snapshot state each enacted plan was
    # computed from; collected over the tpu trickle reps (steal worlds run
    # no engine rounds, so interleaving leaves the samples pure)
    from adlb_tpu.balancer.engine import drain_plan_ages

    drain_plan_ages()
    tric_runs = interleaved(tric_one, modes=("steal", "steal_fast", "tpu"))
    ages = sorted(drain_plan_ages())
    tric_steal = median_by(tric_runs["steal"],
                           key=lambda r: r.dispatch_p50_ms)
    tric_fast = median_by(tric_runs["steal_fast"],
                          key=lambda r: r.dispatch_p50_ms)
    tric_tpu = median_by(tric_runs["tpu"], key=lambda r: r.dispatch_p50_ms)

    # pipelined consumer (get_work_stream depth=4) vs the blocking
    # two-call loop above, both balancer modes, paired interleaved reps:
    # the data-plane PR's dispatch-latency claim (remote fused fetch
    # removes the GET_RESERVED leg; the stream removes the re-park gap)
    # measured as a first-class metric rather than folklore. The steal
    # side runs in BROADCAST mode (steal_fast — the framework's own
    # steal path, where the empty->nonempty event qmstat lands): under
    # the upstream-faithful 0.1 s ring, dispatch is gossip-cadence-bound
    # and no consumer shape can move it — that row stays the ring
    # baseline above.
    def tric_pipe_one(mode):
        return trickle.run(
            n_tasks=200, interval=0.01, group=2, work_time=0.002,
            num_app_ranks=8, nservers=4, cfg=cfg(mode), timeout=300.0,
            consumer="stream", stream_depth=4,
        )

    tric_pipe_runs = interleaved(tric_pipe_one, modes=("steal_fast", "tpu"))
    tric_pipe_steal = median_by(tric_pipe_runs["steal_fast"],
                                key=lambda r: r.dispatch_p50_ms)
    tric_pipe_tpu = median_by(tric_pipe_runs["tpu"],
                              key=lambda r: r.dispatch_p50_ms)

    # device solve IN THE LOOP: every balancer round's solve forced
    # through the accelerator (solver_host_threshold=0), so the
    # snapshot->device-solve->plan->enactment pipeline runs end-to-end in
    # the production shape. On THIS host the chip sits behind a ~90 ms
    # tunnel, so the row COSTS dispatch latency vs the adaptive host path
    # above — that is the point of reporting both: the configuration
    # works, and the host/device placement threshold is a latency
    # decision, not a correctness one. On locally attached hardware
    # (~1 ms dispatch) the same configuration is the fast path.
    from adlb_tpu.runtime.world import Config as _Cfg

    dev_err = None
    try:
        from adlb_tpu.balancer.solve import AssignmentSolver as _AS

        warm_dev = _AS(types=(1, 2), max_tasks=256, max_requesters=64,
                       host_threshold_reqs=0)
        warm_dev.solve(
            {0: {"tasks": [(1, 1, 1, 1)], "reqs": [(0, 1, None)]}}, None
        )  # compile at the world's exact shapes
        tric_dev = trickle.run(
            n_tasks=200, interval=0.01, group=2, work_time=0.002,
            num_app_ranks=8, nservers=4,
            cfg=_Cfg(balancer="tpu", exhaust_check_interval=0.2,
                     balancer_max_tasks=256, balancer_max_requesters=64,
                     solver_host_threshold=0),
            timeout=300.0,
        )
        device_rows = {
            "trickle_dispatch_p50_ms_tpu_device_solve": round(
                tric_dev.dispatch_p50_ms, 2),
            "trickle_dispatch_p90_ms_tpu_device_solve": round(
                tric_dev.dispatch_p90_ms, 2),
        }
    except Exception as e:  # noqa: BLE001 — a wedged tunnel must not
        dev_err = repr(e)  # kill the whole bench
        device_rows = {"device_solve_error": dev_err}

    def pct(v, p):
        return v[min(int(p * len(v)), len(v) - 1)] if v else 0.0

    plan_age_p50_ms = round(pct(ages, 0.50) * 1e3, 2)
    plan_age_p90_ms = round(pct(ages, 0.90) * 1e3, 2)

    # solve scale: end-to-end snapshot->pairs latency of the batched global
    # solve at pool sizes far beyond the reference's feasible scale (its
    # 0.1s ring gossip + O(n) scans); device path forced
    def solve_scale(S, K, R, reps=3):
        import numpy as np

        from adlb_tpu.balancer.solve import AssignmentSolver

        rng = np.random.default_rng(0)
        solver = AssignmentSolver(
            types=(1, 2, 3, 4), max_tasks=K, max_requesters=R,
            backend="auto", host_threshold_reqs=0,
        )
        snaps = {}
        for s in range(S):
            snaps[100 + s] = {
                "tasks": [
                    (i + 1, int(rng.integers(1, 5)),
                     int(rng.integers(-50, 50)), 64)
                    for i in range(K)
                ],
                "reqs": [
                    (s * R + i, i + 1, [int(rng.integers(1, 5))])
                    for i in range(R)
                ],
            }
        solver.solve(snaps, None)  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            pairs = solver.solve(snaps, None)
            best = min(best, time.perf_counter() - t0)
        assert len(pairs) == S * R
        return round(best * 1e3, 1)

    import jax as _jax

    on_tpu = _jax.default_backend() not in ("cpu",)
    solve_4k_ms = solve_scale(8, 512, 64)
    solve_16k_ms = solve_scale(16, 1024, 128) if on_tpu else None

    # VERDICT r4 item 8: the kernel's ON-CHIP solve time separated from
    # the tunnel RTT. solve_scale above is end-to-end (snapshot packing +
    # dispatch + kernel + result fetch); here the device arrays are
    # pre-staged, the warmed jitted call is timed around
    # block_until_ready, and the measured null-dispatch round trip (a
    # trivial jitted op on the same device) is subtracted — what remains
    # is kernel execution plus result transfer, the budget that matters
    # on locally attached chips where the tunnel disappears.
    def null_rtt(reps=5):
        """Dispatch round trip of a trivial jitted op: the device-global
        tunnel cost to subtract from every on-chip measurement."""
        import jax.numpy as jnp

        nf = _jax.jit(lambda x: x + 1)
        x = _jax.device_put(jnp.zeros((8,), jnp.int32))
        nf(x).block_until_ready()  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            nf(x).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    def solve_onchip(S, K, R, null_s, reps=5):
        import numpy as np

        import jax.numpy as jnp
        from adlb_tpu.balancer.solve import AssignmentSolver

        rng = np.random.default_rng(0)
        T = 4
        solver = AssignmentSolver(
            types=tuple(range(1, T + 1)), max_tasks=K, max_requesters=R,
            backend="auto", host_threshold_reqs=0,
        )
        fn = solver._device_assign()
        task_prio = rng.integers(-50, 50, size=(S * K,)).astype(np.int32)
        task_type = rng.integers(0, T, size=(S * K,)).astype(np.int32)
        req_mask = np.zeros((S * R, T), dtype=bool)
        req_mask[np.arange(S * R), rng.integers(0, T, S * R)] = True
        req_valid = np.ones((S * R,), dtype=bool)
        args = [
            _jax.device_put(jnp.asarray(a))
            for a in (task_prio, task_type, req_mask, req_valid)
        ]
        fn(*args).block_until_ready()  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(*args).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return round(max(best - null_s, 0.0) * 1e3, 1)

    def solve_chained(nt, nr, k1=10, k2=50, reps=3):
        """Per-solve time via the two-K difference: two jitted chains of
        10 and 50 data-DEPENDENT kernel calls, (T50-T10)/40. The tunnel
        RTT (and any fixed dispatch cost) cancels exactly, which the
        single-dispatch null-subtraction above cannot guarantee — the
        tunnel's RTT varies by tens of ms between samples, and round-5
        re-measurement showed the subtraction overstating the 65k x 8k
        kernel ~4x. The dependency (out[0] & 1 perturbs priorities) stops
        XLA hoisting the loop-invariant solve (out[0] * 0 folds away and
        runs ONE kernel for any K). The K spread must put the signal,
        (k2-k1) x per-solve, well above the tunnel's tens-of-ms RTT
        jitter — the 4k x 512 shape (~0.3 ms/solve) needs a few hundred
        extra solves or the difference drowns (a first draw at 10/50
        measured -0.55 ms)."""
        import numpy as np

        import jax.numpy as jnp
        from adlb_tpu.balancer.pallas_solve import pallas_greedy_assign

        rng = np.random.default_rng(0)
        prio = jnp.asarray(rng.integers(0, 100, nt), jnp.int32)
        ttype = jnp.asarray(rng.integers(0, 8, nt), jnp.int32)
        mask = jnp.asarray(rng.random((nr, 8)) < 0.5)
        valid = jnp.ones((nr,), bool)

        def chain(K):
            @_jax.jit
            def chained(p):
                def step(p, _):
                    out = pallas_greedy_assign(p, ttype, mask, valid)
                    return p + (out[0] & 1).astype(p.dtype), out[0]
                _c, outs = _jax.lax.scan(step, p, None, length=K)
                return outs

            int(chained(prio).sum())  # compile + full sync
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                int(chained(prio).sum())
                best = min(best, time.perf_counter() - t0)
            return best

        return round((chain(k2) - chain(k1)) / (k2 - k1) * 1e3, 2)

    onchip_4k = onchip_65k = null_rtt_ms = None
    chain_4k = chain_65k = None
    if on_tpu:
        try:
            null_s = null_rtt()
            null_rtt_ms = round(null_s * 1e3, 1)
            onchip_4k = solve_onchip(8, 512, 64, null_s)
            onchip_65k = solve_onchip(16, 4096, 512, null_s, reps=3)
        except Exception as e:  # noqa: BLE001 — tunnel wedge must not kill
            device_rows.setdefault("device_solve_error", repr(e))
        # separate containment: a failure here must not discard the
        # legacy rows measured above
        try:
            chain_4k = solve_chained(4096, 512, k1=10, k2=410)
            chain_65k = solve_chained(65536, 8192)
        except Exception as e:  # noqa: BLE001
            device_rows.setdefault("device_chain_error", repr(e))

    # pop latency (coinop): paired interleaved reps + medians since round
    # 7 — the ~1 ms/pop ceiling this PR attacks needs a draw-robust
    # estimate, not the single run rounds 1-6 recorded
    def coin_one(mode):
        return coinop.run(
            n_tokens=400, num_app_ranks=APPS, nservers=SERVERS,
            cfg=cfg(mode), timeout=300.0,
        )

    coin_runs = interleaved(coin_one)
    lat_steal = median_by(coin_runs["steal"],
                          key=lambda r: r.latency_p50_ms)
    lat_tpu = median_by(coin_runs["tpu"], key=lambda r: r.latency_p50_ms)

    # server-failover recovery cost (on_server_failure="failover"): an
    # 8-rank TCP world (6 apps + 2 servers, real processes) with the
    # NON-master server SIGKILLed mid-workload — records the buddy's
    # detection->promotion MTTR plus the units lost (counted replication
    # lag) / re-executed accounting, so the policy's recovery cost lands
    # in BENCH_*.json instead of folklore. Own containment: a failed row
    # must not discard the rest of the bench.
    def failover_bench():
        import struct

        from adlb_tpu.runtime.transport_tcp import spawn_world as _sw
        from adlb_tpu.types import ADLB_SUCCESS
        from adlb_tpu.types import InfoKey as _IK

        n_units = 160

        def app(ctx):
            if ctx.rank == 0:
                for i in range(n_units):
                    ctx.put(struct.pack("<q", i), 1)
            got = []
            while True:
                rc, w = ctx.get_work([1])
                if rc != ADLB_SUCCESS:
                    return got
                got.append(struct.unpack("<q", w.payload)[0])
                time.sleep(0.002)

        res = _sw(
            6, 2, [1], app,
            cfg=Config(on_server_failure="failover",
                       exhaust_check_interval=0.2,
                       fault_spec={"seed": 9,
                                   "kill_server_at_frame": {1: 80}}),
            timeout=240.0,
        )
        done = [x for v in res.app_results.values() for x in v]
        lost = sum(s.get(int(_IK.FAILOVER_LOST), 0.0)
                   for s in res.server_stats.values())
        mttr = max(
            (s.get(int(_IK.FAILOVER_MTTR_MS), 0.0)
             for s in res.server_stats.values()),
            default=0.0,
        )
        missing = len(set(range(n_units)) - set(done))
        assert missing <= lost, f"{missing} units vanished, {lost} counted"
        return {
            "failover_mttr_ms": round(mttr, 1),
            "failover_units_total": n_units,
            "failover_units_lost": int(lost),
            "failover_units_reexecuted": len(done) - len(set(done)),
            "failover_server_casualties": res.server_casualties,
        }

    try:
        failover_rows = failover_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        failover_rows = {"failover_error": repr(e)[:200]}

    # MASTER-failover recovery cost: the same TCP world but the MASTER
    # is the one SIGKILLed — the ring buddy is the standing deputy and
    # promotes under a bumped fleet epoch (ISSUE 20). Records the median
    # detection->takeover MTTR over 3 worlds (the kill frame halves per
    # retry until the kill lands inside the run, like the chaos draw),
    # plus what the standing deputy costs when nothing dies: wall-clock
    # of an identical in-proc put-storm world with the brain stream on
    # ("failover") vs off ("abort"), as a ratio. Own containment.
    def master_failover_bench():
        import struct

        from adlb_tpu.api import run_world as _rw
        from adlb_tpu.runtime.transport_tcp import spawn_world as _sw
        from adlb_tpu.types import ADLB_SUCCESS
        from adlb_tpu.types import InfoKey as _IK

        n_units = 160

        def app(ctx):
            if ctx.rank == 0:
                for i in range(n_units):
                    ctx.put(struct.pack("<q", i), 1)
            got = []
            while True:
                rc, w = ctx.get_work([1])
                if rc != ADLB_SUCCESS:
                    return got
                got.append(struct.unpack("<q", w.payload)[0])
                time.sleep(0.002)

        mttrs, lost_total = [], 0
        for rep in range(3):
            frame = 80
            for _attempt in range(3):
                res = _sw(
                    6, 2, [1], app,
                    cfg=Config(on_server_failure="failover",
                               exhaust_check_interval=0.2,
                               failover_client_wait=30.0,
                               fault_spec={"seed": 21 + rep,
                                           "kill_server_at_frame":
                                               {0: frame}}),
                    timeout=240.0,
                )
                assert not res.aborted
                done = [x for v in res.app_results.values() for x in v]
                lost = sum(s.get(int(_IK.FAILOVER_LOST), 0.0)
                           for s in res.server_stats.values())
                missing = len(set(range(n_units)) - set(done))
                assert missing <= lost, \
                    f"{missing} units vanished, {lost} counted"
                if res.server_casualties:
                    break
                frame = max(10, frame // 2)
            assert res.server_casualties, "master outlived every retry"
            lost_total += int(lost)
            mttrs.append(max(
                (s.get(int(_IK.FAILOVER_MTTR_MS), 0.0)
                 for s in res.server_stats.values()),
                default=0.0,
            ))

        def storm_s(policy):
            def sapp(ctx):
                if ctx.rank == 0:
                    for i in range(400):
                        ctx.put(struct.pack("<q", i), 1)
                n = 0
                while True:
                    rc, _w = ctx.get_work([1])
                    if rc != ADLB_SUCCESS:
                        return n
                    n += 1

            t0 = time.monotonic()
            _rw(4, 2, [1], sapp,
                cfg=Config(on_server_failure=policy,
                           exhaust_check_interval=0.2),
                timeout=120.0)
            return time.monotonic() - t0

        on = median_by([storm_s("failover") for _ in range(3)])
        off = median_by([storm_s("abort") for _ in range(3)])
        return {
            "master_failover_mttr_ms": round(median_by(mttrs), 1),
            "master_failover_mttr_reps_ms": [round(m, 1) for m in mttrs],
            "master_failover_units_lost": lost_total,
            "brain_repl_on_s": round(on, 3),
            "brain_repl_off_s": round(off, 3),
            "brain_repl_overhead_ratio":
                round(on / off, 3) if off > 0 else 0.0,
        }

    try:
        master_failover_rows = master_failover_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        master_failover_rows = {"master_failover_error": repr(e)[:200]}

    # gray-failure recovery cost (lease_timeout_s armed): a worker
    # SIGSTOPped mid-trickle while holding an unfetched reservation —
    # hang_mttr_ms is stall-to-redelivery (expiry detection + re-enqueue
    # + rematch, measured across processes on the shared CLOCK_MONOTONIC)
    # — and a put storm against a tiny hard-watermarked memory cap,
    # recording that backoff sheds the overload instead of aborting the
    # producer. Own containment, like the failover row.
    def gray_bench():
        import struct

        from adlb_tpu.runtime.faults import sigstop_self
        from adlb_tpu.runtime.transport_tcp import spawn_world as _sw
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        T_W, T_V, T_ANS, T_STALL, T_GO = 1, 2, 3, 4, 5
        lease_s = 0.5

        def hang_app(ctx):
            # rank 1 is the ONLY requester of T_V until it confirms (via
            # the T_GO token) that it HOLDS the marked unit's lease —
            # then it stamps the clock and freezes. Expiry re-enqueues
            # the unit; rank 2 (unblocked by T_GO) stamps its
            # redelivery. Rank 0 waits for BOTH stamps before
            # terminating, so the world can never tear down under the
            # still-stopped victim.
            if ctx.rank == 0:
                assert ctx.put(b"marked", T_V) == _OK
                for i in range(20):  # the trickle around the stall
                    assert ctx.put(struct.pack("<q", i), T_W) == _OK
                stamps = {}
                while len(stamps) < 2:
                    rc, r = ctx.reserve([T_ANS, T_STALL])
                    assert rc == _OK, rc
                    rc, buf = ctx.get_reserved(r.handle)
                    if rc != _OK:
                        continue
                    stamps[r.work_type] = struct.unpack("<d", buf)[0]
                ctx.set_problem_done()
                return (stamps[T_ANS] - stamps[T_STALL]) * 1e3
            if ctx.rank == 1:
                rc, r = ctx.reserve([T_V])
                assert rc == _OK, rc
                assert ctx.put(b"go", T_GO) == _OK
                t_stall = time.monotonic()
                # past worst-case expiry latency (~1.25x lease + scan
                # jitter) but under the 2x hang bar: a declared-dead
                # rank would be excluded from the exhaustion vote and
                # the world could terminate before this stamp lands
                sigstop_self(1.6 * lease_s)
                ctx.get_reserved(r.handle)  # fenced/void: rc != OK
                ctx.put(struct.pack("<d", t_stall), T_STALL,
                        target_rank=0)
                return "stalled"
            rc, r = ctx.reserve([T_GO])  # rank 1 holds the T_V lease now
            assert rc == _OK, rc
            ctx.get_reserved(r.handle)
            got = 0
            while True:
                rc, r = ctx.reserve([T_W, T_V])
                if rc != _OK:
                    return got
                rc, buf = ctx.get_reserved(r.handle)
                if rc != _OK:
                    continue
                if buf == b"marked":  # the redelivered stalled unit
                    ctx.put(struct.pack("<d", time.monotonic()), T_ANS,
                            target_rank=0)
                got += 1
                time.sleep(0.01)

        res = _sw(
            3, 2, [T_W, T_V, T_ANS, T_STALL, T_GO], hang_app,
            cfg=Config(on_worker_failure="reclaim",
                       lease_timeout_s=lease_s,
                       exhaust_check_interval=0.2),
            timeout=120.0,
        )
        mttr_ms = res.app_results[0]
        rows = {"hang_mttr_ms": round(mttr_ms, 1),
                "hang_lease_timeout_ms": lease_s * 1e3}

        def storm_app(ctx):
            n = 80
            if ctx.rank == 0:
                for i in range(n):
                    rc = ctx.put(struct.pack("<q", i) + b"\0" * 56, T_W)
                    assert rc == _OK, rc
                return {"put_backoffs":
                        ctx._c.metrics.value("put_backoffs"),
                        "put_retries": ctx._c.metrics.value("put_retries")}
            got = 0
            while True:
                rc, w = ctx.get_work([T_W])
                if rc != _OK:
                    return got
                got += 1
                time.sleep(0.005)

        res = _sw(
            2, 2, [T_W], storm_app,
            cfg=Config(max_malloc_per_server=512, mem_soft_frac=0.85,
                       mem_hard_frac=0.9, put_max_retries=200,
                       exhaust_check_interval=0.2),
            timeout=120.0,
        )
        rows.update(
            put_storm_units=80,
            put_storm_consumed=res.app_results[1],
            put_storm_backoffs=int(res.app_results[0]["put_backoffs"]),
            put_storm_retries=int(res.app_results[0]["put_retries"]),
        )
        return rows

    try:
        gray_rows = gray_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        gray_rows = {"gray_error": repr(e)[:200]}

    # durable-service recovery cost (Config(wal_dir)): cold restart of a
    # server from its write-ahead log — construction-to-recovered-pool
    # time over a synthetic log of WAL_UNITS 64 B puts, the shard-load +
    # replay path a restarted fleet pays per server. Own containment,
    # like the failover row.
    def service_bench():
        import shutil
        import struct as _struct
        import tempfile

        from adlb_tpu.runtime import wal as _walmod
        from adlb_tpu.runtime.queues import WorkUnit as _WU
        from adlb_tpu.runtime.server import Server as _Server
        from adlb_tpu.runtime.transport import InProcFabric as _Fab
        from adlb_tpu.runtime.world import WorldSpec as _WS

        WAL_UNITS = 2000
        wal_dir = tempfile.mkdtemp(prefix="adlb-bench-wal-")
        try:
            world = _WS(nranks=4, nservers=2, types=(1,))
            w = _walmod.WriteAheadLog(wal_dir, 2, world, fsync_ms=0.0)
            for i in range(WAL_UNITS):
                w.log_put(
                    _WU(seqno=i + 1, work_type=1, prio=0, target_rank=-1,
                        answer_rank=-1,
                        payload=_struct.pack("<q", i) + b"\0" * 56),
                    src=0, put_id=i,
                )
            # a realistic tail: half the pool consumed before the crash
            for i in range(WAL_UNITS // 2):
                w.log_pin(i + 1, 0)
                w.log_consume(i + 1)
            w.tick(time.monotonic(), force=True)
            w.close()
            cfg2 = Config(wal_dir=wal_dir, exhaust_check_interval=0.2)
            # warm the module graph: Server's first construction pulls
            # the balancer (and jax) imports, which would otherwise be
            # billed to the replay measurement
            _Server(_WS(nranks=4, nservers=2, types=(1,)),
                    Config(exhaust_check_interval=0.2), _Fab(4).endpoint(2))
            fabric = _Fab(4)
            t0 = time.monotonic()
            srv = _Server(world, cfg2, fabric.endpoint(2))
            replay_ms = (time.monotonic() - t0) * 1e3
            assert srv.wal_recovered == WAL_UNITS - WAL_UNITS // 2, \
                srv.wal_recovered
            srv.wal.close()
            return {
                "restart_replay_ms": round(replay_ms, 1),
                "restart_replay_units": srv.wal_recovered,
                "restart_replay_log_entries": WAL_UNITS * 2,
            }
        finally:
            shutil.rmtree(wal_dir, ignore_errors=True)

    try:
        service_rows = service_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        service_rows = {"service_error": repr(e)[:200]}

    # shm ring fabric + spill tier (round 7, ROADMAP item 4): pop
    # latency over REAL PROCESSES on the ring fabric vs the identical
    # world on TCP (paired interleaved reps), the >1 MiB payload put
    # row, and the spill tier's fault-in latency + the put-storm
    # acceptance (0 backoffs over a hard-watermarked cap when spill_dir
    # is set, every payload byte-identical). Own containment. NOTE for
    # cross-round reads: on this single-core dev box every cross-process
    # hop pays a scheduler wakeup, so absolute latencies here are
    # scheduling-bound — the fabric's syscall/copy savings show in the
    # batched-consumer row and the large-payload row, and fully on
    # multi-core hosts (the in-proc coinop rows above remain the
    # single-host thread-fabric continuity metric).
    def shm_bench():
        import hashlib
        import shutil
        import struct as _struct
        import tempfile

        from adlb_tpu.runtime.transport_shm import shm_available
        from adlb_tpu.runtime.transport_tcp import spawn_world as _sw
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        if not shm_available():
            return {"shm_note": "no usable /dev/shm; shm rows skipped"}

        def coin_spawn(fabric, consumer="classic"):
            return coinop.run(
                n_tokens=400, num_app_ranks=4, nservers=2,
                cfg=Config(fabric=fabric, exhaust_check_interval=0.25),
                timeout=180.0, spawn=True, consumer=consumer,
            )

        runs = interleaved(lambda f: coin_spawn(f), modes=("shm", "tcp"))
        shm_med = median_by(runs["shm"], key=lambda r: r.latency_p50_ms)
        tcp_med = median_by(runs["tcp"], key=lambda r: r.latency_p50_ms)
        rows = {
            "coinop_shm_p50_ms": round(shm_med.latency_p50_ms, 3),
            "coinop_spawn_tcp_p50_ms": round(tcp_med.latency_p50_ms, 3),
            "coinop_shm_p95_ms": round(shm_med.latency_p95_ms, 3),
            "coinop_spawn_tcp_p95_ms": round(tcp_med.latency_p95_ms, 3),
            "coinop_shm_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["shm"]],
            "coinop_spawn_tcp_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["tcp"]],
        }
        # the framework's own best consumer path on the ring fabric:
        # batched fused fetch amortizes the scheduler round trip
        bat = [coin_spawn("shm", consumer="batch:8") for _ in range(3)]
        bmed = median_by(bat, key=lambda r: r.latency_p50_ms)
        rows["coinop_shm_batch8_p50_ms"] = round(bmed.latency_p50_ms, 3)

        # >1 MiB payload put latency (acked round trip), shm vs tcp —
        # the scatter-gather encode + ring streaming vs loopback TCP
        PAY = 2 << 20
        N_BIG = 24

        def big_app(ctx):
            if ctx.rank == 0:
                lats = []
                blob = b"P" * PAY
                for _i in range(N_BIG):
                    t0 = time.monotonic()
                    assert ctx.put(blob, 1) == _OK
                    lats.append(time.monotonic() - t0)
                return lats
            n = 0
            while True:
                rc, w = ctx.get_work([1])
                if rc != _OK:
                    return n
                assert len(w.payload) == PAY
                n += 1

        def big_one(fabric):
            res = _sw(2, 1, [1], big_app,
                      cfg=Config(fabric=fabric,
                                 exhaust_check_interval=0.25),
                      timeout=180.0)
            lats = sorted(res.app_results[0])
            assert sum(v for k, v in res.app_results.items()
                       if k != 0) == N_BIG
            return lats[len(lats) // 2] * 1e3

        big = interleaved(lambda f: big_one(f), modes=("shm", "tcp"))
        rows["put_large_p50_ms_shm"] = round(median_by(big["shm"]), 2)
        rows["put_large_p50_ms_tcp"] = round(median_by(big["tcp"]), 2)
        rows["put_large_payload_mib"] = PAY >> 20

        # spill tier: store-level fault-in latency for 1 MiB payloads
        from adlb_tpu.runtime.spill import SpillStore

        sdir = tempfile.mkdtemp(prefix="adlb-bench-spill-")
        try:
            store = SpillStore(sdir, 0)
            blob = os.urandom(1 << 20)
            for i in range(32):
                store.put(i, blob)
            lats = []
            for i in range(32):
                t0 = time.monotonic()
                got = store.take(i)
                lats.append(time.monotonic() - t0)
                assert got == blob
            store.close()
            lats.sort()
            rows["spill_faultin_ms"] = round(lats[len(lats) // 2] * 1e3, 3)

            # acceptance storm: ~240 KiB of puts through a 64 KiB
            # hard-watermarked cap WITH spill_dir — must complete with
            # zero ADLB_BACKOFF and byte-identical fetch-back
            N_STORM, SPAY = 60, 4096

            def storm_app(ctx):
                if ctx.rank == 0:
                    sent = {}
                    for i in range(N_STORM):
                        p = _struct.pack("<q", i) + hashlib.sha256(
                            str(i).encode()).digest() * (SPAY // 32)
                        assert ctx.put(p, 1) == _OK
                        sent[i] = hashlib.sha256(p).hexdigest()
                    return {"sent": sent,
                            "backoffs":
                            ctx._c.metrics.value("put_backoffs"),
                            "retries":
                            ctx._c.metrics.value("put_retries")}
                got = {}
                while True:
                    rc, w = ctx.get_work([1])
                    if rc != _OK:
                        return got
                    i = _struct.unpack("<q", w.payload[:8])[0]
                    got[i] = hashlib.sha256(w.payload).hexdigest()
                    time.sleep(0.002)

            res = _sw(3, 2, [1], storm_app,
                      cfg=Config(max_malloc_per_server=64 << 10,
                                 mem_soft_frac=0.7, mem_hard_frac=0.8,
                                 spill_dir=sdir,
                                 exhaust_check_interval=0.25),
                      timeout=180.0)
            prod = res.app_results[0]
            got = {}
            for r, v in res.app_results.items():
                if r != 0:
                    got.update(v)
            rows.update(
                spill_storm_units=N_STORM,
                spill_storm_consumed=len(got),
                spill_storm_backoffs=int(prod["backoffs"]),
                spill_storm_retries=int(prod["retries"]),
                spill_storm_byte_identical=all(
                    got.get(i) == h for i, h in prod["sent"].items()
                ),
            )
        finally:
            shutil.rmtree(sdir, ignore_errors=True)
        return rows

    try:
        shm_rows = shm_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        shm_rows = {"shm_error": repr(e)[:200]}

    # wire-codec microbench (round 8, ROADMAP item 5c): encode+decode
    # per-frame cost of the compiled C codec vs the pure-Python twin on
    # the wire-native frame mix (put/reserve/fused-response/state-delta
    # — the Put/Reserve/Get_reserved hot path's actual traffic shape).
    # codec_encode_us is the bench_guard-guarded row; the speedup rows
    # carry the >=5x acceptance claim. Own containment.
    def codec_bench():
        from adlb_tpu.runtime import codec as codec_mod
        from adlb_tpu.runtime.messages import Tag, msg

        mix = [
            msg(Tag.FA_PUT, 3, payload=b"\xa5" * 1024, work_type=2,
                prio=-7, target_rank=-1, answer_rank=0, common_len=0,
                common_server=-1, common_seqno=-1, put_id=12),
            msg(Tag.TA_PUT_RESP, 5, rc=1, hint=-1, put_id=12),
            msg(Tag.FA_RESERVE, 0, req_types=[1, 2, 9], hang=True,
                rqseqno=42, prefetch=1),
            msg(Tag.TA_RESERVE_RESP, 6, rc=1, work_type=1, prio=3,
                handle=[7, 5, 0, -1, -1], work_len=4096, answer_rank=-1,
                fetch=1, payloads=[b"u" * 4096] * 8,
                work_types=[1] * 8, prios=[0] * 8,
                answer_ranks=[-1] * 8,
                times_on_q=[0.25] * 8),
            msg(Tag.TA_GET_RESERVED_RESP, 6, rc=1, payload=b"w" * 4096,
                time_on_q=0.125),
            msg(Tag.SS_STATE_DELTA, 4, seqnos=list(range(32)),
                work_types=[1] * 32, prios=[0] * 32,
                work_lens=[64] * 32, nbytes=2048),
            msg(Tag.FA_PUT, 1, payload=b"j" * 64, work_type=1, job_id=7),
            msg(Tag.FA_LOCAL_APP_DONE, 1),
        ]
        bodies = [b"".join(bytes(p) for p in
                           codec_mod.encode_binary_iov_py(m)) for m in mix]
        reps = 4000  # x8 frames = 32k encodes per implementation

        def us_per_frame(fn, args):
            best = float("inf")
            for _rep in range(3):
                t0 = time.perf_counter()
                for a in args:
                    for _ in range(reps // 4):
                        fn(a)
                best = min(
                    best,
                    (time.perf_counter() - t0) / (len(args) * (reps // 4)),
                )
            return best * 1e6

        have_c = codec_mod._load_c_codec()
        rows = {"codec_impl": codec_mod.active_codec(),
                "codec_frames_in_mix": len(mix)}
        enc_py = us_per_frame(codec_mod.encode_binary_iov_py, mix)
        dec_py = us_per_frame(codec_mod.decode_binary_py, bodies)
        rows["codec_encode_us_py"] = round(enc_py, 2)
        rows["codec_decode_us_py"] = round(dec_py, 2)
        if have_c:
            enc_c = us_per_frame(codec_mod._c_encode_iov, mix)
            dec_c = us_per_frame(codec_mod._c_decode, bodies)
            rows["codec_encode_us_c"] = round(enc_c, 2)
            rows["codec_decode_us_c"] = round(dec_c, 2)
            rows["codec_encode_speedup"] = round(enc_py / enc_c, 2)
            rows["codec_decode_speedup"] = round(dec_py / dec_c, 2)
        # the GUARDED row is the ACTIVE implementation's cost — what
        # this record's real frames actually paid — so a record that
        # silently fell back to py regresses against a compiled
        # baseline, which is exactly what the guard exists to catch
        active_c = codec_mod.active_codec() == "c" and have_c
        rows["codec_encode_us"] = rows["codec_encode_us_c"] if active_c \
            else round(enc_py, 2)
        rows["codec_decode_us"] = rows["codec_decode_us_c"] if active_c \
            else round(dec_py, 2)
        if not have_c:
            rows["codec_note"] = "compiled codec unavailable; rows are py"
        return rows

    try:
        codec_rows = codec_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        codec_rows = {"codec_error": repr(e)[:200]}

    # multiplexed channel plane (round 8, ROADMAP item 5b): pop latency
    # over REAL PROCESSES with every python<->python frame riding the
    # host broker (tcp_mux="on") vs the identical per-pair world, paired
    # interleaved reps — on a 1-core box both are scheduler-bound (the
    # provenance stamp records that); plus the 8-burst submission row:
    # wall time for an 8-frame burst delivered through one coalesced
    # gather vs eight sequential sends, endpoint-level (no scheduler in
    # the loop). Own containment.
    def mux_bench():
        from adlb_tpu.runtime.channel import ChannelBroker
        from adlb_tpu.runtime.messages import Tag as _Tag
        from adlb_tpu.runtime.messages import msg as _msg

        def coin_mux(mode):
            return coinop.run(
                n_tokens=400, num_app_ranks=4, nservers=2,
                cfg=Config(fabric="tcp", tcp_mux=mode,
                           exhaust_check_interval=0.25),
                timeout=180.0, spawn=True,
            )

        runs = interleaved(lambda m: coin_mux(m), modes=("on", "off"))
        mux_med = median_by(runs["on"], key=lambda r: r.latency_p50_ms)
        tcp_med = median_by(runs["off"], key=lambda r: r.latency_p50_ms)
        rows = {
            "coinop_mux_p50_ms": round(mux_med.latency_p50_ms, 3),
            "coinop_mux_tcp_p50_ms": round(tcp_med.latency_p50_ms, 3),
            "coinop_mux_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["on"]],
            "coinop_mux_tcp_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["off"]],
        }

        # 8-burst submission: one coalesced gather vs 8 sequential sends
        from adlb_tpu.runtime.transport_tcp import TcpEndpoint as _EP

        broker = ChannelBroker()
        a = _EP(0, {0: ("127.0.0.1", 0)}, mux=broker.addr)
        b = _EP(1, {1: ("127.0.0.1", 0)}, mux=broker.addr)
        try:
            frame = _msg(_Tag.FA_PUT, 0, payload=b"b" * 256, work_type=1)

            def burst(batched):
                t0 = time.perf_counter()
                if batched:
                    a.submit_begin()
                for _i in range(8):
                    a.send(1, frame)
                if batched:
                    a.submit_flush()
                got = 0
                while got < 8:
                    if b.recv(timeout=5.0) is not None:
                        got += 1
                return (time.perf_counter() - t0) * 1e3

            for _warm in range(20):
                burst(True)
                burst(False)
            bat = sorted(burst(True) for _ in range(60))
            seq = sorted(burst(False) for _ in range(60))
            rows["mux_burst8_batched_ms"] = round(bat[len(bat) // 2], 3)
            rows["mux_burst8_sequential_ms"] = round(seq[len(seq) // 2], 3)
        finally:
            a.close()
            b.close()
            broker.close()
        return rows

    try:
        mux_rows = mux_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        mux_rows = {"mux_error": repr(e)[:200]}

    # multichip planning-round latency at scale: the sharded balancer's
    # full round (snapshot-delta ingest -> sharded solve -> plan
    # extraction) at 1,000 servers / 100k parked and 10,000 servers /
    # 1M parked on an 8-way host-simulated mesh. Measures the HOST
    # auction tier: on a host-SIMULATED mesh the on-device tier's round
    # is dominated by the 8-way virtual-device dispatch/rendezvous cost
    # (~90 ms/call regardless of scale — see MULTICHIP_r08), which
    # would drown any real regression AND break continuity with the
    # r06-r10 plan_round_1k_ms records; the device tier's correctness
    # is pair-list-fuzzed in CI (tests/test_device_auction.py) and its
    # host-sim latency recorded per MULTICHIP round. Runs in a
    # subprocess so the virtual-mesh provisioning cannot disturb this
    # process's accelerator backend. Own containment.
    def plan_round_bench():
        import subprocess as _sp

        proc = _sp.run(
            [sys.executable, "-m", "adlb_tpu.balancer.plan_bench",
             "--quick", "--auction", "host", "--json-only"],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"plan_bench rc={proc.returncode}: {proc.stderr[-200:]}")
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        by_servers = {r["servers"]: r for r in doc["rows"]}
        big = by_servers.get(1000, doc["rows"][-1])
        out = {
            "plan_round_1k_ms": big["plan_round_p50_ms"],
            "plan_round_1k_p90_ms": big["plan_round_p90_ms"],
            "plan_round_1k_servers": big["servers"],
            "plan_round_1k_parked": big["parked_reqs"],
            "plan_round_sweep_ms": big["device_sweep_ms"],
        }
        huge = by_servers.get(10000)
        if huge is not None:
            out["plan_round_10k_ms"] = huge["plan_round_p50_ms"]
            out["plan_round_10k_p90_ms"] = huge["plan_round_p90_ms"]
            out["plan_round_10k_parked"] = huge["parked_reqs"]
        return out

    try:
        plan_rows = plan_round_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        plan_rows = {"plan_round_error": repr(e)[:200]}

    # host-tier round admission: engine.round() overhead at 1k/10k/100k
    # parked requesters (array-resident ledger vs the pure-Python twin;
    # null solver, so this is purely the admission the host ledger
    # vectorizes). Subprocess-isolated like the plan sweep; needs no
    # devices. Own containment.
    def engine_round_bench():
        import subprocess as _sp

        proc = _sp.run(
            [sys.executable, "-m", "adlb_tpu.balancer.plan_bench",
             "--engine-rounds", "--json-only"],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"engine_rounds rc={proc.returncode}: {proc.stderr[-200:]}")
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        out = {}
        for row in doc["rows"]:
            parked = row["parked_reqs"]
            label = f"{parked // 1000}k"
            out[f"engine_round_us_{label}"] = row["engine_round_us"]
            out[f"engine_round_py_us_{label}"] = row["engine_round_py_us"]
        big = doc["rows"][-1]
        out["engine_round_us"] = big["engine_round_us"]
        out["engine_round_speedup"] = big["speedup"]
        out["ledger_patches"] = big["ledger_patches"]
        out["ledger_resyncs"] = big["ledger_resyncs"]
        # guarded compact key (ms): the 1k-parked admission p50 whose
        # 2.4x floor the stamp-keyed SnapshotStore sync removed
        if "admission_1k_ms" in doc:
            out["admission_1k_ms"] = doc["admission_1k_ms"]
        return out

    try:
        engine_rows = engine_round_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        engine_rows = {"engine_round_error": repr(e)[:200]}

    # unit-lifecycle tracing overhead (round 9, the SLO sensor layer):
    # coinop pop p50 at trace_sample=1.0 (every put journeyed — the
    # worst case), at the DEFAULT sample rate, and at 0.0 (off), paired
    # interleaved reps. trace_overhead_ratio is the DEFAULT-rate/off
    # per-pair median — the ISSUE 13 acceptance bar bench_guard bounds
    # absolutely at 1.05; the full-sampling rows are baseline-relative
    # regression rows. Own containment.
    def trace_overhead_bench():
        default_rate = Config().trace_sample

        def coin_trace(rate):
            return coinop.run(
                n_tokens=400, num_app_ranks=APPS, nservers=SERVERS,
                cfg=Config(balancer="steal", exhaust_check_interval=0.2,
                           trace_sample=rate),
                timeout=300.0,
            )

        rates = {"full": 1.0, "default": default_rate, "off": 0.0}
        runs = interleaved(
            lambda m: coin_trace(rates[m]), modes=tuple(rates),
        )

        def med(mode):
            return median_by(
                runs[mode], key=lambda r: r.latency_p50_ms
            ).latency_p50_ms

        def pair_med(mode):
            pairs = sorted(
                a.latency_p50_ms / b.latency_p50_ms
                for a, b in zip(runs[mode], runs["off"])
                if b.latency_p50_ms
            )
            return round(pairs[len(pairs) // 2], 3) if pairs else 0.0

        return {
            "coinop_trace_p50_ms": round(med("full"), 3),
            "coinop_trace_default_p50_ms": round(med("default"), 3),
            "coinop_notrace_p50_ms": round(med("off"), 3),
            # per-pair medians (phase-cancelling, like the bar metrics)
            "trace_overhead_ratio": pair_med("default"),
            "trace_overhead_full_ratio": pair_med("full"),
            "trace_sample_default": default_rate,
            "coinop_trace_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["full"]],
            "coinop_notrace_p50_reps": [
                round(r.latency_p50_ms, 3) for r in runs["off"]],
        }

    try:
        trace_rows = trace_overhead_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        trace_rows = {"trace_overhead_error": repr(e)[:200]}

    # tail-aware tracing + continuous profiler overhead (round 10):
    # trace_tail forced on (every unit journeys server-side, retention
    # decided at close; trace_sample pinned 0 so the arm is the pure
    # tail cost), the 19 Hz profiler, and both off. The acceptance
    # ratios are RUN-CPU pair ratios (process_time over a 2000-token
    # world, on/off runs ADJACENT with order alternating per rep so
    # linear box drift cancels inside each pair): on the 1-core dev box
    # pop-p50 pair noise is +-15% (scheduler-bound, the r08 caveat made
    # policy in bench_guard's cpu-count skip), while added CPU is the
    # scheduler-immune measure of what the feature actually costs — and
    # is what surfaces as latency on any saturated core. p50 medians
    # ride along for the latency view. Own containment.
    def tail_profile_overhead_bench():
        def coin_mode(mode):
            kw = {"trace_tail": "off", "profile_hz": 0.0}
            if mode == "tail":
                kw["trace_tail"] = "on"
            elif mode == "prof":
                kw["profile_hz"] = 19.0
            c0 = time.process_time()
            r = coinop.run(
                n_tokens=2000, num_app_ranks=APPS, nservers=SERVERS,
                cfg=Config(balancer="steal", exhaust_check_interval=0.2,
                           trace_sample=0.0, **kw),
                timeout=300.0,
            )
            return r, time.process_time() - c0

        coin_mode("off")  # warm (imports, thread pools)
        p50s = {"tail": [], "prof": [], "off": []}
        cpus = {"tail": [], "prof": [], "off": []}
        ratios = {"tail": [], "prof": []}
        # 9 pairs per arm: single-pair noise on this host class is +-8%
        # (hypervisor phases), so the median needs depth — see the
        # bench-box-noise note; ~90 s total, cheap for what it buys
        for rep in range(9):
            for armed in ("tail", "prof"):
                order = (armed, "off") if rep % 2 == 0 else ("off", armed)
                pair = {}
                for m in order:
                    r, c = coin_mode(m)
                    pair[m] = c
                    p50s[m].append(r.latency_p50_ms)
                    cpus[m].append(c)
                ratios[armed].append(pair[armed] / pair["off"])

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        return {
            "coinop_tail_p50_ms": round(med(p50s["tail"]), 3),
            "coinop_prof_p50_ms": round(med(p50s["prof"]), 3),
            "coinop_tailprof_off_p50_ms": round(med(p50s["off"]), 3),
            "coinop_tail_cpu_s": round(med(cpus["tail"]), 4),
            "coinop_prof_cpu_s": round(med(cpus["prof"]), 4),
            "coinop_tailprof_off_cpu_s": round(med(cpus["off"]), 4),
            # per-adjacent-pair medians: the acceptance bars
            "trace_tail_overhead_ratio": round(med(ratios["tail"]), 3),
            "profile_overhead_ratio": round(med(ratios["prof"]), 3),
            "tailprof_overhead_metric": "run-cpu-adjacent-pair",
            "tail_overhead_ratio_reps": [
                round(x, 3) for x in ratios["tail"]],
            "profile_overhead_ratio_reps": [
                round(x, 3) for x in ratios["prof"]],
        }

    try:
        tail_rows = tail_profile_overhead_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        tail_rows = {"tail_profile_overhead_error": repr(e)[:200]}

    # SLO evaluator overhead (round 12, ISSUE 16): the master-side
    # burn-rate loop armed with 8 objectives (one per work type, tight
    # windows so every obs tick appends to the snapshot ring and walks
    # the full objective list) vs the identical observed world with no
    # objectives. Both arms carry ops_port=0 + obs gossip so the ratio
    # isolates the evaluator itself, not the plumbing it rides on.
    # Same RUN-CPU adjacent-pair method as the tail/profiler rows
    # (process_time around a 2000-token world, order alternating per
    # rep, median of per-pair ratios) — the bench-box-noise policy.
    # Own containment.
    def slo_overhead_bench():
        objectives = tuple(
            {"job": 0, "type": t, "p99_ms": 50.0, "error_frac": 0.01,
             "window_s": 6.0, "severity": "warn"}
            for t in range(8)
        )

        def coin_mode(mode):
            kw = {}
            if mode == "slo":
                kw["slo"] = objectives
                kw["slo_eval_interval"] = 0.1
            c0 = time.process_time()
            r = coinop.run(
                n_tokens=2000, num_app_ranks=APPS, nservers=SERVERS,
                cfg=Config(balancer="steal", exhaust_check_interval=0.2,
                           trace_sample=0.0, ops_port=0,
                           obs_sync_interval=0.2, **kw),
                timeout=300.0,
            )
            return r, time.process_time() - c0

        coin_mode("off")  # warm (imports, thread pools)
        p50s = {"slo": [], "off": []}
        cpus = {"slo": [], "off": []}
        ratios = []
        for rep in range(9):
            order = ("slo", "off") if rep % 2 == 0 else ("off", "slo")
            pair = {}
            for m in order:
                r, c = coin_mode(m)
                pair[m] = c
                p50s[m].append(r.latency_p50_ms)
                cpus[m].append(c)
            ratios.append(pair["slo"] / pair["off"])

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        return {
            "coinop_slo_p50_ms": round(med(p50s["slo"]), 3),
            "coinop_slo_off_p50_ms": round(med(p50s["off"]), 3),
            "coinop_slo_cpu_s": round(med(cpus["slo"]), 4),
            "coinop_slo_off_cpu_s": round(med(cpus["off"]), 4),
            "slo_overhead_ratio": round(med(ratios), 3),
            "slo_overhead_metric": "run-cpu-adjacent-pair",
            "slo_objectives_armed": len(objectives),
            "slo_overhead_ratio_reps": [round(x, 3) for x in ratios],
        }

    try:
        slo_rows = slo_overhead_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        slo_rows = {"slo_overhead_error": repr(e)[:200]}

    # elastic membership (round 11, ISSUE 15): attach latency — the
    # rank-allocation + fleet-wide fan-out/ack barrier a joining rank
    # pays before its first protocol frame can land anywhere — and
    # scale-out MTTR (scale request -> new shard spawned, bootstrapped
    # by the donor rebalance, and counted ready by the master; the
    # master's own scaleout_mttr_ms gauge, so the row measures the
    # protocol, not the harness). Absolute one-shot latencies, so no
    # on/off CPU pairing applies — per the bench-box noise policy the
    # estimator is the median over reps (3 worlds x 3 attaches, one
    # scale-out each; single draws on the 1-core box are not
    # certifiable) and the rows are guarded baseline-relative
    # (bench_guard "member" row, missing-row = fail). Own containment.
    def membership_bench():
        import struct as _struct
        import threading as _th

        from adlb_tpu.runtime.membership import ElasticWorld
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        attach_reps, detach_reps, mttr_reps, wall_reps = [], [], [], []
        for _ in range(3):
            ew = ElasticWorld(
                2, 2, [1],
                cfg=Config(exhaust_check_interval=0.2), timeout=120.0,
            )
            hold = _th.Event()

            def consume(ctx):
                n = 0
                while True:
                    rc, _w = ctx.get_work([1])
                    if rc != _OK:
                        return n
                    n += 1

            def producer(ctx, hold=hold, consume=consume):
                # a standing backlog so the scale-out's donor rebalance
                # ships real units, like a production trigger would
                for i in range(48):
                    assert ctx.put(
                        _struct.pack("<q", i) + b"\0" * 56, 1
                    ) == _OK
                hold.wait(90)
                return consume(ctx)

            def holder(ctx, hold=hold, consume=consume):
                hold.wait(90)
                return consume(ctx)

            ew.run_app(0, producer)
            ew.run_app(1, holder)
            for _ in range(3):
                t0 = time.perf_counter()
                jw = ew.attach_ctx()
                attach_reps.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                assert jw.ctx.detach_world() == _OK
                detach_reps.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            ew.scale_out()
            wall_reps.append((time.perf_counter() - t0) * 1e3)
            mttr = ew.master.metrics.value("scaleout_mttr_ms")
            mttr_reps.append(mttr if mttr > 0 else wall_reps[-1])
            hold.set()
            res = ew.finish(timeout=120)
            got = sum(v for v in res.values() if isinstance(v, int))
            assert got == 48, f"membership bench lost work ({got}/48)"
        return {
            "attach_ms": round(med(attach_reps), 2),
            "detach_ms": round(med(detach_reps), 2),
            "scaleout_mttr_ms": round(med(mttr_reps), 1),
            "scaleout_wall_ms": round(med(wall_reps), 1),
            "attach_ms_reps": [round(x, 2) for x in attach_reps],
            "scaleout_mttr_ms_reps": [round(x, 1) for x in mttr_reps],
        }

    try:
        member_rows = membership_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        member_rows = {"membership_error": repr(e)[:200]}

    # tail hedging (round 12, ISSUE 17): two rows. hedge_p999 — the
    # straggler-rescue arm: a worker freezes while holding an unfetched
    # reservation strictly UNDER the lease timeout, so only the hedge
    # plane (budgeted speculative sibling, fenced first-wins) can close
    # the unit early; the row is the answer-economy completion time
    # with hedging on vs off over the same stall, medians over
    # interleaved reps. hedge_storm — the budget-subordination arm: a
    # put-storm shape driven handler-by-handler against one hedging
    # server with a forced memory-pressure window mid-storm, recording
    # launches vs the token-bucket bound (frac x deliveries + burst)
    # and the count of sticky-vetoed origins that later launched — both
    # structural zeros by construction, guarded absolutely. Own
    # containment.
    def hedge_bench():
        import struct as _struct

        from adlb_tpu.runtime.membership import ElasticWorld
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        T, T_ANS = 1, 3
        n_units = 8
        stall_s = 1.2

        def one_world(hedge_on):
            cfg = Config(
                exhaust_check_interval=0.2, on_worker_failure="reclaim",
                lease_timeout_s=4.0,
                hedge_budget_frac=0.5 if hedge_on else 0.0,
                hedge_min_age_ms=80.0,
            )
            ew = ElasticWorld(3, 1, [T, T_ANS], cfg=cfg)
            if hedge_on:
                for s in ew.servers.values():
                    # what the master's obs gossip would install
                    s.journeys.tail_thr = {(0, T): 0.25}

            def collector(ctx):
                for i in range(n_units):
                    assert ctx.put(_struct.pack("<q", i), T,
                                   answer_rank=0) == _OK
                t0 = time.perf_counter()
                seen = set()
                while len(seen) < n_units:
                    rc, r = ctx.reserve([T_ANS])
                    assert rc == _OK, rc
                    rc, buf = ctx.get_reserved(r.handle)
                    if rc != _OK:
                        continue
                    seen.add(_struct.unpack("<q", buf)[0])
                return (time.perf_counter() - t0) * 1e3

            def worker(sleepy):
                def app(ctx):
                    n, slept = 0, False
                    while True:
                        rc, r = ctx.reserve([T])
                        if rc != _OK:
                            return n
                        if sleepy and not slept:
                            slept = True
                            time.sleep(stall_s)  # reserved, unfetched
                        rc, buf = ctx.get_reserved(r.handle)
                        if rc != _OK:
                            continue  # fenced: the sibling won
                        ctx.put(buf, T_ANS, target_rank=0)
                        n += 1
                return app

            ew.run_app(0, collector)
            ew.run_app(1, worker(True))
            ew.run_app(2, worker(False))
            res = ew.finish(timeout=60)
            done = res[1] + res[2]
            assert done == n_units, f"hedge bench lost work ({done})"
            return res[0]

        on_ms, off_ms = [], []
        for rep in range(3):
            order = (True, False) if rep % 2 == 0 else (False, True)
            for m in order:
                (on_ms if m else off_ms).append(one_world(m))

        # -- hedge_storm: budget subordination under a put storm -------
        from adlb_tpu.runtime.hedge import BURST_TOKENS
        from adlb_tpu.runtime.messages import Tag as _Tag
        from adlb_tpu.runtime.messages import msg as _msg
        from adlb_tpu.runtime.server import Server as _Server
        from adlb_tpu.runtime.transport import InProcFabric as _Fab
        from adlb_tpu.runtime.world import WorldSpec as _WS

        frac, rounds = 0.25, 40
        world = _WS(nranks=4, nservers=2, types=(T,))
        fab = _Fab(4)
        srv = _Server(
            world,
            Config(on_worker_failure="reclaim", lease_timeout_s=0.5,
                   hedge_budget_frac=frac, hedge_min_age_ms=50.0,
                   max_malloc_per_server=1024, mem_soft_frac=0.6),
            fab.endpoint(2),
        )
        srv.journeys.tail_thr[(0, T)] = 0.01

        def drain(rank):
            while fab.endpoints[rank].recv(timeout=0.0) is not None:
                pass

        for i in range(rounds):
            srv._handle(_msg(_Tag.FA_PUT, 0, payload=b"u%d" % i,
                             work_type=T, prio=0, target_rank=-1,
                             answer_rank=-1, common_len=0,
                             common_server=-1, common_seqno=-1))
            srv._handle(_msg(_Tag.FA_RESERVE, 0, req_types=[T],
                             hang=True, rqseqno=2 * i + 1))
            drain(0)
            srv._handle(_msg(_Tag.FA_RESERVE, 1, req_types=[T],
                             hang=True, rqseqno=2 * i + 2))
            pressured = 10 <= i < 20  # mid-storm overload window
            if pressured:
                srv.mem.alloc(800)
            srv._scan_hedges(time.monotonic() + 1.0)
            if pressured:
                srv.mem.free(800)
            for ls in list(srv.leases.leases()):
                u = srv.wq.get(ls.seqno)
                if u is None or not u.pinned:
                    continue
                srv._handle(_msg(_Tag.FA_GET_RESERVED, ls.owner,
                                 seqno=ls.seqno))
            drain(0)
            drain(1)
        assert srv.wq.count == 0, "hedge storm left unsettled inventory"
        launched_seqs, vetoed_seqs = set(), set()
        for _, txt in srv.flight.entries():
            if txt.startswith("hedge_launched"):
                launched_seqs.add(txt.split("origin=")[1].split()[0])
            elif txt.startswith("hedge_vetoed") and "backpressure" in txt:
                vetoed_seqs.add(txt.split("seqno=")[1].split()[0])
        launched = int(srv.metrics.value("hedges_launched"))
        bound = frac * rounds + BURST_TOKENS
        return {
            "hedge_p999_on_ms": round(med(on_ms), 1),
            "hedge_p999_off_ms": round(med(off_ms), 1),
            "hedge_p999_rescue_ratio": round(
                med(off_ms) / med(on_ms), 2) if med(on_ms) else 0.0,
            "hedge_p999_on_ms_reps": [round(x, 1) for x in on_ms],
            "hedge_p999_off_ms_reps": [round(x, 1) for x in off_ms],
            "hedge_storm_deliveries": rounds,
            "hedge_storm_launched": launched,
            "hedge_storm_budget_bound": round(bound, 1),
            "hedge_storm_launch_excess": round(
                max(0.0, launched - bound), 1),
            "hedge_storm_vetoed_backpressure": len(vetoed_seqs),
            "hedge_storm_veto_breaches": len(
                launched_seqs & vetoed_seqs),
        }

    try:
        hedge_rows = hedge_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        hedge_rows = {"hedge_error": repr(e)[:200]}

    # multi-job fairness (round 13, ISSUE 19): a light tenant (8 units,
    # 4:1 fair-share weight) rides the PLANNED path while a heavy
    # tenant floods 40 units against a squeezed snapshot horizon
    # (balancer_max_tasks=16) — the weight bias decides whether the
    # light job's units make the horizon and win solve slots while the
    # flood drains, or wait behind it. The row is the light job's p99
    # put->deliver sojourn with weights on vs off (same worlds,
    # interleaved reps); < 1 means weighting shielded the tenant.
    # Guarded baseline-relative (bench_guard "fairness" row, r08
    # skip-with-note policy until a baseline carries it). Own
    # containment.
    def fairness_bench():
        import struct as _struct

        from adlb_tpu.runtime.membership import ElasticWorld
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        T = 1
        n_heavy, n_light = 40, 8

        def one_world(weighted):
            cfg = Config(
                balancer="tpu", balancer_max_jobs=3,
                job_weights={2: 4.0} if weighted else None,
                balancer_max_tasks=16, put_routing="home",
                exhaust_check_interval=0.2,
            )
            ew = ElasticWorld(3, 2, [T], cfg=cfg, timeout=90.0)

            def producer(ctx):
                rc, ja = ctx.submit_job("heavy")
                assert (rc, ja) == (_OK, 1)
                rc, jb = ctx.submit_job("light")
                assert (rc, jb) == (_OK, 2)
                ctx.attach(1)
                for _ in range(n_heavy):
                    assert ctx.put(
                        _struct.pack("<d", time.perf_counter())
                        + b"\0" * 48, T) == _OK
                ctx.attach(2)
                for _ in range(n_light):
                    assert ctx.put(
                        _struct.pack("<d", time.perf_counter())
                        + b"\0" * 48, T) == _OK
                ctx.drain_job(1)
                ctx.drain_job(2)
                return []

            def consumer(jid):
                def app(ctx):
                    time.sleep(0.2)
                    ctx.attach(jid)
                    sojourns = []
                    while True:
                        rc, w = ctx.get_work([T])
                        if rc != _OK:
                            return sojourns
                        sojourns.append(
                            (time.perf_counter()
                             - _struct.unpack("<d", w.payload[:8])[0])
                            * 1e3)
                        time.sleep(0.005)  # per-unit work: a standing
                        # backlog, so horizon ordering matters
                return app

            # home placement (world.home_server: rank % nservers):
            # producer rank 0 and the HEAVY consumer rank 2 share
            # server 0, so the flood drains by local matching; the
            # LIGHT consumer rank 1 parks on server 1, so every light
            # unit must cross through the planner — the path the
            # weight bias arbitrates
            ew.run_app(0, producer)
            ew.run_app(1, consumer(2))
            ew.run_app(2, consumer(1))
            res = ew.finish(timeout=90)
            assert len(res[2]) == n_heavy and len(res[1]) == n_light
            light = sorted(res[1])
            return light[min(len(light) - 1,
                             int(0.99 * len(light)))]

        on_ms, off_ms = [], []
        for rep in range(3):
            order = (True, False) if rep % 2 == 0 else (False, True)
            for m in order:
                (on_ms if m else off_ms).append(one_world(m))
        return {
            "fairness_weighted_p99_ms": round(med(on_ms), 1),
            "fairness_unweighted_p99_ms": round(med(off_ms), 1),
            "fairness_p99_ratio": round(
                med(on_ms) / med(off_ms), 3) if med(off_ms) else 0.0,
            "fairness_weighted_p99_ms_reps": [
                round(x, 1) for x in on_ms],
            "fairness_unweighted_p99_ms_reps": [
                round(x, 1) for x in off_ms],
        }

    try:
        fairness_rows = fairness_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        fairness_rows = {"fairness_error": repr(e)[:200]}

    # fleet controller (round 13, ISSUE 19): autoscale reaction — a
    # put burst drives one server past the scale-out pressure band and
    # the clock runs from the last put acked to the controller-spawned
    # shard LIVE in the membership table (decision latency + the §12
    # scale-out machine, end to end through the closed loop). Median
    # over reps; guarded baseline-relative (bench_guard "control" row,
    # r08 skip-with-note policy). Own containment.
    def control_bench():
        import struct as _struct
        import threading as _th

        from adlb_tpu.runtime.membership import ElasticWorld
        from adlb_tpu.types import ADLB_SUCCESS as _OK

        def med(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        T = 1
        reps = []
        for _ in range(3):
            cfg = Config(
                exhaust_check_interval=0.2, ops_port=0,
                obs_sync_interval=0.1, control=True,
                control_cooldown_s=5.0, control_min_servers=2,
                control_scaleout_pressure=0.25,
                control_scalein_pressure=0.05,
                max_malloc_per_server=256 * 1024,
            )
            ew = ElasticWorld(1, 2, [T], cfg=cfg, timeout=90.0)
            pressured = _th.Event()
            grown = _th.Event()

            def app(ctx, pressured=pressured, grown=grown):
                for i in range(20):
                    assert ctx.put(
                        _struct.pack("<q", i) + b"\0" * 8192, T) == _OK
                ctx._c.flush_puts()
                pressured.set()
                grown.wait(60)
                n = 0
                while True:
                    rc, _w = ctx.get_work([T])
                    if rc != _OK:
                        return n
                    n += 1

            ew.run_app(0, app)
            assert pressured.wait(60)
            t0 = time.perf_counter()
            deadline = t0 + 60.0
            while len(ew.servers) <= 2 and time.perf_counter() < deadline:
                time.sleep(0.005)
            assert len(ew.servers) > 2, "controller never scaled out"
            reps.append((time.perf_counter() - t0) * 1e3)
            grown.set()
            res = ew.finish(timeout=90)
            assert res[0] == 20, f"autoscale bench lost work ({res[0]})"
            acts = ew.master.metrics.value(
                "control_actions", kind="scale_out")
            assert acts >= 1, "scale-out was not controller-driven"
        return {
            "autoscale_react_ms": round(med(reps), 1),
            "autoscale_react_ms_reps": [round(x, 1) for x in reps],
        }

    try:
        control_rows = control_bench()
    except Exception as e:  # noqa: BLE001 — own containment
        control_rows = {"control_error": repr(e)[:200]}

    # measurement provenance (the r07 caveat made policy): every record
    # carries the core count + load so cross-round comparisons can tell
    # a real regression from a different (or busy) box — bench_guard
    # skips-with-note when baseline and candidate disagree on cores
    provenance = {
        "cpu_count": os.cpu_count() or 1,
        "loadavg_1m": round(os.getloadavg()[0], 2)
        if hasattr(os, "getloadavg") else None,
    }

    result = {
        "metric": "hotspot_tasks_per_sec_tpu_balancer",
        "value": round(hot_tpu.tasks_per_sec, 1),
        "unit": "tasks/s",
        "vs_baseline": round(hot_tpu.tasks_per_sec / hot_steal.tasks_per_sec, 3)
        if hot_steal.tasks_per_sec
        else 0.0,
        "detail": {
            **provenance,
            "platform": platform,
            "app_ranks": APPS,
            "servers": SERVERS,
            "baseline": "upstream-faithful stealing (qmstat ring @ 0.1s, "
                        "src/adlb.c:165)",
            "hotspot_steal_tasks_per_sec": round(hot_steal.tasks_per_sec, 1),
            "hotspot_steal_fast_tasks_per_sec": round(
                hot_fast.tasks_per_sec, 1),
            "hotspot_tpu_tasks_per_sec": round(hot_tpu.tasks_per_sec, 1),
            # idle medians taken over the rep distribution directly, not
            # read off the median-RATE run (whose idle draw can be an
            # outlier of its own)
            "hotspot_steal_idle_pct": round(steal_idle_med, 1),
            "hotspot_tpu_idle_pct": round(tpu_idle_med, 1),
            "idle_ratio_vs_upstream": round(
                tpu_idle_med / steal_idle_med, 3) if steal_idle_med else 0.0,
            # best single rep per mode, for the spread floor (medians above
            # are the primary, draw-robust numbers)
            "hotspot_tpu_idle_pct_best": round(
                min(r.idle_pct for r in hot_runs["tpu"]), 1),
            "hotspot_steal_idle_pct_best": round(
                min(r.idle_pct for r in hot_runs["steal"]), 1),
            # continuity: the rounds-1/2 two-call consumer loop
            "hotspot_classic_steal_tasks_per_sec": round(
                hcl_steal.tasks_per_sec, 1),
            "hotspot_classic_tpu_tasks_per_sec": round(
                hcl_tpu.tasks_per_sec, 1),
            "hotspot_classic_ratio": round(
                hcl_tpu.tasks_per_sec / hcl_steal.tasks_per_sec, 3)
            if hcl_steal.tasks_per_sec else 0.0,
            "hotspot_classic_steal_idle_pct": round(hcl_steal_idle, 1),
            "hotspot_classic_tpu_idle_pct": round(hcl_tpu_idle, 1),
            "hotspot_classic_idle_ratio": round(
                hcl_tpu_idle / hcl_steal_idle, 3) if hcl_steal_idle else 0.0,
            "trickle_dispatch_p50_ms_steal": round(
                tric_steal.dispatch_p50_ms, 2),
            "trickle_dispatch_p50_ms_steal_fast": round(
                tric_fast.dispatch_p50_ms, 2),
            "trickle_dispatch_p50_ms_tpu": round(tric_tpu.dispatch_p50_ms, 2),
            "trickle_dispatch_p90_ms_steal": round(
                tric_steal.dispatch_p90_ms, 2),
            "trickle_dispatch_p90_ms_tpu": round(tric_tpu.dispatch_p90_ms, 2),
            # pipelined consumer (get_work_stream depth=4); steal side =
            # broadcast mode (compare with
            # trickle_dispatch_p50_ms_steal_fast, the blocking consumer
            # in the same config)
            "trickle_pipe_p50_ms_steal_fast": round(
                tric_pipe_steal.dispatch_p50_ms, 2),
            "trickle_pipe_p50_ms_tpu": round(
                tric_pipe_tpu.dispatch_p50_ms, 2),
            "trickle_pipe_p90_ms_steal_fast": round(
                tric_pipe_steal.dispatch_p90_ms, 2),
            "trickle_pipe_p90_ms_tpu": round(
                tric_pipe_tpu.dispatch_p90_ms, 2),
            "plan_age_p50_ms": plan_age_p50_ms,
            "plan_age_p90_ms": plan_age_p90_ms,
            **device_rows,
            "dispatch_speedup_vs_upstream": round(
                tric_steal.dispatch_p50_ms / tric_tpu.dispatch_p50_ms, 2)
            if tric_tpu.dispatch_p50_ms else 0.0,
            "solve_4096x512_ms": solve_4k_ms,
            "solve_16384x2048_ms": solve_16k_ms,
            # on-chip kernel time with the tunnel RTT subtracted (see
            # solve_onchip); the end-to-end rows above keep the tunnel
            "solve_onchip_4096x512_ms": onchip_4k,
            "solve_onchip_65536x8192_ms": onchip_65k,
            "device_null_rtt_ms": null_rtt_ms,
            # two-K chained per-solve times: RTT cancels exactly (the
            # robust on-chip numbers; the rows above keep the legacy
            # single-dispatch method for cross-round continuity)
            "solve_chain_4096x512_ms": chain_4k,
            "solve_chain_65536x8192_ms": chain_65k,
            "hotspot_app_ranks": HOT_APPS,
            "hotspot_servers": HOT_SERVERS,
            "nq_n": N,
            "nq_steal_tasks_per_sec": round(steal.tasks_per_sec, 1),
            "nq_tpu_tasks_per_sec": round(tpu.tasks_per_sec, 1),
            "nq_ratio": round(tpu.tasks_per_sec / steal.tasks_per_sec, 3)
            if steal.tasks_per_sec else 0.0,
            "tsp_n_cities": TSP_N,
            "tsp_steal_tasks_per_sec": round(tsp_steal, 1),
            "tsp_tpu_tasks_per_sec": round(tsp_tpu, 1),
            "tsp_ratio": round(tsp_tpu / tsp_steal, 3) if tsp_steal else 0.0,
            "sudoku_steal_tasks_per_sec": round(sudoku_steal, 1),
            "sudoku_tpu_tasks_per_sec": round(sudoku_tpu, 1),
            "sudoku_ratio": round(sudoku_tpu / sudoku_steal, 3)
            if sudoku_steal else 0.0,
            "gfmc_steal_tasks_per_sec": round(gfmc_steal, 1),
            "gfmc_tpu_tasks_per_sec": round(gfmc_tpu, 1),
            "gfmc_ratio": round(gfmc_tpu / gfmc_steal, 3)
            if gfmc_steal else 0.0,
            **native_rows,
            "steal_pop_latency_p50_ms": round(lat_steal.latency_p50_ms, 3),
            "tpu_pop_latency_p50_ms": round(lat_tpu.latency_p50_ms, 3),
            "steal_pops_per_sec": round(lat_steal.pops_per_sec, 1),
            "tpu_pops_per_sec": round(lat_tpu.pops_per_sec, 1),
            "steal_pop_p50_reps": [
                round(r.latency_p50_ms, 3) for r in coin_runs["steal"]],
            "tpu_pop_p50_reps": [
                round(r.latency_p50_ms, 3) for r in coin_runs["tpu"]],
            **failover_rows,
            **master_failover_rows,
            **gray_rows,
            **service_rows,
            **shm_rows,
            **codec_rows,
            **mux_rows,
            **plan_rows,
            **engine_rows,
            **trace_rows,
            **tail_rows,
            **slo_rows,
            **member_rows,
            **hedge_rows,
            **fairness_rows,
            **control_rows,
        },
    }
    # full record first (audit trail for humans / in-tree rehearsal logs)
    print(json.dumps(result))

    # ... then the COMPACT headline as the FINAL line: the only line the
    # driver's 2000-char tail is guaranteed to keep intact. Headline
    # fields + per-rep spreads; short keys; no whitespace.
    def rr(vals, nd=0):
        return [round(v, nd) if nd else int(round(v)) for v in vals]

    rates = lambda runs: [r.tasks_per_sec for r in runs]  # noqa: E731
    idles = lambda runs: [r.idle_pct for r in runs]  # noqa: E731

    def pair_ratio(runs, rate=lambda r: r.tasks_per_sec):
        """Median of per-rep-PAIR tpu/steal ratios: adjacent interleaved
        reps share the host's hour-scale phase, so the per-pair ratio
        cancels it (the VERDICT r4 item-4 interval evidence).  ``rate``
        extracts a rep's rate — result objects by default, or
        (tasks, elapsed) tuples via pair_ratio_t."""
        pairs = [
            rate(t) / rate(s)
            for s, t in zip(runs["steal"], runs["tpu"])
            if rate(s)
        ]
        return round(median_by(pairs), 3) if pairs else 0.0

    def pair_ratio_t(runs):
        return pair_ratio(runs, rate=lambda r: r[0] / r[1])
    compact = {
        "metric": "hotspot_tasks_per_sec_tpu_balancer",
        "value": round(hot_tpu.tasks_per_sec, 1),
        "unit": "tasks/s",
        # BAR METRIC = the PAIRED estimator (round 6, VERDICT r5 items
        # 2/5): median of per-rep-PAIR tpu/steal ratios. Adjacent
        # interleaved reps share the host's hour-scale phase, so pairing
        # cancels it — five rounds of "rehearsals cleared it, the record
        # drew a slow phase" is the pooled median's phase vulnerability.
        # The pooled medians stay as *_pooled for cross-round continuity.
        "vs_baseline": pair_ratio(hot_runs),
        "detail": {
            "hot_pooled": round(
                hot_tpu.tasks_per_sec / hot_steal.tasks_per_sec, 3)
            if hot_steal.tasks_per_sec else 0.0,
            "idle_steal": round(steal_idle_med, 1),
            "idle_tpu": round(tpu_idle_med, 1),
            "idle_ratio": round(tpu_idle_med / steal_idle_med, 3)
            if steal_idle_med else 0.0,
            "classic_ratio": pair_ratio(hcl_runs),
            "classic_pooled": round(
                hcl_tpu.tasks_per_sec / hcl_steal.tasks_per_sec, 3)
            if hcl_steal.tasks_per_sec else 0.0,
            "classic_idle_ratio": round(hcl_tpu_idle / hcl_steal_idle, 3)
            if hcl_steal_idle else 0.0,
            # workload bars: paired first (the bar), pooled second
            "nq": pair_ratio(nq_runs),
            "nq_pooled": round(tpu.tasks_per_sec / steal.tasks_per_sec, 3)
            if steal.tasks_per_sec else 0.0,
            "tsp": pair_ratio_t(tsp_runs),
            "tsp_pooled": round(tsp_tpu / tsp_steal, 3)
            if tsp_steal else 0.0,
            "sudoku": pair_ratio_t(sudoku_runs),
            "sud_pooled": round(sudoku_tpu / sudoku_steal, 3)
            if sudoku_steal else 0.0,
            "gfmc": pair_ratio_t(gfmc_runs),
            "gfmc_pooled": round(gfmc_tpu / gfmc_steal, 3)
            if gfmc_steal else 0.0,
            # HEADLINE scale rows (round 6): both modes on the batched
            # (batch:8) consumer at 64 and 128 ranks —
            # [ratio, steal_wait%, tpu_wait%]. The framework's own best
            # consumer path carries the scale flag; single-fetch rows
            # below are secondary continuity metrics.
            "n64b": [native_rows.get("native_64r_batch8_ratio"),
                     native_rows.get("native_64r_batch8_steal_wait_pct"),
                     native_rows.get("native_64r_batch8_tpu_wait_pct")],
            "n128b": [native_rows.get("native_128r_batch8_ratio"),
                      native_rows.get("native_128r_batch8_steal_wait_pct"),
                      native_rows.get("native_128r_batch8_tpu_wait_pct")],
            # secondary: single-fetch hotspot rows (host-ceiling-bound,
            # kept for cross-round comparison)
            "n16_ratio": native_rows.get("native_16r_ratio"),
            "n64_ratio": native_rows.get("native_64r_ratio"),
            "n16_wait": [native_rows.get("native_16r_steal_wait_pct"),
                         native_rows.get("native_16r_tpu_wait_pct")],
            "n64_wait": [native_rows.get("native_64r_steal_wait_pct"),
                         native_rows.get("native_64r_tpu_wait_pct")],
            # the NAMED north-star workloads at native scale (secondary,
            # single-fetch): [ratio, steal_wait%, tpu_wait%] per scale
            "nq64": [native_rows.get("native_nq_64r_ratio"),
                     native_rows.get("native_nq_64r_steal_wait_pct"),
                     native_rows.get("native_nq_64r_tpu_wait_pct")],
            "nq128": [native_rows.get("native_nq_128r_ratio"),
                      native_rows.get("native_nq_128r_steal_wait_pct"),
                      native_rows.get("native_nq_128r_tpu_wait_pct")],
            "tsp64": [native_rows.get("native_tsp_64r_ratio"),
                      native_rows.get("native_tsp_64r_steal_wait_pct"),
                      native_rows.get("native_tsp_64r_tpu_wait_pct")],
            "tsp128": [native_rows.get("native_tsp_128r_ratio"),
                       native_rows.get("native_tsp_128r_steal_wait_pct"),
                       native_rows.get("native_tsp_128r_tpu_wait_pct")],
            "batch_fetch_delta_pct": native_rows.get(
                "native_batch_fetch_delta_pct"),
            "disp_p50": [round(tric_steal.dispatch_p50_ms, 2),
                         round(tric_tpu.dispatch_p50_ms, 2)],
            # pipelined (get_work_stream) trickle consumer —
            # [steal_fast, tpu]; compare against the blocking consumer in
            # the SAME configs: [disp_fast_p50, disp_p50[1]]
            "disp_pipe_p50": [round(tric_pipe_steal.dispatch_p50_ms, 2),
                              round(tric_pipe_tpu.dispatch_p50_ms, 2)],
            "disp_fast_p50": round(tric_fast.dispatch_p50_ms, 2),
            # pop service latency (coinop), paired-rep medians
            "failover_mttr_ms": failover_rows.get("failover_mttr_ms"),
            "master_failover_mttr_ms":
                master_failover_rows.get("master_failover_mttr_ms"),
            "brain_repl_overhead_ratio":
                master_failover_rows.get("brain_repl_overhead_ratio"),
            "hang_mttr_ms": gray_rows.get("hang_mttr_ms"),
            "storm_backoffs": gray_rows.get("put_storm_backoffs"),
            "restart_replay_ms": service_rows.get("restart_replay_ms"),
            # multichip planning round @ 1k servers / 100k parked (p50)
            "plan_round_1k_ms": plan_rows.get("plan_round_1k_ms"),
            # host-tier round admission @ 100k parked: [array us, py
            # twin us] + the 1k/10k rungs of the same ladder
            "engine_round": [engine_rows.get("engine_round_us_100k"),
                             engine_rows.get("engine_round_py_us_100k")],
            "engine_round_1k": [engine_rows.get("engine_round_us_1k"),
                                engine_rows.get("engine_round_py_us_1k")],
            "engine_round_10k": [engine_rows.get("engine_round_us_10k"),
                                 engine_rows.get("engine_round_py_us_10k")],
            "pop_p50": [round(lat_steal.latency_p50_ms, 3),
                        round(lat_tpu.latency_p50_ms, 3)],
            "pops": [round(lat_steal.pops_per_sec, 1),
                     round(lat_tpu.pops_per_sec, 1)],
            # shm ring fabric (real processes): [shm, tcp, shm-batch:8]
            # classic-consumer pop p50s; large-payload put [shm, tcp];
            # spill fault-in latency and the storm acceptance counters
            # measurement provenance (the r07 caveat made policy)
            "cpu_count": provenance["cpu_count"],
            "load1": provenance["loadavg_1m"],
            # compiled wire codec: [active-impl encode us, py-twin
            # encode us] + speedups (>=5x acceptance) and the impl tag
            "codec_encode_us": codec_rows.get("codec_encode_us"),
            "codec": [codec_rows.get("codec_encode_us"),
                      codec_rows.get("codec_encode_us_py"),
                      codec_rows.get("codec_decode_us"),
                      codec_rows.get("codec_decode_us_py")],
            "codec_speedup": [codec_rows.get("codec_encode_speedup"),
                              codec_rows.get("codec_decode_speedup")],
            "codec_impl": codec_rows.get("codec_impl"),
            # multiplexed channels: [mux pop p50, per-pair pop p50] and
            # the 8-burst submission [coalesced, sequential]
            "coinop_mux": [mux_rows.get("coinop_mux_p50_ms"),
                           mux_rows.get("coinop_mux_tcp_p50_ms")],
            # unit-lifecycle tracing: [p50 @ trace_sample=1.0, p50 @ 0.0,
            # p50 @ default rate] + the default-rate per-pair overhead
            # ratio bench_guard bounds at 1.05 (ISSUE 13 acceptance)
            "trace_overhead": [
                trace_rows.get("coinop_trace_p50_ms"),
                trace_rows.get("coinop_notrace_p50_ms"),
                trace_rows.get("coinop_trace_default_p50_ms"),
            ],
            "trace_overhead_ratio": trace_rows.get("trace_overhead_ratio"),
            "trace_overhead_full_ratio": trace_rows.get(
                "trace_overhead_full_ratio"),
            # tail promotion + continuous profiler (round 10): paired
            # [tail-on p50, profiler-on p50, both-off p50] and the two
            # per-pair ratios bench_guard bounds absolutely at 1.05
            "tail_profile_overhead": [
                tail_rows.get("coinop_tail_p50_ms"),
                tail_rows.get("coinop_prof_p50_ms"),
                tail_rows.get("coinop_tailprof_off_p50_ms"),
            ],
            "trace_tail_overhead_ratio": tail_rows.get(
                "trace_tail_overhead_ratio"),
            "profile_overhead_ratio": tail_rows.get(
                "profile_overhead_ratio"),
            # SLO evaluator (round 12): armed/off coinop run-CPU
            # adjacent-pair ratio — bench_guard absolute arm at 1.05
            "slo_overhead_ratio": slo_rows.get("slo_overhead_ratio"),
            # elastic membership (round 11): attach latency (allocation
            # + fleet fan-out/ack barrier) and server scale-out MTTR
            # (request -> shard bootstrapped + rebalanced + ready),
            # medians over reps — bench_guard "member" row
            "attach_ms": member_rows.get("attach_ms"),
            "scaleout_mttr_ms": member_rows.get("scaleout_mttr_ms"),
            # tail hedging (round 12): straggler completion with the
            # hedge plane on vs off over the same sub-lease stall, and
            # the put-storm budget-subordination counters — bench_guard
            # "hedge" row + absolute zero-excess/zero-breach arms
            "hedge_p999": [hedge_rows.get("hedge_p999_on_ms"),
                           hedge_rows.get("hedge_p999_off_ms")],
            "hedge_storm_launch_excess": hedge_rows.get(
                "hedge_storm_launch_excess"),
            "hedge_storm_veto_breaches": hedge_rows.get(
                "hedge_storm_veto_breaches"),
            # multi-job fairness + fleet controller (round 13): the
            # light tenant's weighted/unweighted p99 sojourn ratio and
            # the closed-loop scale-out reaction — bench_guard
            # "fairness" / "control" rows (r08 skip-with-note arms)
            "fairness_p99": [fairness_rows.get("fairness_weighted_p99_ms"),
                             fairness_rows.get("fairness_unweighted_p99_ms")],
            "fairness_p99_ratio": fairness_rows.get("fairness_p99_ratio"),
            "autoscale_react_ms": control_rows.get("autoscale_react_ms"),
            "mux_burst8": [mux_rows.get("mux_burst8_batched_ms"),
                           mux_rows.get("mux_burst8_sequential_ms")],
            "coinop_shm": [shm_rows.get("coinop_shm_p50_ms"),
                           shm_rows.get("coinop_spawn_tcp_p50_ms"),
                           shm_rows.get("coinop_shm_batch8_p50_ms")],
            "put_large": [shm_rows.get("put_large_p50_ms_shm"),
                          shm_rows.get("put_large_p50_ms_tcp")],
            "spill": [shm_rows.get("spill_faultin_ms")],
            "storm": [shm_rows.get("spill_storm_backoffs"),
                      shm_rows.get("spill_storm_retries"),
                      1 if shm_rows.get("spill_storm_byte_identical")
                      else 0],
            "ndisp_p50": [native_rows.get("native_trickle_p50_ms_steal"),
                          native_rows.get("native_trickle_p50_ms_tpu")],
            # on-chip solve scale (4096x512 / 16384x2048 pools, device
            # path forced) + trickle with EVERY round's solve on the
            # tunneled chip — the TPU-path evidence in the record
            "solve_ms": [solve_4k_ms, solve_16k_ms],
            "solve_onchip_ms": [onchip_4k, onchip_65k],
            "null_rtt_ms": null_rtt_ms,
            "disp_dev_p50": device_rows.get(
                "trickle_dispatch_p50_ms_tpu_device_solve"),
            # per-rep spreads: every headline claim auditable from this
            # record alone (steal first, tpu second in each pair)
            "reps": {
                "hot_s": rr(rates(hot_runs["steal"])),
                "hot_t": rr(rates(hot_runs["tpu"])),
                "hotidle_s": rr(idles(hot_runs["steal"]), 1),
                "hotidle_t": rr(idles(hot_runs["tpu"]), 1),
                "cls_s": rr(rates(hcl_runs["steal"])),
                "cls_t": rr(rates(hcl_runs["tpu"])),
                "clsidle_s": rr(idles(hcl_runs["steal"]), 1),
                "clsidle_t": rr(idles(hcl_runs["tpu"]), 1),
                "nq_s": rr(rates(nq_runs["steal"])),
                "nq_t": rr(rates(nq_runs["tpu"])),
                "tsp_s": rr(t / s for t, s in tsp_runs["steal"]),
                "tsp_t": rr(t / s for t, s in tsp_runs["tpu"]),
                "sud_s": rr(t / s for t, s in sudoku_runs["steal"]),
                "sud_t": rr(t / s for t, s in sudoku_runs["tpu"]),
                "gfmc_s": rr(t / s for t, s in gfmc_runs["steal"]),
                "gfmc_t": rr(t / s for t, s in gfmc_runs["tpu"]),
            },
        },
    }
    if "native_error" in native_rows:
        compact["detail"]["native_error"] = native_rows["native_error"][:120]
    if "engine_round_error" in engine_rows:
        compact["detail"]["engine_round_error"] = (
            engine_rows["engine_round_error"][:120]
        )
    if "device_solve_error" in device_rows:
        compact["detail"]["device_error"] = (
            device_rows["device_solve_error"][:120]
        )
    if "fairness_error" in fairness_rows:
        compact["detail"]["fairness_error"] = (
            fairness_rows["fairness_error"][:120]
        )
    if "control_error" in control_rows:
        compact["detail"]["control_error"] = (
            control_rows["control_error"][:120]
        )
    line = json.dumps(compact, separators=(",", ":"))
    if len(line) > 1900:  # belt-and-braces: the tail window is ~2000
        compact["detail"].pop("reps", None)
        line = json.dumps(compact, separators=(",", ":"))
    print(line)


if __name__ == "__main__":
    import faulthandler

    # defense-in-depth for the run of record: every world has its own
    # timeout (a wedge raises TimeoutError -> the bench_error line), but
    # if a world/teardown path ever wedges past those, dump all thread
    # stacks to stderr every 30 min instead of hanging silently. A
    # healthy full bench finishes in well under one period; the timer
    # is cancelled the moment main() returns so a clean run never dumps.
    faulthandler.dump_traceback_later(1800, repeat=True)
    t0 = time.time()
    try:
        main()
        faulthandler.cancel_dump_traceback_later()
    except Exception as e:  # surface failures as a parseable line
        print(json.dumps({
            "metric": "bench_error",
            "value": 0,
            "unit": "error",
            "vs_baseline": 0,
            "detail": {"error": repr(e), "elapsed_s": round(time.time() - t0, 1)},
        }))
        sys.exit(1)
