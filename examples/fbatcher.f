c     fbatcher: file-driven job batcher in Fortran (behavioral port of
c     the reference examples/fbatcher.f onto this framework's
c     TCP-backed client). Rank 0 reads shell commands, one per line,
c     from the file named by ADLB_BATCH_FILE, Puts each as a work
c     unit, then joins the workers; every rank pops commands and runs
c     them with system(). The pool drains by exhaustion — the batcher
c     pattern of the reference's README-batcher.txt.
      program fbatcher
      implicit none
      include 'adlb/adlbf.h'

      integer TYPEJ
      parameter (TYPEJ = 1)

      integer typev(1), reqt(2)
      integer handle(ADLB_HANDLE_SIZE)
      integer ierr, nserv, usedbg, aprf, amserv, amdbg, napps
      integer me, wtype, wprio, wlen, arank, njobs, nrun, ios
      character*256 line
      character*256 fname
      character*16 env

      typev(1) = TYPEJ
      usedbg = 0
      aprf = 0
      nserv = 1
      call get_environment_variable('ADLB_NUM_SERVERS', env)
      if (env .ne. ' ') read (env, *) nserv

      call adlb_init(nserv, usedbg, aprf, 1, typev, amserv, amdbg,
     &               napps, ierr)
      if (ierr .ne. ADLB_SUCCESS) stop 2
      call adlb_world_rank(me)

      njobs = 0
      if (me .eq. 0) then
         call get_environment_variable('ADLB_BATCH_FILE', fname)
         if (fname .eq. ' ') then
            write (6, *) 'FBATCHER FAIL: ADLB_BATCH_FILE not set'
            call adlb_abort(7, ierr)
            stop 3
         end if
         open (10, file=fname, status='old', iostat=ios)
         if (ios .ne. 0) then
            write (6, *) 'FBATCHER FAIL: cannot open ', fname
            call adlb_abort(7, ierr)
            stop 4
         end if
 100     read (10, '(a)', iostat=ios) line
         if (ios .eq. 0) then
            if (line .ne. ' ') then
               call adlb_put(line, len_trim(line), -1, -1, TYPEJ, 1,
     &                       ierr)
               if (ierr .ne. ADLB_SUCCESS) stop 5
               njobs = njobs + 1
            end if
            go to 100
         end if
         close (10)
         write (6, *) 'FBATCHER QUEUED', njobs
      end if

c     every rank (rank 0 included) works the pool until it drains
      nrun = 0
      reqt(1) = TYPEJ
      reqt(2) = ADLB_RESERVE_EOL
 200  continue
      call adlb_reserve(reqt, wtype, wprio, handle, wlen, arank, ierr)
      if (ierr .ne. ADLB_SUCCESS) go to 300
      line = ' '
      call adlb_get_reserved(line, handle, ierr)
      if (ierr .ne. ADLB_SUCCESS) go to 300
      call system(line(1:wlen))
      nrun = nrun + 1
      go to 200
 300  continue
      write (6, *) 'FBATCHER RAN', nrun

      call adlb_finalize(ierr)
      end
