/* App-to-app messaging smoke: the c1.c pattern in C against this
 * framework — answers travel OUTSIDE the pool as direct app messages
 * (reference examples/c1.c ships B/C answers with MPI_Send on app_comm;
 * here ADLB_App_send/App_recv play that role).
 *
 * Rank 0 puts NJOBS numbered units and then blocks in App_recv collecting
 * one squared answer per unit; workers reserve units, square the value,
 * and App_send the result tagged TAG_ANS back to rank 0.  Rank 0 checks
 * the sum of squares and declares the problem done.  Exit 0 = all checks
 * passed.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

#define WORK 1
#define NJOBS 18
#define TAG_ANS 7

int main(void) {
  int types[1] = {WORK};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *ns = getenv("ADLB_NUM_SERVERS");
  if (!ns) {
    fprintf(stderr, "%s: ADLB_NUM_SERVERS not set (run under the "
            "framework's launcher)\n", __FILE__);
    return 2;
  }
  int nservers = atoi(ns);
  int rc = ADLB_Init(nservers, 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 2;
  int me = ADLB_World_rank();

  if (me == 0) {
    long expect = 0;
    for (int i = 1; i <= NJOBS; i++) {
      rc = ADLB_Put(&i, sizeof i, -1, 0, WORK, 0);
      if (rc != ADLB_SUCCESS) return 3;
      expect += (long)i * i;
    }
    long sum = 0;
    for (int k = 0; k < NJOBS; k++) {
      long v;
      int src = -1, tag = -1;
      int n = ADLB_App_recv(&v, sizeof v, &src, &tag);
      if (n != sizeof v || tag != TAG_ANS) return 4;
      sum += v;
    }
    ADLB_Set_problem_done();
    if (sum != expect) {
      fprintf(stderr, "appmsg: sum %ld != expected %ld\n", sum, expect);
      return 5;
    }
    printf("appmsg rank 0 sum %ld OK\n", sum);
  } else {
    int handled = 0;
    for (;;) {
      int req[2] = {WORK, ADLB_RESERVE_EOL};
      int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
      rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
      if (rc != ADLB_SUCCESS) break; /* NO_MORE_WORK / exhaustion */
      int v;
      rc = ADLB_Get_reserved(&v, handle);
      if (rc != ADLB_SUCCESS) break;
      long ans = (long)v * v;
      rc = ADLB_App_send(ar, &ans, sizeof ans, TAG_ANS);
      if (rc != ADLB_SUCCESS) return 6;
      handled++;
    }
    printf("appmsg rank %d handled %d\n", me, handled);
  }
  ADLB_Finalize();
  return 0;
}
