/* Smoke test of the Fortran binding (adlb_tpu/native/adlbf.c), driven from C.
 *
 * The image has no Fortran compiler, so this program emits exactly the call
 * sequence a GNU-mangled Fortran 77 program would: every shim is the
 * lowercase_ symbol, every argument passed by reference, following the flow
 * of the reference's f1.f (reference examples/f1.f): zero-length
 * begin/end_batch_put bracket, by-reference ADLB_PUT of real*8 payloads,
 * any-type blocking RESERVE, type-filtered IRESERVE polling, targeted
 * answer puts, SET_PROBLEM_DONE + INFO_GET at the master.  Exit 0 only if
 * every by-reference out-parameter round-trips correctly.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

/* the Fortran shims (GNU default mangling: lowercase + trailing _) */
extern void adlb_init_(int *, int *, int *, int *, int *, int *, int *, int *,
                       int *);
extern void adlb_put_(void *, int *, int *, int *, int *, int *, int *);
extern void adlb_reserve_(int *, int *, int *, int *, int *, int *, int *);
extern void adlb_ireserve_(int *, int *, int *, int *, int *, int *, int *);
extern void adlb_get_reserved_(void *, int *, int *);
extern void adlb_get_reserved_timed_(void *, int *, double *, int *);
extern void adlb_begin_batch_put_(void *, int *, int *);
extern void adlb_end_batch_put_(int *);
extern void adlb_set_problem_done_(int *);
extern void adlb_info_get_(int *, double *, int *);
extern void adlb_info_num_work_units_(int *, int *, int *, int *, int *);
extern void adlb_finalize_(int *);
extern void adlb_world_rank_(int *);
extern void adlb_world_size_(int *);

#define TYPE_A 1
#define TYPE_ANS 2
#define NUM_AS 12

int main(void) {
  int types[2] = {TYPE_A, TYPE_ANS};
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  int use_dbg = 0, aflag = 0, ntypes = 2;
  int am_server = -1, am_debug = -1, num_apps = 0, ierr = -42;

  adlb_init_(&nservers, &use_dbg, &aflag, &ntypes, types, &am_server,
             &am_debug, &num_apps, &ierr);
  if (ierr != ADLB_SUCCESS || am_server != 0 || am_debug != 0 ||
      num_apps < 1) {
    fprintf(stderr, "fshim: init ierr=%d\n", ierr);
    return 2;
  }
  int me = -1, wsize = -1;
  adlb_world_rank_(&me);
  adlb_world_size_(&wsize);
  if (me < 0 || wsize <= me) return 3;

  if (me == 0) {
    /* f1.f brackets its A-puts in a zero-length batch (examples/f1.f:163) */
    int zero = 0;
    adlb_begin_batch_put_(types /* unused */, &zero, &ierr);
    if (ierr != ADLB_SUCCESS) return 4;
    for (int i = 0; i < NUM_AS; i++) {
      double work_a[20];
      memset(work_a, 0, sizeof work_a);
      work_a[0] = (double)me;
      work_a[1] = (double)(i + 1);
      int len = 20 * 8, tgt = -1, ans = me, wtype = TYPE_A, prio = -i;
      adlb_put_(work_a, &len, &tgt, &ans, &wtype, &prio, &ierr);
      if (ierr != ADLB_SUCCESS) return 5;
    }
    adlb_end_batch_put_(&ierr);
    if (ierr != ADLB_SUCCESS) return 6;
  }

  int handle[ADLB_HANDLE_SIZE];
  int processed = 0, answers = 0;
  if (me == 0) {
    /* master: collect one answer per A via blocking type-filtered reserve */
    while (answers < NUM_AS) {
      int req[2] = {TYPE_ANS, ADLB_RESERVE_EOL};
      int wt = -1, wp = 0, wl = -1, ar = -1;
      adlb_reserve_(req, &wt, &wp, handle, &wl, &ar, &ierr);
      if (ierr != ADLB_SUCCESS || wt != TYPE_ANS || wl != 8) return 7;
      double ans_val = -1.0;
      adlb_get_reserved_(&ans_val, handle, &ierr);
      if (ierr != ADLB_SUCCESS || ans_val < 1.0) return 8;
      answers++;
    }
    int wtype = TYPE_A, num = -1, nbytes = -1, maxwq = -1;
    adlb_info_num_work_units_(&wtype, &num, &nbytes, &maxwq, &ierr);
    if (ierr != ADLB_SUCCESS || maxwq < 1) return 9;
    double hwm = -1.0;
    int key = ADLB_INFO_MALLOC_HWM;
    adlb_info_get_(&key, &hwm, &ierr);
    if (ierr != ADLB_SUCCESS || hwm <= 0.0) return 10;
    adlb_set_problem_done_(&ierr);
    if (ierr != ADLB_SUCCESS) return 11;
  } else {
    /* workers: poll with IRESERVE (f1.f's inner loop), fall back to the
     * blocking reserve, answer each A with a targeted put to rank 0 */
    for (;;) {
      int req[2] = {TYPE_A, ADLB_RESERVE_EOL};
      int wt = -1, wp = 0, wl = -1, ar = -1;
      adlb_ireserve_(req, &wt, &wp, handle, &wl, &ar, &ierr);
      if (ierr == ADLB_NO_CURRENT_WORK) {
        adlb_reserve_(req, &wt, &wp, handle, &wl, &ar, &ierr);
      }
      if (ierr == ADLB_NO_MORE_WORK || ierr == ADLB_DONE_BY_EXHAUSTION)
        break;
      if (ierr != ADLB_SUCCESS || wt != TYPE_A || wl != 20 * 8) return 12;
      double work_a[20];
      double tq = -1.0;
      adlb_get_reserved_timed_(work_a, handle, &tq, &ierr);
      if (ierr != ADLB_SUCCESS || tq < 0.0) return 13;
      double ans_val = work_a[1]; /* echo the A's index back */
      if (ans_val < 1.0) return 14;
      int len = 8, tgt = 0, ans = -1, wtype = TYPE_ANS, prio = 5;
      adlb_put_(&ans_val, &len, &tgt, &ans, &wtype, &prio, &ierr);
      if (ierr != ADLB_SUCCESS) return 15;
      processed++;
    }
  }

  printf("fshim rank %d: processed=%d answers=%d OK\n", me, processed,
         answers);
  adlb_finalize_(&ierr);
  return ierr == ADLB_SUCCESS ? 0 : 16;
}
