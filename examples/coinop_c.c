/* coinop at native scale: the pop-latency microbenchmark as C clients
 * against the C++ server daemons — the native twin of the in-process
 * probe (adlb_tpu/workloads/coinop.py).  Scenario lineage is the fork's
 * own addition (reference examples/coinop.cpp:79-126,190-213): one
 * producer floods N tokens through the pool; every worker times each
 * Reserve+Get pop and accumulates a streaming mean/stddev (the
 * reference gathers those per-worker moments to the producer with
 * MPI_Gather; here each rank prints its own and the harness gathers).
 *
 * Per-rank machine-readable output, same k=v shape as nq_c.c/tsp_c.c:
 *
 *   COIN rank=<r> pops=<n> mean_ms=<m> stddev_ms=<s> t0=<mono> t1=<mono> wait=<s>
 *   COINLAT <l1> <l2> ...          (raw per-pop latencies, ms)
 *
 * wait duplicates sum(latency) in seconds so probe_aggregate() can
 * compute the usual wait%% column.  Env knobs: ADLB_COIN_NTOKENS
 * (default 400), ADLB_COIN_BYTES (payload size, default 64),
 * ADLB_COIN_WORK_US (per-pop compute sleep, default 0).  Terminates by
 * exhaustion, as the in-process probe does.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <adlb/adlb.h>

#define TOKEN 1

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static int env_int(const char *k, int dflt) {
  const char *v = getenv(k);
  return v ? atoi(v) : dflt;
}

int main(void) {
  int types[1] = {TOKEN};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 rejected by Init */
  int n_tokens = env_int("ADLB_COIN_NTOKENS", 400);
  int token_bytes = env_int("ADLB_COIN_BYTES", 64);
  int work_us = env_int("ADLB_COIN_WORK_US", 0);
  if (n_tokens < 1 || token_bytes < 1) return 2;
  int rc = ADLB_Init(nservers, 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) {
    fprintf(stderr, "coinop: init failed rc=%d\n", rc);
    return 2;
  }
  int me = ADLB_World_rank();

  if (me == 0) {
    char *payload = (char *)malloc((size_t)token_bytes);
    if (!payload) {
      fprintf(stderr, "coinop: payload malloc(%d) failed\n", token_bytes);
      return 2;
    }
    memset(payload, 'c', (size_t)token_bytes);
    double t0 = mono();
    for (int i = 0; i < n_tokens; i++) {
      rc = ADLB_Put(payload, token_bytes, -1, -1, TOKEN, 0);
      if (rc != ADLB_SUCCESS) {
        fprintf(stderr, "coinop: put %d failed rc=%d\n", i, rc);
        return 3;
      }
    }
    free(payload);
    printf("COIN rank=0 pops=0 mean_ms=0 stddev_ms=0 t0=%.6f t1=%.6f "
           "wait=0\nCOINLAT\n",
           t0, mono());
    ADLB_Finalize();
    return 0;
  }

  /* Welford's streaming moments — per-worker mean/stddev, matching the
   * moments the reference gathers back to its producer */
  long pops = 0;
  double mean = 0.0, m2 = 0.0, wait = 0.0;
  double *lat = (double *)malloc((size_t)n_tokens * sizeof(double));
  if (!lat) {
    fprintf(stderr, "coinop: lat malloc(%d) failed\n", n_tokens);
    return 2;
  }
  double t0 = mono(), t1 = t0;
  for (;;) {
    int req[2] = {TOKEN, ADLB_RESERVE_EOL};
    int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    double r0 = mono();
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_DONE_BY_EXHAUSTION || rc == ADLB_NO_MORE_WORK) break;
    if (rc != ADLB_SUCCESS) return 4;
    if (wl != token_bytes) return 5;
    char buf[65536];
    if (wl > (int)sizeof buf) {
      fprintf(stderr, "coinop: token_bytes %d exceeds the %zu-byte cap\n",
              wl, sizeof buf);
      return 5;
    }
    rc = ADLB_Get_reserved(buf, handle);
    if (rc != ADLB_SUCCESS) return 6;
    double dt = mono() - r0;
    wait += dt;
    if (pops < n_tokens) lat[pops] = dt * 1e3;
    pops++;
    double delta = dt * 1e3 - mean;
    mean += delta / (double)pops;
    m2 += delta * (dt * 1e3 - mean);
    if (work_us > 0) usleep((useconds_t)work_us);
    t1 = mono();
  }
  double stddev = pops > 1 ? sqrt(m2 / (double)(pops - 1)) : 0.0;
  printf("COIN rank=%d pops=%ld mean_ms=%.4f stddev_ms=%.4f t0=%.6f "
         "t1=%.6f wait=%.6f\n",
         me, pops, mean, stddev, t0, t1, wait);
  printf("COINLAT");
  for (long i = 0; i < pops && i < n_tokens; i++)
    printf(" %.3f", lat[i]);
  printf("\n");
  free(lat);
  ADLB_Finalize();
  return 0;
}
