/* Branch-and-bound TSP over the native C API: the reference's
 * priority-ordered queue stress (reference examples/tsp.c) rebuilt for
 * this plane.  Same economy, independent decomposition:
 *
 *   - a WORK unit is int32[1 + k]: partial tour length, then the k cities
 *     visited so far (city 0 is always first); longer partials get higher
 *     priority (reference tsp.c:239-240's WORK_PRIO+new_len heuristic),
 *     so the pool drains depth-first and the bound tightens early;
 *   - every rank keeps a local best-so-far bound seeded by the same
 *     nearest-neighbour tour; a worker that completes a better tour puts
 *     a maximum-priority BOUND_UPDT targeted at app rank 0, and every
 *     rank that accepts an improvement forwards it down a binary tree of
 *     app ranks (reference tsp.c:17,240-266) — bound propagation
 *     exercises targeting and priority preemption together;
 *   - expansion happens inside ADLB_Begin_batch_put/ADLB_End_batch_put
 *     with no common buffer (children share nothing large), matching the
 *     reference's ADLB_Begin_batch_put(NULL,0) usage;
 *   - termination is by exhaustion once the tree is pruned dry.
 *
 * The distance matrix comes from ADLB_TSP_DISTS (comma-separated n*n
 * ints, supplied by the Python harness so C and harness agree exactly)
 * or, standalone, from a deterministic LCG over ADLB_TSP_SEED.  Each
 * rank prints one machine-readable line:
 *
 *   TSP rank=<r> best=<d> done=<n> nput=<n> t0=<mono> t1=<mono> wait=<s>
 *
 * done counts WORK units processed (expansions and prunes); wait is time
 * blocked acquiring work (the steal-to-exec quantity, as in hotspot_c.c).
 * The harness validates min(best) against a brute-force optimum.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include <adlb/adlb.h>

#define WORK 1
#define BOUND_UPDT 2
#define BOUND_PRIO 999999999 /* higher than any work priority */
#define MAXN 16

static int n_cities;
static int dists[MAXN][MAXN];
static int best;                  /* local bound (nearest-neighbour seed) */
static int lchild = -1, rchild = -1; /* bound-broadcast tree */
static long done_units, nput;

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

/* deterministic standalone fallback: LCG coordinates on a 101x101 grid,
 * rounded Euclidean distances (the Python harness normally supplies the
 * matrix via ADLB_TSP_DISTS instead, so both sides share one source) */
static void gen_dists(unsigned seed) {
  long xs[MAXN], ys[MAXN];
  unsigned long s = seed * 2654435761UL + 1;
  for (int i = 0; i < n_cities; i++) {
    s = (s * 1103515245UL + 12345UL) & 0x7fffffffUL;
    xs[i] = (long)(s % 101UL);
    s = (s * 1103515245UL + 12345UL) & 0x7fffffffUL;
    ys[i] = (long)(s % 101UL);
  }
  for (int i = 0; i < n_cities; i++)
    for (int j = 0; j < n_cities; j++) {
      double dx = (double)(xs[i] - xs[j]), dy = (double)(ys[i] - ys[j]);
      double d = dx * dx + dy * dy;
      int r = 0;
      while ((double)r * (double)r < d) r++; /* ceil(sqrt), no libm */
      if ((double)r * (double)r > d &&
          ((double)(r - 1) + 0.5) * ((double)(r - 1) + 0.5) > d)
        r--; /* round-to-nearest */
      dists[i][j] = (i == j) ? 0 : r;
    }
}

static int greedy_bound(void) {
  int used[MAXN] = {0}, tour[MAXN], total = 0;
  used[0] = 1;
  tour[0] = 0;
  for (int k = 1; k < n_cities; k++) {
    int best = -1, bd = 0;
    for (int c = 1; c < n_cities; c++)
      if (!used[c] && (best < 0 || dists[tour[k - 1]][c] < bd)) {
        best = c;
        bd = dists[tour[k - 1]][c];
      }
    used[best] = 1;
    tour[k] = best;
    total += bd;
  }
  return total + dists[tour[n_cities - 1]][0];
}

/* One consumed unit (either type), shared by the single-unit and batched
 * loops; returns 0 or a nonzero exit code. */
static int process_unit(int *u, int wl, int wt) {
  int rc;
  if (wt == BOUND_UPDT) {
    if (u[0] < best) {
      best = u[0];
      /* forward the improvement down the binary tree */
      if (lchild >= 0)
        ADLB_Put(u, (int)sizeof(int), lchild, -1, BOUND_UPDT, BOUND_PRIO);
      if (rchild >= 0)
        ADLB_Put(u, (int)sizeof(int), rchild, -1, BOUND_UPDT, BOUND_PRIO);
    }
    return 0;
  }
  done_units++;
  int length = u[0];
  int *path = &u[1];
  int k = wl / (int)sizeof(int) - 1; /* cities in the partial tour */
  if (length >= best) return 0;      /* pruned under a tighter bound */
  if (k == n_cities) {               /* complete: close the tour */
    int total = length + dists[path[k - 1]][0];
    if (total < best) {
      /* funnel to rank 0, which broadcasts down the tree.  Local
       * `best` is deliberately NOT set here (reference tsp.c:245-266
       * semantics): the tightened bound reaches this rank back through
       * the tree, and pre-setting it would make the `u[0] < best`
       * forwarding guard drop the broadcast at the originating rank —
       * an interior node's children would then never learn the bound. */
      int msg = total;
      ADLB_Put(&msg, (int)sizeof(int), 0, -1, BOUND_UPDT, BOUND_PRIO);
    }
    return 0;
  }
  int in_path[MAXN] = {0};
  for (int i = 0; i < k; i++) in_path[path[i]] = 1;
  ADLB_Begin_batch_put(NULL, 0);
  for (int c = 1; c < n_cities; c++) {
    if (in_path[c]) continue;
    int nl = length + dists[path[k - 1]][c];
    if (nl >= best) continue; /* bound prune */
    u[0] = nl;
    path[k] = c;
    rc = ADLB_Put(u, (int)((2 + k) * sizeof(int)), -1, -1, WORK, 1 + k);
    if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK) {
      ADLB_End_batch_put();
      return 5;
    }
    nput++;
  }
  ADLB_End_batch_put();
  u[0] = length; /* restore (path[k] scribble is beyond k, harmless) */
  return 0;
}

int main(void) {
  int types[2] = {WORK, BOUND_UPDT};
  int am_server, am_debug, num_apps;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0;
  n_cities = getenv("ADLB_TSP_N") ? atoi(getenv("ADLB_TSP_N")) : 9;
  if (n_cities < 3 || n_cities > MAXN) return 2;
  const char *dist_env = getenv("ADLB_TSP_DISTS");
  if (dist_env) {
    const char *p = dist_env;
    for (int i = 0; i < n_cities * n_cities; i++) {
      dists[i / n_cities][i % n_cities] = atoi(p);
      p = strchr(p, ',');
      if (!p && i + 1 < n_cities * n_cities) return 2;
      if (p) p++;
    }
  } else {
    unsigned seed =
        getenv("ADLB_TSP_SEED") ? (unsigned)atoi(getenv("ADLB_TSP_SEED")) : 0;
    gen_dists(seed);
  }

  int rc = ADLB_Init(nservers, 0, 0, 2, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 3;
  int me = ADLB_World_rank();
  lchild = 2 * me + 1;
  rchild = 2 * me + 2;
  if (lchild >= num_apps) lchild = -1;
  if (rchild >= num_apps) rchild = -1;

  best = greedy_bound(); /* identical on every rank */
  int buf[2 + MAXN]; /* [length, path...] or [dist] for BOUND_UPDT */

  if (me == 0) {
    buf[0] = 0; /* length so far */
    buf[1] = 0; /* tour starts at city 0 */
    rc = ADLB_Put(buf, 2 * (int)sizeof(int), -1, -1, WORK, 1);
    if (rc != ADLB_SUCCESS) return 4;
  }

  double wait = 0.0, t0 = mono(), t1 = t0;
  /* ADLB_TSP_FETCH=batch:<k> switches consumption to the batched fused
   * fetch (mirrors hotspot_c.c): up to k local units per round trip.
   * Priority order is preserved inside a batch, so a queued BOUND_UPDT
   * still arrives ahead of WORK units; the bound is applied the moment
   * its unit is processed, at most k-1 expansions later than the
   * single-unit loop would. Malformed values (trailing junk included)
   * are rejected with exit 9; k is capped at 32 here (each slot carries
   * a full (2+MAXN)-int tour payload, vs hotspot's 8-byte tokens and
   * cap 64). */
  int batch = 0;
  const char *fetch_env = getenv("ADLB_TSP_FETCH");
  if (fetch_env && strncmp(fetch_env, "batch", 5) == 0) {
    if (fetch_env[5] == ':') {
      char *end = NULL;
      long k = strtol(fetch_env + 6, &end, 10);
      if (!end || *end != '\0' || end == fetch_env + 6) return 9;
      batch = (int)k;
    } else if (fetch_env[5] == '\0') {
      batch = 8;
    } else {
      return 9;
    }
    if (batch < 1 || batch > 32) return 9;
  } else if (fetch_env && strcmp(fetch_env, "single") != 0) {
    return 9;
  }
  long rts = 0;
  if (batch) {
    int req[3] = {BOUND_UPDT, WORK, ADLB_RESERVE_EOL};
    enum { STRIDE = (2 + MAXN) * (int)sizeof(int) };
    static int wts[32], wps[32], wls[32], ars[32];
    static char payloads[32 * STRIDE];
    for (;;) {
      int ngot;
      double r0 = mono();
      rc = ADLB_Get_work_batch(req, batch, &ngot, wts, wps, payloads,
                               STRIDE, wls, ars);
      if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
      if (rc != ADLB_SUCCESS) return 7;
      wait += mono() - r0;
      rts++;
      for (int i = 0; i < ngot; i++) {
        t1 = mono();
        rc = process_unit((int *)(payloads + i * STRIDE), wls[i], wts[i]);
        if (rc) return rc;
      }
    }
  } else {
    for (;;) {
      int req[3] = {BOUND_UPDT, WORK, ADLB_RESERVE_EOL};
      int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
      double r0 = mono();
      rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
      if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
      if (rc != ADLB_SUCCESS) return 7; /* real error, not termination */
      if (wl > (int)sizeof(buf)) return 6;
      rc = ADLB_Get_reserved(buf, handle);
      if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
      if (rc != ADLB_SUCCESS) return 8;
      wait += mono() - r0;
      rts++;
      t1 = mono();
      rc = process_unit(buf, wl, wt);
      if (rc) return rc;
    }
  }

  printf("TSP rank=%d best=%d done=%ld nput=%ld t0=%.6f t1=%.6f wait=%.6f "
         "fetch=%s rts=%ld\n",
         me, best, done_units, nput, t0, t1, wait,
         batch ? "batch" : "single", rts);
  ADLB_Finalize();
  return 0;
}
