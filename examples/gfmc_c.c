/* GFMC-style A/B/C/D work-package economy over the native C API with
 * self-validating counts AND a self-validating checksum (reference
 * examples/c4.c, the abstraction of the GFMC nuclear Monte Carlo code;
 * decomposition shared with adlb_tpu/workloads/gfmc.py):
 *
 *   - the master (app rank 0) emits NA type-A packages, then collects
 *     exactly NA*BPA type-D results targeted back at it;
 *   - workers expand each A into BPA type-B packages; each B spawns CPB
 *     type-C packages carrying answer_rank = the B owner's rank (the
 *     reference's answer-economy field, reference c4.c:31-37), and the C
 *     consumer routes its answer back to that rank, which combines the
 *     CPB answers into one D for the master;
 *   - the expected package counts and the expected checksum are
 *     computable up front; the master exits nonzero on any mismatch
 *     (reference c4.c:495-502's self-check).
 *
 * Shapes via ADLB_GFMC_NA / ADLB_GFMC_BPA / ADLB_GFMC_CPB.  Every rank
 * prints
 *
 *   GFMC rank=<r> a=<n> b=<n> c=<n> ans=<n> d=<n> t0=... t1=... wait=<s>
 *
 * where d counts EMISSIONS (worker-side combines) and ans counts
 * C-answer receptions (units consumed but outside the package-count
 * check); the master's D receptions are its own exit-code check, not a
 * stdout row, keeping the harness's sum-over-ranks == expected test
 * one-sided.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#include <adlb/adlb.h>

#define TYPE_A 1
#define TYPE_B 2
#define TYPE_C 3
#define TYPE_C_ANSWER 4
#define TYPE_D 5
#define PRIO_A 1
#define PRIO_B 2
#define PRIO_C 3
#define PRIO_ANSWER 9

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

int main(void) {
  int types[5] = {TYPE_A, TYPE_B, TYPE_C, TYPE_C_ANSWER, TYPE_D};
  int am_server, am_debug, num_apps;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0;
  int na = getenv("ADLB_GFMC_NA") ? atoi(getenv("ADLB_GFMC_NA")) : 6;
  int bpa = getenv("ADLB_GFMC_BPA") ? atoi(getenv("ADLB_GFMC_BPA")) : 4;
  int cpb = getenv("ADLB_GFMC_CPB") ? atoi(getenv("ADLB_GFMC_CPB")) : 3;
  if (na < 1 || bpa < 1 || cpb < 1) return 2;

  int rc = ADLB_Init(nservers, 0, 0, 5, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 3;
  int me = ADLB_World_rank();

  long ca = 0, cb = 0, cc = 0, cans = 0, cd = 0;
  double wait = 0.0, t0 = mono(), t1 = t0;
  int buf[3];

  if (me == 0) {
    for (int a = 0; a < na; a++) {
      buf[0] = a;
      rc = ADLB_Put(buf, (int)sizeof(int), -1, -1, TYPE_A, PRIO_A);
      if (rc != ADLB_SUCCESS) return 4;
    }
    long expected_d = (long)na * bpa, got = 0, total = 0;
    while (got < expected_d) {
      int req[2] = {TYPE_D, ADLB_RESERVE_EOL};
      int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
      double r0 = mono();
      rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
      if (rc != ADLB_SUCCESS) return 5; /* master must never lose a D */
      rc = ADLB_Get_reserved(buf, handle);
      if (rc != ADLB_SUCCESS) return 6;
      wait += mono() - r0;
      t1 = mono();
      total += buf[0];
      got++;
    }
    ADLB_Set_problem_done();
    /* checksum: sum over (a,b,c) of (a*100+b)+c — the C "physics" */
    long want = 0;
    for (int a = 0; a < na; a++)
      for (int b = 0; b < bpa; b++)
        want += (long)cpb * (a * 100 + b) + (long)cpb * (cpb - 1) / 2;
    printf("GFMC rank=0 a=0 b=0 c=0 ans=0 d=0 t0=%.6f t1=%.6f wait=%.6f\n",
           t0, t1, wait);
    ADLB_Finalize();
    return (total == want) ? 0 : 7;
  }

  /* worker: every B this rank combines gets a slot; a single rank can in
   * principle process every B in the run */
  long max_b = (long)na * bpa;
  int *pend_left = calloc((size_t)max_b, sizeof(int));
  int *pend_acc = calloc((size_t)max_b, sizeof(int));
  if (!pend_left || !pend_acc) return 2;
  int next_b = 0;

  for (;;) {
    int req[5] = {TYPE_A, TYPE_B, TYPE_C, TYPE_C_ANSWER, ADLB_RESERVE_EOL};
    int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    double r0 = mono();
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
    if (rc != ADLB_SUCCESS) return 5;
    rc = ADLB_Get_reserved(buf, handle);
    if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
    if (rc != ADLB_SUCCESS) return 6;
    wait += mono() - r0;
    t1 = mono();
    if (wt == TYPE_A) {
      ca++;
      int a = buf[0];
      for (int b = 0; b < bpa; b++) {
        int out[2] = {a, b};
        /* no answer expected for a B itself — the answer economy runs on
         * the TYPE_C puts below */
        rc = ADLB_Put(out, 2 * (int)sizeof(int), -1, -1, TYPE_B, PRIO_B);
        if (rc != ADLB_SUCCESS) return 8;
      }
    } else if (wt == TYPE_B) {
      cb++;
      int a = buf[0], b = buf[1];
      int b_id = (me << 20) + next_b;
      pend_left[next_b] = cpb;
      pend_acc[next_b] = 0;
      next_b++;
      for (int c = 0; c < cpb; c++) {
        int out[3] = {b_id, a * 100 + b, c};
        /* the answer must come back to THIS rank, which owns the
         * pending-B state (the reference's answer_rank pattern) */
        rc = ADLB_Put(out, 3 * (int)sizeof(int), -1, me, TYPE_C, PRIO_C);
        if (rc != ADLB_SUCCESS) return 8;
      }
    } else if (wt == TYPE_C) {
      cc++;
      int out[2] = {buf[0], buf[1] + buf[2]}; /* b_id, the "physics" */
      rc = ADLB_Put(out, 2 * (int)sizeof(int), ar, -1, TYPE_C_ANSWER,
                    PRIO_ANSWER);
      if (rc != ADLB_SUCCESS) return 8;
    } else { /* TYPE_C_ANSWER */
      cans++;
      int slot = buf[0] & ((1 << 20) - 1);
      if ((buf[0] >> 20) != me || slot >= next_b) return 9; /* misrouted */
      pend_acc[slot] += buf[1];
      if (--pend_left[slot] == 0) {
        int out[1] = {pend_acc[slot]};
        rc = ADLB_Put(out, (int)sizeof(int), 0, -1, TYPE_D, PRIO_ANSWER);
        if (rc != ADLB_SUCCESS) return 8;
        cd++;
      }
    }
  }

  printf(
      "GFMC rank=%d a=%ld b=%ld c=%ld ans=%ld d=%ld t0=%.6f t1=%.6f "
      "wait=%.6f\n",
      me, ca, cb, cc, cans, cd, t0, t1, wait);
  ADLB_Finalize();
  return 0;
}
