/* Trickle at native scale: steady work arrival at ONE server, consumers
 * parked everywhere else — isolates cross-server dispatch (discovery)
 * latency, the structural gap between gossip-guided pull stealing and the
 * event-driven global solve. Native twin of the in-process probe
 * (adlb_tpu/workloads/trickle.py); scenario lineage: the reference's
 * steady-state skel.c shape (reference examples/skel.c:10-40).
 *
 * Rank 0 puts ADLB_TRICK_NTASKS tokens, ADLB_TRICK_GROUP per tick, one
 * tick every ADLB_TRICK_INTERVAL_US; each payload is the producer's
 * CLOCK_MONOTONIC put time (system-wide on Linux). Every consumer prints
 *
 *   TRICK n=<k> lat_ms=<l1> <l2> ...
 *
 * where each l is (delivery time - put time) in ms for one consumed
 * token. Termination is by exhaustion.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <adlb/adlb.h>

#define TOKEN 1
/* parked-on by ranks that share the producer's home server, so they never
 * consume locally — every measured delivery is a CROSS-server dispatch
 * (same trick as the in-process probe, adlb_tpu/workloads/trickle.py) */
#define NEVER 2

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static int env_int(const char *k, int dflt) {
  const char *v = getenv(k);
  return v ? atoi(v) : dflt;
}

int main(void) {
  int types[2] = {TOKEN, NEVER};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  int n_tasks = env_int("ADLB_TRICK_NTASKS", 200);
  int interval_us = env_int("ADLB_TRICK_INTERVAL_US", 10000);
  int group = env_int("ADLB_TRICK_GROUP", 2);
  int work_us = env_int("ADLB_TRICK_WORK_US", 2000);
  int rc = ADLB_Init(nservers, 0, 0, 2, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) {
    fprintf(stderr, "trickle: init failed rc=%d\n", rc);
    return 2;
  }
  int me = ADLB_World_rank();

  if (me == 0) {
    for (int i = 0; i < n_tasks; i++) {
      double t = mono();
      rc = ADLB_Put(&t, (int)sizeof t, -1, -1, TOKEN, 0);
      if (rc != ADLB_SUCCESS) {
        fprintf(stderr, "trickle: put %d failed rc=%d\n", i, rc);
        return 3;
      }
      if (group > 0 && (i + 1) % group == 0)
        usleep((useconds_t)interval_us);
    }
    printf("TRICK n=0 lat_ms=\n");
    ADLB_Finalize();
    return 0;
  }

  /* ranks co-homed with the producer park on NEVER: their home server is
   * where the tokens land, and a local match there measures nothing */
  int hot_home = 0 % nservers;
  int req[2] = {(me % nservers) == hot_home ? NEVER : TOKEN,
                ADLB_RESERVE_EOL};
  int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
  double *lat = (double *)malloc((size_t)n_tasks * sizeof(double));
  int done = 0;
  for (;;) {
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc != ADLB_SUCCESS) break; /* NO_MORE_WORK / DONE_BY_EXHAUSTION */
    double t_put = 0.0;
    rc = ADLB_Get_reserved(&t_put, handle);
    if (rc != ADLB_SUCCESS) break;
    double now = mono();
    if (done < n_tasks) lat[done] = (now - t_put) * 1e3;
    done++;
    usleep((useconds_t)work_us);
  }
  printf("TRICK n=%d lat_ms=", done);
  for (int i = 0; i < done && i < n_tasks; i++)
    printf("%s%.3f", i ? " " : "", lat[i]);
  printf("\n");
  free(lat);
  ADLB_Finalize();
  return 0;
}
