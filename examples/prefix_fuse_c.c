/* Batch-common + fused fetch round trip for the native client: since
 * the remote-fused-fetch change the Python server inlines a prefixed
 * unit's SUFFIX plus the prefix handle in the reservation response, and
 * the C client must assemble prefix + suffix itself (libadlb.cpp
 * fetch_common_prefix).
 *
 * Rank 0 stores a shared prefix and NJOBS numbered members; everyone
 * drains with ADLB_Get_work and validates prefix + payload per unit.
 * Exit 0 = every consumed unit carried the intact prefix.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

#define WORK 1
#define NJOBS 24
#define PREFIX "PFX-HEADER:"

int main(void) {
  int types[1] = {WORK};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *ns = getenv("ADLB_NUM_SERVERS");
  if (!ns) {
    fprintf(stderr, "%s: ADLB_NUM_SERVERS not set\n", __FILE__);
    return 2;
  }
  int rc = ADLB_Init(atoi(ns), 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 2;
  int me = ADLB_World_rank();
  const int plen = (int)strlen(PREFIX);

  if (me == 0) {
    rc = ADLB_Begin_batch_put((void *)PREFIX, plen);
    if (rc != ADLB_SUCCESS) return 3;
    for (int i = 1; i <= NJOBS; i++) {
      rc = ADLB_Put(&i, sizeof i, -1, -1, WORK, 0);
      if (rc != ADLB_SUCCESS) return 3;
    }
    rc = ADLB_End_batch_put();
    if (rc != ADLB_SUCCESS) return 3;
  }

  long sum = 0;
  int n = 0;
  for (;;) {
    int req[2] = {WORK, ADLB_RESERVE_EOL};
    char buf[64];
    int wt, wp, wl, ar;
    rc = ADLB_Get_work(req, &wt, &wp, buf, sizeof buf, &wl, &ar);
    if (rc != ADLB_SUCCESS) break; /* exhaustion */
    if (wl != plen + (int)sizeof(int)) {
      fprintf(stderr, "rank %d: bad work_len %d\n", me, wl);
      return 5;
    }
    if (memcmp(buf, PREFIX, (size_t)plen) != 0) {
      fprintf(stderr, "rank %d: prefix missing/corrupt\n", me);
      return 6;
    }
    int v;
    memcpy(&v, buf + plen, sizeof v);
    sum += v;
    n++;
  }
  printf("OK processed=%d sum=%ld\n", n, sum);
  ADLB_Finalize();
  return 0;
}
