/* n-queens over the native C API — the same decomposition as the Python
 * workload (adlb_tpu/workloads/nq.py) and the reference example in spirit
 * (reference examples/nq.c): a work unit is a partial board (one queen row
 * per filled column, -1 = open); workers expand the first open column,
 * re-Putting each safe child with priority = column (depth-first flavor)
 * until CUTOFF, below which they count the subtree locally.  Terminates by
 * exhaustion; the harness sums the per-rank counts printed on stdout and
 * validates against the known answer.
 *
 * Board size and split depth are env-tunable for the scaling harness
 * (ADLB_NQ_N, default 7; ADLB_NQ_CUTOFF, default 2); each rank prints one
 * machine-readable line in the same shape as tsp_c.c/hotspot_c.c:
 *
 *   NQ rank=<r> solutions=<n> done=<n> t0=<mono> t1=<mono> wait=<s>
 *
 * done counts work units processed; wait is time blocked acquiring work
 * (the steal-to-exec quantity).
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include <adlb/adlb.h>

#define WORK 1
#define MAXN 16

static int N = 7;
static int CUTOFF = 2;

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static int safe_at(const int *rows, int col, int row) {
  for (int c = 0; c < col; c++) {
    int r = rows[c];
    if (r == row || r + c == col + row || c - r == col - row) return 0;
  }
  return 1;
}

static long count_subtree(int *rows, int col) {
  if (col == N) return 1;
  long total = 0;
  for (int row = 0; row < N; row++) {
    if (safe_at(rows, col, row)) {
      rows[col] = row;
      total += count_subtree(rows, col + 1);
      rows[col] = -1;
    }
  }
  return total;
}

int main(void) {
  int types[1] = {WORK};
  int am_server, am_debug, num_apps;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  if (getenv("ADLB_NQ_N")) N = atoi(getenv("ADLB_NQ_N"));
  if (getenv("ADLB_NQ_CUTOFF")) CUTOFF = atoi(getenv("ADLB_NQ_CUTOFF"));
  if (N < 1 || N > MAXN || CUTOFF < 0) return 2;
  int rc = ADLB_Init(nservers, 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS) return 2;
  int me = ADLB_World_rank();

  int root[MAXN];
  int unit_bytes = N * (int)sizeof(int);
  if (me == 0) {
    for (int i = 0; i < N; i++) root[i] = -1;
    rc = ADLB_Put(root, unit_bytes, -1, -1, WORK, 0);
    if (rc != ADLB_SUCCESS) return 3;
  }

  long solutions = 0, done = 0;
  double wait = 0.0, t0 = mono(), t1 = t0;
  for (;;) {
    /* ANY-type reserve: exercises the omitted-req_types wire path (only
     * WORK units ever exist in this pool, so semantics are unchanged) */
    int req[2] = {ADLB_RESERVE_REQUEST_ANY, ADLB_RESERVE_EOL};
    int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    double r0 = mono();
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_DONE_BY_EXHAUSTION || rc == ADLB_NO_MORE_WORK) break;
    if (rc != ADLB_SUCCESS) return 4;
    int rows[MAXN];
    if (wl != unit_bytes) return 5;
    rc = ADLB_Get_reserved(rows, handle);
    if (rc != ADLB_SUCCESS) return 6;
    wait += mono() - r0;
    done++;
    int col = N;
    for (int i = 0; i < N; i++)
      if (rows[i] < 0) {
        col = i;
        break;
      }
    if (col <= CUTOFF && col < N) {
      for (int row = 0; row < N; row++) {
        if (safe_at(rows, col, row)) {
          rows[col] = row;
          rc = ADLB_Put(rows, unit_bytes, -1, -1, WORK, col);
          if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK) return 7;
          rows[col] = -1;
        }
      }
    } else {
      solutions += count_subtree(rows, col);
    }
    t1 = mono();
  }

  /* per-rank counts travel out-of-band via stdout: exhaustion already
   * fired, so no further Puts are accepted (matching the reference
   * semantics) — the harness sums the printed values */
  printf("NQ rank=%d solutions=%ld done=%ld t0=%.6f t1=%.6f wait=%.6f\n",
         me, solutions, done, t0, t1, wait);
  ADLB_Finalize();
  return 0;
}
