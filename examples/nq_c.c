/* n-queens over the native C API — the same decomposition as the Python
 * workload (adlb_tpu/workloads/nq.py) and the reference example in spirit
 * (reference examples/nq.c): a work unit is a partial board (one queen row
 * per filled column, -1 = open); workers expand the first open column,
 * re-Putting each safe child with priority = column (depth-first flavor)
 * until CUTOFF, below which they count the subtree locally.  Terminates by
 * exhaustion; rank 0 collects per-rank counts via targeted TALLY units and
 * validates against the known answer.  Exit 0 only on a correct count.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

#define WORK 1
#define TALLY 2
#define N 7
#define CUTOFF 2
#define EXPECTED 40 /* solutions for 7-queens */

static int safe_at(const int *rows, int col, int row) {
  for (int c = 0; c < col; c++) {
    int r = rows[c];
    if (r == row || r + c == col + row || c - r == col - row) return 0;
  }
  return 1;
}

static long count_subtree(int *rows, int col) {
  if (col == N) return 1;
  long total = 0;
  for (int row = 0; row < N; row++) {
    if (safe_at(rows, col, row)) {
      rows[col] = row;
      total += count_subtree(rows, col + 1);
      rows[col] = -1;
    }
  }
  return total;
}

int main(void) {
  int types[2] = {WORK, TALLY};
  int am_server, am_debug, num_apps;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  int rc = ADLB_Init(nservers, 0, 0, 2, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS) return 2;
  int me = ADLB_World_rank();

  int root[N];
  if (me == 0) {
    for (int i = 0; i < N; i++) root[i] = -1;
    rc = ADLB_Put(root, sizeof root, -1, -1, WORK, 0);
    if (rc != ADLB_SUCCESS) return 3;
  }

  long solutions = 0;
  for (;;) {
    /* ANY-type reserve: exercises the omitted-req_types wire path (only
     * WORK units ever exist in this pool, so semantics are unchanged) */
    int req[2] = {ADLB_RESERVE_REQUEST_ANY, ADLB_RESERVE_EOL};
    int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_DONE_BY_EXHAUSTION || rc == ADLB_NO_MORE_WORK) break;
    if (rc != ADLB_SUCCESS) return 4;
    int rows[N];
    if (wl != sizeof rows) return 5;
    rc = ADLB_Get_reserved(rows, handle);
    if (rc != ADLB_SUCCESS) return 6;
    int col = N;
    for (int i = 0; i < N; i++)
      if (rows[i] < 0) {
        col = i;
        break;
      }
    if (col <= CUTOFF && col < N) {
      for (int row = 0; row < N; row++) {
        if (safe_at(rows, col, row)) {
          rows[col] = row;
          rc = ADLB_Put(rows, sizeof rows, -1, -1, WORK, col);
          if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK) return 7;
          rows[col] = -1;
        }
      }
    } else {
      solutions += count_subtree(rows, col);
    }
  }

  /* funnel per-rank counts to rank 0 — exhaustion already fired, so the
   * pool is flushing; counts travel out-of-band via stdout for the harness
   * AND in-band as the exit path for rank 0's total when it can still
   * collect (after DONE_BY_EXHAUSTION no further Puts are accepted, matching
   * the reference semantics), so the harness sums the printed values. */
  printf("nq_c rank %d solutions %ld\n", me, solutions);
  ADLB_Finalize();
  return 0;
}
