/* Fast-path smoke: pipelined puts (ADLB_Iput/Flush_puts) + fused
 * reserve+get (ADLB_Get_work) — framework extensions over the reference
 * API (upstream pays one round trip per Put and two per consumed unit).
 *
 * Rank 0 streams NJOBS numbered units without waiting per put, flushes,
 * then everyone drains with Get_work until exhaustion; each rank reports
 * its count and checksum, rank 0 is the known-answer check's anchor
 * (per-rank sums printed; the harness sums them).  Exit 0 = local checks
 * passed.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

#define WORK 1
#define NJOBS 40

int main(void) {
  int types[1] = {WORK};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *ns = getenv("ADLB_NUM_SERVERS");
  if (!ns) {
    fprintf(stderr, "%s: ADLB_NUM_SERVERS not set (run under the "
            "framework's launcher)\n", __FILE__);
    return 2;
  }
  int nservers = atoi(ns);
  int rc = ADLB_Init(nservers, 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 2;
  int me = ADLB_World_rank();

  if (me == 0) {
    for (int i = 1; i <= NJOBS; i++) {
      /* the first four are TARGETED at rank 0: nobody else can take
       * them, so rank 0's first Get_work_batch after the flush is
       * guaranteed a multi-unit batch (the 'multi' check below would
       * otherwise be timing-dependent on loaded hosts) */
      int tgt = i <= 4 ? 0 : -1;
      rc = ADLB_Iput(&i, sizeof i, tgt, -1, WORK, i % 5);
      if (rc != ADLB_SUCCESS) return 3;
    }
    rc = ADLB_Flush_puts();
    if (rc != ADLB_SUCCESS) {
      fprintf(stderr, "fastpath: flush rc=%d\n", rc);
      return 4;
    }
  }

  long sum = 0;
  int n = 0;
  int multi = 0; /* at least one multi-unit batch expected somewhere */
  for (;;) {
    int req[2] = {WORK, ADLB_RESERVE_EOL};
    int vs[4], wts[4], wps[4], wls[4], ars[4], ngot = 0;
    rc = ADLB_Get_work_batch(req, 4, &ngot, wts, wps, vs, sizeof vs[0],
                             wls, ars);
    if (rc != ADLB_SUCCESS) break; /* exhaustion */
    if (ngot < 1 || ngot > 4) return 6;
    if (ngot > 1) multi = 1;
    for (int k = 0; k < ngot; k++) {
      if (wts[k] != WORK || wls[k] != (int)sizeof vs[0]) return 5;
      sum += vs[k];
      n++;
    }
  }
  printf("fastpath rank %d got %d sum %ld multi %d\n", me, n, sum, multi);
  ADLB_Finalize();
  return 0;
}
