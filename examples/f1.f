c     f1: the Fortran face of the A/B/answer work-package economy
c     (behavioral port of the reference examples/f1.f onto this
c     framework's TCP-backed client — no MPI; world shape comes from
c     the ADLB_RENDEZVOUS environment, reference role math unchanged).
c
c     Rank 0 emits NAS type-A units; workers expand each A into BPA
c     type-B units; each B produces one type-ANS answer targeted back
c     at rank 0 carrying a deterministic value. Rank 0 sums the
c     answers, checks the closed-form expected total, and prints
c     "F1 OK total=..." — a self-checking mini-app in the reference's
c     style (examples/c4.c:495-502 aborts on count mismatch).
      program f1
      implicit none
      include 'adlb/adlbf.h'

      integer NAS, BPA
      parameter (NAS = 4, BPA = 3)
      integer TYPEA, TYPEB, TYPEANS
      parameter (TYPEA = 1, TYPEB = 2, TYPEANS = 3)

      integer typev(3), reqt(4)
      integer handle(ADLB_HANDLE_SIZE)
      integer ierr, nserv, usedbg, aprf, amserv, amdbg, napps
      integer me, wtype, wprio, wlen, arank
      integer ia, ib, total, expect, nans
      integer buf(2)
      character*16 env

      typev(1) = TYPEA
      typev(2) = TYPEB
      typev(3) = TYPEANS
      usedbg = 0
      aprf = 0
      nserv = 1
      call get_environment_variable('ADLB_NUM_SERVERS', env)
      if (env .ne. ' ') read (env, *) nserv

      call adlb_init(nserv, usedbg, aprf, 3, typev, amserv, amdbg,
     &               napps, ierr)
      if (ierr .ne. ADLB_SUCCESS) stop 2
      call adlb_world_rank(me)

      if (me .eq. 0) then
c        master: emit the As, then collect every answer
         do ia = 1, NAS
            buf(1) = ia
            buf(2) = 0
            call adlb_put(buf, 8, -1, -1, TYPEA, 1, ierr)
            if (ierr .ne. ADLB_SUCCESS) stop 3
         end do
         total = 0
         nans = 0
         reqt(1) = TYPEANS
         reqt(2) = ADLB_RESERVE_EOL
 100     if (nans .lt. NAS * BPA) then
            call adlb_reserve(reqt, wtype, wprio, handle, wlen,
     &                        arank, ierr)
            if (ierr .ne. ADLB_SUCCESS) stop 4
            call adlb_get_reserved(buf, handle, ierr)
            if (ierr .ne. ADLB_SUCCESS) stop 5
            total = total + buf(1)
            nans = nans + 1
            go to 100
         end if
c        expected: sum over ia,ib of (ia*100 + ib)
         expect = 0
         do ia = 1, NAS
            do ib = 1, BPA
               expect = expect + ia * 100 + ib
            end do
         end do
         if (total .ne. expect) then
            write (6, *) 'F1 FAIL total=', total, ' expect=', expect
            call adlb_abort(7, ierr)
            stop 6
         end if
         write (6, *) 'F1 OK total=', total
         call adlb_set_problem_done(ierr)
      else
c        worker: expand As into Bs, answer each B back at rank 0
         reqt(1) = TYPEA
         reqt(2) = TYPEB
         reqt(3) = ADLB_RESERVE_EOL
 200     continue
         call adlb_reserve(reqt, wtype, wprio, handle, wlen, arank,
     &                     ierr)
         if (ierr .ne. ADLB_SUCCESS) go to 300
         call adlb_get_reserved(buf, handle, ierr)
         if (ierr .ne. ADLB_SUCCESS) go to 300
         if (wtype .eq. TYPEA) then
            do ib = 1, BPA
               buf(2) = ib
               call adlb_put(buf, 8, -1, -1, TYPEB, 2, ierr)
               if (ierr .ne. ADLB_SUCCESS) stop 8
            end do
         else
            buf(1) = buf(1) * 100 + buf(2)
            call adlb_put(buf, 8, 0, -1, TYPEANS, 9, ierr)
            if (ierr .ne. ADLB_SUCCESS) stop 9
         end if
         go to 200
 300     continue
      end if

      call adlb_finalize(ierr)
      end
