/* Self-checking smoke test of the native C API (include/adlb/adlb.h)
 * against the framework's servers, in the spirit of the reference's
 * self-validating mini-apps (reference examples/c4.c:495-502 aborts when
 * processed counts mismatch).
 *
 * Flow: rank 0 stores a batch-common prefix and puts NJOBS numbered WORK
 * units; every rank consumes WORK, checks the prefix survived the fetch,
 * and sends an ACK unit targeted back at rank 0; rank 0 collects all ACKs,
 * queries Info_*, then declares the problem done.  Exit code 0 only if
 * every check passed on every rank.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <adlb/adlb.h>

#define WORK 1
#define ACK 2
#define NJOBS 24
#define PREFIX "common-prefix:"

int main(void) {
  int types[2] = {WORK, ACK};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  int use_dbg = getenv("ADLB_USE_DEBUG_SERVER") ? 1 : 0;
  int rc = ADLB_Init(nservers, use_dbg, 0, 2, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) {
    fprintf(stderr, "smoke: init failed rc=%d\n", rc);
    return 2;
  }
  int me = ADLB_World_rank();

  if (me == 0) {
    rc = ADLB_Begin_batch_put((void *)PREFIX, (int)strlen(PREFIX));
    if (rc != ADLB_SUCCESS) return 3;
    for (int i = 0; i < NJOBS; i++) {
      char buf[32];
      int n = snprintf(buf, sizeof buf, "job-%03d", i);
      rc = ADLB_Put(buf, n, -1, 0, WORK, i % 5);
      if (rc != ADLB_SUCCESS) return 4;
    }
    rc = ADLB_End_batch_put();
    if (rc != ADLB_SUCCESS) return 5;
  }

  /* everyone consumes WORK and answers with a targeted ACK */
  int acks_seen = 0, done_consuming = 0, processed = 0;
  while (!done_consuming || (me == 0 && acks_seen < NJOBS)) {
    int req[3], wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    if (me == 0) {
      req[0] = done_consuming ? ACK : WORK;
      req[1] = done_consuming ? ADLB_RESERVE_EOL : ACK;
      req[2] = ADLB_RESERVE_EOL;
    } else {
      req[0] = WORK;
      req[1] = ADLB_RESERVE_EOL;
    }
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
    if (rc != ADLB_SUCCESS) return 6;
    char buf[256];
    double tq = -1.0;
    rc = ADLB_Get_reserved_timed(buf, handle, &tq);
    if (rc != ADLB_SUCCESS) return 7;
    buf[wl] = '\0';
    if (wt == WORK) {
      if (strncmp(buf, PREFIX, strlen(PREFIX)) != 0) {
        fprintf(stderr, "smoke rank %d: missing common prefix in %s\n", me,
                buf);
        return 8;
      }
      if (tq < 0.0) return 9;
      char ackbuf[300];
      int n = snprintf(ackbuf, sizeof ackbuf, "ack:%s", buf + strlen(PREFIX));
      rc = ADLB_Put(ackbuf, n, ar, -1, ACK, 0);
      if (rc != ADLB_SUCCESS) return 10;
      processed++;
    } else { /* ACK at rank 0 */
      if (strncmp(buf, "ack:job-", 8) != 0) return 11;
      acks_seen++;
    }
    if (me == 0 && acks_seen >= NJOBS) done_consuming = 1;
  }

  if (me == 0) {
    if (acks_seen != NJOBS) {
      fprintf(stderr, "smoke: only %d/%d acks\n", acks_seen, NJOBS);
      return 12;
    }
    int num = -1, nbytes = -1, maxwq = -1;
    rc = ADLB_Info_num_work_units(WORK, &num, &nbytes, &maxwq);
    if (rc != ADLB_SUCCESS || num != 0 || maxwq < 1) return 13;
    double hwm = -1.0;
    rc = ADLB_Info_get(ADLB_INFO_MALLOC_HWM, &hwm);
    if (rc != ADLB_SUCCESS || hwm <= 0.0) return 14;
    /* beyond-reference L0 introspection: server RSS + transport backlog */
    double rss = -1.0, backlog = -1.0;
    rc = ADLB_Info_get(ADLB_INFO_RSS_KB, &rss);
    if (rc != ADLB_SUCCESS || rss <= 0.0) return 15;
    rc = ADLB_Info_get(ADLB_INFO_TRANSPORT_BACKLOG, &backlog);
    if (rc != ADLB_SUCCESS || backlog < 0.0) return 16;
    /* pool checkpoint over the C API (framework extension): the pool is
     * drained here, so the shards must report zero captured units */
    const char *ckpt = getenv("ADLB_CKPT_PREFIX");
    if (ckpt != NULL) {
      int captured = -1;
      rc = ADLB_Checkpoint(ckpt, &captured);
      if (rc != ADLB_SUCCESS || captured != 0) return 17;
    }
    ADLB_Set_problem_done();
  }
  printf("smoke rank %d: processed=%d acks=%d OK\n", me, processed, acks_seen);
  ADLB_Finalize();
  return 0;
}
