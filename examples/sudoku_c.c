/* Sudoku DFS over the native C API: multi-type work units with a
 * collector rank (reference examples/sudoku.c rebuilt for this plane;
 * decomposition shared with adlb_tpu/workloads/sudoku.py).
 *
 *   - a WORK unit is 82 bytes: the 81-cell board (digits, 0 = empty)
 *     plus a puzzle-id byte, so several digit-relabeled isomorphs run in
 *     one pool; a worker fills the most-constrained empty cell, putting
 *     one child per legal digit with priority = filled-cell count
 *     (nearly-complete boards drain first);
 *   - a completed board travels to app rank 0 as a max-priority targeted
 *     SOLUTION unit (reference sudoku.c:283-287 prints it; here rank 0
 *     validates it against the puzzle and echoes it for the harness);
 *   - rank 0 declares the problem done once every puzzle has a valid
 *     solution; workers then unblock with NO_MORE_WORK.
 *
 * Puzzles arrive via ADLB_SUDOKU_PUZZLES (comma-separated 81-char digit
 * strings, supplied by the Python harness).  Every rank prints
 *
 *   SUD rank=<r> done=<n> solved=<n> t0=<mono> t1=<mono> wait=<s>
 *
 * and rank 0 additionally prints one "SUDSOL pid=<p> board=<81 chars>"
 * line per solved puzzle; it exits nonzero unless every solution
 * validates.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include <adlb/adlb.h>

#define WORK 1
#define SOLUTION 2
#define SOL_PRIO 999999999
#define MAXP 64 /* max puzzles per run */

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static int candidates(const unsigned char *b, int idx, int *out) {
  int used[10] = {0};
  int r = idx / 9, c = idx % 9;
  for (int i = 0; i < 9; i++) {
    used[b[r * 9 + i]] = 1;
    used[b[i * 9 + c]] = 1;
  }
  int br = 3 * (r / 3), bc = 3 * (c / 3);
  for (int i = 0; i < 3; i++)
    for (int j = 0; j < 3; j++) used[b[(br + i) * 9 + (bc + j)]] = 1;
  int n = 0;
  for (int d = 1; d <= 9; d++)
    if (!used[d]) out[n++] = d;
  return n;
}

static int most_constrained(const unsigned char *b, int *cands, int *ncands) {
  int best = -1;
  *ncands = 10;
  int tmp[9];
  for (int i = 0; i < 81; i++) {
    if (b[i]) continue;
    int n = candidates(b, i, tmp);
    if (n < *ncands) {
      best = i;
      *ncands = n;
      memcpy(cands, tmp, (size_t)n * sizeof(int));
      if (n <= 1) break;
    }
  }
  return best;
}

static int check_solution(const unsigned char *b, const char *puzzle) {
  for (int i = 0; i < 81; i++) {
    int given = puzzle[i] - '0';
    if (given && b[i] != given) return 0;
  }
  for (int r = 0; r < 9; r++) {
    int seen[10] = {0};
    for (int c = 0; c < 9; c++) seen[b[r * 9 + c]]++;
    for (int d = 1; d <= 9; d++)
      if (seen[d] != 1) return 0;
  }
  for (int c = 0; c < 9; c++) {
    int seen[10] = {0};
    for (int r = 0; r < 9; r++) seen[b[r * 9 + c]]++;
    for (int d = 1; d <= 9; d++)
      if (seen[d] != 1) return 0;
  }
  for (int br = 0; br < 3; br++)
    for (int bc = 0; bc < 3; bc++) {
      int seen[10] = {0};
      for (int i = 0; i < 3; i++)
        for (int j = 0; j < 3; j++) seen[b[(3 * br + i) * 9 + (3 * bc + j)]]++;
      for (int d = 1; d <= 9; d++)
        if (seen[d] != 1) return 0;
    }
  return 1;
}

int main(void) {
  int types[2] = {WORK, SOLUTION};
  int am_server, am_debug, num_apps;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0;
  const char *penv = getenv("ADLB_SUDOKU_PUZZLES");
  if (!penv) return 2;
  static char puzzles[MAXP][82];
  int np = 0;
  const char *p = penv;
  while (*p) {
    if (np == MAXP) return 2; /* over the cap: error, not a silent drop */
    if (strlen(p) < 81) return 2;
    memcpy(puzzles[np], p, 81);
    puzzles[np][81] = 0;
    np++;
    p += 81;
    if (*p == ',') p++;
    else if (*p) return 2;
  }
  if (np == 0) return 2;

  int rc = ADLB_Init(nservers, 0, 0, 2, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) return 3;
  int me = ADLB_World_rank();

  long done = 0;
  int solved = 0;
  double wait = 0.0, t0 = mono(), t1 = t0;
  unsigned char buf[82];

  if (me == 0) {
    for (int pid = 0; pid < np; pid++) {
      int filled = 0;
      for (int i = 0; i < 81; i++) {
        buf[i] = (unsigned char)(puzzles[pid][i] - '0');
        if (buf[i]) filled++;
      }
      buf[81] = (unsigned char)pid;
      rc = ADLB_Put(buf, 82, -1, -1, WORK, filled);
      if (rc != ADLB_SUCCESS) return 4;
    }
    int got[MAXP] = {0};
    int bad = 0;
    while (solved < np) {
      int req[2] = {SOLUTION, ADLB_RESERVE_EOL};
      int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
      double r0 = mono();
      rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
      if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
      if (rc != ADLB_SUCCESS || wl != 82) return 5;
      rc = ADLB_Get_reserved(buf, handle);
      if (rc != ADLB_SUCCESS) return 6;
      wait += mono() - r0;
      t1 = mono();
      int pid = buf[81];
      if (pid >= np || got[pid]) continue; /* duplicate solver finish */
      got[pid] = 1;
      solved++;
      if (!check_solution(buf, puzzles[pid])) {
        bad++;
        continue;
      }
      printf("SUDSOL pid=%d board=", pid);
      for (int i = 0; i < 81; i++) putchar('0' + buf[i]);
      putchar('\n');
    }
    ADLB_Set_problem_done();
    printf("SUD rank=0 done=%ld solved=%d t0=%.6f t1=%.6f wait=%.6f\n",
           done, solved, t0, t1, wait);
    ADLB_Finalize();
    return (bad == 0 && solved == np) ? 0 : 7;
  }

  for (;;) {
    int req[2] = {WORK, ADLB_RESERVE_EOL};
    int wt, wp, wl, ar, handle[ADLB_HANDLE_SIZE];
    double r0 = mono();
    rc = ADLB_Reserve(req, &wt, &wp, handle, &wl, &ar);
    if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
    if (rc != ADLB_SUCCESS || wl != 82) return 5;
    rc = ADLB_Get_reserved(buf, handle);
    if (rc == ADLB_NO_MORE_WORK || rc == ADLB_DONE_BY_EXHAUSTION) break;
    if (rc != ADLB_SUCCESS) return 6;
    wait += mono() - r0;
    done++;
    t1 = mono();
    int cands[9], nc;
    int idx = most_constrained(buf, cands, &nc);
    if (idx < 0) { /* solved: send to the collector */
      rc = ADLB_Put(buf, 82, 0, -1, SOLUTION, SOL_PRIO);
      if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK) return 8;
      continue;
    }
    int filled = 0;
    for (int i = 0; i < 81; i++)
      if (buf[i]) filled++;
    ADLB_Begin_batch_put(NULL, 0);
    for (int k = 0; k < nc; k++) {
      buf[idx] = (unsigned char)cands[k];
      rc = ADLB_Put(buf, 82, -1, -1, WORK, filled + 1);
      if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK) {
        ADLB_End_batch_put();
        return 8;
      }
    }
    ADLB_End_batch_put();
    buf[idx] = 0;
  }

  printf("SUD rank=%d done=%ld solved=0 t0=%.6f t1=%.6f wait=%.6f\n", me,
         done, t0, t1, wait);
  ADLB_Finalize();
  return 0;
}
