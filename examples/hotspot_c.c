/* Hotspot at native scale: the BASELINE.json scenario shape (all work
 * enters one server, consumers spread everywhere — the situation
 * cross-server balancing exists for; compare the reference's skel.c
 * synthetic stress shape, reference examples/skel.c:10-40) driven
 * entirely by native processes: C clients (this file) against the C++
 * server daemons, with the JAX balancer sidecar planning in tpu mode.
 *
 * Rank 0 produces ADLB_HOT_NTASKS tokens; with ADLB_PUT_ROUTING=home they
 * all land on rank 0's home server. Every other rank consumes with
 * ADLB_HOT_WORK_US of usleep "compute" per token. Each worker prints one
 * machine-readable line:
 *
 *   HOT done=<n> busy=<secs> t0=<mono> t1=<mono>
 *
 * (CLOCK_MONOTONIC is system-wide on Linux, so the harness can take
 * cross-process makespans.) The producer prints HOT done=0 ... with its
 * first-put timestamp. Termination is by exhaustion.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <adlb/adlb.h>

#define TOKEN 1

static double mono(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

int main(void) {
  int types[1] = {TOKEN};
  int am_server = -1, am_debug = -1, num_apps = 0;
  const char *nsrv_env = getenv("ADLB_NUM_SERVERS");
  int nservers = nsrv_env ? atoi(nsrv_env) : 0; /* <= 0 is rejected by ADLB_Init */
  int n_tasks = getenv("ADLB_HOT_NTASKS") ? atoi(getenv("ADLB_HOT_NTASKS")) : 200;
  int work_us = getenv("ADLB_HOT_WORK_US") ? atoi(getenv("ADLB_HOT_WORK_US")) : 2000;
  int rc = ADLB_Init(nservers, 0, 0, 1, types, &am_server, &am_debug,
                     &num_apps);
  if (rc != ADLB_SUCCESS || am_server || am_debug) {
    fprintf(stderr, "hotspot: init failed rc=%d\n", rc);
    return 2;
  }
  int me = ADLB_World_rank();

  if (me == 0) {
    /* pure producer, like the Python hotspot: put everything, then leave;
     * workers terminate by exhaustion once the pool drains */
    double t0 = mono();
    for (int i = 0; i < n_tasks; i++) {
      rc = ADLB_Put("w", 1, -1, -1, TOKEN, 0);
      if (rc != ADLB_SUCCESS) {
        fprintf(stderr, "hotspot: put %d failed rc=%d\n", i, rc);
        return 3;
      }
    }
    printf("HOT done=0 busy=0.000000 t0=%.6f t1=%.6f\n", t0, t0);
    ADLB_Finalize();
    return 0;
  }

  int req[2] = {TOKEN, ADLB_RESERVE_EOL};
  int wt, wp, wl, ar;
  int done = 0;
  double wait = 0.0;
  double t0 = mono(), t1 = t0;
  /* wait = time blocked acquiring work, the steal-to-exec quantity;
   * "busy" is reported as NOMINAL compute (done * work_us) because on
   * an oversubscribed host the wall time of usleep includes
   * involuntary scheduler delay — a wall-clock busy measure inflates
   * utilization in exactly the runs where the kernel scheduler, not
   * balancing, is the bottleneck, making idle% move against
   * throughput. Default consumption uses the fused ADLB_Get_work (one
   * round trip when the unit is LOCAL to the home server): both modes
   * issue the identical call, so the mode that pre-positions work
   * locally is paid for that locality — the quantity this scenario
   * measures.  ADLB_HOT_FETCH=batch:<k> switches to the batched fused
   * fetch (up to k local units per round trip) so the bench can state
   * the measured single-vs-batch delta on this plane (see BASELINE.md
   * for the cadence-interaction caveat that keeps single-unit the
   * default). */
  int batch = 0;
  const char *fetch_env = getenv("ADLB_HOT_FETCH");
  if (fetch_env && strncmp(fetch_env, "batch", 5) == 0) {
    /* only "batch" (default k=8) or "batch:<k>" — anything else,
     * trailing junk included, is rejected, never silently remapped:
     * the bench records the delta under the REQUESTED k */
    if (fetch_env[5] == ':') {
      char *end = NULL;
      long k = strtol(fetch_env + 6, &end, 10);
      if (!end || *end != '\0' || end == fetch_env + 6) return 4;
      batch = (int)k;
    } else if (fetch_env[5] == '\0') {
      batch = 8;
    } else {
      return 4;
    }
    if (batch < 1 || batch > 64) return 4;
  } else if (fetch_env && strcmp(fetch_env, "single") != 0) {
    return 4;
  }
  long rts = 0; /* fetch round trips: under batching, rts < done when any
                 * batch carried >1 unit — the realized amortization */
  if (batch) {
    int wts[64], wps[64], wls[64], ars[64], ngot;
    char bufs[64 * 8];
    for (;;) {
      double r0 = mono();
      rc = ADLB_Get_work_batch(req, batch, &ngot, wts, wps, bufs, 8, wls,
                               ars);
      if (rc != ADLB_SUCCESS) break; /* NO_MORE_WORK / EXHAUSTION */
      wait += mono() - r0;
      rts++;
      for (int i = 0; i < ngot; i++) {
        usleep((useconds_t)work_us);
        done++;
        t1 = mono();
      }
    }
  } else {
    for (;;) {
      char buf[8];
      double r0 = mono();
      rc = ADLB_Get_work(req, &wt, &wp, buf, (int)sizeof buf, &wl, &ar);
      if (rc != ADLB_SUCCESS) break; /* NO_MORE_WORK / DONE_BY_EXHAUSTION */
      wait += mono() - r0;
      rts++;
      usleep((useconds_t)work_us);
      done++;
      t1 = mono();
    }
  }
  double busy = (double)done * (double)work_us * 1e-6;
  printf("HOT done=%d busy=%.6f t0=%.6f t1=%.6f wait=%.6f fetch=%s rts=%ld\n",
         done, busy, t0, t1, wait, batch ? "batch" : "single", rts);
  ADLB_Finalize();
  return 0;
}
