"""Randomized chaos soak over the framework's public surface.

Each iteration builds a random world (shape, balancer mode, server
plane, memory cap), runs a self-validating workload (answer economy
with targeted answers, or known-answer nq), and randomly layers on
adversities: garbage sprayed at the servers' live ports from inside
the world (rank 0 knows the real addresses), a mid-run abort
(validated to unblock the world), a random worker SIGKILLed mid-run
(exercised under BOTH failure policies — `abort` must classify
cleanly without hanging, `reclaim` must still produce the complete
answer set), seeded fault-injection delays on every endpoint
(adlb_tpu/runtime/faults.py — protocol-invisible, timing-hostile),
exhaustion vs explicit termination, or elastic-membership CHURN (ranks
attaching and detaching mid-world plus a server scale-out under a put
storm — exact coverage and zero counted losses asserted under both
worker policies). Any wrong answer, hang (timeout), or unexpected
exception stops the soak with the seed for replay.

Usage: python scripts/chaos_soak.py [--fabric shm|tcp|auto] <minutes> [seed0]

``--fabric shm`` pins every spawn-plane world onto the shared-memory
ring fabric (transport_shm.py), so the worker-kill / server-kill /
stall / poison adversities all exercise peers dying mid-ring.

First session of use found a real bug within minutes: a mid-run
abort could be misclassified as a world failure when a tearing-down
server closed its clients' connections before their TA_ABORT
frames landed (fixed: HomeServerLostError / abort-collateral
classification in spawn_world; regression test
tests/test_tcp_world.py::test_abort_classification_survives_teardown_race).
"""
import os
import random
import socket
import struct
import sys
import threading
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)), ".."))

from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS, InfoKey
from adlb_tpu.workloads import nq


def load_factor(cap: float = 5.0) -> float:
    """How oversubscribed this host is right now (1.0 = idle enough).

    The gray/two-jobs adversities arm sub-second lease timeouts; their
    quarantine/casualty ORACLES assume the only thing exceeding a lease
    is the injected fault. On a heavily loaded host that assumption
    breaks mechanically, not behaviorally: a HEALTHY worker descheduled
    past lease_timeout_s (its heartbeat thread starved too) gets
    fenced, its unit's attempts bump, and with the deliberately tiny
    retry budget (max_unit_retries=1) a second innocent expiry
    quarantines a NON-poison unit — quarantined becomes 2+ and the
    assert fires. Reproduced identically on ``--fabric tcp`` (CHANGES
    PR 8), i.e. it is load-induced scheduler starvation, not a fabric
    or quarantine bug: the seed replays green on an idle host.

    Fix: scale the armed lease timeouts by the measured 1-minute load
    per core, capped (a saturated CI box still has to finish). The
    stall durations derive from lease_timeout_s, so the
    short-stall/long-stall ratio semantics are preserved.
    """
    try:
        per_core = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:  # no /proc: assume idle
        return 1.0
    return min(max(per_core, 1.0), cap)


def coverage_pool(n_units):
    """Self-validating coverage workload for SERVER-kill adversities:
    rank 0 pre-loads ids, everyone consumes via get_work; the world ends
    by exhaustion. Tolerates re-execution (failover may replay a unit
    whose migration/ack was in flight) — the oracle is id coverage
    modulo the COUNTED replication-lag losses, asserted by the caller.
    The answer economy would deadlock instead: rank 0 blocks on exactly
    n_pairs answers, so a single counted loss would hang the world."""
    def app(ctx):
        T = 1
        if ctx.rank == 0:
            for i in range(n_units):
                rc = ctx.put(struct.pack("<q", i), T)
                assert rc == ADLB_SUCCESS
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                return got
            got.append(struct.unpack("<q", w.payload)[0])
            time.sleep(0.002)

    return app

GARBAGE = [
    struct.pack("<I", 41) + b"\x01" + os.urandom(40),
    struct.pack("<I", 8) + b"\x99" * 8,
    struct.pack("<I", 0x7FFFFFFF),
    struct.pack("<I", 0),
    struct.pack("<I", 12) + b"\x80" + os.urandom(11),
    struct.pack("<I", 9) + b"\x01" + struct.pack("<HiH", 4242, 0, 0),
]


def answer_economy(n_pairs, do_abort, do_spray, victim=None, kill_after=0,
                   kill_at_end=False):
    def app(ctx):
        T_AB, T_C = 1, 2
        if ctx.rank == victim:
            # the kill adversity: SIGKILL myself (uncatchable, a real
            # preemption) at a work-cycle boundary after kill_after
            # answers — or, if the pool drains first and kill_at_end is
            # set (reclaim iterations), right before finalize (the
            # END-ring-held death). Cycle boundaries keep the oracle
            # exact: no consumed-but-unanswered unit is lost, while a
            # death holding an unfetched reservation still exercises
            # lease reclaim.
            import signal as _signal

            n = 0
            while True:
                rc, r = ctx.reserve([T_AB])
                if rc != ADLB_SUCCESS:
                    if kill_at_end:
                        os.kill(os.getpid(), _signal.SIGKILL)
                    return n
                if n >= kill_after:
                    os.kill(os.getpid(), _signal.SIGKILL)
                rc, buf = ctx.get_reserved(r.handle)
                a, b = struct.unpack("<qq", buf)
                ctx.put(struct.pack("<q", a + b), T_C,
                        target_rank=r.answer_rank)
                n += 1
        if ctx.rank == 0 and do_spray:
            # spray from INSIDE the world: clients know every rank's real
            # address (spawn_world binds ephemeral ports, so an outside
            # observer cannot target them); sprayed-frame count is
            # printed so the harness can assert the adversity engaged
            stop = threading.Event()
            sprayed = [0]

            def _spray_all():
                servers = [
                    r for r in range(ctx.world.nranks)
                    if ctx.world.is_server(r)
                ]
                while not stop.is_set():
                    for s in servers:
                        host, port = ctx._c.ep.addr_map[s]
                        try:
                            c = socket.create_connection((host, port),
                                                         timeout=1.0)
                            c.sendall(random.choice(GARBAGE))
                            c.close()
                            sprayed[0] += 1
                        except OSError:
                            pass
                    time.sleep(0.02)

            t = threading.Thread(target=_spray_all, daemon=True)
            t.start()
            try:
                out = _economy_rank0(ctx, n_pairs, do_abort)
            finally:
                stop.set()
                print(f"SPRAYED {sprayed[0]}", flush=True)
            return out
        if ctx.rank == 0:
            return _economy_rank0(ctx, n_pairs, do_abort)
        n = 0
        while True:
            rc, r = ctx.reserve([T_AB])
            if rc != ADLB_SUCCESS:
                return n
            rc, buf = ctx.get_reserved(r.handle)
            a, b = struct.unpack("<qq", buf)
            ctx.put(struct.pack("<q", a + b), T_C, target_rank=r.answer_rank)
            n += 1

    return app


def _economy_rank0(ctx, n_pairs, do_abort):
    T_AB, T_C = 1, 2
    for a in range(n_pairs):
        rc = ctx.put(struct.pack("<qq", a, a * 3), T_AB, answer_rank=0)
        assert rc == ADLB_SUCCESS
    total = 0
    for i in range(n_pairs):
        if do_abort and i == n_pairs // 2:
            ctx.abort(7)
            return "aborted"
        rc, r = ctx.reserve([T_C])
        assert rc == ADLB_SUCCESS, rc
        rc, buf = ctx.get_reserved(r.handle)
        total += struct.unpack("<q", buf)[0]
    ctx.set_problem_done()
    return total


def gray_economy(n_units, victim=None, stall_s=0.0, poison=False,
                 ops_port=None, slo=False):
    """Answer-at-cycle-boundary economy for the GRAY adversities: rank 0
    puts ids (plus one poison-typed unit when ``poison``) and collects
    answers until coverage is complete; workers reserve/fetch/answer with
    a small compute sleep. ``victim`` SIGSTOPs itself between reserve and
    fetch (holding an unfetched lease) and must survive the fencing of
    its late fetch. Kills at reserve-response (the poison fault) land at
    cycle boundaries, so a casualty loses nothing it already answered
    and the id-coverage oracle stays exact.

    With ``ops_port`` the world is OBSERVED (trace_sample=0 + tail
    promotion armed by the port): rank 0 polls the master's
    /trace/tails before finishing and returns the doc, so the harness
    can assert the quarantined / lease-expired unit's journey was
    captured — observability exercised under faults, not happy path.

    With ``slo`` (ISSUE 16) rank 0 additionally polls /alerts until
    the burn-rate engine has driven a page-severity objective to
    FIRING (the lease expiry is the burn), returning the alert doc so
    the harness can assert the incident bundle on disk names the
    SIGSTOP victim — the fleet pages itself under the adversity."""
    T, T_P, T_ANS = 1, 2, 3

    def app(ctx):
        from adlb_tpu.runtime.faults import sigstop_self

        if ctx.rank == 0:
            for i in range(n_units):
                rc = ctx.put(struct.pack("<q", i), T, answer_rank=0)
                assert rc == ADLB_SUCCESS, rc
            if poison:
                assert ctx.put(b"poison", T_P) == ADLB_SUCCESS
            seen = set()
            while len(seen) < n_units:
                rc, r = ctx.reserve([T_ANS])
                assert rc == ADLB_SUCCESS, rc
                rc, buf = ctx.get_reserved(r.handle)
                if rc != ADLB_SUCCESS:
                    continue
                seen.add(struct.unpack("<q", buf)[0])
            tails = alerts = None
            if ops_port:
                import json as _json
                import urllib.request

                def fetch(route):
                    return _json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{ops_port}{route}",
                        timeout=5,
                    ).read().decode())

                # the adversity's journey closes on a server and rides
                # the obs gossip to the master — poll for it (bounded)
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline:
                    try:
                        tails = fetch("/trace/tails")
                    except OSError:
                        time.sleep(0.4)
                        continue
                    js = tails.get("journeys") or []
                    if poison and any(
                        j.get("end") == "quarantined" for j in js
                    ):
                        break
                    if not poison and any(
                        "expire" in [s[0] for s in j.get("spans") or ()]
                        for j in js
                    ):
                        break
                    time.sleep(0.4)
                if slo:
                    # the expiry IS the burn: hold the world open until
                    # the evaluator pages (PENDING -> FIRING needs a
                    # couple of sustained ticks past the expiry above)
                    deadline = time.monotonic() + 25.0
                    while time.monotonic() < deadline:
                        try:
                            alerts = fetch("/alerts")
                        except OSError:
                            time.sleep(0.4)
                            continue
                        if any(
                            a.get("state") == "FIRING"
                            for a in alerts.get("alerts") or []
                        ) or any(
                            h.get("to") == "FIRING"
                            for h in alerts.get("history") or []
                        ):
                            break
                        time.sleep(0.4)
            ctx.set_problem_done()
            return len(seen), tails, alerts
        # the SIGSTOP victim never touches the poison type: it must
        # SURVIVE (the adversity under test is the hang, not a kill)
        my_types = [T] if ctx.rank == victim else [T, T_P]
        n, retries, stopped = 0, 0, False
        while True:
            rc, r = ctx.reserve(my_types)
            if rc != ADLB_SUCCESS:
                return n, retries, stopped
            if ctx.rank == victim and n >= 1 and not stopped:
                stopped = True
                sigstop_self(stall_s)
            rc, buf = ctx.get_reserved(r.handle)
            if rc != ADLB_SUCCESS:
                retries += 1  # fenced/void handle: re-reserve
                continue
            ctx.put(buf, T_ANS, target_rank=0)
            n += 1
            time.sleep(0.003)

    return app


def two_jobs_economy(n_units, poison=True):
    """Service-mode adversity: two namespaces on one fleet. Rank 0
    produces/collects job A (plus one poison-typed unit when ``poison``
    — the fault spec SIGKILLs job-A workers that reserve it until the
    retry budget quarantines it); rank 1 produces/collects job B; the
    worker pool splits between the jobs by parity. Job B must drain to
    completion with exact coverage REGARDLESS of job A's poison churn —
    per-job exhaustion isolation — and job A itself completes with its
    poison unit quarantined."""
    T, T_P, T_ANS = 1, 2, 3

    def producer(ctx, jid, ids_base):
        ctx.attach(jid)
        for i in range(n_units):
            rc = ctx.put(struct.pack("<q", ids_base + i), T,
                         answer_rank=ctx.rank)
            assert rc == ADLB_SUCCESS, rc
        if poison and jid == 1:
            assert ctx.put(b"poison", T_P) == ADLB_SUCCESS
        seen = set()
        while len(seen) < n_units:
            # the producer doubles as a backstop consumer of its own
            # job's work (it never requests the poison type): even if
            # the poison kills the job's whole worker pool, the job
            # still drains — and answers carry the same id payload, so
            # either way one reserve closes one id
            rc, r = ctx.reserve([T, T_ANS])
            assert rc == ADLB_SUCCESS, rc
            rc, buf = ctx.get_reserved(r.handle)
            if rc != ADLB_SUCCESS:
                continue
            seen.add(struct.unpack("<q", buf)[0])
        ctx.drain_job(jid)
        return len(seen)

    def app(ctx):
        if ctx.rank == 0:
            rc, ja = ctx.submit_job("job-a")
            assert (rc, ja) == (ADLB_SUCCESS, 1), (rc, ja)
            rc, jb = ctx.submit_job("job-b")
            assert (rc, jb) == (ADLB_SUCCESS, 2), (rc, jb)
            return producer(ctx, 1, 0)
        if ctx.rank == 1:
            time.sleep(0.3)  # submits land; ids are deterministic
            return producer(ctx, 2, 1000)
        time.sleep(0.3)
        jid = 1 if ctx.rank % 2 == 0 else 2
        my_answer_rank = 0 if jid == 1 else 1
        ctx.attach(jid)
        # only job-A workers touch the poison type: job B's pool must be
        # untouched by job A's adversity
        my_types = [T, T_P] if jid == 1 else [T]
        n = 0
        while True:
            rc, r = ctx.reserve(my_types)
            if rc != ADLB_SUCCESS:
                return jid, n
            rc, buf = ctx.get_reserved(r.handle)
            if rc != ADLB_SUCCESS:
                continue
            ctx.put(buf, T_ANS, target_rank=my_answer_rank)
            n += 1
            time.sleep(0.002)

    return app


def churn_world(rng, apps, servers, mode, policy):
    """Elastic-membership adversity (adlb_tpu/runtime/membership.py):
    ranks JOIN and LEAVE mid-world and a server scales OUT under a put
    storm (the memory-watermark autoscale path, with a manual kick as
    the deterministic fallback), optionally scaling back IN through the
    zero-loss drain. Runs on the in-proc ElasticWorld harness — the
    member spawner lives in the master's process by construction.

    Oracles, under BOTH worker policies: exact id coverage (every put
    acked before the scale-out fetchable after it, the detacher's puts
    included), zero counted losses (churn is clean — `failover_lost`
    and `failover_promoted` both 0), and at least one shard actually
    joined."""
    from adlb_tpu.runtime.membership import ElasticWorld

    # sized against the 16 KiB per-server cap below: round-robin spread
    # puts ~10 KiB on each BASE server — over the 8 KiB soft watermark
    # (the autoscale trigger), comfortably under the cap (the static
    # producer cannot route to the new shard, so the storm must fit the
    # base fleet; what the scale-out relieves is the standing backlog)
    payload_len = 480
    n_units = rng.randint(19, 22) * servers
    cfg = Config(
        balancer=mode,
        exhaust_check_interval=0.2,
        on_worker_failure=policy,
        on_server_failure="failover",  # scale-in drains over promote
        elastic_scaleout="auto",
        elastic_cooldown_s=0.5,
        max_malloc_per_server=16 * 1024,
        mem_soft_frac=0.5,
    )
    ew = ElasticWorld(apps, servers, [1], cfg=cfg)
    hold = threading.Event()   # churn done; unleash the consumers
    stormed = threading.Event()  # every base put acked

    def consume(ctx):
        got = []
        while True:
            rc, w = ctx.get_work([1])
            if rc != ADLB_SUCCESS:
                return got
            got.append(struct.unpack("<q", w.payload[:8])[0])

    def producer(ctx):
        for i in range(n_units):
            assert ctx.put(
                struct.pack("<q", i) + b"x" * (payload_len - 8), 1
            ) == ADLB_SUCCESS
        stormed.set()
        hold.wait(90)
        return consume(ctx)

    def holder(ctx):
        hold.wait(90)
        return consume(ctx)

    ew.run_app(0, producer)
    for r in range(1, apps):
        ew.run_app(r, holder)
    assert stormed.wait(60), "put storm never finished"
    # ranks JOIN mid-world ...
    joined = [ew.attach_app(holder) for _ in range(rng.randint(1, 2))]
    # ... and LEAVE: a joiner that puts its own ids then cleanly detaches
    jw = ew.attach_ctx()
    extra = list(range(1000, 1000 + rng.randint(2, 5)))
    for i in extra:
        assert jw.ctx.put(
            struct.pack("<q", i) + b"y" * (payload_len - 8), 1
        ) == ADLB_SUCCESS
    assert jw.ctx.detach_world() == ADLB_SUCCESS
    # server scale-OUT under the storm: the watermark autoscale should
    # have tripped (0.5 * 16 KiB soft mark vs a ~20-45 KiB storm); kick
    # manually if the timing missed it, so the oracle stays exact
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not ew.master._member_ready:
        time.sleep(0.05)
    if not ew.master._member_ready:
        ew.scale_out()
    new_shards = sorted(ew.master._member_ready)
    # optionally scale back IN (needs >= 3 live servers)
    drained = None
    if rng.random() < 0.5 and servers + len(new_shards) >= 3:
        drained = ew.scale_in()
    hold.set()
    results = ew.finish(timeout=120)
    got = sorted(x for v in results.values() if v for x in v)
    want = sorted(list(range(n_units)) + extra)
    assert got == want, (
        f"coverage broke under churn: missing={set(want) - set(got)} "
        f"dup={[x for x in got if got.count(x) > 1][:5]}"
    )
    # churn is CLEAN: no counted losses, no failover promotions
    for r, s in ew.servers.items():
        if r == drained:
            continue
        assert s.metrics.value("failover_lost") == 0.0, r
        assert s.metrics.value("failover_promoted") == 0.0, r
    assert ew.master.metrics.value("servers_joined") >= 1.0
    # counted once fleet-wide, at the detacher's home
    assert sum(
        s.metrics.value("ranks_detached") for s in ew.servers.values()
    ) == 1.0
    return dict(
        workload="churn", apps=apps, servers=servers, mode=mode,
        policy=policy, n_units=n_units, joined=len(joined) + 1,
        shards=new_shards, drained=drained,
    )


def control_world(rng, apps, policy):
    """Fleet-brain adversity (adlb_tpu/control/controller.py): the
    closed-loop controller rides the obs tick over a live ElasticWorld
    while a put storm drives memory pressure past the scale-out rule's
    threshold. The CONTROLLER — no manual kick anywhere — must grow
    the fleet, and the growth must be clean under BOTH worker
    policies: exact id coverage across the scale-out, `failover_lost`
    0 on every server, at least one enacted scale_out action, and the
    hysteresis rail held (enacted scale actions bounded by the elapsed
    cooldown windows)."""
    from adlb_tpu.runtime.membership import ElasticWorld

    payload_len = 2048
    n_units = rng.randint(30, 40)
    cooldown = 3.0
    cfg = Config(
        exhaust_check_interval=0.2,
        on_worker_failure=policy,
        ops_port=0,
        obs_sync_interval=0.1,
        control=True,
        control_cooldown_s=cooldown,
        control_min_servers=2,
        control_max_servers=4,
        control_scaleout_pressure=0.25,
        control_scalein_pressure=0.05,
        max_malloc_per_server=128 * 1024,
    )
    t0 = time.monotonic()
    ew = ElasticWorld(apps, 2, [1], cfg=cfg)
    hold = threading.Event()     # storm parked; unleash the consumers
    stormed = threading.Event()  # every put acked

    def consume(ctx):
        got = []
        while True:
            rc, w = ctx.get_work([1])
            if rc != ADLB_SUCCESS:
                return got
            got.append(struct.unpack("<q", w.payload[:8])[0])

    def producer(ctx):
        for i in range(n_units):
            assert ctx.put(
                struct.pack("<q", i) + b"x" * (payload_len - 8), 1
            ) == ADLB_SUCCESS
        ctx._c.flush_puts()
        stormed.set()
        hold.wait(90)
        return consume(ctx)

    def holder(ctx):
        hold.wait(90)
        return consume(ctx)

    ew.run_app(0, producer)
    for r in range(1, apps):
        ew.run_app(r, holder)
    assert stormed.wait(60), "put storm never finished"
    # the controller — not a manual kick — grows the fleet
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and len(ew.servers) <= 2:
        time.sleep(0.05)
    assert len(ew.servers) > 2, "controller never scaled out"
    hold.set()
    results = ew.finish(timeout=120)
    elapsed = time.monotonic() - t0
    got = sorted(x for v in results.values() if v for x in v)
    want = list(range(n_units))
    assert got == want, (
        f"coverage broke across controller scale-out: "
        f"missing={set(want) - set(got)} "
        f"dup={[x for x in got if got.count(x) > 1][:5]}"
    )
    # controller-driven growth is CLEAN: no counted losses anywhere
    for r, s in ew.servers.items():
        assert s.metrics.value("failover_lost") == 0.0, r
    acts = ew.master.metrics.value("control_actions", kind="scale_out")
    assert acts >= 1.0, "scale-out happened without an enacted action"
    # hysteresis rail: at most one enacted scale action per cooldown
    # window over the world's whole life
    windows = int(elapsed / cooldown) + 1
    assert acts <= windows, (acts, windows, elapsed)
    return dict(
        workload="control", apps=apps, policy=policy, n_units=n_units,
        servers=len(ew.servers), actions=int(acts), windows=windows,
    )


def hedge_world(rng, apps, mode, policy, fabric=None):
    """Tail-hedging adversity (ISSUE 17): hedging armed, one worker
    SIGSTOPs while holding an unfetched reservation WITHOUT crossing
    the lease timeout — only the hedge plane can rescue the straggler
    early (the p99 trigger: once the rest of the pool drains, the
    frozen unit's age walks past the gossiped tail threshold and the
    home server speculatively re-dispatches it to a parked worker).

    One server on purpose: the sibling targets a parked requester at
    the straggler's HOME, so a single roof makes the launch
    deterministic. The oracle is zero double-count under both worker
    policies: every id answered exactly once at rank 0 AND executed
    exactly once across the pool (the fenced loser's fetch answers a
    retry, never a second payload), with the launch itself asserted
    through the merged /metrics view so the adversity can't pass
    vacuously."""
    T, T_ANS = 1, 3
    n_units = 120
    victim = rng.randrange(1, apps)
    # stall once the fleet has closed ~70 units: past TAIL_MIN_COUNT
    # (the p99 threshold exists) with plenty of pool left to drain
    stall_after = max(1, 70 // max(apps - 1, 1))
    lease_s = round(2.0 * load_factor(), 2)
    stall_s = round(0.45 * lease_s, 2)  # strictly under expiry
    port = probe_free_ports(1)[0]

    def app(ctx):
        from adlb_tpu.runtime.faults import sigstop_self

        if ctx.rank == 0:
            for i in range(n_units):
                rc = ctx.put(struct.pack("<q", i), T, answer_rank=0)
                assert rc == ADLB_SUCCESS, rc
            seen = set()
            while len(seen) < n_units:
                rc, r = ctx.reserve([T_ANS])
                assert rc == ADLB_SUCCESS, rc
                rc, buf = ctx.get_reserved(r.handle)
                if rc != ADLB_SUCCESS:
                    continue
                seen.add(struct.unpack("<q", buf)[0])
            # hold the world open until the launch is visible in the
            # merged fleet metrics (bounded — the rescue already
            # happened or rank 0 would still be short an answer)
            import urllib.request
            launched = False
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and not launched:
                try:
                    text = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5,
                    ).read().decode()
                except OSError:
                    time.sleep(0.4)
                    continue
                for ln in text.splitlines():
                    if ln.startswith("#") or "hedges_launched" not in ln:
                        continue
                    try:
                        launched = launched or float(ln.split()[-1]) > 0
                    except ValueError:
                        pass
                if not launched:
                    time.sleep(0.4)
            ctx.set_problem_done()
            return len(seen), launched
        n, retries, stopped = 0, 0, False
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return n, retries, stopped
            if ctx.rank == victim and n >= stall_after and not stopped:
                stopped = True
                sigstop_self(stall_s)  # reserved, unfetched, frozen
            rc, buf = ctx.get_reserved(r.handle)
            if rc != ADLB_SUCCESS:
                retries += 1  # fenced: the hedge sibling won the race
                continue
            ctx.put(buf, T_ANS, target_rank=0)
            n += 1
            time.sleep(0.003)

    kw = dict(balancer=mode, exhaust_check_interval=0.2,
              on_worker_failure=policy, lease_timeout_s=lease_s,
              hedge_budget_frac=0.5, hedge_min_age_ms=150.0,
              ops_port=port, obs_sync_interval=0.25, trace_sample=0.0)
    if fabric:
        kw["fabric"] = fabric
    res = spawn_world(apps, 1, [T, T_ANS], app, cfg=Config(**kw),
                      timeout=150.0)
    seen, launched = res.app_results[0]
    assert seen == n_units, res.app_results
    executed = sum(res.app_results[r][0] for r in range(1, apps))
    assert executed == n_units, (
        f"double count under hedging: executed={executed} want={n_units}"
    )
    assert launched, "hedge adversity never launched a sibling"
    assert victim in res.app_results, "stalled worker vanished"
    return dict(workload="hedge", apps=apps, servers=1, mode=mode,
                policy=policy, stall_s=stall_s, n_units=n_units)


def master_kill_world(rng, seed, apps, servers, mode, policy, draw,
                      fabric=None):
    """Master-kill adversity (ISSUE 20): the MASTER dies mid-run under
    ``on_server_failure="failover"`` — SIGKILL on the spawn plane, a
    fault-injected disconnect on the in-proc ``mid_attach`` draw. The
    ring-buddy deputy must promote and the world must complete with
    exact id coverage modulo the counted replication-lag losses
    (``failover_lost``); a promotion additionally mints the
    ``master_failover_mttr_ms`` row. Draws vary WHEN the brain dies:

    * ``idle``          — late frame: the fleet is mostly drained
    * ``mid_plan``      — ``balancer="tpu"``: the brain dies while the
                          planner owns dispatch
    * ``mid_attach``    — in-proc: a rank attaches across the
                          succession; the joiner must land at the
                          promoted deputy, never the corpse
    * ``alerts_firing`` — an SLO objective is live (and likely FIRING)
                          when the master dies; the deputy rebuilds the
                          engine under a churn hold and re-announces
                          the rebound ops endpoint via the rendezvous
                          file
    """
    n_units = rng.randint(24, 60)
    # mid_attach pins steal: the in-proc disconnect is FRAME-based and
    # fires only when the master's outbound counter reaches it — the
    # periodic steal-mode qmstat tick walks it deterministically even
    # while the consumers idle, whereas tpu mode event-gates the
    # broadcast and an idle planner can stall below the kill frame
    # forever (planner-owned succession is mid_plan's job)
    kw = dict(
        balancer="tpu" if draw == "mid_plan"
        else ("steal" if draw == "mid_attach" else mode),
        exhaust_check_interval=0.2,
        on_worker_failure=policy,
        on_server_failure="failover",
        failover_client_wait=30.0,
    )
    desc = dict(workload="master_kill", draw=draw, apps=apps,
                servers=servers, mode=kw["balancer"], policy=policy)
    master_rank = apps  # server index 0

    if draw == "mid_attach":
        from adlb_tpu.runtime.membership import ElasticWorld

        kw["fault_spec"] = {
            "seed": seed,
            "disconnect_server_at": {0: rng.randint(30, 90)},
        }
        cfg = Config(**kw)
        ew = ElasticWorld(apps, servers, [1], cfg=cfg)
        stormed = threading.Event()  # every put acked
        hold = threading.Event()     # succession done; drain the pool

        def consume(ctx):
            hold.wait(90)
            got = []
            while True:
                rc, w = ctx.get_work([1])
                if rc != ADLB_SUCCESS:
                    return got
                got.append(struct.unpack("<q", w.payload)[0])

        def producer(ctx):
            for i in range(n_units):
                assert ctx.put(struct.pack("<q", i), 1) == ADLB_SUCCESS
            stormed.set()
            return consume(ctx)

        ew.run_app(0, producer)
        for r in range(1, apps):
            ew.run_app(r, consume)
        assert stormed.wait(60), "put storm never finished"
        # the master's gossip/reactor traffic walks its frame count to
        # the injected disconnect; wait for the succession, then attach
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            m = ew.current_master
            if m.rank != master_rank and m.is_master:
                break
            time.sleep(0.02)
        promoted = ew.current_master
        assert promoted.rank != master_rank and promoted.is_master, \
            "deputy never promoted"
        # the attach dials the CURRENT master (MemberView-aware): a
        # joiner landing at the corpse would time the rpc out
        joiner = ew.attach_app(consume)
        assert joiner.rank != master_rank
        hold.set()
        results = ew.finish(timeout=120)
        got = sorted(x for v in results.values() if v for x in v)
        lost = sum(
            s.metrics.value("failover_lost")
            for r, s in ew.servers.items() if r != master_rank
        )
        missing = set(range(n_units)) - set(got)
        assert len(missing) <= lost, (sorted(missing), lost)
        assert promoted.metrics.value("master_failover_mttr_ms") > 0.0
        # the promoted brain's snapshot names the succession
        snap = promoted.world.snapshot()
        assert snap.get("master") == promoted.rank, snap
        desc["promoted"] = promoted.rank
        return desc

    # spawn-plane draws: a real SIGKILL of the master process
    frame = {
        "idle": rng.randint(100, 180),
        "mid_plan": rng.randint(30, 90),
        "alerts_firing": rng.randint(80, 150),
    }[draw]
    if fabric:
        kw["fabric"] = fabric
    announce_dir = None
    if draw == "alerts_firing":
        # every close breaches the 0.01 ms p99 -> the alert is live
        # (likely FIRING) when the master dies; warn severity keeps the
        # incident capture out of the oracle's way
        kw["ops_port"] = probe_free_ports(1)[0]
        kw["obs_sync_interval"] = 0.25
        kw["slo"] = ({
            "name": f"mkill-{seed}", "job": 0, "type": 1,
            "p99_ms": 0.01, "window_s": 60.0, "fast_s": 1.0,
            "for_s": 0.2, "cooldown_s": 5.0, "min_count": 1,
            "severity": "warn",
        },)
        announce_dir = __import__("tempfile").mkdtemp(prefix="adlb-ann-")
        kw["ops_announce_dir"] = announce_dir
    # the kill frame is drawn against an unknown world length: if the
    # world exhausts before the master's outbound frame counter reaches
    # it, the draw proved nothing — retry earlier until the kill LANDS
    # (a frame inside the put storm always exists)
    for _attempt in range(3):
        kw["fault_spec"] = {"seed": seed,
                            "kill_server_at_frame": {0: frame}}
        cfg = Config(**kw)
        res = spawn_world(apps, servers, [1, 2], coverage_pool(n_units),
                          cfg=cfg, timeout=150.0)
        assert not res.aborted
        done = [x for v in res.app_results.values() for x in v]
        lost = sum(s.get(int(InfoKey.FAILOVER_LOST), 0.0)
                   for s in res.server_stats.values())
        missing = set(range(n_units)) - set(done)
        assert len(missing) <= lost, (sorted(missing), lost)
        desc["killed"] = master_rank in res.server_casualties
        if desc["killed"]:
            break
        frame = max(10, frame // 2)
    desc["kill_frame"] = frame
    if desc["killed"]:
        # the master actually died mid-run: a promotion must have been
        # counted and timed somewhere in the surviving fleet
        promoted = sum(s.get(int(InfoKey.NUM_FAILOVERS), 0.0)
                       for s in res.server_stats.values())
        assert promoted >= 1, "master died but nobody promoted"
        mttr = max(s.get(int(InfoKey.FAILOVER_MTTR_MS), 0.0)
                   for s in res.server_stats.values())
        assert mttr > 0.0, "promotion did not record an MTTR"
        if announce_dir is not None:
            # the rendezvous file was atomically re-written by the
            # promoted deputy: it must name a SURVIVING master
            import json as _json

            p = os.path.join(announce_dir, "ops_endpoint.json")
            assert os.path.exists(p), "no ops rendezvous written"
            with open(p) as fh:
                doc = _json.load(fh)
            assert doc["master"] != master_rank, doc
    return desc


def one_iter(seed, fabric=None):
    rng = random.Random(seed)
    apps = rng.randint(3, 7)
    servers = rng.randint(2, 4)
    mode = rng.choice(["steal", "steal", "tpu"])
    native = rng.random() < 0.5
    cap = rng.choice([None, None, 64 * 1024, 16 * 1024])
    workload = rng.choice(["economy", "nq"])
    do_spray = workload == "economy" and rng.random() < 0.5
    do_abort = workload == "economy" and rng.random() < 0.25
    # kill adversity: SIGKILL a random worker mid-run, under a randomly
    # chosen failure policy (mutually exclusive with do_abort — a world
    # cannot validate two terminal outcomes at once)
    do_kill = workload == "economy" and not do_abort and rng.random() < 0.35
    policy = rng.choice(["abort", "reclaim"]) if do_kill else "abort"
    # server-kill adversity: SIGKILL a random NON-master server mid-run,
    # under both on_server_failure policies — "abort" must classify
    # cleanly without hanging, "failover" must complete with id coverage
    # modulo the counted replication-lag losses (its own coverage
    # workload; mutually exclusive with the other terminal adversities)
    do_skill = (
        workload == "economy" and not do_abort and not do_kill
        and servers >= 2 and rng.random() < 0.3
    )
    s_policy = rng.choice(["abort", "failover"]) if do_skill else "abort"
    # master-kill adversity (ISSUE 20): the MASTER dies mid-run under
    # "failover" — the standing deputy must promote and the world must
    # complete with exact id coverage; the draw varies when the brain
    # dies (idle / mid-plan / mid-attach / alerts-firing), under both
    # worker policies
    do_mkill = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and servers >= 2 and rng.random() < 0.3
    )
    mkill_draw = rng.choice(
        ["idle", "mid_plan", "mid_attach", "alerts_firing"]
    ) if do_mkill else None
    # gray adversities (lease_timeout_s armed): a worker SIGSTOPped
    # mid-lease (expiry + fencing must redeliver its unit and reject its
    # post-SIGCONT fetch), or a poison-typed unit that kills every
    # worker reserving it (the retry budget must quarantine it, exactly
    # once, and the fleet must survive) — both run under both worker
    # policies; python servers only (the daemon has no lease table)
    do_stall = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and not do_mkill and apps >= 3
        and rng.random() < 0.35
    )
    do_poison = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and not do_mkill and not do_stall and apps >= 5
        and rng.random() < 0.35
    )
    # service-mode adversity: two jobs multiplexed over one fleet, a
    # poison unit quarantined in job A while job B drains to completion
    # (per-job exhaustion isolation), under both worker policies
    # (apps >= 5 => at least two even-rank job-A workers, so the
    # budget-1 poison is guaranteed to exceed its retry budget and
    # quarantine even though only job A's half-pool ever touches it)
    do_two_jobs = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and not do_mkill and not do_stall and not do_poison
        and apps >= 5 and rng.random() < 0.4
    )
    # tail-hedging adversity (ISSUE 17): a straggler frozen strictly
    # under the lease timeout — only a speculative sibling can rescue
    # it early; zero double-count asserted under both worker policies
    do_hedge = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and not do_mkill and not do_stall and not do_poison
        and not do_two_jobs and apps >= 3 and rng.random() < 0.3
    )
    # elastic-membership churn (ISSUE 15): ranks joining/leaving
    # mid-world + a server scale-out under a put storm, both worker
    # policies; python servers only (the daemon keeps the fixed world)
    do_churn = (
        workload == "economy" and not do_abort and not do_kill
        and not do_skill and not do_mkill and not do_stall and not do_poison
        and not do_two_jobs and not do_hedge and rng.random() < 0.35
    )
    if do_mkill:
        return master_kill_world(
            rng, seed, apps, servers, mode,
            policy=rng.choice(["abort", "reclaim"]),
            draw=mkill_draw, fabric=fabric,
        )
    if do_hedge:
        return hedge_world(
            rng, apps, mode,
            policy=rng.choice(["abort", "reclaim"]),
            fabric=fabric,
        )
    if do_churn:
        return churn_world(
            rng, apps, servers, mode,
            policy=rng.choice(["abort", "reclaim"]),
        )
    g_policy = rng.choice(["abort", "reclaim"]) if (do_stall or do_poison) \
        else None
    # seeded delay faults: protocol-invisible, timing-hostile; applied to
    # every endpoint via Config so replays of this seed shake the same
    # interleavings
    do_faults = rng.random() < 0.3
    if workload == "nq":
        # nq runs through run_world — the in-process thread fabric — so
        # there is no native plane or TCP port surface there; keep the
        # descriptor honest (the spawn-plane/native coverage comes from
        # the economy iterations)
        native = False
    if (policy == "reclaim" or do_faults or do_skill or do_stall
            or do_poison or do_two_jobs):
        # the C++ daemon implements neither the reclaim/failover/lease
        # protocols, the (Python-side) fault shim, nor job namespaces
        native = False

    kw = dict(balancer=mode, exhaust_check_interval=0.2,
              on_worker_failure=policy,
              on_server_failure=s_policy)
    if fabric:
        # --fabric shm: every spawn-plane world in the soak rides the
        # shared-memory ring fabric, so the kill/stall/poison/server-kill
        # adversities all exercise a peer dying mid-ring
        kw["fabric"] = fabric
    gray_port = None
    if do_stall or do_poison:
        kw["on_worker_failure"] = g_policy
        # load-aware: the quarantine/casualty oracles assume only the
        # injected fault exceeds a lease — scale the timeout by the
        # host's current oversubscription so a starved-but-healthy
        # worker cannot fence/quarantine innocents (see load_factor)
        kw["lease_timeout_s"] = round(
            rng.choice([0.8, 1.2]) * load_factor(), 2)
        if do_poison:
            kw["max_unit_retries"] = 2
            kw["fault_spec"] = {"seed": seed, "poison_types": [2]}
        # observe the adversity (ISSUE 14): the ops port alone arms
        # tail promotion (trace_sample stays 0 — nothing head-sampled),
        # so the quarantined / lease-expired unit's journey MUST
        # surface in /trace/tails with its full hop chain — the
        # observability plane exercised under faults, not happy path
        gray_port = probe_free_ports(1)[0]
        kw["ops_port"] = gray_port
        kw["trace_sample"] = 0.0
        kw["obs_sync_interval"] = 0.25
        if do_stall:
            # the fleet pages ITSELF on the adversity (ISSUE 16): a p99
            # objective on the stalled work type — the expired unit's
            # total time carries the whole lease wait (>= 0.8 s against
            # a ~3 ms healthy close), so its close IS the burn even
            # under "reclaim" where the re-delivery ends the journey
            # "delivered" (no error close). error_frac rides along for
            # the quarantine outcomes. The page-severity FIRING must
            # capture an incident bundle naming the SIGSTOP victim
            # (the leases_expired_by{owner=} window delta). Unique name
            # per seed so the harness can find this iteration's bundle
            # in the shared flight dir.
            kw["slo"] = ({
                "name": f"stall-{seed}", "job": 0, "type": 1,
                "p99_ms": 500.0, "error_frac": 0.05,
                "window_s": 60.0,
                "fast_s": max(2.0, 2 * kw["lease_timeout_s"]),
                "for_s": 0.3, "cooldown_s": 5.0, "min_count": 1,
                "severity": "page",
            },)
    if do_two_jobs:
        # both worker policies: "reclaim" must complete BOTH jobs with
        # the poison quarantined; "abort" must classify the first
        # poison kill cleanly (bounded, never a hang)
        kw["on_worker_failure"] = rng.choice(["abort", "reclaim"])
        # load-aware lease (same rationale as the gray adversities: an
        # innocent expiry under host load would quarantine a second,
        # NON-poison unit and fail the quarantined==1 oracle)
        kw["lease_timeout_s"] = round(
            rng.choice([0.8, 1.2]) * load_factor(), 2)
        # budget 1: the SECOND reclaim quarantines — job A's half-pool
        # (two+ workers) is enough to exceed it
        kw["max_unit_retries"] = 1
        kw["fault_spec"] = {"seed": seed, "poison_types": [2]}
    if native:
        kw["server_impl"] = "native"
    if cap:
        kw["max_malloc_per_server"] = cap
    if do_faults:
        # merge-safe: a gray (poison) spec may already be installed
        kw["fault_spec"] = dict(kw.get("fault_spec") or {},
                                seed=seed, delay=0.03, delay_s=0.002)
    if do_skill:
        # kill a random non-master server a moment into the run (frame
        # counts track protocol activity, so the death lands mid-workload)
        victim_srv = rng.randrange(1, servers)
        kw["fault_spec"] = dict(
            kw.get("fault_spec") or {},
            kill_server_at_frame={victim_srv: rng.randint(30, 120)},
        )
    cfg = Config(**kw)

    if do_stall or do_poison:
        n_units = rng.randint(16, 40)
        victim = rng.randrange(1, apps) if do_stall else None
        # short stalls stay under the 2x hang bar (expiry + fencing only);
        # long ones also trip hang detection (dead-declare + resurrect
        # under "reclaim", world abort under "abort")
        stall_s = round(rng.uniform(1.3, 2.6) * kw["lease_timeout_s"], 2)
        app_fn = gray_economy(n_units, victim=victim, stall_s=stall_s,
                              poison=do_poison, ops_port=gray_port,
                              slo=do_stall)
        desc = dict(apps=apps, servers=servers, mode=mode, cap=cap,
                    workload="gray", stall=do_stall, poison=do_poison,
                    policy=g_policy, stall_s=stall_s if do_stall else None,
                    slo=do_stall, faults=do_faults)
        t0 = time.monotonic()
        try:
            res = spawn_world(apps, servers, [1, 2, 3], app_fn,
                              cfg=cfg, timeout=150.0)
        except RuntimeError:
            # a clean abort classification is a valid outcome under
            # "abort" (hang detection, or a poison kill's EOF) — but it
            # must be CLEAN: bounded, never a hang
            assert g_policy == "abort", "survival policy aborted"
            assert time.monotonic() - t0 < 120.0, "gray abort hung"
            return desc
        if res.aborted:
            assert g_policy == "abort", "survival policy aborted"
            return desc
        # the world completed: coverage must be exact
        n_seen, tails, g_alerts = res.app_results[0]
        assert n_seen == n_units, res.app_results
        # tail-capture oracle: the adversity's journey reached the
        # master's /trace/tails with an anomalous terminal and hops
        # attributed to server ranks only (trace_sample=0, so nothing
        # here came from head sampling)
        server_ranks = set(range(apps, apps + servers))
        js = (tails or {}).get("journeys") or []
        if do_poison:
            quar = [j for j in js if j.get("end") == "quarantined"]
            assert quar, "quarantined journey missing from /trace/tails"
            qj = quar[0]
            assert qj.get("why") == ["quarantined"], qj
            stages = [s[0] for s in qj["spans"]]
            assert stages[0] == "put_recv" and stages[-1] == "finalize", \
                stages
            assert all(s[1] in server_ranks for s in qj["spans"]), \
                qj["spans"]
        if do_stall:
            expired = [
                j for j in js
                if "expire" in [s[0] for s in j.get("spans") or ()]
            ]
            assert expired, "expired-lease journey missing from /trace/tails"
            assert all(
                s[1] in server_ranks for j in expired for s in j["spans"]
            ), expired
        if do_stall:
            # short stall: the victim is fenced, resumes, and reports.
            # long stall (past the 2x hang bar): the world may complete
            # around the hung rank before it resumes — then it is a
            # counted casualty. Either way the FLEET survived with exact
            # coverage; vanishing without a trace is the only failure.
            assert victim in res.app_results or victim in res.casualties, \
                "stalled worker vanished"
            # page oracle (ISSUE 16): the adversity drove the burn-rate
            # engine to a page-severity FIRING (live /alerts state or
            # the transition history — the alert may already have
            # RESOLVED by the time rank 0's poll sampled it) ...
            ga = g_alerts or {}
            fired = any(
                a.get("state") == "FIRING" for a in ga.get("alerts") or []
            ) or any(
                h.get("to") == "FIRING" for h in ga.get("history") or []
            )
            assert fired, f"stall adversity never paged: {ga}"
            # ... and the FIRING snapshotted an incident bundle to the
            # flight dir whose suspect ranks name the SIGSTOP victim
            import glob as _glob
            import json as _json
            bundles = _glob.glob(os.path.join(
                os.environ.get("ADLB_FLIGHT_DIR", ""),
                f"incident-stall-{seed}-p*.json"))
            assert bundles, "page fired but no incident bundle on disk"
            with open(bundles[0]) as fh:
                bundle = _json.load(fh)
            assert victim in (bundle.get("suspect_ranks") or []), \
                (victim, bundle.get("suspect_ranks"))
        if do_poison:
            assert res.quarantined == 1, res.quarantined
            # poison kills at most budget+1 workers, and someone survives
            assert len(res.casualties) <= 3, res.casualties
        return desc

    if do_two_jobs:
        n_units = rng.randint(12, 30)
        tj_policy = kw["on_worker_failure"]
        app_fn = two_jobs_economy(n_units, poison=True)
        desc = dict(apps=apps, servers=servers, mode=mode, cap=cap,
                    workload="two_jobs", policy=tj_policy,
                    faults=do_faults)
        t0 = time.monotonic()
        try:
            res = spawn_world(apps, servers, [1, 2, 3], app_fn,
                              cfg=cfg, timeout=150.0)
        except RuntimeError:
            assert tj_policy == "abort", "survival policy aborted"
            assert time.monotonic() - t0 < 120.0, "two-jobs abort hung"
            return desc
        if res.aborted:
            assert tj_policy == "abort", "survival policy aborted"
            return desc
        # both producers report full coverage of their OWN namespace
        assert res.app_results[0] == n_units, res.app_results
        assert res.app_results[1] == n_units, res.app_results
        # the poison unit was quarantined exactly once, in job A, and
        # only job-A workers (even ranks) could be casualties — job B's
        # pool must come through untouched
        assert res.quarantined == 1, res.quarantined
        assert all(r >= 2 and r % 2 == 0 for r in res.casualties), \
            res.casualties
        assert len(res.casualties) <= 2, res.casualties
        return desc

    if do_skill:
        n_units = rng.randint(24, 60)
        app_fn = coverage_pool(n_units)
        desc = dict(apps=apps, servers=servers, mode=mode, native=native,
                    cap=cap, workload="coverage", skill=True,
                    s_policy=s_policy, victim_srv=victim_srv,
                    faults=do_faults)
        if s_policy == "abort":
            t0 = time.monotonic()
            try:
                res = spawn_world(apps, servers, [1, 2], app_fn,
                                  cfg=cfg, timeout=90.0)
                # the victim server may die after the pool drained; then
                # the world completes before the death can abort it
                done = [x for v in res.app_results.values() for x in v]
                assert sorted(set(done)) == list(range(n_units)), done
            except RuntimeError:
                assert time.monotonic() - t0 < 75.0, "server abort hung"
            return desc
        res = spawn_world(apps, servers, [1, 2], app_fn,
                          cfg=cfg, timeout=150.0)
        done = [x for v in res.app_results.values() for x in v]
        lost = sum(s.get(int(InfoKey.FAILOVER_LOST), 0.0)
                   for s in res.server_stats.values())
        missing = set(range(n_units)) - set(done)
        assert len(missing) <= lost, (sorted(missing), lost)
        assert not res.aborted
        return desc

    if workload == "economy":
        n_pairs = rng.randint(8, 40)
        victim = rng.randrange(1, apps) if do_kill else None
        kill_after = rng.randint(0, 3)
        app_fn = answer_economy(n_pairs, do_abort, do_spray,
                                victim=victim, kill_after=kill_after,
                                kill_at_end=policy == "reclaim")
        want = sum(a + a * 3 for a in range(n_pairs))
        if do_kill and policy == "abort":
            # either the EOF-driven abort classified cleanly (RuntimeError,
            # well before the harness timeout) or the victim finished its
            # share before reaching the kill point and the world completed
            t0 = time.monotonic()
            try:
                res = spawn_world(apps, servers, [1, 2], app_fn,
                                  cfg=cfg, timeout=90.0)
                assert victim in res.app_results, "victim vanished quietly"
                assert res.app_results[0] == want, (res.app_results, want)
            except RuntimeError:
                elapsed = time.monotonic() - t0
                assert elapsed < 60.0, f"abort classification hung {elapsed:.0f}s"
            return dict(apps=apps, servers=servers, mode=mode,
                        native=native, cap=cap, workload=workload,
                        spray=do_spray, abort=do_abort, kill=do_kill,
                        policy=policy, faults=do_faults)
        res = spawn_world(apps, servers, [1, 2], app_fn,
                          cfg=cfg, timeout=90.0)
        if do_abort:
            assert res.aborted, "abort did not propagate"
        else:
            assert res.app_results[0] == want, (res.app_results, want)
            if do_kill:
                # reclaim: the answer set is complete even though the
                # victim died (its leased work was re-enqueued); the
                # victim is a casualty, never an error
                assert res.casualties == [victim], res.casualties
                assert not res.aborted
            else:
                consumed = sum(
                    v for k, v in res.app_results.items() if k != 0)
                assert consumed == n_pairs, res.app_results
    else:
        n = rng.choice([6, 7])
        r = nq.run(n=n, num_app_ranks=apps, nservers=servers,
                   cfg=cfg, timeout=90.0)
        assert r.solutions == nq.KNOWN_SOLUTIONS[n], r.solutions
    return dict(apps=apps, servers=servers, mode=mode, native=native,
                cap=cap, workload=workload, spray=do_spray,
                abort=do_abort, kill=do_kill, policy=policy,
                faults=do_faults)


def main():
    args = list(sys.argv[1:])
    fabric = None
    if "--fabric" in args:
        i = args.index("--fabric")
        fabric = args[i + 1]
        assert fabric in ("auto", "shm", "tcp"), fabric
        del args[i:i + 2]
    minutes = float(args[0]) if args else 10.0
    seed0 = int(args[1]) if len(args) > 1 else 1000
    # every world in the soak writes flight-record post-mortems, so a
    # failure is diagnosable from artifacts instead of demanding a
    # replay (summarize with scripts/obs_report.py <dir>)
    if "ADLB_FLIGHT_DIR" not in os.environ:
        os.environ["ADLB_FLIGHT_DIR"] = __import__("tempfile").mkdtemp(
            prefix="chaos-flight-"
        )
    flight = os.environ["ADLB_FLIGHT_DIR"]
    deadline = time.monotonic() + minutes * 60
    i = 0
    while time.monotonic() < deadline:
        seed = seed0 + i
        try:
            desc = one_iter(seed, fabric=fabric)
        except BaseException as e:
            print(f"CHAOS FAIL seed={seed} fabric={fabric}: {e!r}",
                  flush=True)
            print(f"flight records in {flight} "
                  f"(python scripts/obs_report.py {flight})", flush=True)
            raise
        i += 1
        if i % 10 == 0:
            print(f"{i} iterations ok (last: {desc})", flush=True)
    print(f"CHAOS OK: {i} iterations, no failures")


if __name__ == "__main__":
    main()
