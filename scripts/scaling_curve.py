#!/usr/bin/env python
"""Hotspot scaling curve: TPU balancer vs upstream-faithful stealing as
the server count (and with it, the gossip ring length) grows.

Upstream's global load picture is a store-and-forward ring token at a
fixed interval (reference ``src/adlb.c:165,806-822,1705-1757``): its
staleness is O(ring hops), so the balancing gap should WIDEN with server
count. This script measures that on the all-native plane (C clients, C++
daemons, JAX sidecar — one OS process per rank), printing one row per
scale and a JSON line at the end.

Usage: python scripts/scaling_curve.py [--quick]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="halve task counts (smoke test)")
    ap.add_argument("--plan-sweep", action="store_true",
                    help="run the sharded-balancer planning-latency "
                         "sweep (8-way virtual mesh, to 1,000 servers / "
                         "100k parked requesters) instead of the "
                         "measured-worlds curve")
    args = ap.parse_args()

    if args.plan_sweep:
        # the sweep re-provisions JAX onto a virtual 8-device CPU mesh,
        # so it runs before any world touches the accelerator
        from adlb_tpu.balancer import plan_bench

        raise SystemExit(
            plan_bench.main(["--quick"] if args.quick else []))

    from adlb_tpu.runtime.world import Config
    from adlb_tpu.workloads import hotspot_native

    # apps:servers fixed at 4:1; tasks sized for ~1 s of ideal makespan.
    # Grain: 8 ms through 64 ranks (continuity with earlier rounds); 24 ms
    # at 128 ranks — at 8 ms a 161-process world on this one-core host is
    # kernel-scheduling-bound (~70% idle in BOTH modes, the scheduler
    # decides the draw); the coarser grain keeps 128 ranks in the
    # balancing-bound regime the scenario is about.
    scales = [(16, 4, 8000), (32, 8, 8000), (64, 16, 8000),
              (128, 32, 24000)]
    rows = []
    for apps, servers, work_us in scales:
        n = (apps - 1) * 1000000 // work_us // (2 if args.quick else 1)
        # >= 32 ranks: a 41-161-process world on one core has
        # multi-second scheduler slow phases that swing single draws
        # +-30% in BOTH modes (a round-4 confirmatory run drew a 0.68
        # ratio on a single 32-rank rep whose immediate 3-rep re-draws
        # measured 1.12-1.15); interleaved 3-rep medians keep the rows
        # about balancing
        reps = 1 if (apps < 32 or args.quick) else 3
        runs = {"steal": [], "tpu": []}
        for _ in range(reps):
            for mode in ("steal", "tpu"):
                if mode == "steal":
                    c = Config(balancer="steal", qmstat_mode="ring",
                               qmstat_interval=0.1)
                else:
                    # K=2048 (matching bench.py's native rows): the hot
                    # queue runs ~2k deep and the fair-share pump needs
                    # the real total — a 512-cap snapshot understates the
                    # pool and distorts shares (measured: 16r tpu draws
                    # sag 5-15% under K=512). solver_host_threshold high:
                    # this sidecar has no local accelerator, so every
                    # solve belongs on the numpy path.
                    c = Config(balancer="tpu", balancer_max_tasks=2048,
                               balancer_max_requesters=256,
                               solver_host_threshold=10**6)
                for attempt in (0, 1):
                    try:
                        r = hotspot_native.run(
                            n_tasks=n, work_us=work_us, num_app_ranks=apps,
                            nservers=servers, cfg=c, timeout=180.0,
                        )
                        break
                    except TimeoutError:
                        if attempt:
                            raise
                        print(f"  ({mode}@{servers} timed out; retrying)",
                              file=sys.stderr)
                assert r.tasks == n, f"{mode}@{servers}: lost work ({r.tasks})"
                runs[mode].append(r)

        def med(v, key):
            return sorted(v, key=key)[len(v) // 2]

        per = {m: med(runs[m], key=lambda r: r.tasks_per_sec)
               for m in ("steal", "tpu")}
        ratio = per["tpu"].tasks_per_sec / per["steal"].tasks_per_sec
        row = {
            "apps": apps,
            "servers": servers,
            "steal_tasks_per_sec": round(per["steal"].tasks_per_sec, 1),
            "tpu_tasks_per_sec": round(per["tpu"].tasks_per_sec, 1),
            "ratio": round(ratio, 3),
            "steal_idle_pct": round(per["steal"].idle_pct, 1),
            "tpu_idle_pct": round(per["tpu"].idle_pct, 1),
            "steal_wait_pct": round(per["steal"].wait_pct, 1),
            "tpu_wait_pct": round(per["tpu"].wait_pct, 1),
            "work_us": work_us,
        }
        rows.append(row)
        print(
            f"{apps:4d} ranks / {servers:2d} servers:  "
            f"steal {row['steal_tasks_per_sec']:>8.1f}/s "
            f"(idle {row['steal_idle_pct']:4.1f}%)   "
            f"tpu {row['tpu_tasks_per_sec']:>8.1f}/s "
            f"(idle {row['tpu_idle_pct']:4.1f}%)   ratio {row['ratio']:.3f}"
        )
    print(json.dumps({"metric": "hotspot_scaling_curve", "rows": rows}))


if __name__ == "__main__":
    main()
