#!/usr/bin/env python3
"""Offline summarizer for flight-record JSON artifacts.

A dead world (abort, watchdog timeout, lost home server) leaves one
``flight-rank<R>-<reason>.json`` per rank in the flight directory
(``Config(flight_dir=...)`` / ``ADLB_FLIGHT_DIR``). This tool turns a
directory (or an explicit file list) of them into a post-mortem:

* per rank: role, dump reason, and the tail of its recent-event ring;
* a merged cross-rank **failure timeline**: structured rank_dead /
  lease_reclaimed / targeted_dropped / reconnect / abort events, ordered
  on reconstructed wall-clock time — the post-mortem narrative of who
  died, what was reclaimed where, and who reconnected;
* counter totals (puts/reserves/rfrs/pushes and per-tag message counts)
  summed across ranks, with the top talkers broken out;
* per-server wq/rq queue-depth timelines (min/max/last + a coarse
  sparkline) — the depth history that explains a hang or a flat wait.

With ``--journeys`` the inputs are unit-journey documents instead — the
JSON served by the master's ``/trace/units`` ops route (or any file
holding a ``{"journeys": [...]}`` doc / a bare journey list): prints a
per-stage latency table (p50/p99 by job/type) plus a text waterfall of
the N slowest sampled units (``--slowest N``, default 5).

With ``--tails`` the inputs are ``/trace/tails`` documents (tail-based
promotion, ``Config(trace_tail)``): prints one row per promoted
journey — why it was kept, which stage its excess attributes to, and
the dominant profiler stacks active on the responsible rank during
that stage's window — plus the usual waterfall of the slowest.

With ``--profile`` the inputs are ``/profile?format=json`` documents
(the continuous profiler, ``Config(profile_hz)``): prints top-N
self/cumulative frame tables of the merged fleet profile
(``--top N``, default 15) and, with ``--collapsed PATH``, writes the
flamegraph-compatible collapsed-stack file.

With ``--alerts`` the inputs are ``/alerts`` documents (the SLO engine,
``Config(slo=...)``): one row per objective's alert state (fast/slow
burn rates, degraded/churn-held flags) plus the transition history.

With ``--incidents`` the inputs are ``/incidents`` documents or the
``incident-*.json`` bundles themselves: per incident, the alert that
fired, the suspect ranks, the burn-window metrics delta, the dominant
stacks per responsible rank, and the violating tail journeys.

With ``--index`` the inputs are ``/flight`` inventory documents or a
raw flight directory: one row per post-mortem artifact / incident
bundle (kind, rank, reason, size, age).

Usage:  python scripts/obs_report.py <flight-dir | flight-*.json ...>
        python scripts/obs_report.py --json <...>   (merged record as JSON)
        python scripts/obs_report.py --journeys trace_units.json
        python scripts/obs_report.py --journeys --slowest 8 <file ...>
        python scripts/obs_report.py --tails trace_tails.json
        python scripts/obs_report.py --profile [--top 20]
                                     [--collapsed out.folded] profile.json
        python scripts/obs_report.py --alerts alerts.json
        python scripts/obs_report.py --incidents <flight-dir | file ...>
        python scripts/obs_report.py --index <flight-dir | flight.json>
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from adlb_tpu.obs.metrics import Registry  # noqa: E402

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 32) -> str:
    if not values:
        return ""
    if len(values) > width:  # resample by bucket max (spikes must show)
        step = len(values) / width
        values = [
            max(values[int(i * step): max(int((i + 1) * step), int(i * step) + 1)])
            for i in range(width)
        ]
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1)), len(_SPARK) - 1)]
        for v in values
    )


def load(paths: list[str]) -> list[dict]:
    files: list[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            files.extend(sorted(pp.glob("flight-*.json")))
        else:
            files.append(pp)
    docs = []
    for f in files:
        try:
            doc = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
            continue
        doc["_file"] = str(f)
        docs.append(doc)
    return docs


def _dedup_by_process(docs: list[dict]) -> list[dict]:
    """One artifact per (rank, pid) — a rank can dump several artifacts
    (abort_initiated then abort_event, plus ops /dump), all carrying the
    SAME cumulative counters and timelines; merging every copy would
    double-count. Keep the latest snapshot per process."""
    best: dict[tuple, dict] = {}
    for d in docs:
        if "metrics" not in d:
            continue
        key = (d.get("rank"), d.get("pid"))
        cur = best.get(key)
        if cur is None or d.get("monotonic", 0) >= cur.get("monotonic", 0):
            best[key] = d
    return sorted(best.values(), key=lambda d: d.get("rank", 1 << 30))


def _dedup_metrics(docs: list[dict]) -> list[dict]:
    return [d["metrics"] for d in _dedup_by_process(docs)]


# structured failure events the runtime records with a fixed leading
# keyword (server._on_rank_dead / _resurrect / the failover machinery,
# client._send_retry / _apply_takeover, and the gray-failure surface:
# lease expiry/fencing, hang detection, dead-letter quarantine, and
# overload backpressure)
_FAILURE_PREFIXES = (
    "rank_dead", "lease_reclaimed", "targeted_dropped", "reconnect",
    "abort", "home server", "send to rank",
    "server_dead", "failover_promoted", "failover_lost", "home_takeover",
    "relay_consumed_on_failover", "replication",
    "lease_expired", "rank_hung", "unit_quarantined", "put_backoff",
    "fenced",
)


def failure_timeline(docs: list[dict]) -> list[tuple]:
    """Merge every rank's structured failure events onto one clock.

    Ring entries are stamped with each process's *monotonic* clock;
    ``wall_time - monotonic`` per artifact gives that process's boot
    epoch, so ``epoch + entry_ts`` puts all ranks on comparable wall
    time (skewed only by the clocks themselves). Returns
    ``[(wall_ts, rank, role, text), ...]`` sorted by time."""
    events: list[tuple] = []
    for d in _dedup_by_process(docs) or docs:
        epoch = d.get("wall_time", 0.0) - d.get("monotonic", 0.0)
        for ts, text in d.get("events", []):
            if text.startswith(_FAILURE_PREFIXES):
                events.append(
                    (epoch + ts, d.get("rank", -1), d.get("role", "?"),
                     text)
                )
    events.sort()
    return events


def report(docs: list[dict], tail: int = 8) -> list[str]:
    out: list[str] = []
    ranked = sorted(docs, key=lambda d: d.get("rank", 1 << 30))
    out.append(f"flight artifacts: {len(ranked)}")

    # -- per-rank last events ------------------------------------------------
    for d in ranked:
        rank, role = d.get("rank", "?"), d.get("role", "?")
        reason = d.get("reason", "")
        events = d.get("events", [])
        out.append(
            f"\nrank {rank} [{role}] reason={reason!r} "
            f"({len(events)} ring entries, {d['_file']})"
        )
        for ts, text in events[-tail:]:
            out.append(f"  [{ts:.6f}] {text}")

    # -- failure timeline (merged across ranks) ------------------------------
    timeline = failure_timeline(ranked)
    if timeline:
        out.append("\nfailure timeline (reconstructed wall clock):")
        for wall, rank, role, text in timeline:
            out.append(f"  [{wall:.3f}] rank {rank:>3} [{role}] {text}")

    # -- counter totals across ranks ----------------------------------------
    merged = Registry.merge(_dedup_metrics(ranked))
    if merged["counters"]:
        out.append("\ncounter totals (all ranks):")
        plain = {
            k: v for k, v in merged["counters"].items() if "{" not in k
        }
        for k, v in sorted(plain.items()):
            out.append(f"  {k:<28} {int(v)}")
        tags: dict[str, float] = {}
        for k, v in merged["counters"].items():
            if k.startswith("rx_msgs{") or k.startswith("tx_msgs{"):
                tags[k] = tags.get(k, 0) + v
        if tags:
            out.append("  top message flows:")
            for k, v in sorted(tags.items(), key=lambda kv: -kv[1])[:12]:
                out.append(f"    {k:<40} {int(v)}")

    # -- queue-depth timelines (one per server process) ----------------------
    any_series = False
    for d in _dedup_by_process(ranked):
        series = d.get("metrics", {}).get("series", {})
        for name in ("wq_depth", "rq_depth"):
            samples = series.get(name)
            if not samples:
                continue
            if not any_series:
                out.append("\nqueue-depth timelines (per server rank):")
                any_series = True
            vals = [v for _, v in samples]
            t0, t1 = samples[0][0], samples[-1][0]
            out.append(
                f"  rank {d.get('rank', '?'):>3} {name:<8} "
                f"n={len(vals):<5} min={min(vals):<6g} max={max(vals):<6g} "
                f"last={vals[-1]:<6g} span={t1 - t0:>7.2f}s "
                f"{sparkline(vals)}"
            )
    return out


# ------------------------------------------------------- journey report


def _pctl(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over raw per-journey samples (exact — the
    offline tool sees the spans themselves, not log buckets)."""
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def load_journeys(paths: list[str]) -> list[dict]:
    """Accept /trace/units response docs, bare journey lists, or flight
    dirs holding either as *.json files."""
    files: list[Path] = []
    for p in paths:
        pp = Path(p)
        files.extend(sorted(pp.glob("*.json")) if pp.is_dir() else [pp])
    out: list[dict] = []
    for f in files:
        try:
            doc = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
            continue
        if isinstance(doc, dict):
            doc = doc.get("journeys", [])
        out.extend(j for j in doc if isinstance(j, dict) and j.get("spans"))
    return out


def journey_report(journeys: list[dict], slowest: int = 5) -> list[str]:
    out = [f"journeys: {len(journeys)}"]
    ends: dict[str, int] = {}
    for j in journeys:
        ends[j.get("end", "?")] = ends.get(j.get("end", "?"), 0) + 1
    out.append("ends: " + ", ".join(
        f"{k}={v}" for k, v in sorted(ends.items())
    ))

    # -- per-stage latency table (p50/p99 by job/type) -----------------------
    # stage latency = time to REACH the stage from the previous span,
    # the same attribution the live unit_stage_s histograms use
    cells: dict[tuple, list[float]] = {}
    totals: dict[tuple, list[float]] = {}
    for j in journeys:
        key = (j.get("job", 0), j.get("type", -1))
        spans = j["spans"]
        totals.setdefault(key, []).append(
            j.get("total_s", spans[-1][2] - spans[0][2])
        )
        prev_t = spans[0][2]
        for stage, _rank, t in spans[1:]:
            cells.setdefault(key + (stage,), []).append(max(t - prev_t, 0.0))
            prev_t = t
    if cells:
        out.append("\nper-stage latency (ms) by job/type:")
        out.append(
            f"  {'job':>4} {'type':>5} {'stage':<11} {'n':>6} "
            f"{'p50':>9} {'p99':>9} {'max':>9}"
        )
        for (job, typ, stage), vals in sorted(cells.items()):
            vals.sort()
            out.append(
                f"  {job:>4} {typ:>5} {stage:<11} {len(vals):>6} "
                f"{_pctl(vals, 0.50) * 1e3:>9.3f} "
                f"{_pctl(vals, 0.99) * 1e3:>9.3f} "
                f"{vals[-1] * 1e3:>9.3f}"
            )
        for (job, typ), vals in sorted(totals.items()):
            vals.sort()
            out.append(
                f"  {job:>4} {typ:>5} {'TOTAL':<11} {len(vals):>6} "
                f"{_pctl(vals, 0.50) * 1e3:>9.3f} "
                f"{_pctl(vals, 0.99) * 1e3:>9.3f} "
                f"{vals[-1] * 1e3:>9.3f}"
            )

    # -- waterfall of the N slowest units ------------------------------------
    ranked = sorted(
        journeys,
        key=lambda j: j.get("total_s",
                            j["spans"][-1][2] - j["spans"][0][2]),
        reverse=True,
    )[:slowest]
    if ranked:
        out.append(f"\nslowest {len(ranked)} sampled units (waterfall):")
    width = 40
    for j in ranked:
        spans = j["spans"]
        t0, t1 = spans[0][2], spans[-1][2]
        span_s = (t1 - t0) or 1e-9
        out.append(
            f"  unit trace_id={j.get('trace_id')} job={j.get('job', 0)} "
            f"type={j.get('type', -1)} end={j.get('end')} "
            f"total={span_s * 1e3:.3f} ms"
        )
        prev_t = t0
        for stage, rank, t in spans:
            off = int((prev_t - t0) / span_s * width)
            ln = max(int((t - prev_t) / span_s * width), 0)
            bar = " " * off + ("·" if ln == 0 else "█" * ln)
            out.append(
                f"    {stage:<11} rank {rank:>3} "
                f"+{(t - prev_t) * 1e3:>9.3f} ms |{bar:<{width + 1}}|"
            )
            prev_t = t
    return out


# ------------------------------------------------------- tail report


def tails_report(journeys: list[dict], slowest: int = 5) -> list[str]:
    """One row per promoted tail journey: the retention reasons, the
    stage the excess attributes to, and the dominant profiler stacks on
    the responsible rank during that stage's window (annotations are
    computed server-side by the /trace/tails join)."""
    out = [f"tail journeys: {len(journeys)}"]
    whys: dict[str, int] = {}
    for j in journeys:
        for w in j.get("why") or ("?",):
            whys[w] = whys.get(w, 0) + 1
    out.append("promoted because: " + ", ".join(
        f"{k}={v}" for k, v in sorted(whys.items())
    ))
    out.append(
        f"\n  {'trace_id':>16} {'job':>4} {'type':>5} {'end':<12} "
        f"{'total_ms':>9} {'slow stage':<11} {'rank':>4} {'excess_ms':>9}"
    )
    ranked = sorted(journeys, key=lambda j: -j.get("total_s", 0.0))
    for j in ranked:
        out.append(
            f"  {j.get('trace_id', 0):>16} {j.get('job', 0):>4} "
            f"{j.get('type', -1):>5} {j.get('end', '?'):<12} "
            f"{j.get('total_s', 0.0) * 1e3:>9.3f} "
            f"{j.get('slow_stage', '-'):<11} "
            f"{j.get('slow_rank', -1):>4} "
            f"{j.get('excess_s', 0.0) * 1e3:>9.3f}"
        )
        for stack, n in (j.get("stacks") or [])[:3]:
            out.append(f"      [{n:>4} samples] {stack}")
    out.append("")
    out.extend(journey_report(journeys, slowest=slowest)[2:])
    return out


# --------------------------------------------- alerts / incidents / index


def load_docs(paths: list[str], glob: str = "*.json") -> list[dict]:
    """Generic JSON doc loader (files or dirs), for the /alerts,
    /incidents and /flight response shapes."""
    files: list[Path] = []
    for p in paths:
        pp = Path(p)
        files.extend(sorted(pp.glob(glob)) if pp.is_dir() else [pp])
    out: list[dict] = []
    for f in files:
        try:
            doc = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
            continue
        if isinstance(doc, dict):
            doc["_file"] = str(f)
            out.append(doc)
    return out


def alerts_report(docs: list[dict]) -> list[str]:
    """Render /alerts documents: one row per objective's alert state
    (burn rates, degraded flag), then the transition history."""
    out: list[str] = []
    for doc in docs:
        alerts = doc.get("alerts") or []
        out.append(
            f"slo engine: enabled={doc.get('enabled', False)} "
            f"objectives={len(doc.get('objectives') or [])} "
            f"firing={doc.get('firing', 0)}"
        )
        if alerts:
            out.append(
                f"\n  {'name':<24} {'state':<9} {'sev':<5} "
                f"{'burn_fast':>9} {'burn_slow':>9} {'fired':>6} {'flags'}"
            )
        for a in alerts:
            flags = []
            if a.get("degraded"):
                flags.append(f"degraded({a.get('stale_ranks')})")
            if a.get("held"):
                flags.append("churn-held")
            out.append(
                f"  {a.get('name', '?'):<24} {a.get('state', '?'):<9} "
                f"{a.get('severity', '?'):<5} "
                f"{a.get('burn_fast', 0.0):>9.3f} "
                f"{a.get('burn_slow', 0.0):>9.3f} "
                f"{a.get('fire_count', 0):>6} {' '.join(flags)}"
            )
        hist = doc.get("history") or []
        if hist:
            out.append("\ntransition history:")
            for t in hist:
                out.append(
                    f"  [{t.get('at', 0.0):.3f}] {t.get('name', '?')} "
                    f"{t.get('from', '?')} -> {t.get('to', '?')} "
                    f"sev={t.get('severity')} "
                    f"burn={t.get('burn_fast')}/{t.get('burn_slow')}"
                )
    return out


def incidents_report(docs: list[dict], slowest: int = 5) -> list[str]:
    """Render incident bundles (/incidents docs or incident-*.json
    artifacts): the alert that fired, the suspect ranks, the violating
    tails, and the dominant stacks per responsible rank."""
    bundles: list[dict] = []
    for doc in docs:
        if "incidents" in doc:
            bundles.extend(doc["incidents"])
        elif "incident" in doc:
            bundles.append(doc)
    out = [f"incidents: {len(bundles)}"]
    for b in bundles:
        tr = b.get("transition") or {}
        out.append(
            f"\nincident {b.get('incident', '?')} "
            f"sev={b.get('severity', '?')} job={b.get('job')} "
            f"type={b.get('type')} epoch={b.get('epoch')}"
        )
        out.append(
            f"  fired {tr.get('from', '?')} -> {tr.get('to', '?')} "
            f"burn={tr.get('burn_fast')}/{tr.get('burn_slow')} "
            f"degraded={tr.get('degraded', False)}"
        )
        out.append(f"  suspect ranks: {b.get('suspect_ranks')}")
        delta = b.get("metrics_delta") or {}
        out.append(
            f"  burn-window delta: span={delta.get('span_s')}s "
            f"counters={len(delta.get('counters') or {})} "
            f"histograms={len(delta.get('histograms') or {})}"
        )
        for rank, stacks in sorted((b.get("stacks") or {}).items()):
            out.append(f"  rank {rank} dominant stacks:")
            for stack, n in stacks[:3]:
                out.append(f"    [{n:>4} samples] {stack}")
        tails = b.get("tails") or []
        if tails:
            out.append(f"  violating tails ({len(tails)}):")
            out.extend("  " + ln for ln in
                       tails_report(tails, slowest=slowest)[2:])
    return out


def index_report(docs: list[dict]) -> list[str]:
    """Render /flight inventory documents: one row per artifact."""
    out: list[str] = []
    for doc in docs:
        arts = doc.get("artifacts") or []
        out.append(
            f"flight dir {doc.get('flight_dir')}: {len(arts)} artifacts"
        )
        if arts:
            out.append(
                f"  {'kind':<9} {'rank':>4} {'reason':<28} "
                f"{'bytes':>8} {'age_s':>8}  file"
            )
        for a in arts:
            rank = a.get("rank")
            out.append(
                f"  {a.get('kind', '?'):<9} "
                f"{'-' if rank is None else rank:>4} "
                f"{a.get('reason', '?'):<28} {a.get('bytes', 0):>8} "
                f"{a.get('age_s', 0.0):>8.1f}  {a.get('file')}"
            )
    return out


# ----------------------------------------------------- profile report


def load_profiles(paths: list[str]) -> dict:
    """Merge /profile?format=json documents (or bare {stack: count}
    dicts) from files/dirs into one {stack: count} map."""
    files: list[Path] = []
    for p in paths:
        pp = Path(p)
        files.extend(sorted(pp.glob("*.json")) if pp.is_dir() else [pp])
    merged: dict[str, int] = {}
    for f in files:
        try:
            doc = json.loads(f.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
            continue
        stacks = doc.get("merged", doc) if isinstance(doc, dict) else {}
        for k, v in stacks.items():
            if isinstance(v, (int, float)):
                merged[k] = merged.get(k, 0) + int(v)
    return merged


def profile_report(stacks: dict, top: int = 15) -> list[str]:
    """Top-N frames by self and by cumulative samples. Self = samples
    whose stack ENDS at the frame; cumulative = samples whose stack
    contains it (deduped per stack, so recursion cannot double-count)."""
    total = sum(stacks.values())
    out = [f"profile: {len(stacks)} folded stacks, {total} samples"]
    self_c: dict[str, int] = {}
    cum_c: dict[str, int] = {}
    for stack, n in stacks.items():
        frames = stack.split(";")
        self_c[frames[-1]] = self_c.get(frames[-1], 0) + n
        for fr in set(frames):
            cum_c[fr] = cum_c.get(fr, 0) + n
    for title, table in (("self", self_c), ("cumulative", cum_c)):
        out.append(f"\ntop {top} frames by {title} samples:")
        out.append(f"  {'samples':>8} {'%':>6}  frame")
        for fr, n in sorted(table.items(), key=lambda kv: -kv[1])[:top]:
            pct = 100.0 * n / total if total else 0.0
            out.append(f"  {n:>8} {pct:>5.1f}%  {fr}")
    return out


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("-")]

    def opt(name, default, cast):
        if name not in argv:
            return default
        val = argv[argv.index(name) + 1]
        paths[:] = [a for a in paths if a != val]
        return cast(val)

    slowest = opt("--slowest", 5, int)
    top = opt("--top", 15, int)
    collapsed = opt("--collapsed", None, str)
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2
    if "--journeys" in argv or "--tails" in argv:
        journeys = load_journeys(paths)
        if not journeys:
            print("no journeys found", file=sys.stderr)
            return 1
        if as_json:
            print(json.dumps({"journeys": journeys}))
            return 0
        rep = tails_report if "--tails" in argv else journey_report
        print("\n".join(rep(journeys, slowest=slowest)))
        return 0
    if "--alerts" in argv:
        docs = load_docs(paths)
        if as_json:
            print(json.dumps({"docs": docs}))
            return 0
        print("\n".join(alerts_report(docs)))
        return 0
    if "--incidents" in argv:
        docs = load_docs(paths, glob="incident-*.json")
        if not docs:
            print("no incident bundles found", file=sys.stderr)
            return 1
        if as_json:
            print(json.dumps({"docs": docs}))
            return 0
        print("\n".join(incidents_report(docs, slowest=slowest)))
        return 0
    if "--index" in argv:
        # accept /flight response docs OR a raw flight dir (build the
        # inventory locally with the same filename contract)
        docs = []
        for p in list(paths):
            pp = Path(p)
            if pp.is_dir():
                import re
                import time

                arts = []
                now = time.time()
                for f in sorted(pp.glob("*.json")):
                    m = re.match(
                        r"(flight|incident)-(?:rank(\d+)-)?"
                        r"(.+?)-p(\d+)\.json$", f.name,
                    )
                    if m is None:
                        continue
                    kind, rank, slug, pid = m.groups()
                    st = f.stat()
                    arts.append({
                        "file": f.name,
                        "kind": ("incident" if kind == "incident"
                                 else "flight"),
                        "rank": int(rank) if rank is not None else None,
                        "reason": slug, "pid": int(pid),
                        "bytes": st.st_size,
                        "age_s": round(max(now - st.st_mtime, 0.0), 3),
                    })
                docs.append({"flight_dir": str(pp), "artifacts": arts})
            else:
                docs.extend(load_docs([p]))
        if as_json:
            print(json.dumps({"docs": docs}))
            return 0
        print("\n".join(index_report(docs)))
        return 0
    if "--profile" in argv:
        stacks = load_profiles(paths)
        if not stacks:
            print("no profile stacks found", file=sys.stderr)
            return 1
        if collapsed:
            Path(collapsed).write_text("".join(
                f"{k} {v}\n" for k, v in
                sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
            ))
            print(f"collapsed stacks written to {collapsed}")
        if as_json:
            print(json.dumps({"merged": stacks}))
            return 0
        print("\n".join(profile_report(stacks, top=top)))
        return 0
    docs = load(paths)
    if not docs:
        print("no flight artifacts found", file=sys.stderr)
        return 1
    if as_json:
        merged = Registry.merge(_dedup_metrics(docs))
        print(json.dumps({"artifacts": docs, "merged_counters": merged}))
        return 0
    print("\n".join(report(docs)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
