#!/usr/bin/env python
"""Fit the shared-core simulator's three host constants to a measured
scaling curve (scripts/scaling_curve.py output), then report per-cell
error.  Used each time the engine changes enough to re-measure the
curve: re-run the curve, re-fit here, paste the winning constants +
curve into scripts/sim_scale.py, and re-pin tests/test_sim_scale.py.

Usage:
  python scripts/fit_sim.py '{"4": [0.008, 1632, 1774], ...}'
  (keys = servers, values = [grain_s, steal_tasks/s, tpu_tasks/s];
   defaults to sim_scale.MEASURED_CURVE when no argument is given)
"""

from __future__ import annotations

import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sim_scale import MEASURED_CURVE, Sim  # noqa: E402


def fit(curve) -> dict:
    best = None
    # grid spans: t_serve_shared around the protocol-exchange CPU cost,
    # wake term around the kernel's per-completion runqueue cost
    for ts, tw, fl in itertools.product(
        (24e-6, 28e-6, 32e-6, 36e-6, 40e-6, 48e-6),
        (0.0, 1.5e-6, 2.25e-6, 3.0e-6, 4.5e-6, 6.0e-6),
        (4, 8, 16),
    ):
        worst = 0.0
        cells = {}
        for s, (wt, m_steal, m_tpu) in curve.items():
            r_s = Sim(nservers=s, mode="steal", shared_core=True,
                      work_time=wt, t_serve_shared=ts,
                      t_wake_per_busy=tw, wake_busy_floor=fl).run()
            r_t = Sim(nservers=s, mode="tpu", shared_core=True,
                      work_time=wt, t_serve_shared=ts,
                      t_wake_per_busy=tw, wake_busy_floor=fl).run()
            es = r_s["tasks_per_sec"] / m_steal - 1.0
            et = r_t["tasks_per_sec"] / m_tpu - 1.0
            cells[s] = (round(es, 3), round(et, 3))
            worst = max(worst, abs(es), abs(et))
        if best is None or worst < best["worst"]:
            best = {"t_serve_shared": ts, "t_wake_per_busy": tw,
                    "wake_busy_floor": fl, "worst": round(worst, 3),
                    "cells": cells}
    return best


def main() -> None:
    if len(sys.argv) > 1:
        raw = json.loads(sys.argv[1])
        curve = {int(k): tuple(v) for k, v in raw.items()}
    else:
        curve = MEASURED_CURVE
    best = fit(curve)
    print(json.dumps({"curve": {str(k): v for k, v in curve.items()},
                      "best_fit": best}, indent=2))


if __name__ == "__main__":
    main()
