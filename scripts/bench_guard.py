#!/usr/bin/env python
"""Bench regression guard: diff a fresh BENCH record against a pinned
baseline and FAIL on latency regressions of the guarded per-op rows.

    python scripts/bench_guard.py NEW.json [--baseline BENCH_r05.json]
                                  [--threshold 0.15]

Guarded rows (latencies — higher is worse):

* ``coinop_p50`` — the all-native plane's pop-latency probe
  (``native_coinop_p50_ms_steal`` / ``_tpu``), the per-op transport
  floor;
* ``pop_p50`` — the Python plane's pop service latency
  (``steal_pop_latency_p50_ms`` / ``tpu_pop_latency_p50_ms``).

A guarded value more than ``threshold`` (default 15%) above the
baseline exits 1, naming the row. A guarded value MISSING from the new
record also fails (a silently dropped metric reads as "no regression"
forever); one missing from the baseline is skipped with a note.

Both file shapes are accepted: the driver's wrapper
(``{"tail": ..., "parsed": {...}}`` — values are regex-scanned out of
the raw tail when the parsed compact record lacks them) and bench.py's
own compact JSON line.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

# guarded row -> list of (label, raw-text key) latency scalars
GUARDS = {
    "coinop_p50": [
        ("steal", "native_coinop_p50_ms_steal"),
        ("tpu", "native_coinop_p50_ms_tpu"),
    ],
    "pop_p50": [
        ("steal", "steal_pop_latency_p50_ms"),
        ("tpu", "tpu_pop_latency_p50_ms"),
    ],
    # the batched global solve's end-to-end latency (snapshot->pairs,
    # device path forced, 4096x512 pool) — the balancer-brain budget
    "solve_ms": [
        ("4096x512", "solve_4096x512_ms"),
    ],
    # the multichip planning round on the 8-way simulated mesh: 1,000
    # servers / 100k parked (r06 metric) and 10,000 servers / 1M parked
    # (first carried by the post-r10 record; older baselines skip it
    # with a note, per the missing-baseline rule). Both cells measure
    # the HOST auction tier — on a host-SIMULATED mesh the on-device
    # tier is dominated by the fixed 8-way virtual-device
    # dispatch/rendezvous cost (~90 ms/call at any scale, see
    # MULTICHIP_r08), which would drown real regressions; the device
    # tier is pair-list-fuzzed in CI and its host-sim latency recorded
    # per MULTICHIP round instead.
    "plan_round": [
        ("1k", "plan_round_1k_ms"),
        ("10k", "plan_round_10k_ms"),
    ],
    # host-tier round admission at 1k parked — the r07 2.4x floor the
    # stamp-keyed SnapshotStore sync removed (first carried by the
    # post-r10 record; older baselines skip with a note).
    # MILLISECONDS, array ledger arm.
    "admission": [
        ("1k", "admission_1k_ms"),
    ],
    # host-tier round admission at 100k parked requesters (r08 metric;
    # older baselines skip with a note): engine.round() p50 in
    # MICROSECONDS on the array-resident ledger. Guarded cell is the
    # array path only — the compact pair's second cell is the py twin,
    # kept for reference (it IS the regression the ledger removed).
    "engine_round": [
        ("100k", "engine_round_us_100k"),
    ],
    # shm ring fabric (r07 metrics; older baselines skip with a note):
    # pop latency over real processes on the ring fabric vs the same
    # world on TCP, classic two-call consumer + the batched path
    "coinop_shm": [
        ("shm", "coinop_shm_p50_ms"),
        ("tcp", "coinop_spawn_tcp_p50_ms"),
        ("shm-batch8", "coinop_shm_batch8_p50_ms"),
    ],
    # >1 MiB payload put latency, shm vs tcp (r07)
    "put_large": [
        ("shm", "put_large_p50_ms_shm"),
        ("tcp", "put_large_p50_ms_tcp"),
    ],
    # spill tier: disk fault-in latency for a 1 MiB payload (r07)
    "spill": [
        ("faultin", "spill_faultin_ms"),
    ],
    # compiled wire codec: per-frame encode cost on the wire-native
    # frame mix (r08 metric; older baselines skip with a note). The
    # guarded cell is the ACTIVE implementation's row — a py-fallback
    # record regresses vs a compiled baseline, which is the point.
    "codec": [
        ("encode", "codec_encode_us"),
    ],
    # multiplexed channel plane: pop p50 over real processes with every
    # frame riding the host broker (r08; older baselines skip)
    "coinop_mux": [
        ("mux", "coinop_mux_p50_ms"),
    ],
    # unit-lifecycle tracing (r09 metrics; older baselines skip with a
    # note): pop p50 with every put head-sampled, vs the same world with
    # tracing off — the SLO sensor layer's hot-path cost rows
    "trace_overhead": [
        ("traced", "coinop_trace_p50_ms"),
        ("off", "coinop_notrace_p50_ms"),
    ],
    # tail-based promotion + continuous profiler (r10 metrics; older
    # baselines skip with a note): pop p50 with trace_tail forced on /
    # the 19 Hz profiler sampling / both off, interleaved pairs
    "tail_profile_overhead": [
        ("tail", "coinop_tail_p50_ms"),
        ("prof", "coinop_prof_p50_ms"),
        ("off", "coinop_tailprof_off_p50_ms"),
    ],
    # elastic membership (r11 metrics; older baselines skip with a
    # note): attach latency — rank allocation + the fleet-wide
    # fan-out/ack barrier — and server scale-out MTTR (scale request ->
    # shard spawned + donor-rebalanced + counted ready by the master).
    # Once a baseline carries them, a record MISSING either row fails
    # (the ISSUE 15 missing-row=fail arm).
    "member": [
        ("attach", "attach_ms"),
        ("scaleout", "scaleout_mttr_ms"),
    ],
    # tail hedging (r12 metric; older baselines skip with a note): the
    # SIGSTOP-straggler arm's completion time with the hedge plane ON —
    # a regression here means the speculative rescue got slower (or
    # stopped firing, in which case the value jumps to the stall
    # length). The off arm rides in the compact pair for reference.
    "hedge": [
        ("rescue", "hedge_p999_on_ms"),
    ],
    # multi-job fairness (r13 metric; older baselines skip with a note,
    # the r08 policy): the light tenant's weighted-arm p99 sojourn
    # under a heavy flood through the planned path — a regression means
    # the fair-share bias stopped shielding the tenant. The unweighted
    # arm rides the compact pair for reference (it IS the number the
    # weights exist to beat), and the ratio is recorded alongside.
    "fairness": [
        ("weighted", "fairness_weighted_p99_ms"),
    ],
    # master failover (r20 metrics; older baselines skip with a note,
    # the r08 policy): the ring-deputy's detection->takeover MTTR with
    # the MASTER SIGKILLed mid-run (median over 3 TCP worlds), and the
    # standing deputy's quiet-time cost — put-storm wall-clock with the
    # brain stream on over the identical world with it off. The ratio
    # cell is unitless; a regression there means the always-on brain
    # replication started taxing the hot path while nothing was dying.
    "master_failover": [
        ("mttr", "master_failover_mttr_ms"),
        ("brain-ratio", "brain_repl_overhead_ratio"),
    ],
    # fleet controller (r13 metric; older baselines skip with a note):
    # closed-loop scale-out reaction — pressure step to the
    # controller-spawned shard live in the membership table. Once a
    # baseline carries it, a record missing the row fails.
    "control": [
        ("autoscale", "autoscale_react_ms"),
    ],
}

# Absolute arms: self-contained bounds checked against the NEW record
# alone (no baseline needed — the bound IS the acceptance bar).
# (key, max allowed value, description)
ABSOLUTE = [
    # the DEFAULT sample rate may cost at most 5% of coinop pop p50
    # (ISSUE 13 acceptance); full sampling is gated baseline-relative
    # via the trace_overhead rows above
    ("trace_overhead_ratio", 1.05,
     "default-sample-rate/untraced coinop pop p50 ratio"),
    # tail mode arms spans on EVERY unit (retention decided at close);
    # the profiler samples at 19 Hz — each may add at most 5% to the
    # 2000-token coinop run's CPU (ISSUE 14 acceptance; run-CPU
    # adjacent pairs because pop-p50 pair noise on the 1-core box is
    # +-15%, scheduler-bound — the same caveat behind the cpu-count
    # skip above; added CPU is what surfaces as latency on any
    # saturated core)
    ("trace_tail_overhead_ratio", 1.05,
     "trace_tail-on/off coinop run-CPU adjacent-pair ratio"),
    ("profile_overhead_ratio", 1.05,
     "profiler-19Hz/off coinop run-CPU adjacent-pair ratio"),
    # ISSUE 16: the master-side burn-rate evaluator (8 objectives,
    # tight windows) may cost at most 5% run-CPU over the identical
    # observed-but-unobjectived world
    ("slo_overhead_ratio", 1.05,
     "slo-eval-armed/off coinop run-CPU adjacent-pair ratio"),
    # ISSUE 17: hedging is budget-bounded and backpressure-subordinate
    # STRUCTURALLY — the storm arm may never launch past the token
    # bucket (frac x deliveries + burst) and a sticky-vetoed origin may
    # never launch a sibling afterwards; both bounds are exact zeros
    ("hedge_storm_launch_excess", 0.0,
     "hedge launches over the token-bucket bound under a put storm"),
    ("hedge_storm_veto_breaches", 0.0,
     "sticky-vetoed origins that later launched a sibling"),
]

_NUM = r"(-?[0-9]+(?:\.[0-9]+)?)"


def _load(path: str) -> tuple[dict, str]:
    """(parsed compact detail or {}, raw searchable text)."""
    with open(path) as f:
        raw = f.read()
    try:
        doc = json.loads(raw)
    except ValueError:
        return {}, raw
    if isinstance(doc, dict) and "parsed" in doc:
        parsed = doc.get("parsed") or {}
        detail = parsed.get("detail", {}) if isinstance(parsed, dict) else {}
        # the decoded tail (json.loads already unescaped it) is the
        # searchable text — the raw file holds it \"-escaped
        return detail, str(doc.get("tail") or "")
    if isinstance(doc, dict):
        return doc.get("detail", doc), raw
    return {}, raw


def _scan(text: str, key: str):
    m = re.search(rf'"{re.escape(key)}":\s*{_NUM}', text)
    return float(m.group(1)) if m else None


def extract(detail: dict, text: str, row: str, idx: int,
            raw_key: str):
    """One guarded scalar: the compact pair list first (row key holds
    [steal, tpu]), then a raw-text scan for the long-form key."""
    pair = detail.get(row)
    if isinstance(pair, (list, tuple)) and len(pair) > idx and \
            isinstance(pair[idx], (int, float)):
        return float(pair[idx])
    v = detail.get(raw_key)
    if isinstance(v, (int, float)):
        return float(v)
    return _scan(text, raw_key)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("new", help="fresh BENCH record (json)")
    ap.add_argument("--baseline", default="BENCH_r05.json")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="allowed fractional regression (0.15 = 15%%)")
    args = ap.parse_args(argv)

    new_detail, new_text = _load(args.new)
    base_detail, base_text = _load(args.baseline)

    # measurement-provenance gate (the r07 caveat made policy): latency
    # rows measured on different core counts are not comparable — a
    # 1-core box's numbers are scheduler-bound, a 4-core box's are not.
    # Records carry cpu_count since r08; when both sides have it and
    # they disagree, print a skip-note instead of failing the build.
    base_cpus = extract(base_detail, base_text, "", 0, "cpu_count")
    new_cpus = extract(new_detail, new_text, "", 0, "cpu_count")
    if base_cpus and new_cpus and int(base_cpus) != int(new_cpus):
        print(
            f"[bench-guard] SKIP: baseline measured on {int(base_cpus)} "
            f"core(s), candidate on {int(new_cpus)} — latency rows are "
            f"scheduler-bound incomparable across core counts; "
            f"re-measure both on one box to re-arm the guard"
        )
        return 0

    failures = []
    checked = 0
    for row, cells in GUARDS.items():
        for idx, (label, raw_key) in enumerate(cells):
            base = extract(base_detail, base_text, row, idx, raw_key)
            if base is None or base <= 0:
                print(f"[bench-guard] {row}[{label}]: no usable baseline "
                      f"in {args.baseline}; skipped")
                continue
            new = extract(new_detail, new_text, row, idx, raw_key)
            if new is None:
                failures.append(
                    f"{row}[{label}]: MISSING from {args.new} "
                    f"(baseline {base:.3f} ms) — a dropped metric is "
                    f"not a pass"
                )
                continue
            checked += 1
            ratio = new / base
            verdict = "OK"
            if ratio > 1.0 + args.threshold:
                verdict = "REGRESSION"
                failures.append(
                    f"{row}[{label}]: {new:.3f} ms vs baseline "
                    f"{base:.3f} ms ({(ratio - 1) * 100:+.1f}% > "
                    f"{args.threshold * 100:.0f}% allowed)"
                )
            print(f"[bench-guard] {row}[{label}]: new {new:.3f} ms, "
                  f"baseline {base:.3f} ms ({(ratio - 1) * 100:+.1f}%) "
                  f"{verdict}")
    # absolute arms: bound the NEW record directly (the bound is the
    # acceptance bar, so no baseline row is needed); a metric absent
    # from BOTH records is a not-yet-armed row, skipped with a note
    for key, bound, desc in ABSOLUTE:
        new = extract(new_detail, new_text, "", 0, key)
        if new is None:
            if extract(base_detail, base_text, "", 0, key) is None:
                print(f"[bench-guard] {key}: not present yet; skipped "
                      f"(arms once a record carries it)")
            else:
                failures.append(
                    f"{key}: MISSING from {args.new} but present in the "
                    f"baseline — a dropped metric is not a pass"
                )
            continue
        checked += 1
        if new > bound:
            failures.append(
                f"{key}: {new:.3f} > {bound:.3f} allowed ({desc})"
            )
        print(f"[bench-guard] {key}: {new:.3f} (bound {bound:.3f}, "
              f"{desc}) {'REGRESSION' if new > bound else 'OK'}")
    if failures:
        print("[bench-guard] FAIL:")
        for f in failures:
            print(f"  - {f}")
        return 1
    if checked == 0:
        print("[bench-guard] FAIL: no guarded metric found in either "
              "record")
        return 1
    print(f"[bench-guard] PASS ({checked} guarded rows within "
          f"{args.threshold * 100:.0f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
