#!/usr/bin/env python3
"""Bump ADLB_VERSION_NUMBER / ADLB_VERSION_DATE in include/adlb/adlb.h.

Port of the reference's release helper (reference
``scripts/fix_version.py:1-27``), which derived the new version from the
svn revision; here the number is the repo's commit count (``git rev-list
--count HEAD``) and the date is today, written in place.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_HDR = os.path.join(_REPO, "include", "adlb", "adlb.h")


def main() -> int:
    try:
        n = int(
            subprocess.run(
                ["git", "rev-list", "--count", "HEAD"],
                cwd=_REPO, check=True, capture_output=True, text=True,
            ).stdout.strip()
        )
    except (subprocess.CalledProcessError, OSError, ValueError) as e:
        print(f"cannot obtain revision number: {e}", file=sys.stderr)
        return 1
    date = time.strftime("%d-%b-%Y")
    out = []
    saw_number = saw_date = False
    with open(_HDR) as f:
        for line in f:
            if re.match(r"#define\s+ADLB_VERSION_NUMBER\b", line):
                out.append(f"#define ADLB_VERSION_NUMBER {n}\n")
                saw_number = True
            elif re.match(r"#define\s+ADLB_VERSION_DATE\b", line):
                out.append(f'#define ADLB_VERSION_DATE "{date}"\n')
                saw_date = True
            else:
                out.append(line)
    if saw_number and not saw_date:
        # insert the date right after the number, like the reference header
        for i, line in enumerate(out):
            if "ADLB_VERSION_NUMBER" in line:
                out.insert(i + 1, f'#define ADLB_VERSION_DATE "{date}"\n')
                break
    if not saw_number:
        print("ADLB_VERSION_NUMBER not found in adlb.h", file=sys.stderr)
        return 1
    with open(_HDR, "w") as f:
        f.writelines(out)
    print(f"adlb.h -> version {n}, {date}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
