#!/usr/bin/env python3
"""Offline decoder for STAT_APS periodic-stats log lines.

The rebuild's equivalent of the reference's ``scripts/get_stats.py:1-117``:
reads one or more log files (or stdin), reassembles the chunked ``STAT_APS:``
lines the master server prints every ``periodic_log_interval`` seconds, and
prints a per-period activity table (queue depths by type, waiting requesters,
put/resolved-reserve rates).

Usage:  python scripts/get_stats.py [logfile ...]   (no args = stdin)
        python scripts/get_stats.py --json logfile  (raw records as JSON)
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from adlb_tpu.runtime.stats import parse_stat_lines, summarize  # noqa: E402


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("-")]
    # parse and summarize each file independently: seq numbers and cumulative
    # counters restart per run, so records must never mix across files
    groups: list[list[dict]] = []
    if paths:
        for p in paths:
            groups.append(parse_stat_lines(Path(p).read_text().splitlines()))
    else:
        groups.append(parse_stat_lines(sys.stdin.read().splitlines()))
    if not any(groups):
        print("no STAT_APS records found", file=sys.stderr)
        return 1
    if as_json:
        for records in groups:
            for r in records:
                print(json.dumps(r))
        return 0

    rows: list[dict] = []
    for records in groups:
        rows.extend(summarize(records))
    hdr = f"{'seq':>5} {'wq':>7} {'rq':>5} {'KB':>8} {'puts/s':>9} {'res/s':>9} {'trip_ms':>8}  by_type"
    print(hdr)
    print("-" * len(hdr))
    for row in rows:
        by_type = " ".join(
            f"t{t}:{c['untargeted']}u/{c['targeted']}t"
            for t, c in row["by_type"].items()
        )
        print(
            f"{row['seq']:>5} {row['wq_total']:>7} {row['rq_total']:>5} "
            f"{row['nbytes'] / 1024:>8.1f} "
            f"{row.get('puts_per_s', float('nan')):>9.1f} "
            f"{row.get('resolved_per_s', float('nan')):>9.1f} "
            f"{row['trip_s'] * 1e3:>8.2f}  {by_type}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
