"""Dogfood the continuous profiler on the oldest bench debt (ROADMAP
item 5c): tsp/gfmc tpu-vs-steal parity sits at ~0.91-0.93x and the
PR 11 probe proved it is NOT codec-bound. Run the SAME workloads the
parity rows measure with ``profile_hz=19`` armed, capture the
per-(role, phase) sample attribution for each balancer mode, and diff
them — the phases that grow under "tpu" but not "steal" ARE the
residual, named by the profiler instead of guessed at.

Method: tsp/gfmc ride run_world (one process, thread ranks), so the
first server to call ``profile.start`` owns the single per-process
sampler and every rank's threads land in it role-tagged. A watcher
thread grabs the active Profiler handle mid-run; its cumulative
``counts`` survive the stop, so each rep contributes a full-run fold.
Samples aggregate over reps per mode (sampling noise at 19 Hz needs
the depth), normalized to SHARES before diffing so mode runtime
differences cancel.

Usage: JAX_PLATFORMS=cpu python scripts/parity_profile.py [reps]

Writes nothing; prints the attribution table (the docs/
PARITY_PROFILE.md verdict is the curated output of a run of this).
"""
import os
import re
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from adlb_tpu.obs import profile  # noqa: E402
from adlb_tpu.runtime.world import Config  # noqa: E402
from adlb_tpu.workloads import gfmc, tsp  # noqa: E402

APPS, SERVERS = 6, 3  # the parity rows' shape (bench.py)


def cfg(mode: str) -> Config:
    kw = dict(exhaust_check_interval=0.2, profile_hz=19.0)
    if mode == "steal":
        # upstream-faithful baseline, as in the bench parity rows
        return Config(balancer="steal", qmstat_mode="ring",
                      qmstat_interval=0.1, **kw)
    return Config(balancer="tpu", balancer_max_tasks=256,
                  balancer_max_requesters=64, **kw)


def one_rep(workload: str, mode: str) -> tuple:
    """One workload run; returns (tasks_per_sec, folded counts)."""
    grabbed: dict = {}
    stop = threading.Event()

    def watch():
        # the sampler only exists while the world runs: grab the handle
        # mid-run; its cumulative counts survive the stop
        while not stop.is_set():
            p = profile.active()
            if p is not None:
                grabbed["p"] = p
            time.sleep(0.05)

    w = threading.Thread(target=watch, daemon=True)
    w.start()
    try:
        if workload == "tsp":
            r = tsp.run(n_cities=10, num_app_ranks=APPS, nservers=SERVERS,
                        seed=3, cfg=cfg(mode), timeout=600.0)
        else:
            r = gfmc.run(num_a=400, bs_per_a=8, cs_per_b=5,
                         num_app_ranks=APPS, nservers=SERVERS,
                         cfg=cfg(mode), timeout=600.0)
    finally:
        stop.set()
        w.join()
    p = grabbed.get("p")
    counts = dict(p.counts) if p is not None else {}
    rate = r.tasks_processed / r.elapsed if r.elapsed else 0.0
    return rate, counts


def bucket(stack: str) -> str:
    """role[;phase] — the attribution grain. Balancer-owned phases keep
    their name; handler phases collapse to the tag family so 19 Hz
    sampling depth concentrates instead of scattering."""
    parts = stack.split(";")
    role = parts[0]
    phase = ""
    if len(parts) > 1 and parts[1].startswith("phase:"):
        phase = parts[1][len("phase:"):]
        phase = re.sub(r"^handler:.*", "handler", phase)
    return f"{role};{phase}" if phase else role


def run_mode(workload: str, mode: str, reps: int) -> tuple:
    rates, agg = [], {}
    for _ in range(reps):
        rate, counts = one_rep(workload, mode)
        rates.append(rate)
        for stack, n in counts.items():
            b = bucket(stack)
            agg[b] = agg.get(b, 0) + n
    rates.sort()
    return rates[len(rates) // 2], agg


def shares(agg: dict) -> dict:
    total = sum(agg.values()) or 1
    return {k: v / total for k, v in agg.items()}


def main() -> None:
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    for workload in ("tsp", "gfmc"):
        out = {}
        for mode in ("steal", "tpu"):
            out[mode] = run_mode(workload, mode, reps)
        r_s, a_s = out["steal"]
        r_t, a_t = out["tpu"]
        sh_s, sh_t = shares(a_s), shares(a_t)
        ratio = r_t / r_s if r_s else 0.0
        print(f"\n== {workload}: steal {r_s:.0f}/s  tpu {r_t:.0f}/s  "
              f"ratio {ratio:.3f}  "
              f"(samples steal={sum(a_s.values())} tpu={sum(a_t.values())})")
        keys = sorted(set(sh_s) | set(sh_t),
                      key=lambda k: sh_t.get(k, 0) - sh_s.get(k, 0),
                      reverse=True)
        print(f"   {'bucket':44s} {'steal%':>7s} {'tpu%':>7s} {'delta':>7s}")
        for k in keys:
            s, t = sh_s.get(k, 0) * 100, sh_t.get(k, 0) * 100
            if max(s, t) < 0.5:
                continue
            print(f"   {k:44s} {s:6.1f}% {t:6.1f}% {t - s:+6.1f}%")


if __name__ == "__main__":
    main()
