#!/usr/bin/env python
"""Calibrated discrete-event simulation: hotspot at 16..256 ranks.

256 real ranks are not constructible in this environment (single CPU
core — every measured run shares that core among all ranks, which is why
the measured native curve saturates at 128 ranks). This simulation
models the deployment the 256-rank target actually describes — every
rank its own core, message costs taken from this host's measurements —
so the structural difference between the two balancing modes can be
read without the host artifact. It is labeled as a simulation everywhere
it is reported; parameters and their sources are printed with the
result.

Mechanisms modeled (and their reference/rebuild counterparts):

* Every server is a single-threaded reactor (reference ``src/adlb.c:
  507-868``): each message occupies it for ``t_svc`` seconds. The hot
  server's reactor is the contended resource in the hotspot scenario.
* steal — per-unit pull: a worker's empty home server RFRs the hot
  server (one message), gets a response, the worker then fetches the
  payload from the hot server (another message): ~2 hot-server messages
  PER UNIT (reference ``src/adlb.c:1802-2070``). Discovery of where
  work lives waits on the qmstat ring token (interval 0.1 s, staleness
  grows by one forwarding hop per server, reference ``src/adlb.c:165,
  1705-1757``).
* tpu — batched push: the balancer plans migrations at its event
  cadence; a batch of K units costs the hot server ONE transfer message
  (plus per-unit serialize time) and the destination one receive; the
  adaptive window doubles while a destination re-triggers (engine.py
  LOOKAHEAD/LOOK_GROW_WINDOW semantics). Workers then reserve locally.

The headline mechanism is arithmetic, not tuning: with per-unit pull,
the hot server's reactor serves ~2 messages per delivered unit, so
steal-mode throughput plateaus at ~1/(2*t_svc) tasks/s no matter how
many workers exist; the batched pump costs the hot reactor ~1 message +
k*t_unit per k-unit batch, so its ceiling is ~1/(t_unit + t_svc/k) —
an order of magnitude higher at the adaptive window's converged batch
sizes. The simulation exists to show where each ceiling bites as ranks
grow, with discovery staleness and strike-outs layered on top.

Usage: python scripts/sim_scale.py
"""

from __future__ import annotations

import argparse
import heapq
import json


class Sim:
    """One hotspot run: n_tasks enter at server 0; 4 workers per server
    consume; makespan and worker idle are reported."""

    def __init__(
        self,
        nservers: int,
        workers_per_server: int = 4,
        n_tasks: int | None = None,
        work_time: float = 0.008,
        t_svc: float = 120e-6,  # reactor service time per message
        t_unit: float = 8e-6,  # extra serialize time per unit in a batch
        t_net: float = 60e-6,  # one-way transport latency
        mode: str = "steal",
        qmstat_interval: float = 0.1,
        plan_latency: float = 0.009,  # measured plan-age p50 (bench.py)
        lookahead: int = 8,
        look_max: int = 512,
    ) -> None:
        self.S = nservers
        self.wps = workers_per_server
        self.W = nservers * workers_per_server
        self.n_tasks = n_tasks if n_tasks is not None else self.W * 60
        self.work_time = work_time
        self.t_svc = t_svc
        self.t_unit = t_unit
        self.t_net = t_net
        self.mode = mode
        self.qmstat_interval = qmstat_interval
        self.plan_latency = plan_latency
        self.lookahead = lookahead
        self.look_max = look_max

    def run(self) -> dict:
        S, W = self.S, self.W
        queue = [0] * S
        queue[0] = self.n_tasks
        # reactor availability time per server (single-threaded service)
        reactor_free = [0.0] * S
        done = 0
        busy_time = 0.0
        t_end = 0.0
        events: list = []  # (time, seq, kind, data)
        seq = 0

        def push(t, kind, data):
            nonlocal seq
            heapq.heappush(events, (t, seq, kind, data))
            seq += 1

        def serve(s: int, t: float, cost: float) -> float:
            """Occupy server s's reactor from >=t for cost; returns done
            time."""
            start = max(reactor_free[s], t)
            reactor_free[s] = start + cost
            return start + cost

        # worker i's home server: i % S (reference src/adlb.c:257)
        home = [i % S for i in range(W)]
        idle_since = [0.0] * W
        # a worker must never hold two in-flight requests (a batch-arrival
        # wake racing its own pending want would double-consume)
        requested = [False] * W

        if self.mode == "tpu":
            window = [float(self.lookahead)] * S
            in_flight = [0] * S
            last_fed = [-1e9] * S

            def plan(t: float) -> None:
                """One balancer round at time t: top up starved servers
                from the hot pool in one batch each (engine.py
                _plan_migrations semantics, adaptive windows)."""
                for d in range(1, S):
                    need = int(window[d]) * self.wps
                    if queue[0] <= 0:
                        break
                    if queue[d] + in_flight[d] >= max(1, need // 2):
                        continue
                    k = min(need - queue[d] - in_flight[d], queue[0])
                    if k <= 0:
                        continue
                    queue[0] -= k
                    in_flight[d] += k
                    # one transfer message: hot reactor serializes k units
                    fin = serve(0, t, self.t_svc + k * self.t_unit)
                    arr = serve(d, fin + self.t_net, self.t_svc)
                    push(arr, "batch", (d, k))
                    # adaptive window (engine.py _touch_window)
                    if t - last_fed[d] < 0.25:
                        window[d] = min(window[d] * 2.0, float(self.look_max))
                    else:
                        window[d] = max(float(self.lookahead), window[d] / 2.0)
                    last_fed[d] = t

        def want(t: float, i: int) -> None:
            if not requested[i]:
                requested[i] = True
                push(t, "want", i)

        # kick off: every worker asks for work at t=0
        for i in range(W):
            want(0.0, i)
        if self.mode == "tpu":
            push(0.0, "plan", None)

        qmstat_known_at = 0.0  # when remote servers learned server 0 has work

        while events and done < self.n_tasks:
            t, _, kind, data = heapq.heappop(events)
            if kind == "done":
                i = data
                done += 1
                t_end = max(t_end, t)
                busy_time += self.work_time
                idle_since[i] = t
                want(t, i)
            elif kind == "batch":
                d, k = data
                in_flight[d] -= k
                queue[d] += k
                # local parked workers wake: re-request
                for i in range(W):
                    if home[i] == d and idle_since[i] >= 0:
                        want(t, i)
            elif kind == "plan":
                if done < self.n_tasks:
                    plan(t)
                    push(t + self.plan_latency, "plan", None)
            elif kind == "want":
                i = data
                requested[i] = False
                h = home[i]
                # reserve at home server (one message + response)
                t_resp = serve(h, t + self.t_net, self.t_svc) + self.t_net
                if queue[h] > 0:
                    queue[h] -= 1
                    idle_since[i] = -1.0
                    push(t_resp + self.work_time, "done", i)
                elif self.mode == "steal":
                    # discovery: home must believe the hot server has
                    # work — the ring token carries that info with
                    # interval + per-hop staleness
                    stale = self.qmstat_interval * (1 + (h / max(S - 1, 1)))
                    t_know = max(t_resp, qmstat_known_at + stale)
                    # RFR to hot server + response + worker GET payload
                    t_rfr = serve(0, t_know + self.t_net, self.t_svc)
                    if queue[0] > 0:
                        queue[0] -= 1
                        t_get = serve(0, t_rfr + 2 * self.t_net,
                                      self.t_svc) + self.t_net
                        idle_since[i] = -1.0
                        push(t_get + self.work_time, "done", i)
                    else:
                        # strike-out: retry after a beat
                        want(t_rfr + 0.001, i)
                else:
                    # tpu mode: stay parked; the next batch arrival
                    # re-requests for us
                    idle_since[i] = t

        makespan = t_end if t_end > 0 else 1e-9
        ideal = self.n_tasks * self.work_time / W
        idle_pct = 100.0 * max(0.0, 1.0 - busy_time / (makespan * W))
        return {
            "tasks_per_sec": self.n_tasks / makespan,
            "idle_pct": idle_pct,
            "makespan": makespan,
            "ideal": ideal,
        }


def main() -> None:
    argparse.ArgumentParser().parse_args()

    params = {
        # per-message reactor service time: in-proc Python reactor
        # measured ~5-20k msgs/s; the C++ daemon is faster but localhost
        # TCP recv+dispatch dominates — 120us is the conservative middle
        "t_svc_us": 120,
        # incremental serialize cost per unit inside one batch frame
        "t_unit_us": 8,
        "t_net_us": 60,  # one-way localhost/ICI-class latency
        "qmstat_interval_s": 0.1,  # reference src/adlb.c:165
        "plan_latency_s": 0.009,  # measured plan-age p50 (bench.py)
        "work_time_ms": 8,  # matches scripts/scaling_curve.py grain
    }
    rows = []
    scales = [(4,), (8,), (16,), (32,), (64,)]  # servers; 4 workers each
    for (s,) in scales:
        r_steal = Sim(nservers=s, mode="steal").run()
        r_tpu = Sim(nservers=s, mode="tpu").run()
        ratio = r_tpu["tasks_per_sec"] / r_steal["tasks_per_sec"]
        rows.append({
            "ranks": 4 * s, "servers": s,
            "steal_tasks_per_sec": round(r_steal["tasks_per_sec"], 1),
            "tpu_tasks_per_sec": round(r_tpu["tasks_per_sec"], 1),
            "steal_idle_pct": round(r_steal["idle_pct"], 1),
            "tpu_idle_pct": round(r_tpu["idle_pct"], 1),
            "ratio": round(ratio, 3),
        })
        print(
            f"{4*s:4d} ranks / {s:3d} servers:  "
            f"steal {r_steal['tasks_per_sec']:8.1f}/s "
            f"(idle {r_steal['idle_pct']:4.1f}%)   "
            f"tpu {r_tpu['tasks_per_sec']:8.1f}/s "
            f"(idle {r_tpu['idle_pct']:4.1f}%)   ratio {ratio:.3f}"
        )
    print(json.dumps({"metric": "hotspot_sim_scaling", "rows": rows,
                      "params": params,
                      "note": "discrete-event SIMULATION of a one-core-"
                              "per-rank deployment (message costs from "
                              "this host's measurements) — see "
                              "scripts/sim_scale.py docstring"}))


if __name__ == "__main__":
    main()
