#!/usr/bin/env python
"""Calibrated discrete-event simulation: hotspot at 16..256 ranks.

256 real ranks are not constructible in this environment (single CPU
core — every measured run shares that core among all ranks, which is why
the measured native curve saturates at 128 ranks). This simulation
models the deployment the 256-rank target actually describes — every
rank its own core, message costs taken from this host's measurements —
so the structural difference between the two balancing modes can be
read without the host artifact. It is labeled as a simulation everywhere
it is reported; parameters and their sources are printed with the
result.

Mechanisms modeled (and their reference/rebuild counterparts):

* Every server is a single-threaded reactor (reference ``src/adlb.c:
  507-868``): each message occupies it for ``t_svc`` seconds. The hot
  server's reactor is the contended resource in the hotspot scenario.
* steal — per-unit pull: a worker's empty home server RFRs the hot
  server (one message), gets a response, the worker then fetches the
  payload from the hot server (another message): ~2 hot-server messages
  PER UNIT (reference ``src/adlb.c:1802-2070``). Discovery of where
  work lives waits on the qmstat ring token (interval 0.1 s, staleness
  grows by one forwarding hop per server, reference ``src/adlb.c:165,
  1705-1757``).
* tpu — batched push: the balancer plans migrations at its event
  cadence; a batch of K units costs the hot server ONE transfer message
  (plus per-unit serialize time) and the destination one receive; the
  adaptive window doubles while a destination re-triggers (engine.py
  LOOKAHEAD/LOOK_GROW_WINDOW semantics). Workers then reserve locally.

The headline mechanism is arithmetic, not tuning: with per-unit pull,
the hot server's reactor serves ~2 messages per delivered unit, so
steal-mode throughput plateaus at ~1/(2*t_svc) tasks/s no matter how
many workers exist; the batched pump costs the hot reactor ~1 message +
k*t_unit per k-unit batch, so its ceiling is ~1/(t_unit + t_svc/k) —
an order of magnitude higher at the adaptive window's converged batch
sizes. The simulation exists to show where each ceiling bites as ranks
grow, with discovery staleness and strike-outs layered on top.

Usage: python scripts/sim_scale.py [--plan-sweep]

``--plan-sweep`` instead runs the MEASURED planning-latency sweep of the
sharded balancer (snapshot-delta ingest -> sharded solve -> plan
extracted) on a self-provisioned 8-way virtual mesh, to 1,000 servers /
100k parked requesters — ROADMAP item 1's sub-10 ms target. The sweep
lives in :mod:`adlb_tpu.balancer.plan_bench` (also callable as
``python -m adlb_tpu.balancer.plan_bench``).
"""

from __future__ import annotations

import argparse
import heapq
import json

# Measured native curve (scripts/scaling_curve.py, 2026-07-31, round 5 —
# re-measured with the round-5 engine per the round-4 verdict item 3;
# the host ran ~25% slower than the round-4 session, which the fitted
# constants absorb): {servers: (grain_s, steal_tasks/s, tpu_tasks/s)}.
# Single source of truth for the shared-core calibration — main() prints
# sim/meas against it, scripts/fit_sim.py re-derives the constants from
# it, and tests/test_sim_scale.py pins the fit to it.  The 128-rank rate
# draw inverted (0.938) in this session while the wait%% gap stayed in
# the balancer's favor (30.2 vs 40.1) — the documented one-core
# scheduler artifact; the fit reproduces the inversion (see
# test_shared_core_reproduces_measured_curve_both_columns).
MEASURED_CURVE = {
    4: (0.008, 1572.9, 1685.2),
    8: (0.008, 2882.2, 3270.5),
    16: (0.008, 3774.7, 4567.3),
    32: (0.024, 2462.7, 2309.5),
}


class Sim:
    """One hotspot run: n_tasks enter at server 0; 4 workers per server
    consume; makespan and worker idle are reported."""

    def __init__(
        self,
        nservers: int,
        workers_per_server: int = 4,
        n_tasks: int | None = None,
        work_time: float = 0.008,
        t_svc: float = 120e-6,  # reactor service time per message
        t_unit: float = 8e-6,  # extra serialize time per unit in a batch
        t_net: float = 60e-6,  # one-way transport latency
        mode: str = "steal",
        qmstat_interval: float = 0.1,
        plan_latency: float = 0.009,  # measured plan-age p50 (bench.py)
        lookahead: int = 8,
        look_max: int = 512,
        shared_core: bool = False,
        t_serve_shared: float = 36e-6,  # CPU per protocol exchange
        t_wake_per_proc: float = 0.0,  # per-process wakeup (fitted ~0)
        # round-4 term (the round-3 model's admitted gap): per task
        # completion the kernel's timer/runqueue work scales with how
        # many workers are CONCURRENTLY inside their compute sleep
        # beyond a floor (a shallow runqueue schedules in O(1)). The
        # mode that keeps more workers fed pays more per wakeup on one
        # core — the measured idle-wait asymmetry (tpu workers wait
        # ~7%% for work at 64 ranks yet lose ~40 points of wall to
        # scheduling; steal, paced by its own reactor bottleneck,
        # loses ~8).
        t_wake_per_busy: float = 3.0e-6,
        wake_busy_floor: int = 4,
        t_plan_per_server: float = 25e-6,  # balancer round CPU / server
    ) -> None:
        self.S = nservers
        self.wps = workers_per_server
        # one app rank is the PRODUCER and never consumes (hotspot_c.c
        # rank 0), so a "4 workers/server" world has 4S-1 consumers —
        # the +7% phantom consumer was a systematic bias on every
        # sim-vs-measured comparison until round 4
        self.W = nservers * workers_per_server - 1
        self.n_tasks = n_tasks if n_tasks is not None else self.W * 60
        self.work_time = work_time
        self.t_svc = t_svc
        self.t_unit = t_unit
        self.t_net = t_net
        self.mode = mode
        self.qmstat_interval = qmstat_interval
        self.plan_latency = plan_latency
        self.lookahead = lookahead
        self.look_max = look_max
        # shared-core: the deployment THIS host actually runs — every rank
        # (clients, daemons, sidecar) contends for ONE core. All protocol
        # exchanges serialize on a single CPU resource at t_serve_shared
        # each; every task completion additionally charges the kernel's
        # wakeup/runqueue cost (t_wake_per_proc x live process count —
        # the term that dominates above ~80 processes); worker compute
        # stays a parallel sleep (usleep burns no CPU); and in tpu mode
        # the balancer's Python round cost (t_plan_per_server * S per
        # round) lands on the same core — the sidecar tax a
        # one-core-per-rank deployment does not pay. The constants
        # (t_serve_shared, t_wake_per_busy, wake_busy_floor) are fitted
        # (scripts/fit_sim.py grid search) to BOTH measured columns of
        # scripts/scaling_curve.py (16/32/64/128 ranks, 2026-07-31,
        # round-5 engine); worst fitted cell 11% — inside the host's own
        # ±15-30% draw noise. Pinned by tests/test_sim_scale.py.
        self.shared_core = shared_core
        nprocs = self.W + self.S + (1 if mode == "tpu" else 0)
        # scale every reactor cost into shared-CPU units
        self.shared_scale = t_serve_shared / t_svc
        self.t_wake = t_wake_per_proc * nprocs
        self.t_wake_busy = t_wake_per_busy
        self.wake_busy_floor = wake_busy_floor
        self.t_plan = t_plan_per_server * nservers

    def run(self) -> dict:
        S, W = self.S, self.W
        queue = [0] * S
        queue[0] = self.n_tasks
        # reactor availability time per server (single-threaded service)
        reactor_free = [0.0] * S
        done = 0
        n_busy = 0  # workers currently inside their compute sleep
        busy_time = 0.0
        t_end = 0.0
        events: list = []  # (time, seq, kind, data)
        seq = 0

        def push(t, kind, data):
            nonlocal seq
            heapq.heappush(events, (t, seq, kind, data))
            seq += 1

        def serve(s: int, t: float, cost: float) -> float:
            """Occupy server s's reactor from >=t for cost; returns done
            time. Under shared_core every reactor is the same single CPU
            and message costs carry the scheduler inflation."""
            if self.shared_core:
                s = 0  # one CPU for everyone
                cost = cost * self.shared_scale
            start = max(reactor_free[s], t)
            reactor_free[s] = start + cost
            return start + cost

        # worker i's home server (reference src/adlb.c:257 round-robin).
        # The non-consuming producer is rank 0, homed on server 0 — the
        # hot server — so server 0 has one FEWER consumer than the rest
        # (consumers are app ranks 1..4S-1, homed (rank % S))
        home = [(i + 1) % S for i in range(W)]
        idle_since = [0.0] * W
        # a worker must never hold two in-flight requests (a batch-arrival
        # wake racing its own pending want would double-consume)
        requested = [False] * W

        # Every message HOP is its own event, so serve() is always called
        # at the message's true arrival time and the event heap keeps
        # service in global chronological order. Booking a whole
        # reserve->RFR->GET chain from one event (with future arrival
        # times) would interleave idle holes into the reactor timeline in
        # CALL order, serializing different workers' network latencies
        # into a phantom standing queue (~20 ms at 44% utilization in the
        # shared-core mode, and an artificially low steal ceiling in the
        # one-core-per-rank mode).

        if self.mode == "tpu":
            window = [float(self.lookahead)] * S
            in_flight = [0] * S
            last_fed = [-1e9] * S
            wcount = [sum(1 for i in range(W) if home[i] == s)
                      for s in range(S)]

            def plan(t: float) -> None:
                """One balancer round at time t: top up deficient servers
                from ANY surplus server (engine.py _plan_migrations
                semantics: every server keeps its own fair share; moves
                come from inventory beyond it — the round-3 sim only
                drained server 0, which strands end-game imbalance
                between destinations, a divergence from the engine)."""
                if self.shared_core and self.t_plan > 0:
                    # sidecar CPU on the one core; t_plan is already real
                    # CPU seconds, so pre-divide by the scale serve() will
                    # apply to reactor costs
                    serve(0, t, self.t_plan / self.shared_scale)
                total = sum(queue) + sum(in_flight)
                share = max(total // S, 1)
                srcs = [s for s in range(S) if queue[s] > share]
                if not srcs:
                    return
                srcs.sort(key=lambda s: share - queue[s])  # biggest first
                for d in range(S):
                    wc = wcount[d]
                    if wc == 0:
                        continue
                    have = queue[d] + in_flight[d]
                    starved = have == 0
                    if starved:
                        k_want = share
                    else:
                        # engine.py _need: demand-capped at the share
                        need = min(int(window[d]) * wc, share)
                        if 2 * have >= max(1, need):
                            continue
                        k_want = need - have
                    shipped = 0
                    for s in srcs:
                        if k_want <= 0:
                            break
                        if s == d:
                            continue
                        avail = queue[s] - share
                        if avail <= 0:
                            continue
                        k = min(k_want, avail)
                        queue[s] -= k
                        in_flight[d] += k
                        # one transfer message: the source reactor
                        # serializes k units
                        fin = serve(s, t, self.t_svc + k * self.t_unit)
                        push(fin + self.t_net, "batch_arrive", (d, k))
                        k_want -= k
                        shipped += k
                    if not shipped:
                        continue  # engine.py adapts windows only for
                        # destinations actually shipped a batch
                    if starved:
                        # window seeded at the SHIPPED scale (engine.py
                        # round-3 starved bypass)
                        window[d] = min(max(window[d], shipped / wc),
                                        float(self.look_max))
                    elif t - last_fed[d] < 0.25:
                        # adaptive window (engine.py _touch_window)
                        window[d] = min(window[d] * 2.0,
                                        float(self.look_max))
                    else:
                        window[d] = max(float(self.lookahead),
                                        window[d] / 2.0)
                    last_fed[d] = t

        def want(t: float, i: int) -> None:
            if not requested[i]:
                requested[i] = True
                push(t + self.t_net, "rsv_arrive", i)

        # kick off: workers' first requests are staggered uniformly over
        # one work period — real processes dephase within a cycle, while
        # identical deterministic latencies would phase-lock every worker
        # into synchronized request convoys
        for i in range(W):
            want(self.work_time * i / max(W, 1), i)
        if self.mode == "tpu":
            push(0.0, "plan", None)

        qmstat_known_at = 0.0  # when remote servers learned server 0 has work

        while events and done < self.n_tasks:
            t, _, kind, data = heapq.heappop(events)
            if kind == "done":
                i = data
                done += 1
                n_busy -= 1
                t_end = max(t_end, t)
                busy_time += self.work_time
                idle_since[i] = t
                if self.shared_core:
                    # kernel wakeup/runqueue cost of this completion on
                    # the one shared core: a fixed per-process term plus
                    # the round-4 occupancy term (real CPU seconds;
                    # scaling already folded in)
                    cost = self.t_wake + self.t_wake_busy * max(
                        0, n_busy - self.wake_busy_floor
                    )
                    if cost > 0:
                        start = max(reactor_free[0], t)
                        reactor_free[0] = start + cost
                want(t, i)
            elif kind == "batch_arrive":
                d, k = data
                arr = serve(d, t, self.t_svc)
                push(arr, "batch", (d, k))
            elif kind == "batch":
                d, k = data
                in_flight[d] -= k
                queue[d] += k
                # local parked workers wake: re-request
                for i in range(W):
                    if home[i] == d and idle_since[i] >= 0:
                        want(t, i)
            elif kind == "plan":
                if done < self.n_tasks:
                    plan(t)
                    push(t + self.plan_latency, "plan", None)
            elif kind == "rsv_arrive":
                i = data
                requested[i] = False
                h = home[i]
                # reserve served at the home server on arrival
                t_resp = serve(h, t, self.t_svc) + self.t_net
                if queue[h] > 0:
                    queue[h] -= 1
                    idle_since[i] = -1.0
                    n_busy += 1
                    push(t_resp + self.work_time, "done", i)
                elif self.mode == "steal":
                    # discovery: home must believe the hot server has
                    # work — the ring token carries that info with
                    # interval + per-hop staleness
                    stale = self.qmstat_interval * (1 + (h / max(S - 1, 1)))
                    t_know = max(t_resp, qmstat_known_at + stale)
                    push(t_know + self.t_net, "rfr_arrive", i)
                else:
                    # tpu mode: stay parked; the next batch arrival
                    # re-requests for us
                    idle_since[i] = t
            elif kind == "rfr_arrive":
                i = data
                t_rfr = serve(0, t, self.t_svc)
                if queue[0] > 0:
                    queue[0] -= 1
                    # RFR response to home + reservation to worker, who
                    # then GETs the payload from the hot server
                    push(t_rfr + 2 * self.t_net, "get_arrive", i)
                else:
                    # strike-out: retry after a beat
                    push(t_rfr + 0.001, "retry", i)
            elif kind == "retry":
                want(t, data)
            elif kind == "get_arrive":
                i = data
                t_get = serve(0, t, self.t_svc) + self.t_net
                idle_since[i] = -1.0
                n_busy += 1
                push(t_get + self.work_time, "done", i)

        makespan = t_end if t_end > 0 else 1e-9
        ideal = self.n_tasks * self.work_time / W
        idle_pct = 100.0 * max(0.0, 1.0 - busy_time / (makespan * W))
        return {
            "tasks_per_sec": self.n_tasks / makespan,
            "idle_pct": idle_pct,
            "makespan": makespan,
            "ideal": ideal,
        }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--plan-sweep", action="store_true",
                    help="measured sharded-balancer planning-latency "
                         "sweep (8-way virtual mesh) instead of the "
                         "hotspot simulation")
    ap.add_argument("--quick", action="store_true",
                    help="with --plan-sweep: fewer reps/scales")
    args = ap.parse_args()
    if args.plan_sweep:
        import os
        import sys

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from adlb_tpu.balancer import plan_bench

        argv = ["--quick"] if args.quick else []
        raise SystemExit(plan_bench.main(argv))

    params = {
        # per-message reactor service time: in-proc Python reactor
        # measured ~5-20k msgs/s; the C++ daemon is faster but localhost
        # TCP recv+dispatch dominates — 120us is the conservative middle
        "t_svc_us": 120,
        # incremental serialize cost per unit inside one batch frame
        "t_unit_us": 8,
        "t_net_us": 60,  # one-way localhost/ICI-class latency
        "qmstat_interval_s": 0.1,  # reference src/adlb.c:165
        "plan_latency_s": 0.009,  # measured plan-age p50 (bench.py)
        "work_time_ms": 8,  # matches scripts/scaling_curve.py grain
    }
    rows = []
    scales = [(4,), (8,), (16,), (32,), (64,)]  # servers; 4 workers each
    for (s,) in scales:
        r_steal = Sim(nservers=s, mode="steal").run()
        r_tpu = Sim(nservers=s, mode="tpu").run()
        ratio = r_tpu["tasks_per_sec"] / r_steal["tasks_per_sec"]
        rows.append({
            "ranks": 4 * s, "servers": s,
            "steal_tasks_per_sec": round(r_steal["tasks_per_sec"], 1),
            "tpu_tasks_per_sec": round(r_tpu["tasks_per_sec"], 1),
            "steal_idle_pct": round(r_steal["idle_pct"], 1),
            "tpu_idle_pct": round(r_tpu["idle_pct"], 1),
            "ratio": round(ratio, 3),
        })
        print(
            f"{4*s:4d} ranks / {s:3d} servers:  "
            f"steal {r_steal['tasks_per_sec']:8.1f}/s "
            f"(idle {r_steal['idle_pct']:4.1f}%)   "
            f"tpu {r_tpu['tasks_per_sec']:8.1f}/s "
            f"(idle {r_tpu['idle_pct']:4.1f}%)   ratio {ratio:.3f}"
        )
    # ---- shared-core mode: the deployment THIS host actually runs ------
    # Validation against the measured native curve
    # (scripts/scaling_curve.py): same scales, same grains, all ranks
    # contending for one core. The 16-rank steal point anchors the
    # calibration (sched_alpha); every other cell is out-of-sample.
    print("\nshared-core (this host's deployment) vs measured:")
    sc_rows = []
    for s, (wt, m_steal, m_tpu) in MEASURED_CURVE.items():
        r_steal = Sim(nservers=s, mode="steal", shared_core=True,
                      work_time=wt).run()
        r_tpu = Sim(nservers=s, mode="tpu", shared_core=True,
                    work_time=wt).run()
        ratio = r_tpu["tasks_per_sec"] / r_steal["tasks_per_sec"]
        sc_rows.append({
            "ranks": 4 * s, "servers": s, "work_ms": wt * 1e3,
            "steal_tasks_per_sec": round(r_steal["tasks_per_sec"], 1),
            "tpu_tasks_per_sec": round(r_tpu["tasks_per_sec"], 1),
            "ratio": round(ratio, 3),
            "sim_over_meas_steal": round(
                r_steal["tasks_per_sec"] / m_steal, 3),
            "sim_over_meas_tpu": round(r_tpu["tasks_per_sec"] / m_tpu, 3),
        })
        print(
            f"{4*s:4d} ranks / {s:3d} servers ({wt*1e3:.0f} ms):  "
            f"steal {r_steal['tasks_per_sec']:8.1f}/s   "
            f"tpu {r_tpu['tasks_per_sec']:8.1f}/s   ratio {ratio:.3f}   "
            f"sim/meas steal {r_steal['tasks_per_sec']/m_steal:.2f} "
            f"tpu {r_tpu['tasks_per_sec']/m_tpu:.2f}"
        )

    # ---- sensitivity: the 256-rank one-core-per-rank ratio vs the two
    # calibrated cost constants over +-2x --------------------------------
    print("\n256-rank ratio sensitivity (one-core-per-rank):")
    sens = []
    for fs in (0.5, 1.0, 2.0):
        for fu in (0.5, 1.0, 2.0):
            r_st = Sim(nservers=64, mode="steal",
                       t_svc=120e-6 * fs, t_unit=8e-6 * fu).run()
            r_tp = Sim(nservers=64, mode="tpu",
                       t_svc=120e-6 * fs, t_unit=8e-6 * fu).run()
            ratio = r_tp["tasks_per_sec"] / r_st["tasks_per_sec"]
            sens.append({"t_svc_x": fs, "t_unit_x": fu,
                         "ratio": round(ratio, 3)})
            print(f"  t_svc x{fs:3.1f}  t_unit x{fu:3.1f}  ->  "
                  f"ratio {ratio:.3f}")

    print(json.dumps({"metric": "hotspot_sim_scaling", "rows": rows,
                      "shared_core_rows": sc_rows,
                      "sensitivity_256r": sens,
                      "params": params,
                      "note": "discrete-event SIMULATION of a one-core-"
                              "per-rank deployment (message costs from "
                              "this host's measurements) — see "
                              "scripts/sim_scale.py docstring"}))


if __name__ == "__main__":
    main()
