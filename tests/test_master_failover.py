"""Master failover: the brain survives its own death
(Config(on_server_failure="failover") now covers the MASTER).

The master's ring buddy is a standing DEPUTY: the master streams its
brain — job table, membership snapshot + fleet epoch + id watermarks,
retired-route map, live-POSTed SLO objectives, control policy, parked
scale requests, per-job fair-share weights — over the same replication
plane every shard already uses (append-only ops, replica.py). On the
master's death the deputy promotes under a bumped fleet epoch, fans
SS_MASTER_TAKEOVER behind an ack barrier (no termination verdict races
the succession), adopts the master's app ranks via the ordinary home
takeover, rebinds the ops endpoint on an ephemeral port, and resumes
exhaustion/END duty with exact unit accounting.

Layers:

* **Promotion state matrix** — handler-driven master+deputy pairs: job
  table with weights/quotas, id watermarks, retired routes, epoch bump,
  SLO objectives and control policy POSTed live before the death.
* **Succession protocol** — the ack barrier gates END/exhaustion and
  releases on ack, timeout, or the acker's own death; stale-epoch
  tokens void; double-death runs the chain down to the next deputy.
* **Reconstructed obs** — the churn hold arms at promotion so healed
  alert lifecycles re-enter quietly (no re-fire).
* **Frame identity** — unconfigured worlds mint no deputy stream, no
  takeover frames, no master-failover metrics, and their membership
  snapshots carry no succession keys.
* **End-to-end** — worlds losing their MASTER mid-run complete with
  every unit completed/re-executed/counted, on the in-proc fabric and
  (slow) real-process SIGKILL.
"""

import json
import struct
import time
import urllib.request

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime import replica
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS, InfoKey

T = 1


# world: nranks=5, nservers=3 -> apps 0..1, servers 2 (master), 3, 4.
# ring: 2 -> 3 -> 4 -> 2, so server 3 is the master's buddy — the deputy.


def _world():
    return WorldSpec(nranks=5, nservers=3, types=(T,))


def _pair(master_kw=None, deputy_kw=None):
    """A live master (rank 2) + deputy (rank 3) on one in-proc fabric,
    driven handler-by-handler (no reactor threads)."""
    world = _world()
    fabric = InProcFabric(5)
    m = Server(world, Config(on_server_failure="failover",
                             **(master_kw or {})), fabric.endpoint(2))
    d = Server(world, Config(on_server_failure="failover",
                             **(deputy_kw or {})), fabric.endpoint(3))
    return m, d, fabric


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def _pump(m, d, fabric):
    """Flush the master's replication log and deliver everything queued
    at the deputy (replication frames + job fan-outs)."""
    m._flush_repl()
    for f in _drain(fabric, d.rank):
        d._handle(f)


def _kill_master(m, d, fabric, seed_brain=True):
    """The standard death: brain streamed, then the master's EOF."""
    if seed_brain:
        m._repl_brain()
    _pump(m, d, fabric)
    d._handle(Msg(tag=Tag.PEER_EOF, src=m.rank))


# ------------------------------------------------------- promotion core


def test_deputy_promotes_on_master_death():
    m, d, fabric = _pair()
    _kill_master(m, d, fabric)
    assert not d._aborted
    assert d.is_master and d.world.master_server_rank == 3
    assert d.world.epoch >= 1  # bumped past the brain's epoch
    assert 0 in d.local_apps  # the master's app rank adopted
    # the succession fanned to the surviving server behind the barrier
    fan = [x for x in _drain(fabric, 4)
           if x.tag is Tag.SS_MASTER_TAKEOVER]
    assert fan and fan[0].data["new_master"] == 3
    assert fan[0].data["epoch"] == d.world.epoch
    assert d._takeover_pending and d._takeover_pending["need"] == {4}
    # apps learned the remap AND the new brain in one note
    for app in (0, 1):
        notes = [x for x in _drain(fabric, app)
                 if x.tag is Tag.TA_HOME_TAKEOVER]
        assert notes and notes[0].dead == 2
        assert notes[0].data.get("new_master") == 3
    # MTTR gauged (lazily minted at promote)
    assert any("master_failover_mttr_ms" in k
               for k in d.metrics._gauges)
    # the ack releases the barrier
    tok = fan[0].data["member_tok"]
    d._handle(msg(Tag.SS_MASTER_TAKEOVER, 4, mop="ack", member_tok=tok))
    assert d._takeover_pending is None


def test_master_death_without_brain_is_double_failure():
    """No replication frame ever reached the deputy (death before the
    first flush): unrecoverable — abort, never a half-brained master."""
    world = _world()
    fabric = InProcFabric(5)
    d = Server(world, Config(on_server_failure="failover"),
               fabric.endpoint(3))
    d._handle(Msg(tag=Tag.PEER_EOF, src=2))
    assert d._aborted and d.done and not d.is_master


def test_promotion_state_matrix():
    """The replicated brain lands byte-exact: job table (state, name,
    quota, fair-share weight), id watermarks, retired routes, epoch."""
    m, d, fabric = _pair()
    # job table via the normal control plane (fan-outs reach the deputy,
    # OP_JOB + OP_JOB_WEIGHT ride the replication stream)
    m._handle_ctl({"op": "submit", "name": "tenant-a", "quota_bytes": 0})
    m._handle_ctl({"op": "submit", "name": "tenant-b",
                   "quota_bytes": 4096})
    m._handle_ctl({"op": "update", "job_id": 1, "weight": 3.0})
    # a retired server (an earlier failover the deputy never saw) and a
    # scale watermark ride the brain snapshot as the collapsed route map
    m._dead_servers.add(4)
    m._member_next_rank = 17
    _kill_master(m, d, fabric)
    assert d.is_master
    ja, jb = d.jobs.get(1), d.jobs.get(2)
    assert ja is not None and ja.name == "tenant-a" and ja.weight == 3.0
    assert jb is not None and jb.quota_bytes == 4096
    assert d._job_next_id >= 3, "job-id watermark lost: ids could reissue"
    assert d._member_next_rank >= 17
    assert 4 in d._srv_route, "retired route map lost"
    # the new master's planner starts from the live weight map
    assert d._effective_job_weights().get(1) == 3.0


def test_live_posted_slo_and_control_survive_promotion():
    """Regression (fixed FIRST): objectives POSTed to /slo and policy
    POSTed to /control after startup are brain state — the promoted
    deputy must answer /slo (alerts) and /control identically, not from
    its cold config."""
    kw = dict(ops_port=0, control=True, obs_sync_interval=0.2)
    m, d, fabric = _pair(master_kw=kw, deputy_kw=kw)
    obj = {"name": "finish-rate", "p99_ms": 50.0, "window_s": 60}
    m._handle_ctl({"op": "slo", "objective": obj})
    m._handle_ctl({"op": "control",
                   "policy": {"cooldown_s": 99.0, "dry_run": True}})
    want_slo = [dict(o) for o in m._slo_engine.objectives]
    want_pol = m._controller.policy_doc()
    _kill_master(m, d, fabric)
    try:
        assert d.is_master
        assert [dict(o) for o in d._slo_engine.objectives] == want_slo
        assert d._controller is not None
        assert d._controller.policy_doc() == want_pol
        # and over HTTP, from the REBOUND ephemeral endpoint
        assert d.ops is not None and d.ops.port > 0
        base = f"http://127.0.0.1:{d.ops.port}"
        alerts = json.load(urllib.request.urlopen(f"{base}/alerts",
                                                  timeout=5))
        assert [o["name"] for o in alerts["objectives"]] == ["finish-rate"]
        ctl = json.load(urllib.request.urlopen(f"{base}/control",
                                               timeout=5))
        assert ctl["enabled"] and ctl["policy"] == want_pol
        fleet = json.load(urllib.request.urlopen(f"{base}/fleet",
                                                 timeout=5))
        assert fleet["master"] == 3, "/fleet does not show the succession"
    finally:
        if d.ops is not None:
            d.ops.stop()


def test_parked_scale_request_survives_promotion():
    """A scale-out parked at the master (no spawner registered) is brain
    state: the promoted deputy re-parks it for ITS autoscaler/spawner
    instead of silently dropping the fleet's pending capacity ask."""
    m, d, fabric = _pair()
    m._handle_ctl({"op": "scale_out"})
    assert m._scale_pending is not None
    _kill_master(m, d, fabric)
    assert d.is_master
    assert d._scale_pending is not None
    assert d._scale_pending.get("reason") == m._scale_pending.get("reason")


def test_obs_reconstructs_under_churn_hold_no_refire():
    """Soft obs state is NOT replicated — gossip heals it within one
    sync interval. What must not happen is the transient re-firing a
    pre-death alert: promotion arms the SLO churn hold."""
    kw = dict(ops_port=0, obs_sync_interval=0.2)
    m, d, fabric = _pair(master_kw=kw, deputy_kw=kw)
    m._handle_ctl({"op": "slo",
                   "objective": {"name": "o1", "p99_ms": 25.0,
                                 "window_s": 60}})
    _kill_master(m, d, fabric)
    try:
        assert d.is_master and d._slo_engine is not None
        assert d._slo_engine._hold_until > time.monotonic(), (
            "no churn hold: the takeover transient can flap alerts"
        )
        # and the obs-sync tick is armed on the new master (deputies of
        # scale-out worlds arrive with ops_port stripped)
        assert d._obs_sync_armed and d._next_obs_sync != float("inf")
    finally:
        if d.ops is not None:
            d.ops.stop()


# ------------------------------------------------------- succession protocol


def test_stale_epoch_exhaustion_token_voids_after_promotion():
    m, d, fabric = _pair()
    _kill_master(m, d, fabric)
    assert d.is_master
    old_epoch = 0  # what the dead master's in-flight token carried
    token = {"origin": 2, "token_id": 1, "ok": True, "act": {2: 5},
             "nparked": 1, "parked": [], "epoch": old_epoch}
    d._handle(msg(Tag.SS_EXHAUST_CHK_1, 2, token=token, complete=False))
    assert token["ok"] is False, "stale-epoch exhaustion token not voided"


def test_takeover_barrier_defers_exhaustion_and_end():
    m, d, fabric = _pair()
    _kill_master(m, d, fabric)
    assert d._takeover_pending is not None
    # no exhaustion vote can start under the pending barrier
    d._exhaust_held_since = time.monotonic() - 60.0
    d._check_exhaustion(time.monotonic())
    assert not d._exhaust_inflight
    # a world that was terminating re-kicks END only once the barrier
    # resolves — here via the ack
    d._ending = True
    d._finalized = set(d.local_apps)
    _drain(fabric, 4)
    tok = d._takeover_pending["tok"]
    d._handle(msg(Tag.SS_MASTER_TAKEOVER, 4, mop="ack", member_tok=tok))
    assert d._takeover_pending is None
    end1 = [x for x in _drain(fabric, 4) if x.tag is Tag.SS_END_1]
    assert end1, "END ring not re-initiated after the barrier resolved"
    assert end1[0].token["epoch"] == d.world.epoch


def test_takeover_barrier_times_out():
    m, d, fabric = _pair()
    _kill_master(m, d, fabric)
    assert d._takeover_pending is not None
    d._takeover_pending["deadline"] = time.monotonic() - 0.001
    d._periodic(time.monotonic(), 0.05)
    assert d._takeover_pending is None, "lost acks wedged the barrier"


def test_takeover_barrier_releases_when_acker_dies():
    """The only un-acked server dies mid-barrier: the barrier must
    release through the death ladder, not wait for the timeout."""
    m, d, fabric = _pair()
    # give the deputy a mirror OF server 4 too, so 4's death does not
    # abort as a double failure (4's own buddy is dead master 2, so the
    # walk lands on us)
    log4 = replica.ReplicationLog(buddy=3)
    log4.log_seen_puts(0, [1])  # any entry: an empty log never flushes
    d._handle(msg(Tag.SS_REPL, 4, blob=log4.take(), seq=1))
    _kill_master(m, d, fabric)
    assert d._takeover_pending and 4 in d._takeover_pending["need"]
    d._handle(Msg(tag=Tag.PEER_EOF, src=4))
    assert d._takeover_pending is None


def test_sequential_master_deaths_run_down_the_chain():
    """Master 2 dies -> 3 promotes; 3's own buddy 4 is the NEXT deputy
    (3 ships it the whole brain at promotion). Then 3 dies -> 4
    promotes under a further-bumped epoch. Driven from rank 4's side."""
    world = _world()
    fabric = InProcFabric(5)
    last = Server(world, Config(on_server_failure="failover"),
                  fabric.endpoint(4))
    # first succession, as rank 4 observes it
    last._handle(msg(Tag.SS_SERVER_DEAD, 3, rank=2, epoch=1))
    last._handle(msg(Tag.SS_MASTER_TAKEOVER, 3, new_master=3, epoch=2,
                     member_tok=1))
    assert last.world.master_server_rank == 3
    acks = [x for x in _drain(fabric, 3)
            if x.tag is Tag.SS_MASTER_TAKEOVER
            and x.data.get("mop") == "ack"]
    assert acks and acks[0].data["member_tok"] == 1
    # the promoted master 3 ships rank 4 the brain (it is now deputy)
    log = replica.ReplicationLog(buddy=4)
    log.log_member({"master": 3, "epoch": 2, "next_rank": 0,
                    "member": {"epoch": 2, "master": 3,
                               "master_epoch": 2},
                    "addrs": {}, "live": [], "ready": [], "dead": [2],
                    "drained": [], "srv_route": {}, "job_next_id": 1,
                    "ops_armed": False})
    last._handle(msg(Tag.SS_REPL, 3, blob=log.take(), seq=1))
    # second death: the chain continues
    last._handle(Msg(tag=Tag.PEER_EOF, src=3))
    assert not last._aborted
    assert last.is_master and last.world.master_server_rank == 4
    assert last.world.epoch >= 3, "second succession did not bump epoch"


def test_attach_barrier_racing_death_lands_at_new_master():
    """A joiner whose attach was in flight when the master died retries
    at the promoted deputy (MemberView-aware attach targets the CURRENT
    master): the new master must run the member barrier end-to-end."""
    m, d, fabric = _pair()
    _kill_master(m, d, fabric)
    assert d.is_master
    _drain(fabric, 4)
    prov = 1 << 20  # provisional joiner id
    fabric.add_endpoint(prov)
    d._handle(msg(Tag.FA_MEMBER, prov, mop="attach", kind="app"))
    # the attach fans SS_MEMBER to the surviving server; ack it
    fan = [x for x in _drain(fabric, 4) if x.tag is Tag.SS_MEMBER]
    assert fan, "promoted master did not fan the attach"
    d._handle(msg(Tag.SS_MEMBER, 4, mop="ack",
                  member_tok=fan[0].data["member_tok"]))
    resp = [x for x in _drain(fabric, prov)
            if x.tag is Tag.TA_MEMBER_RESP]
    assert resp and resp[0].data["rc"] == ADLB_SUCCESS
    snap = resp[0].data["member"]
    assert snap["master"] == 3, "joiner seeded with the dead master"


# ------------------------------------------------------- frame identity


def test_unconfigured_worlds_mint_nothing():
    """on_server_failure="abort" (default): no replication stream, no
    deputy brain, no succession keys in snapshots, no master-failover
    metrics — byte/frame identity with pre-failover builds."""
    world = _world()
    fabric = InProcFabric(5)
    srv = Server(world, Config(), fabric.endpoint(2))
    assert srv.repl is None
    srv._repl_brain()  # must be a no-op, not a crash
    assert "master" not in srv.world.snapshot()
    assert not any("master_failover" in k for k in srv.metrics._gauges)
    assert srv._takeover_pending is None


def test_configured_master_streams_brain_only_from_master():
    """Failover worlds: the brain rides the master's stream only — a
    non-master server's log must carry no OP_MEMBER entries (its buddy
    would otherwise adopt a stale brain on an ordinary failover)."""
    world = _world()
    fabric = InProcFabric(5)
    srv3 = Server(world, Config(on_server_failure="failover"),
                  fabric.endpoint(3))
    srv3._repl_brain()
    assert srv3.repl.take() is None, "non-master emitted brain frames"
    # and the master's snapshot gains succession keys only after one
    srv2 = Server(world, Config(on_server_failure="failover"),
                  fabric.endpoint(2))
    assert "master" not in srv2.world.snapshot()
    srv2.world.set_master(3, 2)
    snap = srv2.world.snapshot()
    assert snap["master"] == 3 and snap["master_epoch"] == 2


# ------------------------------------------------------- end-to-end worlds


N_UNITS = 48


def _coverage_economy(ctx):
    if ctx.rank == 0:
        for i in range(N_UNITS):
            ctx.put(struct.pack("<q", i), T)
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(struct.unpack("<q", w.payload)[0])
        time.sleep(0.002)


def _assert_coverage(res, expect_casualty):
    done = [x for v in res.app_results.values() for x in v]
    lost = sum(
        s.get(int(InfoKey.FAILOVER_LOST), 0.0)
        for s in res.server_stats.values()
    )
    missing = set(range(N_UNITS)) - set(done)
    assert len(missing) <= lost, (
        f"units {sorted(missing)} vanished but only {lost} counted lost"
    )
    assert res.server_casualties == [expect_casualty]
    assert not res.aborted
    promoted = sum(
        s.get(int(InfoKey.NUM_FAILOVERS), 0.0)
        for s in res.server_stats.values()
    )
    assert promoted >= 1, "no server reported a takeover"


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_inproc_master_death_failover_completes(mode):
    """Deterministic in-proc MASTER death (fault-injected disconnect of
    server index 0 at its 40th outbound frame): the deputy promotes and
    the world completes with conservation modulo counted losses."""
    res = run_world(
        4, 3, [T], _coverage_economy,
        cfg=Config(
            balancer=mode,
            on_server_failure="failover",
            exhaust_check_interval=0.2,
            failover_client_wait=30.0,
            fault_spec={"seed": 5, "disconnect_server_at": {0: 40}},
        ),
        timeout=120.0,
    )
    _assert_coverage(res, expect_casualty=4)  # server index 0 = rank 4


def test_inproc_master_death_abort_policy_unchanged():
    """Same injected death under the default policy: the world aborts
    (reference semantics), promptly and classified."""
    t0 = time.monotonic()
    with pytest.raises(Exception):
        run_world(
            4, 3, [T], _coverage_economy,
            cfg=Config(
                exhaust_check_interval=0.2,
                fault_spec={"seed": 5, "disconnect_server_at": {0: 40}},
            ),
            timeout=60.0,
        )
    assert time.monotonic() - t0 < 45.0, "abort path hung"


def _delayed_economy(ctx):
    # idle phase first: the dark window below must contain no unit
    # traffic — gossip and brain snapshots are periodic/newest-wins, so
    # the deputy's view self-heals after the window, whereas a unit op
    # eaten by a one-way drop would be an uncounted loss (at-most-once
    # payload commits assume a live link either delivers or EOFs)
    time.sleep(2.0)
    return _coverage_economy(ctx)


def test_inproc_master_death_under_oneway_partition():
    """The asymmetric fault composed with the succession — the
    split-brain-shaped window: the master's outbound leg to its own
    deputy goes dark (the deputy hears nothing from the brain; clients
    still reach it) and the death ladders must NOT race a verdict — no
    spurious promotion, no epoch bump from one-way silence alone. The
    master then really dies mid-storm, after the window heals, and
    exactly ONE promotion carries the world to completion with exact
    accounting."""
    res = run_world(
        4, 3, [T], _delayed_economy,
        cfg=Config(
            on_server_failure="failover",
            exhaust_check_interval=0.2,
            failover_client_wait=30.0,
            # bound the idle-phase frame rate so the injected frame
            # number lands mid-storm, after the window has healed
            qmstat_interval=0.2,
            fault_spec={
                "seed": 9,
                "disconnect_server_at": {0: 60},
                # master (world rank 4) -> deputy (rank 5), one-way,
                # over t in ~(0.4, 1.2): inside the apps' sleep
                "partition": {"pairs": [[4, 5]], "at": 0.4,
                              "for_s": 0.8},
            },
        ),
        timeout=120.0,
    )
    _assert_coverage(res, expect_casualty=4)
    promoted = sum(
        s.get(int(InfoKey.NUM_FAILOVERS), 0.0)
        for s in res.server_stats.values()
    )
    assert promoted == 1, (
        f"{promoted} promotions: the gray window raced a verdict"
    )


def _tcp_economy(ctx):
    return _coverage_economy(ctx)


@pytest.mark.slow
def test_tcp_sigkill_master_failover_completes():
    """The acceptance world: a real-process TCP world survives SIGKILL
    of the MASTER mid-workload; the deputy promotes, clients re-point
    via the takeover note's new_master, and the run completes with
    every unit completed or re-executed (conservation modulo counted
    lag losses); MTTR is recorded."""
    res = spawn_world(
        6, 3, [T], _tcp_economy,
        cfg=Config(
            on_server_failure="failover",
            exhaust_check_interval=0.2,
            failover_client_wait=30.0,
            fault_spec={"seed": 13, "kill_server_at_frame": {0: 60}},
        ),
        timeout=150.0,
    )
    _assert_coverage(res, expect_casualty=6)  # server index 0 = rank 6
    mttr = max(
        s.get(int(InfoKey.FAILOVER_MTTR_MS), 0.0)
        for s in res.server_stats.values()
    )
    assert mttr > 0.0, "promotion did not record an MTTR"
