"""model / c1 / c3 / partest workloads (reference examples/model.c, c1.c,
c3.c, partest.c) plus the app-messaging layer (app_comm equivalent) they
rely on."""

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS
from adlb_tpu.workloads import c1, c3, model, partest

TPU = Config(
    balancer="tpu", balancer_max_tasks=64, balancer_max_requesters=16,
    exhaust_check_interval=0.15,
)


# -- app <-> app messaging (reference app_comm, src/adlb.c:256,318) ----------

def test_app_messaging_roundtrip():
    def app(ctx):
        if ctx.rank == 0:
            got = []
            for _ in range(ctx.num_app_ranks - 1):
                payload, src, tag = ctx.app_recv(apptag=7)
                got.append((src, payload))
            ctx.set_problem_done()
            return sorted(got)
        ctx.app_send(0, f"hello-{ctx.rank}", apptag=7)
        rc, _ = ctx.reserve()  # park until the termination flush
        assert rc != ADLB_SUCCESS
        return None

    res = run_world(3, 1, [1], app)
    assert res.app_results[0] == [(1, "hello-1"), (2, "hello-2")]


def test_app_messaging_stash_during_reserve():
    """An AM_APP frame arriving while the receiver blocks in Reserve must be
    stashed, not confused with a protocol response."""

    def app(ctx):
        if ctx.rank == 0:
            # park in a blocking reserve; rank 1's app message arrives first,
            # then its put satisfies the reserve
            rc, r = ctx.reserve([1])
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            assert ctx.app_iprobe(apptag=3)
            payload, src, _ = ctx.app_recv(apptag=3)
            ctx.set_problem_done()
            return (src, payload)
        ctx.app_send(0, 42, apptag=3)
        import time

        time.sleep(0.2)  # let the message land while rank 0 is parked
        ctx.put(b"x", 1, target_rank=0)
        rc, _ = ctx.reserve()
        assert rc != ADLB_SUCCESS
        return None

    res = run_world(2, 1, [1], app)
    assert res.app_results[0] == (1, 42)


def test_app_messaging_filters_by_tag_and_src():
    def app(ctx):
        if ctx.rank == 0:
            # both messages are already ordered ambiguously; tag filter must
            # pick the right one regardless of arrival order
            p2, s2, t2 = ctx.app_recv(apptag=2)
            p1, s1, t1 = ctx.app_recv(apptag=1)
            ctx.set_problem_done()
            return [(t1, s1, p1), (t2, s2, p2)]
        ctx.app_send(0, ctx.rank * 10, apptag=ctx.rank)
        rc, _ = ctx.reserve()
        assert rc != ADLB_SUCCESS
        return None

    res = run_world(3, 1, [1], app)
    assert res.app_results[0] == [(1, 1, 10), (2, 2, 20)]


def test_app_recv_zero_timeout_drains_delivered():
    """A message already sitting in the endpoint queue must be visible to
    app_recv(timeout=0) — the drain happens before the deadline check."""
    import time

    def app(ctx):
        if ctx.rank == 0:
            deadline = time.monotonic() + 5.0
            got = None
            while got is None and time.monotonic() < deadline:
                got = ctx.app_recv(apptag=4, timeout=0)  # pure poll
                if got is None:
                    time.sleep(0.01)
            ctx.set_problem_done()
            return got
        ctx.app_send(0, "polled", apptag=4)
        rc, _ = ctx.reserve()
        assert rc != ADLB_SUCCESS
        return None

    res = run_world(2, 1, [1], app)
    assert res.app_results[0] == ("polled", 1, 4)


# -- model.c -----------------------------------------------------------------

@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_model_all_problems_done(mode):
    cfg = None if mode == "steal" else TPU
    res = model.run(numprobs=12, work_secs=0.005, num_app_ranks=3,
                    nservers=2, cfg=cfg)
    assert res.ok, f"done {res.num_done} != put {res.numprobs}"
    # the wildcard-reserve loop spreads dummy work over ranks; with 12
    # problems and 3 ranks at least two ranks must see work
    assert sum(1 for v in res.done_by_rank.values() if v > 0) >= 2


# -- c1.c --------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_c1_b_answer_sum(mode):
    cfg = None if mode == "steal" else TPU
    res = c1.run(num_as=3, nunits=4, num_app_ranks=4, nservers=2,
                 delay_reps=200, cfg=cfg)
    assert res.ok, f"sum {res.total} != expected {res.expected}"


def test_c1_single_slave():
    # one slave must self-answer every C through the Ireserve overlap path
    res = c1.run(num_as=2, nunits=2, num_app_ranks=2, nservers=1,
                 delay_reps=100)
    assert res.ok, f"sum {res.total} != expected {res.expected}"


# -- c3.c --------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_c3_batch_economy_self_check(mode):
    cfg = None if mode == "steal" else TPU
    res = c3.run(nas=4, nbs=2, ncs=3, loop1=2, loop2=2,
                 atime=0.002, ctime=0.001, num_app_ranks=4, nservers=2,
                 cfg=cfg)
    assert res.ok, (
        f"A answers {res.a_answers}/{res.exp_as}, "
        f"C answers {res.c_answers}/{res.exp_cs}"
    )


# -- partest.c ---------------------------------------------------------------

def test_partest_calibration_replay_tracks_time():
    unit = partest.define_work(0.05, nugget_reps=50)
    assert unit.i >= 0 and unit.j >= 0 and unit.k >= 0
    elapsed = partest.do_work(unit, nugget_reps=50)
    # replay must take roughly the calibrated time (loose: shared CI host)
    assert 0.2 * unit.calibrated_secs < elapsed < 5.0 * unit.calibrated_secs


def test_partest_more_time_more_work():
    small = partest.define_work(0.01, nugget_reps=50)
    big = partest.define_work(0.08, nugget_reps=50)
    assert (big.i, big.j, big.k) > (small.i, small.j, small.k)
