"""Codec parity fuzz: the compiled TLV codec (native/codec.cpp) vs the
pure-Python twin, byte-identical both directions over randomized frames
of every wire-native shape — including >IOV_MAX-segment frames, 0-byte
and 2 MiB payloads, and the field-97 job id present/absent — plus the
short-write/EINTR resume contract of ``TcpEndpoint._send_iov``.

The C leg skips with a note when the toolchain cannot build the .so
(the runtime degrades to the Python twin the same way)."""

import random

import pytest

from adlb_tpu.runtime import codec as codec_mod
from adlb_tpu.runtime.codec import (
    FIELDS,
    IOV_INLINE_MAX,
    decode_binary_py,
    encodable,
    encode_binary_iov_py,
)
from adlb_tpu.runtime.messages import Msg, Tag, msg

_KIND_I64, _KIND_BYTES, _KIND_LIST, _KIND_F64, _KIND_BLIST, _KIND_FLIST = \
    range(6)

_HAVE_C = codec_mod._load_c_codec()

needs_c = pytest.mark.skipif(
    not _HAVE_C,
    reason="compiled codec unavailable (no toolchain); Python twin "
    "carries the wire — parity legs skipped",
)


def _rand_value(rng: random.Random, kind: int, wild: bool = False):
    if kind == _KIND_I64:
        return rng.choice([
            0, 1, -1, 97, 2**31, -(2**31), 2**62, -(2**62),
            rng.randrange(-(2**40), 2**40),
        ])
    if kind == _KIND_BYTES:
        n = rng.choice([0, 1, 7, IOV_INLINE_MAX - 1, IOV_INLINE_MAX,
                        IOV_INLINE_MAX + 1, 4096, 2 << 20])
        b = rng.randbytes(min(n, 4096)) * max(1, n // 4096)
        b = b[:n]
        if wild and rng.random() < 0.3:
            return bytearray(b) if rng.random() < 0.5 else memoryview(b)
        return b
    if kind == _KIND_LIST:
        n = rng.choice([0, 1, 5, 64, 1500])
        return [rng.randrange(-(2**40), 2**40) for _ in range(n)]
    if kind == _KIND_F64:
        return rng.choice([0.0, -1.5, 3.14159, 1e300, -1e-300,
                           float(rng.randrange(10**6))])
    if kind == _KIND_BLIST:
        n = rng.choice([0, 1, 8, 64])
        return [_rand_value(rng, _KIND_BYTES) if rng.random() < 0.3
                else rng.randbytes(rng.randrange(0, 64))
                for _ in range(n)]
    n = rng.choice([0, 1, 9, 257])
    return [rng.uniform(-1e6, 1e6) for _ in range(n)]


def _rand_frame(rng: random.Random, wild: bool = False) -> Msg:
    tag = rng.choice(list(Tag))
    names = list(FIELDS)
    rng.shuffle(names)
    data = {}
    for name in names[: rng.randrange(0, 12)]:
        _fid, kind = FIELDS[name]
        # None values encode by omission — fuzz that rule too
        data[name] = None if rng.random() < 0.1 else _rand_value(
            rng, kind, wild)
    # the field-97 job id, present/absent, is the service-mode
    # compatibility bit — force both arms to occur often
    if rng.random() < 0.5:
        data["job_id"] = rng.choice([0, 1, 97, 2**31])
    else:
        data.pop("job_id", None)
    # same treatment for the field-98 trace id (unit-lifecycle tracing):
    # omitted-for-unsampled is the trace_sample=0 frame-identity contract
    if rng.random() < 0.5:
        data["trace_id"] = rng.choice(
            [1, (1 << 32) | 1, (255 << 32) | 0xFFFFFFFF, 2**62]
        )
    else:
        data.pop("trace_id", None)
    return Msg(tag=tag, src=rng.randrange(-1, 1 << 20), data=data)


def _flat(parts) -> bytes:
    return b"".join(bytes(p) for p in parts)


@needs_c
def test_parity_fuzz_roundtrip():
    """1,000 randomized frames: identical bytes out of both encoders,
    identical Msg out of both decoders (cross-decoded, so each decoder
    is also proven against the OTHER encoder's bytes)."""
    rng = random.Random(0xAD1B)
    for i in range(1000):
        m = _rand_frame(rng, wild=True)
        py = _flat(encode_binary_iov_py(m))
        c = _flat(codec_mod._c_encode_iov(m))
        assert py == c, f"frame {i} ({m.tag.name}): encode bytes differ"
        d_py = decode_binary_py(c)
        d_c = codec_mod._c_decode(py)
        assert d_py == d_c, f"frame {i} ({m.tag.name}): decode differs"
        assert d_py.tag is m.tag and d_py.src == m.src


@needs_c
def test_parity_known_corpus():
    """The deterministic edge corpus: 0-byte and 2 MiB payloads, the
    inline threshold's both sides, frozenset req_types, bools, empty
    frames, job id on and off."""
    big = b"\xa5" * (2 << 20)
    corpus = [
        msg(Tag.FA_PUT, 0, payload=b"", work_type=1, prio=0,
            target_rank=-1, answer_rank=-1, common_len=0,
            common_server=-1, common_seqno=-1),
        msg(Tag.FA_PUT, 3, payload=big, work_type=2, prio=-7,
            target_rank=-1, answer_rank=0),
        msg(Tag.FA_PUT, 1, payload=b"x" * (IOV_INLINE_MAX - 1)),
        msg(Tag.FA_PUT, 1, payload=b"x" * IOV_INLINE_MAX),
        msg(Tag.FA_PUT, 1, payload=b"x", job_id=7),
        msg(Tag.FA_PUT, 1, payload=b"x"),
        # field-98 trace id: the sampled-put arm and the bare twin whose
        # bytes must not change (trace_sample=0 frame identity)
        msg(Tag.FA_PUT, 1, payload=b"x", put_id=3,
            trace_id=(2 << 32) | 9),
        msg(Tag.FA_PUT, 1, payload=b"x", put_id=3),
        msg(Tag.FA_RESERVE, 0, req_types=frozenset({1, 2, 9}),
            hang=True, rqseqno=42),
        msg(Tag.FA_RESERVE, 0, req_types=None, hang=False, rqseqno=1),
        msg(Tag.TA_RESERVE_RESP, 6, rc=1, payloads=[big[:4096], b"", b"z"],
            work_types=[1, 2, 3], prios=[0, -1, 5],
            answer_ranks=[-1, 0, 2], times_on_q=[0.0, 0.5, 1e9]),
        msg(Tag.SS_STATE_DELTA, 4, seqnos=list(range(1000)),
            work_types=[1] * 1000, prios=[0] * 1000,
            work_lens=[64] * 1000, nbytes=64000),
        msg(Tag.FA_LOCAL_APP_DONE, 9),
        msg(Tag.TA_INFO_GET_RESP, 6, rc=1, value=3.5),
    ]
    for m in corpus:
        assert encodable(m), m.tag
        py = _flat(encode_binary_iov_py(m))
        c = _flat(codec_mod._c_encode_iov(m))
        assert py == c, m.tag
        assert decode_binary_py(c) == codec_mod._c_decode(py)


@needs_c
def test_parity_beyond_iov_max_segments():
    """A batch-fetch frame whose payload list alone exceeds IOV_MAX
    segments (1024): both encoders must agree byte-for-byte and the
    part count must exceed the kernel's gather cap (the _send_iov
    chunking path's precondition)."""
    m = msg(
        Tag.TA_RESERVE_RESP, 6, rc=1,
        payloads=[b"P" * IOV_INLINE_MAX] * 1100,
        work_types=[1] * 1100, prios=[0] * 1100,
        answer_ranks=[-1] * 1100,
    )
    py_parts = encode_binary_iov_py(m)
    c_parts = codec_mod._c_encode_iov(m)
    assert len(py_parts) > 1024 and len(c_parts) > 1024
    assert _flat(py_parts) == _flat(c_parts)
    assert decode_binary_py(_flat(c_parts)) == codec_mod._c_decode(
        _flat(py_parts))


@needs_c
def test_c_codec_unknown_field_skipped_and_errors_match():
    """Unknown wire fields are skipped by both decoders; oversized list
    fields raise on both encoders."""
    import struct

    body = bytearray(_flat(encode_binary_iov_py(
        msg(Tag.TA_PUT_RESP, 5, rc=1))))
    # append an unknown field id 200, kind i64, bump nfields
    body += struct.pack("<BBq", 200, 0, 12345)
    nf = struct.unpack_from("<H", body, 7)[0]
    struct.pack_into("<H", body, 7, nf + 1)
    d_py = decode_binary_py(bytes(body))
    d_c = codec_mod._c_decode(bytes(body))
    assert d_py == d_c and d_py.data == {"rc": 1}

    too_long = msg(Tag.SS_STATE_DELTA, 0, seqnos=list(range(70000)))
    with pytest.raises(ValueError):
        encode_binary_iov_py(too_long)
    with pytest.raises(ValueError):
        codec_mod._c_encode_iov(too_long)


def test_select_codec_roundtrip():
    """select_codec swaps the active implementation and the dispatchers
    follow; 'py' always works, 'c' works iff the .so built."""
    before = codec_mod.active_codec()
    try:
        assert codec_mod.select_codec("py") == "py"
        m = msg(Tag.TA_PUT_RESP, 5, rc=1)
        assert codec_mod.decode_binary(
            codec_mod.encode_binary(m)) == decode_binary_py(
            _flat(encode_binary_iov_py(m)))
        if _HAVE_C:
            assert codec_mod.select_codec("c") == "c"
            assert codec_mod.decode_binary(
                codec_mod.encode_binary(m)).data == {"rc": 1}
        else:
            with pytest.raises(RuntimeError):
                codec_mod.select_codec("c")
        assert codec_mod.select_codec("auto") in ("c", "py")
    finally:
        codec_mod.select_codec("auto" if before == "c" else "py")


# ------------------------------------------------- _send_iov resume contract


class _ShortWriteSock:
    """A socket double whose sendmsg accepts a random prefix of the
    gather (including 0) and raises EINTR at scripted points; sendall
    records the no-sendmsg fallback."""

    def __init__(self, rng: random.Random, eintr_every: int = 7) -> None:
        self.rng = rng
        self.got = bytearray()
        self.calls = 0
        self.eintr_every = eintr_every

    def sendmsg(self, parts):
        self.calls += 1
        if self.eintr_every and self.calls % self.eintr_every == 0:
            raise InterruptedError(4, "scripted EINTR")
        total = sum(len(p) for p in parts)
        n = self.rng.randrange(0, total + 1) if total else 0
        taken = 0
        for p in parts:
            if taken >= n:
                break
            b = bytes(p)[: n - taken]
            self.got += b
            taken += len(b)
        return n


def test_send_iov_short_write_eintr_resume():
    """Random short writes + scripted EINTRs: the receiver-side bytes
    must equal the exact concatenation of the gather, for frames from
    tiny to >IOV_MAX segments."""
    from adlb_tpu.runtime.transport_tcp import TcpEndpoint

    rng = random.Random(7)
    for _case in range(40):
        nparts = rng.choice([1, 2, 5, 30, 1100])
        parts = [rng.randbytes(rng.randrange(0, 600)) for _ in range(nparts)]
        want = b"".join(parts)
        sock = _ShortWriteSock(random.Random(_case), eintr_every=5)
        TcpEndpoint._send_iov(sock, list(parts))
        assert bytes(sock.got) == want, f"case {_case}: stream corrupted"
