"""Randomized conservation soak: churn the pool hard and prove no unit is
lost or duplicated, in every plane x balancer combination.

The reference's soak-test harness is the debug-server watchdog turning
hangs into bounded-time aborts (SURVEY §4, reference src/adlb.c:2528-2635);
here the same role is played by run timeouts, and the oracle is
conservation: with exhaustion-only termination, every accepted put must be
consumed exactly once. Producers interleave targeted and untargeted puts of
several types and priorities with batch/common prefixes; consumers mix
blocking and non-blocking reserves with random type subsets.
"""

import random
import struct

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_NO_CURRENT_WORK, ADLB_SUCCESS

TYPES = [1, 2, 3]
N_PER_PRODUCER = 40


def _app(ctx):
    rng = random.Random(1234 + ctx.rank)
    accepted = []
    consumed = []
    producers = max(ctx.num_app_ranks // 2, 1)
    if ctx.rank < producers:
        in_batch = False
        pipelined = []  # (i) issued via iput; settled at flush
        for i in range(N_PER_PRODUCER):
            if not in_batch and rng.random() < 0.15:
                ctx.begin_batch_put(b"PFX%d" % ctx.rank)
                in_batch = True
            elif in_batch and rng.random() < 0.4:
                ctx.end_batch_put()
                in_batch = False
            t = rng.choice(TYPES)
            target = (
                rng.randrange(ctx.num_app_ranks) if rng.random() < 0.25 else -1
            )
            payload = struct.pack("<iii", ctx.rank, i, t)
            if not in_batch and rng.random() < 0.3:
                # pipelined path: counts as accepted only if the whole
                # flush succeeds (per-put outcomes are aggregated)
                ctx.iput(payload, t, work_prio=rng.randrange(-5, 6),
                         target_rank=target, answer_rank=ctx.rank)
                pipelined.append(i)
                continue
            rc = ctx.put(payload, t, work_prio=rng.randrange(-5, 6),
                         target_rank=target, answer_rank=ctx.rank)
            if rc == ADLB_SUCCESS:
                accepted.append((ctx.rank, i))
        if in_batch:
            ctx.end_batch_put()
        if pipelined:
            rc = ctx.flush_puts()
            assert rc == ADLB_SUCCESS, (
                f"soak flush failed rc={rc}; per-put attribution would "
                f"need put-level results"
            )
            accepted.extend((ctx.rank, i) for i in pipelined)
    # everyone consumes until exhaustion. Non-blocking probes use random
    # type subsets; the blocking park is always wildcard — a rank parked on
    # a subset excluding its own targeted unit's type would let the world
    # exhaust with that unit still queued (legitimate ADLB semantics,
    # reference src/adlb.c:754-785, but it would break this conservation
    # oracle).
    while True:
        subset = (
            None if rng.random() < 0.5
            else rng.sample(TYPES, rng.randrange(1, len(TYPES) + 1))
        )
        if rng.random() < 0.3:
            # fused path: one exchange, payload inline
            rc, w = ctx.get_work()
            if rc != ADLB_SUCCESS:
                break
            src, i, t = struct.unpack("<iii", w.payload[-12:])
            assert w.work_type == t
            consumed.append((src, i))
            continue
        if rng.random() < 0.3:
            rc, r = ctx.ireserve(subset)
            if rc == ADLB_NO_CURRENT_WORK:
                rc, r = ctx.reserve()  # park wildcard, never starve a unit
        else:
            rc, r = ctx.reserve()
        if rc != ADLB_SUCCESS:
            break
        rc, buf = ctx.get_reserved(r.handle)
        if rc != ADLB_SUCCESS:
            break
        src, i, t = struct.unpack("<iii", buf[-12:])
        assert r.work_type == t
        consumed.append((src, i))
    return accepted, consumed


def _check(res, num_app_ranks):
    accepted = sorted(
        x for v in res.app_results.values() if v for x in v[0]
    )
    consumed = sorted(
        x for v in res.app_results.values() if v for x in v[1]
    )
    assert len(res.app_results) == num_app_ranks, "a rank died"
    assert consumed == accepted, (
        f"conservation broken: {len(accepted)} accepted, "
        f"{len(consumed)} consumed; "
        f"lost={set(accepted) - set(consumed)} "
        f"dup_or_phantom={set(consumed) - set(accepted)}"
    )


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_soak_inproc(mode):
    cfg = Config(
        balancer=mode, exhaust_check_interval=0.2,
        balancer_max_tasks=64, balancer_max_requesters=16,
        max_malloc_per_server=8192,  # small: forces rejects + pushes
    )
    res = run_world(6, 3, TYPES, _app, cfg=cfg, timeout=120.0)
    _check(res, 6)


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_soak_native(mode):
    cfg = Config(
        server_impl="native", balancer=mode, exhaust_check_interval=0.2,
        max_malloc_per_server=8192,
    )
    res = spawn_world(6, 3, TYPES, _app, cfg=cfg, timeout=120.0)
    _check(res, 6)
