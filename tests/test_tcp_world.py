"""Multi-process TCP worlds: the analogue of the reference's `mpiexec -n k`
single-host testing story (SURVEY §4 — MPI is the only fake-able boundary;
here the TCP fabric is exercised for real, one OS process per rank)."""

import pytest

from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.transport_tcp import TcpEndpoint, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_DONE_BY_EXHAUSTION, ADLB_SUCCESS


def test_tcp_endpoint_roundtrip():
    a = TcpEndpoint(0, {0: ("127.0.0.1", 0)})
    b = TcpEndpoint(1, {1: ("127.0.0.1", 0)})
    a.addr_map[1] = b.addr_map[1]
    b.addr_map[0] = a.addr_map[0]
    try:
        a.send(1, msg(Tag.FA_PUT, 0, payload=b"x" * 100000, work_type=1))
        m = b.recv(timeout=5.0)
        assert m is not None and m.tag is Tag.FA_PUT
        assert m.payload == b"x" * 100000
        b.send(0, msg(Tag.TA_PUT_RESP, 1, rc=ADLB_SUCCESS))
        m2 = a.recv(timeout=5.0)
        assert m2 is not None and m2.rc == ADLB_SUCCESS
    finally:
        a.close()
        b.close()


def _producer_consumer(ctx):
    """Rank 0 puts tagged units; everyone consumes until exhaustion."""
    made = 0
    if ctx.rank == 0:
        for i in range(40):
            assert ctx.put(f"unit-{i}".encode(), work_type=1, work_prio=i) \
                == ADLB_SUCCESS
            made += 1
    got = []
    while True:
        rc, res = ctx.reserve([1])
        if rc != ADLB_SUCCESS:
            assert rc == ADLB_DONE_BY_EXHAUSTION
            break
        rc2, buf = ctx.get_reserved(res.handle)
        assert rc2 == ADLB_SUCCESS
        got.append(buf.decode())
    return made, got


def test_hostile_pickle_refused():
    """A crafted pickle whose globals reach outside the protocol types
    (the os.system class of payload) must be refused at the transport —
    not executed, not delivered — while legitimate pickled Msg traffic
    keeps flowing on a fresh connection."""
    import pickle
    import socket
    import struct
    import time

    class Evil:
        def __reduce__(self):
            import os

            return (os.system, ("echo pwned > /tmp/adlb_pwned",))

    import os

    if os.path.exists("/tmp/adlb_pwned"):
        os.remove("/tmp/adlb_pwned")
    b = TcpEndpoint(1, {1: ("127.0.0.1", 0)})
    try:
        host, port = b.addr_map[1]
        body = pickle.dumps(Evil(), protocol=pickle.HIGHEST_PROTOCOL)
        s = socket.create_connection((host, port), timeout=5.0)
        s.sendall(struct.pack("<I", len(body)) + body)
        time.sleep(0.3)
        s.close()
        # globals-free plain data (unpickles fine but is not a Msg) must
        # take the same clean refusal path, not crash the reader
        plain = pickle.dumps({"not": "a msg"}, protocol=pickle.HIGHEST_PROTOCOL)
        s = socket.create_connection((host, port), timeout=5.0)
        s.sendall(struct.pack("<I", len(plain)) + plain)
        time.sleep(0.2)
        s.close()
        assert not os.path.exists("/tmp/adlb_pwned"), "pickle executed!"
        assert b.recv(timeout=0.2) is None  # nothing delivered
        # legitimate pickled traffic still flows afterwards
        a = TcpEndpoint(0, {0: ("127.0.0.1", 0)})
        a.addr_map[1] = b.addr_map[1]
        try:
            a.send(1, msg(Tag.FA_PUT, 0, payload=b"ok", work_type=1))
            m = b.recv(timeout=5.0)
            assert m is not None and m.payload == b"ok"
        finally:
            a.close()
    finally:
        b.close()


def test_unregistered_app_payload_class_refused():
    """An app-message payload whose class is not registered via
    register_safe_pickle is refused (loads_restricted raises), and
    registration makes the same bytes load."""
    import pickle

    from adlb_tpu.runtime.codec import (
        loads_restricted,
        register_safe_pickle,
    )

    body = pickle.dumps(
        msg(Tag.AM_APP, 2, payload=Config(), apptag=1),
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    with pytest.raises(pickle.UnpicklingError, match="register_safe_pickle"):
        loads_restricted(body)
    from adlb_tpu.runtime import codec as _codec

    register_safe_pickle("adlb_tpu.runtime.world", "Config")
    try:
        m = loads_restricted(body)
        assert isinstance(m.data["payload"], Config)
    finally:
        # don't leak the registration into other tests' default-deny
        # assertions
        _codec._SAFE_PICKLE_GLOBALS.discard(
            ("adlb_tpu.runtime.world", "Config")
        )


def _abort_mid_economy(ctx):
    import struct as _s

    T_AB, T_C = 1, 2
    if ctx.rank == 0:
        for a in range(12):
            ctx.put(_s.pack("<qq", a, a), T_AB, answer_rank=0)
        for i in range(3):
            rc, r = ctx.reserve([T_C])
            ctx.get_reserved(r.handle)
        ctx.abort(7)
        return "aborted"
    while True:
        rc, r = ctx.reserve([T_AB])
        if rc != ADLB_SUCCESS:
            return None
        rc, buf = ctx.get_reserved(r.handle)
        a, b = _s.unpack("<qq", buf)
        ctx.put(_s.pack("<q", a + b), T_C, target_rank=r.answer_rank)


def test_abort_classification_survives_teardown_race():
    """A mid-run abort must ALWAYS surface as res.aborted, even when a
    tearing-down server closes its clients' connections before their
    TA_ABORT frames land — that home-server EOF is abort collateral
    (HomeServerLostError -> 'conn_lost'), not a world failure. The race
    is timing-dependent, so the world is repeated; pre-fix, a batch of
    this size reproduced the misclassification reliably (found by a
    randomized chaos soak)."""
    for i in range(8):
        res = spawn_world(
            4, 2, [1, 2], _abort_mid_economy,
            cfg=Config(exhaust_check_interval=0.2), timeout=60.0,
        )
        assert res.aborted, f"iteration {i} lost the abort classification"


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_spawn_world_exhaustion(mode):
    r = spawn_world(
        num_app_ranks=3,
        nservers=2,
        types=[1],
        app_fn=_producer_consumer,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    assert set(r.app_results) == {0, 1, 2}
    all_got = [u for _, got in r.app_results.values() for u in got]
    assert sorted(all_got) == sorted(f"unit-{i}" for i in range(40))
    assert len(r.server_stats) == 2


def _nq_app(ctx):
    from adlb_tpu.workloads import nq

    return nq.app_main(ctx, n=6, max_depth_for_puts=2)


def test_spawn_world_nq_known_answer():
    from adlb_tpu.workloads import nq

    r = spawn_world(
        num_app_ranks=3,
        nservers=2,
        types=[nq.WORK],
        app_fn=_nq_app,
        cfg=Config(exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total = sum(s for s, _, _ in r.app_results.values())
    assert total == nq.KNOWN_SOLUTIONS[6]
