"""Sharded-solver parity: the mesh solve must reproduce the exact
sequential greedy matching of the single-device solver.

Contract (see balancer/distributed.py docstring): same matched requester
set AND same total committed score, fuzz-checked at mesh sizes 1, 2 and
8 and at BOTH auction tiers (the on-device fused plan and its host
twin) — plus recompile guards (fixed shapes: varying live
task/requester counts must never retrace the jitted sweep or the fused
device plan), elastic churn mid-planning (joins/leaves patch rows, no
full re-sweep), and the auto-padding of server rows that are not a
multiple of the mesh size."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (forces the 8-device CPU platform)

import jax
from jax.sharding import Mesh

from adlb_tpu.balancer.distributed import (
    DistributedAssignmentSolver,
    build_distributed_solver,
)
from adlb_tpu.balancer.solve import _NEG, AssignmentSolver

TYPES = (1, 2, 3, 4)


@pytest.fixture(scope="module", params=[1, 2, 8])
def mesh(request):
    devs = np.array(jax.devices()[: request.param])
    return Mesh(devs, axis_names=("s",))


def _random_snapshots(rng, nservers, ntasks, nreqs, ntypes):
    types = TYPES[:ntypes]
    snapshots = {}
    seq = 0
    for s in range(100, 100 + nservers):
        tasks = []
        for _ in range(rng.integers(0, ntasks + 1)):
            seq += 1
            tasks.append(
                (seq, int(rng.choice(types)), int(rng.integers(-9, 10)), 8)
            )
        tasks.sort(key=lambda t: -t[2])
        reqs = []
        for r in range(rng.integers(0, nreqs + 1)):
            reqs.append(
                (
                    (s - 100) * 50 + r,
                    int(rng.integers(1, 1000)),
                    None if rng.random() < 0.25
                    else sorted({int(rng.choice(types))
                                 for _ in range(rng.integers(1, 3))}),
                )
            )
        snapshots[s] = {"tasks": tasks, "reqs": reqs}
    return snapshots


def _score(pairs, snapshots):
    prio = {
        (s, t[0]): t[2]
        for s, snap in snapshots.items()
        for t in snap["tasks"]
    }
    return sum(prio[(p[0], p[1])] for p in pairs)


def _check_parity(p_dist, p_single, snapshots):
    def by_req(pairs):
        return {(p[2], p[3], p[4]) for p in pairs}

    assert by_req(p_dist) == by_req(p_single)
    assert _score(p_dist, snapshots) == _score(p_single, snapshots)
    # no task double-assigned, and types respected
    assert len({(p[0], p[1]) for p in p_dist}) == len(p_dist)
    type_of = {
        (s, t[0]): t[1] for s, sn in snapshots.items()
        for t in sn["tasks"]
    }
    masks = {
        (s, r[0], r[1]): r[2] for s, sn in snapshots.items()
        for r in sn["reqs"]
    }
    for holder, seqno, req_home, for_rank, rqseqno in p_dist:
        mask = masks[(req_home, for_rank, rqseqno)]
        assert mask is None or type_of[(holder, seqno)] in mask


@pytest.fixture(params=["device", "host"])
def auction(request):
    return request.param


def test_parity_fuzz(mesh, auction):
    """Random instances: matched requester set AND total score equal the
    single-device greedy, at every mesh size, at both auction tiers."""
    ndev = mesh.devices.size
    rng = np.random.default_rng(1000 + ndev)
    for trial in range(8):
        ntypes = int(rng.integers(1, len(TYPES) + 1))
        nservers = max(ndev, int(rng.integers(1, 3)) * ndev)
        dist = DistributedAssignmentSolver(
            types=TYPES[:ntypes], max_tasks_per_server=12,
            max_requesters=6, mesh=mesh, rounds=64,
            servers_per_device=max(1, nservers // ndev),
            auction=auction,
        )
        single = AssignmentSolver(
            types=TYPES[:ntypes], max_tasks=12, max_requesters=6)
        snaps = _random_snapshots(
            rng, nservers=nservers, ntasks=10, nreqs=5, ntypes=ntypes)
        _check_parity(dist.solve(snaps, None),
                      single.solve(snaps, None), snaps)


def test_parity_across_incremental_rounds(mesh, auction):
    """The stateful delta-ingest path must keep producing the same plans
    a stateless single-device solve of the same snapshots would — across
    rounds that add, consume and re-park work (the candidate-list patch
    path, not just the full sweep)."""
    rng = np.random.default_rng(7)
    ndev = mesh.devices.size
    dist = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=12, max_requesters=6,
        mesh=mesh, rounds=64, servers_per_device=2, auction=auction,
    )
    single = AssignmentSolver(types=TYPES, max_tasks=12, max_requesters=6)
    nservers = 2 * ndev
    snaps = _random_snapshots(
        rng, nservers=nservers, ntasks=8, nreqs=4, ntypes=4)
    stamp = [1.0]
    for s in snaps:
        snaps[s]["stamp"] = snaps[s]["task_stamp"] = stamp[0]
    seq = [10**6]
    for _round in range(6):
        p_dist = dist.solve(snaps, None)
        p_single = single.solve(snaps, None)
        _check_parity(p_dist, p_single, snaps)
        # the data plane consumes the plan; a couple of servers get
        # fresh work and fresh parks
        for holder, seqno, req_home, for_rank, rqseqno in p_dist:
            hs = snaps[holder]
            hs["tasks"] = [t for t in hs["tasks"] if t[0] != seqno]
            stamp[0] += 1
            hs["task_stamp"] = stamp[0]
            rs = snaps[req_home]
            rs["reqs"] = [
                r for r in rs["reqs"]
                if not (r[0] == for_rank and r[1] == rqseqno)
            ]
            rs["stamp"] = stamp[0]
        for s in list(snaps)[:2]:
            seq[0] += 1
            snaps[s]["tasks"].append(
                (seq[0], int(rng.choice(TYPES)),
                 int(rng.integers(-9, 10)), 8))
            snaps[s]["tasks"].sort(key=lambda t: -t[2])
            snaps[s]["reqs"].append(
                ((s - 100) * 50 + 40 + _round, int(rng.integers(1, 1000)),
                 [int(rng.choice(TYPES))]))
            stamp[0] += 1
            snaps[s]["stamp"] = snaps[s]["task_stamp"] = stamp[0]


def test_no_retrace_across_rounds():
    """Varying live task/requester counts must hit the cached executable:
    the jitted sweep compiles exactly once for a solver's fixed shapes
    (host tier: the sweep is what calls the gather fn)."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, axis_names=("s",))
    rng = np.random.default_rng(3)
    dist = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=8, max_requesters=4, mesh=mesh,
        rounds=16, auction="host",
    )
    dist.RESYNC_INTERVAL = 1  # sweep every plan: exercise the jit path
    for trial in range(4):
        snaps = _random_snapshots(
            rng, nservers=8, ntasks=trial * 2, nreqs=trial, ntypes=4)
        dist.solve(snaps, None)
    assert dist._gather_fn._cache_size() == 1
    assert dist.sweep_count >= 3


def test_no_retrace_device_tier():
    """The fused on-device plan compiles exactly once for a solver's
    fixed shapes, across varying live counts AND elastic churn."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, axis_names=("s",))
    rng = np.random.default_rng(4)
    dist = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=8, max_requesters=4, mesh=mesh,
        rounds=16, servers_per_device=2,
    )
    for trial in range(4):
        # churn: the membership shifts by one server every trial
        snaps = _random_snapshots(
            rng, nservers=10 + trial, ntasks=4, nreqs=2, ntypes=4)
        for s in list(snaps)[:trial]:
            del snaps[s]
        dist.solve(snaps, None)
    assert dist._plan_fn._cache_size() == 1


def test_churn_during_planning_no_resweep(mesh, auction):
    """Elastic churn landing between planning rounds (a PR 15 epoch
    bump: joins + drains) must patch only the affected rows — never a
    full re-sweep of the host tier's candidate lists — and keep exact
    single-solver parity every round."""
    rng = np.random.default_rng(21)
    ndev = mesh.devices.size
    dist = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=10, max_requesters=5,
        mesh=mesh, rounds=64, servers_per_device=4, auction=auction,
    )
    single = AssignmentSolver(types=TYPES, max_tasks=10, max_requesters=5)
    nservers = 2 * ndev
    snaps = _random_snapshots(
        rng, nservers=nservers, ntasks=6, nreqs=3, ntypes=4)
    stamp = [1.0]
    for s in snaps:
        snaps[s]["stamp"] = snaps[s]["task_stamp"] = stamp[0]
    next_rank = [100 + nservers]
    seq = [10**6]
    dist.solve(snaps, None)  # cold sweep; churn rounds start counted
    sweeps0 = dist.sweep_count
    for _round in range(5):
        # drain one server, attach one new one (fresh rank)
        victim = sorted(snaps)[_round % len(snaps)]
        del snaps[victim]
        rank = next_rank[0]
        next_rank[0] += 1
        stamp[0] += 1
        tasks = []
        for _ in range(int(rng.integers(1, 6))):
            seq[0] += 1
            tasks.append((seq[0], int(rng.choice(TYPES)),
                          int(rng.integers(-9, 10)), 8))
        tasks.sort(key=lambda t: -t[2])
        snaps[rank] = {
            "tasks": tasks,
            "reqs": [(rank * 50, 1, [int(rng.choice(TYPES))])],
            "stamp": stamp[0], "task_stamp": stamp[0],
        }
        _check_parity(dist.solve(snaps, None),
                      single.solve(snaps, None), snaps)
    # a join/drain pair is a 2-row delta: the host tier patches in
    # place (no delta/cadence re-sweep), the device tier never sweeps
    assert dist.sweep_count == sweeps0
    assert dist.sweep_reasons["delta"] == 0


def test_auto_pads_non_multiple_server_rows():
    """build_distributed_solver pads 5 server rows onto an 8-device mesh
    instead of raising, and padded rows never appear in the plan."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, axis_names=("s",))
    solve = build_distributed_solver(mesh, rounds=16)
    S, K, T = 5, 4, 2
    task_prio = np.full((S, K), int(_NEG), np.int32)
    task_type = np.full((S, K), -1, np.int32)
    task_prio[0, :2] = (5, 3)
    task_type[0, :2] = (0, 1)
    task_prio[4, 0] = 9
    task_type[4, 0] = 0
    NR = 4
    req_mask = np.zeros((NR, T), bool)
    req_valid = np.zeros((NR,), bool)
    req_mask[0, 0] = True
    req_valid[0] = True
    req_mask[2] = True
    req_valid[2] = True
    assign = solve(task_prio, task_type, req_mask, req_valid)
    assert assign.shape == (NR,)
    # requester 0 (type 0 only) gets the global-best type-0 task (gid
    # 4*K), requester 2 (any) the next best (gid 0)
    assert assign[0] == 4 * K
    assert assign[2] == 0
    assert assign[1] == -1 and assign[3] == -1
    # every assigned gid indexes a real (unpadded) row
    assert all(g < S * K for g in assign if g >= 0)


def test_patch_survives_deep_single_type_burst():
    """Regression: a delta whose entries of ONE type exceed the merged
    candidate list's capacity (rows x K >> L) must not crash or corrupt
    the patch path — it truncates at the tail, flags a re-sweep, and
    still plans the top of the burst (2-device mesh, K=256, one type:
    the exact shape that used to raise a broadcast ValueError)."""
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, axis_names=("s",))
    rng = np.random.default_rng(5)
    K = 256
    dist = DistributedAssignmentSolver(
        types=(1,), max_tasks_per_server=K, max_requesters=4, mesh=mesh,
        rounds=16, servers_per_device=8, auction="host",
    )
    stamp = [1.0]
    snaps = {
        100 + s: {"tasks": [], "reqs": [], "stamp": 1.0, "task_stamp": 1.0}
        for s in range(16)
    }
    snaps[100]["reqs"] = [(0, 1, [1]), (1, 2, [1])]
    assert dist.solve(snaps, None) == []  # resident state materialized
    # delta: 10 servers x 256 same-type tasks in one burst (2560 entries
    # vs list capacity L = 2 * (C + m + 1))
    for s in range(10):
        stamp[0] += 1
        snaps[100 + s]["tasks"] = sorted(
            ((s * 1000 + i, 1, int(rng.integers(-50, 50)), 8)
             for i in range(K)), key=lambda t: -t[2])
        snaps[100 + s]["task_stamp"] = stamp[0]
    pairs = dist.solve(snaps, None)
    assert len(pairs) == 2
    # both requesters got the two globally best tasks of the burst
    all_prio = {
        (100 + s, t[0]): t[2]
        for s in range(10) for t in snaps[100 + s]["tasks"]
    }
    got = sorted(all_prio[(p[0], p[1])] for p in pairs)
    best = sorted(all_prio.values())[-2:]
    assert got == best


def test_patch_resurfaces_shard_mate_tasks_beyond_sweep_window():
    """Regression: with servers_per_device > 1, a sweep's per-shard
    top-D window can exclude a shard-mate's lower-priority tasks; when
    a delta drains the shard's top entries, the patch must re-merge the
    WHOLE shard from the host mirror so those tasks resurface at once
    (not at the next resync)."""
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, axis_names=("s",))
    K = 48
    dist = DistributedAssignmentSolver(
        types=(1,), max_tasks_per_server=K, max_requesters=2, mesh=mesh,
        rounds=16, servers_per_device=2, auction="host",
    )
    # shard 0 = servers 100 (hot) + 101 (two low-prio tasks beyond the
    # sweep window: D = C + m + 1 with C = min(64-floor, NR=8) -> small)
    snaps = {
        100: {"tasks": [(i + 1, 1, 1000 - i, 8) for i in range(K)],
              "reqs": [], "stamp": 1.0, "task_stamp": 1.0},
        101: {"tasks": [(900, 1, -5, 8), (901, 1, -6, 8)],
              "reqs": [], "stamp": 1.0, "task_stamp": 1.0},
        102: {"tasks": [], "reqs": [(7, 1, [1]), (8, 2, [1])],
              "stamp": 1.0, "task_stamp": 1.0},
        103: {"tasks": [], "reqs": [], "stamp": 1.0, "task_stamp": 1.0},
    }
    p1 = dist.solve(snaps, None)
    assert {(p[0], p[1]) for p in p1} == {(100, 1), (100, 2)}
    # the data plane consumed server 100's whole queue; 101's tasks are
    # now the only inventory — they must be planned THIS round
    snaps[100]["tasks"] = []
    snaps[100]["task_stamp"] = snaps[100]["stamp"] = 2.0
    snaps[102]["reqs"] = [(7, 3, [1]), (8, 4, [1])]
    snaps[102]["stamp"] = 2.0
    p2 = dist.solve(snaps, None)
    single = AssignmentSolver(types=(1,), max_tasks=K, max_requesters=2)
    p_ref = single.solve(snaps, None)
    assert {(p[0], p[1]) for p in p2} == {(101, 900), (101, 901)}
    assert {(p[2], p[3], p[4]) for p in p2} == {
        (p[2], p[3], p[4]) for p in p_ref}


def test_vanished_server_rows_cleared_even_at_capacity():
    """Regression: a dead server's resident rows must clear even when
    the snapshot count does not shrink below the tracked count (world
    larger than solver capacity: a beyond-capacity rank keeps the
    count level while a tracked server dies)."""
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, axis_names=("s",))
    dist = DistributedAssignmentSolver(
        types=(1,), max_tasks_per_server=4, max_requesters=2, mesh=mesh,
        rounds=16, servers_per_device=1,  # capacity S = 2
    )
    snaps = {
        100: {"tasks": [(1, 1, 9, 8)], "reqs": [],
              "stamp": 1.0, "task_stamp": 1.0},
        101: {"tasks": [], "reqs": [(5, 1, [1])],
              "stamp": 1.0, "task_stamp": 1.0},
        102: {"tasks": [], "reqs": [], "stamp": 1.0,
              "task_stamp": 1.0},  # beyond capacity: untracked
    }
    assert {(p[0], p[1]) for p in dist.solve(snaps, None)} == {(100, 1)}
    # server 100 dies; 102 keeps the snapshot count level at 2
    del snaps[100]
    snaps[101]["reqs"] = [(5, 6, [1])]
    snaps[101]["stamp"] = 2.0
    assert dist.solve(snaps, None) == []  # no phantom pair on the dead row


def test_class_pads_when_servers_not_multiple_of_mesh():
    """The engine-facing class on a 5-servers-per-8-devices world: rows
    pad transparently and parity with the single solver holds."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, axis_names=("s",))
    rng = np.random.default_rng(11)
    dist = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=8, max_requesters=4, mesh=mesh,
        rounds=32,
    )
    single = AssignmentSolver(types=TYPES, max_tasks=8, max_requesters=4)
    snaps = _random_snapshots(rng, nservers=5, ntasks=6, nreqs=3, ntypes=4)
    _check_parity(dist.solve(snaps, None), single.solve(snaps, None),
                  snaps)
