"""Randomized adversarial fuzz of the migration credit/ack state machine
(round-4 verdict item 6).

The phantom-credit bug class (fixed in commit 3236cc1, regression-tested
point-wise in test_balancer.py) lives in the snapshot/credit/ack lattice
spread across ``PlanEngine.round``/``_prune_credits``/``_plan_migrations``
and the master's ``Server._accept_snapshot`` merge.  This harness drives
those REAL code paths — the engine is a live ``PlanEngine`` and snapshot
intake goes through the real unbound ``Server._accept_snapshot`` on a
stub — through randomized adversarial schedules:

* delayed / reordered plan enactments and unit-transfer batches
  (per-(src,dest) FIFO, as TCP guarantees, but arbitrary cross-channel
  interleavings);
* migration batches that go fully or partially stale at the source
  before enactment (the phantom-credit trigger);
* reqs-only-first and reqs-only-interleaved snapshots (the ack-inherit
  merge path);
* snapshots delivered late, skipped, or carrying duplicated acks (the
  running-max ack dict is resent in every snapshot by design);
* optionally, batches lost in transit (the TTL-backstop path).

Oracles checked continuously:

1. **unit conservation / at-most-once delivery** — every unit is in
   exactly one of {queued@rank, in-transit, consumed, lost}; arrival
   asserts the unit was in transit (a double-feed would trip this);
2. **plan-ledger freshness** — the engine never re-plans a (rank, seqno)
   unless a snapshot with a newer task view was accepted after the prior
   plan (guards ledger-eviction regressions);
3. **ack monotonicity** — per (src, dest) channel FIFO implies strictly
   increasing mig_ids at the destination (the sim models BOTH FIFOs
   reality provides: the src->dest unit channel AND the balancer->src
   plan-command stream — without the latter, two batches the engine
   legitimately has outstanding on one channel could enact in inverted
   order under an adversarial due draw and fail this assertion
   spuriously);
4. **credit quiescence** — with the TTL and stamp/min-age fallbacks
   pinned OFF, once all transit drains and every server ships a full
   snapshot, a planning round must leave ``_planned_in`` EMPTY: exact
   ack clearing alone must clear every credit, including fully-stale
   batches.  Reintroducing the round-3 bug (sources dropping fully-stale
   batches instead of shipping the empty batch id) leaks credits here —
   the companion test flips the harness's ``buggy_drop_empty`` knob and
   asserts the oracle catches it.

Reference behavior being protected: the reference balances via per-unit
steal round trips and has no plan credits at all (``src/adlb.c``
PUSH_QUERY path); the credit lattice is this framework's own riskiest
invention, hence the adversarial coverage.
"""

from __future__ import annotations

import random
import time

from adlb_tpu.balancer.engine import PlanEngine
from adlb_tpu.runtime.server import Server

T1, T2 = 1, 2


class _Master:
    """Just enough master-server surface for the real _accept_snapshot."""

    def __init__(self):
        self._snapshots = {}

    def _update_parked(self, src, reqs):
        pass

    def _maybe_wake_balancer(self, src, snap):
        pass

    def accept(self, src, snap):
        Server._accept_snapshot(self, src, snap)


class CreditFuzzSim:
    def __init__(
        self,
        seed: int,
        *,
        nservers: int = 4,
        consumers: int = 2,
        buggy_drop_empty: bool = False,
        drop_prob: float = 0.0,
        stale_all_prob: float = 0.25,
        engine_kw: dict | None = None,
    ):
        self.rng = random.Random(seed)
        self.eng = PlanEngine(
            types=(T1, T2), max_tasks=256, max_requesters=64,
            host_threshold_reqs=10 ** 9, **(engine_kw or {}),
        )
        self.master = _Master()
        self.buggy = buggy_drop_empty
        self.drop_prob = drop_prob
        self.stale_all_prob = stale_all_prob
        self.nservers = nservers
        self.servers = {}
        for s in range(nservers):
            self.servers[s] = {
                "inv": {},  # uid -> (wtype, prio, len)
                "acks": {},  # src -> highest mig_id landed from src
                "workers": [
                    {"busy": 0, "parked": None, "wrank": 100 + s * 10 + i}
                    for i in range(consumers)
                ],
                "rqseq": 0,
                # adversarial: force the first snapshots reqs-only
                "reqs_only_until": self.rng.randrange(0, 6),
            }
        self.meta = {}  # uid -> (wtype, prio, len)
        self.unit_state = {}  # uid -> ("q", rank)|("transit", mid)|state str
        self.next_uid = 0
        self.msgs = []  # balancer->server plan commands
        self.cmd_due = {}  # src -> last mig command due (stream FIFO)
        self.chan = {}  # (src, dest) -> FIFO of unit batches
        self.snap_q = {s: [] for s in range(nservers)}
        self.it = 0
        self.produced = self.consumed = self.lost = 0
        self.stats = {
            "stale_batches": 0, "enacted_batches": 0, "migs_planned": 0,
            "matches_planned": 0, "delivered_units": 0,
        }
        self.last_plan = {}  # (rank, uid) -> monotonic lower bound

    # ------------------------------------------------------------ helpers
    def _consume(self, s: int, uid: int) -> None:
        del self.servers[s]["inv"][uid]
        self.unit_state[uid] = "consumed"
        self.consumed += 1

    def _local_fetch(self, s: int, w: dict) -> bool:
        types = w["parked"][2] if w["parked"] else None
        inv = self.servers[s]["inv"]
        for uid, (wt, _p, _l) in inv.items():
            if types is None or wt in types:
                self._consume(s, uid)
                w["busy"] = self.rng.randrange(2, 10)
                w["parked"] = None
                return True
        return False

    # -------------------------------------------------------- enactments
    def _enact_migration(self, m: dict) -> None:
        rng, src, dest = self.rng, m["src"], m["dest"]
        live = [u for u in m["uids"] if self.unit_state[u] == ("q", src)]
        # adversarial staleness: the source's own workers drain planned
        # units between plan and enactment
        if live and rng.random() < self.stale_all_prob:
            for u in live:
                self._consume(src, u)
            live = []
        elif live:
            for u in list(live):
                if rng.random() < 0.2:
                    self._consume(src, u)
                    live.remove(u)
        self.stats["enacted_batches"] += 1
        if not live:
            self.stats["stale_batches"] += 1
            if self.buggy:
                return  # THE round-3 BUG: fully-stale batch dropped
        if live and self.drop_prob and rng.random() < self.drop_prob:
            for u in live:
                del self.servers[src]["inv"][u]
                self.unit_state[u] = "lost"
                self.lost += 1
            return  # batch lost in transit: only the TTL can clear it
        for u in live:
            del self.servers[src]["inv"][u]
            self.unit_state[u] = ("transit", m["mid"])
        q = self.chan.setdefault((src, dest), [])
        due = self.it + rng.randrange(1, 5)
        if q:
            due = max(due, q[-1]["due"])  # FIFO per channel
        q.append({"due": due, "mid": m["mid"], "uids": live})

    def _arrive(self, src: int, dest: int, batch: dict) -> None:
        sv = self.servers[dest]
        for u in batch["uids"]:
            assert self.unit_state[u] == ("transit", batch["mid"]), (
                "unit delivered twice or from a non-transit state",
                u, self.unit_state[u], batch,
            )
            self.unit_state[u] = ("q", dest)
            sv["inv"][u] = self.meta[u]
        prev = sv["acks"].get(src, 0)
        assert batch["mid"] > prev, (
            "mig_id not strictly increasing per (src,dest) channel",
            src, dest, batch["mid"], prev,
        )
        sv["acks"][src] = batch["mid"]
        self.stats["delivered_units"] += len(batch["uids"])

    def _enact_match(self, m: dict) -> None:
        holder, uid = m["holder"], m["uid"]
        if self.unit_state[uid] != ("q", holder):
            return  # stale plan entry: validated away, as at enactment
        for w in self.servers[m["req_home"]]["workers"]:
            p = w["parked"]
            if p and p[0] == m["for_rank"] and p[1] == m["rqseqno"]:
                self._consume(holder, uid)
                w["busy"] = self.rng.randrange(2, 10)
                w["parked"] = None
                return
        # requester gone (satisfied locally): unit stays where it is

    # --------------------------------------------------------- snapshots
    def _send_snap(self, s: int, reqs_only: bool, immediate: bool = False):
        sv = self.servers[s]
        if self.it < sv["reqs_only_until"]:
            reqs_only = True
        if reqs_only:
            tasks = None
        else:
            tasks = [
                (uid, v[0], v[1], v[2]) for uid, v in sv["inv"].items()
            ][:256]
        reqs = [w["parked"] for w in sv["workers"] if w["parked"]]
        snap = {
            "tasks": tasks,
            "reqs": [(wr, rq, list(ty) if ty else None)
                     for wr, rq, ty in reqs],
            "nbytes": sum(v[2] for v in sv["inv"].values()),
            "consumers": len(sv["workers"]),
            "stamp": time.monotonic(),
            "mig_acks": dict(sv["acks"]),
        }
        if immediate:
            self.master.accept(s, snap)
            return
        due = self.it if self.rng.random() < 0.7 else (
            self.it + self.rng.randrange(1, 4)
        )
        q = self.snap_q[s]
        if q:
            due = max(due, q[-1][0])  # per-server FIFO (TCP ordering)
        q.append((due, snap))

    def _deliver_snaps(self) -> None:
        for s, q in self.snap_q.items():
            while q and q[0][0] <= self.it:
                _, snap = q.pop(0)
                self.master.accept(s, snap)

    # ------------------------------------------------------------- round
    def _check_replan(self, key: tuple, t_before: float) -> None:
        prev = self.last_plan.get(key)
        if prev is None:
            return
        snap = self.master._snapshots.get(key[0])
        assert snap is not None, ("re-plan with no snapshot", key)
        tstamp = snap.get("task_stamp", snap.get("stamp"))
        assert tstamp > prev, (
            "unit re-planned without a fresher accepted task view",
            key, tstamp, prev,
        )

    def _round(self) -> int:
        if not self.master._snapshots:
            return 0
        rng = self.rng
        t_before = time.monotonic()
        matches, migs = self.eng.round(dict(self.master._snapshots))
        seen: set = set()
        for holder, uid, req_home, for_rank, rqseqno in matches:
            key = (holder, uid)
            assert key not in seen, ("unit planned twice in one round", key)
            seen.add(key)
            self._check_replan(key, t_before)
            self.last_plan[key] = t_before
            self.msgs.append({
                "due": self.it + rng.randrange(0, 5), "kind": "match",
                "holder": holder, "uid": uid, "req_home": req_home,
                "for_rank": for_rank, "rqseqno": rqseqno,
            })
            self.stats["matches_planned"] += 1
        for src, dest, uids, mid in migs:
            for uid in uids:
                key = (src, uid)
                assert key not in seen, (
                    "unit planned twice in one round", key,
                )
                seen.add(key)
                self._check_replan(key, t_before)
                self.last_plan[key] = t_before
            # balancer->src is ONE connection: mig commands toward a src
            # enact in plan order (so per-channel mids stay monotonic
            # even with two batches outstanding on one channel — the
            # engine plans that legitimately when a dest's demand grows)
            due = max(self.it + rng.randrange(0, 6),
                      self.cmd_due.get(src, -1))
            self.cmd_due[src] = due
            self.msgs.append({
                "due": due, "kind": "mig",
                "src": src, "dest": dest, "uids": list(uids), "mid": mid,
            })
            self.stats["migs_planned"] += 1
        return len(matches) + len(migs)

    def _check_conservation(self) -> None:
        q = t = 0
        for st in self.unit_state.values():
            if isinstance(st, tuple):
                if st[0] == "q":
                    q += 1
                else:
                    t += 1
        assert self.produced == self.consumed + self.lost + q + t, (
            "unit conservation violated",
            self.produced, self.consumed, self.lost, q, t,
        )
        qd = sum(len(sv["inv"]) for sv in self.servers.values())
        assert qd == q, ("inventory/state divergence", qd, q)

    # -------------------------------------------------------------- step
    def kill(self, rank: int) -> None:
        """Server death mid-run: its snapshots stop (the master pops the
        entry on DS_END / connection loss), its queued inventory and
        everything in transit TO it die with the process.  step() then
        guards every pump on membership in ``servers`` — plans and
        channels referencing the dead rank are dropped, and credits to
        it can only retire via _prune_credits' snapshot-is-None TTL
        branch."""
        self.master._snapshots.pop(rank, None)
        victim = self.servers.pop(rank)
        for uid in victim["inv"]:
            self.unit_state[uid] = "lost"
            self.lost += 1
        for (_src, dst), q in self.chan.items():
            if dst == rank:
                for batch in q:
                    for uid in batch["uids"]:
                        self.unit_state[uid] = "lost"
                        self.lost += 1
                q.clear()
        self.snap_q.pop(rank, None)

    def step(self, produce: bool = True) -> int:
        self.it += 1
        rng = self.rng
        if produce and 0 in self.servers and rng.random() < 0.5:
            for _ in range(rng.randrange(1, 9)):
                uid = self.next_uid
                self.next_uid += 1
                wt = T1 if rng.random() < 0.8 else T2
                self.meta[uid] = (wt, rng.randrange(1, 10), 8)
                self.servers[0]["inv"][uid] = self.meta[uid]
                self.unit_state[uid] = ("q", 0)
                self.produced += 1
        remaining = []
        for m in self.msgs:
            if m["due"] > self.it:
                remaining.append(m)
            elif m["kind"] == "mig":
                # a plan touching a dead rank is dropped: a live source
                # simply keeps its units queued, a dead source's units
                # are already lost
                if m["src"] in self.servers and m["dest"] in self.servers:
                    self._enact_migration(m)
            else:
                if (
                    m["holder"] in self.servers
                    and m["req_home"] in self.servers
                ):
                    self._enact_match(m)
        self.msgs = remaining
        for (src, dest), q in self.chan.items():
            if dest not in self.servers:
                continue  # cleared by kill(); nothing can arrive
            while q and q[0]["due"] <= self.it:
                self._arrive(src, dest, q.pop(0))
        for s, sv in self.servers.items():
            for w in sv["workers"]:
                if w["busy"] > 0:
                    w["busy"] -= 1
                elif w["parked"] is None:
                    if not self._local_fetch(s, w):
                        sv["rqseq"] += 1
                        types = None if rng.random() < 0.7 else (
                            [T1] if rng.random() < 0.8 else [T1, T2]
                        )
                        w["parked"] = (w["wrank"], sv["rqseq"], types)
                else:
                    self._local_fetch(s, w)
        for s in list(self.servers):
            r = rng.random()
            if r < 0.55:
                self._send_snap(s, reqs_only=False)
            elif r < 0.75:
                self._send_snap(s, reqs_only=True)
        self._deliver_snaps()
        planned = self._round()
        self._check_conservation()
        return planned

    def in_flight_empty(self) -> bool:
        return not self.msgs and all(not q for q in self.chan.values()) \
            and all(not q for q in self.snap_q.values())

    def drain(self, max_passes: int = 600) -> bool:
        """Run to quiescence: no production, all transit delivered, full
        snapshots accepted from everyone, and a final round that plans
        nothing. Returns True when quiescent."""
        settled = 0
        for _ in range(max_passes):
            planned = self.step(produce=False)
            if not self.in_flight_empty() or planned:
                settled = 0
                continue
            for s in list(self.servers):
                self._send_snap(s, reqs_only=False, immediate=True)
            if self._round():
                settled = 0
                continue
            settled += 1
            if settled >= 3:
                return True
        return False


def _outstanding_credits(eng: PlanEngine) -> list:
    return [
        (dest, e) for dest, entries in eng._planned_in.items()
        for e in entries
    ]


def test_fuzz_credit_ack_exact_clearing():
    """With the TTL and stamp/min-age fallbacks pinned OFF, exact ack
    clearing alone must clear EVERY migration credit — across random
    adversarial schedules including fully-stale batches, reqs-only-first
    snapshots, and reordered enactments."""
    stale_total = 0
    for seed in (1, 2, 3):
        sim = CreditFuzzSim(
            seed, engine_kw={"inflow_ttl": 1e9, "inflow_min_age": 1e9},
        )
        for _ in range(250):
            sim.step()
        assert sim.drain(), (
            "world failed to quiesce", sim.stats, sim.msgs, sim.chan,
        )
        left = _outstanding_credits(sim.eng)
        assert not left, (
            "phantom credits survived exact ack clearing", left, sim.stats,
        )
        assert sim.stats["migs_planned"] > 0, (
            "schedule never exercised migrations", sim.stats,
        )
        stale_total += sim.stats["stale_batches"]
    # the dangerous path must actually have been exercised
    assert stale_total > 0, "no fully-stale batches across all seeds"


def test_fuzz_detects_reintroduced_phantom_credit_bug():
    """Reintroducing the round-3 bug (source silently drops a fully-stale
    batch instead of shipping its empty id) must leak credits that the
    quiescence oracle catches — i.e. the fuzz genuinely guards the fix."""
    leaked = False
    stale = 0
    for seed in (1, 2, 3, 4):
        sim = CreditFuzzSim(
            seed, buggy_drop_empty=True, stale_all_prob=0.5,
            engine_kw={"inflow_ttl": 1e9, "inflow_min_age": 1e9},
        )
        for _ in range(250):
            sim.step()
        sim.drain()
        stale += sim.stats["stale_batches"]
        if _outstanding_credits(sim.eng):
            leaked = True
            break
    assert stale > 0, "bug path never exercised (no fully-stale batches)"
    assert leaked, (
        "fuzz failed to detect the reintroduced phantom-credit bug"
    )


def test_fuzz_ttl_backstop_clears_lost_batches():
    """Batches lost in transit (crashed peer, dropped connection) leave
    credits only the TTL backstop can clear; after the TTL every credit
    must be gone at the next round."""
    for seed in (7, 8):
        sim = CreditFuzzSim(
            seed, drop_prob=0.3,
            engine_kw={"inflow_ttl": 0.2, "inflow_min_age": 0.01},
        )
        for _ in range(200):
            sim.step()
        sim.drain()
        time.sleep(0.25)  # > inflow_ttl: the backstop horizon passes
        for s in range(sim.nservers):
            sim._send_snap(s, reqs_only=False, immediate=True)
        # age against a PRE-round timestamp: the engine prunes with its
        # own (slightly later) clock, so any credit it keeps is strictly
        # younger than TTL relative to t_round — judging with a fresh
        # post-round clock would flag credits that merely aged a few ms
        # between the prune and the assertion (observed flake)
        t_round = time.monotonic()
        sim._round()
        # the final round prunes everything past the TTL but may itself
        # plan fresh migrations (leftover inventory, parked reqs) — the
        # invariant is that no credit OLDER than the TTL survives a round
        old = [
            (d, e) for d, e in _outstanding_credits(sim.eng)
            if t_round - e[0] > sim.eng.INFLOW_TTL
        ]
        assert not old, ("credits outlived the TTL backstop", old)
        assert sim.lost > 0, "drop schedule never lost a batch"


def test_fuzz_dead_destination_credits_ttl_pruned():
    """A destination that STOPS appearing in snapshots (server ended /
    died — the master pops its snapshot on DS_END) can never ack its
    in-flight credits; _prune_credits' snapshot-is-None branch must
    still retire them by TTL, and the planner must keep functioning for
    the survivors (the conservation oracle stays armed throughout)."""
    exercised = 0
    for seed in (11, 12, 13):
        sim = CreditFuzzSim(
            seed, engine_kw={"inflow_ttl": 0.2, "inflow_min_age": 0.01},
        )
        # run until some non-master rank holds live credits (cap the
        # search so a pathological seed fails loudly, not forever)
        dead = None
        for _ in range(400):
            sim.step()
            cand = [r for r in sim.eng._planned_in if r != 0]
            if cand:
                dead = max(cand, key=lambda r: len(sim.eng._planned_in[r]))
                break
        if dead is None:
            continue  # this seed never migrated off-master; try the next
        assert sim.eng._planned_in.get(dead), "vacuous kill target"
        exercised += 1
        sim.kill(dead)
        # survivors keep running; the dead rank's credits age out via
        # the TTL-only branch (no snapshot can ever ack them again)
        deadline = time.monotonic() + 0.35  # > inflow_ttl
        while time.monotonic() < deadline:
            sim.step(produce=False)
        t_round = time.monotonic()  # pre-round clock (see TTL test note)
        sim.step(produce=False)
        leftover = [
            (d, e) for d, e in _outstanding_credits(sim.eng) if d == dead
        ]
        old = [e for _, e in leftover if t_round - e[0] > sim.eng.INFLOW_TTL]
        assert not old, (
            "dead destination's credits outlived the TTL-only pruning",
            leftover,
        )
    assert exercised > 0, "no seed ever produced off-master credits"
