"""MPE-equivalent event tracing (reference src/adlb_prof.c:46-74,185-236)."""

import json
import time

from adlb_tpu.api import run_world
from adlb_tpu.runtime.trace import Tracer, merge, span_names
from adlb_tpu.runtime.world import Config


def test_tracer_user_state_inference():
    tr = Tracer(rank=3)
    with tr.span("adlb:reserve"):
        pass
    tr.got_work(7)
    time.sleep(0.005)
    tr.api_entry()  # next API call closes the inferred span
    user = [e for e in tr.events if e["name"] == "user:type7"]
    assert len(user) == 1
    assert user[0]["dur"] >= 4_000  # microseconds
    assert user[0]["tid"] == 3
    # no open span left behind
    tr.api_entry()
    assert len([e for e in tr.events if e["name"].startswith("user:")]) == 1


def test_world_trace_collection(tmp_path):
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            for i in range(6):
                ctx.put(b"w" * 16, T, work_prio=i)
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc < 0:
                break
            rc, buf = ctx.get_reserved(r.handle)
            time.sleep(0.002)  # "user compute" the tracer should infer
            n += 1
        if ctx.rank == 0:
            ctx.set_problem_done()
        return n

    res = run_world(
        num_app_ranks=2,
        nservers=1,
        types=[T],
        app_fn=app,
        cfg=Config(trace=True),
        timeout=60.0,
    )
    assert sum(res.app_results.values()) == 6
    names = span_names(res.trace_events)
    assert {"adlb:put", "adlb:reserve", "adlb:get_reserved",
            "adlb:set_problem_done", f"user:type{T}"} <= names
    # six units fetched -> six inferred user-compute spans, each >= the sleep
    user = [e for e in res.trace_events if e["name"] == f"user:type{T}"]
    assert len(user) == 6
    assert all(e["dur"] >= 1_500 for e in user)
    assert all(e["args"]["work_type"] == T for e in user)
    # both app ranks traced (pid 0 = apps); the server traces too (pid 1)
    app_tids = {e["tid"] for e in res.trace_events
                if e["pid"] == 0 and e["ph"] != "M"}
    assert app_tids == {0, 1}
    srv_tids = {e["tid"] for e in res.trace_events
                if e["pid"] == 1 and e["ph"] != "M"}
    assert srv_tids == {2}, "server rank 2 should trace its handlers"
    assert {"srv:FA_PUT", "srv:FA_RESERVE", "srv:FA_GET_RESERVED"} <= names
    # events arrive time-sorted and the file is valid chrome trace JSON
    ts = [e["ts"] for e in res.trace_events]
    assert ts == sorted(ts)
    out = tmp_path / "trace.json"
    res.save_trace(str(out))
    doc = json.loads(out.read_text())
    assert doc["traceEvents"], "empty trace file"


def test_trace_off_by_default():
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"x", T, target_rank=0)
            rc, r = ctx.reserve([T])
            ctx.get_reserved(r.handle)
            ctx.set_problem_done()
        else:
            rc, _ = ctx.reserve([T])
        return True

    res = run_world(num_app_ranks=2, nservers=1, types=[T], app_fn=app,
                    timeout=60.0)
    assert res.trace_events == []


def test_merge_orders_events():
    a, b = Tracer(0), Tracer(1)
    with b.span("later"):
        pass
    with a.span("latest"):
        pass
    events = merge([a, b])
    assert [e["tid"] for e in events] == [1, 0]
