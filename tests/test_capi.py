"""Native C API integration: compile libadlb + C examples and run them as
real processes against Python servers over the TCP fabric (SURVEY C1/C3:
the reference's public C surface, here over the binary codec)."""

import os
import shutil

import pytest

from adlb_tpu.native.capi import build_example, build_libadlb, run_native_world
from adlb_tpu.runtime.world import Config

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None,
    reason="no C toolchain",
)

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


def test_libadlb_builds():
    assert os.path.exists(build_libadlb())


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_capi_smoke(mode):
    exe = build_example(os.path.join(_EXAMPLES, "capi_smoke.c"))
    results, stats = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1, 2],
        exe=exe,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
    assert len(stats) == 2
    total_processed = sum(
        int(out.split("processed=")[1].split()[0]) for _, out, _ in results
    )
    assert total_processed == 24


@pytest.mark.parametrize("server_impl", ["python", "native"])
def test_capi_fastpaths(server_impl):
    """ADLB_Iput/Flush_puts + ADLB_Get_work_batch against both server
    implementations: all 40 units consumed exactly once (sum check),
    with at least one multi-unit batch observed somewhere."""
    exe = build_example(os.path.join(_EXAMPLES, "fastpath_c.c"))
    results, _ = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1],
        exe=exe,
        cfg=Config(server_impl=server_impl, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total_n, total_sum, any_multi = 0, 0, 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        parts = out.split()
        total_n += int(parts[parts.index("got") + 1])
        total_sum += int(parts[parts.index("sum") + 1])
        any_multi += int(parts[parts.index("multi") + 1])
    assert total_n == 40
    assert total_sum == sum(range(1, 41))
    assert any_multi > 0  # the producer runs ahead: batches must form


def test_capi_prefix_fuse():
    """Batch-common + ADLB_Get_work against Python servers: fused
    responses carry only the SUFFIX plus the prefix handle since the
    remote-fused-fetch change, and the native client must fetch the
    prefix and assemble (libadlb.cpp fetch_common_prefix) — the
    codec/libadlb sync check for the new response shape."""
    exe = build_example(os.path.join(_EXAMPLES, "prefix_fuse_c.c"))
    results, _ = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1],
        exe=exe,
        cfg=Config(exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total_n, total_sum = 0, 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
        total_n += int(out.split("processed=")[1].split()[0])
        total_sum += int(out.split("sum=")[1].split()[0])
    assert total_n == 24
    assert total_sum == sum(range(1, 25))


@pytest.mark.parametrize("server_impl", ["python", "native"])
def test_capi_app_messaging(server_impl):
    """The c1.c pattern in C: answers as direct app-to-app messages
    (ADLB_App_send/App_recv, the reference's app_comm role) — against both
    server implementations."""
    exe = build_example(os.path.join(_EXAMPLES, "appmsg_c.c"))
    results, _ = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1],
        exe=exe,
        cfg=Config(server_impl=server_impl, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    handled = 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        if "handled" in out:
            handled += int(out.split("handled")[1].split()[0])
    assert handled == 18
    assert any("sum" in out and "OK" in out for _, out, _ in results)


def test_capi_trace_files(tmp_path):
    """ADLB_TRACE arms the C client's profiling wrapper layer (the
    reference's MPE hooks, src/adlb_prof.c): per-call spans + inferred
    user states land in Chrome-trace JSON, one file per rank."""
    import json

    exe = build_example(os.path.join(_EXAMPLES, "capi_smoke.c"))
    prefix = str(tmp_path / "capi")
    results, _ = run_native_world(
        n_clients=2,
        nservers=1,
        types=[1, 2],
        exe=exe,
        cfg=Config(exhaust_check_interval=0.2),
        env_extra={"ADLB_TRACE": prefix},
        timeout=90.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
    for rank in range(2):
        path = tmp_path / f"capi.{rank}.trace.json"
        assert path.exists(), f"missing trace for rank {rank}"
        events = json.loads(path.read_text())
        names = {e["name"] for e in events}
        assert "adlb:put" in names and "adlb:reserve" in names
        assert any(n.startswith("user:type") for n in names)


def test_capi_nq_known_answer():
    exe = build_example(os.path.join(_EXAMPLES, "nq_c.c"))
    results, _ = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1],
        exe=exe,
        cfg=Config(exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total = 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        total += int(out.split("solutions=")[1].split()[0])
    assert total == 40  # 7-queens
