"""Pipelined puts (iput/flush_puts) — a throughput extension with no
reference analogue (upstream's Put is one synchronous round trip per
unit)."""

import struct

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_PUT_REJECTED, ADLB_SUCCESS

T = 1


def _producer_consumer(ctx):
    if ctx.rank == 0:
        for i in range(200):
            assert ctx.iput(struct.pack("<q", i), T, work_prio=i % 7) \
                == ADLB_SUCCESS
        assert ctx.flush_puts() == ADLB_SUCCESS
    got = []
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return got
        rc, buf = ctx.get_reserved(r.handle)
        got.append(struct.unpack("<q", buf)[0])


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_iput_conservation(mode):
    cfg = Config(balancer=mode, exhaust_check_interval=0.2,
                 balancer_max_tasks=256, balancer_max_requesters=16)
    res = run_world(4, 2, [T], _producer_consumer, cfg=cfg)
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(200))


def test_iput_mixed_with_sync_put_and_reserve():
    """Out-of-band put responses must never answer a synchronous put, and
    reserves interleave safely with unsettled iputs."""

    def app(ctx):
        if ctx.rank == 0:
            for i in range(50):
                ctx.iput(struct.pack("<q", i), T)
            # sync put while 50 responses are in flight — TARGETED at
            # ourselves so the reserve below always has a unit: an
            # untargeted pool can legitimately be drained by the two
            # consumer ranks during a GIL/GC pause of this thread, and
            # the reserve then correctly returns DONE_BY_EXHAUSTION
            # (observed as a rare full-suite-only flake)
            assert ctx.put(struct.pack("<q", 999), T,
                           target_rank=0) == ADLB_SUCCESS
            # reserve while still unsettled
            rc, r = ctx.reserve([T])
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            assert ctx.flush_puts() == ADLB_SUCCESS
        got = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return got
            ctx.get_reserved(r.handle)
            got += 1

    res = run_world(3, 2, [T], app, cfg=Config(exhaust_check_interval=0.2))
    total = sum(v if isinstance(v, int) else 0 for v in res.app_results.values())
    assert total + 1 == 51  # 50 iputs + 1 sync put, one consumed by rank 0


def test_iput_rejects_settle_at_flush():
    """With a tiny per-server cap and no consumers until flush, some iputs
    must terminally reject after retries — reported by flush_puts."""

    def app(ctx):
        if ctx.rank == 0:
            for i in range(20):
                ctx.iput(b"x" * 1024, T)
            rc = ctx.flush_puts()
            ctx.set_problem_done()
            return rc
        rc, _ = ctx.reserve([2])  # park on an unused type
        assert rc != ADLB_SUCCESS
        return None

    res = run_world(
        2, 2, [T, 2], app,
        cfg=Config(max_malloc_per_server=4096, put_max_retries=2,
                   exhaust_check_interval=10.0),
    )
    # 20 KB offered into 8 KB of capacity: flush must report rejections
    assert res.app_results[0] == ADLB_PUT_REJECTED


def test_iput_flush_reports_no_more_work():
    """Termination, not capacity: a producer whose pipelined puts land
    after set_problem_done must see ADLB_NO_MORE_WORK (its stop signal),
    not a capacity rejection."""
    import time

    from adlb_tpu.types import ADLB_NO_MORE_WORK

    def app(ctx):
        if ctx.rank == 1:
            ctx.set_problem_done()
            return None
        time.sleep(0.3)  # let NO_MORE_WORK propagate to the servers
        for i in range(5):
            ctx.iput(struct.pack("<q", i), T)
        return ctx.flush_puts()

    res = run_world(2, 2, [T], app, cfg=Config(exhaust_check_interval=10.0))
    assert res.app_results[0] == ADLB_NO_MORE_WORK


def test_iput_native_servers():
    cfg = Config(server_impl="native", exhaust_check_interval=0.2)
    res = spawn_world(4, 2, [T], _producer_consumer, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(200))


def test_iput_inside_batch_refused():
    def app(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(b"pfx")
            with pytest.raises(Exception, match="iput inside"):
                ctx.iput(b"x", T)
            ctx.end_batch_put()
            ctx.set_problem_done()
        else:
            rc, _ = ctx.reserve([2])
            assert rc != ADLB_SUCCESS
        return None

    run_world(2, 1, [T, 2], app, cfg=Config(exhaust_check_interval=10.0))
