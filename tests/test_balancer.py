"""Tests for the jitted global assignment solve and the end-to-end TPU
balancer mode (snapshot -> solve -> plan -> enactment)."""

from adlb_tpu.api import run_world
from adlb_tpu.balancer.solve import AssignmentSolver
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import (
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_NO_MORE_WORK,
    ADLB_SUCCESS,
)

T1, T2 = 1, 2


def _world(ns=2):
    return WorldSpec(nranks=4 + ns, nservers=ns, types=(T1, T2))


def test_solver_basic_match():
    s = AssignmentSolver(types=(T1, T2), max_tasks=8, max_requesters=4)
    snapshots = {
        10: {"tasks": [(100, T1, 5, 1)], "reqs": []},
        11: {"tasks": [], "reqs": [(0, 1, [T1])]},
    }
    pairs = s.solve(snapshots, None)
    assert pairs == [(10, 100, 11, 0, 1)]


def test_solver_type_mask_respected():
    s = AssignmentSolver(types=(T1, T2), max_tasks=8, max_requesters=4)
    snapshots = {
        10: {"tasks": [(100, T2, 99, 1)], "reqs": []},
        11: {"tasks": [], "reqs": [(0, 1, [T1])]},
    }
    assert s.solve(snapshots, None) == []
    # any-type requester (None mask) takes it
    snapshots[11]["reqs"] = [(0, 2, None)]
    assert s.solve(snapshots, None) == [(10, 100, 11, 0, 2)]


def test_solver_priority_wins():
    s = AssignmentSolver(types=(T1,), max_tasks=8, max_requesters=4)
    snapshots = {
        10: {"tasks": [(1, T1, 1, 1), (2, T1, 9, 1), (3, T1, 5, 1)], "reqs": []},
        11: {"tasks": [], "reqs": [(0, 1, [T1])]},
    }
    pairs = s.solve(snapshots, None)
    assert pairs == [(10, 2, 11, 0, 1)]  # highest priority task chosen


def test_solver_many_to_many_no_double_assignment():
    s = AssignmentSolver(types=(T1,), max_tasks=16, max_requesters=16)
    snapshots = {
        10: {"tasks": [(i, T1, i, 1) for i in range(10)], "reqs": []},
        11: {"tasks": [], "reqs": [(r, r, [T1]) for r in range(6)]},
    }
    pairs = s.solve(snapshots, None)
    assert len(pairs) == 6
    seqnos = [p[1] for p in pairs]
    assert len(set(seqnos)) == 6  # no task assigned twice
    assert set(seqnos) == set(range(4, 10))  # the 6 highest priorities move


def test_tpu_mode_end_to_end():
    """Full world in balancer=tpu mode: untargeted cross-server movement is
    planner-driven; answers flow back; known answer checked."""
    NTASK = 30

    def app(ctx):
        if ctx.rank == 0:
            for i in range(NTASK):
                assert ctx.put(str(i).encode(), T1, work_prio=i) == ADLB_SUCCESS
            total = 0
            for _ in range(NTASK):
                rc, r = ctx.reserve([T2])
                assert rc == ADLB_SUCCESS
                rc, buf = ctx.get_reserved(r.handle)
                total += int(buf)
            ctx.set_problem_done()
            return total
        n = 0
        while True:
            rc, r = ctx.reserve([T1])
            if rc != ADLB_SUCCESS:
                assert rc in (ADLB_NO_MORE_WORK, ADLB_DONE_BY_EXHAUSTION)
                return n
            rc, buf = ctx.get_reserved(r.handle)
            ctx.put(str(int(buf) * 3).encode(), T2, target_rank=0)
            n += 1

    res = run_world(
        4, 3, [T1, T2], app,
        cfg=Config(balancer="tpu", balancer_max_tasks=64, balancer_max_requesters=16),
        timeout=300.0,
    )
    assert res.app_results[0] == 3 * sum(range(NTASK))
    # workers collectively processed everything
    assert sum(res.app_results[r] for r in range(1, 4)) == NTASK


def test_migration_hysteresis():
    """Fair-share migrations fire only below half share: servers hovering
    near their share must not shuffle inventory (a GIL/message tax on
    already-balanced compute-bound workloads), while a starved server
    still gets supplied immediately."""
    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=16, max_requesters=4)
    # near-balanced: 5 vs 4 with equal consumers -> no moves
    snaps = {
        10: {"tasks": [(i, T1, 1, 8) for i in range(5)], "reqs": [],
             "consumers": 1},
        11: {"tasks": [(i, T1, 1, 8) for i in range(4)], "reqs": [],
             "consumers": 1},
    }
    _, migs = eng.round(snaps, None)
    assert migs == []
    # starved: 8 vs 0 -> the empty server is under half share and is
    # supplied ahead of demand (anticipatory pre-positioning; the
    # round-4 experiment of gating this on recent parking was reverted —
    # see engine._plan_migrations)
    eng2 = PlanEngine(types=(T1,), max_tasks=16, max_requesters=4)
    snaps2 = {
        10: {"tasks": [(i, T1, 1, 8) for i in range(8)], "reqs": [],
             "consumers": 1},
        11: {"tasks": [], "reqs": [], "consumers": 1},
    }
    _, migs2 = eng2.round(snaps2, None)
    assert migs2 and migs2[0][0] == 10 and migs2[0][1] == 11


def test_hungry_gates_put_snapshots(monkeypatch):
    """A world whose cross-rank traffic is all TARGETED (gfmc's collector
    shape: answers only ever arrive as targeted puts) must not pay an
    event snapshot per put — only the parked-reserve events plus the slow
    idle heartbeat remain."""
    from adlb_tpu.runtime import server as srv

    calls = {"n": 0}
    orig = srv.Server._send_snapshot

    def counting(self, reqs_only=False):
        calls["n"] += 1
        orig(self, reqs_only=reqs_only)

    monkeypatch.setattr(srv.Server, "_send_snapshot", counting)
    NTASK = 300

    def app(ctx):
        import time as _t

        if ctx.rank == 0:
            for i in range(NTASK):
                # targeted straight at rank 1: matches at its home server,
                # never enters a balancer snapshot
                assert (
                    ctx.put(str(i).encode(), T1, work_prio=1, target_rank=1)
                    == ADLB_SUCCESS
                )
            rc, r = ctx.reserve([T2])  # consumer's all-done ack
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            ctx.set_problem_done()
            return 0
        # let the producer run ahead so consuming never parks (each park
        # legitimately sends an ungated event snapshot, like steal's RFR)
        _t.sleep(0.5)
        n = 0
        for _ in range(NTASK):
            rc, r = ctx.reserve([T1])
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            n += 1
        ctx.put(b"done", T2, target_rank=0)
        rc, _ = ctx.reserve([T1])  # parks until NO_MORE_WORK
        assert rc != ADLB_SUCCESS
        return n

    res = run_world(
        2, 2, [T1, T2], app,
        cfg=Config(balancer="tpu", balancer_max_tasks=64,
                   balancer_max_requesters=16),
        timeout=300.0,
    )
    assert res.app_results[1] == NTASK
    # ungated, this would be >= NTASK/2 (150) snapshots — one per couple
    # of puts; gated it is a few parks + the slow idle heartbeat. The
    # heartbeat count scales with wall-clock, and under host load the
    # world runs 2-3x longer (measured: the old < 40 bound sat exactly
    # at the boundary ~half the time on a busy host, at this PR's base
    # commit too) — 60 keeps the full gated/ungated discrimination
    # without the load sensitivity.
    assert calls["n"] < 60, calls["n"]


def test_hungry_tracker_drop_arms_shrink():
    """An ended source's parked types must stop being 'hungry' after the
    grace period even if no further snapshots arrive (DS_END path)."""
    from adlb_tpu.balancer.hungry import HungryTracker

    tr = HungryTracker(shrink_grace=0.0)
    out = tr.update(10, [(0, 1, [T1])])
    assert out is not None and out[0] is True and out[1] == [T1]
    tr.drop(10)
    import time as _t

    flushed = tr.flush(_t.monotonic() + 1.0)
    assert flushed is not None
    hungry, req_types, grew = flushed
    assert hungry is False and not grew


def test_solve_gated_when_supply_is_local_only():
    """A parked requester whose wanted type has supply only on its OWN
    server must not trigger the global solve: the data plane's immediate
    local matching covers it, and the solve's same-server pairs are
    dropped anyway. Cross-server supply must still solve."""
    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=16, max_requesters=4)
    calls = []
    inner = eng.solver.solve
    eng.solver.solve = lambda *a, **k: (calls.append(1), inner(*a, **k))[1]
    local_only = {
        10: {"tasks": [(1, T1, 5, 8)], "reqs": [(0, 1, [T1])],
             "consumers": 1},
    }
    matches, _ = eng.round(local_only, None)
    assert matches == [] and calls == []
    cross = {
        10: {"tasks": [(1, T1, 5, 8)], "reqs": [], "consumers": 1},
        11: {"tasks": [], "reqs": [(0, 1, [T1])], "consumers": 1},
    }
    matches, _ = eng.round(cross, None)
    assert calls and matches == [(10, 1, 11, 0, 1)]


def test_migration_inflow_credited_until_fresh_snapshot():
    """Units planned toward a destination count as its inventory until the
    destination ships a FRESH task snapshot — otherwise every round chains
    another phantom top-up to a server that is already being fed."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=64, max_requesters=4)
    # the transit window and TTL compare against real wall-clock; pin
    # them so a CI scheduler pause between rounds cannot expire the
    # credit mid-test
    eng.INFLOW_MIN_AGE = 1e9
    eng.INFLOW_TTL = 1e9
    eng.PUMP_INTERVAL = 0.0  # credit semantics under test, not pacing
    t0 = _time.monotonic()
    snaps = {
        10: {"tasks": [(i, T1, 1, 8) for i in range(40)], "reqs": [],
             "consumers": 1, "stamp": t0, "task_stamp": t0},
        11: {"tasks": [], "reqs": [], "consumers": 1, "stamp": t0,
             "task_stamp": t0},
    }
    _, migs = eng.round(snaps, None)
    assert migs, "starved server must be supplied"
    # same stale snapshots again: the in-flight batch covers 11's need
    _, migs2 = eng.round(snaps, None)
    assert migs2 == []
    # a fresh-but-instant snapshot (captured before the batch could have
    # LANDED) must not wipe the credit either
    t1 = _time.monotonic()
    snaps[11] = {"tasks": [], "reqs": [], "consumers": 1, "stamp": t1,
                 "task_stamp": t1}
    snaps[10] = dict(snaps[10], stamp=t1, task_stamp=t1)
    _, migs2b = eng.round(snaps, None)
    assert migs2b == []
    # past the transit window, a fresh drained snapshot clears the credit
    # -> supply again (pin the window instead of sleeping through it)
    eng.INFLOW_MIN_AGE = 0.0
    t2 = _time.monotonic()
    snaps[11] = {"tasks": [], "reqs": [], "consumers": 1, "stamp": t2,
                 "task_stamp": t2}
    snaps[10] = dict(snaps[10], stamp=t2, task_stamp=t2)
    _, migs3 = eng.round(snaps, None)
    assert migs3


def test_migration_window_grows_on_fast_drain():
    """A destination that keeps draining its top-ups faster than the
    re-plan round trip gets a doubling transfer window, so batch sizes
    converge on the drain rate instead of trickling fixed-size refills
    (batches are O(1) messages regardless of size)."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=512, max_requesters=4)
    # the growth criterion is "re-triggered within the window"; pin it so
    # a slow CI machine cannot flip growth into decay mid-test, and drop
    # the in-flight transit crediting (tested elsewhere) so each fresh
    # snapshot re-triggers immediately
    sizes = _run_four_topups(eng, dest_parked=True)
    assert sizes[-1] > sizes[0], sizes
    assert sizes == sorted(sizes), sizes


def _run_four_topups(eng, dest_parked: bool):
    """Four quick pump rounds against a deep source and a dest holding a
    couple of units (fully empty would hit the starved full-share path).
    ``dest_parked`` controls whether the dest has a parked requester —
    window growth is reserved for destinations whose workers actually
    outpace their supply. Returns the per-round shipped batch sizes."""
    import time as _time

    eng.LOOK_GROW_WINDOW = 1e9
    eng.INFLOW_MIN_AGE = 0.0
    eng.PUMP_INTERVAL = 0.0  # window mechanics under test, not pacing
    sizes = []
    for i in range(4):
        t = _time.monotonic()
        snaps = {
            10: {"tasks": [(1000 * i + j, T1, 1, 8) for j in range(400)],
                 "reqs": [], "consumers": 1, "stamp": t, "task_stamp": t},
            11: {"tasks": [(1000 * i + 900 + j, T1, 1, 8) for j in range(2)],
                 "reqs": [(5, i + 1, [T1])] if dest_parked else [],
                 "consumers": 1, "stamp": t, "task_stamp": t},
        }
        _, migs = eng.round(snaps, None)
        if dest_parked:
            assert migs and migs[0][1] == 11
        sizes.append(sum(len(q) for _, _, q, _ in migs))
    return sizes


def test_window_growth_gated_on_recent_parking():
    """A destination fed while its workers never measurably wait keeps
    its window at the floor: bursty-but-balanced pools must not have
    their transfer batches inflated (the round-4 churn bound — the feed
    itself stays on, see engine._plan_migrations). An already-inflated
    window DECAYS under gated triggers instead of staying pinned."""
    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=512, max_requesters=8)
    sizes = _run_four_topups(eng, dest_parked=False)
    assert all(s > 0 for s in sizes), sizes  # still fed (pre-positioning)
    assert eng._window(11) == float(eng.LOOKAHEAD), eng._look
    # parked phase: the window inflates on quick re-triggers
    eng2 = PlanEngine(types=(T1,), max_tasks=512, max_requesters=8)
    _run_four_topups(eng2, dest_parked=True)
    grown = eng2._window(11)
    assert grown > eng2.LOOKAHEAD, eng2._look
    # quiet phase (stale parked stamp): still fed, but the window decays
    eng2.PARK_RECENT = -1.0  # make the last park immediately "old"
    sizes2 = _run_four_topups(eng2, dest_parked=False)
    assert all(s > 0 for s in sizes2), sizes2
    assert eng2._window(11) < grown, eng2._look


def test_starved_destination_gets_full_share_immediately():
    """A destination with a parked requester, zero inventory, and zero
    inflow (hotspot's empty servers) must receive its full fair share in
    ONE batch — not window-sized refills that ramp from the lookahead
    floor while its workers idle a re-plan round trip at a time (the
    round-2 hotspot regression)."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=512, max_requesters=8)
    t = _time.monotonic()
    snaps = {
        10: {"tasks": [(j, T1, 1, 8) for j in range(400)], "reqs": [],
             "consumers": 2, "stamp": t, "task_stamp": t},
        11: {"tasks": [], "reqs": [(5, 1, [T1])], "consumers": 2,
             "stamp": t, "task_stamp": t},
    }
    matches, migs = eng.round(snaps, None)
    shipped = sum(len(q) for _, dest, q, _ in migs if dest == 11)
    # one unit goes via the match; of the remaining 399 the source keeps
    # its own ceil-share (200) and ships the rest. The old window-capped
    # first batch was LOOKAHEAD*consumers = 16.
    assert len(matches) == 1 and shipped == 199, (matches, migs)
    # the window is seeded at the shipped scale: a follow-up deficit tops
    # up at fair-share size instead of re-ramping from the floor
    assert eng._window(11) >= 99, eng._look
    # an empty server whose workers are all mid-compute (no parked
    # requester — tsp's transient dips) stays on the window-capped path
    eng2 = PlanEngine(types=(T1,), max_tasks=512, max_requesters=8)
    snaps2 = {
        10: {"tasks": [(j, T1, 1, 8) for j in range(400)], "reqs": [],
             "consumers": 2, "stamp": t, "task_stamp": t},
        11: {"tasks": [], "reqs": [], "consumers": 2, "stamp": t,
             "task_stamp": t},
    }
    _, migs2 = eng2.round(snaps2, None)
    shipped2 = sum(len(q) for _, dest, q, _ in migs2 if dest == 11)
    assert 0 < shipped2 <= eng2.LOOKAHEAD * 2, migs2


def test_migration_spares_locally_demanded_unit():
    """With the solve gated off (supply local-only), migration planning
    must not ship away the unit a locally parked requester wants."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1, T2), max_tasks=16, max_requesters=4)
    t0 = _time.monotonic()
    snaps = {
        10: {"tasks": [(1, T1, 5, 8), (2, T1, 4, 8), (3, T2, 3, 8)],
             "reqs": [(0, 1, [T2])], "consumers": 1, "stamp": t0,
             "task_stamp": t0},
        11: {"tasks": [], "reqs": [], "consumers": 1, "stamp": t0,
             "task_stamp": t0},
    }
    matches, migs = eng.round(snaps, None)
    assert matches == []  # T2 supply is local to its demander: no solve
    moved = {q for _, _, qs, _ in migs for q in qs}
    assert 3 not in moved, (matches, migs)


def test_pump_knobs_config_wiring():
    """The adaptive-pump constants are per-instance Config knobs, not just
    class constants."""
    import pytest

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=16, max_requesters=4,
                     lookahead=3, look_max=64, grow_window=0.5,
                     inflow_ttl=9.0, inflow_min_age=0.2)
    assert (eng.LOOKAHEAD, eng.LOOK_MAX, eng.LOOK_GROW_WINDOW,
            eng.INFLOW_TTL, eng.INFLOW_MIN_AGE) == (3, 64, 0.5, 9.0, 0.2)
    # class defaults untouched
    assert PlanEngine.LOOKAHEAD == 8
    with pytest.raises(ValueError):
        Config(balancer_lookahead=-1)
    # look_max below the lookahead floor would let window decay pin a
    # destination's need to 0, silently disabling migrations to it
    with pytest.raises(ValueError):
        Config(balancer_look_max=0)
    with pytest.raises(ValueError):
        Config(balancer_lookahead=16, balancer_look_max=4)
    with pytest.raises(ValueError):
        PlanEngine(types=(T1,), max_tasks=16, max_requesters=4,
                   lookahead=16, look_max=4)


def test_matched_requester_not_double_withheld():
    """A requester the solve matched cross-server this round is consumed
    by the match; withholding a second local unit for it would
    double-reserve supply and starve migration sources."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    t0 = _time.monotonic()
    snaps = {
        10: {"tasks": [(1, T1, 1, 8), (2, T1, 1, 8)],
             "reqs": [(5, 1, [T1])], "consumers": 0, "stamp": t0,
             "task_stamp": t0},
        11: {"tasks": [], "reqs": [], "consumers": 1, "stamp": t0,
             "task_stamp": t0},
    }
    filtered = {
        r: {"tasks": s["tasks"], "reqs": s["reqs"]} for r, s in snaps.items()
    }
    # requester (10, 5, 1) was matched cross-server this round: both units
    # stay eligible for the starved dest
    eng = PlanEngine(types=(T1,), max_tasks=64, max_requesters=8)
    migs = eng._plan_migrations(snaps, filtered, {}, t0,
                                matched_reqs={(10, 5, 1)})
    moved = {q for _, _, qs, _ in migs for q in qs}
    assert moved == {1, 2}, migs
    # unmatched, the requester still protects one locally-matchable unit
    eng2 = PlanEngine(types=(T1,), max_tasks=64, max_requesters=8)
    migs2 = eng2._plan_migrations(snaps, filtered, {}, t0)
    moved2 = {q for _, _, qs, _ in migs2 for q in qs}
    assert len(moved2) == 1, migs2
    # LOCAL pairs (dropped from matches, unit in planned_away) consume
    # their requester too: withholding a second unit for it would starve
    # the migration path end-to-end through round()
    eng3 = PlanEngine(types=(T1,), max_tasks=64, max_requesters=8)
    snaps3 = {
        10: {"tasks": [(1, T1, 5, 8), (2, T1, 4, 8), (3, T1, 3, 8)],
             "reqs": [(9, 7, [T1])], "consumers": 0, "stamp": t0,
             "task_stamp": t0},
        11: {"tasks": [], "reqs": [(5, 1, [T1])], "consumers": 0,
             "stamp": t0, "task_stamp": t0},
        12: {"tasks": [], "reqs": [], "consumers": 1, "stamp": t0,
             "task_stamp": t0},
    }
    matches3, migs3 = eng3.round(snaps3, None)
    # one local pair (dropped) + one cross match leave exactly one unit;
    # it must reach the starved consumer on 12, not be double-withheld
    assert len(matches3) == 1 and matches3[0][2] == 11, matches3
    moved3 = {q for _, _, qs, _ in migs3 for q in qs}
    assert moved3, (matches3, migs3)


def test_pump_precheck_admits_rank_with_only_planned_away_inventory():
    """ADVICE r4: a req-parked destination whose stale snapshot still
    lists units the plan ledger already moved away must ADMIT the
    scarce+concentrated pump pre-check — its raw count is nonzero but it
    is starved NOW. Before the fix the pump stayed gated a whole
    snapshot generation after the opening burst was planned out."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    eng = PlanEngine(types=(T1,), max_tasks=64, max_requesters=8)
    t0 = _time.monotonic()
    snaps = {
        # 4 units < 5 consumers (scarce), 3 of 4 on rank 10 (concentrated)
        10: {"tasks": [(j, T1, 1, 8) for j in range(3)],
             "reqs": [], "consumers": 3, "stamp": t0, "task_stamp": t0},
        # rank 11: one consumer parked; its snapshot still lists unit 99
        # but the ledger says 99 was planned away AFTER this task view
        11: {"tasks": [(99, T1, 1, 8)], "reqs": [(5, 1, [T1])],
             "consumers": 2, "stamp": t0, "task_stamp": t0},
    }
    eng._planned_tasks[(11, 99)] = t0 + 1.0  # planned after the view
    assert eng._maybe_imbalanced(snaps), (
        "pre-check must admit: rank 11 is req-parked and every listed "
        "unit is planned away"
    )
    # sanity: with the unit genuinely eligible (ledger older than the
    # view) the same shape is NOT admitted via the planned-away clause
    eng2 = PlanEngine(types=(T1,), max_tasks=64, max_requesters=8)
    eng2._planned_tasks[(11, 99)] = t0 - 1.0
    assert not eng2._maybe_imbalanced(snaps)


def test_fully_stale_migration_batch_still_clears_credit(monkeypatch):
    """Round-4 regression: a planner migration whose every unit is stale
    at enactment must STILL result in the destination acking the batch
    id, clearing the planner's in-flight credit. Before the fix the
    source silently dropped such batches and the phantom credit made the
    destination look fed (solve suppressed + pump skipped) until the
    TTLs expired — whole worker pools parked ~180 ms mid-run.

    The TTL and stamp fallbacks are pinned OFF so only the exact
    ack-clearing path can clear the forged credit."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine

    monkeypatch.setattr(PlanEngine, "INFLOW_TTL", 1e9)
    monkeypatch.setattr(PlanEngine, "INFLOW_MIN_AGE", 1e9)

    holder = {}
    orig = PlanEngine.round

    def forging(self, snapshots, world=None):
        holder["eng"] = self
        matches, migs = orig(self, snapshots, world)
        servers = sorted(snapshots)
        if not holder.get("forged") and len(servers) >= 2:
            src, dest = servers[0], servers[1]
            mid = self._mig_next
            self._mig_next += 1
            # credit exactly as _plan_migrations would record it
            self._planned_in.setdefault(dest, []).append(
                (_time.monotonic(), 5, mid, src, frozenset({T1}))
            )
            migs = list(migs) + [(src, dest, [987654321], mid)]
            holder["forged"] = dest
        return matches, migs

    monkeypatch.setattr(PlanEngine, "round", forging)

    def app(ctx):
        deadline = _time.monotonic() + 8.0
        ok = False
        while _time.monotonic() < deadline:
            eng = holder.get("eng")
            dest = holder.get("forged")
            if dest is not None and eng is not None:
                live = eng._planned_in.get(dest)
                if not live:
                    ok = True  # ack arrived; credit cleared exactly
                    break
            _time.sleep(0.05)
        if ctx.rank == 0:
            ctx.set_problem_done()
        return ok

    res = run_world(
        2, 2, [T1], app,
        cfg=Config(balancer="tpu", balancer_max_tasks=16,
                   balancer_max_requesters=4),
        timeout=60.0,
    )
    assert res.app_results[0] or res.app_results[1], (
        "forged fully-stale migration credit was never cleared by the "
        "destination's ack"
    )


def test_sidecar_survives_dead_destination():
    """End-of-world race: a server closes its listener before the sidecar
    finishes broadcasting/planning to it. The sidecar must mark the
    destination ended and drain out — not die with an unhandled thread
    exception (observed as BrokenPipe->ConnectionRefused tracebacks in
    bench teardown)."""
    from adlb_tpu.balancer.sidecar import run_sidecar
    from adlb_tpu.runtime.messages import Tag, msg

    world = _world(ns=2)
    s0, s1 = world.server_ranks

    class DeadEp:
        """One SS_STATE with a parked requester (forces a HUNGRY
        broadcast), then silence; every send is refused."""

        def __init__(self):
            self.frames = [
                msg(Tag.SS_STATE, s0, tasks_flat=[100, T1, 5, 8],
                    reqs_flat=[0, 1, 1, T1], nbytes=8, consumers=1),
            ]
            self.sends = 0

        def recv(self, timeout=None):
            return self.frames.pop(0) if self.frames else None

        def send(self, dest, m, **kw):
            self.sends += 1
            raise ConnectionRefusedError(111, "refused")

    ep = DeadEp()
    cfg = Config(balancer="tpu", balancer_min_gap=0.0)
    rounds = run_sidecar(world, cfg, ep)  # must return, not raise
    assert ep.sends >= 1  # it really tried the dead destinations
    # the refused broadcast popped the only snapshot, so no solve ran
    assert rounds == 0


def test_sidecar_survives_plan_frame_to_dead_holder():
    """Same teardown race on the PLAN paths: the HUNGRY broadcast goes
    through, the solve plans a match, and THEN the holder's listener is
    gone — the plan-frame send must mark it ended (skipping its other
    plan frames) and drain, not raise."""
    from adlb_tpu.balancer.sidecar import run_sidecar
    from adlb_tpu.runtime.messages import Tag, msg

    world = _world(ns=2)
    s0, s1 = world.server_ranks

    class PlanDeadEp:
        def __init__(self):
            # Batch 1: holder s0 has two units; requester home s1 has two
            # parked requesters -> the solve emits two matches for holder
            # s0 (the None ends the batch so the solve runs). Batch 2:
            # s1 finishes normally via DS_END, letting the loop drain.
            self.script = [
                msg(Tag.SS_STATE, s0,
                    tasks_flat=[100, T1, 5, 8, 101, T1, 4, 8],
                    reqs_flat=[], nbytes=16, consumers=1),
                msg(Tag.SS_STATE, s1, tasks_flat=[],
                    reqs_flat=[0, 1, 1, T1, 1, 2, 1, T1],
                    nbytes=0, consumers=2),
                None,
                msg(Tag.DS_END, s1),
            ]
            self.plan_sends = 0
            self.hungry_sends = 0

        def recv(self, timeout=None):
            return self.script.pop(0) if self.script else None

        def send(self, dest, m, **kw):
            if m.tag is Tag.SS_PLAN_MATCH or m.tag is Tag.SS_PLAN_MIGRATE:
                self.plan_sends += 1
                raise ConnectionRefusedError(111, "refused")
            self.hungry_sends += 1  # HUNGRY broadcasts still deliver

        def close(self):
            pass

    ep = PlanDeadEp()
    cfg = Config(balancer="tpu", balancer_min_gap=0.0)
    rounds = run_sidecar(world, cfg, ep)  # must return, not raise
    assert rounds >= 1  # the solve really ran
    assert ep.hungry_sends >= 1
    # first plan frame to the dead holder ends it; its second match is
    # skipped rather than re-attempted
    assert ep.plan_sends == 1, ep.plan_sends
