"""Rank-failure detection over TCP: the reference's model is any rank
failure kills the job (MPI_Abort paths, reference src/adlb.c:2508-2526).
A TCP world must not do worse — a SIGKILLed app used to hang everyone
until the harness timeout; now the home server sees the connection EOF
before LOCAL_APP_DONE and aborts the world."""

import os
import struct
import time

import pytest

from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS


def _app_with_casualty(ctx):
    T = 1
    if ctx.rank == 0:
        for i in range(10):
            ctx.put(struct.pack("<q", i), T)
        # rank 0 keeps producing slowly so the world is mid-flight
        time.sleep(0.2)
    if ctx.rank == 1:
        # die mid-protocol (after real traffic, so connections exist —
        # EOF detection is connection-based; a rank that dies before ever
        # contacting a server is only caught by the harness timeout)
        rc, r = ctx.reserve([T])
        assert rc == ADLB_SUCCESS
        ctx.get_reserved(r.handle)
        os._exit(1)  # simulated crash: no finalize, no goodbye
    n = 0
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return n
        ctx.get_reserved(r.handle)
        time.sleep(0.02)
        n += 1


@pytest.mark.parametrize("server_impl", ["python", "native"])
def test_dead_app_aborts_world_quickly(server_impl):
    t0 = time.monotonic()
    with pytest.raises(RuntimeError):
        # the dying rank reports nothing; the EOF-driven abort tears the
        # rest down well before the 60s harness timeout
        spawn_world(
            3, 2, [1], _app_with_casualty,
            cfg=Config(server_impl=server_impl,
                       exhaust_check_interval=10.0),  # exhaustion can't save it
            timeout=60.0,
        )
    elapsed = time.monotonic() - t0
    assert elapsed < 30.0, f"failure detection took {elapsed:.1f}s"
