"""Known-answer workload integration tests (the reference's test strategy:
self-checking mini-apps, SURVEY §4), in both balancer modes."""

import pytest

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads import batcher, coinop, gfmc, nq, sudoku, tsp


STEAL = None  # default Config
TPU = Config(
    balancer="tpu", balancer_max_tasks=64, balancer_max_requesters=16,
    exhaust_check_interval=0.15,
)


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_nq_known_answer(mode):
    cfg = None if mode == "steal" else TPU
    res = nq.run(n=6, num_app_ranks=3, nservers=2, cfg=cfg)
    assert res.solutions == nq.KNOWN_SOLUTIONS[6]
    assert res.tasks_processed > 0


def test_nq_deeper_cutoff():
    res = nq.run(n=7, num_app_ranks=4, nservers=2, max_depth_for_puts=3)
    assert res.solutions == nq.KNOWN_SOLUTIONS[7]


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_tsp_optimal(mode):
    cfg = None if mode == "steal" else TPU
    n = 8
    dists = tsp.dist_matrix(tsp.make_cities(n, seed=3))
    want = tsp.brute_force_optimum(dists)
    res = tsp.run(n_cities=n, num_app_ranks=3, nservers=2, seed=3, cfg=cfg)
    assert res.best == want


def test_sudoku_solves():
    res = sudoku.run(num_app_ranks=3, nservers=2)
    assert res.valid, "sudoku solution missing or invalid"


def test_batcher_parallel_speedup():
    durations = [0.2, 0.1, 0.1, 0.05, 0.05, 0.05, 0.05, 0.1, 0.1]  # 0.8s serial
    res = batcher.run(durations, num_app_ranks=4, nservers=1)
    assert sum(res.jobs_run.values()) == len(durations)
    # 3 workers on 0.8s of work: generous bound still proves parallelism
    assert res.elapsed < 0.75 * res.serial_time, (
        f"elapsed {res.elapsed:.2f}s vs serial {res.serial_time:.2f}s"
    )


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_gfmc_economy_self_check(mode):
    cfg = None if mode == "steal" else TPU
    res = gfmc.run(num_a=4, bs_per_a=3, cs_per_b=2,
                   num_app_ranks=4, nservers=2, cfg=cfg)
    assert res.ok, f"counts {res.counts} != expected {res.expected}"


def test_coinop_latency_probe():
    res = coinop.run(n_tokens=200, num_app_ranks=4, nservers=2)
    assert res.pops == 200
    assert res.latency_p50_ms > 0
    assert res.per_worker  # every reporting worker has stats
