"""The fleet controller (adlb_tpu/control/): closed-loop sense→decide→act.

Coverage layers:

* **Policy gate** — ``parse_policy`` defaults, validation, and the
  POST /control merge semantics (unknown keys and bad values 400).
* **Decision rules** — each rule as a pure function of ``(now,
  inputs)``: mem_pressure / slo_firing scale-out, tenant_hog throttle
  with the pressure_recovered release, fleet_idle scale-in, min/max
  server rails.
* **Hysteresis** — a flapping signal produces at most ONE action per
  cooldown window; scale_out/scale_in share a cooldown key (no
  out-then-in bounce); an epoch bump freezes actions for the churn
  grace; dry-run records and paces but acts nothing.
* **History discipline** — a rule stuck in the same suppressed outcome
  is recorded once, not every tick.
* **Frame identity** — an unconfigured world (`control=False`)
  constructs no Controller and mints no controller metrics;
  GET /control answers ``enabled: false``.
* **End-to-end** — an ElasticWorld under real memory pressure: the
  controller requests the scale-out, the shard joins through the
  membership plane with ``failover_lost == 0``, the decision surfaces
  at GET /control as ``enacted``, and POST /control live-tweaks the
  policy.
"""

import json
import struct
import time
import urllib.error
import urllib.request

import pytest

from adlb_tpu.control import Controller, parse_policy
from adlb_tpu.control.controller import (
    ACT,
    BOUNDED,
    COOLDOWN,
    DRY_RUN,
    HELD,
)
from adlb_tpu.runtime.membership import ElasticWorld
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

T = 1


# ------------------------------------------------------------ policy gate


def test_parse_policy_defaults():
    pol = parse_policy({})
    assert pol == {
        "dry_run": False, "min_servers": 1, "max_servers": 0,
        "cooldown_s": 10.0, "scaleout_pressure": 0.85,
        "scalein_pressure": 0.30, "throttle_frac": 0.5,
    }


@pytest.mark.parametrize("bad", [
    {"nope": 1},
    {"min_servers": 0},
    {"max_servers": -1},
    {"min_servers": 3, "max_servers": 2},
    {"cooldown_s": -1},
    {"scaleout_pressure": 0.0},
    {"scaleout_pressure": 1.5},
    {"scalein_pressure": 0.9},      # >= scaleout default
    {"throttle_frac": 0.0},
    "not-a-dict",
])
def test_parse_policy_rejects(bad):
    with pytest.raises(ValueError):
        parse_policy(bad)


def test_update_policy_merges_and_swaps():
    ctl = Controller({"cooldown_s": 5.0}, now=0.0)
    old = ctl.policy
    pol = ctl.update_policy({"dry_run": True})
    assert pol["dry_run"] is True and pol["cooldown_s"] == 5.0
    assert ctl.policy is not old  # swap-published, never mutated
    with pytest.raises(ValueError):
        ctl.update_policy({"bogus": 1})
    assert ctl.policy["dry_run"] is True  # rejected tweak changed nothing


# ------------------------------------------------------- decision rules


def _frame(**kw):
    base = {
        "live_servers": 3, "pressure": {}, "firing": 0, "jobs": {},
        "backoffs": 0, "oldest_lease_s": 0.0, "epoch": 0,
    }
    base.update(kw)
    return base


def _ctl(**policy):
    policy.setdefault("cooldown_s", 10.0)
    return Controller(policy, eval_interval=1.0, now=0.0)


def test_mem_pressure_scale_out_names_hot_rank():
    ctl = _ctl()
    out = ctl.evaluate(1.0, _frame(pressure={4: 0.2, 5: 0.91}))
    assert len(out) == 1
    d = out[0]
    assert d["rule"] == "mem_pressure"
    assert d["action"] == {"kind": "scale_out", "hot_rank": 5}
    assert d["outcome"] == ACT
    assert d["inputs"]["worst_pressure"] == 0.91


def test_slo_firing_scale_out_needs_backlog():
    ctl = _ctl()
    # firing without backlog: nothing to scale for
    assert ctl.evaluate(1.0, _frame(firing=1)) == []
    out = ctl.evaluate(2.0, _frame(
        firing=1, jobs={1: {"depth": 7, "bytes": 10}},
    ))
    assert [d["rule"] for d in out] == ["slo_firing"]
    assert out[0]["action"]["kind"] == "scale_out"
    assert out[0]["outcome"] == ACT


def test_max_servers_rail_bounds_scale_out():
    ctl = _ctl(max_servers=3)
    out = ctl.evaluate(1.0, _frame(
        live_servers=3, pressure={4: 0.95},
    ))
    assert out[0]["outcome"] == BOUNDED
    assert out[0]["bound"] == "max_servers"
    # a bounded decision stamps NO cooldown: raising the rail frees the
    # rule immediately
    ctl.update_policy({"max_servers": 4})
    out = ctl.evaluate(2.0, _frame(live_servers=3, pressure={4: 0.95}))
    assert out[0]["outcome"] == ACT


def test_fleet_idle_scale_in_floor():
    ctl = _ctl(min_servers=1)
    # at the drain-safety floor of 2 the rule does not trigger at all
    assert ctl.evaluate(1.0, _frame(live_servers=2)) == []
    out = ctl.evaluate(2.0, _frame(live_servers=3))
    assert [d["rule"] for d in out] == ["fleet_idle"]
    assert out[0]["action"] == {"kind": "scale_in"}
    assert out[0]["outcome"] == ACT
    # min_servers above the floor is respected too
    ctl2 = _ctl(min_servers=4)
    assert ctl2.evaluate(1.0, _frame(live_servers=4)) == []


def test_tenant_hog_throttle_then_pressure_recovered():
    ctl = _ctl()
    jobs = {
        1: {"depth": 9, "bytes": 800, "quota_bytes": 0,
            "state": "running"},
        2: {"depth": 1, "bytes": 100, "quota_bytes": 0,
            "state": "running"},
    }
    out = ctl.evaluate(1.0, _frame(pressure={4: 0.9}, jobs=jobs))
    rules = {d["rule"]: d for d in out}
    # mem_pressure fires too (separate cooldown key); the hog throttle
    # caps job 1 at its current footprint
    assert set(rules) == {"mem_pressure", "tenant_hog"}
    th = rules["tenant_hog"]
    assert th["action"] == {"kind": "throttle", "job": 1,
                            "quota_bytes": 800}
    assert th["outcome"] == ACT
    # pressure recedes: the tenant is released; pre-throttle quota 0
    # (unlimited) restores as -1, the update op's "unlimited" encoding
    out = ctl.evaluate(30.0, _frame(pressure={4: 0.1}, jobs=jobs))
    rec = [d for d in out if d["rule"] == "pressure_recovered"]
    assert rec and rec[0]["action"] == {
        "kind": "unthrottle", "job": 1, "quota_bytes": -1,
    }
    assert rec[0]["outcome"] == ACT


def test_tenant_hog_skips_quotad_and_default_jobs():
    ctl = _ctl()
    jobs = {
        0: {"depth": 1, "bytes": 900, "quota_bytes": 0,
            "state": "running"},          # default namespace: never
        1: {"depth": 1, "bytes": 80, "quota_bytes": 64,
            "state": "running"},          # already quota'd: never
    }
    out = ctl.evaluate(1.0, _frame(pressure={4: 0.9}, jobs=jobs))
    assert [d["rule"] for d in out] == ["mem_pressure"]


# ---------------------------------------------------------- hysteresis


def test_flapping_pressure_one_action_per_cooldown_window():
    """Pressure oscillating across the threshold every tick: the acts
    the controller emits are spaced >= cooldown_s apart — at most one
    per window."""
    ctl = _ctl(cooldown_s=10.0)
    acts = []
    for i in range(31):
        now = float(i)
        p = 0.95 if i % 2 == 0 else 0.05
        for d in ctl.evaluate(now, _frame(pressure={4: p})):
            if d["outcome"] == ACT:
                acts.append(now)
    assert len(acts) <= 4  # 31 s of flapping, 10 s windows
    assert all(b - a >= 10.0 for a, b in zip(acts, acts[1:]))


def test_scale_out_and_in_share_one_cooldown_key():
    """After a scale-out act, a fleet-idle scale-in inside the window is
    refused by the SHARED cooldown — the controller can never bounce a
    shard out and straight back in."""
    ctl = _ctl(cooldown_s=10.0)
    out = ctl.evaluate(1.0, _frame(pressure={4: 0.95}))
    assert out[0]["outcome"] == ACT
    out = ctl.evaluate(2.0, _frame(live_servers=4, pressure={4: 0.05}))
    assert [d["rule"] for d in out] == ["fleet_idle"]
    assert out[0]["outcome"] == COOLDOWN


def test_epoch_churn_hold_freezes_actions():
    ctl = _ctl()
    # mid-band pressure: no rule triggers, the epoch is just noted
    ctl.evaluate(1.0, _frame(epoch=0, pressure={4: 0.5}))
    # epoch bump: hold = max(4 * eval_interval, 2.0) = 4 s
    out = ctl.evaluate(2.0, _frame(epoch=1, pressure={4: 0.95}))
    assert out[0]["outcome"] == HELD
    out = ctl.evaluate(3.0, _frame(epoch=1, pressure={4: 0.95}))
    assert out == []  # same suppressed outcome: recorded once
    out = ctl.evaluate(6.5, _frame(epoch=1, pressure={4: 0.95}))
    assert out[0]["outcome"] == ACT


def test_dry_run_paces_but_acts_nothing():
    ctl = _ctl(dry_run=True, cooldown_s=10.0)
    out = ctl.evaluate(1.0, _frame(pressure={4: 0.95}))
    assert out[0]["outcome"] == DRY_RUN
    assert ctl.actions_total == 0
    # the would-act stamped its cooldown: the stream paces like live
    out = ctl.evaluate(2.0, _frame(pressure={4: 0.95}))
    assert out[0]["outcome"] == COOLDOWN
    assert ctl.actions_total == 0


def test_history_dedup_and_bound():
    ctl = _ctl(max_servers=2)
    for i in range(50):
        ctl.evaluate(float(i), _frame(live_servers=2,
                                      pressure={4: 0.95}))
    bounded = [d for d in ctl.history if d["outcome"] == BOUNDED]
    assert len(bounded) == 1  # stuck outcome recorded once
    assert ctl.history.maxlen == 256


def test_publish_swaps_status():
    ctl = _ctl()
    frame = _frame(live_servers=3, pressure={4: 0.4}, backoffs=7)
    ctl.evaluate(1.0, frame)
    ctl.publish(1.0, frame)
    st = ctl.status_pub
    assert st["live_servers"] == 3
    assert st["worst_pressure"] == 0.4
    assert st["backoffs"] == 7
    assert st["held"] is False


# ------------------------------------------------- world-level plumbing


def _wait(pred, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(tick)
    return None


def _get(port, route):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{route}", timeout=10).read().decode())


def _post(port, route, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{route}",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10)
                      .read().decode())


def _consume(ctx, pace=0.002):
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(w.payload)
        if pace:
            time.sleep(pace)


def test_unconfigured_world_frame_identity():
    """control=False (the default): no Controller object, no controller
    metrics, and GET /control answers enabled=false — frame-identical
    to a pre-controller build."""
    cfg = Config(exhaust_check_interval=0.2, ops_port=0,
                 obs_sync_interval=0.1)
    ew = ElasticWorld(1, 2, [T], cfg=cfg)

    def app(ctx):
        for i in range(4):
            ctx.put(struct.pack("<q", i), T)
        return _consume(ctx)

    ew.run_app(0, app)
    try:
        master = ew.master
        assert master._controller is None
        assert _wait(lambda: master.ops is not None)
        doc = _get(master.ops.port, "control")
        assert doc["enabled"] is False
        assert doc["decisions"] == []
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(master.ops.port, "control", {"dry_run": True})
        assert ei.value.code == 400  # controller not configured
        snap = master.metrics.snapshot()
        assert not any("control" in k for k in snap["counters"])
    finally:
        ew.finish(timeout=60)


def test_config_gate():
    with pytest.raises(ValueError, match="obs_sync_interval"):
        Config(control=True, ops_port=0, obs_sync_interval=0.0)
    with pytest.raises(ValueError, match="python"):
        Config(control=True, ops_port=0, obs_sync_interval=0.1,
               server_impl="native")


def test_e2e_controller_scaleout_zero_loss(tmp_path):
    """Real memory pressure drives the mem_pressure rule end to end:
    the controller requests the scale-out, the ElasticWorld spawner
    services it through the membership plane, the decision surfaces at
    GET /control as ``enacted`` with the action counter minted, the
    join's epoch bump self-holds the controller, and the rebalance
    counts failover_lost == 0. POST /control then live-flips dry_run."""
    cap = 256 * 1024
    cfg = Config(
        exhaust_check_interval=0.2, ops_port=0, obs_sync_interval=0.1,
        control=True, control_cooldown_s=5.0,
        control_scaleout_pressure=0.25, control_scalein_pressure=0.05,
        control_min_servers=2,
        max_malloc_per_server=cap, flight_dir=str(tmp_path),
    )
    ew = ElasticWorld(2, 2, [T], cfg=cfg)
    import threading
    drain = threading.Event()

    def producer(ctx):
        # ~160 KB split across two 256 KB servers: per-server pressure
        # crosses 0.25 while staying under the 0.95 spill watermark
        for i in range(20):
            ctx.put(struct.pack("<q", i) + b"p" * 8192, T)
        ctx._c.flush_puts()
        drain.wait(60)
        return _consume(ctx)

    def consumer(ctx):
        drain.wait(60)
        return _consume(ctx)

    ew.run_app(0, producer)
    ew.run_app(1, consumer)
    try:
        master = ew.master
        assert master._controller is not None
        # the controller saw the pressure and the spawner serviced it
        assert _wait(lambda: len(ew.servers) == 3, timeout=30.0), \
            "controller never scaled out"
        assert master.metrics.value(
            "control_actions", kind="scale_out") >= 1
        assert master._controller.actions_total >= 1
        assert _wait(lambda: master.ops is not None)
        port = master.ops.port
        doc = _get(port, "control")
        assert doc["enabled"] is True
        enacted = [d for d in doc["decisions"]
                   if d["rule"] == "mem_pressure"
                   and d["outcome"] == "enacted"]
        assert enacted, doc["decisions"]
        assert enacted[0]["action"]["kind"] == "scale_out"
        # the join bumped the epoch: the controller noted the churn at
        # its next tick
        assert _wait(
            lambda: master._controller._epoch == master.world.epoch
        )
        # live policy tweak over POST /control
        out = _post(port, "control", {"dry_run": True})
        assert out["policy"]["dry_run"] is True
        assert master._controller.dry_run is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, "control", {"scaleout_pressure": 7})
        assert ei.value.code == 400
    finally:
        drain.set()
        results = ew.finish(timeout=90)
    # zero-loss bar: nothing the rebalance shipped was lost
    assert sum(
        s.metrics.value("failover_lost") for s in ew.servers.values()
    ) == 0
    got = sorted(
        struct.unpack("<q", p[:8])[0]
        for v in results.values() if v for p in v
    )
    assert got == list(range(20))
