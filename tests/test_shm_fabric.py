"""The shared-memory ring fabric (adlb_tpu/runtime/transport_shm.py).

Four layers of coverage:

* **Ring mechanics** — SPSC byte ring wraparound, streaming of frames
  larger than the ring, occupancy accounting.
* **Endpoint pair** — two ShmEndpoints in one process: pair upgrade via
  the doorbell probe + SHM_HELLO, TLV and pickle bodies, metrics, and
  the cross-channel EOF ordering fix (final ring frames must beat the
  TCP-carried PEER_EOF).
* **Fault-injection parity** — the seeded FaultPlan produces
  byte-identical injected-event logs over all THREE fabrics (in-proc
  queues, TCP, shm rings): decisions are a pure function of
  (seed, rank, frame), never of transport.
* **World acceptance** — spawn_world worlds with ``fabric="shm"``:
  clean completion (incl. a >ring-size payload), and a worker SIGKILLed
  mid-ring under ``on_worker_failure="reclaim"`` with leases reclaimed
  and the world completing around the casualty.
"""

import os
import signal
import struct
import time

import pytest

from adlb_tpu.runtime.faults import FaultPlan, FaultyEndpoint
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_shm import (
    ShmEndpoint,
    ShmRing,
    cleanup_world,
    new_world_key,
    shm_available,
)
from adlb_tpu.runtime.transport_tcp import TcpEndpoint, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

pytestmark = pytest.mark.skipif(
    not shm_available(), reason="no usable /dev/shm on this host"
)

T = 1


# --------------------------------------------------------------------- ring


def test_ring_wraparound_and_occupancy():
    key = new_world_key()
    try:
        w = ShmRing(f"{key}.a", 4096, create=True)
        r = ShmRing(f"{key}.a")
        # fill, drain, refill across the wrap point, several times
        for rep in range(5):
            blob = bytes([rep]) * 3000
            mv = memoryview(blob)
            n = w.write_some(mv)
            assert 0 < n <= 3000
            assert r.occupancy > 0
            got = r.read_some()
            assert got == blob[:n]
            if n < len(blob):
                assert w.write_some(mv[n:]) == len(blob) - n
                assert r.read_some() == blob[n:]
        assert r.avail() == 0 and w.occupancy == 0.0
        r.close(unlink=False)
        w.close()
        assert not os.path.exists(w.path)
    finally:
        cleanup_world(key)


def test_ring_full_returns_zero():
    key = new_world_key()
    try:
        w = ShmRing(f"{key}.a", 4096, create=True)
        assert w.write_some(memoryview(b"x" * 8192)) == w.cap
        assert w.write_some(memoryview(b"y")) == 0  # full, not blocked
        w.close()
    finally:
        cleanup_world(key)


# ----------------------------------------------------------- endpoint pair


def _pair(key, ring_bytes=64 << 10):
    """Two shm endpoints in one process, rendezvous'd."""
    a = ShmEndpoint(TcpEndpoint(0, {0: ("127.0.0.1", 0)}), key,
                    ring_bytes=ring_bytes)
    b = ShmEndpoint(TcpEndpoint(1, {1: ("127.0.0.1", 0)}), key,
                    ring_bytes=ring_bytes)
    a.addr_map.update(b.addr_map)
    b.addr_map.update(a.addr_map)
    return a, b


def test_pair_upgrade_and_both_codecs():
    key = new_world_key()
    a, b = _pair(key)
    try:
        # TLV-able frame (hot path) and a pickle-only frame (dict token)
        a.send(1, msg(Tag.FA_PUT, 0, payload=b"p" * 100, work_type=T,
                      prio=3, target_rank=-1, answer_rank=-1))
        a.send(1, msg(Tag.SS_PERIODIC_STATS, 0, token={"seq": 1}))
        m1 = b.recv(timeout=5.0)
        m2 = b.recv(timeout=5.0)
        assert m1.tag is Tag.FA_PUT and bytes(m1.payload) == b"p" * 100
        assert m1.prio == 3 and m1.work_type == T
        assert m2.tag is Tag.SS_PERIODIC_STATS and m2.token == {"seq": 1}
        # both frames rode the ring, not TCP
        assert a.shm_frames_tx == 2
        assert b.shm_frames_rx == 2
        # reply direction upgrades independently
        b.send(0, msg(Tag.TA_PUT_RESP, 1, rc=ADLB_SUCCESS, put_id=7))
        r = a.recv(timeout=5.0)
        assert r.tag is Tag.TA_PUT_RESP and r.rc == ADLB_SUCCESS
        assert r.put_id == 7
    finally:
        a.close()
        b.close()
        cleanup_world(key)


def test_pair_streams_frame_larger_than_ring():
    key = new_world_key()
    a, b = _pair(key, ring_bytes=16 << 10)
    try:
        big = os.urandom(1 << 20)  # 1 MiB through a 16 KiB ring
        got = {}

        import threading

        def rx():
            m = b.recv(timeout=30.0)
            got["m"] = m

        t = threading.Thread(target=rx)
        t.start()
        a.send(1, msg(Tag.FA_PUT, 0, payload=big, work_type=T, prio=0,
                      target_rank=-1, answer_rank=-1))
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert bytes(got["m"].payload) == big
    finally:
        a.close()
        b.close()
        cleanup_world(key)


def test_doorbell_coalescing_suppresses_redundant_bells():
    """A send burst toward a peer that has not yet drained must ring
    the doorbell at most once for the outstanding data: subsequent
    frames see the unconsumed head and skip the FIFO write
    (``doorbell_suppressed``), yet every frame is delivered — and a
    receiver parked in a blocking recv still gets a fresh frame
    promptly (the bell after a drained period is NOT suppressed)."""
    key = new_world_key()
    a, b = _pair(key)
    try:
        N = 20
        for i in range(N):
            a.send(1, msg(Tag.FA_PUT, 0, payload=b"x" * 64, work_type=T,
                          prio=i, target_rank=-1, answer_rank=-1))
        # burst sent before the peer drained anything: all but the
        # first bell are redundant and must have been skipped
        assert a.doorbell_suppressed >= N - 2, a.doorbell_suppressed
        for i in range(N):
            m = b.recv(timeout=5.0)
            assert m.tag is Tag.FA_PUT and m.prio == i
        # peer fully drained: the next frame must ring (not suppress)
        # and arrive promptly even though the receiver blocks first
        import threading

        got = {}

        def rx():
            got["m"] = b.recv(timeout=10.0)

        t = threading.Thread(target=rx)
        t.start()
        time.sleep(0.1)  # b is parked in select before the send
        sup_before = a.doorbell_suppressed
        t0 = time.monotonic()
        a.send(1, msg(Tag.FA_PUT, 0, payload=b"y", work_type=T, prio=99,
                      target_rank=-1, answer_rank=-1))
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert got["m"].prio == 99
        # the LOAD-BEARING assert is the sender-side ledger: the bell
        # was sent, not suppressed (a wall-clock wakeup bound would
        # flake under scheduler starvation, and the 0.25 s insurance
        # re-scan delivers even a lost bell — sender truth is the only
        # reliable discriminator)
        assert a.doorbell_suppressed == sup_before
        assert time.monotonic() - t0 < 5.0  # and it did not hang
    finally:
        a.close()
        b.close()
        cleanup_world(key)


def test_eof_never_overtakes_final_ring_frames():
    """The peer's last ring frames are written before the close that
    raises the TCP EOF; recv must deliver them BEFORE the synthetic
    PEER_EOF even though the EOF entered the inbox first (the
    cross-channel ordering fix — without it every clean finalize over
    shm reads as 'died before finalize')."""
    key = new_world_key()
    a, b = _pair(key)
    try:
        for i in range(5):
            a.send(1, msg(Tag.FA_PUT, 0, payload=struct.pack("<q", i),
                          work_type=T, prio=0, target_rank=-1,
                          answer_rank=-1))
        a.close()  # EOF races the 5 undrained ring frames
        seen = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            m = b.recv(timeout=0.5)
            if m is None:
                continue
            seen.append(m.tag)
            if m.tag is Tag.PEER_EOF:
                break
        assert seen.count(Tag.FA_PUT) == 5
        assert seen[-1] is Tag.PEER_EOF
        # and after the EOF, sends toward the dead peer fail like TCP's
        with pytest.raises(OSError):
            b.send(0, msg(Tag.TA_PUT_RESP, 1, rc=ADLB_SUCCESS))
    finally:
        b.close()
        cleanup_world(key)


# -------------------------------------------------- fault parity (3 fabrics)


_SCRIPT_TAGS = [Tag.FA_PUT, Tag.FA_RESERVE, Tag.SS_QMSTAT, Tag.TA_PUT_RESP]


def _drive_scripted(ep, spec, n=200):
    plan = FaultPlan(spec, ep.rank)
    fep = FaultyEndpoint(ep, plan)
    for i in range(n):
        fep.send(
            1,
            msg(_SCRIPT_TAGS[i % len(_SCRIPT_TAGS)], 0, payload=b"x" * 10,
                work_type=1),
        )
    return plan.event_log()


def test_fault_plan_identical_across_three_fabrics():
    """drop/delay/duplicate schedules are byte-identical on the in-proc
    queue fabric, the TCP fabric, and the shm ring fabric."""
    spec = dict(seed=42, drop=0.15, delay=0.1, delay_s=0.0, duplicate=0.1)
    logs = []
    fabric = InProcFabric(2)
    logs.append(_drive_scripted(fabric.endpoints[0], spec))
    a = TcpEndpoint(0, {0: ("127.0.0.1", 0)})
    b = TcpEndpoint(1, {1: ("127.0.0.1", 0)})
    a.addr_map[1] = b.addr_map[1]
    try:
        logs.append(_drive_scripted(a, spec))
    finally:
        a.close()
        b.close()
    key = new_world_key()
    sa, sb = _pair(key)
    try:
        logs.append(_drive_scripted(sa, spec))
        assert sa.shm_frames_tx > 0, "scripted frames never rode the ring"
    finally:
        sa.close()
        sb.close()
        cleanup_world(key)
    assert logs[0], "seeded plan injected nothing — test is vacuous"
    assert logs[0] == logs[1] == logs[2]


def test_disconnect_at_frame_over_shm():
    """A fault-injected disconnect over the shm fabric: the endpoint
    closes (peers see EOF), further sends raise OSError."""
    key = new_world_key()
    a, b = _pair(key)
    try:
        plan = FaultPlan(dict(seed=1, disconnect_at={0: 3}), 0)
        fep = FaultyEndpoint(a, plan)
        fep.send(1, msg(Tag.FA_PUT, 0, payload=b"1", work_type=T))
        fep.send(1, msg(Tag.FA_PUT, 0, payload=b"2", work_type=T))
        with pytest.raises(OSError):
            fep.send(1, msg(Tag.FA_PUT, 0, payload=b"3", work_type=T))
        tags = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            m = b.recv(timeout=0.5)
            if m is None:
                continue
            tags.append(m.tag)
            if m.tag is Tag.PEER_EOF:
                break
        assert tags.count(Tag.FA_PUT) == 2
        assert tags[-1] is Tag.PEER_EOF
    finally:
        b.close()
        cleanup_world(key)


# -------------------------------------------------------- world acceptance


def _echo_app(ctx):
    big = b"B" * (1 << 20)
    if ctx.rank == 0:
        assert ctx.put(big, T) == ADLB_SUCCESS  # > ring size: streams
        for i in range(30):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
    got, nbig = [], 0
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got, nbig
        if len(w.payload) > 1000:
            assert w.payload == big
            nbig += 1
        else:
            got.append(struct.unpack("<q", w.payload)[0])


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_shm_world_completes(mode):
    res = spawn_world(
        3, 2, [T], _echo_app,
        cfg=Config(balancer=mode, fabric="shm", exhaust_check_interval=0.2),
        timeout=90.0,
    )
    done = sorted(x for v, _ in res.app_results.values() for x in v)
    assert done == list(range(30))
    assert sum(nb for _, nb in res.app_results.values()) == 1
    assert not res.aborted


def _kill_mid_ring(ctx):
    if ctx.rank == 0:
        for i in range(24):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
    n = 0
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return n
        if ctx.rank == 1 and n >= 1:
            # dies holding a lease, between reserve and fetch — the
            # reclaim must recover the pinned unit over the ring fabric
            os.kill(os.getpid(), signal.SIGKILL)
        rc, buf = ctx.get_reserved(r.handle)
        if rc != ADLB_SUCCESS:
            continue
        n += 1
        time.sleep(0.004)


def test_shm_worker_sigkill_mid_ring_reclaimed():
    """chaos leg: a peer dying mid-ring (SIGKILL between reserve and
    fetch) over the shm fabric — leases reclaimed, world completes
    around the casualty, segments swept."""
    import glob

    before = set(glob.glob("/dev/shm/adlb*"))
    res = spawn_world(
        4, 2, [T], _kill_mid_ring,
        cfg=Config(fabric="shm", on_worker_failure="reclaim",
                   exhaust_check_interval=0.2),
        timeout=90.0,
    )
    assert res.casualties == [1]
    assert not res.aborted
    # conservation: the victim consumed exactly 1 unit before dying; its
    # reserved-but-unfetched unit was reclaimed and re-delivered
    consumed = sum(v for k, v in res.app_results.items())
    assert consumed == 24 - 1
    # the world sweep left nothing NEW behind (scoped to this world:
    # concurrent/previous worlds' teardown must not flake this)
    leaked = set(glob.glob("/dev/shm/adlb*")) - before
    assert not leaked, f"leaked shm artifacts: {sorted(leaked)}"
