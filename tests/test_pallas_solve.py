"""Pallas greedy-sweep kernel vs the host/XLA twins (bit-exact)."""

import numpy as np
import pytest

from adlb_tpu.balancer.solve import _NEG, AssignmentSolver, _host_greedy


def _random_instance(rng, nt, nr, t):
    task_prio = rng.integers(-1000, 1000, size=nt).astype(np.int32)
    task_type = rng.integers(0, t, size=nt).astype(np.int32)
    pad = rng.random(nt) < 0.25
    task_prio[pad] = int(_NEG)
    task_type[pad] = -1
    req_mask = rng.random((nr, t)) < 0.5
    req_valid = rng.random(nr) < 0.8
    return task_prio, task_type, req_mask, req_valid


@pytest.mark.parametrize("nt,nr,t", [(16, 8, 2), (64, 32, 4), (200, 130, 6)])
def test_pallas_matches_host_greedy(nt, nr, t):
    import jax.numpy as jnp

    from adlb_tpu.balancer.pallas_solve import make_pallas_assign

    kern = make_pallas_assign()
    rng = np.random.default_rng(nt * 1000 + nr)
    for _ in range(5):
        tp, tt, rm, rv = _random_instance(rng, nt, nr, t)
        want = _host_greedy(tp, tt, rm, rv)
        got = np.asarray(
            kern(jnp.asarray(tp), jnp.asarray(tt), jnp.asarray(rm),
                 jnp.asarray(rv))
        )
        np.testing.assert_array_equal(got, want)


def test_pallas_all_padding_and_no_requesters():
    import jax.numpy as jnp

    from adlb_tpu.balancer.pallas_solve import make_pallas_assign

    kern = make_pallas_assign()
    tp = np.full(8, int(_NEG), dtype=np.int32)
    tt = np.full(8, -1, dtype=np.int32)
    rm = np.ones((4, 2), dtype=bool)
    rv = np.ones(4, dtype=bool)
    out = np.asarray(kern(jnp.asarray(tp), jnp.asarray(tt), jnp.asarray(rm),
                          jnp.asarray(rv)))
    assert (out == -1).all()
    # and the mirror case: live tasks, zero valid requesters
    tp2 = np.arange(8, dtype=np.int32)
    tt2 = np.zeros(8, dtype=np.int32)
    out2 = np.asarray(
        kern(jnp.asarray(tp2), jnp.asarray(tt2), jnp.asarray(rm),
             jnp.asarray(np.zeros(4, dtype=bool)))
    )
    assert (out2 == -1).all()


def test_solver_pallas_backend_matches_host():
    """AssignmentSolver with the pallas backend produces the identical
    plan to the default backends on the same snapshots."""
    types = (1, 2, 3)
    snaps = {
        10: {"tasks": [(1, 1, 5, 8), (2, 2, 9, 8), (3, 3, 1, 8)],
             "reqs": [(0, 1, [2]), (1, 2, None)]},
        11: {"tasks": [(7, 1, 9, 8)],
             "reqs": [(2, 3, [1, 3]), (3, 4, [2])]},
    }
    base = AssignmentSolver(types=types, max_tasks=8, max_requesters=4)
    pal = AssignmentSolver(
        types=types, max_tasks=8, max_requesters=4, backend="pallas",
        host_threshold_reqs=None,
    )
    assert sorted(base.solve(dict(snaps), None)) == sorted(
        pal.solve(dict(snaps), None)
    )


def test_pallas_multiblock_sweep_matches_host(monkeypatch):
    """Force the task-block grid (several sequential blocks sharing the
    open-vector scratch) at small shapes; must stay bit-exact with the
    host greedy — this is the path large pools (e.g. 16k x 2k once hit
    the VMEM cap) take on real hardware."""
    import jax.numpy as jnp

    from adlb_tpu.balancer import pallas_solve

    # 16 KiB slab -> block = 16384/(4*128) = 32 rows -> NT=300 uses 10 blocks
    monkeypatch.setattr(pallas_solve, "_SLAB_BYTES", 16 << 10)
    kern = pallas_solve.make_pallas_assign()
    rng = np.random.default_rng(7)
    for _ in range(3):
        tp, tt, rm, rv = _random_instance(rng, 300, 60, 4)
        want = _host_greedy(tp, tt, rm, rv)
        got = np.asarray(
            kern(jnp.asarray(tp), jnp.asarray(tt), jnp.asarray(rm),
                 jnp.asarray(rv))
        )
        np.testing.assert_array_equal(got, want)


def test_pallas_int8_upcast_path_matches_host(monkeypatch):
    """The size-gated int8-streaming + per-block-upcast layout (taken for
    compat matrices >= _BIG_ELEMS) must be bit-exact with the host twin;
    the gate is monkeypatched down so the branch runs at test shapes.
    A distinctive shape avoids a stale jit-cache entry traced with the
    real gate."""
    import jax.numpy as jnp

    from adlb_tpu.balancer import pallas_solve
    from adlb_tpu.balancer.pallas_solve import make_pallas_assign

    monkeypatch.setattr(pallas_solve, "_BIG_ELEMS", 1)
    # shrink the slab too, so the upcast path also runs MULTI-block:
    # stale upcast scratch on grid step i>0, counter persistence, and
    # the exhaustion-skip branch are all upcast-specific states a
    # single-block sweep would never exercise
    monkeypatch.setattr(pallas_solve, "_SLAB_BYTES", 16 * 128)
    kern = make_pallas_assign()
    rng = np.random.default_rng(8)
    for nt, nr, t in ((37, 19, 3), (211, 77, 5)):
        tp, tt, rm, rv = _random_instance(rng, nt, nr, t)
        want = _host_greedy(tp, tt, rm, rv)
        got = np.asarray(
            kern(jnp.asarray(tp), jnp.asarray(tt), jnp.asarray(rm),
                 jnp.asarray(rv))
        )
        np.testing.assert_array_equal(got, want)
    # few requesters vs many tasks: exhaustion fires early, so most
    # blocks of this multi-block upcast sweep take the skip branch
    tp, tt, rm, rv = _random_instance(rng, 1024, 9, 2)
    want = _host_greedy(tp, tt, rm, rv)
    got = np.asarray(
        kern(jnp.asarray(tp), jnp.asarray(tt), jnp.asarray(rm),
             jnp.asarray(rv))
    )
    np.testing.assert_array_equal(got, want)
