"""Native C++ server daemon (adlb_tpu/native/serverd.cpp): the all-native
data plane of SURVEY §7's language split. Python clients over the binary
codec, multi-server stealing, exhaustion, batch-common puts, memory
admission, abort — and a fully native world (C clients + C++ servers)."""

import os
import shutil
import struct

import pytest

from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_PUT_REJECTED, ADLB_SUCCESS, InfoKey

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)

NATIVE = Config(server_impl="native")


def _answer_economy(ctx):
    T_AB, T_C = 1, 2
    if ctx.rank == 0:
        pairs = [(i, i * 3) for i in range(24)]
        for a, b in pairs:
            ctx.put(struct.pack("<qq", a, b), T_AB, answer_rank=0)
        total = 0
        for _ in range(len(pairs)):
            rc, r = ctx.reserve([T_C])
            assert rc == ADLB_SUCCESS
            rc, buf = ctx.get_reserved(r.handle)
            total += struct.unpack("<q", buf)[0]
        ctx.set_problem_done()
        return total
    n = 0
    while True:
        rc, r = ctx.reserve([T_AB])
        if rc != ADLB_SUCCESS:
            return n
        rc, buf = ctx.get_reserved(r.handle)
        a, b = struct.unpack("<qq", buf)
        ctx.put(struct.pack("<q", a + b), T_C, target_rank=r.answer_rank)
        n += 1


def test_native_answer_economy_two_servers():
    res = spawn_world(3, 2, [1, 2], _answer_economy, cfg=NATIVE, timeout=60.0)
    assert res.app_results[0] == sum(i + i * 3 for i in range(24))
    assert sum(v for k, v in res.app_results.items() if k != 0) == 24
    assert sorted(res.server_stats) == [3, 4]
    # stats surface carried through: someone answered reserves
    assert sum(
        s.get(int(InfoKey.NUM_RESERVES), 0) for s in res.server_stats.values()
    ) > 0


def _exhaustion_app(ctx):
    T = 1
    if ctx.rank == 0:
        for i in range(10):
            ctx.put(struct.pack("<q", i), T)
    n = 0
    while True:
        rc, r = ctx.reserve()  # wildcard; ends by exhaustion
        if rc != ADLB_SUCCESS:
            return n
        rc, _ = ctx.get_reserved(r.handle)
        n += 1


def test_native_exhaustion_termination():
    res = spawn_world(
        3, 2, [1], _exhaustion_app,
        cfg=Config(server_impl="native", exhaust_check_interval=0.15),
        timeout=60.0,
    )
    assert sum(res.app_results.values()) == 10


def _batch_common_app(ctx):
    T = 1
    prefix = b"COMMONPREFIX"
    if ctx.rank == 0:
        ctx.begin_batch_put(prefix)
        for i in range(6):
            ctx.put(struct.pack("<q", i), T)
        ctx.end_batch_put()
    got = []
    while True:
        rc, r = ctx.reserve([T])  # terminate by exhaustion: problem_done
        if rc != ADLB_SUCCESS:    # would drop still-queued units
            return sorted(got)
        rc, buf = ctx.get_reserved(r.handle)
        assert buf.startswith(prefix), buf
        got.append(struct.unpack("<q", buf[len(prefix):])[0])


def test_native_batch_common_prefix():
    res = spawn_world(
        3, 2, [1], _batch_common_app,
        cfg=Config(server_impl="native", exhaust_check_interval=0.15),
        timeout=60.0,
    )
    all_got = sorted(
        x for v in res.app_results.values() if v for x in v
    )
    assert all_got == list(range(6))


def _memcap_app(ctx):
    T = 1
    rcs = []
    if ctx.rank == 0:
        # server cap is 4KB; 3 x 2KB puts must spill across servers via
        # reject + least-loaded hint (reference src/adlb.c:2779-2796)
        rcs = [ctx.put(b"x" * 2048, T) for _ in range(3)]
    n = 0
    while True:
        rc, r = ctx.reserve([T])  # all ranks drain; exhaustion terminates
        if rc != ADLB_SUCCESS:
            return (rcs, n)
        ctx.get_reserved(r.handle)
        n += 1


def test_native_put_rejection_and_hint_redirect():
    res = spawn_world(
        2, 2, [1],
        _memcap_app,
        cfg=Config(
            server_impl="native", max_malloc_per_server=4096,
            exhaust_check_interval=0.15,
        ),
        timeout=60.0,
    )
    rcs = res.app_results[0][0]
    assert all(rc in (ADLB_SUCCESS, ADLB_PUT_REJECTED) for rc in rcs)
    # with two 4KB servers all three 2KB units fit somewhere
    assert rcs.count(ADLB_SUCCESS) == 3, rcs
    assert sum(n for _, n in res.app_results.values()) == 3


def _info_app(ctx):
    T = 1
    if ctx.rank == 0:
        for i in range(5):
            ctx.put(struct.pack("<q", i), T, work_prio=i)
        rc, count, nbytes, max_wq = ctx.info_num_work_units(T)
        assert rc == ADLB_SUCCESS
        rc, hwm = ctx.info_get(InfoKey.MALLOC_HWM)
        assert rc == ADLB_SUCCESS
        ctx.set_problem_done()
        return (count, nbytes, max_wq, hwm)
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return None
        ctx.get_reserved(r.handle)


def test_native_info_surface():
    res = spawn_world(2, 1, [1], _info_app, cfg=NATIVE, timeout=60.0)
    count, nbytes, max_wq, hwm = res.app_results[0]
    assert 0 <= count <= 5 and max_wq >= 1 and hwm >= 8


def _abort_app(ctx):
    if ctx.rank == 0:
        ctx.put(b"x", 1)
        ctx.abort(42)  # raises AdlbAborted
    while True:
        rc, r = ctx.reserve([1])
        if rc != ADLB_SUCCESS:
            return None
        ctx.get_reserved(r.handle)


def test_native_abort_fans_out():
    res = spawn_world(3, 2, [1], _abort_app, cfg=NATIVE, timeout=60.0)
    assert res.aborted


def _sidecar_spread_app(ctx):
    import time

    T = 1
    if ctx.rank == 0:
        for i in range(90):
            ctx.put(struct.pack("<q", i), T)
    n = 0
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return n
        ctx.get_reserved(r.handle)
        time.sleep(0.005)
        n += 1


def test_native_tpu_sidecar_spreads_work():
    """balancer='tpu' with native servers: the JAX sidecar receives native
    SS_STATE snapshots and its SS_PLAN_MATCH/SS_PLAN_MIGRATE plan is
    enacted by the C++ data plane — every rank on every server eats."""
    cfg = Config(
        server_impl="native", balancer="tpu", put_routing="home",
        exhaust_check_interval=0.2,
    )
    res = spawn_world(6, 3, [1], _sidecar_spread_app, cfg=cfg, timeout=90.0)
    assert sum(res.app_results.values()) == 90
    # work entered one server; consumers on ALL servers got a share
    assert all(v > 0 for v in res.app_results.values()), res.app_results


def test_all_native_tpu_c_clients():
    """The complete SURVEY §7 architecture: C clients + C++ servers +
    Python/JAX balancer sidecar."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.native.capi import build_example, run_native_world

    examples = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples",
    )
    exe = build_example(os.path.join(examples, "capi_smoke.c"))
    results, stats = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1, 2],
        exe=exe,
        cfg=Config(server_impl="native", balancer="tpu",
                   exhaust_check_interval=0.2),
        timeout=90.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
    total = sum(
        int(out.split("processed=")[1].split()[0]) for _, out, _ in results
    )
    assert total == 24


def _ring_app(ctx):
    import struct
    import time

    T = 1
    if ctx.rank == 0:
        for i in range(24):
            ctx.put(struct.pack("<q", i), T)
        time.sleep(0.3)  # let ring tokens complete trips
    n = 0
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return n
        ctx.get_reserved(r.handle)
        n += 1


def test_native_ring_qmstat_gossip():
    """Reference-faithful ring-token gossip runs natively: the master
    records trip times and stolen work reaches other servers."""
    cfg = Config(
        server_impl="native", qmstat_mode="ring", qmstat_interval=0.05,
        put_routing="home", exhaust_check_interval=0.2,
    )
    res = spawn_world(6, 3, [1], _ring_app, cfg=cfg, timeout=90.0)
    assert sum(res.app_results.values()) == 24
    trip = max(
        s.get(int(InfoKey.AVG_QMSTAT_TRIP_TIME), 0)
        for s in res.server_stats.values()
    )
    assert trip > 0, "master recorded no ring trips"


def test_native_periodic_stats_ring(capfd):
    """Native masters emit STAT_APS chunks in the decoder's format
    (reference src/adlb.c:712-753; scripts/get_stats.py)."""
    import time

    from adlb_tpu.runtime.stats import parse_stat_lines

    def app(ctx):
        T = 1
        if ctx.rank == 0:
            for i in range(30):
                ctx.put(struct.pack("<q", i), T)
            time.sleep(0.5)  # keep the world alive across several ticks
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return n
            ctx.get_reserved(r.handle)
            time.sleep(0.004)
            n += 1

    cfg = Config(
        server_impl="native", periodic_log_interval=0.1,
        exhaust_check_interval=0.2,
    )
    res = spawn_world(3, 2, [1], app, cfg=cfg, timeout=90.0)
    assert sum(res.app_results.values()) == 30
    out, _ = capfd.readouterr()
    records = parse_stat_lines(out.splitlines())
    assert records, "no STAT_APS records emitted"
    assert records[-1]["total"]["puts"] == 30
    assert records[-1]["nservers"] == 2


def test_native_with_debug_server_watchdog():
    """Native daemons heartbeat the Python watchdog with binary DS_LOG
    frames and release it with DS_END at shutdown."""
    cfg = Config(
        server_impl="native", exhaust_check_interval=0.15,
        debug_log_interval=0.1,
    )
    res = spawn_world(
        3, 2, [1], _exhaustion_app, cfg=cfg, use_debug_server=True,
        timeout=60.0,
    )
    assert sum(res.app_results.values()) == 10
    assert not res.aborted


def _nq_app(ctx):
    from adlb_tpu.workloads import nq

    return nq.app_main(ctx, n=6, max_depth_for_puts=2)




@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_native_nq_known_answer(mode):
    """The nq workload (Python clients) over native C++ servers in both
    balancer modes reproduces the known answer."""
    from adlb_tpu.workloads import nq

    cfg = Config(
        server_impl="native", balancer=mode, exhaust_check_interval=0.2,
    )
    res = spawn_world(3, 2, [nq.WORK], _nq_app, cfg=cfg, timeout=90.0)
    total = sum(v[0] for v in res.app_results.values())
    assert total == nq.KNOWN_SOLUTIONS[6]


def test_all_native_world_c_clients():
    """C clients (libadlb.so) against C++ server daemons — zero Python in
    the data plane."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.native.capi import build_example, run_native_world

    examples = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples",
    )
    exe = build_example(os.path.join(examples, "capi_smoke.c"))
    results, stats = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1, 2],
        exe=exe,
        cfg=Config(server_impl="native", exhaust_check_interval=0.2),
        timeout=90.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
    total = sum(
        int(out.split("processed=")[1].split()[0]) for _, out, _ in results
    )
    assert total == 24
    assert len(stats) == 2  # daemon STATS lines parsed


def test_all_native_nq_known_answer():
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.native.capi import build_example, run_native_world

    examples = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples",
    )
    exe = build_example(os.path.join(examples, "nq_c.c"))
    results, _ = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1],
        exe=exe,
        cfg=Config(server_impl="native", exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total = 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        total += int(out.split("solutions=")[1].split()[0])
    assert total == 40  # n-queens(7) known answer


def test_all_native_nq_harness_scaled():
    """The nq_native harness at a non-default board size: env-tuned N and
    cutoff reach the C client, counts validate against the known answer,
    and the timing line parses."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import nq_native

    r = nq_native.run(
        n=8, cutoff=2, num_app_ranks=4, nservers=2,
        cfg=Config(balancer="tpu", exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.solutions == r.expected == 92
    assert r.tasks > 0 and r.tasks_per_sec > 0
    assert 0.0 <= r.wait_pct <= 100.0


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_all_native_tsp_known_answer(mode):
    """Branch-and-bound TSP as C clients against C++ daemons: multi-type
    reserve (BOUND_UPDT preempts WORK by priority), targeted binary-tree
    bound broadcast, batch puts, exhaustion termination — min(best)
    across ranks must equal the brute-force optimum in both balancer
    modes (reference examples/tsp.c ported to the native plane)."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import tsp_native

    r = tsp_native.run(
        n_cities=8, num_app_ranks=4, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.optimum is not None
    assert r.best == r.optimum, (r.best, r.optimum)
    assert r.tasks > 0
    # batched fused fetch: same answer, B&B pruning correct with up-to-k
    # units in hand per round trip (bound updates still preempt inside
    # the batch by priority)
    rb = tsp_native.run(
        n_cities=8, num_app_ranks=4, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=120.0, fetch="batch:4",
    )
    assert rb.best == rb.optimum, (rb.best, rb.optimum)


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_all_native_sudoku_known_answer(mode):
    """Sudoku as C clients against C++ daemons: collector-rank economy
    (targeted max-priority SOLUTION units), batch-put expansion, problem-
    done termination; solutions validate in C (exit code) AND in the
    harness (reference examples/sudoku.c on the native plane)."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import sudoku_native

    r = sudoku_native.run(
        n_puzzles=2, num_app_ranks=4, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.valid, r
    assert r.solved == 2 and r.tasks > 0


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_all_native_gfmc_known_answer(mode):
    """The A/B/C/D answer economy as C clients: answer_rank routing of C
    answers back to the B owner, targeted D funnel to the master, count
    AND checksum self-checks (reference examples/c4.c:31-37,495-502 on
    the native plane)."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import gfmc_native

    r = gfmc_native.run(
        num_a=6, bs_per_a=4, cs_per_b=3, num_app_ranks=4, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.ok, (r.counts, r.expected)
    # every package plus one C-answer reception per C emission
    assert r.tasks == sum(r.expected.values()) + r.expected["c"]


def test_all_native_hotspot_harness():
    """The native-scale hotspot bench harness: home-routed C producers, C
    worker processes, C++ daemons, tpu balancer sidecar — every token
    accounted and idle% computed from per-process monotonic stamps."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import hotspot_native

    r = hotspot_native.run(
        n_tasks=120, work_us=1000, num_app_ranks=6, nservers=3,
        cfg=Config(balancer="tpu", exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.tasks == 120
    assert r.tasks_per_sec > 0
    assert 0.0 <= r.idle_pct <= 100.0
    # batched fused fetch: same scenario, consumers on ADLB_Get_work_batch
    rb = hotspot_native.run(
        n_tasks=120, work_us=1000, num_app_ranks=6, nservers=3,
        cfg=Config(balancer="tpu", exhaust_check_interval=0.2),
        timeout=120.0, fetch="batch:4",
    )
    assert rb.tasks == 120  # no unit lost or double-counted under batching


def test_all_native_trickle_harness():
    """The native trickle probe: timestamped C producer, cross-server-only
    C consumers (co-homed ranks park on NEVER), dispatch percentiles from
    the shared monotonic clock."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import trickle_native

    r = trickle_native.run(
        n_tasks=60, interval_us=5000, group=2, work_us=1000,
        num_app_ranks=6, nservers=3,
        cfg=Config(balancer="tpu", exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.tasks == 60
    assert r.dispatch_p50_ms > 0
    assert r.dispatch_p90_ms >= r.dispatch_p50_ms


def _garbage_then_work(ctx):
    """Rank 0 sprays malformed frames straight at its home server's TCP
    port (each on a fresh, never-established connection), then runs a
    normal put/reserve cycle: the daemon must close each garbage
    connection and keep serving — one stray connection must not kill a
    server other ranks depend on."""
    import socket
    import time as _t

    T = 1
    if ctx.rank == 0:
        host, port = ctx._c.ep.addr_map[ctx.world.home_server(0)]
        garbage = [
            # (a) valid length prefix, binary magic, garbage TLV body
            struct.pack("<I", 41) + b"\x01" + os.urandom(40),
            # (b) non-binary frame (neither TLV magic nor pickle magic)
            struct.pack("<I", 8) + b"\x99" * 8,
            # (c) truncated-inside-TLV frame: magic + tag + src +
            # nfields=1, then a bytes field pointing past the body
            struct.pack("<I", 15) + b"\x01" + struct.pack("<Hi", 1, 0)
            + struct.pack("<H", 1) + b"\x05\x02"
            + struct.pack("<I", 10_000),
            # (d) hostile length prefix: closed before allocating
            struct.pack("<I", 0x7FFFFFFF),
            # (e) zero-length frame
            struct.pack("<I", 0),
            # (f) pickle-magic line noise (no pickled-Msg module path)
            struct.pack("<I", 12) + b"\x80" + os.urandom(11),
            # (g) syntactically valid TLV but an unknown wire tag
            # (nfields=0): must not reach the fatal dispatch arm
            struct.pack("<I", 9) + b"\x01"
            + struct.pack("<HiH", 4242, 0, 0),
        ]
        for frame in garbage:
            s = socket.create_connection((host, port), timeout=5.0)
            s.sendall(frame)
            _t.sleep(0.05)
            s.close()
        _t.sleep(0.2)
        for i in range(6):
            assert ctx.put(b"x%d" % i, T) == ADLB_SUCCESS
        return 0  # exhaustion terminates once workers drain all 6
    n = 0
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return n
        rc, _buf = ctx.get_reserved(r.handle)
        assert rc == ADLB_SUCCESS
        n += 1


def test_native_daemon_survives_malformed_frames():
    """Frame-decoder robustness: garbage connections (random TLV bodies,
    wrong magic, truncated fields, hostile length prefixes, empty frames)
    are closed with a diagnostic while the daemon keeps serving real
    clients; only corruption on an ESTABLISHED peer stream is fatal."""
    res = spawn_world(
        3, 2, [1], _garbage_then_work,
        cfg=Config(server_impl="native", exhaust_check_interval=0.2),
        timeout=60.0,
    )
    assert sum(v for k, v in res.app_results.items() if k != 0) == 6


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_all_native_coinop_latency_probe(mode):
    """The fork's own pop-latency microbenchmark as C clients: producer
    floods the pool, workers time every Reserve+Get and report Welford
    mean/stddev per rank plus raw latencies; no token lost, moments
    consistent with the gathered raw values (reference
    examples/coinop.cpp:79-126,190-213 on the native plane)."""
    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.workloads import coinop_native

    r = coinop_native.run(
        n_tokens=150, num_app_ranks=4, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=120.0,
    )
    assert r.pops == 150
    assert r.latency_p50_ms > 0
    assert r.latency_p95_ms >= r.latency_p50_ms
    assert r.per_worker  # at least one consuming rank reported moments
    # the C-side Welford mean of every reporting worker must sit inside
    # the raw latency envelope the same rank shipped
    assert all(
        0.0 < m <= r.latency_p95_ms * 20 for m, _s in r.per_worker.values()
    )
