"""Fortran binding (adlbf.c) validation.

No Fortran compiler ships in this image, so the shim layer is exercised
from C with the exact GNU-mangled, by-reference calling convention a
Fortran 77 program emits (reference examples/f1.f flow): see
examples/fshim_smoke.c.
"""

import os
import shutil
import subprocess

import pytest

from adlb_tpu.native.capi import build_example, build_libadlb, run_native_world
from adlb_tpu.runtime.world import Config

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None,
    reason="no C toolchain",
)

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


def test_fortran_shims_exported():
    """Every reference Fortran entry point must be present with GNU
    mangling (reference src/adlbf.c:6-103 exports the same set)."""
    lib = build_libadlb()
    syms = subprocess.run(
        ["nm", "-D", "--defined-only", lib],
        check=True, capture_output=True, text=True,
    ).stdout
    for name in (
        "adlb_init_", "adlb_server_", "adlb_debug_server_", "adlb_put_",
        "adlb_reserve_", "adlb_ireserve_", "adlb_get_reserved_",
        "adlb_get_reserved_timed_", "adlb_begin_batch_put_",
        "adlb_end_batch_put_", "adlb_set_problem_done_",
        "adlb_set_no_more_work_", "adlb_info_get_",
        "adlb_info_num_work_units_", "adlb_finalize_", "adlb_abort_",
        "adlb_world_rank_", "adlb_world_size_",
    ):
        assert f" {name}" in syms, f"missing Fortran shim {name}"


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_fshim_world(mode):
    exe = build_example(os.path.join(_EXAMPLES, "fshim_smoke.c"))
    results, stats = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1, 2],
        exe=exe,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total = 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
        total += int(out.split("processed=")[1].split()[0])
    assert total == 12
    assert len(stats) == 2


def _fortran_compiler():
    for fc in ("gfortran", "f77", "flang"):
        if shutil.which(fc):
            return fc
    return None


@pytest.mark.skipif(_fortran_compiler() is None,
                    reason="no Fortran compiler in this image")
@pytest.mark.parametrize("prog", ["f1", "fbatcher"])
def test_real_fortran_examples(prog, tmp_path):
    """Compile and run the actual Fortran programs (examples/f1.f,
    examples/fbatcher.f — the reference treats Fortran as first-class,
    reference src/adlbf.c:6-103) against native servers."""
    fc = _fortran_compiler()
    lib = build_libadlb()
    libdir = os.path.dirname(lib)
    exe = str(tmp_path / prog)
    src = os.path.join(_EXAMPLES, f"{prog}.f")
    inc = os.path.join(os.path.dirname(_EXAMPLES), "include")
    subprocess.run(
        [fc, "-O2", f"-I{inc}", "-o", exe, src,
         f"-L{libdir}", "-ladlb", f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True, text=True,
    )
    env_extra = {}
    if prog == "fbatcher":
        batch = tmp_path / "jobs.txt"
        batch.write_text("".join(f"echo JOB-{i}\n" for i in range(6)))
        env_extra["ADLB_BATCH_FILE"] = str(batch)
    results, _ = run_native_world(
        n_clients=3, nservers=2, types=[1, 2, 3], exe=exe,
        cfg=Config(server_impl="native", exhaust_check_interval=0.2),
        env_extra=env_extra, timeout=120.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
    if prog == "f1":
        assert "F1 OK" in results[0][1]
    else:
        ran = sum(
            int(out.split("FBATCHER RAN")[1].split()[0])
            for _, out, _ in results if "FBATCHER RAN" in out
        )
        assert ran == 6
        jobs = "".join(out for _, out, _ in results)
        assert all(f"JOB-{i}" in jobs for i in range(6))


def test_mangling_override_abi(tmp_path):
    """The ADLB_FC_GLOBAL override path: build the shim with an UPPERCASE
    no-underscore convention (what FortranCInterface generates for e.g.
    classic UPPERCASE compilers) and drive it from a caller emitting that
    convention — validating the macro plumbing against a second ABI
    besides the GNU default (reference CMakeLists.txt:62-68)."""
    native = os.path.join(os.path.dirname(_EXAMPLES), "adlb_tpu", "native")
    inc = os.path.join(os.path.dirname(_EXAMPLES), "include")
    lib = str(tmp_path / "libadlb_uc.so")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
         "-DADLB_FC_GLOBAL(lc,UC)=UC", f"-I{inc}", "-o", lib,
         os.path.join(native, "libadlb.cpp"),
         os.path.join(native, "adlbf.c")],
        check=True, capture_output=True, text=True,
    )
    syms = subprocess.run(
        ["nm", "-D", "--defined-only", lib],
        check=True, capture_output=True, text=True,
    ).stdout
    assert " ADLB_INIT\n" in syms.replace("T ", " ").replace("t ", " ") or (
        "ADLB_INIT" in syms
    )
    assert "adlb_init_" not in syms  # the default convention is replaced
