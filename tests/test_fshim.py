"""Fortran binding (adlbf.c) validation.

No Fortran compiler ships in this image, so the shim layer is exercised
from C with the exact GNU-mangled, by-reference calling convention a
Fortran 77 program emits (reference examples/f1.f flow): see
examples/fshim_smoke.c.
"""

import os
import shutil
import subprocess

import pytest

from adlb_tpu.native.capi import build_example, build_libadlb, run_native_world
from adlb_tpu.runtime.world import Config

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None,
    reason="no C toolchain",
)

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


def test_fortran_shims_exported():
    """Every reference Fortran entry point must be present with GNU
    mangling (reference src/adlbf.c:6-103 exports the same set)."""
    lib = build_libadlb()
    syms = subprocess.run(
        ["nm", "-D", "--defined-only", lib],
        check=True, capture_output=True, text=True,
    ).stdout
    for name in (
        "adlb_init_", "adlb_server_", "adlb_debug_server_", "adlb_put_",
        "adlb_reserve_", "adlb_ireserve_", "adlb_get_reserved_",
        "adlb_get_reserved_timed_", "adlb_begin_batch_put_",
        "adlb_end_batch_put_", "adlb_set_problem_done_",
        "adlb_set_no_more_work_", "adlb_info_get_",
        "adlb_info_num_work_units_", "adlb_finalize_", "adlb_abort_",
        "adlb_world_rank_", "adlb_world_size_",
    ):
        assert f" {name}" in syms, f"missing Fortran shim {name}"


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_fshim_world(mode):
    exe = build_example(os.path.join(_EXAMPLES, "fshim_smoke.c"))
    results, stats = run_native_world(
        n_clients=3,
        nservers=2,
        types=[1, 2],
        exe=exe,
        cfg=Config(balancer=mode, exhaust_check_interval=0.2),
        timeout=90.0,
    )
    total = 0
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
        assert "OK" in out
        total += int(out.split("processed=")[1].split()[0])
    assert total == 12
    assert len(stats) == 2
