"""Host-tier ledger parity: the array-resident ledger must be
indistinguishable from the retained pure-Python twin.

Contract (see balancer/ledger.py): identical kept-requester and
eligible-task sets — and therefore identical matches AND migrations —
across randomized sequences of full snapshot restamps, in-place task
deltas (``delta_seq`` bumps, no stamp change), dead-rank requester
patches (``req_seq`` bumps), server death/rejoin, credit suppression,
plan-mark expiry (pruning), and direct plan-dict pokes.  Checked with
the single-device solver and the sharded solver at mesh sizes 1/2/8,
plus a no-realloc guard on the resident arrays and the sharded solver's
no-retrace guard under view ingest.

The wall-clock window knobs (SUPPRESS_TTL, INFLOW_*, PARK_RECENT,
LOOK_GROW_WINDOW) are pinned to deterministic extremes: the two engines
run sequentially, so their round clocks differ by one solve — a credit
or park sitting exactly on a window edge would flip between them for
timing, not semantics.
"""

import copy
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (forces the 8-device CPU platform)

import jax
from jax.sharding import Mesh

from adlb_tpu.balancer.distributed import DistributedAssignmentSolver
from adlb_tpu.balancer.engine import PlanEngine

TYPES = (1, 2, 3, 4)

# the multi-job fuzz arm: 3 planned namespaces with deliberately
# lopsided fair-share weights, so the weighted-score path (priority
# bias folded at pack time, jobdim.weight_bias) is part of the parity
# bar, not just the job-isolation masks
MAX_JOBS = 3
JOB_WEIGHTS = {1: 3.0, 2: 0.25}


def _mk_engine(host_ledger, solver=None, max_jobs=1, job_weights=None):
    eng = PlanEngine(types=TYPES, max_tasks=12, max_requesters=6,
                     host_ledger=host_ledger, max_jobs=max_jobs,
                     job_weights=job_weights)
    if solver is not None:
        eng.solver = solver
    eng.PUMP_INTERVAL = 0.0
    eng.INFLOW_MIN_AGE = 0.0
    eng.INFLOW_TTL = 1e9
    eng.SUPPRESS_TTL = 1e9
    eng.PARK_RECENT = 1e9
    eng.LOOK_GROW_WINDOW = 1e9
    return eng


def _rand_job(rng, J):
    """Job column draw: mostly the default namespace, a spread over the
    planned ones, and a rare overflow id (== J, i.e. >= max_jobs) to
    exercise the planner-invisible skip identically on both arms."""
    if J <= 1 or rng.random() < 0.4:
        return 0
    if rng.random() < 0.08:
        return J
    return int(rng.integers(1, J))


def _job_task(rng, seqno, J):
    """A task tuple honoring the wire rule: the 5th (job) element is
    present ONLY when the unit is outside the default namespace."""
    tk = (seqno, int(rng.choice(TYPES)), int(rng.integers(-9, 10)), 8)
    jb = _rand_job(rng, J)
    return tk + (jb,) if jb else tk


def _rand_snaps(rng, nservers, seq, stamp, J=1):
    snaps = {}
    for s in range(100, 100 + nservers):
        tasks = []
        for _ in range(int(rng.integers(0, 10))):
            seq[0] += 1
            tasks.append(_job_task(rng, seq[0], J))
        tasks.sort(key=lambda t: -t[2])
        reqs = []
        for r in range(int(rng.integers(0, 5))):
            rq = ((s - 100) * 50 + r, int(rng.integers(1, 1000)),
                  None if rng.random() < 0.2
                  else sorted({int(rng.choice(TYPES))
                               for _ in range(int(rng.integers(1, 3)))}))
            jb = _rand_job(rng, J)
            if jb:
                rq = rq + (0, jb)
            reqs.append(rq)
        snaps[s] = {"tasks": tasks, "reqs": reqs,
                    "consumers": int(rng.integers(0, 3)),
                    "stamp": stamp, "task_stamp": stamp}
    return snaps


def _bump(snaps, rank):
    """Version an in-place mutation when the dict is a SnapshotStore
    (the producer contract the runtime follows); no-op on plain dicts."""
    b = getattr(snaps, "bump", None)
    if b is not None:
        b(rank)


def _mutate(rng, pair, seq, rnd, matches, J=1):
    """One randomized world step applied identically to both engines'
    snapshot dicts: consume the plan, then a mix of delta appends,
    req-seq patches, death/rejoin, and fresh restamps."""
    t = time.monotonic()
    for snaps in pair:
        for holder, s_, rh, fr, rq in matches:
            hs = snaps.get(holder)
            if hs is not None:
                hs["tasks"] = [x for x in hs["tasks"] if x[0] != s_]
                hs["task_stamp"] = t
                _bump(snaps, holder)
            rs = snaps.get(rh)
            if rs is not None:
                rs["reqs"] = [
                    r for r in rs["reqs"]
                    if not (r[0] == fr and r[1] == rq)
                ]
                rs["stamp"] = t
                _bump(snaps, rh)
    ranks = sorted(pair[0])
    if not ranks:
        return
    # in-place task delta (no stamp bump, delta_seq carries it)
    if rng.random() < 0.7:
        tgt = int(rng.choice(ranks))
        seq[0] += 1
        unit = _job_task(rng, seq[0], J)
        for snaps in pair:
            snaps[tgt]["tasks"].append(unit)
            snaps[tgt]["delta_seq"] = snaps[tgt].get("delta_seq", 0) + 1
            _bump(snaps, tgt)
    # dead-rank req patch (req_seq bump, no stamp bump)
    if rng.random() < 0.4:
        tgt = int(rng.choice(ranks))
        dead = int(rng.integers(0, 400))
        for snaps in pair:
            kept = [r for r in snaps[tgt]["reqs"] if r[0] != dead]
            if len(kept) != len(snaps[tgt]["reqs"]):
                snaps[tgt]["reqs"] = kept
                snaps[tgt]["req_seq"] = snaps[tgt].get("req_seq", 0) + 1
                _bump(snaps, tgt)
    # server death (and a later rejoin via the restamp below)
    if rng.random() < 0.15 and len(ranks) > 2:
        tgt = int(rng.choice(ranks))
        for snaps in pair:
            snaps.pop(tgt, None)
    # fresh full restamps for a couple of servers (rejoins included)
    t2 = time.monotonic()
    for _ in range(int(rng.integers(1, 3))):
        tgt = 100 + int(rng.integers(0, 8))
        tasks = []
        for _ in range(int(rng.integers(0, 10))):
            seq[0] += 1
            tasks.append(_job_task(rng, seq[0], J))
        tasks.sort(key=lambda x: -x[2])
        rq = ((tgt - 100) * 50 + 20 + rnd, int(rng.integers(1, 1000)),
              [int(rng.choice(TYPES))])
        jb = _rand_job(rng, J)
        reqs = [rq + (0, jb) if jb else rq]
        cons = int(rng.integers(0, 3))  # drawn ONCE: both dicts identical
        for snaps in pair:
            snaps[tgt] = {"tasks": list(tasks), "reqs": list(reqs),
                          "consumers": cons, "stamp": t2, "task_stamp": t2}


def _assert_filter_parity(a, p, snapsA, snapsP):
    """Beyond plan equality: the per-rank kept/eligible row sets must
    match exactly.  Both ledgers re-filter at compare time (the py
    twin's kept lists are a round-time snapshot, the array ledger's
    columns are live — this round's plan marks already applied)."""
    now = time.monotonic()
    for e, sn in ((a, snapsA), (p, snapsP)):
        e._ledger.sync(sn, now)
        e._ledger.filter_reqs(sn, {}, now)
    for rank in snapsA:
        assert a._ledger.kept_reqs(rank) == p._ledger.kept_reqs(rank), rank
        assert a._ledger.elig_tasks(rank) == p._ledger.elig_tasks(rank), rank


def _drive(a, p, seed, rounds=14, nservers=8, J=1, reweight=None):
    rng = np.random.default_rng(seed)
    seq = [0]
    snapsA = _rand_snaps(rng, nservers, seq, time.monotonic(), J=J)
    snapsP = copy.deepcopy(snapsA)
    pair = (snapsA, snapsP)
    for rnd in range(rounds):
        if rnd == 4:
            # identical far-future in-flight credits: the suppression
            # budget path (fed types + budget) on both engines
            far = time.monotonic() + 100.0
            for e in (a, p):
                e._planned_in.setdefault(102, []).append(
                    (far, 2, 10**6, 100, frozenset({1, 2})))
        if rnd == 7 and reweight is not None:
            # live reweight mid-drive: both engines swap the same bias
            # vector (the POST /jobs/<id> weight path) and must keep
            # producing identical pair lists afterwards
            for e in (a, p):
                assert e.set_job_weights(reweight)
        mA = a.round(snapsA, None)
        mP = p.round(snapsP, None)
        assert mA == mP, (rnd, mA, mP)
        _assert_filter_parity(a, p, snapsA, snapsP)
        _mutate(rng, pair, seq, rnd, mA[0], J=J)


def test_parity_single_device_solver():
    for seed in range(4):
        a = _mk_engine("array")
        p = _mk_engine("py")
        _drive(a, p, seed)


def test_parity_single_device_solver_multi_job():
    """Job-column parity: snapshots carry a mixed job population
    (default, weighted namespaces, rare overflow ids) and both engines
    plan with lopsided fair-share weights plus a live mid-drive
    reweight — matches and kept/eligible sets must stay identical."""
    for seed in range(4):
        a = _mk_engine("array", max_jobs=MAX_JOBS, job_weights=JOB_WEIGHTS)
        p = _mk_engine("py", max_jobs=MAX_JOBS, job_weights=JOB_WEIGHTS)
        _drive(a, p, 50 + seed, J=MAX_JOBS,
               reweight={1: 0.5, 2: 2.0})


@pytest.fixture(scope="module", params=[1, 2, 8])
def mesh(request):
    devs = np.array(jax.devices()[: request.param])
    return Mesh(devs, axis_names=("s",))


def test_parity_sharded_solver(mesh):
    """Array-ledger view ingest into the sharded solver vs the py twin's
    materialized-dict path, at mesh 1/2/8 — same plans, same filters."""
    ndev = mesh.devices.size
    nservers = 2 * ndev if ndev > 4 else 8

    def dist():
        return DistributedAssignmentSolver(
            types=TYPES, max_tasks_per_server=12, max_requesters=6,
            mesh=mesh, rounds=64,
            servers_per_device=-(-nservers // ndev),
        )

    a = _mk_engine("array", dist())
    p = _mk_engine("py", dist())
    _drive(a, p, 1000 + ndev, nservers=nservers)


def test_parity_sharded_solver_multi_job(mesh):
    """The sharded solver's composite (job, type) axis vs the py twin,
    at mesh 1/2/8 — the death/rejoin churn in _mutate rides along, so
    the job column survives restamps and membership changes too."""
    ndev = mesh.devices.size
    nservers = 2 * ndev if ndev > 4 else 8

    def dist():
        return DistributedAssignmentSolver(
            types=TYPES, max_tasks_per_server=12, max_requesters=6,
            mesh=mesh, rounds=64,
            servers_per_device=-(-nservers // ndev),
            max_jobs=MAX_JOBS, job_weights=JOB_WEIGHTS,
        )

    a = _mk_engine("array", dist(), max_jobs=MAX_JOBS,
                   job_weights=JOB_WEIGHTS)
    p = _mk_engine("py", dist(), max_jobs=MAX_JOBS,
                   job_weights=JOB_WEIGHTS)
    _drive(a, p, 2000 + ndev, nservers=nservers, J=MAX_JOBS,
           reweight={1: 1.0, 2: 5.0})


def test_no_realloc_and_no_retrace_steady_state():
    """Steady rounds must neither reallocate the ledger's resident
    arrays nor retrace the sharded solver's jitted sweep."""
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, axis_names=("s",))
    eng = _mk_engine("array", DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=12, max_requesters=6, mesh=mesh,
        rounds=16))
    rng = np.random.default_rng(3)
    seq = [0]
    snaps = _rand_snaps(rng, 8, seq, time.monotonic())
    eng.round(snaps, None)  # registration/allocation round
    led = eng._ledger
    ids = {
        n: id(getattr(led, n))
        for n in ("pk_tp", "pk_tt", "pk_rv", "pk_rm", "g_dem", "g_sup",
                  "g_taskcnt", "g_eligreq")
    }
    for rnd in range(12):
        t = time.monotonic()
        for tgt in (100, 101):
            seq[0] += 1
            snaps[tgt]["tasks"] = [
                (seq[0], int(rng.choice(TYPES)), int(rng.integers(-9, 10)),
                 8)
            ]
            snaps[tgt]["reqs"] = [
                ((tgt - 100) * 50 + rnd, int(rng.integers(1, 1000)),
                 [int(rng.choice(TYPES))])
            ]
            snaps[tgt]["stamp"] = snaps[tgt]["task_stamp"] = t
        eng.round(snaps, None)
    for n, i in ids.items():
        assert id(getattr(led, n)) == i, f"{n} reallocated mid-steady-state"
    # the engine's solver defaults to the fused device tier; whichever
    # jitted program carried the rounds must have compiled exactly once
    plan_fn = eng.solver._plan_fn or eng.solver._gather_fn
    assert plan_fn._cache_size() == 1
    assert led.patch_count > 0
    # the fast path really carried the rounds: no cadence resync yet
    assert led.resync_count == 0


def test_parity_store_driven_stamp_stampless_mix():
    """The runtime shape since the O(S) scan kill: the array engine is
    driven by a versioned SnapshotStore (every in-place mutation
    bump()ed, as server.py/sidecar.py do) while the py twin reads a
    plain dict mutated identically — with a STAMPLESS minority mixed in
    (snapshots from planes that never stamp re-derive every round by
    contract). Plans and kept/eligible sets must stay identical, and
    the store fast path must actually carry the steady rounds: full
    walks only at the cold start and on real membership churn."""
    from adlb_tpu.balancer.ledger import SnapshotStore

    for seed in (21, 22, 23):
        a = _mk_engine("array")
        p = _mk_engine("py")
        rng = np.random.default_rng(seed)
        seq = [0]
        base = _rand_snaps(rng, 8, seq, time.monotonic())
        for s in sorted(base)[::3]:  # stampless minority
            base[s].pop("stamp")
            base[s].pop("task_stamp")
        snapsA: SnapshotStore = SnapshotStore(base)
        snapsP = copy.deepcopy(base)
        pair = (snapsA, snapsP)
        rounds = 14
        for rnd in range(rounds):
            mA = a.round(snapsA, None)
            mP = p.round(snapsP, None)
            assert mA == mP, (seed, rnd, mA, mP)
            _assert_filter_parity(a, p, snapsA, snapsP)
            _mutate(rng, pair, seq, rnd, mA[0])
        led = a._ledger
        reasons = led.resync_reasons
        assert reasons.get("cold", 0) <= 1, reasons
        # deaths/rejoins in _mutate are the only legitimate full walks
        # beyond the cold one; most rounds must ride the O(changed)
        # fast path (the compare-time syncs in _assert_filter_parity
        # are same-version no-ops on the store arm)
        assert sum(reasons.values()) < rounds, reasons


def test_store_fork_isolates_concurrent_mutation():
    """The balancer worker plans over store.fork() while the reactor
    keeps mutating the live store: the fork's version marks must make
    the NEXT sync see exactly the ranks that changed after the fork —
    nothing lost, kept/eligible sets equal to a from-scratch twin's."""
    from adlb_tpu.balancer.ledger import SnapshotStore

    a = _mk_engine("array")
    p = _mk_engine("py")
    rng = np.random.default_rng(5)
    seq = [0]
    live: SnapshotStore = SnapshotStore(
        _rand_snaps(rng, 6, seq, time.monotonic()))
    plain = copy.deepcopy(dict(live))
    fork0 = live.fork()
    assert a.round(fork0, None) == p.round(plain, None)
    # concurrent-style mutations on the LIVE store after the fork (the
    # fork the round just used is untouched); the py twin's plain dict
    # gets the identical mutations
    t = time.monotonic()
    for d in (live, plain):
        d[100]["tasks"].append((10**6, 1, 9, 8))
        d[100]["delta_seq"] = d[100].get("delta_seq", 0) + 1
        d[101]["reqs"] = [(50, 999, [2])]
        d[101]["stamp"] = t
        d.pop(104)
    live.bump(100)
    live.bump(101)
    assert 104 in fork0 and 104 not in live  # fork really is isolated
    fork1 = live.fork()
    assert a.round(fork1, None) == p.round(plain, None)
    _assert_filter_parity(a, p, fork1, plain)
    # the post-fork changes arrived through the log tail, not a walk:
    # no membership/cold full pass beyond the initial one
    assert a._ledger.resync_reasons.get("cold", 0) == 1
    # (104's death IS a membership change — that one full walk is the
    # contract; nothing else may have forced one)
    assert a._ledger.resync_reasons.get("membership", 0) == 1


def test_direct_plan_dict_pokes_stay_coherent():
    """Tests (and future code) poke engine._planned_tasks/_planned_reqs
    directly; the array ledger's columns must follow via the dict
    hooks — including deletes (the prune path)."""
    a = _mk_engine("array")
    p = _mk_engine("py")
    t0 = time.monotonic()
    snaps = {
        10: {"tasks": [(1, 1, 5, 8), (2, 2, 4, 8)], "reqs": [],
             "consumers": 1, "stamp": t0, "task_stamp": t0},
        11: {"tasks": [], "reqs": [(5, 1, [1]), (6, 2, [2])],
             "consumers": 1, "stamp": t0, "task_stamp": t0},
    }
    snaps2 = copy.deepcopy(snaps)
    now = time.monotonic()
    for e, sn in ((a, snaps), (p, snaps2)):
        e._ledger.sync(sn, now)
        e._ledger.filter_reqs(sn, {}, now)
    # poke AFTER the array columns exist: mark task (10, 1) and req
    # (11, 6, 2) planned in the future — the dict hooks must keep the
    # columns live
    for e in (a, p):
        e._planned_tasks[(10, 1)] = t0 + 100.0
        e._planned_reqs[(11, 6, 2)] = t0 + 100.0
    assert a._ledger.elig_tasks(10) == p._ledger.elig_tasks(10) == [
        (2, 2, 4, 8)]
    # only the unmarked pair remains — and it is type-incompatible, so
    # no plan on either engine
    mA, mP = a.round(snaps, None), p.round(snaps2, None)
    assert mA == mP == ([], [])
    _assert_filter_parity(a, p, snaps, snaps2)
    # delete the marks (what pruning does) — both become eligible again
    for e in (a, p):
        del e._planned_tasks[(10, 1)]
        del e._planned_reqs[(11, 6, 2)]
    mA, mP = a.round(snaps, None), p.round(snaps2, None)
    assert mA == mP and len(mA[0]) == 2
    _assert_filter_parity(a, p, snaps, snaps2)


def test_pump_precheck_parity_fuzz():
    """The vectorized _maybe_imbalanced twin answers exactly like the
    Python pre-check over random synced instances (consumers, raw
    counts, windows, planned-away edges)."""
    rng = np.random.default_rng(11)
    for trial in range(30):
        eng = _mk_engine("array")
        seq = [0]
        t0 = time.monotonic()
        snaps = _rand_snaps(rng, int(rng.integers(2, 8)), seq, t0)
        # sprinkle planned-away marks over some listed tasks
        for rank, snap in snaps.items():
            for tk in snap["tasks"]:
                if rng.random() < 0.3:
                    eng._planned_tasks[(rank, tk[0])] = (
                        t0 + (1.0 if rng.random() < 0.5 else -100.0))
        # random adaptive windows
        for rank in snaps:
            if rng.random() < 0.4:
                eng._look[rank] = float(rng.integers(8, 64))
        now = time.monotonic()
        eng._ledger.sync(snaps, now)
        fast = eng._ledger.maybe_imbalanced(eng, snaps)
        assert fast is not None, "ledger should be synced here"
        assert fast == eng._maybe_imbalanced(snaps), (trial, snaps)


def test_unsynced_direct_call_falls_back():
    """maybe_imbalanced on a dict the ledger never synced returns None
    (the engine then runs the Python pre-check) — the contract the
    pre-existing direct-call unit tests rely on."""
    eng = _mk_engine("array")
    snaps = {
        10: {"tasks": [(1, 1, 1, 8)], "reqs": [], "consumers": 1},
        11: {"tasks": [], "reqs": [], "consumers": 1},
    }
    assert eng._ledger.maybe_imbalanced(eng, snaps) is None
    assert isinstance(eng._maybe_imbalanced(snaps), bool)
