"""Remote fused fetch + client prefix cache + get_work_stream.

Three layers of coverage:

* end-to-end conservation on BOTH fabrics (in-proc threads, TCP
  processes) with the client-side metrics proving the round trips are
  gone (no FA_GET_RESERVED on the RFR path, one FA_GET_COMMON per
  prefix per client);
* the race lattice driven directly against a Server instance with a
  recording endpoint (UNRESERVE crossing a payload-carrying RFR
  response, SS_DELIVERED after the pin moved, rank death with a relay
  in flight, duplicate reserve frames across reconnect);
* prefix-cache refcount exactness after forfeit notifications.
"""

import struct
import threading
import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.queues import RqEntry
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import (
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_NO_MORE_WORK,
    ADLB_SUCCESS,
)

T = 1


# --------------------------------------------------------------- end-to-end


def _remote_consumer(ctx):
    """Producer home-routes its puts; every other rank consumes via the
    fused get_work and reports its GET_RESERVED send count."""
    if ctx.rank == 0:
        for i in range(40):
            ctx.put(struct.pack("<q", i), T)
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got, ctx._c.metrics.value("tx_msgs", tag="FA_GET_RESERVED")
        got.append(struct.unpack("<q", w.payload)[0])


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_remote_fused_fetch_no_get_leg(mode):
    """Cross-server delivery (work pre-positioned at the producer's home
    server, consumers homed elsewhere) completes with ZERO client
    GET_RESERVED round trips in both balancer modes."""
    cfg = Config(balancer=mode, put_routing="home",
                 exhaust_check_interval=0.2)
    res = run_world(4, 2, [T], _remote_consumer, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v[0])
    assert got == list(range(40))
    assert all(v[1] == 0 for v in res.app_results.values()), {
        r: v[1] for r, v in res.app_results.items()
    }


def test_remote_fused_fetch_tcp():
    """Same contract over the TCP fabric (real processes)."""
    cfg = Config(balancer="steal", put_routing="home",
                 exhaust_check_interval=0.2)
    res = spawn_world(4, 2, [T], _remote_consumer, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v[0])
    assert got == list(range(40))
    assert all(v[1] == 0 for v in res.app_results.values())


def _prefix_consumer(ctx):
    if ctx.rank == 0:
        ctx.begin_batch_put(b"PREFIX:")
        for i in range(24):
            ctx.put(struct.pack("<q", i), T)
        ctx.end_batch_put()
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            m = ctx._c.metrics
            return (got, m.value("tx_msgs", tag="FA_GET_COMMON"),
                    m.value("prefix_cache_hits"))
        assert w.payload.startswith(b"PREFIX:")
        got.append(struct.unpack("<q", w.payload[7:])[0])


def test_prefix_cache_one_fetch_per_client():
    """Batch-common units fuse as suffix + prefix handle: each client
    fetches the prefix at most once; every further member is served from
    the LRU with a forfeit accounting note (hits + the one miss account
    every consumed member, so the server's refcount stays exact)."""
    res = run_world(3, 2, [T], _prefix_consumer,
                    cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v[0])
    assert got == list(range(24))
    for rank, (units, gets, hits) in res.app_results.items():
        assert gets <= 1, (rank, gets)
        assert not units or gets + hits == len(units), (rank, gets, hits)


def test_prefix_cache_disabled_falls_back():
    """prefix_cache_bytes=0: every member pays the fetch (reference
    behaviour), and conservation still holds."""
    res = run_world(3, 2, [T], _prefix_consumer,
                    cfg=Config(exhaust_check_interval=0.2,
                               prefix_cache_bytes=0), timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v[0])
    assert got == list(range(24))
    for rank, (units, gets, hits) in res.app_results.items():
        assert hits == 0
        assert gets == len(units), (rank, gets, len(units))


# ----------------------------------------------------------- stream worlds


def _stream_consumer(ctx):
    if ctx.rank == 0:
        for i in range(60):
            ctx.iput(struct.pack("<q", i), T)
        ctx.flush_puts()
    got = []
    with ctx.get_work_stream([T], depth=4) as ws:
        for w in ws:
            got.append(struct.unpack("<q", w.payload)[0])
        rc = ws.rc
    assert rc in (ADLB_NO_MORE_WORK, ADLB_DONE_BY_EXHAUSTION), rc
    return got


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_stream_drain_at_exhaustion(mode):
    """get_work_stream consumes everything exactly once and every slot
    drains cleanly when the world exhausts, in both balancer modes (the
    producer mixes iput into the same endpoint, exercising the passive
    routing of stream deliveries)."""
    cfg = Config(balancer=mode, exhaust_check_interval=0.2)
    res = run_world(4, 2, [T], _stream_consumer, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(60))


@pytest.mark.slow
def test_stream_drain_tcp():
    """TCP-fabric stream drain. Marked slow: the in-proc drain tests
    above carry the tier-1 signal and an 8-process world is the
    expensive part — CI's fault-matrix job runs the full file. (The
    historical startup wedge that used to flake these worlds was
    root-caused to SimpleQueue.get(timeout=0.0) hanging in forked
    children on this host class; transports now route zero timeouts
    through get_nowait().)"""
    res = spawn_world(4, 2, [T], _stream_consumer,
                      cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(60))


def _stream_early_close(ctx):
    if ctx.rank == 0:
        for i in range(30):
            ctx.put(struct.pack("<q", i), T)
    got = []
    ws = ctx.get_work_stream([T], depth=3)
    for w in ws:
        got.append(struct.unpack("<q", w.payload)[0])
        if ctx.rank == 1 and len(got) >= 2:
            ws.close()  # abandon mid-stream: banked units must re-pool
            break
    return got


def test_stream_early_close_repools():
    """A consumer abandoning its stream hands banked work back (re-put /
    unreserve), so the world still conserves every unit."""
    res = run_world(3, 2, [T], _stream_early_close,
                    cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(30))


def test_stream_with_prefixed_units():
    """Streamed batch-common units assemble through the prefix cache."""
    def app(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(b"HD:")
            for i in range(16):
                ctx.put(struct.pack("<q", i), T)
            ctx.end_batch_put()
        got = []
        with ctx.get_work_stream([T], depth=3) as ws:
            for w in ws:
                assert w.payload.startswith(b"HD:")
                got.append(struct.unpack("<q", w.payload[3:])[0])
        return got

    res = run_world(3, 2, [T], app, cfg=Config(exhaust_check_interval=0.2),
                    timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(16))


@pytest.mark.slow
def test_stream_survives_worker_death_reclaim():
    """Prefetch + worker-death reclaim together (the CI fault-matrix
    world): a consumer killed mid-stream is absorbed; survivors drain
    and no unit is consumed twice. The killed rank may take delivered
    (at-most-once) units with it, so the check is duplicates + world
    completion, not exact conservation."""
    fault_spec = {"seed": 7, "ranks": [2], "kill_at_frame": {2: 12}}
    cfg = Config(balancer="steal", exhaust_check_interval=0.2,
                 on_worker_failure="reclaim", fault_spec=fault_spec)
    res = spawn_world(4, 2, [T], _stream_consumer, cfg=cfg, timeout=120.0)
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert len(got) == len(set(got)), "unit consumed twice"
    assert set(got) <= set(range(60))
    # the producer survived, so at least its locally-matched units flowed
    assert got, "no survivor consumed anything"


def test_stream_conserves_under_duplicate_frames():
    """Duplicate frames (re-sends across reconnect) must not double-pin
    or double-deliver: the monotone rqseqno dedup absorbs them."""
    fault_spec = {"seed": 11, "duplicate": 0.2, "ranks": [0, 1, 2, 3]}
    cfg = Config(balancer="steal", exhaust_check_interval=0.2,
                 on_worker_failure="reclaim", fault_spec=fault_spec)
    res = run_world(4, 2, [T], _stream_consumer, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(60))


# ------------------------------------------------- direct race-lattice


class _RecEp:
    """Recording endpoint: send() appends, recv() never delivers."""

    def __init__(self, rank):
        self.rank = rank
        self.sent = []

    def send(self, dest, m):
        self.sent.append((dest, m))

    def recv(self, timeout=None):
        return None

    def of(self, tag):
        return [(d, m) for d, m in self.sent if m.tag is tag]


def _mk_server(rank=2, nranks=4, nservers=2, **cfg_kw):
    world = WorldSpec(nranks=nranks, nservers=nservers, types=(T,))
    cfg = Config(balancer="steal", native_queues="off", **cfg_kw)
    ep = _RecEp(rank)
    return Server(world, cfg, ep), ep


def _put(server, seqno_payload, src=0, target=-1):
    server._handle(msg(Tag.FA_PUT, src, payload=seqno_payload, work_type=T,
                       prio=0, target_rank=target, answer_rank=-1,
                       common_len=0, common_server=-1, common_seqno=-1,
                       put_id=None))


def test_rfr_fetch_pins_and_ships_payload():
    """A fetch-flagged RFR answers with the payload riding the RFR_RESP
    while the unit stays PINNED (lease intact) until SS_DELIVERED."""
    holder, ep = _mk_server(rank=2)
    _put(holder, b"unit0")
    holder._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=5, req_types=[T],
                       targeted_lookup=False, lookup_type=-1, fetch=1))
    (dest, resp), = ep.of(Tag.SS_RFR_RESP)
    assert dest == 3 and resp.found and resp.payload == b"unit0"
    unit = holder.wq.get(resp.seqno)
    assert unit is not None and unit.pinned and unit.pin_rank == 1
    assert holder.leases.get(resp.seqno) is not None
    assert holder._relay_inflight[resp.seqno] == 1
    # confirmation consumes it
    holder._handle(msg(Tag.SS_DELIVERED, 3, seqno=resp.seqno, for_rank=1))
    assert holder.wq.get(resp.seqno) is None
    assert holder.leases.get(resp.seqno) is None
    assert not holder._relay_inflight


def test_unreserve_race_unpins_relay():
    """UNRESERVE crossing a payload-carrying RFR_RESP (the requester got
    satisfied locally meanwhile): the holder unpins and the unit
    re-matches; a LATE SS_DELIVERED for the old pin is ignored."""
    holder, ep = _mk_server(rank=2)
    _put(holder, b"unit0")
    holder._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=5, req_types=[T],
                       targeted_lookup=False, lookup_type=-1, fetch=1))
    (_, resp), = ep.of(Tag.SS_RFR_RESP)
    holder._handle(msg(Tag.SS_UNRESERVE, 3, seqno=resp.seqno, for_rank=1))
    unit = holder.wq.get(resp.seqno)
    assert unit is not None and not unit.pinned
    assert not holder._relay_inflight
    # late stale confirm: unit is unpinned -> must NOT consume
    holder._handle(msg(Tag.SS_DELIVERED, 3, seqno=resp.seqno, for_rank=1))
    assert holder.wq.get(resp.seqno) is not None


def test_home_compensates_when_entry_is_stale():
    """Home side: a payload-carrying RFR_RESP for an entry that no longer
    matches (satisfied + re-parked with a new rqseqno) sends UNRESERVE and
    does NOT forward a second reservation response."""
    home, ep = _mk_server(rank=2)
    home.rq.add(RqEntry(world_rank=0, rqseqno=9, req_types=frozenset([T]),
                        fetch=True))
    home._handle(msg(Tag.SS_RFR_RESP, 3, found=True, for_rank=0, rqseqno=8,
                     seqno=77, work_type=T, prio=0, target_rank=-1,
                     work_len=5, answer_rank=-1, common_len=0,
                     common_server=-1, common_seqno=-1, payload=b"stale",
                     time_on_q=0.0))
    assert ep.of(Tag.SS_UNRESERVE)
    assert not ep.of(Tag.TA_RESERVE_RESP)
    assert 0 in home.rq  # the live entry is untouched


def test_home_forwards_fused_and_confirms():
    home, ep = _mk_server(rank=2)
    home.rq.add(RqEntry(world_rank=0, rqseqno=9, req_types=frozenset([T]),
                        fetch=True))
    home._rfr_out[0] = time.monotonic()
    home._handle(msg(Tag.SS_RFR_RESP, 3, found=True, for_rank=0, rqseqno=9,
                     seqno=77, work_type=T, prio=0, target_rank=-1,
                     work_len=5, answer_rank=-1, common_len=0,
                     common_server=-1, common_seqno=-1, payload=b"fused",
                     time_on_q=0.0))
    (dest, r), = ep.of(Tag.TA_RESERVE_RESP)
    assert dest == 0 and r.rc == ADLB_SUCCESS and r.payload == b"fused"
    (dest, d), = ep.of(Tag.SS_DELIVERED)
    assert dest == 3 and d.seqno == 77 and d.for_rank == 0
    assert 0 not in home.rq


def test_rank_death_consumes_relay_inflight():
    """Requester dies with a remote fused delivery in flight: the holder
    treats the unit as delivered (at-most-once — the payload may already
    have landed) instead of re-enqueueing it."""
    holder, ep = _mk_server(rank=2, on_worker_failure="reclaim")
    _put(holder, b"unit0")
    holder._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=5, req_types=[T],
                       targeted_lookup=False, lookup_type=-1, fetch=1))
    (_, resp), = ep.of(Tag.SS_RFR_RESP)
    holder._handle(msg(Tag.SS_RANK_DEAD, 3, rank=1))
    assert holder.wq.get(resp.seqno) is None  # consumed, not re-queued
    assert not holder._relay_inflight
    assert holder.mem.curr == 0


def test_rank_death_reclaims_plain_pins():
    """Contrast: a classic (non-relay) pin owned by the dead rank IS
    re-enqueued — the PR-2 reclaim path is untouched."""
    holder, ep = _mk_server(rank=2, on_worker_failure="reclaim")
    _put(holder, b"unit0")
    holder._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=5, req_types=[T],
                       targeted_lookup=False, lookup_type=-1, fetch=0))
    (_, resp), = ep.of(Tag.SS_RFR_RESP)
    assert "payload" not in resp.data
    holder._handle(msg(Tag.SS_RANK_DEAD, 3, rank=1))
    unit = holder.wq.get(resp.seqno)
    assert unit is not None and not unit.pinned


def test_duplicate_reserve_frames_dropped():
    """Windowed rqseqno dedup: a replayed frame never pins a second
    unit, fresh rqseqnos (pipeline slots) all park — and an OLDER frame
    that was never processed (cross-connection reorder after a
    reconnect re-send) still parks rather than being mistaken for a
    replay."""
    server, ep = _mk_server(rank=2)
    for rq_id in (1, 2, 2, 1, 3):
        server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=rq_id, req_types=[T],
                           hang=True, fetch=True, prefetch=True))
    assert server.rq.count_for(0) == 3  # rqseqnos 1, 2, 3 each once
    # reorder: rank 1's re-sent frame 2 overtakes its in-flight frame 1
    for rq_id in (2, 1):
        server._handle(msg(Tag.FA_RESERVE, 1, rqseqno=rq_id, req_types=[T],
                           hang=True, fetch=True, prefetch=True))
    assert server.rq.count_for(1) == 2  # both were genuinely unprocessed


def test_stream_idle_note_voided_by_crossing_delivery():
    """An FA_STREAM_IDLE whose in-flight count disagrees with the parked
    entry count (a delivery crossed it on the wire) must NOT mark the
    rank idle — the exhaustion vote would otherwise race the bank."""
    server, ep = _mk_server(rank=2)
    for rq_id in (1, 2):
        server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=rq_id, req_types=[T],
                           hang=True, fetch=True, prefetch=True))
    server._handle(msg(Tag.FA_STREAM_IDLE, 0, slots=[1, 2, 3]))
    assert 0 not in server._stream_idle  # crossed: {1,2} parked, 3 claimed
    server._handle(msg(Tag.FA_STREAM_IDLE, 0, slots=[1, 2]))
    assert 0 in server._stream_idle
    assert server._all_local_apps_parked()
    # a delivery clears the mark
    _put(server, b"unit0")
    assert 0 not in server._stream_idle
    assert not server._all_local_apps_parked()


def test_prefetch_parks_not_idle_block_exhaustion():
    """A rank whose only parked entries are prefetch slots does NOT count
    as parked until it reports idle (it may be computing a banked unit
    whose descendants still need the pool open)."""
    server, ep = _mk_server(rank=2)
    server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=1, req_types=[T],
                       hang=True, fetch=True, prefetch=True))
    assert not server._all_local_apps_parked()
    server._handle(msg(Tag.FA_STREAM_IDLE, 0, slots=[1]))
    assert server._all_local_apps_parked()


def test_common_refcount_exact_after_forfeits():
    """One real get + (refcnt-1) forfeit notes GC the prefix exactly."""
    server, ep = _mk_server(rank=2)
    server._handle(msg(Tag.FA_PUT_COMMON, 0, payload=b"PFX"))
    (_, r), = ep.of(Tag.TA_PUT_COMMON_RESP)
    seqno = r.common_seqno
    server._handle(msg(Tag.FA_BATCH_DONE, 0, common_seqno=seqno, refcnt=3))
    assert len(server.cq) == 1
    server._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=seqno, get_id=1))
    server._handle(msg(Tag.SS_COMMON_FORFEIT, 1, common_seqno=seqno,
                       op="forfeit"))
    assert len(server.cq) == 1
    server._handle(msg(Tag.SS_COMMON_FORFEIT, 2, common_seqno=seqno,
                       op="forfeit"))
    assert len(server.cq) == 0  # 1 get + 2 forfeits == refcnt 3 -> GC'd
    assert server.mem.curr == 0


def test_swept_stream_rearmed_on_idle():
    """Reclaim churn: a rank declared dead has its prefetch entries swept
    with no response; when it resurrects and reports idle, the server
    answers the phantom in-flight slots with ADLB_RETRY so the stream
    re-arms instead of hanging forever."""
    server, ep = _mk_server(rank=2, on_worker_failure="reclaim")
    for rq_id in (1, 2, 3):
        server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=rq_id, req_types=[T],
                           hang=True, fetch=True, prefetch=True))
    server._handle(msg(Tag.SS_RANK_DEAD, 3, rank=0))
    assert server.rq.count_for(0) == 0 and 0 in server._swept_streams
    # the rank talks again (resurrection) and reports its stale view
    server._handle(msg(Tag.FA_STREAM_IDLE, 0, slots=[1, 2, 3]))
    from adlb_tpu.types import ADLB_RETRY
    retries = [m for _, m in ep.of(Tag.TA_RESERVE_RESP)
               if m.rc == ADLB_RETRY]
    assert len(retries) == 3
    assert sorted(m.rqseqno for _, m in ep.of(Tag.TA_RESERVE_RESP)
                  if m.rc == ADLB_RETRY) == [1, 2, 3]
    assert 0 not in server._stream_idle  # re-arms park first, then idle


def _targeted_close(ctx):
    if ctx.rank == 0:
        for i in range(10):
            ctx.put(struct.pack("<q", i), T, target_rank=1)
        for i in range(10, 20):
            ctx.put(struct.pack("<q", i), T)
    got = []
    ws = ctx.get_work_stream([T], depth=3)
    for w in ws:
        got.append(struct.unpack("<q", w.payload)[0])
        if ctx.rank == 1 and len(got) >= 1:
            ws.close()  # banked targeted units must re-pool TARGETED
            break
    if ctx.rank != 1:
        return got
    with ctx.get_work_stream([T], depth=3) as ws2:
        for w in ws2:
            got.append(struct.unpack("<q", w.payload)[0])
    return got


def test_stream_close_preserves_targeting():
    """Fused responses carry target_rank, so a stream closing early
    re-puts banked targeted units still targeted — no other rank may
    ever run them."""
    res = run_world(3, 2, [T], _targeted_close,
                    cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    per_rank = dict(res.app_results)
    all_units = sorted(x for v in per_rank.values() for x in v)
    assert all_units == list(range(20))
    # units 0..9 were targeted at rank 1: nobody else may have run them
    assert sorted(x for x in per_rank[1] if x < 10) == list(range(10))
    assert all(x >= 10 for r in (0, 2) for x in per_rank[r])


def test_stream_iterate_after_close_stops():
    """Iterating past close() must raise StopIteration, not spin: the
    cancel dropped the parked reserves unanswered, so inflight never
    drains on its own."""
    def app(ctx):
        if ctx.rank == 0:
            for i in range(12):
                ctx.put(struct.pack("<q", i), T)
        got = []
        ws = ctx.get_work_stream([T], depth=3)
        for w in ws:  # NO break after close: the loop itself must end
            got.append(struct.unpack("<q", w.payload)[0])
            if ctx.rank == 1:
                ws.close()
        return got

    res = run_world(3, 2, [T], app, cfg=Config(exhaust_check_interval=0.2),
                    timeout=60.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == list(range(12))


def test_swept_stream_rearmed_even_with_no_parked_entries():
    """Rank death can catch a stream whose slots were all already
    matched (responses lost with the connection): remove_rank returns
    nothing, but the phantom re-arm must still fire on the resurrected
    rank's idle note."""
    server, ep = _mk_server(rank=2, on_worker_failure="reclaim")
    server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=1, req_types=[T],
                       hang=True, fetch=True, prefetch=True))
    _put(server, b"unit0")  # satisfies the entry; response "lost"
    assert server.rq.count_for(0) == 0
    server._handle(msg(Tag.SS_RANK_DEAD, 3, rank=0))
    server._handle(msg(Tag.FA_STREAM_IDLE, 0, slots=[1]))
    from adlb_tpu.types import ADLB_RETRY
    retries = [m for _, m in ep.of(Tag.TA_RESERVE_RESP)
               if m.rc == ADLB_RETRY]
    assert len(retries) == 1


def test_stream_cancel_drops_prefetch_entries():
    server, ep = _mk_server(rank=2)
    server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=1, req_types=[T],
                       hang=True, fetch=True, prefetch=True))
    server._handle(msg(Tag.FA_RESERVE, 0, rqseqno=2, req_types=[T],
                       hang=True, fetch=True, prefetch=True))
    server._handle(msg(Tag.FA_STREAM_CANCEL, 0))
    assert server.rq.count_for(0) == 0
    assert ep.of(Tag.TA_STREAM_CANCEL_RESP)
