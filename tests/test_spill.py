"""The disk spill tier (adlb_tpu/runtime/spill.py + server hooks).

* **Store mechanics** — crc-framed put/take byte-identity, corruption
  detection, discard, dead-space compaction.
* **Server residency lattice** — a handler-driven Server over a tiny
  memory cap: puts over the watermark spill the coldest/largest parked
  payloads (resident vs spilled accounting splits), delivery faults
  them back in byte-identical, quarantine records fault in before
  capturing the payload, and a dead targeted rank's spilled units
  release their spill-file entries.
* **Acceptance** — a put storm over the soft watermark against a
  hard-watermarked cap completes with ZERO ADLB_BACKOFF when
  ``spill_dir`` is set, every payload fetched back byte-identical.
"""

import hashlib
import struct
import time

import pytest

from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.spill import SpillCorruption, SpillStore
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS

T = 1


# -------------------------------------------------------------------- store


def test_store_roundtrip_and_discard(tmp_path):
    s = SpillStore(str(tmp_path), 2)
    blobs = {i: bytes([i]) * (100 + i) for i in range(8)}
    for i, b in blobs.items():
        s.put(i, b)
    assert len(s) == 8 and s.live_bytes == sum(map(len, blobs.values()))
    assert s.take(3) == blobs[3]
    assert 3 not in s
    assert s.discard(5) == len(blobs[5])
    assert s.discard(5) == 0  # idempotent
    for i in (0, 1, 2, 4, 6, 7):
        assert s.take(i) == blobs[i]
    assert s.live_bytes == 0
    s.close()


def test_store_detects_corruption(tmp_path):
    s = SpillStore(str(tmp_path), 0)
    s.put(7, b"payload-bytes" * 10)
    # flip one byte of the record body on disk
    with open(s.path, "r+b") as f:
        f.seek(20)
        c = f.read(1)
        f.seek(20)
        f.write(bytes([c[0] ^ 0xFF]))
    with pytest.raises(SpillCorruption):
        s.take(7)
    s.close()


def test_store_compacts_dead_space(tmp_path):
    import os

    s = SpillStore(str(tmp_path), 0)
    blob = b"z" * (1 << 20)
    for i in range(12):
        s.put(i, blob)
    for i in range(10):
        s.take(i)  # 10 MiB dead vs 2 MiB live -> compaction triggers
    assert s.compactions >= 1
    assert os.path.getsize(s.path) < 4 * len(blob)
    assert s.take(10) == blob and s.take(11) == blob  # index survived
    s.close()


# -------------------------------------------------- server residency lattice


def _mini_server(tmp_path, cap=4096, **cfg_kw):
    world = WorldSpec(nranks=4, nservers=2, types=(T,))
    fabric = InProcFabric(4)
    cfg = Config(max_malloc_per_server=cap, mem_soft_frac=0.5,
                 spill_dir=str(tmp_path), **cfg_kw)
    return Server(world, cfg, fabric.endpoint(2)), fabric


def _put(srv, payload, src=0, target=-1):
    srv._handle(msg(Tag.FA_PUT, src, payload=payload, work_type=T, prio=0,
                    target_rank=target, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1))


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def test_put_over_watermark_spills_cold_payloads(tmp_path):
    srv, fabric = _mini_server(tmp_path, cap=4096)
    blob = b"a" * 1500
    _put(srv, blob)          # resident: 1500 / soft 2048
    time.sleep(0.01)         # strictly older time_stamp
    _put(srv, b"b" * 1500)   # resident: 3000 > soft -> next put spills
    _put(srv, b"c" * 1500)
    assert srv.mem.spilled > 0, "nothing spilled over the watermark"
    assert srv.mem.curr + srv.mem.spilled == 4500
    assert srv.mem.curr <= 0.5 * 4096 + 1500
    spilled = [u for u in srv.wq.units() if u.spilled]
    assert spilled and all(u.payload == b"" for u in spilled)
    assert all(u.spill_len == 1500 for u in spilled)
    assert all(u.work_len == 1500 for u in spilled)  # metadata keeps size
    # every accepted (no backoff/reject rcs)
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP]
    assert [m.rc for m in resp] == [ADLB_SUCCESS] * 3


def test_delivery_faults_spilled_payload_back_in(tmp_path):
    srv, fabric = _mini_server(tmp_path, cap=4096)
    payloads = [bytes([65 + i]) * 1500 for i in range(3)]
    for p in payloads:
        _put(srv, p)
        time.sleep(0.005)
    assert srv.mem.spilled > 0
    got = []
    for rq in range(3):
        srv._handle(msg(Tag.FA_RESERVE, 1, req_types=[T], hang=True,
                        rqseqno=rq, fetch=1))
        for m in _drain(fabric, 1):
            if m.tag is Tag.TA_RESERVE_RESP and m.rc == ADLB_SUCCESS:
                got.append(bytes(m.payload))
    assert sorted(got) == sorted(payloads), "fault-in not byte-identical"
    assert srv.mem.spilled == 0 and len(srv.spill) == 0
    assert srv.mem.curr == 0  # all consumed
    assert srv.metrics.value("spill_faultins") >= 1


def test_quarantine_record_faults_in_spilled_payload(tmp_path):
    srv, fabric = _mini_server(tmp_path, cap=4096, max_unit_retries=1,
                               on_worker_failure="reclaim")
    blob = b"q" * 1500
    _put(srv, blob)
    time.sleep(0.005)
    _put(srv, b"r" * 1500)
    _put(srv, b"s" * 1500)
    victim = next(u for u in srv.wq.units() if u.spilled)
    victim.attempts = 5  # budget exhausted: next failure quarantines
    srv._quarantine_unit(victim, in_wq=True)
    [rec] = srv.quarantine
    assert rec["payload"] in (blob, b"r" * 1500, b"s" * 1500)
    assert len(rec["payload"]) == 1500, "quarantined a spilled stub"
    assert victim.seqno not in srv.spill


def test_dead_target_releases_spilled_entry(tmp_path):
    srv, fabric = _mini_server(tmp_path, cap=4096,
                               on_worker_failure="reclaim")
    _put(srv, b"t" * 1500, target=1)
    time.sleep(0.005)
    _put(srv, b"u" * 1500)
    _put(srv, b"v" * 1500)
    assert srv.mem.spilled > 0
    spilled_total = srv.mem.spilled
    srv._handle(Msg(tag=Tag.PEER_EOF, src=1))  # rank 1 dies
    # its targeted unit is dropped; if it was spilled, the spill entry
    # and accounting released with it
    assert srv.mem.spilled <= spilled_total
    assert srv.mem.curr + srv.mem.spilled == sum(
        u.payload_len for u in srv.wq.units()
    )


def test_checkpoint_faults_in_all(tmp_path):
    srv, fabric = _mini_server(tmp_path, cap=4096)
    for c in b"xyz":
        _put(srv, bytes([c]) * 1500)
        time.sleep(0.005)
    assert srv.mem.spilled > 0
    n = srv._write_checkpoint_shard(str(tmp_path / "ck"))
    assert n == 3
    assert srv.mem.spilled == 0  # everything resident again
    from adlb_tpu.runtime import checkpoint

    units, _ = checkpoint.load_shard(str(tmp_path / "ck"), 2, srv.world)
    assert sorted(len(u["payload"]) for u in units) == [1500] * 3


# --------------------------------------------------------------- acceptance


_N_STORM = 60
_PAY = 4096


def _storm_app(ctx):
    if ctx.rank == 0:
        sent = {}
        for i in range(_N_STORM):
            p = struct.pack("<q", i) + hashlib.sha256(
                str(i).encode()).digest() * (_PAY // 32)
            assert ctx.put(p, T) == ADLB_SUCCESS
            sent[i] = hashlib.sha256(p).hexdigest()
        return {"sent": sent,
                "backoffs": ctx._c.metrics.value("put_backoffs"),
                "retries": ctx._c.metrics.value("put_retries")}
    got = {}
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        i = struct.unpack("<q", w.payload[:8])[0]
        got[i] = hashlib.sha256(w.payload).hexdigest()
        time.sleep(0.002)


def test_put_storm_over_watermark_zero_backoffs(tmp_path):
    """The spill acceptance world: ~240 KiB of puts through a 64 KiB
    hard-watermarked cap. With spill_dir set the storm completes with 0
    ADLB_BACKOFF rcs and every spilled payload fetches back
    byte-identical."""
    res = spawn_world(
        3, 2, [T], _storm_app,
        cfg=Config(max_malloc_per_server=64 << 10, mem_soft_frac=0.7,
                   mem_hard_frac=0.8, spill_dir=str(tmp_path),
                   exhaust_check_interval=0.25),
        timeout=120.0,
    )
    prod = res.app_results[0]
    got = {}
    for r, v in res.app_results.items():
        if r != 0:
            got.update(v)
    assert len(got) == _N_STORM
    assert prod["backoffs"] == 0, "spill tier still answered BACKOFF"
    assert prod["retries"] == 0, "spill tier still rejected puts"
    assert all(got[i] == h for i, h in prod["sent"].items()), \
        "spilled payload came back different"
