"""Binary wire codec round-trips (the native-client protocol)."""

import pickle

import pytest

from adlb_tpu.runtime.codec import (
    FIELDS,
    WIRE_TAG,
    decode_binary,
    encodable,
    encode_binary,
)
from adlb_tpu.runtime.messages import Msg, Tag, msg


CASES = [
    msg(Tag.FA_PUT, 3, payload=b"\x00\xffhello", work_type=2, prio=-7,
        target_rank=-1, answer_rank=0, common_len=0, common_server=-1,
        common_seqno=-1),
    msg(Tag.TA_PUT_RESP, 5, rc=1, hint=-1),
    msg(Tag.FA_RESERVE, 0, req_types=[1, 2, 9], hang=True, rqseqno=42),
    msg(Tag.FA_RESERVE, 0, req_types=None, hang=False, rqseqno=1),
    msg(Tag.TA_RESERVE_RESP, 6, rc=1, work_type=1, prio=3,
        handle=[7, 5, 0, -1, -1], work_len=12, answer_rank=-1),
    msg(Tag.TA_GET_RESERVED_RESP, 6, rc=1, payload=b"", time_on_q=0.125),
    msg(Tag.FA_INFO_GET, 2, key=7),
    msg(Tag.TA_INFO_GET_RESP, 6, rc=1, value=3.5),
    msg(Tag.TA_ABORT, 6, code=-2),
    msg(Tag.FA_LOCAL_APP_DONE, 1),
    # batched put delta (round 4): parallel per-unit lists so streaming
    # producers reach the balancer within one rate-limit gap
    msg(Tag.SS_STATE_DELTA, 4, seqnos=[11, 12, 13], work_types=[1, 1, 2],
        prios=[0, -3, 9], work_lens=[8, 0, 4096], nbytes=4104),
]


@pytest.mark.parametrize("m", CASES, ids=lambda m: m.tag.name)
def test_roundtrip(m):
    assert encodable(m)
    body = encode_binary(m)
    assert body[0] == 0x01
    out = decode_binary(body)
    assert out.tag is m.tag
    assert out.src == m.src
    expect = {k: v for k, v in m.data.items() if v is not None}
    assert out.data == expect


def test_pickle_discriminator():
    """Pickled frames must never look like binary frames."""
    for m in CASES:
        body = pickle.dumps(m, protocol=pickle.HIGHEST_PROTOCOL)
        assert body[0] == 0x80


def test_wire_ids_total_and_unique():
    assert set(WIRE_TAG) == set(Tag), "every tag needs a wire id"
    assert len(set(WIRE_TAG.values())) == len(WIRE_TAG)
    ids = [fid for fid, _ in FIELDS.values()]
    assert len(set(ids)) == len(ids)


def test_pickled_abort_carries_module_path():
    """The C client (libadlb.cpp reader_loop) honors a pickled frame as
    the TA_ABORT fan-out only when the body contains the pickled Msg's
    module path — this pins the invariant that heuristic depends on, so
    a module rename fails here instead of silently breaking abort
    delivery to native clients that a Python server hasn't learned are
    binary peers."""
    body = pickle.dumps(
        msg(Tag.TA_ABORT, 4, code=-2), protocol=pickle.HIGHEST_PROTOCOL
    )
    assert body[0] == 0x80
    assert b"adlb_tpu" in body
