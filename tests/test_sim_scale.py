"""Sanity tests for the scaling simulator (scripts/sim_scale.py).

The simulator backs BASELINE.md's 256-rank extrapolation, so its core
properties need pinning: work conservation (makespan covers all tasks),
determinism, and the structural result — per-unit pull saturates the hot
server's reactor while the batched pump does not.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

from sim_scale import Sim  # noqa: E402


def test_conservation_and_determinism():
    a = Sim(nservers=4, n_tasks=200, mode="steal").run()
    b = Sim(nservers=4, n_tasks=200, mode="steal").run()
    assert a == b  # fully deterministic: same params, same history
    # makespan must cover at least the serialized hot-server service time
    assert a["makespan"] > 0 and a["tasks_per_sec"] > 0


def test_steal_hot_reactor_ceiling():
    """Per-unit pull: ~2 hot-server messages per unit caps throughput
    near 1/(2*t_svc) regardless of worker count."""
    t_svc = 120e-6
    small = Sim(nservers=16, t_svc=t_svc, mode="steal").run()
    big = Sim(nservers=64, t_svc=t_svc, mode="steal").run()
    ceiling = 1.0 / (2 * t_svc)
    assert big["tasks_per_sec"] < ceiling * 1.05
    # adding 4x the workers buys almost nothing once saturated
    assert big["tasks_per_sec"] < small["tasks_per_sec"] * 1.5


def test_pump_beats_pull_at_scale():
    steal = Sim(nservers=32, mode="steal").run()
    tpu = Sim(nservers=32, mode="tpu").run()
    assert tpu["tasks_per_sec"] > 1.5 * steal["tasks_per_sec"]


def test_shared_core_reproduces_measured_curve_both_columns():
    """The shared-core mode's whole claim is calibration: with the fitted
    constants (t_serve_shared, t_wake_per_busy, wake_busy_floor —
    re-derived by scripts/fit_sim.py against the round-5 curve per the
    round-4 verdict item 3) it must keep reproducing BOTH columns of the
    measured scripts/scaling_curve.py run (2026-07-31, BASELINE.md 'sim
    vs measured') within the host's ±15-30%% draw-noise band. Worst
    fitted cell is 11.1%% (tpu@32r); the pin catches parameter drift —
    including the measured 128-rank rate inversion (0.938), which the
    fit reproduces rather than smooths away."""
    from sim_scale import MEASURED_CURVE

    for s, (wt, m_steal, m_tpu) in MEASURED_CURVE.items():
        r_s = Sim(nservers=s, mode="steal", shared_core=True,
                  work_time=wt).run()
        r_t = Sim(nservers=s, mode="tpu", shared_core=True,
                  work_time=wt).run()
        assert 0.80 < r_s["tasks_per_sec"] / m_steal < 1.20, (s, r_s, m_steal)
        assert 0.80 < r_t["tasks_per_sec"] / m_tpu < 1.20, (s, r_t, m_tpu)


def test_shared_core_sidecar_tax_charged():
    """The tpu sidecar's planning CPU must be charged to the shared core:
    zeroing it can only help tpu throughput."""
    with_tax = Sim(nservers=16, mode="tpu", shared_core=True).run()
    no_tax = Sim(nservers=16, mode="tpu", shared_core=True,
                 t_plan_per_server=0.0).run()
    assert no_tax["tasks_per_sec"] >= with_tax["tasks_per_sec"]
