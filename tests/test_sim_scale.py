"""Sanity tests for the scaling simulator (scripts/sim_scale.py).

The simulator backs BASELINE.md's 256-rank extrapolation, so its core
properties need pinning: work conservation (makespan covers all tasks),
determinism, and the structural result — per-unit pull saturates the hot
server's reactor while the batched pump does not.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

from sim_scale import Sim  # noqa: E402


def test_conservation_and_determinism():
    a = Sim(nservers=4, n_tasks=200, mode="steal").run()
    b = Sim(nservers=4, n_tasks=200, mode="steal").run()
    assert a == b  # fully deterministic: same params, same history
    # makespan must cover at least the serialized hot-server service time
    assert a["makespan"] > 0 and a["tasks_per_sec"] > 0


def test_steal_hot_reactor_ceiling():
    """Per-unit pull: ~2 hot-server messages per unit caps throughput
    near 1/(2*t_svc) regardless of worker count."""
    t_svc = 120e-6
    small = Sim(nservers=16, t_svc=t_svc, mode="steal").run()
    big = Sim(nservers=64, t_svc=t_svc, mode="steal").run()
    ceiling = 1.0 / (2 * t_svc)
    assert big["tasks_per_sec"] < ceiling * 1.05
    # adding 4x the workers buys almost nothing once saturated
    assert big["tasks_per_sec"] < small["tasks_per_sec"] * 1.5


def test_pump_beats_pull_at_scale():
    steal = Sim(nservers=32, mode="steal").run()
    tpu = Sim(nservers=32, mode="tpu").run()
    assert tpu["tasks_per_sec"] > 1.5 * steal["tasks_per_sec"]


def test_shared_core_reproduces_measured_steal_column():
    """The shared-core mode's whole claim is calibration: with the fitted
    (t_serve_shared, t_wake_per_proc) it must keep reproducing the
    MEASURED steal column of scripts/scaling_curve.py (2026-07-30 run,
    BASELINE.md 'sim vs measured') within the host's noise band. The tpu
    column is intentionally NOT pinned — the model over-predicts it at
    >=64 ranks (no wakeup-contention asymmetry; see BASELINE.md)."""
    measured = {4: (0.008, 1589.4), 8: (0.008, 3014.9),
                16: (0.008, 4673.6), 32: (0.024, 2998.9)}
    for s, (wt, m) in measured.items():
        r = Sim(nservers=s, mode="steal", shared_core=True,
                work_time=wt).run()
        assert 0.8 < r["tasks_per_sec"] / m < 1.25, (s, r, m)


def test_shared_core_sidecar_tax_charged():
    """The tpu sidecar's planning CPU must be charged to the shared core:
    zeroing it can only help tpu throughput."""
    with_tax = Sim(nservers=16, mode="tpu", shared_core=True).run()
    no_tax = Sim(nservers=16, mode="tpu", shared_core=True,
                 t_plan_per_server=0.0).run()
    assert no_tax["tasks_per_sec"] >= with_tax["tasks_per_sec"]
