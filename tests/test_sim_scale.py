"""Sanity tests for the scaling simulator (scripts/sim_scale.py).

The simulator backs BASELINE.md's 256-rank extrapolation, so its core
properties need pinning: work conservation (makespan covers all tasks),
determinism, and the structural result — per-unit pull saturates the hot
server's reactor while the batched pump does not.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

from sim_scale import Sim  # noqa: E402


def test_conservation_and_determinism():
    a = Sim(nservers=4, n_tasks=200, mode="steal").run()
    b = Sim(nservers=4, n_tasks=200, mode="steal").run()
    assert a == b  # fully deterministic: same params, same history
    # makespan must cover at least the serialized hot-server service time
    assert a["makespan"] > 0 and a["tasks_per_sec"] > 0


def test_steal_hot_reactor_ceiling():
    """Per-unit pull: ~2 hot-server messages per unit caps throughput
    near 1/(2*t_svc) regardless of worker count."""
    t_svc = 120e-6
    small = Sim(nservers=16, t_svc=t_svc, mode="steal").run()
    big = Sim(nservers=64, t_svc=t_svc, mode="steal").run()
    ceiling = 1.0 / (2 * t_svc)
    assert big["tasks_per_sec"] < ceiling * 1.05
    # adding 4x the workers buys almost nothing once saturated
    assert big["tasks_per_sec"] < small["tasks_per_sec"] * 1.5


def test_pump_beats_pull_at_scale():
    steal = Sim(nservers=32, mode="steal").run()
    tpu = Sim(nservers=32, mode="tpu").run()
    assert tpu["tasks_per_sec"] > 1.5 * steal["tasks_per_sec"]
