"""Fused reserve+get (get_work): one round trip per unit when local and
prefix-free, transparent fallback to handle+Get for remote holders and
batch-common units."""

import struct

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

T = 1


def _pc(ctx):
    if ctx.rank == 0:
        for i in range(60):
            ctx.iput(struct.pack("<q", i), T, work_prio=i % 5)
        ctx.flush_puts()
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        assert w.work_type == T and w.time_on_q >= 0.0
        got.append(struct.unpack("<q", w.payload)[0])


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_get_work_conservation(mode):
    cfg = Config(balancer=mode, exhaust_check_interval=0.2,
                 balancer_max_tasks=128, balancer_max_requesters=16)
    res = run_world(4, 2, [T], _pc, cfg=cfg)
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(60))


def test_get_work_native_servers():
    cfg = Config(server_impl="native", exhaust_check_interval=0.2)
    res = spawn_world(4, 2, [T], _pc, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(60))


def test_get_work_falls_back_for_common_prefix():
    """Batch-common units cannot be fused (the prefix may live on another
    server); get_work must still deliver the full payload via the handle
    path."""
    common = b"HDR:"

    def app(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(common)
            for i in range(8):
                ctx.put(struct.pack("<q", i), T)
            ctx.end_batch_put()
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                return got
            assert w.payload.startswith(common)
            got.append(struct.unpack("<q", w.payload[len(common):])[0])

    res = run_world(3, 2, [T], app, cfg=Config(exhaust_check_interval=0.2))
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(8))


def _pc_batch(ctx):
    if ctx.rank == 0:
        for i in range(60):
            # the first four are TARGETED at rank 0: nobody else can take
            # them, so rank 0's first post-flush batch is deterministically
            # multi-unit (the saw_multi check is otherwise timing-dependent)
            tgt = 0 if i < 4 else -1
            ctx.iput(struct.pack("<q", i), T, work_prio=i % 5,
                     target_rank=tgt)
        ctx.flush_puts()
    got = []
    saw_multi = 0
    while True:
        rc, ws = ctx.get_work_batch([T], max_units=4)
        if rc != ADLB_SUCCESS:
            return got, saw_multi
        assert 1 <= len(ws) <= 4
        saw_multi += len(ws) > 1
        for w in ws:
            assert w.work_type == T and w.time_on_q >= 0.0
            got.append(struct.unpack("<q", w.payload)[0])


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_get_work_batch_conservation(mode):
    """Batched fused fetch: every unit delivered exactly once, batches
    capped at max_units, and at least one multi-unit batch observed (the
    producer runs ahead, so local inventory exists)."""
    cfg = Config(balancer=mode, exhaust_check_interval=0.2,
                 balancer_max_tasks=128, balancer_max_requesters=16)
    res = run_world(4, 2, [T], _pc_batch, cfg=cfg)
    got = sorted(x for v in res.app_results.values() for x in v[0])
    assert got == list(range(60))
    assert sum(v[1] for v in res.app_results.values()) > 0


def test_get_work_batch_native_servers():
    """Native daemons speak the batch response too (blist/flist TLV
    kinds): every unit delivered exactly once, with multi-unit batches
    observed when local inventory runs deep."""
    cfg = Config(server_impl="native", exhaust_check_interval=0.2)
    res = spawn_world(4, 2, [T], _pc_batch, cfg=cfg, timeout=90.0)
    got = sorted(x for v in res.app_results.values() for x in (v or [[]])[0])
    assert got == list(range(60))
    assert sum(v[1] for v in res.app_results.values() if v) > 0


def test_get_work_batch_common_prefix_falls_back():
    common = b"HDR:"

    def app(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(common)
            for i in range(8):
                ctx.put(struct.pack("<q", i), T)
            ctx.end_batch_put()
        got = []
        while True:
            rc, ws = ctx.get_work_batch([T], max_units=4)
            if rc != ADLB_SUCCESS:
                return got
            for w in ws:
                assert w.payload.startswith(common)
                got.append(struct.unpack("<q", w.payload[len(common):])[0])

    res = run_world(3, 2, [T], app, cfg=Config(exhaust_check_interval=0.2))
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(8))


def test_get_work_remote_steal_fallback():
    """A parked get_work satisfied through a cross-server RFR handoff falls
    back to fetching from the remote holder."""

    def app(ctx):
        if ctx.rank == 0:
            import time

            time.sleep(0.15)  # let other ranks park first
            for i in range(12):
                ctx.put(struct.pack("<q", i), T)  # round-robin over servers
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                return got
            got.append(struct.unpack("<q", w.payload)[0])

    res = run_world(
        4, 2, [T], app,
        cfg=Config(exhaust_check_interval=0.25, qmstat_interval=0.02),
    )
    got = sorted(x for v in res.app_results.values() for x in (v or []))
    assert got == list(range(12))
