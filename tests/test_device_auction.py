"""On-device auction tier, unit-level: the fused candidate-gen -> merge
-> auction shard_map program (`_build_plan_fn`) against the retained
host twin, on the SAME resident solver state.

tests/test_sharded_parity.py proves both tiers against the single-device
greedy through the full ingest path; these tests pin the tighter
contract the twins share — the device tier's committed [T, C+1]
assignment matrix and extracted pair list must equal the host tier's
EXACTLY (not just matched-set-and-score: both tiers rank the same
rank-keyed gids over the same requester windows, so any divergence at
all is a commit-threshold or tie-break bug) — plus the fixed-shape
guarantee at the 10,000-server shape: live counts, task deltas and
churn must never retrace the one compiled program.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (forces the 8-device CPU platform)

import jax
from jax.sharding import Mesh

from adlb_tpu.balancer.distributed import DistributedAssignmentSolver

TYPES = (1, 2, 3, 4)

# the multi-job arm: 3 planned namespaces, skewed weights — the bias
# lands in the packed priorities (jobdim.weight_bias), so it is part of
# the exact-pair-list bar below, not a separate score check
MAX_JOBS = 3
JOB_WEIGHTS = {1: 4.0, 2: 0.2}


def _mesh(ndev):
    return Mesh(np.array(jax.devices()[:ndev]), axis_names=("s",))


def _rand_job(rng, J):
    """Mostly default namespace, a spread over planned jobs, and a rare
    overflow id (== J) exercising the planner-invisible pack skip."""
    if J <= 1 or rng.random() < 0.4:
        return 0
    if rng.random() < 0.08:
        return J
    return int(rng.integers(1, J))


def _random_snapshots(rng, nservers, ntasks, nreqs, ntypes, J=1):
    types = TYPES[:ntypes]
    snapshots = {}
    seq = 0
    for s in range(100, 100 + nservers):
        tasks = []
        for _ in range(rng.integers(0, ntasks + 1)):
            seq += 1
            tk = (seq, int(rng.choice(types)), int(rng.integers(-9, 10)), 8)
            jb = _rand_job(rng, J)
            tasks.append(tk + (jb,) if jb else tk)
        tasks.sort(key=lambda t: -t[2])
        reqs = []
        for r in range(rng.integers(0, nreqs + 1)):
            rq = (
                (s - 100) * 50 + r,
                int(rng.integers(1, 1000)),
                None if rng.random() < 0.25
                else sorted({int(rng.choice(types))
                             for _ in range(rng.integers(1, 3))}),
            )
            jb = _rand_job(rng, J)
            reqs.append(rq + (0, jb) if jb else rq)
        snapshots[s] = {"tasks": tasks, "reqs": reqs}
    return snapshots


def _twin_solvers(mesh, ntypes, nservers, rounds=64, max_jobs=1,
                  job_weights=None):
    kw = dict(
        types=TYPES[:ntypes], max_tasks_per_server=10, max_requesters=5,
        mesh=mesh, rounds=rounds,
        servers_per_device=max(1, -(-nservers // mesh.devices.size)),
        max_jobs=max_jobs, job_weights=job_weights,
    )
    return (DistributedAssignmentSolver(auction="device", **kw),
            DistributedAssignmentSolver(auction="host", **kw))


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_device_pairs_equal_host_pairs_exactly(ndev):
    """Same snapshots through both tiers: the extracted pair LISTS are
    identical — stronger than the matched-set-and-score parity bar."""
    mesh = _mesh(ndev)
    rng = np.random.default_rng(7000 + ndev)
    for trial in range(6):
        ntypes = int(rng.integers(1, len(TYPES) + 1))
        nservers = max(ndev, int(rng.integers(1, 4)) * ndev)
        dev, host = _twin_solvers(mesh, ntypes, nservers)
        snaps = _random_snapshots(
            rng, nservers=nservers, ntasks=8, nreqs=4, ntypes=ntypes)
        assert dev.solve(snaps, None) == host.solve(snaps, None)


@pytest.mark.parametrize("ndev", [2, 8])
def test_device_tier_tracks_host_across_mutating_rounds(ndev):
    """Incremental rounds — task deltas, req churn, a vanished server —
    keep the tiers pair-identical round after round (the device tier
    re-derives from resident state; the host tier patches its merged
    candidate lists). Also pins zero-commit rounds: when every
    requester is satisfied or incompatible, both return empty."""
    mesh = _mesh(ndev)
    rng = np.random.default_rng(8100 + ndev)
    nservers = 2 * ndev
    dev, host = _twin_solvers(mesh, len(TYPES), nservers)
    snaps = _random_snapshots(
        rng, nservers=nservers, ntasks=6, nreqs=3, ntypes=len(TYPES))
    seq = [10**6]
    for rnd in range(6):
        assert dev.solve(snaps, None) == host.solve(snaps, None)
        # mutate: one server gains a task burst, one loses its reqs,
        # and on round 3 a server vanishes entirely (elastic drain)
        ranks = sorted(snaps)
        burst_at = snaps[ranks[rnd % len(ranks)]]
        for _ in range(3):
            seq[0] += 1
            burst_at["tasks"].append(
                (seq[0], int(rng.choice(TYPES)),
                 int(rng.integers(-9, 10)), 8))
        burst_at["tasks"].sort(key=lambda t: -t[2])
        snaps[ranks[(rnd + 1) % len(ranks)]]["reqs"] = []
        if rnd == 3 and len(snaps) > 1:
            del snaps[ranks[-1]]
    # zero-requester world: both tiers plan nothing
    for snap in snaps.values():
        snap["reqs"] = []
    assert dev.solve(snaps, None) == []
    assert host.solve(snaps, None) == []


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_device_pairs_equal_host_pairs_multi_job(ndev):
    """Weighted multi-job worlds through both tiers: the composite
    (job, type) axis, the weight bias folded into packed priorities,
    and overflow-id skips must all reproduce EXACTLY — pair lists, not
    just matched sets."""
    mesh = _mesh(ndev)
    rng = np.random.default_rng(9300 + ndev)
    for trial in range(6):
        ntypes = int(rng.integers(1, len(TYPES) + 1))
        nservers = max(ndev, int(rng.integers(1, 4)) * ndev)
        dev, host = _twin_solvers(mesh, ntypes, nservers,
                                  max_jobs=MAX_JOBS,
                                  job_weights=JOB_WEIGHTS)
        snaps = _random_snapshots(
            rng, nservers=nservers, ntasks=8, nreqs=4, ntypes=ntypes,
            J=MAX_JOBS)
        assert dev.solve(snaps, None) == host.solve(snaps, None)


@pytest.mark.parametrize("ndev", [2, 8])
def test_multi_job_tiers_track_across_churn_and_reweight(ndev):
    """Churn mid-sweep on the job arm: task bursts land in random
    namespaces, a server vanishes at round 3, and round 4 swaps the
    live bias vector on BOTH tiers (the set_job_bias fan-out) — every
    round's pair lists stay identical."""
    mesh = _mesh(ndev)
    rng = np.random.default_rng(9400 + ndev)
    nservers = 2 * ndev
    dev, host = _twin_solvers(mesh, len(TYPES), nservers,
                              max_jobs=MAX_JOBS, job_weights=JOB_WEIGHTS)
    snaps = _random_snapshots(
        rng, nservers=nservers, ntasks=6, nreqs=3, ntypes=len(TYPES),
        J=MAX_JOBS)
    seq = [10**6]
    for rnd in range(6):
        assert dev.solve(snaps, None) == host.solve(snaps, None)
        ranks = sorted(snaps)
        burst_at = snaps[ranks[rnd % len(ranks)]]
        for _ in range(3):
            seq[0] += 1
            tk = (seq[0], int(rng.choice(TYPES)),
                  int(rng.integers(-9, 10)), 8)
            jb = _rand_job(rng, MAX_JOBS)
            burst_at["tasks"].append(tk + (jb,) if jb else tk)
        burst_at["tasks"].sort(key=lambda t: -t[2])
        snaps[ranks[(rnd + 1) % len(ranks)]]["reqs"] = []
        if rnd == 3 and len(snaps) > 1:
            del snaps[ranks[-1]]
        if rnd == 4:
            for sol in (dev, host):
                assert sol.set_job_bias({1: 0.5, 2: 6.0})


def test_no_retrace_at_10k_shape_multi_job():
    """The job column must not cost compiles either: at the 10k-server
    shape with a composite (job, type) axis, deltas, churn and
    namespace-hopping bursts reuse the ONE compiled program."""
    mesh = _mesh(8)
    rng = np.random.default_rng(199)
    sol = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=4, max_requesters=2,
        mesh=mesh, rounds=16, servers_per_device=1250, auction="device",
        max_jobs=2, job_weights={1: 3.0},
    )
    assert sol.S == 10000
    snaps = {}
    seq = 0
    for s in range(100, 100 + 256):
        seq += 4
        jb = _rand_job(rng, 2)
        tk = (seq, int(rng.choice(TYPES)), int(rng.integers(-9, 10)), 8)
        rq = (s * 50, 1, [int(rng.choice(TYPES))])
        snaps[s] = {
            "tasks": [tk + (jb,) if jb else tk],
            "reqs": [rq + (0, jb) if jb else rq] if s % 2 else [],
        }
    sol.solve(snaps, None)
    for rnd in range(3):
        victim = sorted(snaps)[rnd]
        del snaps[victim]
        fresh = 20000 + rnd
        snaps[fresh] = {
            "tasks": [(10**7 + rnd, int(rng.choice(TYPES)), 5, 8, 1)],
            "reqs": [(fresh * 50, 1, None, 0, 1)],
        }
        seq += 1
        first = snaps[sorted(snaps)[0]]
        first["tasks"] = (first["tasks"] + [
            (seq, int(rng.choice(TYPES)), int(rng.integers(-9, 10)), 8)
        ])[: sol.K]
        sol.solve(snaps, None)
    assert sol._plan_fn._cache_size() == 1


def test_no_retrace_at_10k_shape():
    """The 10,000-server shape (ISSUE 18 acceptance): the fused device
    program compiles ONCE and every subsequent plan — different live
    counts, deltas, churn — reuses it (`_cache_size() == 1`)."""
    mesh = _mesh(8)
    rng = np.random.default_rng(99)
    sol = DistributedAssignmentSolver(
        types=TYPES, max_tasks_per_server=4, max_requesters=2,
        mesh=mesh, rounds=16, servers_per_device=1250, auction="device",
    )
    assert sol.S == 10000
    # sparse world: most rows empty (the fixed shape covers them), a
    # couple hundred live servers — the SHAPE is what is under test
    snaps = {}
    seq = 0
    for s in range(100, 100 + 256):
        seq += 4
        snaps[s] = {
            "tasks": [(seq, int(rng.choice(TYPES)),
                       int(rng.integers(-9, 10)), 8)],
            "reqs": [(s * 50, 1,
                      [int(rng.choice(TYPES))])] if s % 2 else [],
        }
    sol.solve(snaps, None)
    for rnd in range(3):
        # churn: drop one server, add a fresh high rank, burst a third
        victim = sorted(snaps)[rnd]
        del snaps[victim]
        fresh = 20000 + rnd
        snaps[fresh] = {
            "tasks": [(10**7 + rnd, int(rng.choice(TYPES)), 5, 8)],
            "reqs": [(fresh * 50, 1, None)],
        }
        seq += 1
        first = snaps[sorted(snaps)[0]]
        first["tasks"] = (first["tasks"] + [
            (seq, int(rng.choice(TYPES)), int(rng.integers(-9, 10)), 8)
        ])[: sol.K]
        sol.solve(snaps, None)
    assert sol._plan_fn._cache_size() == 1
