"""Multiplexed cross-host channels (adlb_tpu/runtime/channel.py): the
O(hosts^2)-not-O(ranks^2) socket regime, envelope routing, coalesced
submit batches, end-to-end compression, and — the load-bearing part —
the per-rank PEER_EOF ladder surviving the mux (clean close ordering,
kill-one-rank-on-a-shared-channel, whole-broker death)."""

import os
import signal
import struct
import time

import pytest

from adlb_tpu.obs.metrics import Registry
from adlb_tpu.runtime.channel import ChannelBroker
from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.transport_tcp import TcpEndpoint, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_DONE_BY_EXHAUSTION, ADLB_SUCCESS


def _mux_ep(rank, broker, compress_min=0):
    return TcpEndpoint(rank, {rank: ("127.0.0.1", 0)}, mux=broker.addr,
                       compress_min=compress_min)


def _drain(ep, n, timeout=10.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        m = ep.recv(timeout=0.2)
        if m is not None:
            out.append(m)
    return out


def test_64_rank_single_host_holds_o1_channels_per_rank():
    """The acceptance shape: a 64-rank single-host world's data plane is
    64 rank->broker channels (one listening broker socket), NOT the
    O(ranks^2) per-pair mesh — asserted via the tcp_channels_open gauge
    (1 per rank) and the endpoints' empty direct-socket maps, with real
    frames crossing every channel."""
    N = 64
    broker = ChannelBroker()
    eps = []
    regs = []
    try:
        for r in range(N):
            ep = _mux_ep(r, broker)
            reg = Registry(rank=r)
            ep.metrics = reg
            eps.append(ep)
            regs.append(reg)
        # ring traffic: every rank sends to its successor and to rank 0
        # (a hotspot), so every channel carries frames both ways
        for r, ep in enumerate(eps):
            ep.send((r + 1) % N, msg(Tag.FA_PUT, r, payload=b"u" * 64,
                                     work_type=1))
            if r != 0:
                ep.send(0, msg(Tag.TA_PUT_RESP, r, rc=ADLB_SUCCESS))
        for r, ep in enumerate(eps):
            want = N if r == 0 else 1  # rank 0: N-1 resps + 1 ring frame
            got = _drain(ep, want)
            assert len(got) == want, f"rank {r}: {len(got)}/{want}"
        # the socket census: one channel per rank, zero direct sockets
        for r, (ep, reg) in enumerate(zip(eps, regs)):
            assert not ep._out, f"rank {r} opened direct per-pair sockets"
            assert reg.value("tcp_channels_open") == 1
        assert broker.conns_open == N
        assert broker.peak_conns == N
        assert broker.frames_forwarded >= 2 * N - 1
        # the ops surface: the channel census and codec latency ride the
        # registry exposition (/metrics) like the shm ring gauges
        exposed = regs[0].expose()
        assert "adlb_tcp_channels_open" in exposed
        assert "adlb_codec_encode_us" in exposed
    finally:
        for ep in eps:
            ep.close()
        broker.close()


def test_clean_close_orders_frames_before_peer_eof():
    """A rank's last frames beat its DETACH: the receiving endpoint sees
    the data, THEN the synthesized PEER_EOF — the finalize ordering every
    termination ladder depends on."""
    broker = ChannelBroker()
    a = _mux_ep(0, broker)
    b = _mux_ep(1, broker)
    try:
        for i in range(20):
            a.send(1, msg(Tag.FA_PUT, 0, payload=bytes([i]) * 32,
                          work_type=1))
        a.send(1, msg(Tag.FA_LOCAL_APP_DONE, 0))
        a.close()
        got = _drain(b, 22)
        assert [m.tag for m in got[:20]] == [Tag.FA_PUT] * 20
        assert got[20].tag is Tag.FA_LOCAL_APP_DONE
        assert got[21].tag is Tag.PEER_EOF and got[21].src == 0
    finally:
        b.close()
        broker.close()


def test_unseen_peer_death_synthesizes_no_eof():
    """Byte-for-byte the per-pair ladder: a rank we never heard from
    dying must not synthesize PEER_EOF (per-pair TCP had no connection
    to EOF)."""
    broker = ChannelBroker()
    a = _mux_ep(0, broker)
    b = _mux_ep(1, broker)
    c = _mux_ep(2, broker)
    try:
        a.send(1, msg(Tag.FA_PUT, 0, payload=b"x", work_type=1))
        assert _drain(b, 1)[0].tag is Tag.FA_PUT
        c.close()  # rank 2 dies; neither a nor b ever heard from it
        assert b.recv(timeout=0.5) is None
        assert a.recv(timeout=0.2) is None
        a.close()
        eof = _drain(b, 1)
        assert eof and eof[0].tag is Tag.PEER_EOF and eof[0].src == 0
        # sends to a known-dead peer fail like a refused reconnect
        with pytest.raises(OSError):
            b.send(0, msg(Tag.TA_PUT_RESP, 1, rc=ADLB_SUCCESS))
    finally:
        b.close()
        broker.close()


def test_submit_batch_coalesces_burst_into_one_gather():
    """submit_begin/submit_flush: an 8-frame burst drains as ONE gather
    (frames_coalesced == 7), arrives complete and in order."""
    broker = ChannelBroker()
    a = _mux_ep(0, broker)
    b = _mux_ep(1, broker)
    reg = Registry(rank=0)
    a.metrics = reg
    try:
        a.submit_begin()
        for i in range(8):
            a.send(1, msg(Tag.FA_PUT, 0, payload=struct.pack("<q", i),
                          work_type=1))
        # nothing on the wire until the flush (deferred submission)
        assert b.recv(timeout=0.15) is None
        a.submit_flush()
        got = _drain(b, 8)
        assert [struct.unpack("<q", m.payload)[0] for m in got] == \
            list(range(8))
        assert reg.value("frames_coalesced") == 7
        assert "adlb_frames_coalesced_total" in reg.expose()
    finally:
        a.close()
        b.close()
        broker.close()


def test_envelope_compression_end_to_end():
    """Bodies above compress_min_bytes ride zlib-compressed envelopes
    (flag bit 0), inflate transparently, and the saved bytes surface on
    the sender's registry."""
    broker = ChannelBroker()
    a = _mux_ep(0, broker, compress_min=1024)
    b = _mux_ep(1, broker)
    reg = Registry(rank=0)
    a.metrics = reg
    blob = b"compressible " * 8192  # ~100 KiB, highly redundant
    try:
        a.send(1, msg(Tag.FA_PUT, 0, payload=blob, work_type=1))
        a.send(1, msg(Tag.FA_PUT, 0, payload=b"tiny", work_type=1))
        got = _drain(b, 2)
        assert got[0].payload == blob
        assert got[1].payload == b"tiny"
        saved = reg.value("bytes_compressed")
        assert saved > len(blob) // 2, "compression never engaged"
    finally:
        a.close()
        b.close()
        broker.close()


def test_two_host_bridge_is_one_channel_per_host_pair():
    """Two brokers ('hosts') with routed ranks: cross-host traffic flows
    over exactly ONE bridge channel per host-pair, and a remote rank's
    death propagates across the bridge as a per-rank EOF."""
    bk_a = ChannelBroker()
    bk_b = ChannelBroker()
    routes_ranks = {0: bk_a.hostkey, 1: bk_a.hostkey,
                    2: bk_b.hostkey, 3: bk_b.hostkey}
    addrs = {bk_a.hostkey: bk_a.addr, bk_b.hostkey: bk_b.addr}
    bk_a.set_routes(routes_ranks, addrs)
    bk_b.set_routes(routes_ranks, addrs)
    e0 = TcpEndpoint(0, {0: ("127.0.0.1", 0)}, mux=bk_a.addr)
    e2 = TcpEndpoint(2, {2: ("127.0.0.1", 0)}, mux=bk_b.addr)
    e3 = TcpEndpoint(3, {3: ("127.0.0.1", 0)}, mux=bk_b.addr)
    try:
        # both B-side ranks talk to rank 0 on A: one bridge carries both
        for i in range(10):
            e2.send(0, msg(Tag.FA_PUT, 2, payload=b"x" * 32, work_type=1))
            e3.send(0, msg(Tag.FA_PUT, 3, payload=b"y" * 32, work_type=1))
        assert len(_drain(e0, 20)) == 20
        e0.send(2, msg(Tag.TA_PUT_RESP, 0, rc=ADLB_SUCCESS))
        assert _drain(e2, 1)[0].rc == ADLB_SUCCESS
        assert len(bk_a.bridges) == 1 and len(bk_b.bridges) == 1
        # remote death: rank 2 closes; rank 0 (which heard from it)
        # gets PEER_EOF(2) across the bridge
        e2.close()
        eof = _drain(e0, 1)
        assert eof and eof[0].tag is Tag.PEER_EOF and eof[0].src == 2
    finally:
        e0.close()
        e3.close()
        bk_a.close()
        bk_b.close()


# ----------------------------------------------------------- world-level


def _producer_consumer(ctx):
    made = 0
    if ctx.rank == 0:
        for i in range(30):
            assert ctx.put(f"unit-{i}".encode(), work_type=1,
                           work_prio=i) == ADLB_SUCCESS
            made += 1
    got = []
    while True:
        rc, res = ctx.reserve([1])
        if rc != ADLB_SUCCESS:
            assert rc == ADLB_DONE_BY_EXHAUSTION
            break
        rc2, buf = ctx.get_reserved(res.handle)
        assert rc2 == ADLB_SUCCESS
        got.append(buf.decode())
    return made, got


def test_mux_spawn_world_exhaustion():
    """A real process world end-to-end over the channel plane (broker in
    the harness, one channel per rank): full unit conservation through
    exhaustion."""
    r = spawn_world(
        3, 2, [1], _producer_consumer,
        cfg=Config(tcp_mux="on", fabric="tcp", exhaust_check_interval=0.2),
        timeout=90.0,
    )
    all_got = [u for _, got in r.app_results.values() for u in got]
    assert sorted(all_got) == sorted(f"unit-{i}" for i in range(30))


T_AB, T_C = 1, 2
_N_PAIRS = 24


def _sigkill_economy(ctx):
    """Answer economy where rank 1 SIGKILLs itself mid-run while its
    traffic shares the host's one broker channel fabric with everyone
    else's."""
    if ctx.rank == 0:
        for a in range(_N_PAIRS):
            assert ctx.put(struct.pack("<qq", a, 3 * a), T_AB,
                           answer_rank=0) == ADLB_SUCCESS
        total = 0
        for _ in range(_N_PAIRS):
            rc, r = ctx.reserve([T_C])
            assert rc == ADLB_SUCCESS, rc
            rc, buf = ctx.get_reserved(r.handle)
            total += struct.unpack("<q", buf)[0]
        ctx.set_problem_done()
        return total
    n = 0
    while True:
        rc, r = ctx.reserve([T_AB])
        if rc != ADLB_SUCCESS:
            return n
        if ctx.rank == 1 and n >= 1:
            os.kill(os.getpid(), signal.SIGKILL)  # dies holding the lease
        rc, buf = ctx.get_reserved(r.handle)
        a, b = struct.unpack("<qq", buf)
        ctx.put(struct.pack("<q", a + b), T_C, target_rank=0)
        n += 1
        time.sleep(0.002)


def test_mux_kill_rank_on_shared_channel_preserves_eof_ladder():
    """SIGKILL one rank whose frames share a broker channel with five
    others: the broker's DETACH fan-out must synthesize exactly that
    rank's PEER_EOF everywhere it was known, the reclaim ladder must
    re-enqueue its leased unit, and the world completes with the full
    answer set — per-pair death semantics, byte-for-byte, over the mux."""
    res = spawn_world(
        6, 2, [T_AB, T_C], _sigkill_economy,
        cfg=Config(tcp_mux="on", fabric="tcp",
                   on_worker_failure="reclaim",
                   exhaust_check_interval=0.2),
        timeout=90.0,
    )
    assert res.app_results[0] == sum(a + 3 * a for a in range(_N_PAIRS))
    assert res.casualties == [1]
    assert not res.aborted
