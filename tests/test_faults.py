"""Worker-death survival (on_worker_failure="reclaim") and the seeded
fault-injection transport (adlb_tpu/runtime/faults.py).

Three layers of coverage:

* **FaultPlan determinism** — the same seed produces byte-identical
  injected-event logs on both fabrics (the in-proc queue fabric and the
  real TCP fabric), run twice each; the tentpole's requirement that every
  failure path has a deterministic reproduction.
* **Reclaim race lattice** — Server instances driven handler-by-handler
  (no reactor threads), pinning the exact interleavings: a worker dying
  while its leased unit's RFR handoff is in flight (UNRESERVE
  compensation on one side, lease reclaim on the other), and
  targeted-to-dead-rank units sharing a batch-common prefix (the
  refcount must not leak).
* **End-to-end policy acceptance** — a TCP world running the
  self-validating answer economy with 2 of 8 workers SIGKILLed mid-run:
  completes with the correct answer set under "reclaim", aborts cleanly
  (no hang, correct classification) under the default "abort".
"""

import os
import signal
import struct
import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.faults import FaultPlan, FaultyEndpoint
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import TcpEndpoint, spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_RETRY, ADLB_SUCCESS

T_AB, T_C = 1, 2


# --------------------------------------------------------------- determinism


_SCRIPT_TAGS = [Tag.FA_PUT, Tag.FA_RESERVE, Tag.SS_QMSTAT, Tag.TA_PUT_RESP]


def _drive_scripted(ep, spec, n=200):
    """Send a fixed frame sequence through a fault-wrapped endpoint and
    return the injected-event log."""
    plan = FaultPlan(spec, ep.rank)
    fep = FaultyEndpoint(ep, plan)
    for i in range(n):
        fep.send(
            1,
            msg(_SCRIPT_TAGS[i % len(_SCRIPT_TAGS)], 0, payload=b"x" * 10,
                work_type=1),
        )
    return plan.event_log()


def test_fault_plan_deterministic_both_fabrics():
    spec = dict(seed=42, drop=0.15, delay=0.1, delay_s=0.0, duplicate=0.1)
    logs = []
    for _ in range(2):  # two independent in-proc runs
        fabric = InProcFabric(2)
        logs.append(_drive_scripted(fabric.endpoints[0], spec))
    for _ in range(2):  # two independent TCP runs
        a = TcpEndpoint(0, {0: ("127.0.0.1", 0)})
        b = TcpEndpoint(1, {1: ("127.0.0.1", 0)})
        a.addr_map[1] = b.addr_map[1]
        try:
            logs.append(_drive_scripted(a, spec))
        finally:
            a.close()
            b.close()
    assert logs[0], "seeded plan injected nothing — test is vacuous"
    # identical within a fabric AND across fabrics: decisions are a pure
    # function of (seed, rank, frame), never of transport or wall clock
    assert logs[0] == logs[1] == logs[2] == logs[3]
    # different seed => different schedule (no accidental constants)
    fabric = InProcFabric(2)
    other = _drive_scripted(fabric.endpoints[0], dict(spec, seed=43))
    assert other != logs[0]


def test_fault_plan_disconnect_at_frame_synthesizes_eof():
    fabric = InProcFabric(3)
    plan = FaultPlan({"disconnect_at": {0: 3}}, 0)
    fep = FaultyEndpoint(fabric.endpoints[0], plan)
    fep.send(1, msg(Tag.FA_PUT, 0, payload=b"a"))
    fep.send(1, msg(Tag.FA_PUT, 0, payload=b"b"))
    with pytest.raises(OSError):
        fep.send(1, msg(Tag.FA_PUT, 0, payload=b"c"))  # frame 3: dies
    with pytest.raises(OSError):
        fep.send(2, msg(Tag.FA_PUT, 0, payload=b"d"))  # stays dead
    assert plan.event_log() == [(3, "disconnect", "FA_PUT", 1)]
    # both frames delivered before death, then one synthetic PEER_EOF at
    # EVERY other rank (a home server must learn even if never contacted)
    got = [fabric.endpoints[1].recv(timeout=1.0) for _ in range(3)]
    assert [m.tag for m in got] == [Tag.FA_PUT, Tag.FA_PUT, Tag.PEER_EOF]
    eof2 = fabric.endpoints[2].recv(timeout=1.0)
    assert eof2.tag is Tag.PEER_EOF and eof2.src == 0


# ------------------------------------------------------- reclaim race lattice


def _mini_server(rank=2, on_worker_failure="reclaim", nranks=4, nservers=2):
    """A Server on an in-proc fabric, driven handler-by-handler (its
    reactor loop never runs). world: apps 0..1, servers 2..3."""
    world = WorldSpec(nranks=nranks, nservers=nservers, types=(T_AB, T_C))
    fabric = InProcFabric(nranks)
    cfg = Config(on_worker_failure=on_worker_failure)
    return Server(world, cfg, fabric.endpoint(rank)), fabric


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def test_reclaim_reenqueues_leased_unit_and_rematches():
    """Rank 0 reserves (lease granted), dies before fetching; the unit
    must return to the queue and satisfy the next parked requester."""
    srv, fabric = _mini_server()
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"unit", work_type=T_AB, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1))
    srv._handle(msg(Tag.FA_RESERVE, 0, req_types=[T_AB], hang=True,
                    rqseqno=1))
    assert len(srv.leases) == 1
    [unit] = list(srv.wq.units())
    assert unit.pinned and unit.pin_rank == 0
    # rank 1 parks behind the pinned unit
    srv._handle(msg(Tag.FA_RESERVE, 1, req_types=[T_AB], hang=True,
                    rqseqno=1))
    assert 1 in srv.rq
    _drain(fabric, 0), _drain(fabric, 1)
    # rank 0 dies: EOF at its home server (this one)
    srv._handle(Msg(tag=Tag.PEER_EOF, src=0))
    assert 0 in srv._dead_ranks and 0 in srv._finalized
    # the dead rank holds nothing; the reclaimed unit went straight to
    # the surviving parked requester (who now holds the fresh lease)
    assert not srv.leases.owned_by(0)
    [lease] = srv.leases.owned_by(1)
    resp = [m for m in _drain(fabric, 1) if m.tag is Tag.TA_RESERVE_RESP]
    assert resp and resp[0].rc == ADLB_SUCCESS
    # structured failure-timeline events are in the flight ring
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("rank_dead rank=0") for t in texts)
    assert any(t.startswith("lease_reclaimed") for t in texts)
    # the fan-out reached the peer server
    fan = [m for m in _drain(fabric, 3) if m.tag is Tag.SS_RANK_DEAD]
    assert fan and fan[0].rank == 0


def test_reclaim_rfr_in_flight_compensates_with_unreserve():
    """Home side of the mid-migration race: the requester dies while an
    RFR is in flight; the late found=True response must be compensated
    with SS_UNRESERVE so the remote holder re-enqueues the unit."""
    srv, fabric = _mini_server()
    srv._handle(msg(Tag.FA_RESERVE, 0, req_types=[T_AB], hang=True,
                    rqseqno=7))
    srv._handle(Msg(tag=Tag.PEER_EOF, src=0))  # dies while parked
    assert 0 not in srv.rq
    srv._handle(msg(Tag.SS_RFR_RESP, 3, found=True, for_rank=0, rqseqno=7,
                    seqno=77, work_type=T_AB, prio=0, target_rank=-1,
                    work_len=4, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1))
    unres = [m for m in _drain(fabric, 3) if m.tag is Tag.SS_UNRESERVE]
    assert unres and unres[0].seqno == 77


def test_reclaim_holder_side_unpins_on_rank_dead():
    """Holder side of the same race: a unit pinned for a remote requester
    (via RFR) is unpinned when SS_RANK_DEAD arrives, and becomes
    matchable again."""
    srv, fabric = _mini_server(rank=3)
    srv._handle(msg(Tag.FA_PUT, 1, payload=b"unit", work_type=T_AB, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1))
    srv._handle(msg(Tag.SS_RFR, 2, for_rank=0, rqseqno=1, req_types=[T_AB],
                    targeted_lookup=False, lookup_type=-1))
    [unit] = list(srv.wq.units())
    assert unit.pinned and unit.pin_rank == 0 and len(srv.leases) == 1
    srv._handle(msg(Tag.SS_RANK_DEAD, 2, rank=0))
    [unit] = list(srv.wq.units())
    assert not unit.pinned and len(srv.leases) == 0
    assert srv.wq.find_match(1, frozenset([T_AB])) is not None


def test_reclaim_drops_targeted_units_without_leaking_common_refcount():
    """Two targeted units share a batch-common prefix (refcnt 2); the
    target of one dies. Its unit is dropped with a forfeited get, so the
    prefix still GCs when the surviving member is fetched."""
    srv, fabric = _mini_server()
    srv._handle(msg(Tag.FA_PUT_COMMON, 0, payload=b"PREFIX"))
    common_seqno = _drain(fabric, 0)[-1].common_seqno
    for target in (0, 1):
        srv._handle(msg(Tag.FA_PUT, 0, payload=b"u%d" % target,
                        work_type=T_AB, prio=0, target_rank=target,
                        answer_rank=-1, common_len=6,
                        common_server=srv.rank, common_seqno=common_seqno))
    srv._handle(msg(Tag.FA_BATCH_DONE, 0, common_seqno=common_seqno,
                    refcnt=2))
    mem_before = srv.mem.curr
    srv._handle(msg(Tag.SS_RANK_DEAD, 3, rank=1))  # rank 1 dies remotely
    assert srv.wq.count == 1  # rank 1's unit dropped
    assert len(srv.cq) == 1  # prefix still alive for the survivor
    assert srv.mem.curr == mem_before - 2  # b"u1" freed
    # survivor fetches its unit + the prefix: the forfeited get must make
    # this final fetch the one that GCs the entry
    srv._handle(msg(Tag.FA_RESERVE, 0, req_types=None, hang=True, rqseqno=1))
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_RESERVE_RESP][-1]
    assert resp.rc == ADLB_SUCCESS
    handle = resp.handle
    srv._handle(msg(Tag.FA_GET_COMMON, 0, common_seqno=common_seqno))
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=handle[0]))
    assert len(srv.cq) == 0, "common prefix leaked after forfeit"
    assert srv.mem.curr == 0
    assert srv.metrics.value("targeted_dropped") == 1


def test_put_targeted_at_dead_rank_is_dropped_with_forfeit():
    """A put that arrives FOR a dead rank after the death is accepted and
    dropped (at-most-once), including its common-prefix share."""
    srv, fabric = _mini_server()
    srv._handle(msg(Tag.SS_RANK_DEAD, 3, rank=1))
    srv._handle(msg(Tag.FA_PUT_COMMON, 0, payload=b"PFX"))
    common_seqno = _drain(fabric, 0)[-1].common_seqno
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"late", work_type=T_AB, prio=0,
                    target_rank=1, answer_rank=-1, common_len=3,
                    common_server=srv.rank, common_seqno=common_seqno))
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP][-1]
    assert resp.rc == ADLB_SUCCESS  # accepted-and-dropped, putter moves on
    assert srv.wq.count == 0
    srv._handle(msg(Tag.FA_BATCH_DONE, 0, common_seqno=common_seqno,
                    refcnt=1))
    assert len(srv.cq) == 0, "dropped member's prefix share leaked"


def test_dead_rank_resurrects_with_retriable_code():
    """An EOF that was connection churn, not death: the rank's next
    FA_RESERVE gets ADLB_RETRY, it is un-finalized, and a reconnect
    event lands in the flight ring."""
    srv, fabric = _mini_server()
    srv._handle(Msg(tag=Tag.PEER_EOF, src=0))
    assert 0 in srv._dead_ranks and 0 in srv._finalized
    srv._handle(msg(Tag.FA_RESERVE, 0, req_types=None, hang=True, rqseqno=9))
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_RESERVE_RESP]
    assert resp and resp[0].rc == ADLB_RETRY
    assert 0 not in srv._dead_ranks and 0 not in srv._finalized
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("reconnect rank=0") for t in texts)
    # the retried reserve (fresh rqseqno) is then served normally
    srv._handle(msg(Tag.FA_RESERVE, 0, req_types=None, hang=True,
                    rqseqno=10))
    assert 0 in srv.rq


def test_abort_policy_unchanged_on_eof():
    """Default policy: the reference's rank-death-kills-job semantics."""
    srv, fabric = _mini_server(on_worker_failure="abort")
    srv._handle(Msg(tag=Tag.PEER_EOF, src=0))
    assert srv._aborted and srv.done
    aborts = [m for m in _drain(fabric, 3) if m.tag is Tag.SS_ABORT]
    assert aborts, "abort did not broadcast"


# -------------------------------------------- deterministic in-proc reclaim


def _fault_economy(n_pairs):
    def app(ctx):
        if ctx.rank == 0:
            for a in range(n_pairs):
                assert ctx.put(struct.pack("<qq", a, 3 * a), T_AB,
                               answer_rank=0) == ADLB_SUCCESS
            total = 0
            for _ in range(n_pairs):
                rc, r = ctx.reserve([T_C])
                assert rc == ADLB_SUCCESS, rc
                rc, buf = ctx.get_reserved(r.handle)
                total += struct.unpack("<q", buf)[0]
            ctx.set_problem_done()
            return total
        n = 0
        while True:
            rc, r = ctx.reserve([T_AB])
            if rc != ADLB_SUCCESS:
                return n
            rc, buf = ctx.get_reserved(r.handle)
            a, b = struct.unpack("<qq", buf)
            ctx.put(struct.pack("<q", a + b), T_C, target_rank=0)
            n += 1
            time.sleep(0.002)

    return app


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_inproc_fault_disconnect_reclaimed(mode):
    """Byte-deterministic worker death: rank 1's connectivity dies at its
    4th protocol frame (reserve, get, put-answer, then the fatal 2nd
    reserve) — it contributes exactly one answer, and the reclaim policy
    completes the world with the full answer set anyway."""
    n_pairs = 24
    res = run_world(
        4, 2, [T_AB, T_C], _fault_economy(n_pairs),
        cfg=Config(
            balancer=mode,
            on_worker_failure="reclaim",
            exhaust_check_interval=0.2,
            fault_spec={"seed": 5, "disconnect_at": {1: 4}},
        ),
        timeout=60.0,
    )
    assert res.app_results[0] == sum(a + 3 * a for a in range(n_pairs))
    assert res.casualties == [1]
    assert 1 not in res.app_results


# ------------------------------------------------- end-to-end TCP acceptance


N_PAIRS_TCP = 40
VICTIMS = (1, 2)


def _sigkill_economy(ctx):
    """Answer economy with 8 workers; ranks 1 and 2 SIGKILL themselves
    mid-run — rank 1 while holding an unfetched reservation (the lease
    reclaim case), rank 2 between work units (plain death)."""
    if ctx.rank == 0:
        for a in range(N_PAIRS_TCP):
            assert ctx.put(struct.pack("<qq", a, 3 * a), T_AB,
                           answer_rank=0) == ADLB_SUCCESS
        total = 0
        for _ in range(N_PAIRS_TCP):
            rc, r = ctx.reserve([T_C])
            assert rc == ADLB_SUCCESS, rc
            rc, buf = ctx.get_reserved(r.handle)
            total += struct.unpack("<q", buf)[0]
        ctx.set_problem_done()
        return total
    n = 0
    while True:
        rc, r = ctx.reserve([T_AB])
        if rc != ADLB_SUCCESS:
            return n
        if ctx.rank == VICTIMS[0] and n >= 1:
            os.kill(os.getpid(), signal.SIGKILL)  # dies holding the lease
        rc, buf = ctx.get_reserved(r.handle)
        a, b = struct.unpack("<qq", buf)
        ctx.put(struct.pack("<q", a + b), T_C, target_rank=0)
        n += 1
        if ctx.rank == VICTIMS[1] and n >= 2:
            os.kill(os.getpid(), signal.SIGKILL)  # dies between units
        time.sleep(0.005)


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_tcp_sigkill_workers_reclaim_completes(mode):
    res = spawn_world(
        9, 2, [T_AB, T_C], _sigkill_economy,
        cfg=Config(balancer=mode, on_worker_failure="reclaim",
                   exhaust_check_interval=0.2),
        timeout=90.0,
    )
    assert res.app_results[0] == sum(a + 3 * a for a in range(N_PAIRS_TCP))
    assert res.casualties == list(VICTIMS)
    assert not res.aborted
    # conservation: the victims answered exactly 3 units before dying
    # (rank 1: one, rank 2: two) and rank 1's reserved-but-unfetched unit
    # was reclaimed, so the survivors account for the other 37
    consumed = sum(v for k, v in res.app_results.items() if k != 0)
    assert consumed == N_PAIRS_TCP - 3, res.app_results


def _die_instead_of_finalize(ctx):
    """A worker preempted between its last unit and finalize: the EOF
    lands while the termination machinery (no-more-work flush / END
    ring) is already underway — the reclaim accounting must release the
    held END_1 token or the world hangs."""
    if ctx.rank == 0:
        for a in range(8):
            ctx.put(struct.pack("<qq", a, a), T_AB, answer_rank=0)
        total = 0
        for _ in range(8):
            rc, r = ctx.reserve([T_C])
            rc, buf = ctx.get_reserved(r.handle)
            total += struct.unpack("<q", buf)[0]
        ctx.set_problem_done()
        return total
    n = 0
    while True:
        rc, r = ctx.reserve([T_AB])
        if rc != ADLB_SUCCESS:
            if ctx.rank == 1:
                os.kill(os.getpid(), signal.SIGKILL)  # dies pre-finalize
            return n
        rc, buf = ctx.get_reserved(r.handle)
        a, b = struct.unpack("<qq", buf)
        ctx.put(struct.pack("<q", a + b), T_C, target_rank=0)
        n += 1


def test_tcp_death_during_termination_reclaimed():
    t0 = time.monotonic()
    res = spawn_world(
        4, 2, [T_AB, T_C], _die_instead_of_finalize,
        cfg=Config(on_worker_failure="reclaim",
                   exhaust_check_interval=0.2),
        timeout=60.0,
    )
    assert time.monotonic() - t0 < 45.0, "END ring hung on the casualty"
    assert res.app_results[0] == sum(a + a for a in range(8))
    assert res.casualties == [1]


def test_tcp_sigkill_workers_abort_classifies_cleanly():
    t0 = time.monotonic()
    with pytest.raises(RuntimeError):
        spawn_world(
            9, 2, [T_AB, T_C], _sigkill_economy,
            cfg=Config(on_worker_failure="abort",
                       exhaust_check_interval=0.2),
            timeout=60.0,
        )
    assert time.monotonic() - t0 < 45.0, "abort path hung"
