"""Native C++ work queue: build + behavioral parity with the Python queue."""

import random

import pytest

from adlb_tpu.runtime.queues import WorkQueue, WorkUnit

native = pytest.importorskip("adlb_tpu.native")
if not native.native_available():  # pragma: no cover
    pytest.skip("native core failed to build", allow_module_level=True)

from adlb_tpu.native.wq import NativeWorkQueue  # noqa: E402


def mk(seqno, wtype=1, prio=0, target=-1, payload=b"x"):
    return WorkUnit(
        seqno=seqno, work_type=wtype, prio=prio, target_rank=target,
        answer_rank=-1, payload=payload,
    )


def mirror_pair():
    return WorkQueue(), NativeWorkQueue()


def test_basic_match_and_pin():
    py, nat = mirror_pair()
    for q in (py, nat):
        q.add(mk(1, prio=5))
        q.add(mk(2, prio=9, target=3))
    assert py.find_match(3, None).seqno == nat.find_match(3, None).seqno == 2
    assert py.find_match(0, None).seqno == nat.find_match(0, None).seqno == 1
    for q in (py, nat):
        q.pin(1, 0)
    assert py.find_match(0, None) is None and nat.find_match(0, None) is None
    for q in (py, nat):
        q.unpin(1)
    assert nat.find_match(0, None).seqno == 1


def test_randomized_parity_with_python_queue():
    rng = random.Random(99)
    py, nat = mirror_pair()
    alive: dict[int, WorkUnit] = {}
    seqno = 0
    for step in range(4000):
        op = rng.random()
        if op < 0.45 or not alive:
            seqno += 1
            u1 = mk(seqno, wtype=rng.randint(1, 4), prio=rng.randint(-9, 9),
                    target=rng.choice([-1, -1, -1, 0, 1, 2]),
                    payload=b"p" * rng.randint(0, 32))
            u2 = mk(u1.seqno, u1.work_type, u1.prio, u1.target_rank,
                    u1.payload)
            py.add(u1)
            nat.add(u2)
            alive[seqno] = u1
        elif op < 0.72:
            rank = rng.randint(0, 2)
            req = rng.choice(
                [None, frozenset([1]), frozenset([2, 3]), frozenset([4, 1])]
            )
            a = py.find_match(rank, req)
            b = nat.find_match(rank, req)
            assert (a is None) == (b is None), f"step {step}"
            if a is not None:
                assert a.seqno == b.seqno, f"step {step}"
        elif op < 0.86:
            s = rng.choice(list(alive))
            if alive[s].pinned:
                py.unpin(s)
                nat.unpin(s)
            else:
                py.pin(s, 0)
                nat.pin(s, 0)
        else:
            s = rng.choice(list(alive))
            py.remove(s)
            nat.remove(s)
            del alive[s]
        if step % 500 == 0:
            assert py.count == nat.count == len(alive)
            for t in range(1, 5):
                assert py.hi_prio_of_type(t) == nat.hi_prio_of_type(t)
            assert (
                py.num_unpinned_untargeted() == nat.num_unpinned_untargeted()
            )
            assert py.num_unpinned() == nat.num_unpinned()


def test_snapshot_untargeted_sorted():
    _, nat = mirror_pair()
    nat.add(mk(1, prio=3))
    nat.add(mk(2, prio=9))
    nat.add(mk(3, prio=9))
    nat.add(mk(4, prio=1, target=5))  # targeted: excluded
    nat.pin(1, 0)  # pinned: excluded
    snap = nat.snapshot_untargeted(cap=8)
    assert [s[0] for s in snap] == [2, 3]
    assert [s[2] for s in snap] == [9, 9]
