"""Regression tests for the driver entry points (``__graft_entry__``).

The driver's multi-chip gate imports ``__graft_entry__`` and calls
``dryrun_multichip(8)`` directly — these tests exercise exactly that path
so a green suite implies a green gate. Under the conftest's 8-device
virtual CPU mesh the call proceeds in-process (no subprocess re-exec).
"""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_assigns():
    fn, args = graft.entry()
    out = np.asarray(fn(*args))
    assert out.shape == (8 * 32,)  # one slot per requester
    assert (out >= 0).sum() > 0


def test_dryrun_multichip_8():
    # asserts internally: mesh solve pairs, type masks respected, and a
    # production engine round that plans both matches and migrations
    graft.dryrun_multichip(8)
