"""Periodic cluster-wide stats ring (reference src/adlb.c:712-753,2391-2465)
and the offline decoder (reference scripts/get_stats.py)."""

import subprocess
import sys
import time
from pathlib import Path

from adlb_tpu.api import run_world
from adlb_tpu.runtime.stats import (
    emit_stat_aps,
    parse_stat_lines,
    set_sink,
    summarize,
)
from adlb_tpu.runtime.world import Config

REPO = Path(__file__).resolve().parent.parent


def _collect_lines():
    lines = []
    set_sink(lines.append)
    return lines


def teardown_function(_fn):
    set_sink(None)


def test_periodic_stats_ring_aggregates_all_servers():
    lines = _collect_lines()

    def app(ctx):
        if ctx.rank == 0:
            for i in range(40):
                ctx.put(b"x" * 64, work_type=1, work_prio=i)
        done = 0
        while True:
            rc, r = ctx.reserve([1])
            if rc < 0:
                break
            ctx.get_reserved(r.handle)
            done += 1
            time.sleep(0.002)
            if ctx.rank == 0 and done == 10:
                # keep the world alive long enough for >=2 stat periods
                time.sleep(0.15)
        if ctx.rank == 0:
            ctx.set_problem_done()
        return done

    run_world(
        num_app_ranks=3,
        nservers=3,
        types=[1],
        app_fn=app,
        cfg=Config(periodic_log_interval=0.03, exhaust_check_interval=5.0),
        timeout=60.0,
    )

    records = parse_stat_lines(lines)
    assert records, "no STAT_APS records emitted"
    # every aggregate must include all three servers' contributions
    assert all(r["nservers"] == 3 for r in records)
    # counters are cumulative and monotone
    puts = [r["total"]["puts"] for r in records]
    assert puts == sorted(puts)
    assert puts[-1] == 40
    rows = summarize(records)
    assert rows[0]["seq"] == records[0]["seq"]


def test_stat_aps_chunking_roundtrip():
    lines = _collect_lines()
    big = {
        "seq": 7,
        "t": 123.0,
        "trip_s": 0.001,
        "nservers": 64,
        "by_type": {str(t): {"targeted": t, "untargeted": 2 * t} for t in range(40)},
        "total": {"wq": 1, "rq": 2, "puts": 3, "resolved": 4, "nbytes": 5},
        "per_server": {str(r): {"wq": r, "rq": 0, "nbytes": 0} for r in range(64)},
    }
    emit_stat_aps(big)
    assert len(lines) > 1, "expected multi-chunk STAT_APS output"
    assert all(line.startswith("STAT_APS: seq=7 part=") for line in lines)
    [rec] = parse_stat_lines(lines)
    assert rec == big
    # interleaved with noise and a second record, both still decode
    emit_stat_aps({**big, "seq": 8})
    noisy = ["unrelated log line"] + lines + ["more noise"]
    recs = parse_stat_lines(noisy)
    assert [r["seq"] for r in recs] == [7, 8]


def test_get_stats_script(tmp_path):
    lines = _collect_lines()
    for seq in (1, 2):
        emit_stat_aps(
            {
                "seq": seq,
                "t": 100.0 + seq,
                "trip_s": 0.002,
                "nservers": 2,
                "by_type": {"1": {"targeted": 0, "untargeted": 5}},
                "total": {
                    "wq": 5,
                    "rq": 1,
                    "puts": 10 * seq,
                    "resolved": 8 * seq,
                    "nbytes": 320,
                },
                "per_server": {},
            }
        )
    log = tmp_path / "run.log"
    log.write_text("\n".join(lines) + "\n")
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "get_stats.py"), str(log)],
        capture_output=True,
        text=True,
        check=True,
    )
    assert "seq" in out.stdout
    assert "10.0" in out.stdout  # puts/s between the two periods (dt=1s)
