"""Debug plumbing: aprintf, flight recorder, self-diagnosis dumps
(reference src/adlb.c:176-179,558-710,3371-3417)."""

import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.debug import FlightRecorder, aprintf, set_sink
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import AdlbAborted


@pytest.fixture
def sink():
    lines = []
    set_sink(lines.append)
    yield lines
    set_sink(None)


def test_aprintf_gated_and_stamped(sink):
    aprintf(False, 3, "invisible")
    assert sink == []
    aprintf(True, 3, "hello")
    assert len(sink) == 1
    assert "rank 3" in sink[0]
    assert "test_debug_plumbing.py:" in sink[0]
    assert "hello" in sink[0]


def test_flight_recorder_is_circular(sink):
    fr = FlightRecorder(rank=1, capacity=4)
    for i in range(10):
        fr.record(f"event {i}")
    assert len(fr) == 4
    assert [t for _, t in fr.entries()] == [f"event {i}" for i in range(6, 10)]
    fr.dump(reason="test")
    assert "FLIGHT_RECORDER rank 1 (test): 4 entries" in sink[0]
    assert "event 9" in sink[-1]


def test_selfdiag_reports_stuck_requesters_and_tags(sink):
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"x" * 32, T, target_rank=0)
            rc, r = ctx.reserve([T])
            ctx.get_reserved(r.handle)
            time.sleep(0.4)  # give selfdiag ticks time while rank 1 is stuck
            ctx.set_problem_done()
            return True
        rc, _ = ctx.reserve([T])  # parks: no untargeted work ever arrives
        return True

    run_world(
        num_app_ranks=2,
        nservers=1,
        types=[T],
        app_fn=app,
        cfg=Config(selfdiag_interval=0.1, selfdiag_stuck_after=0.15,
                   exhaust_check_interval=30.0),
        timeout=60.0,
    )
    diag = [l for l in sink if l.startswith("SELFDIAG")]
    assert any("wq=" in l and "rq=" in l for l in diag)
    # rank 1 sat parked > 0.2s: reported as stuck with its age
    assert any("stuck requesters" in l and "rank1" in l for l in diag)
    # tag frequency dump saw the puts/reserves
    assert any("tags " in l and "FA_" in l for l in diag)


def test_abort_dumps_flight_recorder(sink):
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            ctx.abort(42)
        else:
            ctx.reserve([T])
        return True

    res = run_world(num_app_ranks=2, nservers=2, types=[T], app_fn=app,
                    timeout=60.0)
    assert res.aborted
    dumps = [l for l in sink if l.startswith("FLIGHT_RECORDER")]
    assert dumps, "abort did not dump the flight recorder"
    assert any("abort" in l for l in sink)
