"""Debug plumbing: aprintf, flight recorder, self-diagnosis dumps
(reference src/adlb.c:176-179,558-710,3371-3417)."""

import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.debug import FlightRecorder, aprintf, set_sink
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import AdlbAborted


@pytest.fixture
def sink():
    lines = []
    set_sink(lines.append)
    yield lines
    set_sink(None)


def test_aprintf_gated_and_stamped(sink):
    aprintf(False, 3, "invisible")
    assert sink == []
    aprintf(True, 3, "hello")
    assert len(sink) == 1
    assert "rank 3" in sink[0]
    assert "test_debug_plumbing.py:" in sink[0]
    assert "hello" in sink[0]


def test_flight_recorder_is_circular(sink):
    fr = FlightRecorder(rank=1, capacity=4)
    for i in range(10):
        fr.record(f"event {i}")
    assert len(fr) == 4
    assert [t for _, t in fr.entries()] == [f"event {i}" for i in range(6, 10)]
    fr.dump(reason="test")
    assert "FLIGHT_RECORDER rank 1 (test): 4 entries" in sink[0]
    assert "event 9" in sink[-1]


def test_selfdiag_reports_stuck_requesters_and_tags(sink):
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"x" * 32, T, target_rank=0)
            rc, r = ctx.reserve([T])
            ctx.get_reserved(r.handle)
            time.sleep(0.4)  # give selfdiag ticks time while rank 1 is stuck
            ctx.set_problem_done()
            return True
        rc, _ = ctx.reserve([T])  # parks: no untargeted work ever arrives
        return True

    run_world(
        num_app_ranks=2,
        nservers=1,
        types=[T],
        app_fn=app,
        cfg=Config(selfdiag_interval=0.1, selfdiag_stuck_after=0.15,
                   exhaust_check_interval=30.0),
        timeout=60.0,
    )
    diag = [l for l in sink if l.startswith("SELFDIAG")]
    assert any("wq=" in l and "rq=" in l for l in diag)
    # rank 1 sat parked > 0.2s: reported as stuck with its age
    assert any("stuck requesters" in l and "rank1" in l for l in diag)
    # tag frequency dump saw the puts/reserves
    assert any("tags " in l and "FA_" in l for l in diag)


def test_abort_dumps_flight_recorder(sink):
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            ctx.abort(42)
        else:
            ctx.reserve([T])
        return True

    res = run_world(num_app_ranks=2, nservers=2, types=[T], app_fn=app,
                    timeout=60.0)
    assert res.aborted
    dumps = [l for l in sink if l.startswith("FLIGHT_RECORDER")]
    assert dumps, "abort did not dump the flight recorder"
    assert any("abort" in l for l in sink)


def test_ds_log_11_counters_and_aggregate_prints(sink):
    """Debug-server parity with the reference's 11-counter heartbeat and
    per-interval printed aggregates (reference src/adlb.c:2539-2610,
    3222-3259): counter totals across a run line up with the work done,
    and aggregate lines are printed."""
    T = 1
    N = 40

    def app(ctx):
        if ctx.rank == 0:
            for i in range(N):
                ctx.put(b"x", T, work_prio=i)
            time.sleep(0.6)  # let a few DS_LOG heartbeats land
            ctx.set_problem_done()
            return 0
        n = 0
        from adlb_tpu.types import ADLB_SUCCESS

        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return n
            ctx.get_reserved(r.handle)
            n += 1

    res = run_world(
        3, 2, [T], app,
        cfg=Config(debug_log_interval=0.1, debug_print_interval=0.25,
                   exhaust_check_interval=0.2),
        use_debug_server=True,
        timeout=120.0,
    )
    assert sum(v for k, v in res.app_results.items() if k != 0) == N
    ds = res.debug_server
    assert ds is not None and not ds.timed_out
    printed = ds.printed_lines
    assert printed, "no aggregate lines printed"
    assert "events=" in printed[0] and "avg_rq=" in printed[0]
    # reserves counted across printed windows + the live window are > 0
    total_reserves = sum(
        int(ln.split("reserves=")[1].split()[0]) for ln in printed
    ) + int(ds._window.get("reserves", 0))
    assert total_reserves > 0


def test_info_rss_and_backlog_keys():
    """L0 parity (reference src/adlb.c:3347-3369,3645-3719): the RSS probe
    and transport-backlog introspection are live Info keys."""
    from adlb_tpu.types import ADLB_SUCCESS, InfoKey

    def app(ctx):
        if ctx.rank == 0:
            rc, rss = ctx.info_get(InfoKey.RSS_KB)
            rc2, backlog = ctx.info_get(InfoKey.TRANSPORT_BACKLOG)
            ctx.set_problem_done()
            return (rc, rss, rc2, backlog)
        rc, _ = ctx.reserve([1])
        return None

    res = run_world(2, 1, [1], app, cfg=Config(exhaust_check_interval=0.2),
                    timeout=60.0)
    rc, rss, rc2, backlog = res.app_results[0]
    assert rc == ADLB_SUCCESS and rc2 == ADLB_SUCCESS
    assert rss > 1000  # a live CPython process is at least a few MB
    assert backlog >= 0
    # and the final stats carry the RSS probe
    assert res.info_get(InfoKey.RSS_KB) > 1000
