"""Unified observability layer: metrics registry semantics, flight-record
JSON artifacts on abort, the master's live ops endpoint, and the merged
client+server trace stream (adlb_tpu/obs/, ISSUE 1 tentpole)."""

import json
import os
import struct
import subprocess
import sys
import time
import urllib.request

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.obs.flight import FlightRecorder, resolve_flight_dir
from adlb_tpu.obs.metrics import Registry
from adlb_tpu.runtime.trace import PID_APP, PID_SERVER, span_names
from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


# --------------------------------------------------------------- registry


def test_counter_semantics():
    reg = Registry(rank=3)
    c = reg.counter("puts")
    c.inc()
    c.inc(4)
    assert reg.value("puts") == 5
    # labeled counters are distinct instruments; get-or-create returns
    # the same object for the same (name, labels)
    a = reg.counter("tx_msgs", tag="FA_PUT")
    b = reg.counter("tx_msgs", tag="FA_RESERVE")
    assert a is not b
    a.inc(2)
    b.inc(3)
    assert reg.counter("tx_msgs", tag="FA_PUT") is a
    assert reg.value("tx_msgs", tag="FA_PUT") == 2
    assert reg.sum_counter("tx_msgs") == 5


def test_gauge_and_timeseries():
    reg = Registry(rank=0)
    g = reg.gauge("wq_depth")
    g.set(17)
    g.set(4)
    assert reg.value("wq_depth") == 4
    ts = reg.timeseries("wq_depth", capacity=4)
    for i in range(10):
        ts.append(float(i), i * 10)
    assert len(ts) == 4  # bounded ring
    assert ts.samples() == [(6.0, 60), (7.0, 70), (8.0, 80), (9.0, 90)]


def test_histogram_log_buckets():
    reg = Registry(rank=0)
    h = reg.histogram("send_s", base=1e-6, mult=10.0, nbuckets=4)
    # bounds: 1e-6, 1e-5, 1e-4, 1e-3 (+ overflow)
    assert h.bounds == pytest.approx((1e-6, 1e-5, 1e-4, 1e-3), rel=1e-9)
    for x in (5e-7, 5e-6, 5e-6, 5e-4, 1.0):
        h.observe(x)
    assert h.counts == [1, 2, 0, 1, 1]
    assert h.n == 5
    assert h.sum == pytest.approx(5e-7 + 1e-5 + 5e-4 + 1.0, rel=1e-6)
    # quantiles interpolate linearly within the bucket the target rank
    # lands in: p50 target = 2.5 of 5, bucket (1e-6, 1e-5] holds ranks
    # 2..3, so 1e-6 + (1e-5 - 1e-6) * 1.5/2
    assert h.quantile(0.5) == pytest.approx(7.75e-6, rel=1e-9)
    # a quantile in the +Inf overflow bucket answers the highest finite
    # bound (Prometheus histogram_quantile convention), never inf
    assert h.quantile(1.0) == pytest.approx(1e-3, rel=1e-9)
    # q=0 pins to the lower edge of the first occupied bucket
    assert h.quantile(0.0) == pytest.approx(0.0, abs=1e-12)


def test_exposition_format():
    reg = Registry(rank=8)
    reg.counter("puts").inc(12)
    reg.counter("tx_msgs", tag="FA_PUT").inc(3)
    reg.gauge("wq_depth").set(7)
    reg.histogram("send_s", nbuckets=2).observe(0.5)
    text = reg.expose()
    assert 'adlb_puts_total{rank="8"} 12' in text
    assert 'adlb_tx_msgs_total{rank="8",tag="FA_PUT"} 3' in text
    assert 'adlb_wq_depth{rank="8"} 7' in text
    assert '# TYPE adlb_send_s histogram' in text
    assert 'adlb_send_s_bucket{le="+Inf",rank="8"} 1' in text
    assert 'adlb_send_s_count{rank="8"} 1' in text
    # point-quantile compat lines ride alongside the cumulative buckets
    assert 'adlb_send_s{quantile="0.5",rank="8"}' in text
    assert 'adlb_send_s{quantile="0.99",rank="8"}' in text


def test_merge_across_ranks():
    a, b = Registry(rank=1), Registry(rank=2)
    a.counter("puts").inc(3)
    b.counter("puts").inc(4)
    a.gauge("wq_depth").set(10)
    b.gauge("wq_depth").set(20)
    for reg, x in ((a, 1e-6), (b, 1e-2)):
        reg.histogram("send_s").observe(x)
    merged = Registry.merge([a.snapshot(), b.snapshot()])
    assert merged["counters"]["puts"] == 7
    # gauges keep per-rank identity
    assert merged["gauges"]["wq_depth{rank=1}"] == 10
    assert merged["gauges"]["wq_depth{rank=2}"] == 20
    assert merged["histograms"]["send_s"]["count"] == 2


# --------------------------------------------------------- flight recorder


def test_flight_recorder_artifact_roundtrip(tmp_path):
    fr = FlightRecorder(5, capacity=4, out_dir=str(tmp_path), role="server")
    reg = Registry(rank=5)
    reg.counter("puts").inc(9)
    reg.timeseries("wq_depth").append(1.0, 3)
    fr.metrics = reg
    fr.context = {"is_master": True}
    for i in range(6):
        fr.record(f"event {i}")
    path = fr.dump_json("unit test")
    assert path is not None and os.path.exists(path)
    doc = json.loads(open(path).read())
    assert doc["rank"] == 5 and doc["role"] == "server"
    assert doc["reason"] == "unit test"
    # the ring is circular: only the last `capacity` events survive
    assert [t for _, t in doc["events"]] == [
        "event 2", "event 3", "event 4", "event 5"
    ]
    assert doc["metrics"]["counters"]["puts"] == 9
    assert doc["metrics"]["series"]["wq_depth"] == [[1.0, 3]]
    assert doc["context"]["is_master"] is True


def test_flight_recorder_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("ADLB_FLIGHT_DIR", raising=False)
    fr = FlightRecorder(1)
    fr.record("x")
    assert fr.dump_json("nope") is None
    # env contract: ADLB_FLIGHT_DIR enables artifacts worlds didn't config
    monkeypatch.setenv("ADLB_FLIGHT_DIR", str(tmp_path))
    assert resolve_flight_dir(None) == str(tmp_path)
    fr2 = FlightRecorder(2)
    assert fr2.dump_json("env") is not None


def _flight_files(d):
    return sorted(f for f in os.listdir(d) if f.startswith("flight-"))


def test_flight_dump_on_injected_abort(tmp_path):
    """A chaos-style world — garbage sprayed at live server ports plus a
    mid-run abort — must leave per-rank JSON post-mortems that
    scripts/obs_report.py can summarize (reuses the chaos-soak helpers)."""
    sys.path.insert(0, SCRIPTS)
    try:
        import chaos_soak
    finally:
        sys.path.remove(SCRIPTS)
    cfg = Config(exhaust_check_interval=0.2, flight_dir=str(tmp_path))
    res = spawn_world(
        4, 2, [1, 2],
        chaos_soak.answer_economy(20, do_abort=True, do_spray=True),
        cfg=cfg, timeout=90.0,
    )
    assert res.aborted, "injected abort did not propagate"
    arts = _flight_files(tmp_path)
    # every server dumps; the aborting rank and at least some collateral
    # app ranks dump too
    server_arts = [a for a in arts if a.startswith(("flight-rank4", "flight-rank5"))]
    assert len(server_arts) == 2, arts
    assert any("abort_initiated" in a or "abort" in a for a in arts)
    doc = json.loads((tmp_path / server_arts[0]).read_text())
    assert doc["role"] == "server"
    assert any("abort" in text for _, text in doc["events"])
    # queue-depth timeline captured on the periodic tick
    assert doc["metrics"]["series"]["wq_depth"], "no wq timeline sampled"
    # offline summary: per-rank last events + counters + timelines
    out = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "obs_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "counter totals" in out.stdout
    assert "wq_depth" in out.stdout
    assert "abort" in out.stdout


# ------------------------------------------------------------ ops endpoint


def test_ops_endpoint_round_trip(tmp_path):
    """8-rank TCP world with the master serving /metrics, /healthz and
    /dump on localhost: per-tag message counters and wq/rq depth gauges
    must be scrapeable live, with the world aggregate rows carrying the
    STAT_APS ring's seq (the issue's acceptance criterion)."""
    port = probe_free_ports(1)[0]
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            for a in range(30):
                ctx.put(struct.pack("<q", a), T)
            time.sleep(0.6)  # let consumers run + the stats ring tick
            out = {}
            for route in ("healthz", "metrics", "dump"):
                out[route] = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/{route}", timeout=10
                ).read().decode()
            ctx.set_problem_done()
            return out
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return n
            ctx.get_reserved(r.handle)
            time.sleep(0.02)
            n += 1

    cfg = Config(ops_port=port, periodic_log_interval=0.1,
                 flight_dir=str(tmp_path))
    res = spawn_world(6, 2, [T], app, cfg=cfg, timeout=90.0)
    got = res.app_results[0]

    health = json.loads(got["healthz"])
    assert health["ok"] is True
    assert health["role"] == "master"
    assert health["nservers"] == 2

    m = got["metrics"]
    # per-tag transport counters from the master's own registry
    assert 'adlb_rx_msgs_total{rank="6",tag="FA_PUT"}' in m
    assert 'adlb_tx_msgs_total{rank="6",tag="TA_PUT_RESP"}' in m
    # queue-depth gauges sampled on the periodic tick
    assert 'adlb_wq_depth{rank="6"}' in m
    assert 'adlb_rq_depth{rank="6"}' in m
    # latency histograms
    assert "adlb_send_s_bucket" in m and "adlb_recv_wait_s_count" in m
    # world aggregate via the existing stats ring, stamped with its seq;
    # the per-server depth rows must cover every server rank
    assert "adlb_stat_aps_seq" in m
    assert "adlb_world_wq_total" in m
    assert 'adlb_server_wq_depth{rank="6"}' in m
    assert 'adlb_server_wq_depth{rank="7"}' in m
    # .. and the exposed aggregate is self-consistent: world totals are
    # the sum of the per-server rows from the SAME STAT_APS record
    per_server = {
        line.split()[0]: float(line.split()[1])
        for line in m.splitlines()
        if line.startswith("adlb_server_wq_depth")
    }
    world_wq = next(
        float(line.split()[1]) for line in m.splitlines()
        if line.startswith("adlb_world_wq_total")
    )
    assert sum(per_server.values()) == world_wq

    dump = json.loads(got["dump"])
    assert dump["record"]["role"] == "server"
    assert dump["record"]["metrics"]["series"]["wq_depth"]
    assert dump["artifact"] and dump["artifact"].endswith(".json")

    assert sum(v for k, v in res.app_results.items() if k != 0) == 30


def test_ops_port_validation():
    with pytest.raises(ValueError):
        Config(ops_port=70000)
    Config(ops_port=None)
    Config(ops_port=0)


# ------------------------------------------------------------ merged trace


def test_merged_trace_client_and_server_share_timeline(tmp_path):
    """Client API spans (pid 0) and server handler / balancer-round spans
    (pid 1) land in ONE Chrome-trace stream on a shared clock, so a
    merged Perfetto file shows both sides of every reserve."""
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            for i in range(10):
                ctx.put(b"w" * 16, T, work_prio=i)
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc < 0:
                break
            ctx.get_reserved(r.handle)
            time.sleep(0.005)
            n += 1
        if ctx.rank == 0:
            ctx.set_problem_done()
        return n

    res = run_world(2, 1, [T], app, cfg=Config(trace=True, balancer="tpu"),
                    timeout=60.0)
    assert sum(res.app_results.values()) == 10
    ev = res.trace_events
    names = span_names(ev)
    # both sides of the put/reserve/get round trips
    assert {"adlb:put", "adlb:reserve", "adlb:get_reserved"} <= names
    assert {"srv:FA_PUT", "srv:FA_RESERVE", "srv:FA_GET_RESERVED"} <= names
    # the balancer thread's rounds trace into the same stream
    assert "balancer:round" in names
    # pid = role; process_name metadata labels both lanes
    pids = {e["pid"] for e in ev if e["ph"] != "M"}
    assert pids == {PID_APP, PID_SERVER}
    meta = {
        e["args"]["name"] for e in ev
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert meta == {"apps", "servers"}
    # one timeline: globally time-sorted, and the server span for a put
    # overlaps the interval in which SOME client-side put span ran
    ts = [e["ts"] for e in ev]
    assert ts == sorted(ts)
    cli_puts = [e for e in ev if e["name"] == "adlb:put"]
    srv_puts = [e for e in ev if e["name"] == "srv:FA_PUT"]
    assert cli_puts and srv_puts
    lo = min(e["ts"] for e in cli_puts)
    hi = max(e["ts"] + e["dur"] for e in cli_puts)
    assert any(lo <= e["ts"] <= hi for e in srv_puts), (
        "server put handling does not overlap client put spans — "
        "clocks not shared?"
    )
    # the dump loads as one valid chrome trace
    out = tmp_path / "merged.json"
    res.save_trace(str(out))
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]


def test_tracer_bounded_memory():
    from adlb_tpu.runtime.trace import Tracer

    tr = Tracer(0, max_events=3)
    for i in range(10):
        tr.instant(f"e{i}")
    assert len(tr.events) == 3
    assert tr.dropped == 7


# ------------------------------------------------- registry in the reactor


def test_server_counters_feed_stats_ring_and_ds_log():
    """The registry replaces the ad-hoc _ds_counters dict: the periodic
    stats ring and the debug-server heartbeat read the same counters the
    reactor increments."""
    T = 1

    def app(ctx):
        if ctx.rank == 0:
            for i in range(5):
                ctx.put(b"x", T)
            time.sleep(0.3)  # let the stats ring tick while work drains
            ctx.set_problem_done()
            return 0
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc < 0:
                return n
            ctx.get_reserved(r.handle)
            time.sleep(0.02)
            n += 1

    from adlb_tpu.runtime import stats as pstats

    lines = []
    pstats.set_sink(lines.append)
    try:
        run_world(2, 1, [T], app,
                  cfg=Config(periodic_log_interval=0.05), timeout=60.0)
    finally:
        pstats.set_sink(None)
    records = pstats.parse_stat_lines(lines)
    assert records, "no STAT_APS records emitted"
    assert records[-1]["total"]["puts"] == 5
