"""SLO engine + burn-rate alerting + live incident capture
(adlb_tpu/obs/slo.py, ISSUE 16 tentpole).

Coverage layers:

* **SnapshotRing** — windowed deltas over timestamped merged registry
  snapshots: baseline selection, zero-clamping under membership churn,
  honest span reporting on a young ring.
* **Objective parsing** — schema defaults (fast window = slow/12,
  floored at two evaluation ticks) and validation errors.
* **Engine lifecycle** — OK→PENDING→FIRING→RESOLVED on a sustained
  burn; a single-tick blip reaches PENDING but never FIRING (the
  multi-window discipline); error-fraction objectives; staleness flags
  evaluation ``degraded`` without zeroing the stale rank's last values;
  epoch churn freezes state transitions (no flapping).
* **Live worlds** (in-proc ElasticWorld) — Config(slo=...) arms the
  master evaluator; /alerts, /flight and POST /slo routes; fired alert
  rows agree fleet-wide via the SS_OBS_SYNC reply ``alerts`` key; a
  page FIRING captures an incident bundle naming the suspect ranks;
  a healthy world under membership churn fires nothing.
* **TCP acceptance** (slow) — a real multi-process fleet with a p99 +
  error objective and a deliberately SIGSTOP-stalled worker drives an
  alert PENDING→FIRING→RESOLVED; the incident bundle names the stalled
  rank and carries the violating (job, type) tails.
"""

import json
import os
import struct
import subprocess
import sys
import time
import urllib.request

import pytest

from adlb_tpu.obs.metrics import Registry, SnapshotRing
from adlb_tpu.obs.slo import (
    FIRING,
    OK,
    PENDING,
    RESOLVED,
    SloEngine,
    parse_objective,
)
from adlb_tpu.runtime.membership import ElasticWorld
from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

T = 1
SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _hist_reg(rank=0):
    reg = Registry(rank)
    h = reg.histogram("unit_total_s", job="0", type="1")
    e = reg.counter("unit_errors", job="0", type="1")
    return reg, h, e


def _merged(reg):
    return Registry.merge([reg.snapshot()])


# ------------------------------------------------------------ snapshot ring


def test_snapshot_ring_counter_and_hist_deltas():
    ring = SnapshotRing(capacity=16)
    reg, h, e = _hist_reg()
    now = 100.0
    for i in range(6):
        e.inc(2)
        h.observe(0.01)
        ring.append(now + i, _merged(reg))
    # window fully inside the ring: baseline = newest entry >= window old
    d, span = ring.counter_delta("unit_errors{job=0,type=1}", 3.0, 105.0)
    assert d == 6.0 and span == pytest.approx(3.0)
    hd = ring.hist_delta("unit_total_s{job=0,type=1}", 3.0, 105.0)
    bounds, counts, n, span = hd
    assert n == 3 and span == pytest.approx(3.0)
    assert sum(counts) == 3
    # window older than the ring: falls back to the oldest entry and
    # reports the ACTUAL covered span, not the requested one
    d, span = ring.counter_delta("unit_errors{job=0,type=1}", 60.0, 105.0)
    assert d == 10.0 and span == pytest.approx(5.0)
    # a key the baseline lacks: hist falls back to full cumulative
    reg.histogram("unit_total_s", job="0", type="9").observe(0.5)
    ring.append(106.0, _merged(reg))
    hd = ring.hist_delta("unit_total_s{job=0,type=9}", 3.0, 106.0)
    assert hd is not None and hd[2] == 1
    # a key that never appeared answers None
    assert ring.hist_delta("unit_total_s{job=7,type=7}", 3.0, 106.0) is None


def test_snapshot_ring_clamps_on_shrinking_merge():
    """Membership churn shrinks the merged view (a dead server's cells
    leave it): cumulative deltas must clamp at zero, never report a
    negative rate."""
    ring = SnapshotRing(capacity=8)
    a, b = Registry(1), Registry(2)
    a.counter("unit_errors", job="0", type="1").inc(5)
    b.counter("unit_errors", job="0", type="1").inc(7)
    ring.append(10.0, Registry.merge([a.snapshot(), b.snapshot()]))
    # rank 2 dies; the merge now carries only rank 1's 5
    ring.append(12.0, Registry.merge([a.snapshot()]))
    d, _span = ring.counter_delta("unit_errors{job=0,type=1}", 2.0, 12.0)
    assert d == 0.0  # clamped, not -7
    assert ring.window_delta(2.0, 12.0)["counters"] == {}


def test_snapshot_ring_grow_preserves_entries():
    ring = SnapshotRing(capacity=4)
    for i in range(4):
        ring.append(float(i), {"counters": {"c": i}})
    ring.grow(8)
    assert len(ring) == 4 and ring.capacity == 8
    ring.grow(2)  # never shrinks
    assert ring.capacity == 8


# ------------------------------------------------------------- objectives


def test_parse_objective_defaults():
    o = parse_objective(
        {"job": 0, "type": 3, "p99_ms": 50, "error_frac": 0.001,
         "window_s": 300}, eval_interval=1.0,
    )
    assert o["name"] == "job0-type3-p99+err"
    assert o["fast_s"] == pytest.approx(25.0)  # window / 12
    assert o["for_s"] == pytest.approx(2.0)    # two eval ticks
    assert o["severity"] == "page"
    # fast window floors at two eval ticks for tiny windows
    o = parse_objective({"type": 1, "p99_ms": 5, "window_s": 3},
                        eval_interval=0.5)
    assert o["fast_s"] == pytest.approx(1.0)


@pytest.mark.parametrize("bad", [
    {"job": 0, "type": 1, "window_s": 60},            # no bound at all
    {"type": 1, "p99_ms": 0, "window_s": 60},         # p99 <= 0
    {"type": 1, "error_frac": 2.0, "window_s": 60},   # frac > 1
    {"type": 1, "p99_ms": 5},                         # no window
    {"type": 1, "p99_ms": 5, "window_s": 60, "severity": "sms"},
    "not-a-dict",
])
def test_parse_objective_rejects(bad):
    with pytest.raises(ValueError):
        parse_objective(bad)


def test_engine_rejects_duplicates_and_caps():
    eng = SloEngine(0.5)
    eng.add({"name": "x", "type": 1, "p99_ms": 5, "window_s": 10})
    with pytest.raises(ValueError, match="duplicate"):
        eng.add({"name": "x", "type": 1, "p99_ms": 9, "window_s": 10})


# -------------------------------------------------------- engine lifecycle


def _drive(eng, reg, now, ticks, observe, tick_s=0.5, stale=None):
    """Advance the engine `ticks` evaluations, calling observe() before
    each; returns (states_seen, final_now)."""
    states = []
    for _ in range(ticks):
        observe()
        eng.evaluate(now, _merged(reg), stale or [])
        states.append(eng.alerts_pub[0]["state"])
        now += tick_s
    return states, now


def test_engine_full_lifecycle():
    eng = SloEngine(0.5)
    eng.add({"job": 0, "type": 1, "p99_ms": 5, "window_s": 10,
             "for_s": 1.0, "cooldown_s": 1.0})
    reg, h, _e = _hist_reg()
    now = 100.0
    healthy, now = _drive(
        eng, reg, now, 8, lambda: [h.observe(0.001) for _ in range(20)])
    assert set(healthy) == {OK}
    burn, now = _drive(
        eng, reg, now, 8, lambda: [h.observe(0.05) for _ in range(20)])
    assert PENDING in burn and FIRING in burn
    assert burn.index(PENDING) < burn.index(FIRING)
    rec, now = _drive(
        eng, reg, now, 40, lambda: [h.observe(0.001) for _ in range(200)])
    assert RESOLVED in rec
    assert [
        (t["from"], t["to"]) for t in eng.history
    ] == [(OK, PENDING), (PENDING, FIRING), (FIRING, RESOLVED)]
    row = eng.alerts_pub[0]
    assert row["fire_count"] == 1 and row["fired_at"] is not None


def test_engine_blip_pends_but_never_fires():
    """One burst of slow closes inside an otherwise healthy stream:
    the fast window trips (PENDING) but the slow window's p99 refuses
    to confirm — the alert must fall back to OK without FIRING."""
    eng = SloEngine(0.5)
    eng.add({"job": 0, "type": 1, "p99_ms": 5, "window_s": 30,
             "fast_s": 1.0, "for_s": 1.0})
    reg, h, _e = _hist_reg()
    now = 100.0
    _, now = _drive(
        eng, reg, now, 20, lambda: [h.observe(0.001) for _ in range(50)])
    # the blip: one tick of slow closes
    for _ in range(3):
        h.observe(0.05)
    eng.evaluate(now, _merged(reg), [])
    now += 0.5
    states, now = _drive(
        eng, reg, now, 12, lambda: [h.observe(0.001) for _ in range(50)])
    assert FIRING not in states
    assert all(t["to"] != FIRING for t in eng.history)


def test_engine_error_fraction_burn():
    eng = SloEngine(0.5)
    eng.add({"job": 0, "type": 1, "error_frac": 0.01, "window_s": 10,
             "for_s": 0.5, "cooldown_s": 0.5})
    reg, h, e = _hist_reg()
    now = 50.0

    def bad():
        for _ in range(10):
            h.observe(0.001)
        e.inc(5)  # 50% errors >> 1% objective

    states, now = _drive(eng, reg, now, 6, bad)
    assert FIRING in states
    row = eng.alerts_pub[0]
    assert row["fast"].get("errors", 0) > 0


def test_engine_staleness_degrades_not_zeroes():
    """A stale rank's last snapshot stays in the merge (the caller keeps
    feeding it), so the burn math still sees its cells — but every row
    is flagged degraded with the rank list."""
    eng = SloEngine(0.5)
    eng.add({"job": 0, "type": 1, "p99_ms": 5, "window_s": 10})
    a, b = Registry(1), Registry(2)
    for reg in (a, b):
        reg.histogram("unit_total_s", job="0", type="1").observe(0.001)
    stale_snap = b.snapshot()  # rank 2 goes quiet; this is its last word
    now = 10.0
    for i in range(4):
        a.histogram("unit_total_s", job="0", type="1").observe(0.001)
        eng.evaluate(now, Registry.merge([a.snapshot(), stale_snap]),
                     [2])
        now += 0.5
    row = eng.alerts_pub[0]
    assert row["degraded"] and row["stale_ranks"] == [2]
    # the in-window closes are rank 1's live ones (rank 2's predate the
    # window start, so the delta rightly excludes them)...
    assert row["slow"]["closes"] == 3
    # ...but the cumulative view the ring holds still carries rank 2's
    # last word — it degraded to "last known", it did not zero
    _t, snap = eng.ring.latest()
    assert snap["histograms"]["unit_total_s{job=0,type=1}"]["count"] == 6


def test_engine_churn_hold_freezes_transitions():
    """An epoch bump opens a grace hold: burn keeps updating but the
    state machine cannot transition — elastic churn cannot flap
    PENDING/FIRING/RESOLVED."""
    eng = SloEngine(0.5)
    eng.add({"job": 0, "type": 1, "p99_ms": 5, "window_s": 10,
             "for_s": 0.5})
    reg, h, _e = _hist_reg()
    now = 100.0
    eng.note_epoch(1, now)
    held_states = []
    for i in range(8):
        for _ in range(20):
            h.observe(0.05)  # hard violation every tick
        if i % 2 == 0:
            eng.note_epoch(10 + i, now)  # churn keeps bumping the epoch
        eng.evaluate(now, _merged(reg), [])
        held_states.append(eng.alerts_pub[0]["state"])
        now += 0.5
    # PENDING is reachable (entry is allowed); FIRING is not while held
    assert FIRING not in held_states
    assert eng.alerts_pub[0]["held"]
    # once churn stops and the hold expires, the sustained burn fires
    now += 5.0
    for _ in range(3):
        for _ in range(20):
            h.observe(0.05)
        eng.evaluate(now, _merged(reg), [])
        now += 0.5
    assert eng.alerts_pub[0]["state"] == FIRING


# ---------------------------------------------------------- live worlds


def _consume(ctx, pace=0.002):
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(w.payload)
        if pace:
            time.sleep(pace)


def _producer(n):
    def app(ctx):
        for i in range(n):
            ctx.put(struct.pack("<q", i), T)
        return _consume(ctx)
    return app


def _wait(pred, timeout=20.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(tick)
    return None


def _get(port, route):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{route}", timeout=10).read().decode())


def _post(port, route, doc):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{route}",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10)
                      .read().decode())


def test_world_alert_agreement_and_incident(tmp_path):
    """In-proc fleet: a violation injected into the master's registry
    drives PENDING→FIRING; the rows every NON-master heard over the
    SS_OBS_SYNC reply `alerts` key agree with the master's /alerts; the
    page FIRING captured an incident bundle (served at /incidents and
    written to flight_dir) naming the objective; POST /slo adds a
    second objective to the live engine; /flight indexes the bundle."""
    obj = {"name": "inj", "job": 0, "type": 1, "p99_ms": 5,
           "window_s": 4, "fast_s": 0.4, "for_s": 0.2,
           "cooldown_s": 0.3, "min_count": 1}
    cfg = Config(
        exhaust_check_interval=0.2, ops_port=0, obs_sync_interval=0.1,
        slo=(obj,), flight_dir=str(tmp_path),
    )
    ew = ElasticWorld(2, 2, [T], cfg=cfg)
    ew.run_app(0, _producer(10))
    ew.run_app(1, _consume)
    # hold the world open past exhaustion while we drive the engine
    jw = ew.attach_ctx()
    try:
        master = ew.master
        assert _wait(lambda: master.ops is not None)
        port = master.ops.port
        doc = _get(port, "alerts")
        assert doc["enabled"] and doc["objectives"][0]["name"] == "inj"

        # POST /slo: a second objective lands on the live engine;
        # malformed bodies answer 400 from the HTTP thread
        out = _post(port, "slo", {"name": "extra", "job": 0, "type": 2,
                                  "error_frac": 0.5, "window_s": 30})
        assert out["n_objectives"] == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, "slo", {"job": 0, "type": 2, "window_s": 30})
        assert ei.value.code == 400

        # inject the violation straight into the master's registry
        # (GIL-atomic writes; the eval tick merges its own snapshot)
        h = master.metrics.histogram("unit_total_s", job="0", type="1")

        def burn():
            for _ in range(50):
                h.observe(0.05)
            return [a for a in _get(port, "alerts")["alerts"]
                    if a["name"] == "inj" and a["state"] == FIRING]

        assert _wait(burn, timeout=30.0, tick=0.2), "alert never fired"
        assert master.metrics.value("alerts_firing") == 1

        # fleet-wide agreement: every non-master heard the same rows
        # over the SS_OBS_SYNC reply `alerts` key
        def agree():
            rows = [s._slo_alerts_remote for s in ew.servers.values()
                    if not s.is_master]
            return rows and all(
                any(r[0] == "inj" and r[1] == FIRING for r in got)
                for got in rows
            )

        assert _wait(agree, timeout=10.0), "gossip never agreed"
        wire = master._slo_alerts_wire
        assert any(r[0] == "inj" and r[1] == FIRING for r in wire)

        # the page FIRING captured an incident bundle
        inc = _get(port, "incidents")
        assert inc["count"] >= 1
        bundle = inc["incidents"][-1]
        assert bundle["incident"] == "inj"
        assert bundle["job"] == 0 and bundle["type"] == 1
        assert "fleet" in bundle and bundle["epoch"] >= 0
        assert bundle["metrics_delta"]["span_s"] > 0
        # ...and wrote the durable copy the /flight index discovers
        files = list(tmp_path.glob("incident-inj-p*.json"))
        assert len(files) == 1
        on_disk = json.loads(files[0].read_text())
        assert on_disk["incident"] == "inj" and on_disk["schema"] == 1
        idx = _get(port, "flight")
        kinds = {a["file"]: a["kind"] for a in idx["artifacts"]}
        assert kinds.get(files[0].name) == "incident"
    finally:
        jw.ctx.detach_world()
        ew.finish(timeout=60)


def test_world_healthy_churn_fires_nothing():
    """The no-flap satellite: a HEALTHY world under elastic churn —
    attach, detach, scale-out, all bumping the fleet epoch — must not
    flap alert state: zero transitions, alerts stay OK, nothing
    degraded once churn settles."""
    obj = {"name": "guard", "job": 0, "type": 1, "p99_ms": 60000,
           "window_s": 4, "fast_s": 0.4, "for_s": 0.2}
    cfg = Config(
        exhaust_check_interval=0.2, ops_port=0, obs_sync_interval=0.1,
        slo=(obj,),
    )
    ew = ElasticWorld(2, 2, [T], cfg=cfg)
    ew.run_app(0, _producer(30))
    ew.run_app(1, _consume)
    jw = ew.attach_ctx()
    try:
        master = ew.master
        assert _wait(lambda: master._slo_engine is not None
                     and len(master._slo_engine.ring) > 0)
        epoch0 = master.world.epoch
        # churn: a put-and-detach rank plus a server scale-out
        jw2 = ew.attach_ctx()
        jw2.ctx.put(struct.pack("<q", 777), T)
        assert jw2.ctx.detach_world() == ADLB_SUCCESS
        ew.scale_out()
        assert _wait(lambda: master.world.epoch > epoch0)
        time.sleep(1.0)  # several evaluation ticks across the churn
        eng = master._slo_engine
        assert list(eng.history) == []  # no transitions at all
        assert all(a["state"] == OK for a in eng.alerts_pub)
        assert master.metrics.value("alerts_firing") == 0
        assert _get(master.ops.port, "alerts")["firing"] == 0
    finally:
        jw.ctx.detach_world()
        ew.finish(timeout=60)


# ------------------------------------------------------- obs_report modes


def test_obs_report_alerts_incidents_index(tmp_path):
    alerts_doc = {
        "enabled": True, "firing": 1,
        "objectives": [{"name": "a"}],
        "alerts": [{"name": "a", "state": "FIRING", "severity": "page",
                    "burn_fast": 2.5, "burn_slow": 1.2, "fire_count": 1,
                    "degraded": True, "stale_ranks": [3], "held": False}],
        "history": [{"at": 12.0, "name": "a", "from": "PENDING",
                     "to": "FIRING", "severity": "page",
                     "burn_fast": 2.5, "burn_slow": 1.2}],
    }
    (tmp_path / "alerts.json").write_text(json.dumps(alerts_doc))
    bundle = {
        "schema": 1, "incident": "a", "severity": "page", "job": 0,
        "type": 1, "epoch": 2, "suspect_ranks": [2, 4],
        "transition": {"from": "PENDING", "to": "FIRING",
                       "burn_fast": 2.5, "burn_slow": 1.2},
        "metrics_delta": {"span_s": 4.0, "counters": {"x": 1},
                          "histograms": {}},
        "stacks": {"4": [["server;run", 9]]},
        "tails": [{"trace_id": -5, "job": 0, "type": 1,
                   "end": "delivered", "why": ["expired_lease"],
                   "total_s": 2.5, "slow_stage": "match", "slow_rank": 4,
                   "excess_s": 2.4,
                   "spans": [["put_recv", 3, 1.0], ["match", 4, 3.5]]}],
    }
    (tmp_path / "incident-a-p1.json").write_text(json.dumps(bundle))
    env = {**os.environ, "PYTHONPATH": os.path.dirname(SCRIPTS)}

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(SCRIPTS, "obs_report.py"),
             *args],
            capture_output=True, text=True, env=env, timeout=60,
        )

    r = run("--alerts", str(tmp_path / "alerts.json"))
    assert r.returncode == 0, r.stderr
    assert "FIRING" in r.stdout and "degraded([3])" in r.stdout
    assert "PENDING -> FIRING" in r.stdout

    r = run("--incidents", str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "incident a" in r.stdout
    assert "suspect ranks: [2, 4]" in r.stdout
    assert "server;run" in r.stdout

    r = run("--index", str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "incident-a-p1.json" in r.stdout and "incident" in r.stdout


# ------------------------------------------------------- TCP acceptance


@pytest.mark.slow
def test_acceptance_slo_incident_tcp(tmp_path):
    """The ISSUE 16 acceptance world: a real TCP fleet with a p99 +
    error objective and a worker that SIGSTOPs through its leases. The
    alert walks PENDING→FIRING→RESOLVED, and the captured incident
    bundle names the stalled rank (via the leases_expired_by owner
    delta) and carries the violating (job, type) tail journeys."""
    from adlb_tpu.runtime.faults import sigstop_self  # noqa: F401

    port = probe_free_ports(1)[0]
    n_fast = 80
    try:
        load = min(max(os.getloadavg()[0] / max(os.cpu_count() or 1, 1),
                       1.0), 3.0)
    except OSError:
        load = 1.0
    lease = round(1.2 * load, 2)
    obj = {
        "name": "p99-acc", "job": 0, "type": T, "p99_ms": 500,
        "error_frac": 0.05, "window_s": round(4 * lease, 2),
        "fast_s": round(max(lease, 1.0), 2), "for_s": 0.4,
        "cooldown_s": 1.0, "min_count": 4,
    }

    def fetch(route):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{route}", timeout=10,
        ).read().decode())

    def app(ctx):
        from adlb_tpu.runtime.faults import sigstop_self

        if ctx.rank == 1:
            # fast consumer: the healthy baseline AND the eventual
            # drain of re-enqueued expired units
            n = 0
            while True:
                rc, _got = ctx.get_work([T])
                if rc != ADLB_SUCCESS:
                    return n
                n += 1
        if ctx.rank == 2:
            # the stalled worker: hold leases through SIGSTOPs, never
            # fetch — every lease expires against this rank
            stalls = 0
            while True:
                rc, r = ctx.reserve([T])
                if rc != ADLB_SUCCESS:
                    return stalls
                stalls += 1
                sigstop_self(round(lease * 1.5, 2))
        # rank 0: producer + observer
        for i in range(n_fast):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        out = {"states": []}

        def note(timeout, want):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                doc = fetch("alerts")
                row = next((a for a in doc["alerts"]
                            if a["name"] == "p99-acc"), None)
                if row and (not out["states"]
                            or out["states"][-1] != row["state"]):
                    out["states"].append(row["state"])
                if row and row["state"] == want:
                    return True
                time.sleep(0.3)
            return False

        # healthy phase first: the bulk must close fast and fire
        # nothing while rank 2 burns through the stall units
        time.sleep(1.0)
        # stall food: targeted at rank 2, small budget — expiries then
        # quarantines, all against owner rank 2
        for i in range(3):
            assert ctx.put(b"stall%d" % i, T, target_rank=2) \
                == ADLB_SUCCESS
        out["fired"] = note(90.0, "FIRING")
        if out["fired"]:
            out["incidents"] = fetch("incidents")
            out["alerts_at_fire"] = fetch("alerts")
            out["flight_index"] = fetch("flight")
        # recovery: flood the window with fast closes so the burn ages
        # out, then wait for RESOLVED
        for i in range(n_fast):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        out["resolved"] = note(90.0, "RESOLVED")
        ctx.set_problem_done()
        return out

    cfg = Config(
        balancer="steal", ops_port=port, trace_sample=0.0,
        obs_sync_interval=0.2, exhaust_check_interval=0.2,
        lease_timeout_s=lease, max_unit_retries=1,
        on_worker_failure="reclaim", flight_dir=str(tmp_path),
        slo=(obj,), profile_hz=19.0,
    )
    res = spawn_world(3, 2, [T], app, cfg=cfg, timeout=300.0)
    got = res.app_results[0]
    assert got["fired"], f"alert never fired; states={got['states']}"
    assert got["resolved"], \
        f"alert never resolved; states={got['states']}"
    # lifecycle order as observed from /alerts
    states = got["states"]
    assert states.index("FIRING") < states.index("RESOLVED")
    # the incident bundle: right objective, right (job, type), and the
    # stalled rank named as a suspect via the lease-expiry owner delta
    inc = got["incidents"]
    assert inc["count"] >= 1
    bundle = inc["incidents"][-1]
    assert bundle["incident"] == "p99-acc"
    assert bundle["job"] == 0 and bundle["type"] == T
    assert 2 in bundle["suspect_ranks"], bundle["suspect_ranks"]
    # violating (job, type) tails rode along, epoch-correct topology too
    assert bundle["tails"], "bundle carried no tail journeys"
    assert all(j["job"] == 0 and j["type"] == T
               for j in bundle["tails"])
    assert any("expired_lease" in (j.get("why") or [])
               or j.get("end") == "quarantined"
               for j in bundle["tails"])
    assert bundle["fleet"]["epoch"] == bundle["epoch"]
    # profiler stacks for at least one responsible rank (the fleet is
    # profiled at 19 Hz; span ranks are the unit's server hops)
    assert bundle["stacks"], "bundle carried no profiler stacks"
    # durable copy on disk, discoverable through /flight
    files = list(tmp_path.glob("incident-p99-acc-p*.json"))
    assert files, "incident bundle never written to flight_dir"
    names = [a["file"] for a in got["flight_index"]["artifacts"]]
    assert files[0].name in names
