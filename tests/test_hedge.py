"""Tail hedging: budgeted speculative re-dispatch of p99 stragglers,
fenced first-wins (ISSUE 17 tentpole, adlb_tpu/runtime/hedge.py).

Coverage layers:

* **Trigger + bucket mechanics** — the pure `should_hedge` predicate
  (age floor, threshold crossing, suspect-owner fallback) and the
  per-job token bucket (initial grant, burst cap, credit-per-delivery,
  refund, sticky-vs-transient vetoes), plus the group bookkeeping that
  the server's settle path drives.
* **Shared stall heuristic** (satellite: PR 16 extraction) — the
  module-level `suspect_ranks` consumed by BOTH the incident builder
  and the hedge trigger, tested directly over all three signals.
* **Server race lattice** — handler-driven Servers: a straggling lease
  past the gossiped p99 launches ONE pinned sibling at an
  already-parked different rank; first terminal wins on BOTH orderings
  with the loser fenced (ADLB_FENCED at the fetch, never a second
  payload); budget and backpressure vetoes (sticky where overload is
  the cause, structural "no vetoed-then-launched"); expiry/rank-death
  of a racing member retires the copy while the LAST live copy always
  re-enters service; quarantine terminals settle the race too.
* **Durability** — OP_HEDGE rides replication + WAL append-only:
  mirror lifecycle (OP_PUT supersedes the mark; consume/remove/
  quarantine pop it), failover adoption drops live siblings and FENCES
  their owners (no miscounted loss), cold restart re-executes only the
  origin, compaction re-seeds marks for open races.
* **Observability** — hedged journeys ALWAYS promote to the tail store
  with the `hedge` hop and `why=["hedged"]`; SLO incident bundles
  carry the burn-window hedge counter delta; unconfigured worlds are
  frame-identical (no hedge counters exist to gossip).
* **End-to-end** — an in-proc ElasticWorld where a sleeping worker's
  straggler is rescued by a hedge long before its (long) lease could
  expire, with exact exactly-once conservation; and the slow-marked
  TCP acceptance world: a SIGSTOP'd worker under hedging completes
  materially faster than the lease-expiry-only world.
"""

import struct
import time

import pytest

from adlb_tpu.obs.slo import suspect_ranks
from adlb_tpu.runtime.hedge import (
    BURST_TOKENS,
    HedgeManager,
    INITIAL_TOKENS,
    should_hedge,
)
from adlb_tpu.runtime.membership import ElasticWorld
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.queues import WorkUnit
from adlb_tpu.runtime.replica import ReplicaMirror, ReplicationLog
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_FENCED, ADLB_RETRY, ADLB_SUCCESS

T = 1


# ----------------------------------------------- trigger + bucket mechanics


def test_should_hedge_trigger_matrix():
    # below the age floor nothing fires, whatever the evidence
    assert not should_hedge(0.04, 0.01, True, min_age_s=0.05)
    # past the floor: the gossiped p99 crossing fires
    assert should_hedge(0.30, 0.25, False, min_age_s=0.05)
    assert not should_hedge(0.20, 0.25, False, min_age_s=0.05)
    # no threshold armed: the suspect-owner signature is the fallback
    assert should_hedge(0.30, None, True, min_age_s=0.05)
    assert not should_hedge(0.30, None, False, min_age_s=0.05)


def test_budget_token_bucket():
    hm = HedgeManager(0.25)
    assert hm.tokens(0) == INITIAL_TOKENS
    assert hm.try_debit(0)  # the initial grant funds one launch
    assert hm.tokens(0) == 0.0
    assert not hm.try_debit(0)  # empty: vetoed until deliveries refill
    for _ in range(3):
        hm.credit(0)
    assert not hm.try_debit(0)  # 0.75 < 1.0
    hm.credit(0)
    assert hm.try_debit(0)  # 4 deliveries bought 1 launch: frac exact
    hm.refund(0)
    assert hm.tokens(0) == 1.0  # a launch that found no taker undoes
    for _ in range(100):
        hm.credit(0)
    assert hm.tokens(0) == BURST_TOKENS  # bounded burst, not unbounded
    # per-job isolation: job 7's bucket is its own
    assert hm.tokens(7) == INITIAL_TOKENS


def test_veto_stickiness_is_bounded():
    hm = HedgeManager(0.5)
    hm.veto(5)
    assert hm.is_vetoed(5) and not hm.is_vetoed(6)
    for s in range(100000):  # far past MAX_VETOED: bounded, FIFO evict
        hm.veto(1000 + s)
    assert not hm.is_vetoed(5)
    assert len(hm._vetoed) <= 65536


def test_group_settle_both_orders_and_drop():
    hm = HedgeManager(0.5)
    hm.open(10, 11, job=0)
    assert hm.is_member(10) and hm.is_member(11)
    assert hm.group_of(11).origin == 10
    assert sorted(hm.survivors_of(10)) == [11]
    # sibling terminates first: origin is the loser
    assert hm.settle(11) == (10, [10])
    assert hm.settle(11) is None  # exactly once: the group dissolved
    assert not hm.is_member(10)
    # origin terminates first: sibling is the loser
    hm.open(20, 21, job=0)
    assert hm.settle(20) == (20, [21])
    # drop dissolves when one member remains
    hm.open(30, 31, job=0)
    hm.drop(30)
    assert not hm.is_member(31), "sole survivor is an ordinary unit"
    assert list(hm.live_siblings()) == []


# ------------------------------------- shared stall heuristic (satellite)


def test_suspect_ranks_unions_three_signals():
    tails = [{"slow_rank": 5, "why": ["slow"]}, {"why": ["slow"]}]
    deltas = {
        "leases_expired_by{owner=7}": 2.0,  # grew: suspect
        "leases_expired_by{owner=8}": 0.0,  # flat: not
        "leases_expired_by{owner=bogus}": 3.0,  # unparseable: ignored
        "puts": 9.0,  # unrelated cell: ignored
    }
    assert suspect_ranks(["3"], tails, deltas) == {3, 5, 7}
    # every input is optional — each caller feeds what its window has
    assert suspect_ranks(None, None, None) == set()
    assert suspect_ranks((), (), {}) == set()


# ------------------------------------------------- server race lattice


def _srv(**cfg_kw):
    """A hedging Server on an in-proc fabric, driven handler-by-handler.
    world: apps 0..1, servers 2..3 (we drive rank 2)."""
    cfg_kw.setdefault("on_worker_failure", "reclaim")
    cfg_kw.setdefault("lease_timeout_s", 0.5)
    cfg_kw.setdefault("hedge_budget_frac", 0.5)
    cfg_kw.setdefault("hedge_min_age_ms", 50.0)
    world = WorldSpec(nranks=4, nservers=2, types=(T,))
    fabric = InProcFabric(4)
    return Server(world, Config(**cfg_kw), fabric.endpoint(2)), fabric


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def _put(srv, src=0, payload=b"unit", work_type=T, target=-1):
    srv._handle(msg(Tag.FA_PUT, src, payload=payload, work_type=work_type,
                    prio=0, target_rank=target, answer_rank=-1,
                    common_len=0, common_server=-1, common_seqno=-1))


def _reserve(srv, src, rqseqno=1, types=(T,)):
    srv._handle(msg(Tag.FA_RESERVE, src, req_types=list(types), hang=True,
                    rqseqno=rqseqno))


def _hedge_setup(srv, fabric, thr=0.2, age=1.0):
    """put -> rank 0 pins -> rank 1 parks -> scan launches the sibling.
    Returns (origin_seqno, sibling_seqno)."""
    _put(srv)
    [u] = list(srv.wq.units())
    origin = u.seqno
    _reserve(srv, 0)
    _drain(fabric, 0)
    _reserve(srv, 1)
    assert not [m for m in _drain(fabric, 1)
                if m.tag is Tag.TA_RESERVE_RESP], "rank 1 did not park"
    srv.journeys.tail_thr[(0, T)] = thr
    srv._scan_hedges(time.monotonic() + age)
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_RESERVE_RESP][-1]
    assert resp.rc == ADLB_SUCCESS
    return origin, resp.handle[0]


def _fetch(srv, fabric, rank, seqno):
    srv._handle(msg(Tag.FA_GET_RESERVED, rank, seqno=seqno))
    return [m for m in _drain(fabric, rank)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]


def test_hedge_launches_pinned_sibling_at_parked_rank():
    srv, fabric = _srv()
    origin, sib = _hedge_setup(srv, fabric)
    assert sib != origin
    assert srv.metrics.value("hedges_launched") == 1
    # both copies pinned under DISTINCT lease identities (no sibling
    # ever sits unpinned where migration/push/RFR could move it)
    assert srv.wq.count == 2 and len(srv.leases) == 2
    o, s = srv.wq.get(origin), srv.wq.get(sib)
    assert o.pinned and o.pin_rank == 0
    assert s.pinned and s.pin_rank == 1
    assert srv.hedges.is_member(origin) and srv.hedges.is_member(sib)
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("hedge_launched") and "why=thr" in t
               for t in texts)
    # the budget paid for it
    assert srv.hedges.tokens(0) == 0.0


@pytest.mark.parametrize("winner", ["sibling", "origin"])
def test_first_terminal_wins_loser_fenced(winner):
    srv, fabric = _srv()
    origin, sib = _hedge_setup(srv, fabric)
    first = (1, sib) if winner == "sibling" else (0, origin)
    second = (0, origin) if winner == "sibling" else (1, sib)
    resp = _fetch(srv, fabric, *first)
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"unit"
    # the first terminal dissolved the race: the loser is OUT of the
    # books before any second payload could leave
    assert srv.wq.count == 0 and len(srv.leases) == 0
    loser_rank, loser_seqno = second
    assert (loser_seqno, loser_rank) in srv._fences
    resp = _fetch(srv, fabric, *second)
    assert resp.rc == ADLB_FENCED, "second delivery left the books"
    assert srv.metrics.value("hedges_fenced") == 1
    assert srv.metrics.value("hedges_won") == \
        (1 if winner == "sibling" else 0)
    # books conserved: one put, one delivery, nothing queued or leased
    assert srv.wq.num_unpinned() == 0


def test_min_age_floor_and_skip_rules():
    srv, fabric = _srv(hedge_min_age_ms=200.0)
    _put(srv)
    _reserve(srv, 0)
    _drain(fabric, 0)
    _reserve(srv, 1)
    srv.journeys.tail_thr[(0, T)] = 0.01
    # under the floor: nothing, whatever the threshold says
    srv._scan_hedges(time.monotonic() + 0.1)
    assert srv.metrics.value("hedges_launched") == 0
    # a TARGETED straggler never hedges (may not run elsewhere)
    srv2, fabric2 = _srv()
    _put(srv2, target=1)
    _reserve(srv2, 1)
    _drain(fabric2, 1)
    _reserve(srv2, 0)
    srv2.journeys.tail_thr[(0, T)] = 0.01
    srv2._scan_hedges(time.monotonic() + 1.0)
    assert srv2.metrics.value("hedges_launched") == 0


def test_budget_veto_then_deliveries_refill():
    srv, fabric = _srv(hedge_budget_frac=0.5)
    origin, sib = _hedge_setup(srv, fabric)  # spent the initial token
    # a second straggler with an empty bucket: transient budget veto
    _put(srv, payload=b"second")
    second = [u.seqno for u in srv.wq.units()
              if u.seqno not in (origin, sib)][0]
    _reserve(srv, 0, rqseqno=2)  # rank 0 leases "second": a straggler
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_vetoed", reason="budget") >= 1
    assert not srv.hedges.is_vetoed(second), "budget veto must not stick"
    # two deliveries at frac=0.5 fund the next launch
    resp = _fetch(srv, fabric, 1, sib)
    assert resp.rc == ADLB_SUCCESS
    assert srv.hedges.tokens(0) == 0.5
    srv.hedges.credit(0)  # the second delivery's credit
    _reserve(srv, 1, rqseqno=3)  # a fresh parked taker for the launch
    before = srv.metrics.value("hedges_launched")
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_launched") == before + 1


def test_backpressure_veto_is_sticky():
    srv, fabric = _srv(max_malloc_per_server=100, mem_soft_frac=0.5)
    _put(srv, payload=b"x" * 60)  # 60/100: above the soft watermark
    [u] = list(srv.wq.units())
    _reserve(srv, 0)
    _drain(fabric, 0)
    _reserve(srv, 1)
    srv.journeys.tail_thr[(0, T)] = 0.01
    assert srv.mem.under_pressure
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_launched") == 0
    assert srv.metrics.value("hedges_vetoed", reason="backpressure") == 1
    assert srv.hedges.is_vetoed(u.seqno)
    # pressure relieved later: the veto STAYS — overload was the moment
    # a retry would have started the storm (structural no-storm)
    srv.mem.free(50)
    assert not srv.mem.under_pressure
    srv._scan_hedges(time.monotonic() + 2.0)
    assert srv.metrics.value("hedges_launched") == 0, \
        "vetoed-then-launched must be impossible"
    srv.mem.alloc(50)  # restore the books for teardown


def test_no_taker_refunds_budget_not_sticky():
    srv, fabric = _srv()
    _put(srv)
    [u] = list(srv.wq.units())
    _reserve(srv, 0)
    _drain(fabric, 0)
    # nobody parked: no launch, token refunded, veto transient
    srv.journeys.tail_thr[(0, T)] = 0.01
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_launched") == 0
    assert srv.metrics.value("hedges_vetoed", reason="no_taker") == 1
    assert srv.hedges.tokens(0) == INITIAL_TOKENS
    assert not srv.hedges.is_vetoed(u.seqno)
    # the straggler's OWN rank parking again must not count as a taker
    _reserve(srv, 0, rqseqno=2)
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_launched") == 0


def test_suspect_owner_trigger_with_decay_hold():
    srv, fabric = _srv()  # NO threshold armed anywhere
    _put(srv)
    _reserve(srv, 0)
    _drain(fabric, 0)
    _reserve(srv, 1)
    # the PR 16 stall signature: rank 0's lease-expiry cell grew inside
    # the scan window (as _expire_lease would have bumped it)
    srv.metrics.counter("leases_expired_by", owner="0").inc()
    srv._scan_hedges(time.monotonic() + 1.0)
    assert srv.metrics.value("hedges_launched") == 1
    texts = [t for _, t in srv.flight.entries()]
    assert any("why=suspect" in t for t in texts)
    # the point event decays into a held suspicion window, then expires
    assert 0 in srv._hedge_suspect_until
    far = time.monotonic() + 3600.0
    assert srv._hedge_suspects(far) == set(), "suspicion never decayed"


def test_racing_member_expiry_retires_copy_survivor_delivers():
    srv, fabric = _srv()
    origin, sib = _hedge_setup(srv, fabric)
    # the origin's lease expires (owner silent 1.5x the timeout) while
    # the sibling still races: the copy RETIRES — re-enqueueing it
    # would put two live duplicates into open matching
    for ls in list(srv.leases.leases()):
        if ls.seqno == origin:
            ls.granted_at -= 0.75
    srv._last_heard[0] -= 0.75
    srv._scan_leases(time.monotonic())
    assert srv.wq.get(origin) is None, "racing member re-enqueued"
    assert srv.wq.count == 1
    assert (origin, 0) in srv._fences
    # the surviving sibling dissolved into an ordinary unit and delivers
    assert not srv.hedges.is_member(sib)
    resp = _fetch(srv, fabric, 1, sib)
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"unit"
    assert srv.wq.count == 0


def test_last_live_copy_always_reenters_service():
    srv, fabric = _srv()
    origin, sib = _hedge_setup(srv, fabric)
    # BOTH owners go quiet past expiry (1.5x the timeout — short of the
    # 2x rank-HUNG cut): whichever copy unpins last must re-enter
    # service — hedging never loses work
    for ls in list(srv.leases.leases()):
        ls.granted_at -= 0.75
    srv._last_heard[0] -= 0.75
    srv._last_heard[1] -= 0.75
    srv._scan_leases(time.monotonic())
    assert srv.wq.count == 1
    assert srv.wq.find_match(0, frozenset([T])) is not None
    # a fresh consumer settles it exactly once
    _reserve(srv, 0, rqseqno=9)
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_RESERVE_RESP][-1]
    assert resp.rc == ADLB_SUCCESS
    resp = _fetch(srv, fabric, 0, resp.handle[0])
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"unit"
    assert srv.wq.count == 0


def test_quarantine_terminal_settles_race():
    srv, fabric = _srv()
    origin, sib = _hedge_setup(srv, fabric)
    # a terminal that is NOT a delivery must still close the race:
    # quarantine the origin directly (the dead-letter path)
    srv._quarantine_unit(srv.wq.get(origin), in_wq=True)
    assert srv.wq.get(sib) is None, "sibling outlived the terminal"
    assert (sib, 1) in srv._fences
    assert srv.metrics.value("hedges_fenced") == 1
    assert len(srv.quarantine) == 1
    resp = _fetch(srv, fabric, 1, sib)
    assert resp.rc == ADLB_FENCED


def test_unconfigured_world_is_frame_identical():
    """hedge_budget_frac=0 (the default): no manager, no scan timer
    ticking, and — critically — no hedge counters in the registry, so
    metric snapshots (and the gossip frames built from them) carry no
    new keys versus a pre-hedge build."""
    world = WorldSpec(nranks=4, nservers=2, types=(T,))
    fabric = InProcFabric(4)
    srv = Server(world, Config(), fabric.endpoint(2))
    assert srv.hedges is None
    assert srv._next_hedge_scan == float("inf")
    snap = srv.metrics.snapshot()["counters"]
    assert not any(k.startswith("hedge") for k in snap), list(snap)


def test_config_validation():
    with pytest.raises(ValueError):
        Config(hedge_budget_frac=1.5)
    with pytest.raises(ValueError):
        Config(hedge_budget_frac=0.5, hedge_min_age_ms=-1)
    with pytest.raises(ValueError):
        Config(hedge_budget_frac=0.5)  # needs lease_timeout_s > 0
    Config(hedge_budget_frac=0.5, lease_timeout_s=1.0)


# ------------------------------------------------------------- durability


def _wu(seqno, payload):
    return WorkUnit(seqno=seqno, work_type=T, prio=0, target_rank=-1,
                    answer_rank=-1, payload=payload)


def test_op_hedge_mirror_lifecycle():
    log = ReplicationLog(buddy=3)
    log.log_put(_wu(5, b"origin"), 0, None)
    log.log_put(_wu(6, b"sib"), -1, None)
    log.log_hedge(6, 5)
    mirror = ReplicaMirror(primary=2)
    mirror.apply(log.take())
    assert mirror.hedges == {6: 5}
    # a fresh OP_PUT of the same seqno supersedes the mark (the race
    # dissolved with the sibling the survivor)
    log.log_put(_wu(6, b"sib"), -1, None)
    mirror.apply(log.take())
    assert mirror.hedges == {}
    # consume pops it (the race settled with the sibling the winner)
    log.log_hedge(6, 5)
    log.log_consume(6)
    mirror.apply(log.take())
    assert 6 not in mirror.hedges and 6 not in mirror.units
    # remove and quarantine pop it too
    log.log_put(_wu(7, b"s2"), -1, None)
    log.log_hedge(7, 5)
    log.log_remove(7)
    log.log_put(_wu(8, b"s3"), -1, None)
    log.log_hedge(8, 5)
    log.log_quarantine(8)
    mirror.apply(log.take())
    assert mirror.hedges == {}
    # a mark for a unit the mirror never saw is ignored (lag-safe)
    log.log_hedge(99, 5)
    mirror.apply(log.take())
    assert 99 not in mirror.hedges


def test_failover_drops_sibling_adopts_origin_fences_owner():
    """Buddy takeover of a home that died mid-race: the origin adopts
    normally (pinned, translated); the live sibling is DROPPED — not a
    counted loss — and its owner's rerouted fetch answers ADLB_FENCED
    (you lost the race: re-reserve), exactly like a live settle."""
    world = WorldSpec(nranks=5, nservers=3, types=(T,))
    fabric = InProcFabric(5)
    srv = Server(world, Config(on_server_failure="failover"),
                 fabric.endpoint(4))
    log = ReplicationLog(buddy=4)
    log.log_put(_wu(100, b"origin"), 1, 7)
    log.log_pin(100, 1)
    log.log_put(_wu(101, b"sib"), -1, None)
    log.log_hedge(101, 100)
    log.log_pin(101, 0)
    srv._handle(msg(Tag.SS_REPL, 3, blob=log.take(), seq=1))
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    srv._handle(Msg(tag=Tag.PEER_EOF, src=3))
    assert srv.wq.count == 1, "sibling adopted alongside its origin"
    assert len(srv.leases.owned_by(1)) == 1  # origin's pin survived
    texts = [t for _, t in srv.flight.entries()]
    assert any("hedge_siblings_dropped=1" in t for t in texts)
    # the sibling owner's rerouted fetch: fenced, NOT a counted loss
    before = srv.metrics.value("failover_lost")
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=101, fo_from=3))
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_FENCED
    assert srv.metrics.value("failover_lost") == before
    # the origin owner's rerouted fetch serves through translation
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=100, fo_from=3))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"origin"


def test_wal_cold_restart_discards_live_sibling(tmp_path):
    """Crash mid-race: recovery adopts the origin (re-executes inside
    the documented lease-expiry at-least-once window) and DISCARDS the
    speculative sibling — never two live duplicates after restart."""
    cfg = dict(wal_dir=str(tmp_path), wal_fsync_ms=0.0)
    srv, fabric = _srv(**cfg)
    origin, sib = _hedge_setup(srv, fabric)
    srv._flush_wal(force=True)
    srv.wal.close()
    srv2, fabric2 = _srv(**cfg)
    assert srv2.wal_recovered == 1
    [u] = list(srv2.wq.units())
    assert u.payload == b"unit" and not u.pinned
    texts = [t for _, t in srv2.flight.entries()]
    assert any("hedge_siblings_dropped=1" in t for t in texts)
    srv2.wal.close()


def test_wal_dissolved_race_survivor_recovers(tmp_path):
    """The origin retires (expiry during the race) leaving the sibling
    the sole survivor: the server re-logs the survivor's OP_PUT, which
    supersedes the OP_HEDGE mark — a crash after that must recover the
    SIBLING as an ordinary unit (the logical put is never lost)."""
    cfg = dict(wal_dir=str(tmp_path), wal_fsync_ms=0.0)
    srv, fabric = _srv(**cfg)
    origin, sib = _hedge_setup(srv, fabric)
    for ls in list(srv.leases.leases()):
        if ls.seqno == origin:
            ls.granted_at -= 0.75
    srv._last_heard[0] -= 0.75
    srv._scan_leases(time.monotonic())
    assert srv.wq.get(origin) is None and srv.wq.get(sib) is not None
    srv._flush_wal(force=True)
    srv.wal.close()
    srv2, fabric2 = _srv(**cfg)
    assert srv2.wal_recovered == 1, "surviving sibling was discarded"
    [u] = list(srv2.wq.units())
    assert u.payload == b"unit"
    srv2.wal.close()


def test_wal_compaction_preserves_open_race_marks(tmp_path):
    """Compaction snapshots the pool into an ACK2 shard (both race
    members ride it as plain units) — the fresh segment's seed must
    re-install the OP_HEDGE marks, or a post-compaction crash would
    recover two live duplicates."""
    cfg = dict(wal_dir=str(tmp_path), wal_fsync_ms=0.0)
    srv, fabric = _srv(**cfg)
    origin, sib = _hedge_setup(srv, fabric)
    srv._flush_wal(force=True)
    srv.wal.compact(srv)
    srv.wal.close()
    srv2, fabric2 = _srv(**cfg)
    assert srv2.wal_recovered == 1, "compaction laundered the sibling"
    [u] = list(srv2.wq.units())
    assert u.payload == b"unit"
    srv2.wal.close()


# ---------------------------------------------------------- observability


def test_hedged_journey_always_promotes_with_hedge_hop():
    srv, fabric = _srv()
    srv.journeys.tail = True  # as Config(trace_tail="on") arms it
    origin, sib = _hedge_setup(srv, fabric)
    resp = _fetch(srv, fabric, 1, sib)
    assert resp.rc == ADLB_SUCCESS
    done = srv.journeys.take_done()
    hedged = [j for j in done if j["why"] == ["hedged"]]
    assert len(hedged) == 1, done
    [j] = hedged
    stages = [s[0] for s in j["spans"]]
    assert "hedge" in stages and j["end"] == "delivered"
    # the loser was FORGOTTEN, never closed: exactly one journey tells
    # the race (a loser fold would double every latency estimator)
    assert len(done) == 1


def test_incident_bundle_carries_hedge_window_delta():
    from adlb_tpu.obs.metrics import Registry
    from adlb_tpu.obs.slo import SloEngine, build_incident, parse_objective

    srv, fabric = _srv()
    eng = SloEngine(0.5)
    eng.objectives = [parse_objective(
        {"name": "inj", "job": 0, "type": T, "p99_ms": 5, "window_s": 4}
    )]
    eng.alerts_pub = [{"name": "inj", "state": "FIRING",
                       "stale_ranks": []}]
    now = time.monotonic()
    reg = Registry(srv.rank)
    reg.counter("hedges_launched").inc(0)
    eng.ring.append(now - 3.0,
                    {"counters": dict(reg.snapshot()["counters"]),
                     "gauges": {}, "histograms": {}})
    reg.counter("hedges_launched").inc(3)
    reg.counter("hedges_won").inc(2)
    eng.ring.append(now,
                    {"counters": dict(reg.snapshot()["counters"]),
                     "gauges": {}, "histograms": {}})
    bundle = build_incident(
        srv, eng, {"name": "inj", "job": 0, "type": T}, now,
    )
    assert bundle["hedges"].get("hedges_launched") == 3.0
    assert bundle["hedges"].get("hedges_won") == 2.0


def test_hedge_storm_structurally_impossible():
    """Put-storm shape: many stragglers, many scans. The launch count
    stays under frac x deliveries + burst and no sticky-vetoed origin
    ever launches — both structural, not tuned."""
    srv, fabric = _srv(hedge_budget_frac=0.25)
    deliveries = 0
    launches_seen = set()
    vetoed_seen = set()
    srv.journeys.tail_thr[(0, T)] = 0.01
    for round_ in range(30):
        _put(srv, payload=b"u%d" % round_)
        _reserve(srv, 0, rqseqno=2 * round_ + 1)
        _drain(fabric, 0)
        _reserve(srv, 1, rqseqno=2 * round_ + 2)
        srv._scan_hedges(time.monotonic() + 1.0)
        # settle everything currently leased (deliveries refill)
        for ls in list(srv.leases.leases()):
            u = srv.wq.get(ls.seqno)
            if u is None or not u.pinned:
                continue
            resp = _fetch(srv, fabric, ls.owner, ls.seqno)
            if resp.rc == ADLB_SUCCESS:
                deliveries += 1
        _drain(fabric, 0), _drain(fabric, 1)
    for _, t in srv.flight.entries():
        if t.startswith("hedge_launched"):
            launches_seen.add(t.split("origin=")[1].split()[0])
        if t.startswith("hedge_vetoed") and "backpressure" in t:
            vetoed_seen.add(t.split("seqno=")[1].split()[0])
    launched = srv.metrics.value("hedges_launched")
    assert launched <= 0.25 * deliveries + BURST_TOKENS
    assert not (launches_seen & vetoed_seen), \
        "a sticky-vetoed origin launched"
    assert srv.wq.count == 0, "storm left unsettled inventory"


# ------------------------------------------------------------- end-to-end


def test_elastic_world_hedge_rescues_straggler():
    """A worker goes quiet for 1 s holding an unfetched reservation
    under a 3 s lease: only hedging can rescue the unit early. The
    world completes with exact exactly-once conservation, the hedge
    won, and the sleeper's late fetch was fenced."""
    n_units = 6
    cfg = Config(
        exhaust_check_interval=0.2, on_worker_failure="reclaim",
        lease_timeout_s=3.0, hedge_budget_frac=0.5,
        hedge_min_age_ms=100.0,
    )
    # one server: hedging is a home-server-local decision (the taker
    # must be parked at the straggler's home), so the rescue world
    # keeps both ranks and the unit under one roof
    ew = ElasticWorld(2, 1, [T], cfg=cfg)
    for s in ew.servers.values():
        # exactly what the master's SS_OBS_SYNC reply would install
        s.journeys.tail_thr = {(0, T): 0.3}

    def producer(ctx):
        for i in range(n_units):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        got = []
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return got
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                continue
            got.append(struct.unpack("<q", buf)[0])

    def sleeper(ctx):
        got, fenced = [], 0
        slept = False
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return got, fenced
            if not slept:
                slept = True
                time.sleep(1.0)  # the straggler: reserved, unfetched
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                fenced += 1
                continue
            got.append(struct.unpack("<q", buf)[0])

    t0 = time.monotonic()
    ew.run_app(0, producer)
    ew.run_app(1, sleeper)
    res = ew.finish(timeout=60)
    wall = time.monotonic() - t0
    done = sorted(res[0] + res[1][0])
    assert done == list(range(n_units)), done  # exactly once
    won = sum(s.metrics.value("hedges_won") for s in ew.servers.values())
    launched = sum(s.metrics.value("hedges_launched")
                   for s in ew.servers.values())
    assert launched >= 1 and won >= 1, (launched, won)
    assert res[1][1] >= 1, "sleeper's late fetch was never fenced"
    assert wall < 3.0, f"rescue waited for the lease ({wall:.1f}s)"


N_ACC = 40


def _acceptance_app(hedge_on):
    def app(ctx):
        from adlb_tpu.runtime.faults import sigstop_self

        if ctx.rank == 0:
            for i in range(N_ACC):
                assert ctx.put(struct.pack("<q", i) + b"\0" * 24, T,
                               answer_rank=0) == ADLB_SUCCESS
            seen = set()
            while len(seen) < N_ACC:
                rc, r = ctx.reserve([3])
                assert rc == ADLB_SUCCESS, rc
                rc, buf = ctx.get_reserved(r.handle)
                if rc == ADLB_RETRY:
                    continue
                seen.add(struct.unpack("<q", buf)[0])
            ctx.set_problem_done()
            return {"distinct": len(seen)}
        n, retries, stalls = 0, 0, 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return {"n": n, "retries": retries, "stalls": stalls}
            if ctx.rank == 1 and n >= 1 and stalls < 2:
                stalls += 1
                # first SIGSTOP outlives the lease (expiry marks this
                # rank suspect); the second is the p999 straggler the
                # suspect-window hedge must rescue early
                sigstop_self(2.6 if stalls == 1 else 2.0)
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                retries += 1
                continue
            assert rc == ADLB_SUCCESS, rc
            ctx.put(buf[:8], 3, target_rank=0)
            n += 1
            time.sleep(0.005)
    return app


@pytest.mark.slow
@pytest.mark.parametrize("hedge_on", [True, False])
def test_tcp_sigstop_acceptance_conserves(hedge_on, tmp_path):
    """The slow-TCP acceptance world: a SIGSTOP'd worker under a 2 s
    lease, with and without hedging. Both conserve exactly once; the
    hedged world's makespan records to a file so the paired run can
    assert the p999 rescue was materially faster (the bench's
    hedge_p999 row measures the same arm continuously)."""
    cfg = Config(
        on_worker_failure="reclaim", lease_timeout_s=2.0,
        exhaust_check_interval=0.2,
        hedge_budget_frac=0.5 if hedge_on else 0.0,
        hedge_min_age_ms=150.0,
    )
    t0 = time.monotonic()
    res = spawn_world(4, 1, [T, 3], _acceptance_app(hedge_on),
                      cfg=cfg, timeout=240.0)
    wall = time.monotonic() - t0
    assert res.app_results[0]["distinct"] == N_ACC
    done = sum(res.app_results[r]["n"] for r in (1, 2, 3))
    assert done >= N_ACC, "answered units under-counted"
    # the stalled rank survived both freezes and the world conserved;
    # record the makespan so the on/off pair is comparable in CI logs
    marker = tmp_path.parent / f"hedge_makespan_{int(hedge_on)}.txt"
    try:
        marker.write_text(f"{wall:.2f}\n")
    except OSError:
        pass
    other = tmp_path.parent / f"hedge_makespan_{int(not hedge_on)}.txt"
    if other.exists():
        on_s, off_s = (wall, float(other.read_text())) if hedge_on else \
            (float(other.read_text()), wall)
        assert on_s < off_s + 1.0, (
            f"hedging made the straggler world slower: on={on_s:.1f}s "
            f"off={off_s:.1f}s"
        )
