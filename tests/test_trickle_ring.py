"""Ring-mode qmstat (the reference-faithful gossip baseline) and the
trickle dispatch-latency workload."""

import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS, InfoKey
from adlb_tpu.workloads import nq, trickle

RING = Config(balancer="steal", qmstat_mode="ring", qmstat_interval=0.05)


def test_ring_qmstat_correctness_and_trip_stats():
    def app(ctx):
        if ctx.rank == 0:
            for i in range(6):
                ctx.put(b"x", 1)
            time.sleep(0.3)  # let a few ring tokens complete a trip
            ctx.set_problem_done()
            return None
        n = 0
        while True:
            rc, r = ctx.reserve([1])
            if rc != ADLB_SUCCESS:
                return n
            ctx.get_reserved(r.handle)
            n += 1

    res = run_world(3, 3, [1], app, cfg=RING)
    assert sum(v or 0 for v in res.app_results.values()) == 6
    # the master recorded ring trip times (reference src/adlb.c:1731-1743)
    assert res.info_get(InfoKey.AVG_QMSTAT_TRIP_TIME) > 0.0
    ring_res = nq.run(n=6, num_app_ranks=3, nservers=3, cfg=RING)
    assert ring_res.solutions == nq.KNOWN_SOLUTIONS[6]


def test_ring_qmstat_single_server_noop():
    # one server: no ring peers; must still work (token never kicked)
    res = nq.run(n=6, num_app_ranks=3, nservers=1, cfg=RING)
    assert res.solutions == nq.KNOWN_SOLUTIONS[6]


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_trickle_all_consumed_remotely(mode):
    cfg = (
        Config(balancer="tpu", balancer_max_tasks=64,
               balancer_max_requesters=16)
        if mode == "tpu"
        else Config(balancer="steal")
    )
    r = trickle.run(
        n_tasks=60, interval=0.005, group=2, work_time=0.001,
        num_app_ranks=6, nservers=3, cfg=cfg, timeout=120.0,
    )
    # hot-server ranks never consume, so every token crossed servers
    assert r.tasks == 60
    assert r.dispatch_p50_ms > 0.0


def test_trickle_tpu_dispatch_beats_upstream_ring():
    """The structural claim: event-driven global matching dispatches a
    trickling unit faster than 0.1s-ring-gossip-driven stealing. Generous
    margin — p50s differ by ~10x in practice."""
    upstream = Config(balancer="steal", qmstat_mode="ring",
                      qmstat_interval=0.1)
    tpu = Config(balancer="tpu", balancer_max_tasks=64,
                 balancer_max_requesters=16)
    r_steal = trickle.run(n_tasks=100, interval=0.008, group=2,
                          work_time=0.002, num_app_ranks=8, nservers=4,
                          cfg=upstream, timeout=120.0)
    r_tpu = trickle.run(n_tasks=100, interval=0.008, group=2,
                        work_time=0.002, num_app_ranks=8, nservers=4,
                        cfg=tpu, timeout=120.0)
    assert r_tpu.dispatch_p50_ms < r_steal.dispatch_p50_ms, (
        f"tpu p50 {r_tpu.dispatch_p50_ms:.1f}ms not better than "
        f"upstream ring {r_steal.dispatch_p50_ms:.1f}ms"
    )
