"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices instead. The
full env/config/backend-reset dance lives in
``adlb_tpu.utils.jaxenv.force_cpu_devices`` (shared with
``__graft_entry__.dryrun_multichip``'s self-provisioned subprocess).
"""

from adlb_tpu.utils.jaxenv import force_cpu_devices

force_cpu_devices(8)

# hang diagnosis lives in pytest.ini (faulthandler_timeout): pytest's
# built-in plugin dumps to the ORIGINAL stderr fd, surviving --capture,
# and covers setup/teardown phases a fixture-armed timer would miss
