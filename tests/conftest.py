"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices instead.

The ambient environment may have registered a single-chip accelerator plugin
and pinned ``jax_platforms`` at the *config* level (overriding env vars), so
this both sets the env and updates the config, clearing any backends that
were initialized before pytest imported us.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from jax._src import xla_bridge as _xb  # noqa: E402

if _xb.backends_are_initialized():  # pragma: no cover
    from jax.extend.backend import clear_backends

    clear_backends()
