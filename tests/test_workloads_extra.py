"""grid / add2 / skel workloads (reference examples/grid_daf.c, add2.c,
skel.c) — known-answer and self-checking runs."""

import numpy as np
import pytest

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads import add2, grid, skel


def test_grid_sequential_oracle_properties():
    g = grid.run_sequential(6, 6, 0)
    # zero iterations leaves the interior at its initial value
    assert np.all(g[1:-1, 1:-1] == 0.0)
    g1 = grid.run_sequential(6, 6, 1)
    # one sweep pulls boundary values one cell inward
    assert g1[1, 1] == (g[0, 1] + g[2, 1] + g[1, 0] + g[1, 2]) / 4.0


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_grid_matches_sequential(mode):
    nrows, ncols, niters = 6, 5, 3
    want = grid.run_sequential(nrows, ncols, niters)
    got = grid.run(
        nrows, ncols, niters, num_app_ranks=3, nservers=2,
        cfg=Config(balancer=mode, exhaust_check_interval=0.25),
    )
    np.testing.assert_array_equal(got.grid, want)
    assert got.average == float(want[1:-1, 1:-1].mean())
    # every row x iteration was computed exactly once, by someone
    assert sum(got.rows_computed.values()) == nrows * niters


def test_add2_known_answer():
    pairs = [(i, 2 * i + 1) for i in range(30)]
    r = add2.run(pairs, num_app_ranks=3, nservers=2)
    assert r.ok, f"sum {r.total} != {r.expected}"
    assert sum(v for k, v in r.sums_by_rank.items() if k != 0) == len(pairs)


def test_skel_stress_accounting():
    r = skel.run(num_app_ranks=4, nservers=2)
    assert r.ok, f"consumed {r.consumed} != produced {r.produced}"
    assert r.tasks_per_sec > 0


def test_skel_respects_priorities_single_consumer():
    # one rank, one server: strict priority order within a type mix
    mix = [
        skel.TypeSpec(work_type=1, count=5, prio=1),
        skel.TypeSpec(work_type=2, count=5, prio=9),
    ]
    order = []

    import struct
    import time

    from adlb_tpu.api import run_world
    from adlb_tpu.types import ADLB_SUCCESS

    def app(ctx):
        if ctx.rank == 0:
            for s in mix:
                for _ in range(s.count):
                    ctx.put(struct.pack("<i", s.work_type), s.work_type,
                            work_prio=s.prio)
            time.sleep(0.1)  # let everything enqueue before consuming
            while True:
                rc, r = ctx.reserve()
                if rc != ADLB_SUCCESS:
                    return True
                ctx.get_reserved(r.handle)
                order.append(r.work_type)
                if len(order) == 10:
                    ctx.set_problem_done()
        return True

    run_world(1, 1, [1, 2], app, cfg=Config(exhaust_check_interval=5.0))
    assert order == [2] * 5 + [1] * 5
