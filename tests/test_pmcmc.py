"""pmcmc workload (reference examples/pmcmc.c port)."""

import numpy as np

from adlb_tpu.workloads import pmcmc


def test_chain_deterministic_and_valid():
    a = pmcmc.chain(seed=123, steps=2000)
    b = pmcmc.chain(seed=123, steps=2000)
    assert np.array_equal(a, b)
    assert pmcmc.valid_config(a)
    # a different seed must (overwhelmingly) land elsewhere
    c = pmcmc.chain(seed=124, steps=2000)
    assert not np.array_equal(a, c)


def test_pmcmc_world_collects_all_solutions():
    r = pmcmc.run(num_mcs=6, steps=1500, num_app_ranks=3, nservers=1)
    assert r.ok, f"invalid or missing solutions: {sorted(r.solutions)}"
    assert sorted(r.solutions) == [100, 101, 102, 103, 104, 105]
    # worker results must be reproducible: re-run one chain locally
    assert np.array_equal(r.solutions[100], pmcmc.chain(100, 1500))


def test_pmcmc_under_tpu_balancer():
    from adlb_tpu.runtime.world import Config

    r = pmcmc.run(
        num_mcs=4, steps=800, num_app_ranks=3, nservers=2,
        cfg=Config(balancer="tpu", exhaust_check_interval=0.2),
    )
    assert r.ok
    assert len(r.solutions) == 4
