"""Server failover: replicated pool shards + home-server takeover
(Config(on_server_failure="failover"), adlb_tpu/runtime/replica.py).

Four layers of coverage:

* **Replication log <-> mirror** — packed entries (checkpoint.py unit
  wire format) reconstruct the primary's units/pins/commons/tombstones.
* **Takeover race lattice** — Server instances driven handler-by-handler:
  promotion replays the mirror (pinned units survive behind the seqno
  translation, tombstoned fetches answer ADLB_RETRY and are counted),
  a fused relay in flight through the dead home server resolves
  delivered-at-death, a held END_1 token is re-kicked by the master,
  and the double failure (no mirror at the buddy) aborts cleanly.
* **Checkpoint shard header (ACK2)** — world-shape validation is loud;
  ACK1 shards stay readable.
* **End-to-end policy acceptance** — worlds surviving a server death on
  both fabrics with conservation asserted modulo the counted
  replication-lag losses; the default "abort" policy unchanged.
"""

import os
import struct
import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime import checkpoint, replica
from adlb_tpu.runtime.faults import resolve_spec
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.queues import WorkUnit
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import (
    ADLB_RETRY,
    ADLB_SUCCESS,
    InfoKey,
)

T = 1


# ------------------------------------------------------- log <-> mirror


def test_replication_log_mirror_roundtrip():
    log = replica.ReplicationLog(buddy=4)
    u1 = WorkUnit(seqno=10, work_type=T, prio=5, target_rank=-1,
                  answer_rank=2, payload=b"alpha")
    u2 = WorkUnit(seqno=11, work_type=T, prio=0, target_rank=1,
                  answer_rank=-1, payload=b"beta", common_len=3,
                  common_server_rank=3, common_seqno=7)
    log.log_put(u1, src=0, put_id=42)
    log.log_put(u2, src=0, put_id=43)
    log.log_common_put(7, b"PFX")
    log.log_common_refcnt(7, 2)
    log.log_pin(10, 0)
    log.log_consume(11)
    log.log_app_done(1)
    mirror = replica.ReplicaMirror(primary=3)
    mirror.apply(log.take())
    assert set(mirror.units) == {10}
    assert mirror.units[10]["payload"] == b"alpha"
    assert mirror.pins == {10: 0}
    assert 11 in mirror.tombstones
    assert mirror.commons[7][0] == b"PFX" and mirror.commons[7][1] == 2
    assert mirror.commons[7][2] == 0
    assert mirror.seen_puts[0] == [42, 43]
    assert mirror.finalized == {1}
    # unpin + second frame: streams are cumulative and ordered
    log.log_unpin(10)
    log.log_common_op(7, "get")
    mirror.apply(log.take())
    assert mirror.pins == {}
    assert mirror.commons[7][2] == 1
    # a sealed mirror ignores late frames (post-promotion tail)
    mirror.seal()
    log.log_consume(10)
    mirror.apply(log.take())
    assert 10 in mirror.units


def test_replicated_dedup_identities():
    """Get/forfeit ids and the re-bootstrap put-window op ride the
    stream, so the buddy's replay windows absorb requests the dead
    server already accounted."""
    log = replica.ReplicationLog(buddy=4)
    log.log_common_put(7, b"PFX")
    log.log_common_op(7, "get", src=0, op_id=91)
    log.log_common_op(-1, "forfeit", src=2, op_id=55)  # window-only entry
    log.log_seen_puts(5, [1, 2, 3])
    m = replica.ReplicaMirror(primary=3)
    m.apply(log.take())
    assert m.last_common == {0: 91}
    assert m.forfeit_ids == {2: [55]}
    assert m.seen_puts[5] == [1, 2, 3]
    assert m.commons[7][2] == 1  # the get still accounted against ngets


def test_buddy_of_skips_dead_successors():
    w = WorldSpec(nranks=5, nservers=3, types=(T,))
    assert replica.buddy_of(w, 3) == 4
    assert replica.buddy_of(w, 3, dead_servers={4}) == 2
    assert replica.buddy_of(w, 3, dead_servers={4, 2, 3}) == 3  # nobody


# ------------------------------------------------------- takeover lattice

# world: nranks=5, nservers=3 -> apps 0..1, servers 2 (master), 3, 4.
# app 0 homes at 2, app 1 homes at 3; ring: 2 -> 3 -> 4 -> 2, so server
# 4 is server 3's buddy (mirrors its replication stream).


def _mini(rank, **cfg_kw):
    world = WorldSpec(nranks=5, nservers=3, types=(T,))
    fabric = InProcFabric(5)
    cfg = Config(on_server_failure="failover", **cfg_kw)
    return Server(world, cfg, fabric.endpoint(rank)), fabric


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def _primary_blob(extra_consumed=False):
    """A replication stream as server 3 would have sent it: one queued
    unit, one unit pinned for (live) app rank 1, a batch-common prefix
    with one consumed member tombstoned."""
    log = replica.ReplicationLog(buddy=4)
    queued = WorkUnit(seqno=100, work_type=T, prio=1, target_rank=-1,
                      answer_rank=-1, payload=b"queued")
    pinned = WorkUnit(seqno=101, work_type=T, prio=0, target_rank=-1,
                      answer_rank=-1, payload=b"pinned")
    log.log_put(queued, src=1, put_id=7)
    log.log_put(pinned, src=1, put_id=8)
    log.log_pin(101, 1)
    log.log_common_put(5, b"COMMONPFX")
    log.log_common_refcnt(5, 1)
    if extra_consumed:
        consumed = WorkUnit(seqno=102, work_type=T, prio=0, target_rank=-1,
                            answer_rank=-1, payload=b"gone")
        log.log_put(consumed, src=1, put_id=9)
        log.log_pin(102, 1)
        log.log_consume(102)
    return log.take()


def test_promotion_replays_shard_and_takes_over_home_duty():
    srv, fabric = _mini(4)
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(extra_consumed=True),
                    seq=1))
    # fan-out arrives before the dead server's own EOF: promotion waits
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    assert 3 in srv._dead_servers and 3 in srv._pending_promotion
    assert srv.wq.count == 0
    srv._handle(Msg(tag=Tag.PEER_EOF, src=3))  # tail drained: promote
    assert 3 not in srv._pending_promotion
    assert srv.wq.count == 2  # queued + pinned replayed
    assert len(srv.leases.owned_by(1)) == 1  # pin survived, same owner
    assert 1 in srv.local_apps  # home duty adopted
    assert srv.metrics.value("failover_promoted") == 1
    assert srv._g_fo_mttr.v > 0
    # every app rank got the epoch-stamped remap
    for app in (0, 1):
        notes = [m for m in _drain(fabric, app)
                 if m.tag is Tag.TA_HOME_TAKEOVER]
        assert notes and notes[0].dead == 3 and notes[0].src == 4
    # the adopted pin serves the client's rerouted fetch via translation
    old_seqno = 101
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=old_seqno, fo_from=3))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"pinned"
    # a consumed-at-death unit's fetch is a counted loss, not a crash
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=102, fo_from=3))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_RETRY
    assert srv.metrics.value("failover_lost") == 1
    # the adopted common prefix serves under translation too
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=5, fo_from=3))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_COMMON_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"COMMONPFX"
    # replayed puts are dedup-protected: rank 1 re-sending an acked put
    # (id 7, accepted by the dead server) gets the idempotent ack
    before = srv.wq.count
    srv._handle(msg(Tag.FA_PUT, 1, payload=b"dup", work_type=T, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=7))
    assert srv.wq.count == before, "duplicate re-sent put was stored twice"


def test_rerouted_common_ops_translate_and_count_lost():
    """fo_from translation on the batch-common control plane: a rerouted
    BATCH_DONE finalizes the ADOPTED prefix (not whatever local seqno
    happens to collide with the dead server's numbering), and a rerouted
    fetch of a prefix that missed the last replication flush answers
    ADLB_RETRY and is counted — ADLB_ERROR would read as terminal and
    the member would vanish uncounted."""
    srv, fabric = _mini(4)
    log = replica.ReplicationLog(buddy=4)
    member = WorkUnit(seqno=100, work_type=T, prio=0, target_rank=-1,
                      answer_rank=-1, payload=b"sfx", common_len=9,
                      common_server_rank=3, common_seqno=5)
    log.log_common_put(5, b"COMMONPFX")  # batch still open: no refcnt yet
    log.log_put(member, src=1, put_id=7)
    srv._handle(msg(Tag.SS_REPL, 3, blob=log.take(), seq=1))
    srv._server_eof_at[3] = time.monotonic()
    srv._server_tail_drained.add(3)  # simulate the handled inbound EOF
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    adopted = srv._adopted_commons[(3, 5)]
    # the client's end_batch_put reroutes here naming the DEAD server's
    # seqno; the final refcount must land on the adopted entry
    srv._handle(msg(Tag.FA_BATCH_DONE, 1, common_seqno=5, refcnt=1,
                    fo_from=3))
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=5, fo_from=3,
                    get_id=1))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_COMMON_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"COMMONPFX"
    # refcount satisfied by the one fetch -> adopted prefix GC'd, which
    # proves the rerouted BATCH_DONE hit the right entry
    assert srv.cq.peek(adopted) is None
    # a prefix that missed the last flush: counted loss, ADLB_RETRY
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=77, fo_from=3,
                    get_id=2))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_COMMON_RESP][-1]
    assert resp.rc == ADLB_RETRY
    assert srv.metrics.value("failover_lost") == 1
    # and the matching BATCH_DONE is a no-op, not a refcount misapplied
    # to some unrelated live prefix
    srv._handle(msg(Tag.FA_BATCH_DONE, 1, common_seqno=77, refcnt=3,
                    fo_from=3))


def test_send_failure_evidence_does_not_promote_before_tail_drains():
    """A failed SEND to the dying server proves nothing about the
    inbound replication tail: promotion must wait for the handled EOF
    (or the deadline), or frames still queued — e.g. an acked put's
    write-ahead entry — would be sealed out and lost uncountably."""
    srv, fabric = _mini(4)
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(), seq=1))
    srv._server_eof_at[3] = time.monotonic()  # send-failure evidence only
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    assert 3 in srv._pending_promotion and srv.wq.count == 0
    # the tail (a write-ahead-acked put) drains, THEN the EOF arrives
    log = replica.ReplicationLog(buddy=4)
    tail = WorkUnit(seqno=103, work_type=T, prio=0, target_rank=-1,
                    answer_rank=-1, payload=b"tail")
    log.log_put(tail, src=1, put_id=10)
    srv._handle(msg(Tag.SS_REPL, 3, blob=log.take(), seq=2))
    srv._handle(Msg(tag=Tag.PEER_EOF, src=3))
    assert 3 not in srv._pending_promotion
    assert srv.wq.count == 3, "the replication tail was sealed out"
    assert {u.payload for u in srv.wq.units()} >= {b"tail"}


def test_replayed_get_window_absorbs_resent_fetch():
    """A common fetch the dead server accounted (and replicated) that the
    client re-sends toward the buddy must be re-served, not accounted a
    second time — double-accounting would GC the prefix one get early
    and answer a later live member with a terminal error."""
    srv, fabric = _mini(4)
    log = replica.ReplicationLog(buddy=4)
    log.log_common_put(5, b"PFX")
    log.log_common_refcnt(5, 2)  # two members will fetch
    log.log_common_op(5, "get", src=1, op_id=9)  # first fetch, accounted;
    #                                              its response died
    srv._handle(msg(Tag.SS_REPL, 3, blob=log.take(), seq=1))
    srv._server_eof_at[3] = time.monotonic()
    srv._server_tail_drained.add(3)  # simulate the handled inbound EOF
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    adopted = srv._adopted_commons[(3, 5)]
    # the client re-sends the SAME request (same get_id) to the buddy
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=5, fo_from=3,
                    get_id=9))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_COMMON_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"PFX"
    assert srv.cq.peek(adopted) == b"PFX", "re-send was double-accounted"
    # the second member's genuinely new fetch satisfies the refcount
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=5, fo_from=3,
                    get_id=10))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_COMMON_RESP][-1]
    assert resp.rc == ADLB_SUCCESS
    assert srv.cq.peek(adopted) is None  # refcount satisfied -> GC


def test_takeover_note_reannounced_until_window_closes():
    """The promote-time TA_HOME_TAKEOVER fan-out is one connect attempt
    per rank; a note lost to a refused connect must be repaired by the
    periodic re-announce before the client's failover window expires."""
    srv, fabric = _mini(4)
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(), seq=1))
    srv._server_eof_at[3] = time.monotonic()
    srv._server_tail_drained.add(3)  # simulate the handled inbound EOF
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    for app in (0, 1):
        _drain(fabric, app)  # discard the promote-time notes
    assert 3 in srv._takeover_renotify
    srv._next_renotify = 0.0
    srv._periodic(time.monotonic(), 0.05)
    for app in (0, 1):
        notes = [m for m in _drain(fabric, app)
                 if m.tag is Tag.TA_HOME_TAKEOVER]
        assert notes and notes[0].dead == 3, "note was not re-announced"
    # window closed: the re-announce retires itself
    srv._takeover_renotify[3] = time.monotonic() - 1.0
    srv._next_renotify = 0.0
    srv._periodic(time.monotonic(), 0.05)
    assert 3 not in srv._takeover_renotify
    assert not [m for m in _drain(fabric, 0)
                if m.tag is Tag.TA_HOME_TAKEOVER]


def test_promotion_deadline_fires_without_eof():
    srv, fabric = _mini(4)
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(), seq=1))
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    srv._pending_promotion[3] = time.monotonic() - 0.001  # force deadline
    srv._periodic(time.monotonic(), 0.05)
    assert srv.wq.count == 2
    assert srv.metrics.value("failover_promoted") == 1


def test_double_failure_aborts_cleanly():
    """Buddy died before promotion: the shard has no replica anywhere —
    the world must abort, not hang or run with silent loss."""
    srv, fabric = _mini(4)
    srv._server_eof_at[3] = time.monotonic()
    srv._server_tail_drained.add(3)  # simulate the handled inbound EOF
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))  # no SS_REPL
    assert srv._aborted and srv.done
    aborts = [m for m in _drain(fabric, 2) if m.tag is Tag.SS_ABORT]
    assert aborts, "double failure did not broadcast an abort"


def test_master_death_promotes_the_deputy():
    """The master's ring buddy is its standing deputy: a brain-carrying
    replication stream makes the master's death one more failover, not
    an abort (the full succession matrix lives in
    tests/test_master_failover.py)."""
    srv, fabric = _mini(3)
    log = replica.ReplicationLog(buddy=3)
    log.log_member({"master": 2, "epoch": 0, "member": {}})
    srv._handle(msg(Tag.SS_REPL, 2, blob=log.take(), seq=1))
    srv._handle(Msg(tag=Tag.PEER_EOF, src=2))  # master's EOF
    assert not srv._aborted
    assert srv.is_master and srv.world.master_server_rank == 3


def test_server_death_under_abort_policy_unchanged():
    world = WorldSpec(nranks=5, nservers=3, types=(T,))
    fabric = InProcFabric(5)
    srv = Server(world, Config(), fabric.endpoint(4))
    srv._handle(Msg(tag=Tag.PEER_EOF, src=3))
    assert srv._aborted and srv.done


def test_relay_in_flight_through_dead_home_resolves_at_most_once():
    """Holder side: a fused relay left toward the dead home server, the
    payload possibly already forwarded — delivered-at-death (consume);
    a handle-shaped pin for the same home unpins and re-matches, and
    the owner's late fetch gets ADLB_RETRY instead of an abort."""
    srv, fabric = _mini(4)
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(), seq=1))
    # two local units; app rank 1's home is server 3
    for payload in (b"relay", b"handle"):
        srv._handle(msg(Tag.FA_PUT, 0, payload=payload, work_type=T, prio=0,
                        target_rank=-1, answer_rank=-1, common_len=0,
                        common_server=-1, common_seqno=-1))
    _drain(fabric, 0)
    units = {u.payload: u for u in srv.wq.units()}
    # fused relay: payload rode the RFR response toward home server 3
    srv._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=1, req_types=[T],
                    targeted_lookup=False, lookup_type=-1, fetch=1))
    # handle handoff for the same rank via a second RFR
    srv._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=2, req_types=[T],
                    targeted_lookup=False, lookup_type=-1))
    assert sum(1 for u in srv.wq.units() if u.pinned) == 2
    assert len(srv._relay_inflight) == 1
    srv._server_eof_at[3] = time.monotonic()
    srv._server_tail_drained.add(3)  # simulate the handled inbound EOF
    srv._handle(msg(Tag.SS_SERVER_DEAD, 2, rank=3, epoch=1))
    # relay unit consumed (at-most-once), handle unit unpinned + rematchable
    left = {u.payload for u in srv.wq.units() if not u.pinned}
    assert units[b"relay"].seqno not in {u.seqno for u in srv.wq.units()}
    assert b"handle" in left
    # the owner's late fetch of the unpinned unit re-reserves, not aborts
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=units[b"handle"].seqno))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_RETRY


def test_end_ring_rekicked_when_server_dies_holding_token():
    """Master side: END_1 was circulating when a server died — the ring
    restarts over the survivors instead of waiting forever."""
    srv, fabric = _mini(2)  # the master
    srv._handle(msg(Tag.SS_REPL, 3, blob=_primary_blob(), seq=1))
    srv._finalized = set(srv.local_apps)
    srv._end1_pending = True
    srv._ending = True
    srv._handle(msg(Tag.SS_SERVER_DEAD, 4, rank=3, epoch=1))
    # ring next live of 2 is 4 (3 is dead): the restarted token went there
    end1 = [m for m in _drain(fabric, 4) if m.tag is Tag.SS_END_1]
    assert end1, "END_1 was not re-kicked around the surviving ring"


def test_migrate_batch_in_transit_to_dead_dest_requeues():
    srv, fabric = _mini(2)
    srv._handle(msg(Tag.SS_REPL, 3, blob=b"", seq=1))
    for i in range(3):
        srv._handle(msg(Tag.FA_PUT, 0, payload=b"u%d" % i, work_type=T,
                        prio=0, target_rank=-1, answer_rank=-1, common_len=0,
                        common_server=-1, common_seqno=-1))
    _drain(fabric, 0)
    seqnos = [u.seqno for u in srv.wq.units()]
    srv._handle(msg(Tag.SS_PLAN_MIGRATE, 2, dest=3, seqnos=seqnos, mig_id=1))
    assert srv.wq.count == 0 and srv._migrate_unacked == 1
    srv._handle(msg(Tag.SS_SERVER_DEAD, 4, rank=3, epoch=1))
    assert srv.wq.count == 3, "in-transit migration batch lost"
    assert srv._migrate_unacked == 0


# ------------------------------------------------------- checkpoint header


def test_checkpoint_ack2_shape_validated(tmp_path):
    w = WorldSpec(nranks=5, nservers=3, types=(T,))
    prefix = str(tmp_path / "pool")
    units = [WorkUnit(seqno=1, work_type=T, prio=0, target_rank=-1,
                      answer_rank=-1, payload=b"x")]
    checkpoint.save_shard(prefix, 2, units, None, world=w)
    got, commons = checkpoint.load_shard(prefix, 2, w)
    assert len(got) == 1 and got[0]["payload"] == b"x"
    other = WorldSpec(nranks=7, nservers=3, types=(T,))
    with pytest.raises(checkpoint.ShardShapeError):
        checkpoint.load_shard(prefix, 2, other)
    # shape-free callers (bare tooling) still load
    got, _ = checkpoint.load_shard(prefix, 2)
    assert len(got) == 1


def test_checkpoint_ack1_gated_behind_allow_legacy(tmp_path):
    """A pre-header ACK1 shard (old builds / old native daemons) is
    refused LOUDLY by default — it carries no world shape to validate,
    and the WAL compacts into ACK2 only — with the error naming the
    Config(allow_legacy_shards) opt-in, which restores the old read."""
    path = tmp_path / "old.2.ckpt"
    body = [b"ACK1", struct.pack("<I", 1)]
    body.append(struct.pack("<iiiqqq", T, -1, -1, 0, -1, -1))
    body.append(struct.pack("<I", 0))  # common_len
    body.append(struct.pack("<I", 3))  # payload_len
    body.append(b"old")
    body.append(struct.pack("<I", 0))  # no common entries
    path.write_bytes(b"".join(body))
    with pytest.raises(checkpoint.ShardShapeError) as ei:
        checkpoint.load_shard(str(tmp_path / "old"), 2,
                              WorldSpec(5, 3, (T,)))
    assert "allow_legacy_shards" in str(ei.value)
    units, commons = checkpoint.load_shard(str(tmp_path / "old"), 2,
                                           WorldSpec(5, 3, (T,)),
                                           allow_legacy=True)
    assert len(units) == 1 and units[0]["payload"] == b"old"
    assert commons == []


def test_resolve_spec_translates_server_kills():
    w = WorldSpec(nranks=8, nservers=2, types=(T,))  # apps 0..5, servers 6,7
    spec = resolve_spec({"kill_server_at_frame": {1: 40},
                         "kill_server_at": {"0": 2.5}}, w)
    assert spec["kill_at_frame"] == {7: 40}
    assert spec["kill_at"] == {6: 2.5}
    with pytest.raises(ValueError):
        resolve_spec({"kill_server_at_frame": {2: 1}}, w)


# ------------------------------------------------------- end-to-end worlds


N_UNITS = 48


def _coverage_economy(ctx):
    """Producer pre-loads N_UNITS ids; every rank consumes via get_work
    and returns the id set it executed. Failover may re-execute a unit
    (at-least-once for in-transit state) but every id must be covered
    modulo the counted replication-lag losses."""
    if ctx.rank == 0:
        for i in range(N_UNITS):
            ctx.put(struct.pack("<q", i), T)
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(struct.unpack("<q", w.payload)[0])
        time.sleep(0.002)


def _assert_coverage(res, expect_casualty):
    done = [x for v in res.app_results.values() for x in v]
    lost = sum(
        s.get(int(InfoKey.FAILOVER_LOST), 0.0)
        for s in res.server_stats.values()
    )
    missing = set(range(N_UNITS)) - set(done)
    assert len(missing) <= lost, (
        f"units {sorted(missing)} vanished but only {lost} counted lost"
    )
    assert res.server_casualties == [expect_casualty]
    assert not res.aborted
    promoted = sum(
        s.get(int(InfoKey.NUM_FAILOVERS), 0.0)
        for s in res.server_stats.values()
    )
    assert promoted >= 1, "no server reported a takeover"


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_inproc_server_death_failover_completes(mode):
    """Deterministic in-proc server death (fault-injected disconnect of
    server index 1 at its 40th outbound frame): the buddy takes over and
    the world completes with conservation modulo counted losses."""
    res = run_world(
        4, 3, [T], _coverage_economy,
        cfg=Config(
            balancer=mode,
            on_server_failure="failover",
            exhaust_check_interval=0.2,
            failover_client_wait=30.0,
            fault_spec={"seed": 3, "disconnect_server_at": {1: 40}},
        ),
        timeout=120.0,
    )
    _assert_coverage(res, expect_casualty=5)  # server index 1 = rank 5


def test_inproc_server_death_abort_policy_unchanged():
    """Same injected death under the default policy: the world aborts
    (reference semantics), promptly and classified."""
    t0 = time.monotonic()
    with pytest.raises(Exception):
        run_world(
            4, 3, [T], _coverage_economy,
            cfg=Config(
                exhaust_check_interval=0.2,
                fault_spec={"seed": 3, "disconnect_server_at": {1: 40}},
            ),
            timeout=60.0,
        )
    assert time.monotonic() - t0 < 45.0, "abort path hung"


def _tcp_economy(ctx):
    return _coverage_economy(ctx)


@pytest.mark.slow
def test_tcp_sigkill_server_failover_completes():
    """The acceptance world: an 8-rank TCP world survives SIGKILL of the
    non-master server mid-workload; clients re-arm via the takeover remap
    and the run completes with every unit completed or re-executed
    (conservation modulo counted lag losses); MTTR is recorded."""
    res = spawn_world(
        6, 2, [T], _tcp_economy,
        cfg=Config(
            on_server_failure="failover",
            exhaust_check_interval=0.2,
            failover_client_wait=30.0,
            fault_spec={"seed": 11, "kill_server_at_frame": {1: 60}},
        ),
        timeout=150.0,
    )
    _assert_coverage(res, expect_casualty=7)  # server index 1 = rank 7
    mttr = max(
        s.get(int(InfoKey.FAILOVER_MTTR_MS), 0.0)
        for s in res.server_stats.values()
    )
    assert mttr > 0.0, "promotion did not record an MTTR"


@pytest.mark.slow
def test_tcp_sigkill_server_abort_policy_classifies():
    t0 = time.monotonic()
    with pytest.raises(RuntimeError):
        spawn_world(
            6, 2, [T], _tcp_economy,
            cfg=Config(
                exhaust_check_interval=0.2,
                fault_spec={"seed": 11, "kill_server_at_frame": {1: 60}},
            ),
            timeout=90.0,
        )
    assert time.monotonic() - t0 < 75.0, "abort classification hung"
