"""Unit tests for the indexed server queues.

Property-checks the semantic contract inherited from the reference xq library
(pinned/targeted exclusion, priority order, FIFO tie-break — reference
src/xq.c:190-247,199-201,229-231).
"""

import random

from adlb_tpu.runtime.queues import (
    CommonStore,
    MemoryAccountant,
    ReserveQueue,
    RqEntry,
    TargetedDirectory,
    WorkQueue,
    WorkUnit,
)
from adlb_tpu.types import ADLB_LOWEST_PRIO


def mk(seqno, wtype=1, prio=0, target=-1, payload=b"x", answer=-1):
    return WorkUnit(
        seqno=seqno,
        work_type=wtype,
        prio=prio,
        target_rank=target,
        answer_rank=answer,
        payload=payload,
    )


def test_priority_order_and_fifo_tiebreak():
    wq = WorkQueue()
    wq.add(mk(1, prio=5))
    wq.add(mk(2, prio=9))
    wq.add(mk(3, prio=9))
    wq.add(mk(4, prio=1))
    u = wq.find_match(rank=0, req_types=None)
    assert u.seqno == 2  # highest prio, earliest seqno
    wq.remove(2)
    assert wq.find_match(0, None).seqno == 3
    wq.remove(3)
    assert wq.find_match(0, None).seqno == 1


def test_type_filtering():
    wq = WorkQueue()
    wq.add(mk(1, wtype=1, prio=1))
    wq.add(mk(2, wtype=2, prio=100))
    assert wq.find_match(0, frozenset([1])).seqno == 1
    assert wq.find_match(0, frozenset([2])).seqno == 2
    assert wq.find_match(0, frozenset([3])) is None
    assert wq.find_match(0, None).seqno == 2


def test_targeted_only_given_to_target_and_takes_precedence():
    wq = WorkQueue()
    wq.add(mk(1, prio=100))          # untargeted, high prio
    wq.add(mk(2, prio=0, target=7))  # targeted at 7, low prio
    # rank 7: targeted work wins even at lower priority (reference order)
    assert wq.find_match(7, None).seqno == 2
    # rank 3 never sees rank-7-targeted work
    assert wq.find_match(3, None).seqno == 1
    wq.remove(1)
    assert wq.find_match(3, None) is None


def test_pinned_invisible_and_unpin_restores():
    wq = WorkQueue()
    wq.add(mk(1, prio=5))
    wq.pin(1, rank=3)
    assert wq.find_match(0, None) is None
    assert wq.num_unpinned_untargeted() == 0
    wq.unpin(1)
    assert wq.find_match(0, None).seqno == 1


def test_hi_prio_of_type_tracks_available_only():
    wq = WorkQueue()
    assert wq.hi_prio_of_type(1) == ADLB_LOWEST_PRIO
    wq.add(mk(1, wtype=1, prio=4))
    wq.add(mk(2, wtype=1, prio=9, target=5))  # targeted: not in qmstat cell
    assert wq.hi_prio_of_type(1) == 4
    wq.pin(1, 0)
    assert wq.hi_prio_of_type(1) == ADLB_LOWEST_PRIO


def test_randomized_against_naive_model():
    rng = random.Random(1234)
    wq = WorkQueue()
    model: dict[int, WorkUnit] = {}
    seqno = 0
    for _ in range(2000):
        op = rng.random()
        if op < 0.5 or not model:
            seqno += 1
            u = mk(
                seqno,
                wtype=rng.randint(1, 3),
                prio=rng.randint(-5, 5),
                target=rng.choice([-1, -1, -1, 0, 1]),
            )
            wq.add(u)
            model[seqno] = u
        elif op < 0.75:
            rank = rng.randint(0, 1)
            req = rng.choice([None, frozenset([1]), frozenset([2, 3])])
            got = wq.find_match(rank, req)
            # naive: targeted-first then untargeted, max prio, min seqno
            def naive(pred):
                cands = [
                    u for u in model.values()
                    if not u.pinned and pred(u)
                    and (req is None or u.work_type in req)
                ]
                return min(cands, key=lambda u: (-u.prio, u.seqno)) if cands else None
            want = naive(lambda u: u.target_rank == rank) or naive(
                lambda u: u.target_rank < 0
            )
            assert (got is None) == (want is None)
            if got is not None:
                assert got.seqno == want.seqno
        elif op < 0.9:
            s = rng.choice(list(model))
            if not model[s].pinned:
                wq.pin(s, 0)
                model[s].pinned = True
            else:
                wq.unpin(s)
                model[s].pinned = False
        else:
            s = rng.choice(list(model))
            wq.remove(s)
            del model[s]
    assert wq.count == len(model)


def test_reserve_queue_fifo_and_type_match():
    rq = ReserveQueue()
    rq.add(RqEntry(world_rank=3, rqseqno=1, req_types=frozenset([2])))
    rq.add(RqEntry(world_rank=1, rqseqno=2, req_types=None))
    assert rq.find_for_type(2).world_rank == 3  # FIFO: rank 3 parked first
    assert rq.find_for_type(9).world_rank == 1  # only the any-type waiter
    assert rq.find_for_type(2, target_rank=1).world_rank == 1
    assert rq.find_for_type(2, target_rank=5) is None
    rq.remove(3)
    assert rq.find_for_type(2).world_rank == 1


def test_targeted_directory():
    tq = TargetedDirectory()
    tq.add(app_rank=4, work_type=1, server_rank=10)
    tq.add(app_rank=4, work_type=1, server_rank=10)
    assert tq.lookup(4, None) == (10, 1)
    assert tq.lookup(4, frozenset([2])) is None
    tq.remove(4, 1, 10)
    assert tq.lookup(4, None) == (10, 1)
    tq.remove(4, 1, 10)
    assert tq.lookup(4, None) is None


def test_common_store_gc():
    cq = CommonStore()
    s = cq.put(b"prefix")
    assert cq.get(s) == b"prefix"
    assert len(cq) == 1  # refcnt unknown: no GC yet
    cq.set_refcnt(s, 3)
    assert len(cq) == 1
    cq.get(s)
    cq.get(s)
    assert len(cq) == 0  # ngets == refcnt -> GC'd


def test_memory_accountant():
    m = MemoryAccountant(max_bytes=100)
    assert m.try_alloc(60)
    assert not m.try_alloc(50)  # over cap -> put rejected
    assert m.try_alloc(40)
    assert m.under_pressure
    m.free(60)
    assert not m.under_pressure
    assert m.hwm == 100
