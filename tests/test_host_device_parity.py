"""The host greedy twin must produce bit-identical assignments to the jitted
device scan — the adaptive threshold is a latency knob, never a semantics
change."""

import numpy as np

import tests.conftest  # noqa: F401

import jax.numpy as jnp

from adlb_tpu.balancer.solve import _NEG, _greedy_assign, _host_greedy


def test_host_matches_device_on_random_instances():
    rng = np.random.default_rng(7)
    for trial in range(20):
        NT = int(rng.integers(1, 200))
        NR = int(rng.integers(1, 40))
        T = int(rng.integers(1, 5))
        task_prio = rng.integers(-50, 50, NT).astype(np.int32)
        task_type = rng.integers(0, T, NT).astype(np.int32)
        pad = rng.random(NT) < 0.3
        task_prio[pad] = int(_NEG)
        task_type[pad] = -1
        req_mask = rng.random((NR, T)) < 0.5
        req_valid = rng.random(NR) < 0.7

        host = _host_greedy(task_prio, task_type, req_mask, req_valid)
        dev = np.asarray(
            _greedy_assign(
                jnp.asarray(task_prio),
                jnp.asarray(task_type),
                jnp.asarray(req_mask),
                jnp.asarray(req_valid),
            )
        )
        np.testing.assert_array_equal(host, dev, err_msg=f"trial {trial}")
