"""Multi-host launcher (adlb_tpu.runtime.launch) + join_world: two
launcher invocations (one per simulated host) rendezvous through a shared
directory and run a complete world."""

import os
import re
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_APP = textwrap.dedent(
    """
    import os, struct, sys
    sys.path.insert(0, %r)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from adlb_tpu.api import join_world
    from adlb_tpu.types import ADLB_SUCCESS

    T = 1
    with join_world(types=[T]) as ctx:
        if ctx.rank == 0:
            for i in range(40):
                ctx.iput(struct.pack("<q", i), T)
            assert ctx.flush_puts() == ADLB_SUCCESS
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                break
            got.append(struct.unpack("<q", w.payload)[0])
        # ONE write: multi-arg print issues a pipe write per argument,
        # and two apps sharing the launcher's stdout interleave
        # mid-token ("APP 0 GOTAPP 1 ...") under load
        sys.stdout.write("APP {} GOT {!r}\\n".format(ctx.rank, sorted(got)))
    """
) % (_REPO,)


def test_launcher_with_c_clients(tmp_path):
    """The launcher's env contract drives native C binaries directly."""
    import shutil

    if shutil.which("gcc") is None:
        pytest.skip("no C toolchain")
    from adlb_tpu.native.capi import build_example

    exe = build_example(os.path.join(_REPO, "examples", "fastpath_c.c"))
    rdv = str(tmp_path / "worldc")
    common = [
        sys.executable, "-m", "adlb_tpu.runtime.launch",
        "--rendezvous", rdv, "--nranks", "5", "--nservers", "2",
        "--types", "1", "--server-impl", "native", "--timeout", "60",
    ]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    pa = subprocess.Popen(common + ["--ranks", "0,1,3", exe], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    pb = subprocess.Popen(common + ["--ranks", "2,4", exe], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    out_a, err_a = pa.communicate(timeout=120)
    out_b, err_b = pb.communicate(timeout=120)
    assert pa.returncode == 0, f"A rc={pa.returncode}\n{out_a}\n{err_a}"
    assert pb.returncode == 0, f"B rc={pb.returncode}\n{out_b}\n{err_b}"
    total_n = sum(
        int(line.split("got")[1].split()[0])
        for out in (out_a, out_b)
        for line in out.splitlines()
        if "fastpath rank" in line
    )
    assert total_n == 40


@pytest.mark.parametrize("server_impl", ["python", "native"])
def test_two_launchers_one_world(tmp_path, server_impl):
    app_py = tmp_path / "app.py"
    app_py.write_text(_APP)
    rdv = str(tmp_path / "world")
    common = [
        sys.executable, "-m", "adlb_tpu.runtime.launch",
        "--rendezvous", rdv, "--nranks", "6", "--nservers", "2",
        "--types", "1", "--server-impl", server_impl,
        "--timeout", "60",
    ]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    # "host A": apps 0,1 + server 4; "host B": apps 2,3 + server 5
    pa = subprocess.Popen(
        common + ["--ranks", "0,1,4", sys.executable, str(app_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    pb = subprocess.Popen(
        common + ["--ranks", "2,3,5", sys.executable, str(app_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out_a, err_a = pa.communicate(timeout=120)
    out_b, err_b = pb.communicate(timeout=120)
    assert pa.returncode == 0, f"launcher A rc={pa.returncode}\n{out_a}\n{err_a}"
    assert pb.returncode == 0, f"launcher B rc={pb.returncode}\n{out_b}\n{err_b}"
    got = []
    # regex, not line-splitting: app subprocesses share the launcher's
    # stdout pipe and their report lines can interleave mid-line under
    # load ("[...]APP 2 GOT [...]"), which a per-line eval chokes on
    for out in (out_a, out_b):
        for lst in re.findall(r"APP \d+ GOT (\[[^\]]*\])", out):
            got.extend(eval(lst))
    assert sorted(got) == list(range(40)), sorted(got)


def test_port_clash_check():
    """Two ranks published on one (host, port) — possible when concurrent
    launchers' closed-socket probe subranges overlap — must fail the
    rendezvous loudly instead of dying on EADDRINUSE mid-world."""
    from adlb_tpu.runtime.launch import _check_port_clash

    _check_port_clash({0: ("h", 1), 1: ("h", 2), 2: ("h2", 1)})  # ok
    with pytest.raises(RuntimeError, match="duplicate addresses"):
        _check_port_clash({0: ("h", 1), 1: ("h", 2), 2: ("h", 1)})


def test_two_launchers_mux_forced(tmp_path):
    """The channel plane over the rendezvous launcher: ADLB_TCP_MUX=1 on
    a pure-TCP fabric forces every python<->python frame through the
    per-launcher brokers (one `broker.<host>.<pid>.addr` each, bridged
    by the rank routes) — the world must complete identically."""
    import glob

    app_py = tmp_path / "app.py"
    app_py.write_text(_APP)
    rdv = str(tmp_path / "worldmux")
    common = [
        sys.executable, "-m", "adlb_tpu.runtime.launch",
        "--rendezvous", rdv, "--nranks", "6", "--nservers", "2",
        "--types", "1", "--fabric", "tcp", "--timeout", "60",
    ]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
               ADLB_TCP_MUX="1")
    pa = subprocess.Popen(
        common + ["--ranks", "0,1,4", sys.executable, str(app_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    pb = subprocess.Popen(
        common + ["--ranks", "2,3,5", sys.executable, str(app_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out_a, err_a = pa.communicate(timeout=120)
    out_b, err_b = pb.communicate(timeout=120)
    assert pa.returncode == 0, f"A rc={pa.returncode}\n{out_a}\n{err_a}"
    assert pb.returncode == 0, f"B rc={pb.returncode}\n{out_b}\n{err_b}"
    got = []
    for out in (out_a, out_b):
        for lst in re.findall(r"APP \d+ GOT (\[[^\]]*\])", out):
            got.extend(eval(lst))
    assert sorted(got) == list(range(40)), sorted(got)
    # both launchers published their broker through the rendezvous
    assert len(glob.glob(os.path.join(rdv, "broker.*.addr"))) == 2


_ELASTIC_BASE = textwrap.dedent(
    """
    import os, struct, sys, time
    sys.path.insert(0, %r)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from adlb_tpu.api import join_world
    from adlb_tpu.types import ADLB_SUCCESS

    T = 1
    sentinel = os.environ["TEST_SENTINEL"]
    with join_world(types=[T]) as ctx:
        if ctx.rank == 0:
            for i in range(16):
                ctx.put(struct.pack("<q", i), T)
        # hold the world open (off the rq, so exhaustion cannot fire)
        # until the ATTACHED rank has joined and contributed
        deadline = time.monotonic() + 60
        while not os.path.exists(sentinel):
            assert time.monotonic() < deadline, "attach never happened"
            time.sleep(0.05)
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                break
            got.append(struct.unpack("<q", w.payload)[0])
        sys.stdout.write("APP {} GOT {!r}\\n".format(ctx.rank, sorted(got)))
    """
) % (_REPO,)

_ELASTIC_JOINER = textwrap.dedent(
    """
    import os, struct, sys
    sys.path.insert(0, %r)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from adlb_tpu.api import join_world

    T = 1
    # ADLB_ATTACH=1 (set by launch.py --attach): join_world negotiates a
    # fresh rank id from the running world's master
    with join_world(types=[T]) as ctx:
        assert ctx.rank >= 4, ctx.rank  # allocated ABOVE the base world
        for i in range(100, 104):
            ctx.put(struct.pack("<q", i), T)
    open(os.environ["TEST_SENTINEL"], "w").write("joined")
    """
) % (_REPO,)


def test_launcher_attach_grows_running_world(tmp_path):
    """launch.py --attach: a second launcher invocation adds app ranks
    to an ALREADY-RUNNING world — the joiner's puts are covered by the
    base consumers, no restart anywhere."""
    base_py = tmp_path / "base.py"
    base_py.write_text(_ELASTIC_BASE)
    joiner_py = tmp_path / "joiner.py"
    joiner_py.write_text(_ELASTIC_JOINER)
    rdv = str(tmp_path / "worldgrow")
    sentinel = str(tmp_path / "joined.flag")
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
               TEST_SENTINEL=sentinel)
    world = subprocess.Popen(
        [sys.executable, "-m", "adlb_tpu.runtime.launch",
         "--rendezvous", rdv, "--nranks", "4", "--nservers", "2",
         "--types", "1", "--ranks", "0-3", "--timeout", "60",
         sys.executable, str(base_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    attach = subprocess.Popen(
        [sys.executable, "-m", "adlb_tpu.runtime.launch",
         "--rendezvous", rdv, "--nservers", "2", "--types", "1",
         "--attach", "1", "--timeout", "60",
         sys.executable, str(joiner_py)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out_j, err_j = attach.communicate(timeout=120)
    assert attach.returncode == 0, f"attach rc={attach.returncode}\n{out_j}\n{err_j}"
    out, err = world.communicate(timeout=120)
    assert world.returncode == 0, f"world rc={world.returncode}\n{out}\n{err}"
    got = []
    for lst in re.findall(r"APP \d+ GOT (\[[^\]]*\])", out):
        got.extend(eval(lst))
    assert sorted(got) == sorted(list(range(16)) + [100, 101, 102, 103]), \
        sorted(got)
