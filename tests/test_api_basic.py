"""End-to-end tests of the minimum slice: Put/Reserve/Get, priorities,
targeting, batch puts, Ireserve, explicit termination — single- and
multi-server worlds on the in-process fabric."""

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import (
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_NO_CURRENT_WORK,
    ADLB_NO_MORE_WORK,
    ADLB_SUCCESS,
)

TYPE_TASK = 1
TYPE_RESULT = 2


def _echo_world(nservers, cfg=None):
    """Rank 0 produces, everyone consumes and echoes payloads back via
    answer-routed results; rank 0 validates the sum."""

    NTASK = 40

    def app(ctx):
        if ctx.rank == 0:
            for i in range(NTASK):
                rc = ctx.put(str(i).encode(), TYPE_TASK, work_prio=i)
                assert rc == ADLB_SUCCESS
            total = 0
            for _ in range(NTASK):
                rc, r = ctx.reserve([TYPE_RESULT])
                assert rc == ADLB_SUCCESS
                rc, buf = ctx.get_reserved(r.handle)
                assert rc == ADLB_SUCCESS
                total += int(buf)
            ctx.set_problem_done()
            return total
        else:
            while True:
                rc, r = ctx.reserve([TYPE_TASK])
                if rc != ADLB_SUCCESS:
                    assert rc in (ADLB_NO_MORE_WORK, ADLB_DONE_BY_EXHAUSTION)
                    return None
                rc, buf = ctx.get_reserved(r.handle)
                assert rc == ADLB_SUCCESS
                v = int(buf) * 2
                ctx.put(str(v).encode(), TYPE_RESULT, target_rank=r.answer_rank)

    # answer_rank defaults to -1; use targeting to route results to rank 0
    def app2(ctx):
        if ctx.rank == 0:
            return app(ctx)
        while True:
            rc, r = ctx.reserve([TYPE_TASK])
            if rc != ADLB_SUCCESS:
                return None
            rc, buf = ctx.get_reserved(r.handle)
            v = int(buf) * 2
            ctx.put(str(v).encode(), TYPE_RESULT, target_rank=0)
        return None

    res = run_world(4, nservers, [TYPE_TASK, TYPE_RESULT], app2, cfg=cfg)
    assert res.app_results[0] == 2 * sum(range(NTASK))


def test_single_server_end_to_end():
    _echo_world(nservers=1)


def test_multi_server_end_to_end():
    _echo_world(nservers=3)


def test_multi_server_pure_python_queues():
    # keep the Python work-queue path covered now that auto prefers native
    _echo_world(nservers=3, cfg=Config(native_queues="off"))


def test_priority_order_observed():
    """A single consumer must see strictly descending priorities when all
    work is queued before the first reserve."""

    prios = [3, 9, 1, 7, 5]

    def app(ctx):
        if ctx.rank == 0:
            for i in prios:
                ctx.put(str(i).encode(), TYPE_TASK, work_prio=i)
            # hand the consumer a go signal so ordering is deterministic
            ctx.put(b"go", TYPE_RESULT, target_rank=1)
            # wait for the consumer to finish before declaring done
            rc, r = ctx.reserve([TYPE_RESULT])
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            ctx.set_problem_done()
            return None
        got = []
        rc, r = ctx.reserve([TYPE_RESULT])
        assert rc == ADLB_SUCCESS
        ctx.get_reserved(r.handle)
        for _ in prios:
            rc, r = ctx.reserve([TYPE_TASK])
            assert rc == ADLB_SUCCESS
            rc, buf = ctx.get_reserved(r.handle)
            got.append(int(buf))
        ctx.put(b"done", TYPE_RESULT, target_rank=0)
        return got

    res = run_world(2, 1, [TYPE_TASK, TYPE_RESULT], app)
    assert res.app_results[1] == [9, 7, 5, 3, 1]


def test_ireserve_no_current_work():
    def app(ctx):
        if ctx.rank == 0:
            rc, r = ctx.ireserve([TYPE_TASK])
            assert rc == ADLB_NO_CURRENT_WORK and r is None
            ctx.put(b"x", TYPE_TASK)
            rc, r = ctx.ireserve([TYPE_TASK])
            assert rc == ADLB_SUCCESS
            rc, buf = ctx.get_reserved(r.handle)
            assert buf == b"x"
            ctx.set_problem_done()
        return True

    res = run_world(1, 1, [TYPE_TASK], app)
    assert res.app_results[0] is True


def test_batch_common_prefix():
    NPUT = 6

    def app(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(b"COMMON:")
            for i in range(NPUT):
                ctx.put(str(i).encode(), TYPE_TASK)
            ctx.end_batch_put()
            rc, r = ctx.reserve([TYPE_RESULT])  # consumer's completion signal
            ctx.get_reserved(r.handle)
            ctx.set_problem_done()
            return None
        got = []
        for _ in range(NPUT):
            rc, r = ctx.reserve([TYPE_TASK])
            assert rc == ADLB_SUCCESS
            assert r.work_len == len("COMMON:") + 1
            rc, buf = ctx.get_reserved(r.handle)
            assert buf.startswith(b"COMMON:")
            got.append(int(buf[len(b"COMMON:"):]))
        ctx.put(b"done", TYPE_RESULT, target_rank=0)
        return sorted(got)

    res = run_world(2, 2, [TYPE_TASK, TYPE_RESULT], app)
    assert res.app_results[1] == list(range(NPUT))


def test_explicit_termination_unblocks_waiters():
    def app(ctx):
        if ctx.rank == 0:
            import time

            time.sleep(0.1)
            ctx.set_problem_done()
            return "producer"
        rc, r = ctx.reserve([TYPE_TASK])  # blocks until NO_MORE_WORK
        assert rc == ADLB_NO_MORE_WORK
        return "unblocked"

    res = run_world(3, 2, [TYPE_TASK], app)
    assert res.app_results[1] == "unblocked"
    assert res.app_results[2] == "unblocked"


def test_exhaustion_termination():
    """All ranks block with no producer: the double-pass exhaustion protocol
    must flush everyone with ADLB_DONE_BY_EXHAUSTION."""

    def app(ctx):
        rc, r = ctx.reserve([TYPE_TASK])
        return rc

    res = run_world(3, 2, [TYPE_TASK], app, cfg=Config(exhaust_check_interval=0.1))
    assert all(rc == ADLB_DONE_BY_EXHAUSTION for rc in res.app_results.values())


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_exhaustion_despite_orphaned_work(mode):
    """Undeliverable leftovers (a type nobody requests) must not block the
    exhaustion protocol — the reference exhausts with work still queued."""

    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"orphan", TYPE_RESULT)  # nobody ever asks for this type
        rc, _ = ctx.reserve([TYPE_TASK])
        return rc

    res = run_world(
        3, 2, [TYPE_TASK, TYPE_RESULT], app,
        cfg=Config(balancer=mode, exhaust_check_interval=0.1), timeout=60,
    )
    assert all(
        rc == ADLB_DONE_BY_EXHAUSTION for rc in res.app_results.values()
    )


def test_info_num_work_units():
    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"abc", TYPE_TASK)
            ctx.put(b"de", TYPE_TASK)
            rc, count, nbytes, _ = ctx.info_num_work_units(TYPE_TASK)
            assert rc == ADLB_SUCCESS
            assert count == 2
            assert nbytes == 5
            ctx.set_problem_done()
        return True

    run_world(1, 1, [TYPE_TASK], app)
