"""Unit-lifecycle tracing + the fleet metrics plane (ISSUE 13).

Trace-context survival across every path that moves a unit — local
fused delivery, SS_PUSH_WORK, SS_MIGRATE_WORK, the fused-relay
SS_RFR_RESP custody transfer, the replication stream, WAL cold-restart
replay, failover adoption — plus the SS_OBS_SYNC gossip, the master's
merged /metrics + /healthz staleness + /trace/units routes, and the
end-to-end acceptance world (a migrated unit and a relay-delivered unit
both retrievable as complete journeys from the master's ops endpoint).
"""

import json
import struct
import time
import urllib.request

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.obs.journey import (
    STAGE_CODES,
    JourneyRecorder,
    pack_spans,
    trace_fields,
    unpack_spans,
)
from adlb_tpu.obs.metrics import Registry
from adlb_tpu.runtime.codec import decode_binary_py, encode_binary_iov_py
from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.queues import RqEntry, WorkUnit
from adlb_tpu.runtime.replica import ReplicaMirror, ReplicationLog
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS

T = 1


class _RecEp:
    """Recording endpoint: send() appends, recv() never delivers."""

    def __init__(self, rank):
        self.rank = rank
        self.sent = []

    def send(self, dest, m, **_kw):
        self.sent.append((dest, m))

    def recv(self, timeout=None):
        return None

    def of(self, tag):
        return [(d, m) for d, m in self.sent if m.tag is tag]


def _mk_server(rank=2, nranks=4, nservers=2, **cfg_kw):
    cfg_kw.setdefault("balancer", "steal")
    cfg_kw.setdefault("native_queues", "off")
    world = WorldSpec(nranks=nranks, nservers=nservers, types=(T,))
    ep = _RecEp(rank)
    return Server(world, Config(**cfg_kw), ep), ep


def _put(server, payload, src=0, target=-1, trace_id=None, put_id=None,
         job=None):
    data = dict(payload=payload, work_type=T, prio=0, target_rank=target,
                answer_rank=-1, common_len=0, common_server=-1,
                common_seqno=-1, put_id=put_id)
    if trace_id is not None:
        data["trace_id"] = trace_id
    if job is not None:
        data["job_id"] = job
    server._handle(msg(Tag.FA_PUT, src, **data))


def _stages(journey):
    return [s[0] for s in journey["spans"]]


# ------------------------------------------------------------ primitives


def test_span_pack_roundtrip():
    spans = [("put_recv", 4, 12.5), ("enqueue", 4, 12.6),
             ("migrate", 5, 12.9)]
    tid, out = unpack_spans(pack_spans(0xBEEF, spans))
    assert tid == 0xBEEF and out == spans
    # stage codes are append-only wire ids — renumbering would corrupt
    # old WAL replays
    assert STAGE_CODES["put_recv"] == 1 and STAGE_CODES["replay"] == 12


def test_recorder_close_feeds_histograms_and_store():
    reg = Registry(rank=7)
    rec = JourneyRecorder(7, reg, max_live=2, max_done=4)
    u = WorkUnit(seqno=1, work_type=3, prio=0, target_rank=-1,
                 answer_rank=-1, payload=b"x", job=2)
    rec.begin(u, 99, 1.0)
    rec.stamp(u, "enqueue", 1.5)
    rec.stamp(u, "match", 2.0)
    rec.stamp(u, "deliver", 2.25)
    rec.close(u, "delivered", t=2.5)
    assert u.spans is None and u.trace_id == 0 and rec.live == 0
    (j,) = list(rec.done)
    assert j["trace_id"] == 99 and j["end"] == "delivered"
    assert j["job"] == 2 and j["type"] == 3
    assert _stages(j) == ["put_recv", "enqueue", "match", "deliver",
                          "finalize"]
    # per-stage latency = time to REACH the stage from the previous one
    h = reg.histogram("unit_stage_s", stage="enqueue", job="2", type="3")
    assert h.n == 1 and h.sum == pytest.approx(0.5)
    assert reg.histogram("unit_total_s", job="2", type="3").sum == \
        pytest.approx(1.5)
    # live cap: past it, contexts are dropped (counted), not grown
    others = [
        WorkUnit(seqno=i, work_type=3, prio=0, target_rank=-1,
                 answer_rank=-1, payload=b"x") for i in (2, 3, 4)
    ]
    for i, o in enumerate(others):
        rec.begin(o, 100 + i, 1.0)
    assert rec.live == 2
    assert others[2].spans is None
    assert reg.value("trace_dropped") == 1


def test_fa_put_trace_id_codec_roundtrip():
    m = msg(Tag.FA_PUT, 3, payload=b"w", work_type=T, prio=0,
            target_rank=-1, answer_rank=-1, put_id=5, trace_id=(4 << 32) | 7)
    body = b"".join(bytes(p) for p in encode_binary_iov_py(m))
    out = decode_binary_py(body)
    assert out.data["trace_id"] == (4 << 32) | 7
    # omitted = absent (the trace_sample=0 frame-identity contract)
    m2 = msg(Tag.FA_PUT, 3, payload=b"w", work_type=T, prio=0,
             target_rank=-1, answer_rank=-1, put_id=5)
    assert b"".join(bytes(p) for p in encode_binary_iov_py(m2)) != body
    assert "trace_id" not in decode_binary_py(
        b"".join(bytes(p) for p in encode_binary_iov_py(m2))
    ).data


# ------------------------------------------------- server-side lifecycle


def test_local_fused_delivery_closes_journey():
    srv, ep = _mk_server(rank=2)
    _put(srv, b"unit0", trace_id=42)
    assert srv.journeys.live == 1
    srv._handle(msg(Tag.FA_RESERVE, 0, rqseqno=1, req_types=[T],
                    hang=False, fetch=1))
    (dest, r), = ep.of(Tag.TA_RESERVE_RESP)
    assert dest == 0 and r.rc == ADLB_SUCCESS and r.payload == b"unit0"
    assert srv.journeys.live == 0
    (j,) = srv.journeys.take_done()
    assert j["trace_id"] == 42 and j["end"] == "delivered"
    assert _stages(j) == ["put_recv", "enqueue", "match", "deliver",
                          "finalize"]
    assert all(rank == 2 for _, rank, _t in
               [tuple(s) for s in j["spans"]])


def test_untraced_put_records_nothing():
    srv, ep = _mk_server(rank=2)
    _put(srv, b"unit0")
    assert srv.journeys.live == 0
    unit = next(iter(srv.wq.units()))
    assert unit.trace_id == 0 and unit.spans is None
    assert trace_fields(unit) is None
    # no trace key rides the push/migrate dicts for untraced units
    srv._handle(msg(Tag.SS_PLAN_MIGRATE, 3, dest=3,
                    seqnos=[unit.seqno], mig_id=1))
    (_, mig), = ep.of(Tag.SS_MIGRATE_WORK)
    assert "trace" not in mig.units[0]


def test_trace_survives_push():
    src, ep = _mk_server(rank=2)
    _put(src, b"unit0", trace_id=7)
    unit = next(iter(src.wq.units()))
    qid = 1234
    src._push_offered[qid] = unit.seqno
    src._handle(msg(Tag.SS_PUSH_QUERY_RESP, 3, query_id=qid, accept=True))
    (_, pushed), = ep.of(Tag.SS_PUSH_WORK)
    assert pushed.data["trace"]["id"] == 7
    assert src.journeys.live == 0  # custody left with the frame
    dest, _ep2 = _mk_server(rank=3)
    dest._handle(pushed)
    got = next(iter(dest.wq.units()))
    assert got.trace_id == 7
    assert [s[0] for s in got.spans] == ["put_recv", "enqueue", "push"]
    assert got.spans[0][1] == 2 and got.spans[-1][1] == 3
    assert dest.journeys.live == 1


def test_trace_survives_migrate():
    src, ep = _mk_server(rank=2)
    _put(src, b"unit0", trace_id=9)
    unit = next(iter(src.wq.units()))
    src._handle(msg(Tag.SS_PLAN_MIGRATE, 3, dest=3, seqnos=[unit.seqno],
                    mig_id=1))
    (_, mig), = ep.of(Tag.SS_MIGRATE_WORK)
    assert mig.units[0]["trace"]["id"] == 9
    dest, _ep2 = _mk_server(rank=3)
    dest._handle(mig)
    got = next(iter(dest.wq.units()))
    assert got.trace_id == 9
    assert [s[0] for s in got.spans] == ["put_recv", "enqueue", "migrate"]
    assert got.spans[-1][1] == 3  # the migrate hop belongs to the dest


def test_relay_journey_closes_at_home_not_holder():
    holder, hep = _mk_server(rank=2)
    _put(holder, b"fused", trace_id=11)
    holder._handle(msg(Tag.SS_RFR, 3, for_rank=1, rqseqno=5,
                       req_types=[T], targeted_lookup=False,
                       lookup_type=-1, fetch=1))
    (_, resp), = hep.of(Tag.SS_RFR_RESP)
    assert resp.data["trace"]["id"] == 11
    assert [s[0] for s in resp.data["trace"]["spans"]] == \
        ["put_recv", "enqueue", "match", "relay"]
    # home side: forwards + closes with its own deliver hop
    home, ep2 = _mk_server(rank=3)
    home.rq.add(RqEntry(world_rank=1, rqseqno=5,
                        req_types=frozenset([T]), fetch=True))
    home._handle(resp)
    assert ep2.of(Tag.TA_RESERVE_RESP)
    (j,) = home.journeys.take_done()
    assert j["trace_id"] == 11 and j["end"] == "delivered"
    assert _stages(j) == ["put_recv", "enqueue", "match", "relay",
                          "deliver", "finalize"]
    by_stage = {s[0]: s[1] for s in j["spans"]}
    assert by_stage["relay"] == 2 and by_stage["deliver"] == 3
    # holder: SS_DELIVERED consumes WITHOUT a second close
    (_, conf), = ep2.of(Tag.SS_DELIVERED)
    holder._handle(conf)
    assert holder.journeys.live == 0
    assert not holder.journeys.take_done()


def test_quarantine_closes_journey():
    srv, _ep = _mk_server(rank=2, lease_timeout_s=0.05, max_unit_retries=0)
    _put(srv, b"poison", trace_id=13)
    unit = next(iter(srv.wq.units()))
    srv.cfg.max_unit_retries = 1
    unit.attempts = 2
    srv._quarantine_unit(unit, in_wq=True)
    (j,) = srv.journeys.take_done()
    assert j["end"] == "quarantined"
    assert _stages(j)[-1] == "finalize"
    assert srv.journeys.live == 0


def test_trace_survives_replica_roundtrip():
    log = ReplicationLog(buddy=3)
    u = WorkUnit(seqno=5, work_type=T, prio=0, target_rank=-1,
                 answer_rank=-1, payload=b"x", trace_id=21,
                 spans=[("put_recv", 2, 1.0), ("enqueue", 2, 1.1)])
    log.log_put(u, 0, 17)
    mirror = ReplicaMirror(primary=2)
    mirror.apply(log.take())
    f = mirror.units[5]
    assert f["trace_id"] == 21
    assert f["spans"] == [("put_recv", 2, 1.0), ("enqueue", 2, 1.1)]


def test_trace_survives_failover_adoption():
    # primary (rank 2) logs a traced put; its buddy (rank 3) mirrors the
    # stream, the primary dies, and the promoted pool keeps the journey
    # with an "adopt" hop
    log = ReplicationLog(buddy=3)
    u = WorkUnit(seqno=5, work_type=T, prio=0, target_rank=-1,
                 answer_rank=-1, payload=b"x", trace_id=33,
                 spans=[("put_recv", 2, 1.0), ("enqueue", 2, 1.1)])
    log.log_put(u, 0, 17)
    buddy, _ep = _mk_server(rank=3, on_server_failure="failover")
    mirror = ReplicaMirror(primary=2)
    mirror.apply(log.take())
    buddy.mirrors[2] = mirror
    buddy._dead_servers.add(2)
    buddy._promote(2)
    got = next(iter(buddy.wq.units()))
    assert got.trace_id == 33
    assert [s[0] for s in got.spans] == ["put_recv", "enqueue", "adopt"]
    assert got.spans[-1][1] == 3
    assert buddy.journeys.live == 1


def test_trace_survives_wal_cold_restart(tmp_path):
    cfg = dict(wal_dir=str(tmp_path), wal_fsync_ms=0.0)
    srv, ep = _mk_server(rank=2, **cfg)
    _put(srv, b"durable", trace_id=55, put_id=1)
    srv._flush_wal(force=True)
    # the group commit released the held ack AND stamped wal_commit
    unit = next(iter(srv.wq.units()))
    assert [s[0] for s in unit.spans] == \
        ["put_recv", "enqueue", "wal_commit"]
    assert ep.of(Tag.TA_PUT_RESP)
    srv.wal.close()
    # cold restart: same wal_dir, fresh server — the journey continues
    srv2, _ep2 = _mk_server(rank=2, **cfg)
    assert srv2.wal_recovered == 1
    got = next(iter(srv2.wq.units()))
    assert got.trace_id == 55
    assert [s[0] for s in got.spans] == \
        ["put_recv", "enqueue", "wal_commit", "replay"]
    assert srv2.journeys.live == 1
    srv2.wal.close()


def test_trace_survives_wal_compaction(tmp_path):
    """Compaction snapshots the pool into an ACK2 shard (which cannot
    carry spans): the fresh segment's seed must re-install the trace
    contexts via OP_TRACE."""
    cfg = dict(wal_dir=str(tmp_path), wal_fsync_ms=0.0)
    srv, _ep = _mk_server(rank=2, **cfg)
    _put(srv, b"keep", trace_id=77, put_id=1)
    srv._flush_wal(force=True)
    srv.wal.compact(srv)
    srv.wal.close()
    srv2, _ep2 = _mk_server(rank=2, **cfg)
    got = next(iter(srv2.wq.units()))
    assert got.trace_id == 77
    assert [s[0] for s in got.spans] == \
        ["put_recv", "enqueue", "wal_commit", "replay"]
    srv2.wal.close()


# --------------------------------------------------- fleet metrics plane


def test_obs_sync_merges_at_master():
    master, _ep = _mk_server(rank=2, nranks=4, nservers=2, ops_port=0)
    # a gossiped delta from rank 3: counters are cumulative, gauges
    # point-in-time, histograms whole
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=1, journeys=[
        {"trace_id": 1, "job": 0, "type": T, "end": "delivered",
         "t0": 0.0, "total_s": 0.5,
         "spans": [["put_recv", 3, 0.0], ["finalize", 3, 0.5]]},
    ], snap={"counters": {"puts": 4}, "gauges": {"wq_depth": 2.0}}))
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=2, journeys=[],
                       snap={"counters": {"puts": 9}}))
    assert master._fleet_snaps[3]["counters"]["puts"] == 9
    assert master._fleet_seen[3][0] == 2
    assert len(master._journeys_fleet) == 1
    # the ops view: merged fleet counters include the gossiped rank
    from adlb_tpu.obs.ops_server import OpsServer

    master.metrics.counter("puts").inc(3)
    ops = OpsServer(master, 0)
    try:
        m = ops._metrics()
        assert "adlb_fleet_puts_total 12" in m
        assert 'adlb_obs_snapshot_seq{rank="3"} 2' in m
        assert 'adlb_obs_snapshot_age_seconds{rank="3"}' in m
        h = ops._healthz()
        assert h["ranks"]["3"]["seq"] == 2
        assert h["ranks"]["3"]["stale"] is False
        tu = ops._trace_units()
        assert tu["count"] == 1 and tu["journeys"][0]["trace_id"] == 1
    finally:
        ops.stop()


def test_delta_snapshot_sends_changes_only():
    reg = Registry(rank=4)
    c = reg.counter("puts")
    g = reg.gauge("wq_depth")
    h = reg.histogram("unit_total_s", job="0", type="1")
    c.inc(2)
    g.set(5)
    h.observe(0.25)
    memo: dict = {}
    d1 = reg.delta_snapshot(memo)
    assert d1["counters"]["puts"] == 2
    assert d1["gauges"]["wq_depth"] == 5
    assert 'unit_total_s{job=0,type=1}' in d1["histograms"]
    # unchanged -> empty delta (the heartbeat's empty frame)
    assert reg.delta_snapshot(memo) == {}
    c.inc()
    d3 = reg.delta_snapshot(memo)
    assert d3 == {"counters": {"puts": 3}}


def test_job_gauges_on_jobs_route():
    from adlb_tpu.obs.ops_server import OpsServer

    master, _ep = _mk_server(rank=2, nranks=4, nservers=2)
    master.jobs.ensure(5, name="tenant")
    _put(master, b"abc", job=5)
    _put(master, b"defgh", job=5)
    # a peer's gossiped job gauges fold into the totals
    master._fleet_snaps[3] = {
        "rank": 3, "counters": {}, "histograms": {},
        "gauges": {"job_wq_depth{job=5}": 3.0,
                   "job_wq_bytes{job=5}": 64.0,
                   "job_oldest_age_s{job=5}": 9.5},
    }
    ops = OpsServer(master, 0)
    try:
        doc = ops._job_one("5")
        assert doc["queue_depth"] == 5
        assert doc["queued_bytes"] == 8 + 64
        assert doc["oldest_age_s"] >= 9.5
        assert doc["per_rank"]["3"]["depth"] == 3
        assert "stage_latency_s" in doc
    finally:
        ops.stop()


def test_gauge_tick_sets_job_gauges():
    srv, _ep = _mk_server(rank=2)
    _put(srv, b"abcd", job=7)
    srv._next_gauge_sample = 0.0
    srv._periodic(time.monotonic(), 0.05)
    assert srv.metrics.value("job_wq_depth", job="7") == 1
    assert srv.metrics.value("job_wq_bytes", job="7") == 4
    # a killed job's partition disappears: the gauges must zero, not
    # freeze at the last sample (phantom backlog on /jobs/<id>)
    srv._apply_job_ctl("kill", 7)
    srv._next_gauge_sample = 0.0
    srv._periodic(time.monotonic(), 0.05)
    assert srv.metrics.value("job_wq_depth", job="7") == 0
    assert srv.metrics.value("job_wq_bytes", job="7") == 0


def test_job_kill_closes_journeys():
    srv, _ep = _mk_server(rank=2)
    _put(srv, b"doomed", job=9, trace_id=17)
    assert srv.journeys.live == 1
    srv._apply_job_ctl("kill", 9)
    assert srv.journeys.live == 0  # the live slot is released
    (j,) = srv.journeys.take_done()
    assert j["end"] == "dropped" and j["job"] == 9


# --------------------------------------------------- tail-based promotion


def _mk_unit(seqno=1, typ=T, job=0):
    return WorkUnit(seqno=seqno, work_type=typ, prio=0, target_rank=-1,
                    answer_rank=-1, payload=b"x", job=job)


def test_tail_retention_slow_vs_fast():
    from adlb_tpu.obs.journey import JourneyRecorder as JR

    reg = Registry(rank=2)
    rec = JR(2, reg)
    rec.tail = True
    rec.tail_thr = {(0, T): 0.1}
    # fast clean delivery: histograms fed, journey NOT retained
    u = _mk_unit(1)
    rec.begin_tail(u, 1.0)
    assert u.trace_id < 0  # server-minted tail id, never a head id
    rec.stamp(u, "match", 1.01)
    rec.stamp(u, "deliver", 1.02)
    rec.close(u, "delivered", t=1.03)
    assert not rec.take_done()
    assert reg.value("trace_journeys_closed") == 1
    assert reg.histogram("unit_total_s", job="0", type=str(T)).n == 1
    assert reg.value("trace_tail_promoted") == 0
    # slow clean delivery: past the per-(job,type) p99 -> promoted
    u2 = _mk_unit(2)
    rec.begin_tail(u2, 2.0)
    rec.stamp(u2, "match", 2.4)
    rec.stamp(u2, "deliver", 2.45)
    rec.close(u2, "delivered", t=2.5)
    (j,) = rec.take_done()
    assert j["why"] == ["slow"]
    assert j["prof_win"] == [2, 2]  # clock-aligned window ids
    assert reg.value("trace_tail_promoted") == 1


def test_tail_anomalous_terminals_always_promote():
    from adlb_tpu.obs.journey import JourneyRecorder as JR

    rec = JR(2, Registry(rank=2))
    rec.tail = True  # NO thresholds armed (cold histogram)
    u = _mk_unit(1)
    rec.begin_tail(u, 1.0)
    rec.close(u, "quarantined", t=1.001)
    # a delivered journey that crossed a lease expiry is an anomaly too
    u2 = _mk_unit(2)
    rec.begin_tail(u2, 1.0)
    rec.stamp(u2, "expire", 1.01)
    rec.stamp(u2, "deliver", 1.02)
    rec.close(u2, "delivered", t=1.03)
    a, b = rec.take_done()
    assert a["why"] == ["quarantined"] and a["end"] == "quarantined"
    assert b["why"] == ["expired_lease"] and b["end"] == "delivered"


def test_tail_cold_histogram_promotes_nothing_slow():
    """Hysteresis: with no armed threshold (cold cells), a slow-but-
    clean delivery is NOT promoted — only anomalies and head samples
    survive a cold start."""
    from adlb_tpu.obs.journey import JourneyRecorder as JR

    rec = JR(2, Registry(rank=2))
    rec.tail = True
    u = _mk_unit(1)
    rec.begin_tail(u, 1.0)
    rec.stamp(u, "deliver", 99.0)  # absurdly slow
    rec.close(u, "delivered", t=99.1)
    assert not rec.take_done()


def test_tail_head_sample_path_unchanged():
    from adlb_tpu.obs.journey import JourneyRecorder as JR

    # tail OFF: a head-sampled journey closes exactly as in PR 12
    rec = JR(2, Registry(rank=2))
    u = _mk_unit(1)
    rec.begin(u, 42, 1.0)
    rec.stamp(u, "deliver", 1.01)
    rec.close(u, "delivered", t=1.02)
    (j,) = rec.take_done()
    assert j["why"] == ["head"] and j["trace_id"] == 42
    # tail ON: head samples still always keep, threshold or not
    rec2 = JR(2, Registry(rank=2))
    rec2.tail = True
    u2 = _mk_unit(2)
    rec2.begin(u2, 43, 1.0)
    rec2.stamp(u2, "deliver", 1.01)
    rec2.close(u2, "delivered", t=1.02)
    (j2,) = rec2.take_done()
    assert j2["why"] == ["head"]


def test_tail_armed_by_ops_port_and_server_mints_ids():
    # auto + ops_port -> armed; every put journeys in a trace_sample=0
    # world, with NOTHING new riding FA_PUT (server-side arming only)
    srv, _ep = _mk_server(rank=2, ops_port=0, trace_sample=0.0)
    assert srv.journeys.tail
    _put(srv, b"u0")
    u = next(iter(srv.wq.units()))
    assert u.trace_id < 0 and u.spans is not None
    # tail arms skip the enqueue hop (its delta is the put handler's
    # own microseconds — every-unit cost for no attribution)
    assert [s[0] for s in u.spans] == ["put_recv"]
    # unobserved world (no ops_port) stays untraced under auto
    srv2, _ep2 = _mk_server(rank=2, trace_sample=0.0)
    assert not srv2.journeys.tail
    _put(srv2, b"u0")
    assert next(iter(srv2.wq.units())).spans is None
    # explicit off overrides an observed world
    srv3, _ep3 = _mk_server(rank=2, ops_port=0, trace_tail="off")
    assert not srv3.journeys.tail


def test_tail_threshold_computation_and_gossip_reply():
    master, ep = _mk_server(rank=2, nranks=4, nservers=2, ops_port=0)
    h = master.metrics.histogram("unit_total_s", job="0", type=str(T))
    for _ in range(40):
        h.observe(0.001)
    # below TAIL_MIN_COUNT (64): hysteresis keeps the cell unarmed
    assert master._tail_thresholds() == {}
    for _ in range(30):
        h.observe(0.002)
    thr = master._tail_thresholds()
    assert (0, T) in thr and 0.0 < thr[(0, T)] < 0.1
    # fleet cells merge in: a gossiped snapshot's histogram counts too
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=1, journeys=[], snap={
        "histograms": {f"unit_total_s{{job=0,type={T}}}": {
            "bounds": list(h.bounds), "counts": list(h.counts),
            "sum": h.sum, "count": h.n}}}))
    thr2 = master._tail_thresholds()
    assert thr2.keys() == thr.keys()
    # the master's obs tick installs + caches, and gossip frames get the
    # thresholds carried back (SS_OBS_SYNC reply, list-of-triples form)
    master._next_obs_sync = 0.0
    master._periodic(time.monotonic(), 0.05)
    assert master.journeys.tail_thr == thr2
    assert master._tail_thr_cache
    ep.sent.clear()
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=2, journeys=[], snap={}))
    (dest, reply), = ep.of(Tag.SS_OBS_SYNC)
    assert dest == 3
    # and the non-master side installs the reply
    peer, _pep = _mk_server(rank=3, nranks=4, nservers=2, ops_port=0)
    peer._handle(reply)
    assert peer.journeys.tail_thr == thr2


def test_tails_store_routing_and_query_filters():
    from adlb_tpu.obs.ops_server import OpsServer

    master, _ep = _mk_server(rank=2, nranks=4, nservers=2, ops_port=0)
    mk = lambda tid, why, total, job=0: {  # noqa: E731
        "trace_id": tid, "job": job, "type": T, "end": "delivered",
        "why": why, "t0": 1.0, "total_s": total,
        "spans": [["put_recv", 3, 1.0], ["match", 3, 1.0 + total * 0.9],
                  ["finalize", 3, 1.0 + total]]}
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=1, snap={}, journeys=[
        mk(5, ["head"], 0.01),
        mk(-9, ["slow"], 0.8),
        mk(6, ["head", "slow"], 0.9, job=2),
    ]))
    # head -> units, promoted -> tails, both -> both
    assert [j["trace_id"] for j in master._journeys_fleet] == [5, 6]
    assert [j["trace_id"] for j in master._tails_fleet] == [-9, 6]
    ops = OpsServer(master, 0)
    try:
        assert ops._trace_units()["count"] == 2
        assert ops._trace_units({"min_ms": "100"})["count"] == 1
        assert ops._trace_units({"job": "2"})["count"] == 1
        assert ops._trace_units({"type": "99"})["count"] == 0
        assert ops._trace_units({"limit": "1"})["journeys"][0][
            "trace_id"] == 6  # newest kept
        # limit past the store size clamps to everything (a wrapped
        # negative slice index silently DROPPED results; regression)
        assert ops._trace_units({"limit": "999"})["count"] == 2
        assert ops._trace_units({"limit": "0"})["count"] == 0
        tails = ops._trace_tails()
        assert tails["count"] == 2
        # the excess-attribution annotation names the dominant stage
        assert all(j["slow_stage"] == "match" for j in tails["journeys"])
        assert ops._trace_tails({"job": "2"})["count"] == 1
        assert ops._trace_tails({"limit": "1", "min_ms": "1"})[
            "count"] == 1
    finally:
        ops.stop()


# ----------------------------------------------------------- client side


def test_trace_sample_zero_draws_nothing():
    from adlb_tpu.runtime.client import Client

    world = WorldSpec(nranks=3, nservers=1, types=(T,))
    fabric = InProcFabric(3)
    c = Client(world, Config(trace_sample=0.0), fabric.endpoint(0))
    state = c._trace_rng.getstate()
    for _ in range(32):
        assert c._sample_trace() is None
    assert c._trace_rng.getstate() == state  # zero draws, zero allocs
    assert c.metrics.value("traced_puts") == 0
    c2 = Client(world, Config(trace_sample=1.0), fabric.endpoint(1))
    tid = c2._sample_trace()
    assert tid == (2 << 32) | 1
    assert c2.metrics.value("traced_puts") == 1


# -------------------------------------------------- acceptance (worlds)


def _world_journeys(cfg_kw, n_units=40, apps=4, servers=2, port=None):
    port = port if port is not None else probe_free_ports(1)[0]

    def app(ctx):
        if ctx.rank == 0:
            for a in range(n_units):
                ctx.put(struct.pack("<q", a), T)
            deadline = time.monotonic() + 30.0
            out = {}
            while time.monotonic() < deadline:
                time.sleep(0.4)
                tu = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace/units", timeout=10,
                ).read().decode())
                if tu["count"] >= n_units:
                    break
            out["trace"] = tu
            for route in ("metrics", "healthz"):
                out[route] = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/{route}", timeout=10,
                ).read().decode()
            ctx.set_problem_done()
            return out
        if ctx.rank % servers == 0:
            return 0  # consumers live only at the non-master server
        n = 0
        while True:
            rc, _got = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                return n
            time.sleep(0.005)
            n += 1

    cfg = Config(ops_port=port, trace_sample=1.0, obs_sync_interval=0.2,
                 **cfg_kw)
    res = spawn_world(apps, servers, [T], app, cfg=cfg, timeout=120.0)
    consumed = sum(v for k, v in res.app_results.items() if k != 0)
    return res.app_results[0], consumed


@pytest.mark.slow
def test_acceptance_journeys_migrated_and_relayed_tcp():
    """The issue's acceptance world: a multi-server TCP fleet where a
    sampled unit's FULL journey — including one that migrated and one
    delivered via fused relay — is retrievable from the master's
    /trace/units with per-stage latencies attributed to the right
    rank, and /metrics reflects every rank's counters."""
    got, consumed = _world_journeys(
        dict(balancer="tpu", put_routing="home"), n_units=40,
    )
    tu = got["trace"]
    assert consumed == 40
    assert tu["count"] == 40, f"only {tu['count']} journeys closed"
    master = 4  # 4 apps + 2 servers -> master rank 4, peer rank 5
    migrated = [j for j in tu["journeys"] if "migrate" in _stages(j)]
    relayed = [j for j in tu["journeys"] if "relay" in _stages(j)]
    assert migrated, "no migrated journey (planner moved nothing?)"
    assert relayed or migrated, "no cross-server journey at all"
    for j in tu["journeys"]:
        stages = _stages(j)
        assert stages[0] == "put_recv" and stages[-1] == "finalize"
        assert j["end"] == "delivered"
        # per-stage rank attribution: the put landed on rank 0's home
        # (the master, put_routing="home"); delivery happened wherever
        # the consumer's server is
        assert j["spans"][0][1] == master
        for _stage, rank, _t in j["spans"]:
            assert rank in (4, 5)
        # spans are time-ordered (shared CLOCK_MONOTONIC on one host)
        ts = [s[2] for s in j["spans"]]
        assert ts == sorted(ts)
    mj = migrated[0]
    by_stage = {s[0]: s[1] for s in mj["spans"]}
    assert by_stage["put_recv"] == master
    assert by_stage["migrate"] == 5 and by_stage["deliver"] == 5
    if relayed:
        rj = relayed[0]
        rs = {s[0]: s[1] for s in rj["spans"]}
        assert rs["relay"] == master and rs["deliver"] == 5
    # fleet /metrics covers every rank within a gossip cadence
    m = got["metrics"]
    assert "adlb_fleet_puts_total 40" in m
    assert "adlb_fleet_unit_total_s_count" in m
    assert 'adlb_obs_snapshot_seq{rank="5"}' in m
    h = json.loads(got["healthz"])
    assert set(h["ranks"]) == {"4", "5"}
    assert h["stale_ranks"] == []


@pytest.mark.slow
def test_acceptance_journeys_relay_steal_mode_tcp():
    """Same world over the steal balancer: cross-server delivery rides
    RFR + fused relay, and the journey's relay hop must be attributed
    to the holder."""
    got, consumed = _world_journeys(dict(balancer="steal"), n_units=24)
    tu = got["trace"]
    assert consumed == 24
    assert tu["count"] == 24
    relayed = [j for j in tu["journeys"] if "relay" in _stages(j)]
    assert relayed, "no relay journey (all units matched locally?)"
    for j in relayed:
        spans = {s[0]: s[1] for s in j["spans"]}
        assert spans["relay"] != spans["deliver"], (
            "relay and deliver on the same rank — custody transfer "
            "did not happen"
        )


@pytest.mark.slow
def test_acceptance_tail_capture_trace_sample_zero_tcp():
    """The ISSUE 14 acceptance world: in a trace_sample=0 TCP fleet
    (tail promotion armed by ops_port alone), a deliberately stalled
    unit and a quarantined unit BOTH appear in /trace/tails with full
    hop chains and correct stage attribution, while the fast bulk is
    not retained — and /trace/units stays empty (no head samples)."""
    import os
    import re

    port = probe_free_ports(1)[0]
    T2 = 2
    n_fast = 80
    # load-aware stall timing (the chaos_soak lesson): a starved-but-
    # healthy host must not push the SIGSTOP past the 2x hang bar
    try:
        load = min(max(os.getloadavg()[0] / max(os.cpu_count() or 1, 1),
                       1.0), 3.0)
    except OSError:
        load = 1.0
    lease = round(1.2 * load, 2)

    def fetch(route):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{route}", timeout=10,
        ).read().decode()

    def app(ctx):
        from adlb_tpu.runtime.faults import sigstop_self

        if ctx.rank == 1:
            # fast consumer: drains the untargeted bulk promptly
            n = 0
            while True:
                rc, _got = ctx.get_work([T])
                if rc != ADLB_SUCCESS:
                    return n
                n += 1
        if ctx.rank == 2:
            # slow/quarantine agent: wait for the go token, consume the
            # deliberately-stalled targeted unit, then hold leases
            # through SIGSTOPs until the retry budget quarantines one
            rc, r = ctx.reserve([T2])
            assert rc == ADLB_SUCCESS
            ctx.get_reserved(r.handle)
            rc, got = ctx.get_work([T])  # the stalled unit (targeted)
            assert rc == ADLB_SUCCESS and got.payload == b"slow"
            stalls = 0
            while stalls < 6:
                rc, r = ctx.reserve([T])
                if rc != ADLB_SUCCESS:
                    return stalls
                stalls += 1
                sigstop_self(round(lease * 1.5, 2))
                # never fetch: the expired lease re-enqueues the unit
                # (attempts+1) and this rank's late fetch is fenced
            return stalls
        # rank 0: producer + observer
        for i in range(n_fast):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        # wait until the bulk has CLOSED fleet-wide (the p99 estimator
        # needs >= TAIL_MIN_COUNT cells) and the threshold tick ran
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            closed = sum(
                int(x) for x in re.findall(
                    r'adlb_fleet_unit_total_s_count\{[^}]*\} (\d+)',
                    fetch("metrics"))
            )
            if closed >= n_fast:
                break
            time.sleep(0.3)
        time.sleep(1.0)  # two threshold ticks + gossip replies
        # the deliberate stall: a targeted unit that sits queued while
        # its only eligible consumer waits for the go token
        assert ctx.put(b"slow", T, target_rank=2) == ADLB_SUCCESS
        time.sleep(2.0)
        assert ctx.put(b"go", T2, target_rank=2) == ADLB_SUCCESS
        # the poison-ish unit: targeted at the stalling rank, budget 1
        assert ctx.put(b"doom", T, target_rank=2) == ADLB_SUCCESS
        out = {}
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            tails = json.loads(fetch("trace/tails"))
            js = tails["journeys"]
            if any(j["end"] == "quarantined" for j in js) and any(
                "slow" in (j.get("why") or []) for j in js
            ):
                out["tails"] = tails
                break
            time.sleep(0.5)
        out["units"] = json.loads(fetch("trace/units"))
        ctx.set_problem_done()
        return out

    cfg = Config(
        balancer="steal", ops_port=port, trace_sample=0.0,
        obs_sync_interval=0.2, exhaust_check_interval=0.2,
        lease_timeout_s=lease, max_unit_retries=1,
        on_worker_failure="reclaim",
    )
    res = spawn_world(3, 2, [T, T2], app, cfg=cfg, timeout=180.0)
    got = res.app_results[0]
    assert "tails" in got, "tail store never showed both promotions"
    js = got["tails"]["journeys"]
    # no head samples exist in this world at all
    assert got["units"]["count"] == 0
    assert res.quarantined == 1
    slow = [j for j in js if "slow" in (j.get("why") or [])]
    quar = [j for j in js if j["end"] == "quarantined"]
    assert slow and quar
    sj = slow[0]
    stages = _stages(sj)
    # tail journeys skip enqueue and the finalize-after-deliver stamp
    assert stages[0] == "put_recv" and stages[-1] == "deliver"
    assert sj["end"] == "delivered"
    assert sj["total_s"] >= 1.0  # the deliberate 2 s queue sit
    # stage attribution: the sit shows up as time-to-REACH match
    assert sj["slow_stage"] == "match"
    assert all(rank in (3, 4) for _s, rank, _t in
               [tuple(s) for s in sj["spans"]])
    qj = quar[0]
    qs = _stages(qj)
    assert qs[0] == "put_recv" and qs[-1] == "finalize"
    assert "expire" in qs  # the lease-expiry hops that burned the budget
    assert qj.get("why") == ["quarantined"]
    # the fast bulk was NOT retained: every delivered tail journey here
    # is the genuinely slow one
    assert all(j["total_s"] > 0.5 for j in js if j["end"] == "delivered")


def test_obs_report_tails_mode(tmp_path):
    """scripts/obs_report.py --tails: the promotion-reason summary plus
    per-journey slow-stage rows with the joined profiler stacks."""
    import os
    import subprocess
    import sys as _sys

    doc = {"count": 1, "journeys": [
        {"trace_id": -99, "job": 0, "type": T, "end": "delivered",
         "why": ["slow"], "t0": 10.0, "total_s": 2.0,
         "slow_stage": "match", "slow_rank": 4, "excess_s": 1.9,
         "stacks": [["reactor;phase:decode;loop.recv", 12]],
         "spans": [["put_recv", 4, 10.0], ["enqueue", 4, 10.01],
                   ["match", 4, 11.9], ["deliver", 4, 11.95],
                   ["finalize", 4, 12.0]]},
    ]}
    f = tmp_path / "trace_tails.json"
    f.write_text(json.dumps(doc))
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "obs_report.py")
    out = subprocess.run(
        [_sys.executable, script, "--tails", str(f)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "tail journeys: 1" in out.stdout
    assert "slow=1" in out.stdout
    assert "match" in out.stdout  # the attributed stage
    assert "reactor;phase:decode;loop.recv" in out.stdout  # the join
    assert "waterfall" in out.stdout


def test_obs_report_journeys_mode(tmp_path):
    """scripts/obs_report.py --journeys: per-stage p50/p99 table by
    job/type plus the slowest-units waterfall, straight off a
    /trace/units response doc."""
    import os
    import subprocess
    import sys as _sys

    doc = {"count": 2, "journeys": [
        {"trace_id": 1, "job": 0, "type": T, "end": "delivered",
         "t0": 10.0, "total_s": 0.5,
         "spans": [["put_recv", 4, 10.0], ["enqueue", 4, 10.01],
                   ["migrate", 5, 10.2], ["match", 5, 10.3],
                   ["deliver", 5, 10.45], ["finalize", 5, 10.5]]},
        {"trace_id": 2, "job": 3, "type": T, "end": "quarantined",
         "t0": 10.0, "total_s": 0.1,
         "spans": [["put_recv", 4, 10.0], ["enqueue", 4, 10.02],
                   ["finalize", 4, 10.1]]},
    ]}
    f = tmp_path / "trace_units.json"
    f.write_text(json.dumps(doc))
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "obs_report.py")
    out = subprocess.run(
        [_sys.executable, script, "--journeys", "--slowest", "1", str(f)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "journeys: 2" in out.stdout
    assert "delivered=1" in out.stdout and "quarantined=1" in out.stdout
    assert "migrate" in out.stdout  # the stage table has the hop
    assert "TOTAL" in out.stdout
    assert "waterfall" in out.stdout
    assert "trace_id=1" in out.stdout  # the slower of the two
    assert "trace_id=2" not in out.stdout  # --slowest 1 cut it


def test_journey_flow_events_in_merged_trace():
    """Config(trace=True) + sampling: closed journeys emit s/t/f flow
    chains into the merged Chrome-trace stream."""

    def app(ctx):
        if ctx.rank == 0:
            for i in range(6):
                ctx.put(b"w" * 16, T, work_prio=i)
        n = 0
        while True:
            rc, _r = ctx.get_work([T])
            if rc < 0:
                break
            n += 1
        if ctx.rank == 0:
            ctx.set_problem_done()
        return n

    res = run_world(2, 1, [T], app,
                    cfg=Config(trace=True, trace_sample=1.0), timeout=60.0)
    assert sum(res.app_results.values()) == 6
    flows = [e for e in res.trace_events if e.get("cat") == "unit"]
    assert flows, "no journey flow events in the merged trace"
    by_id: dict = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    assert len(by_id) == 6
    for chain in by_id.values():
        phases = [e["ph"] for e in chain]
        assert phases[0] == "s" and phases[-1] == "f"
        assert set(phases[1:-1]) <= {"t"}
        assert chain[0]["args"]["stage"] == "put_recv"
        assert chain[-1]["args"]["stage"] == "finalize"
