"""Continuous profiler (ISSUE 14): sampler lifecycle, phase-marker
attribution, folded-stack delta gossip + fleet merge, the /profile ops
route in a real TCP world, and the off-by-default zero-thread proof.
"""

import json
import struct
import threading
import time
import urllib.request

import pytest

from adlb_tpu.obs import profile
from adlb_tpu.obs.profile import (
    WINDOW_S,
    Profiler,
    collapsed_text,
    merge_stacks,
    window_of,
)
from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

T = 1


@pytest.fixture(autouse=True)
def _clean_singleton():
    """Every test starts and ends with no per-process profiler active
    (a leaked one would make the zero-thread proof lie)."""
    profile.stop(profile.active())
    yield
    profile.stop(profile.active())


def _spin_thread(role, phase=None, duration=0.5):
    """A busy thread that declares a role (and optionally a phase) so
    deterministic sample_once() calls have something to fold."""
    ready = threading.Event()
    stop = threading.Event()

    def run():
        profile.register_thread(role)
        if phase is not None:
            p = profile.active()
            if p is not None:
                p.set_phase(phase)
        ready.set()
        while not stop.wait(0.002):
            sum(range(100))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    ready.wait(duration)
    return t, stop


# ------------------------------------------------------------ lifecycle


def test_start_stop_and_hz():
    p = profile.start(hz=200.0, rank=4)
    assert p is not None and profile.active() is p
    # second starter in the same process does NOT get ownership (the
    # in-proc many-servers-one-interpreter rule)
    assert profile.start(hz=200.0, rank=5) is None
    assert profile.active() is p
    t, stop = _spin_thread("worker")
    time.sleep(0.3)
    stop.set()
    t.join()
    profile.stop(p)
    assert profile.active() is None
    assert not any(
        th.name.startswith("adlb-prof") for th in threading.enumerate()
    )
    # ~200 Hz for ~0.3 s: wide bars, but it must actually have sampled
    assert p.samples >= 10
    assert p.counts and all(v >= 1 for v in p.counts.values())


def test_hz_zero_starts_nothing():
    assert profile.start(hz=0.0, rank=1) is None
    assert profile.active() is None


def test_off_by_default_no_thread_in_config():
    # Config default is 0 (off): constructing + running a world must
    # never spawn a sampler thread (the zero-overhead contract)
    assert Config().profile_hz == 0.0
    from adlb_tpu.api import run_world

    def app(ctx):
        if ctx.rank == 0:
            ctx.put(b"w", T)
            ctx.set_problem_done()
        rc, _ = ctx.get_work([T])
        return int(rc == ADLB_SUCCESS)

    run_world(2, 1, [T], app, cfg=Config(), timeout=60.0)
    assert not any(
        th.name.startswith("adlb-prof") for th in threading.enumerate()
    )


# ------------------------------------------------- folding + attribution


def test_phase_marker_and_role_attribution():
    p = profile.start(hz=1000.0, rank=2)
    p._stop.set()  # deterministic: we drive sample_once ourselves
    t, stop = _spin_thread("reactor", phase="handler:FA_PUT")
    for _ in range(5):
        p.sample_once()
        time.sleep(0.002)
    stop.set()
    t.join()
    tagged = [k for k in p.counts
              if k.startswith("reactor;phase:handler:FA_PUT;")]
    assert tagged, list(p.counts)
    # the pytest main thread shows up too, under its fallback name/role
    assert any(not k.startswith("reactor;") for k in p.counts)


def test_windows_seal_on_id_change_and_are_clock_aligned():
    p = Profiler(hz=100.0, rank=3)
    t, stop = _spin_thread("w")
    now = time.monotonic()
    p.sample_once(now=now)
    assert p._win_counts  # current window accumulated
    p.sample_once(now=now + WINDOW_S)  # next window id -> seals previous
    stop.set()
    t.join()
    assert len(p.windows) == 1
    w = p.windows[0]
    assert w["id"] == window_of(now)
    assert w["t0"] == pytest.approx(w["id"] * WINDOW_S, abs=1e-3)
    assert w["stacks"]
    # the join math: any monotonic stamp inside the window maps back to
    # its id without a profiler handshake
    assert window_of(w["t0"] + 0.5) == w["id"]


def test_delta_gossip_is_cumulative_and_changed_only():
    p = Profiler(hz=100.0, rank=3)
    p.counts["reactor;a;b"] = 5
    memo = {}
    d1 = p.take_delta(memo)
    assert d1["stacks"] == {"reactor;a;b": 5}
    assert p.take_delta(memo) == {}  # unchanged -> empty frame
    p.counts["reactor;a;b"] = 9  # cumulative, not a diff
    d2 = p.take_delta(memo)
    assert d2["stacks"] == {"reactor;a;b": 9}
    # windows ship once each
    p.windows.append({"id": 7, "t0": 7.0, "t1": 8.0, "stacks": {"x": 1}})
    d3 = p.take_delta(memo)
    assert [w["id"] for w in d3["win"]] == [7]
    assert p.take_delta(memo) == {}


def test_merge_and_collapsed_text():
    merged = merge_stacks({
        4: {"reactor;a": 3, "reactor;b": 1},
        5: {"reactor;a": 2, "client;c": 7},
    })
    assert merged == {"reactor;a": 5, "reactor;b": 1, "client;c": 7}
    txt = collapsed_text(merged)
    assert txt.splitlines()[0] == "client;c 7"  # heaviest first
    assert "reactor;a 5" in txt


# ------------------------------------------------ master-side gossip


def test_obs_sync_installs_prof_and_serves_profile():
    from adlb_tpu.obs.ops_server import OpsServer
    from tests.test_lifecycle_trace import _mk_server

    master, _ep = _mk_server(rank=2, nranks=4, nservers=2, ops_port=0)
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=1, journeys=[], snap={},
                       prof={"hz": 19.0, "samples": 10,
                             "stacks": {"reactor;decode": 4},
                             "win": [{"id": 50, "t0": 50.0, "t1": 51.0,
                                      "stacks": {"reactor;decode": 4}}]}))
    # cumulative overwrite heals: a later frame replaces per-key
    master._handle(msg(Tag.SS_OBS_SYNC, 3, seq=2, journeys=[], snap={},
                       prof={"hz": 19.0, "samples": 20,
                             "stacks": {"reactor;decode": 11}}))
    assert master._prof_fleet[3]["reactor;decode"] == 11
    assert [w["id"] for w in master._prof_windows[3]] == [50]
    ops = OpsServer(master, 0)
    try:
        doc = ops._profile_doc()
        assert doc["ranks"]["3"] == {"reactor;decode": 11}
        assert doc["merged"]["reactor;decode"] == 11
        assert ops._profile_text().startswith("reactor;decode 11")
    finally:
        ops.stop()


def test_obs_report_profile_mode(tmp_path):
    import os
    import subprocess
    import sys as _sys

    doc = {"hz": 19.0, "ranks": {"4": {"reactor;phase:decode;loop.recv": 6}},
           "merged": {"reactor;phase:decode;loop.recv": 6,
                      "balancer;round.solve": 3},
           "windows": {}}
    f = tmp_path / "profile.json"
    f.write_text(json.dumps(doc))
    out_path = tmp_path / "out.folded"
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "obs_report.py")
    out = subprocess.run(
        [_sys.executable, script, "--profile", "--top", "3",
         "--collapsed", str(out_path), str(f)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "top 3 frames by self samples" in out.stdout
    assert "top 3 frames by cumulative samples" in out.stdout
    assert "loop.recv" in out.stdout
    folded = out_path.read_text()
    assert "reactor;phase:decode;loop.recv 6" in folded
    assert "balancer;round.solve 3" in folded


# ------------------------------------------------ acceptance (TCP world)


@pytest.mark.slow
def test_profile_route_merged_fleet_tcp():
    """The acceptance bar: /profile serves a merged fleet collapsed-
    stack view with reactor phase tags from >= 2 ranks, live, in a real
    multi-process TCP world."""
    port = probe_free_ports(1)[0]

    def app(ctx):
        if ctx.rank != 0:
            n = 0
            while True:
                rc, _got = ctx.get_work([T])
                if rc != ADLB_SUCCESS:
                    return n
                n += 1
        deadline = time.monotonic() + 30.0
        doc = None
        # keep protocol traffic flowing so reactor phases are exercised
        # while we poll for both server ranks' profiles to arrive
        while time.monotonic() < deadline:
            for i in range(8):
                ctx.put(struct.pack("<q", i), T)
            time.sleep(0.4)
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?format=json", timeout=10,
            ).read().decode())
            if len(doc["ranks"]) >= 2 and any(
                ";phase:" in k for st in doc["ranks"].values() for k in st
            ):
                break
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/profile", timeout=10,
        ).read().decode()
        ctx.set_problem_done()
        return {"doc": doc, "text": text}

    cfg = Config(balancer="steal", ops_port=port, profile_hz=47.0,
                 obs_sync_interval=0.2, exhaust_check_interval=0.2)
    res = spawn_world(2, 2, [T], app, cfg=cfg, timeout=120.0)
    got = res.app_results[0]
    doc = got["doc"]
    # both server processes contributed (master live + peer via gossip)
    assert set(doc["ranks"]) == {"2", "3"}, set(doc["ranks"])
    for r, stacks in doc["ranks"].items():
        assert stacks, f"rank {r} shipped an empty profile"
        assert any(k.startswith("reactor") for k in stacks), (r, stacks)
    assert any(";phase:" in k for st in doc["ranks"].values() for k in st)
    # merged = elementwise sum of the rank views
    some_key = next(iter(doc["merged"]))
    assert doc["merged"][some_key] == sum(
        st.get(some_key, 0) for st in doc["ranks"].values()
    )
    # the text form is collapsed-stack lines "stack count"
    line = got["text"].splitlines()[0]
    assert line.rsplit(" ", 1)[1].isdigit()
