"""Pool checkpoint/resume — a capability the reference lacks entirely
(SURVEY §5: no serialization of wq state; killing a run loses every queued
unit)."""

import struct

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

T1, T2, T_NEVER = 1, 2, 3


def test_checkpoint_and_resume_roundtrip(tmp_path):
    prefix = str(tmp_path / "pool")

    def phase1(ctx):
        """Put 30 units, consume 10, checkpoint the remainder, stop."""
        if ctx.rank == 0:
            for i in range(30):
                ctx.put(struct.pack("<q", i), T1 if i % 2 else T2,
                        work_prio=i % 7,
                        target_rank=1 if i % 5 == 0 else -1)
            got = []
            for _ in range(10):
                rc, r = ctx.reserve()
                assert rc == ADLB_SUCCESS
                rc, buf = ctx.get_reserved(r.handle)
                got.append(struct.unpack("<q", buf)[0])
            rc, n = ctx.checkpoint(prefix)
            assert rc == ADLB_SUCCESS
            ctx.set_problem_done()
            return got, n
        rc, _ = ctx.reserve([T_NEVER])  # parked; nothing may match before
        assert rc != ADLB_SUCCESS       # the termination flush
        return None

    res1 = run_world(3, 2, [T1, T2, T_NEVER], phase1,
                     cfg=Config(exhaust_check_interval=10.0))
    got1, n_captured = res1.app_results[0]
    assert len(got1) == 10
    assert n_captured == 20, f"checkpoint captured {n_captured} units"

    def phase2(ctx):
        """Fresh world restores the shards and drains the remainder."""
        got = []
        while True:
            rc, r = ctx.reserve()
            if rc != ADLB_SUCCESS:
                return got
            rc, buf = ctx.get_reserved(r.handle)
            got.append((struct.unpack("<q", buf)[0], r.work_type))

    res2 = run_world(
        3, 2, [T1, T2, T_NEVER], phase2,
        cfg=Config(restore_path=prefix, exhaust_check_interval=0.2),
    )
    drained = sorted(x for v in res2.app_results.values() for x in (v or []))
    assert len(drained) == 20
    # exactly the unconsumed 20 of the 30, with types intact
    expected = sorted(
        (i, T1 if i % 2 else T2) for i in range(30) if i not in got1
    )
    assert drained == expected
    # targeted units went to their target
    targeted = [i for i in range(30) if i % 5 == 0 and i not in got1]
    rank1 = [i for i, _ in (res2.app_results[1] or [])]
    assert set(targeted) <= set(rank1), (targeted, rank1)


def test_checkpoint_preserves_batch_common_prefix(tmp_path):
    prefix = str(tmp_path / "pool2")
    common = b"SHAREDHDR:"

    def phase1(ctx):
        if ctx.rank == 0:
            ctx.begin_batch_put(common)
            for i in range(6):
                ctx.put(struct.pack("<q", i), T1)
            ctx.end_batch_put()
            rc, n = ctx.checkpoint(prefix)
            assert rc == ADLB_SUCCESS and n == 6
            ctx.set_problem_done()
        else:
            rc, _ = ctx.reserve([T_NEVER])
            assert rc != ADLB_SUCCESS
        return None

    run_world(2, 2, [T1, T2, T_NEVER], phase1,
              cfg=Config(exhaust_check_interval=10.0))

    def phase2(ctx):
        got = []
        while True:
            rc, r = ctx.reserve([T1])
            if rc != ADLB_SUCCESS:
                return got
            rc, buf = ctx.get_reserved(r.handle)
            assert buf.startswith(common), buf
            got.append(struct.unpack("<q", buf[len(common):])[0])

    res = run_world(
        2, 2, [T1, T2, T_NEVER], phase2,
        cfg=Config(restore_path=prefix, exhaust_check_interval=0.2),
    )
    drained = sorted(x for v in res.app_results.values() for x in (v or []))
    assert drained == list(range(6))


def test_checkpoint_under_balancer_churn(tmp_path):
    """Checkpoint taken while the TPU balancer is actively migrating a
    hot server's inventory: the token is held at servers with unacked
    migration batches, so accepted = consumed-before + drained-after."""
    import time

    prefix = str(tmp_path / "pool3")

    def phase1(ctx):
        if ctx.rank == 0:
            for i in range(80):
                ctx.put(struct.pack("<q", i), T1, work_prio=i % 5)
            time.sleep(0.08)  # migrations in flight
            rc, n = ctx.checkpoint(prefix)
            assert rc == ADLB_SUCCESS
            ctx.set_problem_done()
            return ("ckpt", n)
        got = []
        while True:
            rc, r = ctx.reserve([T1])
            if rc != ADLB_SUCCESS:
                return ("got", got)
            rc, buf = ctx.get_reserved(r.handle)
            got.append(struct.unpack("<q", buf)[0])
            time.sleep(0.004)

    cfg1 = Config(
        balancer="tpu", put_routing="home", exhaust_check_interval=10.0,
        balancer_max_tasks=64, balancer_max_requesters=16,
    )
    res1 = run_world(4, 3, [T1, T2, T_NEVER], phase1, cfg=cfg1)
    consumed1 = sorted(
        x for v in res1.app_results.values() if v[0] == "got" for x in v[1]
    )

    def phase2(ctx):
        got = []
        while True:
            rc, r = ctx.reserve([T1])
            if rc != ADLB_SUCCESS:
                return got
            rc, buf = ctx.get_reserved(r.handle)
            got.append(struct.unpack("<q", buf)[0])

    res2 = run_world(
        4, 3, [T1, T2, T_NEVER], phase2,
        cfg=Config(restore_path=prefix, exhaust_check_interval=0.2),
    )
    drained = sorted(x for v in res2.app_results.values() for x in (v or []))
    # snapshot semantics: everything put is either consumed before the
    # NO_MORE_WORK flush or present in the checkpoint; units consumed
    # between token and flush may legitimately appear in both
    assert set(consumed1) | set(drained) == set(range(80)), (
        sorted(set(range(80)) - (set(consumed1) | set(drained)))
    )


def test_checkpoint_missing_shard_is_loud(tmp_path):
    from adlb_tpu.runtime.checkpoint import load_shard

    with pytest.raises(FileNotFoundError):
        load_shard(str(tmp_path / "nothing"), 3)


def test_concurrent_held_checkpoints_all_complete():
    """Two checkpoint tokens arriving while migrations are unacked must
    BOTH be processed after the last ack — a single held slot would
    overwrite the first and leave its client blocked forever."""
    from adlb_tpu.runtime.messages import Tag, msg
    from adlb_tpu.runtime.server import Server

    s = Server.__new__(Server)
    s._migrate_unacked = 2
    processed = []
    s._process_checkpoint = lambda m: processed.append(m.path)
    s._on_ss_checkpoint(msg(Tag.SS_CHECKPOINT, 0, path="a", client=1,
                            started=False))
    s._on_ss_checkpoint(msg(Tag.SS_CHECKPOINT, 0, path="b", client=2,
                            started=False))
    assert processed == []
    s._on_migrate_ack(msg(Tag.SS_MIGRATE_ACK, 5))
    assert processed == []  # one batch still in flight
    s._on_migrate_ack(msg(Tag.SS_MIGRATE_ACK, 5))
    assert processed == ["a", "b"]
