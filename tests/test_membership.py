"""Elastic membership (adlb_tpu/runtime/membership.py): ranks and
servers that join and leave a RUNNING world.

Coverage layers:

* **MemberView** — duck-typed WorldSpec surface: behavior-identical
  delegation with no dynamic members, attach/detach/server-join
  mutation, snapshot/seed round-trip, the dynamic ring order.
* **Attach/detach lifecycle** — a rank attached mid-run consumes real
  work and its puts land in the coverage set; detach is a clean
  lease-draining exit (counted once fleet-wide, idempotent on re-send,
  finalize-after-detach a no-op).
* **Epoch-based termination** — a join racing the exhaustion/END
  machinery can never freeze the world or lose its work: the
  membership epoch voids in-flight verdicts (stress-looped).
* **Scale-out** — a new server shard bootstraps from a donor over the
  acked migration plane: every put acked before the scale-out is
  fetchable after it, byte-identically.
* **Scale-in** — draining a server through the promote path counts
  ZERO losses and ZERO failovers (the clean/dirty metrics split).
* **Targeted-put redirection** — a static client's base-modulo route
  toward an attached rank lands off-home and is redirected through the
  TargetedDirectory announce plane.
* **Watermark autoscale** — Config(elastic_scaleout="auto") requests a
  shard when a server crosses the soft watermark.
* **Churn observability** — units that crossed a scale-out rebalance /
  a drain carry `attach`/`drain` journey hops, always promoted under
  tail mode; /healthz drops a drained server from per-rank staleness.
* **TCP acceptance** (slow) — a real multi-process world gains a rank
  over TCP mid-run and serves /fleet.
"""

import struct
import threading
import time

import pytest

from adlb_tpu.runtime.membership import (
    ElasticWorld,
    MemberView,
    attach_app,
    is_provisional,
    provisional_rank,
)
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS, AdlbError

T = 1


def _cfg(**kw):
    kw.setdefault("exhaust_check_interval", 0.2)
    return Config(**kw)


def _consume(ctx, pace=0.002):
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(w.payload)
        if pace:
            time.sleep(pace)
    return got


def _producer(n, base=0, consume=True):
    def app(ctx):
        for i in range(n):
            ctx.put(struct.pack("<q", base + i), T)
        return _consume(ctx) if consume else []
    return app


def _ids(results):
    return sorted(
        struct.unpack("<q", p)[0]
        for v in results.values() if v for p in v
    )


# ------------------------------------------------------------ MemberView


def test_member_view_static_identity():
    spec = WorldSpec(nranks=7, nservers=3, types=(1, 2))
    view = MemberView(spec)
    assert view.nservers == spec.nservers
    assert list(view.server_ranks) == list(spec.server_ranks)
    assert list(view.app_ranks) == list(spec.app_ranks)
    for r in range(7):
        assert view.is_app(r) == spec.is_app(r)
        assert view.is_server(r) == spec.is_server(r)
    for r in spec.app_ranks:
        assert view.home_server(r) == spec.home_server(r)
    for s in spec.server_ranks:
        assert view.local_apps(s) == spec.local_apps(s)
        assert view.ring_next(s) == spec.ring_next(s)
    # non-topology attributes delegate to the spec
    assert view.types == spec.types
    assert view.master_server_rank == spec.master_server_rank
    # idempotent wrap
    assert MemberView.of(view) is view


def test_member_view_dynamic_mutation():
    spec = WorldSpec(nranks=6, nservers=2, types=(1,))
    view = MemberView(spec)
    # attach: a new app rank above the base world
    view.add_app(8, home=5, epoch=3)
    assert view.is_app(8) and not view.is_server(8)
    assert view.home_server(8) == 5
    assert 8 in view.local_apps(5)
    assert view.epoch == 3
    # an attached rank the view has NOT learned raises (never the
    # silent base-modulo misroute)
    with pytest.raises(KeyError):
        view.home_server(9)
    # detach: leaves membership, stays remembered
    view.remove_app(8, epoch=4)
    assert not view.is_app(8)
    assert 8 not in view.local_apps(5)
    assert view.epoch == 4
    # server join extends the ring AFTER the base range, in join order
    view.add_server(7, epoch=5)
    assert view.is_server(7)
    assert view.nservers == 3
    assert list(view.server_ranks) == [4, 5, 7]
    assert view.ring_next(5) == 7 and view.ring_next(7) == 4
    # epochs never regress
    view.note_epoch(2)
    assert view.epoch == 5
    # snapshot/seed round-trip seeds a fresh joiner's view
    other = MemberView(spec)
    other.seed(view.snapshot())
    assert other.epoch == 5
    assert other.is_server(7)
    assert not other.is_app(8) and 8 in other.detached


def test_provisional_ranks_distinct():
    a, b = provisional_rank(), provisional_rank()
    assert a != b
    assert is_provisional(a) and is_provisional(b)
    spec = WorldSpec(nranks=6, nservers=2, types=(1,))
    view = MemberView(spec)
    # provisional ids classify as neither app nor server
    assert not view.is_app(a) and not view.is_server(a)


def test_attach_refused_on_native_cfg():
    spec = WorldSpec(nranks=4, nservers=2, types=(1,))
    with pytest.raises(AdlbError, match="python servers"):
        attach_app(spec, Config(server_impl="native"), fabric=object())


# ------------------------------------------------- attach/detach lifecycle


def test_attach_detach_lifecycle():
    n = 20
    ew = ElasticWorld(2, 2, [T], cfg=_cfg())
    h0 = ew.run_app(0, _producer(n))
    ew.run_app(1, _consume)
    time.sleep(0.2)
    # a rank attached mid-run consumes real work...
    attached = ew.attach_app(_consume)
    assert attached.rank >= ew.world.nranks
    # ...and another attaches, puts, and detaches cleanly
    jw = ew.attach_ctx()
    ctx = jw.ctx
    ctx.put(struct.pack("<q", 777), T)
    assert ctx.detach_world() == ADLB_SUCCESS
    # finalize after detach is a no-op, not a protocol error
    assert ctx._c.finalize() == ADLB_SUCCESS
    results = ew.finish(timeout=90)
    assert _ids(results) == sorted(list(range(n)) + [777])
    # membership metrics count ONCE fleet-wide; the epoch advanced
    master = ew.master
    attached_total = sum(
        s.metrics.value("ranks_attached") for s in ew.servers.values()
    )
    detached_total = sum(
        s.metrics.value("ranks_detached") for s in ew.servers.values()
    )
    assert attached_total == 2.0
    assert detached_total == 1.0
    assert master.world.epoch >= 3  # two attaches + one detach
    assert ctx.rank in master.world.detached


def test_detach_idempotent():
    ew = ElasticWorld(1, 2, [T], cfg=_cfg())
    ew.run_app(0, _producer(4))
    jw = ew.attach_ctx()
    ctx = jw.ctx
    assert ctx.detach_world() == ADLB_SUCCESS
    # a re-sent detach (response lost across churn) settles SUCCESS
    c = ctx._c
    c._detached = False
    assert c.detach() == ADLB_SUCCESS
    ew.finish(timeout=60)


def test_fleet_doc_reflects_membership():
    ew = ElasticWorld(1, 2, [T], cfg=_cfg())
    ew.run_app(0, _producer(6))
    jw = ew.attach_ctx()
    rank = jw.ctx.rank
    doc = ew.master.fleet_doc()
    me = [a for a in doc["apps"] if a["rank"] == rank]
    assert me and me[0]["attached"] and me[0]["state"] == "live"
    assert doc["epoch"] >= 1
    assert all(s["state"] == "live" for s in doc["servers"])
    assert jw.ctx.detach_world() == ADLB_SUCCESS
    doc = ew.master.fleet_doc()
    assert rank in doc["detached"]
    assert all(a["rank"] != rank for a in doc["apps"])
    ew.finish(timeout=60)


# ----------------------------------------------- join vs END-ring racing


def test_join_racing_termination_never_hangs():
    """A rank attaching as the world drains: either the attach lands
    (its put must be covered — the epoch voids any mid-flight
    exhaustion/END verdict) or termination was already underway and the
    attach is REFUSED loudly. A hang or a lost put is the only failure.
    Stress-looped: the race window is the exhaustion check cadence."""
    for trial in range(4):
        n = 6
        ew = ElasticWorld(2, 2, [T], cfg=_cfg(exhaust_check_interval=0.05))
        ew.run_app(0, _producer(n))
        ew.run_app(1, _consume)
        # no sleep: the attach races bring-up/drain directly
        extra = None
        got = []
        try:
            jw = ew.attach_ctx()
            extra = 1000 + trial
            jw.ctx.put(struct.pack("<q", extra), T)
            got = _consume(jw.ctx)
            jw.ctx._c.finalize()  # the joiner gates END until it reports
        except AdlbError as e:
            # refused: termination was underway — must be the loud path
            assert "refused" in str(e) or "terminating" in str(e), e
        results = ew.finish(timeout=90)
        ids = _ids(results) + sorted(struct.unpack("<q", p)[0] for p in got)
        want = list(range(n)) + ([extra] if extra is not None else [])
        assert sorted(ids) == sorted(want), (trial, sorted(ids), want)


# ------------------------------------------------------------- scale-out


def test_scaleout_ships_backlog_byte_identically():
    """Every put acked BEFORE the scale-out is fetchable after it: the
    donor ships a slice of its backlog to the new shard over the acked
    migration plane, and consumers drain the lot byte-identically."""
    n = 40
    payloads = {struct.pack("<q", i) * 3 for i in range(n)}
    ew = ElasticWorld(2, 2, [T], cfg=_cfg())
    acked = threading.Event()  # every put acknowledged
    go = threading.Event()     # scale-out done; start consuming

    def producer(ctx):
        for p in sorted(payloads):
            assert ctx.put(p, T) == ADLB_SUCCESS  # put() acks synchronously
        acked.set()
        # membership ops are refused once termination is underway, so
        # the rank stays live (unfinalized) across the scale-out
        go.wait(60)
        return _consume(ctx, pace=0)

    ew.run_app(0, producer)
    assert acked.wait(30)
    new = ew.scale_out()
    assert new not in ew.world.server_ranks  # a genuinely new rank
    # the donor rebalance lands asynchronously: wait for the new shard
    # to hold inventory before unleashing the consumers
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if ew.servers[new].wq.count > 0:
            break
        time.sleep(0.02)
    shipped = ew.servers[new].wq.count
    assert shipped > 0, "scale-out shard received no bootstrap inventory"
    ew.run_app(1, lambda ctx: _consume(ctx, pace=0))
    go.set()
    results = ew.finish(timeout=120)
    got = [p for v in results.values() if v for p in v]
    assert sorted(got) == sorted(payloads)  # byte-identical coverage
    master = ew.master
    assert master.metrics.value("servers_joined") == 1.0
    assert new in master._member_ready
    assert master.world.epoch >= 2  # server_join + server_live


def test_scalein_drain_counts_zero_losses():
    """Scale-in drains through the failover promote path WITHOUT the
    death accounting: exact coverage, failover_lost == 0 everywhere,
    and failover_promoted == 0 (a drain is not a failover)."""
    n = 30
    ew = ElasticWorld(2, 3, [T],
                      cfg=_cfg(on_server_failure="failover",
                               put_routing="round_robin"))
    acked = threading.Event()
    go = threading.Event()

    def producer(ctx):
        for i in range(n):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        acked.set()
        go.wait(60)
        return _consume(ctx, pace=0)

    ew.run_app(0, producer)
    assert acked.wait(30)  # backlog spread over all three servers, acked
    victim = ew.scale_in()
    assert victim != ew.world.master_server_rank
    ew.run_app(1, lambda ctx: _consume(ctx, pace=0))
    go.set()
    results = ew.finish(timeout=120)
    assert _ids(results) == list(range(n))
    live = [s for r, s in ew.servers.items() if r != victim]
    assert all(s.metrics.value("failover_lost") == 0.0 for s in live)
    assert all(s.metrics.value("failover_promoted") == 0.0 for s in live)
    assert ew.master.metrics.value("servers_drained") == 1.0
    doc = ew.master.fleet_doc()
    state = {s["rank"]: s["state"] for s in doc["servers"]}
    assert state[victim] == "drained"


# ------------------------------------------- targeted-put redirection


def test_targeted_put_to_attached_rank_redirects():
    """A static client's route toward an attached rank cannot know its
    assigned home (the base modulo formula predates the attach): the
    put lands off-home and the receiving server must announce the
    inventory to the real home so the rank's reserve finds it."""
    ew = ElasticWorld(2, 2, [T], cfg=_cfg())
    jw = ew.attach_ctx()
    target = jw.ctx.rank
    box = {}
    fetched = threading.Event()

    def putter(ctx):
        # static WorldSpec view: this route is the base-modulo guess
        assert ctx.put(b"hello-attached", T, target_rank=target) \
            == ADLB_SUCCESS
        fetched.wait(40)
        return []

    ew.run_app(0, putter)
    ew.run_app(1, lambda ctx: (fetched.wait(40), [])[1])

    def fetch():
        rc, w = jw.ctx.get_work([T])
        box["rc"], box["payload"] = rc, (w.payload if w else None)
        fetched.set()

    t = threading.Thread(target=fetch, daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive(), "targeted fetch never redirected"
    assert box["rc"] == ADLB_SUCCESS and box["payload"] == b"hello-attached"
    jw.ctx._c.finalize()
    ew.finish(timeout=60)


# ------------------------------------------------------ watermark autoscale


def test_watermark_autoscale_spawns_shard():
    """Config(elastic_scaleout='auto'): crossing the soft watermark
    requests a scale-out BEFORE spill/backpressure — with the harness
    spawner registered, a shard actually joins."""
    ew = ElasticWorld(
        2, 2, [T],
        cfg=_cfg(elastic_scaleout="auto", elastic_cooldown_s=0.5,
                 max_malloc_per_server=8 * 1024, mem_soft_frac=0.5),
    )
    payload = b"x" * 512
    go = threading.Event()

    def storm(ctx):
        for _ in range(24):
            ctx.put(payload, T)
        go.wait(60)
        return []

    ew.run_app(0, storm)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ew.master._member_ready:
            break
        time.sleep(0.05)
    assert ew.master._member_ready, "watermark never requested a shard"
    ew.run_app(1, lambda ctx: _consume(ctx, pace=0))
    go.set()
    ew.finish(timeout=90)
    assert ew.master.metrics.value("servers_joined") >= 1.0


def test_scale_pending_drained_on_spawner_registration():
    """A watermark scale-out arriving SPAWNERLESS parks in the
    single-slot _scale_pending (dedup-collapsed — each new request
    overwrites, newest wins) and is visible at /fleet; a spawner
    registering later must service the parked request immediately —
    the shard joins WITHOUT the trigger having to re-fire."""
    ew = ElasticWorld(
        2, 2, [T],
        cfg=_cfg(elastic_scaleout="auto", elastic_cooldown_s=0.5,
                 max_malloc_per_server=8 * 1024, mem_soft_frac=0.5),
    )
    master = ew.master
    spawner = master.member_spawner
    master.member_spawner = None  # the harness has not registered yet
    payload = b"x" * 512
    go = threading.Event()

    def storm(ctx):
        for _ in range(24):
            ctx.put(payload, T)
        go.wait(60)
        return []

    ew.run_app(0, storm)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if master._scale_pending is not None:
            break
        time.sleep(0.05)
    assert master._scale_pending is not None, "request never parked"
    doc = master.fleet_doc()
    assert doc["scale_pending"]["reason"] == "mem_watermark"
    # dedup-collapse: a second spawnerless request overwrites the slot
    master._request_scale_out("manual_probe", hot_rank=None)
    assert master._scale_pending["reason"] == "manual_probe"
    nservers = len(ew.servers)
    # registration drains the parked slot synchronously...
    master.member_spawner = spawner
    assert master._scale_pending is None
    # ...and the shard actually joins, with no trigger re-firing
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if len(ew.servers) > nservers:
            break
        time.sleep(0.05)
    assert len(ew.servers) > nservers, "parked request never serviced"
    ew.run_app(1, lambda ctx: _consume(ctx, pace=0))
    go.set()
    ew.finish(timeout=90)
    assert ew.master.metrics.value("servers_joined") >= 1.0


def test_autoscale_config_validation():
    with pytest.raises(ValueError):
        Config(elastic_scaleout="sideways")
    with pytest.raises(ValueError):
        Config(elastic_scaleout="auto", server_impl="native")
    with pytest.raises(ValueError):
        Config(elastic_cooldown_s=-1)


def test_attach_after_scalein_routes_around_drained():
    """A rank attaching AFTER a server retirement missed every
    TA_HOME_TAKEOVER broadcast: the attach reply must seed its
    client-side route map (retired -> live successor), or its
    round-robin puts dial the drained listener and die waiting for a
    takeover note that never re-arrives."""
    n = 20
    ew = ElasticWorld(2, 3, [T],
                      cfg=_cfg(on_server_failure="failover",
                               put_routing="round_robin"))
    hold = threading.Event()

    def producer(ctx):
        for i in range(n):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        hold.wait(60)
        return _consume(ctx, pace=0)

    ew.run_app(0, producer)
    ew.run_app(1, lambda ctx: (hold.wait(60), _consume(ctx, pace=0))[1])
    victim = ew.scale_in()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            victim not in ew.master._drained_servers:
        time.sleep(0.02)
    jw = ew.attach_ctx()
    route = jw.ctx._c._srv_route
    assert victim in route and route[victim] != victim, route
    # enough round-robin puts to hit every server slot, the drained
    # one's included — each must resolve to the live successor at once
    extra = list(range(1000, 1000 + 2 * len(ew.world.server_ranks)))
    t0 = time.monotonic()
    for i in extra:
        assert jw.ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
    assert time.monotonic() - t0 < 5.0  # no takeover-window stalls
    assert jw.ctx.detach_world() == ADLB_SUCCESS
    hold.set()
    results = ew.finish(timeout=120)
    assert _ids(results) == list(range(n)) + extra


# --------------------------------------------- churn observability


def test_churn_hops_promoted_and_healthz_drops_drained():
    """Churn events are visible in the tracing plane: a unit shipped to
    a scale-out shard's bootstrap rebalance carries an `attach` hop, a
    unit that crossed a scale-in drain carries a `drain` hop, and both
    journeys are ALWAYS promoted (why == churn) under tail mode even
    though they delivered cleanly in a trace_sample=0 world. The
    drained server drops out of /healthz per-rank staleness instead of
    reporting stale forever (/fleet keeps the topology history)."""
    from adlb_tpu.obs.ops_server import OpsServer

    n = 40
    ew = ElasticWorld(
        2, 3, [T],
        cfg=_cfg(on_server_failure="failover", trace_sample=0.0,
                 trace_tail="on", put_routing="round_robin"),
    )
    acked = threading.Event()
    go = threading.Event()

    def producer(ctx):
        for i in range(n):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        acked.set()
        go.wait(60)
        return _consume(ctx, pace=0)

    ew.run_app(0, producer)
    assert acked.wait(30)
    new = ew.scale_out()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and ew.servers[new].wq.count == 0:
        time.sleep(0.02)
    assert ew.servers[new].wq.count > 0
    victim = ew.scale_in()
    ew.run_app(1, lambda ctx: _consume(ctx, pace=0))
    go.set()
    results = ew.finish(timeout=120)
    assert _ids(results) == list(range(n))
    done = [
        j for s in ew.servers.values() for j in s.journeys.take_done()
    ]
    churned = [j for j in done if j["why"] == ["churn"]]
    hops = {
        st for j in churned for st, _r, _t in j["spans"]
        if st in ("attach", "drain")
    }
    assert "attach" in hops, f"no attach hop in {len(done)} journeys"
    assert "drain" in hops, f"no drain hop in {len(done)} journeys"
    assert all(j["end"] == "delivered" for j in churned)
    # the drained server must NOT linger in per-rank staleness
    ops = OpsServer(ew.master, port=0)
    try:
        ranks = ops._healthz()["ranks"]
        assert str(victim) not in ranks
        assert str(ew.master.rank) in ranks
    finally:
        ops.stop()


# ------------------------------------------------------- TCP acceptance


@pytest.mark.slow
def test_tcp_world_gains_rank_and_serves_fleet():
    """Real multi-process acceptance: a spawn-plane TCP world gains an
    app rank over TCP mid-run (rank 0 attaches it from inside the
    world, via the master's published address), the joiner's put is
    covered, and GET /fleet serves the attached topology."""
    import json
    import urllib.request

    from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
    from adlb_tpu.api import attach_world

    n = 16
    ops_port = probe_free_ports(1)[0]

    def app(ctx):
        if ctx.rank != 0:
            return [struct.unpack("<q", p)[0] for p in _consume(ctx)]
        ep = ctx._c.ep
        base = getattr(ep, "_ep", ep)  # unwrap shm/fault shims
        master = ctx._c.world.master_server_rank
        addr = base.addr_map[master]
        world = WorldSpec(nranks=ctx._c.world.nranks,
                          nservers=ctx._c.world.nservers, types=(T,))
        with attach_world(world, _cfg(), master_addr=addr) as actx:
            assert actx.rank >= world.nranks
            actx.put(struct.pack("<q", 999), T)
            fleet = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ops_port}/fleet", timeout=10
            ).read())
            assert any(
                a["rank"] == actx.rank and a["attached"]
                for a in fleet["apps"]
            ), fleet
        for i in range(n):
            ctx.put(struct.pack("<q", i), T)
        return [struct.unpack("<q", p)[0] for p in _consume(ctx)]

    res = spawn_world(3, 2, [T], app,
                      cfg=_cfg(ops_port=ops_port), timeout=180.0)
    got = sorted(x for v in res.app_results.values() for x in v)
    assert got == sorted(list(range(n)) + [999]), got
