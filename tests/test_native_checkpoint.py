"""Checkpoint/resume on the native plane (serverd.cpp) and shard
interchangeability with the Python plane.

The shard bytes are the same ACK1 format both planes write
(``runtime/checkpoint.py``), so a pool checkpointed under C++ daemons can
be restored under Python servers and vice versa — the crash-recovery
story does not depend on which data plane a deployment runs.
"""

import shutil
import struct

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)

T = 1
PREFIX = b"COMMONPREFIX"
N_PLAIN = 18
N_COMMON = 5
TARGETED_VALUE = 1000


def _writer(prefix):
    def app(ctx):
        if ctx.rank != 0:
            return None
        for i in range(N_PLAIN):
            assert ctx.put(struct.pack("<q", i), T,
                           work_prio=i % 5) == ADLB_SUCCESS
        assert ctx.put(struct.pack("<q", TARGETED_VALUE), T,
                       target_rank=1) == ADLB_SUCCESS
        ctx.begin_batch_put(PREFIX)
        for i in range(N_COMMON):
            assert ctx.put(struct.pack("<q", 100 + i), T) == ADLB_SUCCESS
        ctx.end_batch_put()
        rc, count = ctx.checkpoint(prefix)
        assert rc == ADLB_SUCCESS
        return count

    return app


def _consumer(ctx):
    got = []
    while True:
        rc, r = ctx.reserve([T])
        if rc != ADLB_SUCCESS:
            return sorted(got)
        rc, buf = ctx.get_reserved(r.handle)
        if buf.startswith(PREFIX):
            buf = buf[len(PREFIX):]
        got.append(struct.unpack("<q", buf)[0])


EXPECTED = sorted(
    list(range(N_PLAIN))
    + [TARGETED_VALUE]
    + [100 + i for i in range(N_COMMON)]
)


def _check_restore(res):
    all_got = sorted(
        x for v in res.app_results.values() if v for x in v
    )
    assert all_got == EXPECTED
    # the targeted unit must have gone to rank 1 and only rank 1
    assert TARGETED_VALUE in (res.app_results.get(1) or [])


def test_native_checkpoint_restore_roundtrip(tmp_path):
    prefix = str(tmp_path / "pool")
    res = spawn_world(
        3, 2, [T], _writer(prefix),
        cfg=Config(server_impl="native"), timeout=60.0,
    )
    assert res.app_results[0] == N_PLAIN + 1 + N_COMMON
    res2 = spawn_world(
        3, 2, [T], _consumer,
        cfg=Config(server_impl="native", restore_path=prefix,
                   exhaust_check_interval=0.15),
        timeout=60.0,
    )
    _check_restore(res2)


def test_native_shard_restores_into_python_servers(tmp_path):
    prefix = str(tmp_path / "pool")
    spawn_world(
        3, 2, [T], _writer(prefix),
        cfg=Config(server_impl="native"), timeout=60.0,
    )
    res = run_world(
        3, 2, [T], _consumer,
        cfg=Config(restore_path=prefix, exhaust_check_interval=0.15),
        timeout=60.0,
    )
    _check_restore(res)


def test_python_shard_restores_into_native_servers(tmp_path):
    prefix = str(tmp_path / "pool")
    res = run_world(
        3, 2, [T], _writer(prefix), cfg=Config(), timeout=60.0,
    )
    assert res.app_results[0] == N_PLAIN + 1 + N_COMMON
    res2 = spawn_world(
        3, 2, [T], _consumer,
        cfg=Config(server_impl="native", restore_path=prefix,
                   exhaust_check_interval=0.15),
        timeout=60.0,
    )
    _check_restore(res2)


def test_c_client_checkpoint_call(tmp_path):
    """ADLB_Checkpoint over the C API: the drained pool checkpoints with
    zero captured units and every server writes its (empty) shard."""
    import os

    from adlb_tpu.native.capi import build_example, run_native_world

    exa = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "capi_smoke.c",
    )
    prefix = str(tmp_path / "cpool")
    exe = build_example(exa)
    results, stats = run_native_world(
        n_clients=3, nservers=2, types=[1, 2], exe=exe,
        cfg=Config(exhaust_check_interval=0.2),
        env_extra={"ADLB_CKPT_PREFIX": prefix},
        timeout=90.0,
    )
    for rc, out, err in results:
        assert rc == 0, f"exit {rc}\nstdout:{out}\nstderr:{err}"
    from adlb_tpu.runtime.checkpoint import existing_shard_ranks

    assert existing_shard_ranks(prefix) == [3, 4]


def test_native_ckpt_preserves_fifo_among_equal_prio(tmp_path):
    """Restore assigns fresh seqnos in shard order, so the shard must be
    written seqno-sorted: a hash-ordered dump would scramble FIFO dispatch
    among equal-priority units (the wqcore.hpp 'FIFO by seqno among
    equals' contract), which the Python plane's insertion-ordered dict
    preserves."""
    prefix = str(tmp_path / "pool")
    n = 12

    def writer(ctx):
        for i in range(n):
            assert ctx.put(struct.pack("<q", i), T,
                           work_prio=7) == ADLB_SUCCESS
        rc, count = ctx.checkpoint(prefix)
        assert rc == ADLB_SUCCESS
        return count

    res = spawn_world(
        1, 1, [T], writer, cfg=Config(server_impl="native"), timeout=60.0,
    )
    assert res.app_results[0] == n

    def consumer(ctx):
        got = []
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return got
            _, buf = ctx.get_reserved(r.handle)
            got.append(struct.unpack("<q", buf)[0])

    res2 = spawn_world(
        1, 1, [T], consumer,
        cfg=Config(server_impl="native", restore_path=prefix,
                   exhaust_check_interval=0.15),
        timeout=60.0,
    )
    assert res2.app_results[0] == list(range(n))


def test_native_restore_rejects_stray_shards(tmp_path):
    """A shard for a server rank outside the restore world means a
    different world shape: the daemon must die loudly, not silently drop
    that shard's units (mirrors the Python server's guard)."""
    prefix = str(tmp_path / "pool")
    spawn_world(
        3, 2, [T], _writer(prefix),
        cfg=Config(server_impl="native"), timeout=60.0,
    )
    # forge a shard for a rank the smaller world below does not have
    import shutil as _sh

    _sh.copy(f"{prefix}.3.ckpt", f"{prefix}.9.ckpt")
    with pytest.raises(RuntimeError):
        spawn_world(
            3, 2, [T], _consumer,
            cfg=Config(server_impl="native", restore_path=prefix,
                       exhaust_check_interval=0.15),
            timeout=30.0,
        )
