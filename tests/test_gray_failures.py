"""Gray-failure survival: lease expiry with fencing, poison-unit
quarantine, and overload backpressure (ISSUE 5 tentpole).

Four layers of coverage:

* **Fault-shim mechanics** — the `stall_at_frame`/`stall_at` gray-failure
  injection (endpoint freezes, process stays alive, buffered frames
  flush on resume) and the `poison_types` reserve-response kill, plus
  `resolve_spec`'s server-index stall keys.
* **Expiry race lattice** — Server instances driven handler-by-handler:
  expiry fences the owner and re-enqueues under a fresh attempt, a
  heartbeat (or explicit `extend_lease` renewal) crossing the expiry
  window prevents it, late settles from the fenced owner answer
  ADLB_FENCED (including after a failover, via the replicated fence
  set), retry budgets quarantine poison units with exactly-once
  counting, and the hard-watermark backpressure answers ADLB_BACKOFF to
  untargeted puts only.
* **Replication** — fences, attempt counts, and the dead-letter store
  ride the PR 4 replication stream (log <-> mirror roundtrip), so
  failover neither un-fences a stalled owner nor resets a poison unit's
  budget.
* **End-to-end** — in-proc worlds (both balancer modes) where a worker
  stalls mid-lease and the world completes with exact unit conservation;
  a quarantined unit settling the exhaustion vote; and the slow-marked
  8-rank TCP acceptance world: one SIGSTOP'd worker, one poison unit,
  and a put storm under `lease_timeout_s > 0` — every unit accounted
  exactly once as completed, re-executed, or quarantined, and the
  fenced owner's post-SIGCONT fetch rejected without double-execution.
"""

import os
import struct
import time

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.runtime.faults import (
    FaultPlan,
    FaultyEndpoint,
    resolve_spec,
    sigstop_self,
)
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.replica import ReplicaMirror, ReplicationLog
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.runtime.queues import WorkUnit
from adlb_tpu.types import (
    ADLB_BACKOFF,
    ADLB_FENCED,
    ADLB_RETRY,
    ADLB_SUCCESS,
    InfoKey,
)

T = 1
T_POISON = 2


# ---------------------------------------------------- fault-shim mechanics


def test_stall_buffers_outbound_and_flushes_in_order():
    fabric = InProcFabric(2)
    plan = FaultPlan({"stall_at_frame": {0: 2}, "stall_for_s": 0.6}, 0)
    fep = FaultyEndpoint(fabric.endpoints[0], plan)
    fep.send(1, msg(Tag.FA_PUT, 0, payload=b"a"))
    for p in (b"b", b"c"):  # frames 2, 3: stalled, buffered
        fep.send(1, msg(Tag.FA_PUT, 0, payload=p))
    got = [fabric.endpoints[1].recv(timeout=0.1) for _ in range(2)]
    assert [m.payload for m in got if m is not None] == [b"a"]
    # recv goes silent inside the window (inbound waits in the transport)
    fabric.endpoints[1].send(0, msg(Tag.TA_PUT_RESP, 1, rc=0))
    assert fep.recv(timeout=0.01) is None
    time.sleep(0.6)  # window passes; next op flushes the buffer in order
    m = fep.recv(timeout=1.0)
    assert m is not None and m.tag is Tag.TA_PUT_RESP
    got = [fabric.endpoints[1].recv(timeout=1.0) for _ in range(2)]
    assert [m.payload for m in got] == [b"b", b"c"]
    acts = [a for _, a, _, _ in plan.event_log()]
    assert "stall" in acts and "resume" in acts


def test_stall_now_rearms_for_repeated_gray_failures():
    fabric = InProcFabric(2)
    plan = FaultPlan({"seed": 1, "stall_for_s": 0.05}, 0)
    FaultyEndpoint(fabric.endpoints[0], plan)
    for _ in range(2):
        plan.stall_now()
        assert plan.stalled()
        time.sleep(0.08)
        assert not plan.stalled()
    assert [a for _, a, _, _ in plan.event_log()].count("stall") == 2


def test_poison_types_kills_on_marked_reserve_resp(monkeypatch):
    fabric = InProcFabric(2)
    plan = FaultPlan({"poison_types": [T_POISON]}, 1)
    fep = FaultyEndpoint(fabric.endpoints[1], plan)
    killed = []
    monkeypatch.setattr(
        FaultyEndpoint, "_kill_now", lambda self: killed.append(True)
    )
    # an unmarked type passes through unharmed
    fabric.endpoints[0].send(
        1, msg(Tag.TA_RESERVE_RESP, 0, rc=ADLB_SUCCESS, work_type=T)
    )
    assert fep.recv(timeout=1.0) is not None and not killed
    # the marked type kills the worker on the spot (lease left behind)
    fabric.endpoints[0].send(
        1, msg(Tag.TA_RESERVE_RESP, 0, rc=ADLB_SUCCESS, work_type=T_POISON)
    )
    fep.recv(timeout=1.0)
    assert killed
    assert any(a == "poison" for _, a, _, _ in plan.event_log())


def test_resolve_spec_translates_server_stall_keys():
    world = WorldSpec(nranks=6, nservers=2, types=(T,))
    spec = {"stall_server_at_frame": {1: 40}, "stall_server_at": {0: 2.5}}
    out = resolve_spec(spec, world)
    servers = sorted(world.server_ranks)
    assert out["stall_at_frame"] == {servers[1]: 40}
    assert out["stall_at"] == {servers[0]: 2.5}
    assert "stall_server_at_frame" not in out


# -------------------------------------------------- expiry race lattice


def _mini_server(nranks=4, nservers=2, **cfg_kw):
    """A Server on an in-proc fabric, driven handler-by-handler (its
    reactor loop never runs). world: apps 0..1, servers 2..3."""
    cfg_kw.setdefault("on_worker_failure", "reclaim")
    cfg_kw.setdefault("lease_timeout_s", 0.5)
    world = WorldSpec(nranks=nranks, nservers=nservers, types=(T, T_POISON))
    fabric = InProcFabric(nranks)
    return Server(world, Config(**cfg_kw), fabric.endpoint(2)), fabric


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


def _put(srv, src=0, payload=b"unit", work_type=T, target=-1,
         common_len=0, common_server=-1, common_seqno=-1):
    srv._handle(msg(Tag.FA_PUT, src, payload=payload, work_type=work_type,
                    prio=0, target_rank=target, answer_rank=-1,
                    common_len=common_len, common_server=common_server,
                    common_seqno=common_seqno))


def _reserve(srv, src, rqseqno=1, types=(T,)):
    srv._handle(msg(Tag.FA_RESERVE, src, req_types=list(types), hang=True,
                    rqseqno=rqseqno))


def test_expiry_fences_and_reenqueues_with_attempt_bump():
    srv, fabric = _mini_server()
    _put(srv)
    _reserve(srv, 0)
    [unit] = list(srv.wq.units())
    assert unit.pinned and len(srv.leases) == 1
    _drain(fabric, 0)
    # the owner goes silent past the timeout: expiry, not rank death
    srv._scan_leases(time.monotonic() + 0.75)
    assert len(srv.leases) == 0
    assert (unit.seqno, 0) in srv._fences
    assert not unit.pinned and unit.attempts == 1
    assert srv.metrics.value("leases_expired") == 1
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("lease_expired") for t in texts)
    # the re-enqueued unit is matchable right now
    assert srv.wq.find_match(1, frozenset([T])) is not None
    # ... and the fenced owner's late fetch is rejected: no double-settle
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=unit.seqno))
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_FENCED
    # the survivor reserves and settles the unit exactly once
    _reserve(srv, 1)
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=unit.seqno))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_SUCCESS and resp.payload == b"unit"
    assert srv.wq.count == 0


def test_liveness_piggyback_and_heartbeat_cross_expiry():
    srv, fabric = _mini_server()
    _put(srv)
    _reserve(srv, 0)
    _drain(fabric, 0)
    # any frame from the owner is liveness evidence: a scan inside the
    # timeout (aged from last-heard, which the reserve stamped) is a
    # no-op even though the GRANT is older than you'd think
    srv._scan_leases(time.monotonic() + 0.4)
    assert len(srv.leases) == 1
    # an idle-but-computing owner heartbeats: still no expiry at +0.9
    # measured from the heartbeat
    time.sleep(0.05)
    srv._handle(msg(Tag.FA_HEARTBEAT, 0))
    assert srv.metrics.value("heartbeats") == 1
    srv._scan_leases(srv._last_heard[0] + 0.4)
    assert len(srv.leases) == 1, "heartbeat did not carry liveness"


def test_extend_lease_renews_one_lease_not_the_rank():
    srv, fabric = _mini_server()
    _put(srv, payload=b"short")
    _put(srv, payload=b"long")
    _reserve(srv, 0, rqseqno=1)
    _reserve(srv, 0, rqseqno=2)
    _drain(fabric, 0)
    short, long_ = sorted(srv.leases.leases(), key=lambda ls: ls.seqno)
    # ctx.extend_lease(handle) -> FA_HEARTBEAT with the unit's seqno
    srv._on_heartbeat(msg(Tag.FA_HEARTBEAT, 0, seqno=long_.seqno))
    assert srv.leases.get(long_.seqno).renewed_at > 0
    # age the rank 1.5x the timeout (silent, but under the 2x hang bar):
    # the un-renewed lease expires, the renewed one survives
    for ls in (short, long_):
        ls.granted_at -= 0.75
    srv._last_heard[0] -= 0.75
    srv._scan_leases(time.monotonic())
    assert srv.leases.get(short.seqno) is None
    assert srv.leases.get(long_.seqno) is not None
    assert (short.seqno, 0) in srv._fences
    # a renewal for a lease already gone is silently stale
    srv._on_heartbeat(msg(Tag.FA_HEARTBEAT, 0, seqno=short.seqno))
    assert srv.leases.get(short.seqno) is None


@pytest.mark.parametrize("policy", ["reclaim", "abort"])
def test_hang_detection_after_2x_silence(policy):
    srv, fabric = _mini_server(on_worker_failure=policy)
    _put(srv)
    _reserve(srv, 0)
    _drain(fabric, 0)
    srv._last_heard[0] -= 1.2  # 2.4x the 0.5 s timeout of total silence
    for ls in srv.leases.leases():
        ls.granted_at -= 1.2
    srv._scan_leases(time.monotonic())
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("rank_hung rank=0") for t in texts)
    if policy == "reclaim":
        assert 0 in srv._dead_ranks and not srv._aborted
        # termination accounting released: nothing leased, rank excluded
        assert not srv.leases.owned_by(0)
    else:
        assert srv._aborted


def test_native_clients_exempt_from_expiry_and_hang():
    """A native (C) client cannot heartbeat: its silence while
    compute-bound must not expire its lease or declare it hung."""
    srv, fabric = _mini_server()
    _put(srv)
    _reserve(srv, 0)
    _drain(fabric, 0)
    srv.ep.binary_peers = {0}
    [ls] = srv.leases.leases()
    ls.granted_at -= 5.0
    srv._last_heard[0] -= 5.0  # 10x the timeout of total silence
    srv._scan_leases(time.monotonic())
    assert len(srv.leases) == 1, "binary peer's lease expired"
    assert 0 not in srv._dead_ranks and not srv._aborted
    texts = [t for _, t in srv.flight.entries()]
    assert not any(t.startswith(("lease_expired", "rank_hung"))
                   for t in texts)


def test_expiry_credits_common_prefix_against_double_get():
    """The silent owner may already have fetched the batch prefix; the
    re-consumption fetches it again. The expiry-time credit absorbs
    that second get so the prefix cannot GC out from under surviving
    members (bounded leak, not a crash)."""
    srv, fabric = _mini_server()
    srv._handle(msg(Tag.FA_PUT_COMMON, 0, payload=b"PREFIX"))
    common_seqno = _drain(fabric, 0)[-1].common_seqno
    for p in (b"u0", b"u1"):
        _put(srv, payload=p, common_len=6, common_server=srv.rank,
             common_seqno=common_seqno)
    srv._handle(msg(Tag.FA_BATCH_DONE, 0, common_seqno=common_seqno,
                    refcnt=2))
    _reserve(srv, 0)
    _drain(fabric, 0)
    [lease] = srv.leases.leases()
    # the owner fetches the prefix, then stalls before the suffix
    srv._handle(msg(Tag.FA_GET_COMMON, 0, common_seqno=common_seqno,
                    get_id=1))
    srv._scan_leases(time.monotonic() + 0.75)
    assert len(srv.leases) == 0
    # survivor consumes BOTH members, fetching the prefix once each:
    # without the credit the second get would overrun refcnt
    for rq in (1, 2):
        _reserve(srv, 1, rqseqno=rq)
        resp = [m for m in _drain(fabric, 1)
                if m.tag is Tag.TA_RESERVE_RESP][-1]
        assert resp.rc == ADLB_SUCCESS
        srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=common_seqno,
                        get_id=rq))
        srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=resp.handle[0]))
    assert srv.wq.count == 0
    assert len(srv.cq) == 0, "credited prefix never GC'd"


def test_expiry_quarantine_record_reassembles_and_books_balance():
    """A fused member that expires its way into quarantine: the
    dead-letter record carries prefix+suffix (not the bare suffix),
    the quarantining expiry adds no credit (a re-consumption will
    never come) and no forfeit (the silent owner's fetches are already
    in the books), and the prefix still GCs once the surviving member
    fetches."""
    srv, fabric = _mini_server(max_unit_retries=1)
    srv._handle(msg(Tag.FA_PUT_COMMON, 0, payload=b"PREFIX-"))
    common_seqno = _drain(fabric, 0)[-1].common_seqno
    _put(srv, payload=b"bad", target=0, common_len=7,
         common_server=srv.rank, common_seqno=common_seqno)
    _put(srv, payload=b"good", target=1, common_len=7,
         common_server=srv.rank, common_seqno=common_seqno)
    srv._handle(msg(Tag.FA_BATCH_DONE, 0, common_seqno=common_seqno,
                    refcnt=2))
    # two consumption epochs by rank 0: each fetches the prefix, then
    # stalls past the timeout; the second expiry exhausts the budget
    for epoch in (1, 2):
        _reserve(srv, 0, rqseqno=epoch)
        resp = [m for m in _drain(fabric, 0)
                if m.tag is Tag.TA_RESERVE_RESP][-1]
        assert resp.rc == ADLB_SUCCESS
        srv._handle(msg(Tag.FA_GET_COMMON, 0, common_seqno=common_seqno,
                        get_id=epoch))
        for ls in srv.leases.leases():
            ls.granted_at -= 0.75
        srv._last_heard[0] -= 0.75
        srv._scan_leases(time.monotonic())
    assert srv.stats[InfoKey.QUARANTINED] == 1
    [rec] = srv.quarantine
    assert rec["payload"] == b"PREFIX-bad" and not rec["suffix_only"]
    # the survivor's fetch closes the books exactly: refcnt (2 member
    # shares + 1 first-expiry credit) == ngets (three fetches)
    _reserve(srv, 1, rqseqno=1)
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_RESERVE_RESP][-1]
    assert resp.rc == ADLB_SUCCESS
    srv._handle(msg(Tag.FA_GET_COMMON, 1, common_seqno=common_seqno,
                    get_id=1))
    srv._handle(msg(Tag.FA_GET_RESERVED, 1, seqno=resp.handle[0]))
    assert srv.wq.count == 0
    assert len(srv.cq) == 0, "prefix failed to GC after quarantine"


def test_retry_budget_quarantines_exactly_once_and_settles():
    srv, fabric = _mini_server(max_unit_retries=2)
    _put(srv, payload=b"poison")
    for attempt in range(3):
        _reserve(srv, attempt % 2, rqseqno=attempt)
        _drain(fabric, attempt % 2)
        srv._scan_leases(time.monotonic() + 0.75)
    # third expiry exceeded the budget: out of the wq, settled for the
    # exhaustion vote, counted exactly once, payload retained
    assert srv.wq.count == 0 and srv.wq.num_unpinned() == 0
    assert len(srv.leases) == 0
    assert len(srv.quarantine) == 1
    assert srv.quarantine[0]["payload"] == b"poison"
    assert srv.quarantine[0]["attempts"] == 3
    assert srv.stats[InfoKey.QUARANTINED] == 1
    assert srv.metrics.value("quarantined") == 1
    texts = [t for _, t in srv.flight.entries()]
    assert any(t.startswith("unit_quarantined") for t in texts)
    # dead-letter retrieval round trip (parallel-list wire form)
    srv._handle(msg(Tag.FA_GET_QUARANTINED, 1))
    resp = [m for m in _drain(fabric, 1)
            if m.tag is Tag.TA_QUARANTINED_RESP][-1]
    assert resp.data["payloads"] == [b"poison"]
    assert resp.data["attempts_list"] == [3]


def test_backoff_above_hard_watermark_untargeted_only():
    srv, fabric = _mini_server(max_malloc_per_server=100,
                               mem_soft_frac=0.85, mem_hard_frac=0.9,
                               lease_timeout_s=0.0)
    for st in srv.peers.values():  # gossip: every peer full
        st.nbytes = 100
    _put(srv, payload=b"x" * 85)
    assert [m.rc for m in _drain(fabric, 0)
            if m.tag is Tag.TA_PUT_RESP] == [ADLB_SUCCESS]
    # above hard, no peer has room: untargeted put answers ADLB_BACKOFF
    # with a retry-after hint (not a reject — hopping would not help)
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"y" * 20, work_type=T, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=7))
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP][-1]
    assert resp.rc == ADLB_BACKOFF and resp.data["retry_after_ms"] > 0
    assert resp.data["put_id"] == 7
    assert srv.metrics.value("put_backoff") == 1
    # a targeted put is completion traffic bound to THIS server:
    # backpressuring it would starve the consumers that drain the
    # pressure — it falls through to the reference admission path
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"z" * 20, work_type=T, prio=0,
                    target_rank=1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=8))
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP][-1]
    assert resp.rc != ADLB_BACKOFF
    # ... and a believed-roomy peer turns backoff into the normal
    # reject-with-hint hop
    [peer] = [s for s in srv.peers if s != srv.rank]
    srv.peers[peer].nbytes = 0
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"w" * 20, work_type=T, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=9))
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP][-1]
    assert resp.rc != ADLB_BACKOFF


# ------------------------------------------------------------ replication


def test_fences_attempts_quarantine_ride_replication_stream():
    log = ReplicationLog(buddy=4)
    unit = WorkUnit(seqno=5, work_type=T, prio=0, target_rank=-1,
                    answer_rank=3, payload=b"pp", attempts=2)
    log.log_put(unit, 0, None)
    log.log_fence(5, 1)
    log.log_attempts(5, 3)
    other = WorkUnit(seqno=6, work_type=T, prio=0, target_rank=-1,
                     answer_rank=-1, payload=b"qq", attempts=4)
    log.log_put(other, 0, None)
    log.log_quarantine(6)
    mirror = ReplicaMirror(primary=3)
    mirror.apply(log.take())
    assert mirror.units[5]["attempts"] == 3  # put carried 2, update to 3
    assert (5, 1, -1) in mirror.fences  # origin -1: the primary's own
    # a fence the primary itself adopted keeps its origin numbering
    log.log_fence(7, 2, origin=11)
    mirror.apply(log.take())
    assert (7, 2, 11) in mirror.fences
    assert 6 not in mirror.units  # moved, not duplicated
    assert mirror.quarantined[6]["attempts"] == 4
    assert mirror.quarantined[6]["payload"] == b"qq"


def test_adopted_fence_rejects_rerouted_late_fetch():
    """After a failover the fenced owner's fetch arrives at the buddy
    stamped fo_from: it must stay rejected (ADLB_FENCED), not be
    miscounted as a replication-lag loss."""
    srv, fabric = _mini_server()
    dead = 9
    srv._adopted_fences.add((dead, 55, 0))
    before = srv.metrics.value("failover_lost")
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=55, fo_from=dead))
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_FENCED
    assert srv.metrics.value("failover_lost") == before
    # an unfenced unknown seqno still takes the counted-loss path
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=56, fo_from=dead))
    resp = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_GET_RESERVED_RESP][-1]
    assert resp.rc == ADLB_RETRY
    assert srv.metrics.value("failover_lost") == before + 1


def test_adopt_quarantined_recounts_at_survivor():
    srv, fabric = _mini_server(max_unit_retries=1)
    dead = srv.rank + 1
    srv._adopt_quarantined(
        {"work_type": T, "prio": 0, "target_rank": -1, "answer_rank": -1,
         "payload": b"dead-letter", "attempts": 2},
        old_seqno=40, dead=dead,
    )
    assert srv.stats[InfoKey.QUARANTINED] == 1
    [rec] = srv.quarantine
    assert rec["payload"] == b"dead-letter" and rec["server_rank"] == srv.rank
    assert not rec["suffix_only"]
    # a fused member whose prefix this buddy adopted: the record
    # translates the common handle and reattaches the prefix
    new_c = srv.cq.adopt(b"PREFIX-", refcnt=5, ngets=0, credits=0)
    srv._adopted_commons[(dead, 7)] = new_c
    srv._adopt_quarantined(
        {"work_type": T, "prio": 0, "target_rank": -1, "answer_rank": -1,
         "payload": b"suffix", "attempts": 2, "common_seqno": 7,
         "common_server_rank": dead, "common_len": 7},
        old_seqno=41, dead=dead,
    )
    rec = srv.quarantine[-1]
    assert rec["payload"] == b"PREFIX-suffix" and not rec["suffix_only"]
    # ... and one whose prefix was lost to replication lag stays an
    # honestly-flagged suffix
    srv._adopt_quarantined(
        {"work_type": T, "prio": 0, "target_rank": -1, "answer_rank": -1,
         "payload": b"tail", "attempts": 2, "common_seqno": 9,
         "common_server_rank": dead, "common_len": 4},
        old_seqno=42, dead=dead,
    )
    rec = srv.quarantine[-1]
    assert rec["payload"] == b"tail" and rec["suffix_only"]
    assert srv.stats[InfoKey.QUARANTINED] == 3


# ---------------------------------------------------- end-to-end, in-proc


def _stall_coverage(n_units, stall_s):
    """Coverage workload where rank 1 freezes (endpoint stall — the
    in-proc analogue of SIGSTOP) while holding an unfetched
    reservation, then resumes and retries its fenced fetch."""
    def app(ctx):
        if ctx.rank == 0:
            for i in range(n_units):
                assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        got, retries = [], 0
        stalled = False
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return got, retries
            if ctx.rank == 1 and not stalled and len(got) >= 1:
                stalled = True
                ctx._c.ep.plan.stall_now()
                time.sleep(stall_s)  # frozen: heartbeats buffer, recv silent
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                retries += 1  # fenced (or resurrected): re-reserve
                continue
            assert rc == ADLB_SUCCESS, rc
            got.append(struct.unpack("<q", buf)[0])
            time.sleep(0.002)

    return app


@pytest.mark.parametrize("mode", ["steal", "tpu"])
def test_inproc_stalled_worker_fenced_and_conserved(mode):
    """A worker freezes mid-lease past the timeout: its unit is fenced +
    re-enqueued and executed elsewhere, its own late fetch answers a
    retriable code, and every unit is delivered exactly once."""
    n_units = 16
    res = run_world(
        3, 2, [T], _stall_coverage(n_units, stall_s=0.9),
        cfg=Config(
            balancer=mode,
            on_worker_failure="reclaim",
            lease_timeout_s=0.6,
            exhaust_check_interval=0.2,
            fault_spec={"seed": 3, "stall_for_s": 0.9},
        ),
        timeout=90.0,
    )
    done = [x for got, _ in res.app_results.values() for x in got]
    assert sorted(done) == list(range(n_units)), done  # exactly once
    assert res.app_results[1][1] >= 1, "stalled rank's fetch was not fenced"
    assert res.quarantined == 0


def test_inproc_quarantine_settles_exhaustion_and_is_retrievable():
    """A unit that fails every delivery: the retry budget moves it to
    the dead-letter store, the exhaustion vote settles around it (the
    world terminates instead of hanging on the poison unit), and
    ctx.get_quarantined() returns it."""
    def app(ctx):
        assert ctx.put(b"poison", T) == ADLB_SUCCESS
        tries = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                rcq, recs = ctx.get_quarantined()
                assert rcq == ADLB_SUCCESS
                return tries, recs
            ctx._c.ep.plan.stall_now()
            time.sleep(0.85)
            rc, _ = ctx.get_reserved(r.handle)
            assert rc == ADLB_RETRY, rc
            tries += 1

    t0 = time.monotonic()
    res = run_world(
        1, 2, [T], app,
        cfg=Config(
            on_worker_failure="reclaim",
            lease_timeout_s=0.55,
            max_unit_retries=1,
            exhaust_check_interval=0.2,
            fault_spec={"seed": 4, "stall_for_s": 0.7},
        ),
        timeout=60.0,
    )
    assert time.monotonic() - t0 < 45.0, "exhaustion hung on the poison unit"
    tries, recs = res.app_results[0]
    assert tries == 2  # budget 1: two failed attempts, then quarantine
    assert res.quarantined == 1
    assert [r["payload"] for r in recs] == [b"poison"]
    assert recs[0]["attempts"] == 2


def test_lease_disarmed_world_is_frame_identical():
    """lease_timeout_s=0 (the default): no heartbeat thread, no
    heartbeat frames, no fence/backoff rcs — byte-identical behavior to
    the pre-gray-failure protocol."""
    def app(ctx):
        if ctx.rank == 0:
            for i in range(6):
                ctx.put(struct.pack("<q", i), T)
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                m = ctx._c.metrics
                return got, (
                    m.value("tx_msgs", tag="FA_HEARTBEAT"),
                    m.value("rx_msgs", tag="TA_QUARANTINED_RESP"),
                    m.value("fenced_fetches"),
                    m.value("put_backoffs"),
                )
            got.append(struct.unpack("<q", w.payload)[0])

    res = run_world(2, 2, [T], app,
                    cfg=Config(exhaust_check_interval=0.2), timeout=60.0)
    for got, counters in res.app_results.values():
        assert counters == (0.0, 0.0, 0.0, 0.0), counters
    assert res.quarantined == 0
    done = [x for got, _ in res.app_results.values() for x in got]
    assert sorted(done) == list(range(6))


# ------------------------------------------- end-to-end, TCP (acceptance)


N_STORM = 60


def _acceptance_app(ctx):
    """6 apps + 2 servers: rank 0 storms 60 puts against a tiny memory
    cap (backpressure), rank 1 SIGSTOPs itself holding an unfetched
    reservation (lease expiry + fencing), ranks 2-5 are exposed to the
    poison unit (fault-spec poison_types kills them at reserve-response;
    the retry budget quarantines it after 3 kills). Workers answer every
    unit at cycle boundaries, so a killed worker loses nothing it
    already answered and conservation stays exact."""
    T_ANS = 3
    if ctx.rank == 0:
        # 64 B units against a 512 B/server cap: the storm must cross
        # the hard watermark long before it finishes
        for i in range(N_STORM):
            rc = ctx.put(struct.pack("<q", i) + b"\0" * 56, T,
                         answer_rank=0)
            assert rc == ADLB_SUCCESS, rc
        rc = ctx.put(b"poison", T_POISON)
        assert rc == ADLB_SUCCESS, rc
        seen = set()
        answers = 0
        while len(seen) < N_STORM:
            rc, r = ctx.reserve([T_ANS])
            assert rc == ADLB_SUCCESS, rc
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                continue
            answers += 1
            seen.add(struct.unpack("<q", buf)[0])
        ctx.set_problem_done()
        return {
            "distinct": len(seen),
            "answers": answers,
            "put_backoffs": ctx._c.metrics.value("put_backoffs"),
        }
    my_types = [T] if ctx.rank == 1 else [T, T_POISON]
    n, retries, stopped = 0, 0, False
    while True:
        rc, r = ctx.reserve(my_types)
        if rc != ADLB_SUCCESS:
            return {"n": n, "retries": retries, "stopped": stopped}
        if ctx.rank == 1 and n >= 1 and not stopped:
            stopped = True
            sigstop_self(2.0)  # the REAL gray failure; resumes via SIGCONT
        rc, buf = ctx.get_reserved(r.handle)
        if rc == ADLB_RETRY:
            retries += 1  # post-SIGCONT fenced fetch: re-reserve
            continue
        assert rc == ADLB_SUCCESS, rc
        ctx.put(buf[:8], 3, target_rank=0)
        n += 1
        time.sleep(0.01)  # compute: the storm must outrun the drain


@pytest.mark.slow
def test_tcp_sigstop_poison_storm_conservation():
    """The acceptance world: 8-rank TCP, one SIGSTOP'd worker, one
    poison unit, a put storm over the hard watermark — completes under
    lease_timeout_s>0 with every unit accounted exactly once as
    completed, re-executed, or quarantined; the fenced owner survives
    SIGCONT without double-execution."""
    res = spawn_world(
        6, 2, [T, T_POISON, 3], _acceptance_app,
        cfg=Config(
            on_worker_failure="reclaim",
            lease_timeout_s=1.2,
            max_unit_retries=2,
            max_malloc_per_server=512,
            mem_soft_frac=0.85,
            mem_hard_frac=0.9,
            put_max_retries=200,
            exhaust_check_interval=0.2,
            fault_spec={"seed": 11, "poison_types": [T_POISON]},
        ),
        timeout=240.0,
    )
    assert not res.aborted
    r0 = res.app_results[0]
    # conservation: all 60 storm units answered (each exactly once --
    # distinct==answers would even forbid re-execution, but expiry makes
    # delivery at-least-once by design, so only coverage is asserted),
    # and the poison unit accounted exactly once, in the quarantine
    assert r0["distinct"] == N_STORM
    assert res.quarantined == 1, res.quarantined
    # the put storm hit the hard watermark and was shed, not aborted
    assert r0["put_backoffs"] >= 1, r0
    # the SIGSTOP'd worker survived: fenced on resume, then kept working
    assert 1 in res.app_results, "stalled worker did not survive"
    r1 = res.app_results[1]
    assert r1["stopped"] and r1["retries"] >= 1, r1
    # the poison unit serially killed workers until the budget tripped:
    # attempts 1..3 with max_unit_retries=2 means up to 3 casualties,
    # at least 1 (it never executed anywhere)
    assert 1 <= len(res.casualties) <= 3, res.casualties
    assert 1 not in res.casualties


@pytest.mark.slow
def test_tcp_sigstop_abort_policy_detects_hang():
    """Under on_worker_failure="abort" with expiry armed, a hung worker
    is DETECTED (2x timeout of silence) and the world aborts instead of
    hanging forever — bounded detection, reference-faithful outcome."""
    def app(ctx):
        if ctx.rank == 0:
            for i in range(8):
                ctx.put(struct.pack("<q", i), T)
        n = 0
        while True:
            rc, r = ctx.reserve([T])
            if rc != ADLB_SUCCESS:
                return n
            if ctx.rank == 1 and n >= 1:
                sigstop_self(6.0)  # resumes only after the abort fanout
            rc, buf = ctx.get_reserved(r.handle)
            if rc == ADLB_RETRY:
                continue
            n += 1
            time.sleep(0.01)

    t0 = time.monotonic()
    try:
        res = spawn_world(
            3, 2, [T], app,
            cfg=Config(on_worker_failure="abort", lease_timeout_s=0.8,
                       exhaust_check_interval=0.2),
            timeout=90.0,
        )
        # the server-initiated abort fans out TA_ABORT and the harness
        # classifies the world aborted (a straggler's nonzero exit may
        # instead surface as RuntimeError — both are clean detection)
        assert res.aborted, "hung worker was not detected"
    except RuntimeError:
        pass
    assert time.monotonic() - t0 < 60.0, "hang detection did not bound MTTR"
