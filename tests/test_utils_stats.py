"""Running-stats utility (reference examples/stats.c port)."""

import math
import random

import numpy as np

from adlb_tpu.utils import RunningStats


def test_gate_and_reset():
    s = RunningStats("t1")
    assert not s.enter(5.0)  # starts off, like the reference statsinit
    s.on()
    for v in (1.0, 2.0, 3.0):
        assert s.enter(v)
    s.off()
    assert not s.enter(1_000_000.0)  # ignored while off
    assert s.numvals == 3
    assert s.sum == 6.0
    assert s.min == 1.0 and s.max == 3.0
    assert s.mean == 2.0
    assert math.isclose(s.stddev, 1.0)
    s.reset()
    assert s.numvals == 0 and s.sum == 0.0 and s.mean == 0.0
    assert not s.active


def test_constant_sequence_has_zero_stddev():
    s = RunningStats()
    s.on()
    for _ in range(1000):
        s.enter(500.0)
    assert s.numvals == 1000
    assert s.mean == 500.0
    assert s.stddev == 0.0


def test_matches_numpy_on_random_stream():
    rng = random.Random(7)
    vals = [rng.uniform(-50, 50) for _ in range(5000)]
    s = RunningStats()
    s.on()
    for v in vals:
        s.enter(v)
    a = np.array(vals)
    assert math.isclose(s.mean, float(a.mean()), rel_tol=1e-12)
    assert math.isclose(s.stddev, float(a.std(ddof=1)), rel_tol=1e-10)
    assert s.min == float(a.min()) and s.max == float(a.max())
    assert "n=5000" in s.dump()
