"""Durable service mode: per-server WAL, job namespaces, /jobs control
plane (Config(wal_dir) / ctx.attach, adlb_tpu/runtime/wal.py + jobs.py).

Four layers of coverage:

* **WAL mechanics** — log<->mirror roundtrip through the on-disk
  crc-framed records; torn-tail recovery (truncation mid-record and at
  a record boundary) stops at the last durable op; group-commit fsync
  holds put acks until the commit that covers them; compaction writes
  an ACK2 shard + manifest-headed fresh segment that recovers
  identically.
* **Cold restart** — an aborted in-proc world's pool replays from the
  WAL into a fresh world of the same shape with exact unit
  conservation, including across a mid-run server connectivity death
  (the put-ack write-ahead invariant: every ACKED put is recovered).
* **Job namespaces** — two concurrent jobs on one fleet complete with
  independent termination; per-tenant quotas backpressure one job while
  the other keeps accepting; kill flushes parked requesters; matching
  never crosses namespaces.
* **Control plane** — the FA_JOB_CTL round trip and the ops endpoint's
  /jobs HTTP surface (submit/status/drain), plus /deadletter honoring
  Config(ops_dump_bytes).
"""

import json
import os
import struct
import threading
import time
import urllib.request

import pytest

from adlb_tpu.api import run_world
from adlb_tpu.obs.ops_server import OpsServer
from adlb_tpu.runtime import checkpoint, wal as walmod
from adlb_tpu.runtime.jobs import DRAINING, DONE, KILLED, RUNNING
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.queues import PartitionedWorkQueue, WorkQueue, WorkUnit
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.transport_tcp import probe_free_ports, spawn_world
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import (
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_NO_MORE_WORK,
    ADLB_SUCCESS,
)

T = 1
T2 = 2


def _unit(seqno, payload=b"x", job=0, **kw):
    kw.setdefault("work_type", T)
    kw.setdefault("prio", 0)
    kw.setdefault("target_rank", -1)
    kw.setdefault("answer_rank", -1)
    return WorkUnit(seqno=seqno, payload=payload, job=job, **kw)


# ------------------------------------------------------------ WAL mechanics


def _make_wal(tmp_path, rank=2, **kw):
    world = WorldSpec(nranks=4, nservers=2, types=(T, T2))
    kw.setdefault("fsync_ms", 0.0)
    return walmod.WriteAheadLog(str(tmp_path), rank, world, **kw)


def test_wal_log_mirror_roundtrip(tmp_path):
    w = _make_wal(tmp_path)
    w.log_put(_unit(10, b"alpha"), src=0, put_id=42)
    w.log_put(_unit(11, b"beta", job=3, attempts=1), src=0, put_id=43)
    w.log_pin(10, 1)
    w.log_consume(10)
    w.log_common_put(7, b"PFX")
    w.log_common_refcnt(7, 2)
    w.log_job(3, 0, 4096, "tenant-a")
    w.tick(time.monotonic(), force=True)
    w.close()

    w2 = _make_wal(tmp_path)
    mirror = w2.recover()
    assert mirror is not None and not w2.recovered_torn
    assert 10 not in mirror.units and 10 in mirror.tombstones
    assert mirror.units[11]["payload"] == b"beta"
    assert mirror.units[11]["job"] == 3
    assert mirror.units[11]["attempts"] == 1
    assert mirror.commons[7][0] == b"PFX" and mirror.commons[7][1] == 2
    assert mirror.jobs_meta[3] == (0, 4096, "tenant-a")
    # the per-sender put-dedup window rides the log into the MIRROR
    # (the failover promote path adopts it); WAL cold restart leaves it
    # behind — fresh clients restart their put ids from 1, and a
    # restored window would swallow their first puts as duplicates
    assert mirror.seen_puts[0] == [42, 43]


def test_wal_torn_tail_mid_record(tmp_path):
    w = _make_wal(tmp_path)
    for i in range(8):
        w.log_put(_unit(100 + i, b"p%d" % i), src=0, put_id=i)
    w.tick(time.monotonic(), force=True)
    w.close()
    path = walmod.log_path(str(tmp_path), 2)
    size = os.path.getsize(path)
    os.truncate(path, size - 11)  # cut INSIDE the last record's body

    w2 = _make_wal(tmp_path)
    mirror = w2.recover()
    assert w2.recovered_torn
    # replay stopped cleanly at the last durable op: exactly the first
    # 7 puts survive, and the writer resumed at the truncation point
    assert sorted(mirror.units) == [100 + i for i in range(7)]
    recs, torn = walmod.scan_records(path)
    assert len(recs) == 7 and not torn  # the torn tail was truncated away


def test_wal_torn_tail_at_record_boundary(tmp_path):
    w = _make_wal(tmp_path)
    sizes = []
    for i in range(4):
        w.log_put(_unit(200 + i), src=0, put_id=i)
        w.tick(time.monotonic(), force=True)
        sizes.append(w.size)
    w.close()
    path = walmod.log_path(str(tmp_path), 2)
    os.truncate(path, sizes[1])  # exactly after the 2nd record

    w2 = _make_wal(tmp_path)
    mirror = w2.recover()
    # a boundary cut is a clean (shorter) log, not a torn one
    assert not w2.recovered_torn
    assert sorted(mirror.units) == [200, 201]


def test_wal_group_commit_holds_acks(tmp_path):
    w = _make_wal(tmp_path, fsync_ms=10_000.0)
    t0 = time.monotonic()
    w.log_put(_unit(1), src=0, put_id=1)
    w.defer_ack(0, "ack-1")
    assert w.tick(t0) == []          # window open: ack held
    assert not w._buf and w._unsynced == 1  # entry reached the OS file
    assert w.tick(t0 + 1.0) == []    # still inside the window
    assert w.tick(t0 + 11.0) == [(0, "ack-1")]  # commit releases it
    # fsync_ms=0: strict mode releases on every tick
    w0 = _make_wal(tmp_path, rank=3, fsync_ms=0.0)
    w0.log_put(_unit(2), src=0, put_id=2)
    w0.defer_ack(0, "ack-2")
    assert w0.tick(time.monotonic()) == [(0, "ack-2")]
    w.close()
    w0.close()


def _wal_server(tmp_path, rank=2, **cfg_kw):
    world = WorldSpec(nranks=4, nservers=2, types=(T, T2))
    fabric = InProcFabric(4)
    cfg_kw.setdefault("wal_fsync_ms", 0.0)
    cfg = Config(wal_dir=str(tmp_path), **cfg_kw)
    return Server(world, cfg, fabric.endpoint(rank)), fabric


def test_wal_compaction_shard_plus_tail(tmp_path):
    srv, fabric = _wal_server(tmp_path)
    for i in range(6):
        srv._handle(msg(Tag.FA_PUT, 0, payload=b"unit-%d" % i, work_type=T,
                        prio=i, target_rank=-1, answer_rank=-1,
                        common_len=0, common_server=-1, common_seqno=-1,
                        put_id=i))
    srv._flush_wal(force=True)
    srv.wal.compact(srv)
    # compaction wrote an ACK2 shard for the current generation
    shard = checkpoint.shard_path(
        walmod.snap_prefix(str(tmp_path), 2, srv.wal.generation), 2
    )
    assert os.path.exists(shard)
    with open(shard, "rb") as f:
        assert f.read(4) == b"ACK2"
    # ... and tail entries after the snapshot correlate by seqno: fetch
    # the best match (prio 5 -> b"unit-5") so a pin + consume land in
    # the fresh segment AFTER the manifest
    srv._handle(msg(Tag.FA_RESERVE, 0, rqseqno=1, hang=False,
                    req_types=[T]))
    resv = [m for m in _drain(fabric, 0)
            if m.tag is Tag.TA_RESERVE_RESP][-1]
    assert resv.rc == ADLB_SUCCESS
    consumed_seqno = resv.handle[0]
    srv._handle(msg(Tag.FA_GET_RESERVED, 0, seqno=consumed_seqno))
    srv._flush_wal(force=True)
    srv.wal.close()

    w2 = _make_wal(tmp_path)
    mirror = w2.recover()
    # the consume resolved against the SHARD-loaded state via the
    # manifest: 5 remain, the fetched one is tombstoned
    assert len(mirror.units) == 5
    assert consumed_seqno not in mirror.units
    assert consumed_seqno in mirror.tombstones
    payloads = sorted(f["payload"] for f in mirror.units.values())
    assert payloads == sorted(b"unit-%d" % i for i in range(5))


def test_wal_put_ack_is_write_ahead_on_server(tmp_path):
    """The server holds the put ack for the group commit: with a huge
    fsync window, the ack only leaves once the commit runs."""
    srv, fabric = _wal_server(tmp_path, wal_fsync_ms=10_000.0)
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"held", work_type=T, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=9))
    srv._flush_wal()  # window open: nothing released
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP]
    assert resp == [], "put ack escaped before its entry was durable"
    srv._flush_wal(force=True)
    resp = [m for m in _drain(fabric, 0) if m.tag is Tag.TA_PUT_RESP]
    assert len(resp) == 1 and resp[0].rc == ADLB_SUCCESS
    srv.wal.close()


def _drain(fabric, rank):
    out = []
    while True:
        m = fabric.endpoints[rank].recv(timeout=0.0)
        if m is None:
            return out
        out.append(m)


# ------------------------------------------------------------ cold restart


def _abort_after_puts(ctx):
    if ctx.rank == 0:
        for i in range(10):
            rc = ctx.put(struct.pack("<q", i), T)
            assert rc == ADLB_SUCCESS
        ctx.abort(7)
    else:
        time.sleep(30)  # aborted long before this


def _drain_all(ctx):
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return got
        got.append(struct.unpack("<q", w.payload)[0])


def test_wal_cold_restart_replays_conserved_pool(tmp_path):
    """World 1 puts 10 acked units and aborts; a fresh same-shape world
    on the same wal_dir recovers EVERY acked unit — the conservation
    contract across process death."""
    cfg = Config(wal_dir=str(tmp_path), wal_fsync_ms=2.0,
                 exhaust_check_interval=0.2)
    res1 = run_world(2, 2, [T], _abort_after_puts, cfg=cfg, timeout=60.0)
    assert res1.aborted
    res2 = run_world(2, 2, [T], _drain_all, cfg=cfg, timeout=60.0)
    done = sorted(x for g in res2.app_results.values() for x in g)
    assert done == list(range(10)), done


def test_wal_restart_after_server_death_keeps_acked_puts(tmp_path):
    """Put-ack write-ahead under a mid-run server connectivity death
    (the in-proc analogue of kill_server_at_frame, same fault plane):
    every put ACKED before the death is recovered by the restart."""
    acked = []

    def app(ctx):
        if ctx.rank != 0:
            time.sleep(30)
            return
        try:
            for i in range(200):
                rc = ctx.put(struct.pack("<q", i), T)
                if rc == ADLB_SUCCESS:
                    acked.append(i)
        except BaseException:
            pass  # the death lands mid-loop; abort tears the world down

    cfg = Config(
        wal_dir=str(tmp_path), wal_fsync_ms=1.0,
        exhaust_check_interval=0.2, put_max_retries=1,
        fault_spec={"seed": 3, "disconnect_server_at": {0: 60}},
    )
    try:
        res1 = run_world(2, 2, [T], app, cfg=cfg, timeout=60.0)
        assert res1.aborted
    except OSError:
        pass  # the dying server's thread may surface its own socket error
    assert acked, "the fault fired before any put was acked"
    cfg2 = Config(wal_dir=str(tmp_path), wal_fsync_ms=2.0,
                  exhaust_check_interval=0.2)
    res2 = run_world(2, 2, [T], _drain_all, cfg=cfg2, timeout=60.0)
    done = {x for g in res2.app_results.values() for x in g}
    missing = [i for i in acked if i not in done]
    assert not missing, f"acked puts lost across restart: {missing}"


def _killed_fleet_producer(ctx):
    """World 1 of the restart-replay acceptance: rank 0 streams puts,
    appending each ACKED id to the oracle file the instant its ack
    lands; a server is SIGKILLed mid-stream and the world aborts."""
    if ctx.rank != 0:
        time.sleep(15)  # outlive the kill, then fold on the abort
        return None
    path = os.environ["ADLB_TEST_ACKED"]
    with open(path, "a") as f:
        try:
            for i in range(400):
                rc = ctx.put(struct.pack("<q", i), T)
                if rc == ADLB_SUCCESS:
                    f.write(f"{i}\n")
                    f.flush()
        except BaseException:
            return None  # the kill landed mid-put; abort tears us down
    return None


@pytest.mark.slow
def test_restart_replay_tcp_kill_server(tmp_path):
    """CI restart-replay leg: run a TCP world, SIGKILL a server process
    mid-job (kill_server_at_frame), cold-restart the fleet from the WAL,
    and assert unit conservation — every put acked before the kill is
    recovered and drained by the new incarnation."""
    acked_path = tmp_path / "acked.txt"
    wal_dir = tmp_path / "wal"
    os.environ["ADLB_TEST_ACKED"] = str(acked_path)
    cfg = Config(
        wal_dir=str(wal_dir), wal_fsync_ms=1.0,
        exhaust_check_interval=0.2, put_max_retries=1,
        fault_spec={"seed": 9, "kill_server_at_frame": {1: 150}},
    )
    try:
        try:
            res1 = spawn_world(2, 2, [T], _killed_fleet_producer,
                               cfg=cfg, timeout=90.0)
            assert res1.aborted
        except RuntimeError:
            pass  # abort classification may surface as a world error
    finally:
        os.environ.pop("ADLB_TEST_ACKED", None)
    acked = [int(x) for x in acked_path.read_text().split()]
    assert acked, "the kill fired before any put was acked"
    cfg2 = Config(wal_dir=str(wal_dir), wal_fsync_ms=2.0,
                  exhaust_check_interval=0.2)
    res2 = spawn_world(2, 2, [T], _drain_all, cfg=cfg2, timeout=90.0)
    done = {x for g in res2.app_results.values() for x in g}
    missing = [i for i in acked if i not in done]
    assert not missing, (
        f"{len(missing)} acked puts lost across the fleet restart: "
        f"{missing[:10]}"
    )


# ------------------------------------------------------------ job namespaces


def test_partitioned_wq_isolates_jobs():
    wq = PartitionedWorkQueue(WorkQueue)
    wq.add(_unit(1, b"default"))
    wq.add(_unit(2, b"tenant", job=5))
    assert wq.count == 2 and wq.part(5).count == 1
    # matching never crosses namespaces
    assert wq.find_match(0, frozenset([T])).seqno == 1
    assert wq.find_match(0, frozenset([T]), job=5).seqno == 2
    assert wq.find_match(0, frozenset([T]), job=9) is None
    # seqno-addressed ops route through the partition index
    wq.pin(2, 0)
    assert wq.get(2).pinned
    wq.unpin(2)
    assert wq.job_hi_prio() == {(5, T): 0}
    dropped = wq.drop_job(5)
    assert [u.seqno for u in dropped] == [2]
    assert wq.count == 1 and wq.part(5) is None


def _two_jobs_app(ctx):
    """Ranks 0-1 work job A, ranks 2-3 work job B; each pair's producer
    is its rank 0. Jobs complete independently."""
    me_a = ctx.rank < 2
    jid = 1 if me_a else 2
    if ctx.rank == 0:
        rc, ja = ctx.submit_job("job-a")
        assert (rc, ja) == (ADLB_SUCCESS, 1)
        rc, jb = ctx.submit_job("job-b")
        assert (rc, jb) == (ADLB_SUCCESS, 2)
    else:
        time.sleep(0.2)  # let the submits land (ids are deterministic)
    ctx.attach(jid)
    if ctx.rank in (0, 2):
        for i in range(8):
            rc = ctx.put(struct.pack("<q", 100 * jid + i), T)
            assert rc == ADLB_SUCCESS
    got = []
    while True:
        rc, w = ctx.get_work([T])
        if rc != ADLB_SUCCESS:
            return (jid, rc, got)
        got.append(struct.unpack("<q", w.payload)[0])


def test_two_concurrent_jobs_independent_termination():
    res = run_world(4, 2, [T], _two_jobs_app,
                    cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    by_job = {1: [], 2: []}
    for jid, rc, got in res.app_results.values():
        assert rc == ADLB_DONE_BY_EXHAUSTION
        by_job[jid].extend(got)
    assert sorted(by_job[1]) == [100 + i for i in range(8)]
    assert sorted(by_job[2]) == [200 + i for i in range(8)]


def test_multi_job_planned_path_cross_server_zero_rfr():
    """PR 19 multi-job planning, end to end: two weighted jobs' units
    produced home-routed onto one server reach consumers parked on the
    other purely through the snapshot -> solve -> ship path — both jobs
    complete exactly, and no server ever fires the qmstat/RFR fallback
    (planned namespaces are the balancer's, id >= balancer_max_jobs
    keeps the pull)."""
    from adlb_tpu.runtime.membership import ElasticWorld

    cfg = Config(balancer="tpu", balancer_max_jobs=3,
                 job_weights={2: 4.0}, put_routing="home",
                 exhaust_check_interval=0.2)
    ew = ElasticWorld(3, 2, [T], cfg=cfg, timeout=90.0)

    def producer(ctx):
        rc, ja = ctx.submit_job("heavy")
        assert (rc, ja) == (ADLB_SUCCESS, 1)
        rc, jb = ctx.submit_job("light")
        assert (rc, jb) == (ADLB_SUCCESS, 2)
        for jid in (1, 2):
            ctx.attach(jid)
            for i in range(6):
                rc = ctx.put(struct.pack("<q", 100 * jid + i), T)
                assert rc == ADLB_SUCCESS
        ctx.drain_job(1)
        ctx.drain_job(2)
        return ("prod",)

    def consumer(jid):
        def app(ctx):
            time.sleep(0.3)  # let the submits land (ids deterministic)
            ctx.attach(jid)
            got = []
            while True:
                rc, w = ctx.get_work([T])
                if rc != ADLB_SUCCESS:
                    return (jid, rc, got)
                got.append(struct.unpack("<q", w.payload)[0])
        return app

    ew.run_app(0, producer)
    ew.run_app(1, consumer(1))
    ew.run_app(2, consumer(2))
    res = ew.finish(timeout=90)
    for jid in (1, 2):
        row = res[jid]
        assert row[1] == ADLB_DONE_BY_EXHAUSTION
        assert sorted(row[2]) == [100 * jid + i for i in range(6)]
    assert sum(
        s.metrics.value("rfrs") for s in ew.servers.values()
    ) == 0, "a planned namespace took the RFR fallback"


def test_job_quota_backpressures_one_tenant_not_the_other():
    """Job A (tiny per-server quota) is backpressured at its watermark
    while job B keeps accepting puts unimpeded — per-tenant admission."""

    def app(ctx):
        if ctx.rank == 0:
            rc, ja = ctx.submit_job("quota-a", quota_bytes=96)
            assert (rc, ja) == (ADLB_SUCCESS, 1)
            rc, jb = ctx.submit_job("free-b")
            assert (rc, jb) == (ADLB_SUCCESS, 2)
            ctx.attach(1)
            for i in range(12):  # 12 x 64B against a 96B/server quota
                rc = ctx.put(b"A" * 64, T, work_prio=i)
                assert rc == ADLB_SUCCESS  # backoff retries, never fails
            ctx._c.flush_puts()
            backoffs_a = ctx._c.metrics.value("put_backoffs")
            ctx.drain_job(1)
            ctx.drain_job(2)
            return ("prod-a", backoffs_a)
        if ctx.rank == 1:
            time.sleep(0.3)
            ctx.attach(2)
            for i in range(12):
                rc = ctx.put(b"B" * 64, T, work_prio=i)
                assert rc == ADLB_SUCCESS
            backoffs_b = ctx._c.metrics.value("put_backoffs")
            n = 0
            while True:
                rc, w = ctx.get_work([T])
                if rc != ADLB_SUCCESS:
                    return ("prod-b", backoffs_b, n)
                n += 1
        time.sleep(0.3)
        ctx.attach(1)  # ranks 2-3 drain job A (unblocking its producer)
        n = 0
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                return ("cons-a", n)
            n += 1

    res = run_world(4, 2, [T], app,
                    cfg=Config(exhaust_check_interval=0.2), timeout=90.0)
    out = list(res.app_results.values())
    backoffs_a = next(r[1] for r in out if r[0] == "prod-a")
    b_row = next(r for r in out if r[0] == "prod-b")
    a_consumed = sum(r[1] for r in out if r[0] == "cons-a")
    assert backoffs_a > 0, "job A never hit its quota watermark"
    assert b_row[1] == 0, "job B was backpressured by job A's quota"
    assert a_consumed == 12  # everything A put eventually flowed
    assert b_row[2] == 12    # B's own 12 units all came back to it


def test_job_kill_flushes_parked_requesters():
    def app(ctx):
        if ctx.rank == 0:
            rc, jid = ctx.submit_job("doomed")
            assert (rc, jid) == (ADLB_SUCCESS, 1)
            ctx.attach(1)
            for i in range(4):
                ctx.put(struct.pack("<q", i), T)
            time.sleep(0.5)  # let rank 1 park in the empty namespace
            rc, _ = ctx.kill_job(1)
            assert rc == ADLB_SUCCESS
            rc, status = ctx.job_status(1)
            assert rc == ADLB_SUCCESS and status["state"] == KILLED
            return "killer"
        ctx.attach(1)
        time.sleep(0.2)
        rcs = []
        while True:
            rc, w = ctx.get_work([T2])  # a type nobody puts: stays parked
            rcs.append(rc)
            if rc != ADLB_SUCCESS:
                return rcs

    res = run_world(2, 2, [T, T2], app,
                    cfg=Config(exhaust_check_interval=0.2), timeout=60.0)
    rcs = next(r for r in res.app_results.values() if r != "killer")
    assert rcs[-1] == ADLB_NO_MORE_WORK


def test_single_job_world_stays_quiet():
    """No jobs submitted => no control-plane traffic, no job gossip —
    the legacy protocol untouched (the service-mode analogue of the
    disarmed-world frame-identity tests)."""

    def app(ctx):
        if ctx.rank == 0:
            for i in range(5):
                ctx.put(struct.pack("<q", i), T)
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                m = ctx._c.metrics
                return got, (
                    m.value("tx_msgs", tag="FA_JOB_CTL"),
                    m.value("rx_msgs", tag="TA_JOB_CTL_RESP"),
                )
            got.append(struct.unpack("<q", w.payload)[0])

    res = run_world(2, 2, [T], app,
                    cfg=Config(exhaust_check_interval=0.2), timeout=60.0)
    for got, counters in res.app_results.values():
        assert counters == (0.0, 0.0)
    done = sorted(x for got, _ in res.app_results.values() for x in got)
    assert done == list(range(5))


def test_job_ids_not_reused_after_wal_restart(tmp_path):
    """A job id restored from the WAL must never be reissued to a new
    tenant — a reused id inherits the old job's state (a DONE job is
    born closed; a RUNNING one merges two tenants)."""

    def world1(ctx):
        rc, jid = ctx.submit_job("first")
        assert (rc, jid) == (ADLB_SUCCESS, 1)
        ctx.attach(jid)
        assert ctx.put(struct.pack("<q", 0), T) == ADLB_SUCCESS
        rc, w = ctx.get_work([T])
        assert rc == ADLB_SUCCESS
        ctx.drain_job(jid)
        ctx.attach(0)  # detach: an attached-but-busy rank (this poll
        # loop) would block the job's parked-ness vote by design
        # wait for the per-job ring to mark it done (state is durable
        # in the WAL either way once logged)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            rc, st = ctx.job_status(jid)
            if st and st["state"] == DONE:
                return st["state"]
            time.sleep(0.1)
        return None

    cfg = Config(wal_dir=str(tmp_path), wal_fsync_ms=0.0,
                 exhaust_check_interval=0.2)
    res1 = run_world(1, 1, [T], world1, cfg=cfg, timeout=60.0)
    assert res1.app_results[0] == DONE

    def world2(ctx):
        rc, jid = ctx.submit_job("second")
        assert rc == ADLB_SUCCESS
        ctx.attach(jid)
        # the fresh namespace must accept work (a reused DONE id would
        # answer ADLB_NO_MORE_WORK)
        assert ctx.put(struct.pack("<q", 7), T) == ADLB_SUCCESS
        rc, w = ctx.get_work([T])
        assert rc == ADLB_SUCCESS
        return jid

    res2 = run_world(1, 1, [T], world2, cfg=cfg, timeout=60.0)
    assert res2.app_results[0] == 2, res2.app_results


# ------------------------------------------------------------ control plane


def test_deadletter_honors_ops_dump_bytes(tmp_path):
    srv, _fabric = _wal_server(tmp_path, ops_dump_bytes=8,
                               max_unit_retries=1)
    unit = _unit(50, payload=b"Z" * 64, attempts=2)
    srv._quarantine_unit(unit, in_wq=False)
    ops = OpsServer.__new__(OpsServer)  # view methods only, no socket
    ops.server = srv
    doc = ops._deadletter()
    [rec] = doc["records"]
    assert rec["payload_len"] == 64
    assert rec["payload_hex"] == ("5a" * 8)  # truncated at 8 bytes
    srv.wal.close()


def _http_jobs_app(ctx):
    port = int(os.environ["ADLB_TEST_OPS_PORT"])
    if ctx.rank == 0:
        body = json.dumps({"name": "web-job", "quota_bytes": 1 << 20})
        deadline = time.monotonic() + 20
        while True:  # the master's listener races this rank's startup
            try:
                resp = json.loads(urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{port}/jobs",
                        data=body.encode(), method="POST",
                    ),
                    timeout=10,
                ).read())
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        jid = resp["job_id"]
        assert jid == 1 and resp["state"] == "running"
        ctx.attach(jid)
        for i in range(6):
            assert ctx.put(struct.pack("<q", i), T) == ADLB_SUCCESS
        listing = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jobs", timeout=10).read())
        assert any(j["job_id"] == jid and j["name"] == "web-job"
                   for j in listing["jobs"])
        got = []
        while True:
            rc, w = ctx.get_work([T])
            if rc != ADLB_SUCCESS:
                break
            got.append(struct.unpack("<q", w.payload)[0])
        # the per-job exhaustion ring marked it done; /jobs/<id> agrees
        deadline = time.monotonic() + 10
        state = None
        while time.monotonic() < deadline:
            state = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/jobs/{jid}", timeout=10
            ).read())["state"]
            if state == "done":
                break
            time.sleep(0.1)
        return sorted(got), state
    return None


def test_jobs_http_control_plane(monkeypatch):
    port = probe_free_ports(1)[0]
    os.environ["ADLB_TEST_OPS_PORT"] = str(port)
    try:
        res = spawn_world(
            2, 2, [T], _http_jobs_app,
            cfg=Config(ops_port=port, exhaust_check_interval=0.2),
            timeout=90.0,
        )
    finally:
        os.environ.pop("ADLB_TEST_OPS_PORT", None)
    got, state = res.app_results[0]
    assert got == list(range(6))
    assert state == "done"


def test_wal_gauges_in_metrics(tmp_path):
    srv, _fabric = _wal_server(tmp_path)
    srv._handle(msg(Tag.FA_PUT, 0, payload=b"w", work_type=T, prio=0,
                    target_rank=-1, answer_rank=-1, common_len=0,
                    common_server=-1, common_seqno=-1, put_id=1))
    srv._periodic(time.monotonic(), 0.05)
    expo = srv.metrics.expose()
    assert "adlb_wal_depth" in expo
    assert "adlb_wal_fsync_lag_ms" in expo
    srv.wal.close()


def test_config_validation():
    with pytest.raises(ValueError):
        Config(wal_dir="/tmp/x", server_impl="native")
    with pytest.raises(ValueError):
        Config(wal_dir="/tmp/x", restore_path="/tmp/y")
    with pytest.raises(ValueError):
        Config(wal_fsync_ms=-1)
    with pytest.raises(ValueError):
        Config(ops_dump_bytes=-1)
    Config(wal_dir="/tmp/x", wal_fsync_ms=0, wal_max_bytes=0)
