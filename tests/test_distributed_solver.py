"""The sharded solve must agree with the single-device auction on an
8-virtual-device CPU mesh."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (forces the 8-device CPU platform)

import jax
from jax.sharding import Mesh

from adlb_tpu.balancer.distributed import DistributedAssignmentSolver
from adlb_tpu.balancer.solve import AssignmentSolver

T1, T2 = 1, 2


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("s",))


def _random_snapshots(rng, nservers, ntasks, nreqs):
    snapshots = {}
    seq = 0
    for s in range(100, 100 + nservers):
        tasks = []
        for _ in range(rng.integers(0, ntasks + 1)):
            seq += 1
            tasks.append(
                (seq, int(rng.choice([T1, T2])), int(rng.integers(-5, 10)), 8)
            )
        tasks.sort(key=lambda t: -t[2])
        reqs = []
        for r in range(rng.integers(0, nreqs + 1)):
            reqs.append(
                (
                    (s - 100) * 50 + r,
                    int(rng.integers(1, 1000)),
                    None if rng.random() < 0.3 else [int(rng.choice([T1, T2]))],
                )
            )
        snapshots[s] = {"tasks": tasks, "reqs": reqs}
    return snapshots


def test_matches_single_device_solver(mesh):
    """Contract vs the exact single-device greedy: identical matched
    requester set (maximality under greedy order), type safety, and no
    double assignment. Exact task pairing may differ across shards — commits
    happen in parallel rounds, not one global sequential scan — which is
    fine: plan entries are hints validated at enactment, and the next
    balancer round re-plans leftovers."""
    rng = np.random.default_rng(42)
    dist = DistributedAssignmentSolver(
        types=(T1, T2), max_tasks_per_server=16, max_requesters=8, mesh=mesh,
        rounds=64,
    )
    single = AssignmentSolver(types=(T1, T2), max_tasks=16, max_requesters=8)
    for trial in range(5):
        snaps = _random_snapshots(rng, nservers=8, ntasks=12, nreqs=6)
        p_dist = dist.solve(snaps, None)
        p_single = single.solve(snaps, None)

        def by_req(pairs):
            return {(p[2], p[3]): (p[0], p[1]) for p in pairs}

        d, s = by_req(p_dist), by_req(p_single)
        assert set(d) == set(s), f"trial {trial}: matched sets differ"
        # no task double-assigned
        assert len({(p[0], p[1]) for p in p_dist}) == len(p_dist)
        # type safety: assigned task's type is acceptable to the requester
        type_of = {(s_, t[0]): t[1] for s_, sn in snaps.items() for t in sn["tasks"]}
        masks = {
            ((s_, r[0])): r[2] for s_, sn in snaps.items() for r in sn["reqs"]
        }
        for holder, seqno, req_home, for_rank, rqseqno in p_dist:
            mask = masks[(req_home, for_rank)]
            assert mask is None or type_of[(holder, seqno)] in mask


def test_runs_on_mesh_without_recompile(mesh):
    dist = DistributedAssignmentSolver(
        types=(T1,), max_tasks_per_server=8, max_requesters=4, mesh=mesh
    )
    snaps = {
        100: {"tasks": [(1, T1, 5, 8)], "reqs": []},
        101: {"tasks": [], "reqs": [(0, 1, [T1])]},
    }
    assert dist.solve(snaps, None) == [(100, 1, 101, 0, 1)]
    # second call, different content, same shapes -> cached executable
    snaps2 = {
        100: {"tasks": [], "reqs": [(3, 7, None)]},
        101: {"tasks": [(9, T1, 2, 8)], "reqs": []},
    }
    assert dist.solve(snaps2, None) == [(101, 9, 100, 3, 7)]


def test_plan_engine_uses_mesh_when_available():
    """PlanEngine(use_mesh=True) shards the solve over all visible devices
    (8 virtual CPU devices in CI) and plans cross-server matches."""
    import jax

    from adlb_tpu.balancer.distributed import DistributedAssignmentSolver
    from adlb_tpu.balancer.engine import PlanEngine

    assert len(jax.devices()) >= 2  # conftest forces a virtual CPU mesh
    engine = PlanEngine(types=(1, 2), max_tasks=8, max_requesters=4,
                        use_mesh=True, nservers=4)
    assert isinstance(engine.solver, DistributedAssignmentSolver)
    snaps = {
        100: {"tasks": [(1, 1, 5, 8), (2, 2, 3, 8)], "reqs": [],
              "nbytes": 16, "consumers": 1},
        101: {"tasks": [], "reqs": [(7, 1, [1]), (8, 2, [2])],
              "nbytes": 0, "consumers": 2},
    }
    matches, migrations = engine.round(snaps, None)
    assert len(matches) == 2
    for holder, seqno, req_home, for_rank, rqseqno in matches:
        assert holder == 100 and req_home == 101


def test_world_runs_with_mesh_balancer():
    from adlb_tpu.runtime.world import Config
    from adlb_tpu.workloads import model

    res = model.run(
        numprobs=10, work_secs=0.003, num_app_ranks=3, nservers=2,
        cfg=Config(balancer="tpu", balancer_mesh="auto",
                   balancer_max_tasks=16, balancer_max_requesters=8,
                   exhaust_check_interval=0.2),
    )
    assert res.ok, res


def test_more_servers_than_devices(mesh):
    """16 servers on an 8-device mesh: the shard axis packs two servers
    per device; the matched-requester contract vs the single-device greedy
    must hold unchanged."""
    rng = np.random.default_rng(7)
    dist = DistributedAssignmentSolver(
        types=(T1, T2), max_tasks_per_server=8, max_requesters=4, mesh=mesh,
        servers_per_device=2, rounds=64,
    )
    single = AssignmentSolver(types=(T1, T2), max_tasks=8, max_requesters=4)
    for trial in range(3):
        snaps = _random_snapshots(rng, nservers=16, ntasks=6, nreqs=3)
        p_dist = dist.solve(snaps, None)
        p_single = single.solve(snaps, None)

        def by_req(pairs):
            return {(p[2], p[3]): (p[0], p[1]) for p in pairs}

        assert set(by_req(p_dist)) == set(by_req(p_single)), f"trial {trial}"
        assert len({(p[0], p[1]) for p in p_dist}) == len(p_dist)
