"""Core constants and value types.

Return codes and info keys mirror the reference public API so that programs
written against ADLB translate directly (reference ``include/adlb/adlb.h:16-40``).
"""

from __future__ import annotations

import dataclasses
import enum

ADLB_SUCCESS = 1
ADLB_ERROR = -1
ADLB_NO_MORE_WORK = -999999999
ADLB_DONE_BY_EXHAUSTION = -999999998
ADLB_NO_CURRENT_WORK = -999999997
ADLB_PUT_REJECTED = -999999996
# Retriable transient failure (no reference analogue): the server could
# not serve the request *right now* but the condition clears on its own
# (e.g. the requester reconnected while its rank-death fan-out was still
# settling). Clients retry with capped exponential backoff + jitter.
ADLB_RETRY = -999999995
# Fenced operation (no reference analogue; Config(lease_timeout_s) > 0):
# the requester's lease on this unit EXPIRED — the unit was re-enqueued
# under a new attempt, and this late settle attempt from the old owner is
# rejected so a slow-but-alive worker can never double-settle a unit.
# Clients map it onto the ADLB_RETRY backoff path (drop the handle,
# re-reserve).
ADLB_FENCED = -999999994
# Overload backpressure (no reference analogue; Config(mem_hard_frac) > 0):
# the server is above its hard memory watermark and knows no peer with
# room either — retry the SAME request after the carried retry-after
# hint instead of hopping between equally-full servers until the retry
# budget aborts the producer. Does not burn put_max_retries.
ADLB_BACKOFF = -999999993
ADLB_LOWEST_PRIO = -999999999

ADLB_RESERVE_REQUEST_ANY = -1
ADLB_RESERVE_EOL = -1
ADLB_HANDLE_SIZE = 5

# Max number of distinct types one Reserve may request, matching the
# reference's REQ_TYPE_VECT_SZ (reference src/xq.h:37).
REQ_TYPE_VECT_SZ = 16


class InfoKey(enum.IntEnum):
    """Statistics keys for ``Info_get`` (reference include/adlb/adlb.h:25-36)."""

    MALLOC_HWM = 1
    AVG_TIME_ON_RQ = 2
    NPUSHED_FROM_HERE = 3
    NPUSHED_TO_HERE = 4
    NREJECTED_PUTS = 5
    LOOP_TOP_TIME = 6
    MAX_QMSTAT_TRIP_TIME = 7
    AVG_QMSTAT_TRIP_TIME = 8
    NUM_QMS_EXCEED_INT = 9
    NUM_RESERVES = 10
    NUM_RESERVES_PUT_ON_RQ = 11
    MAX_WQ_COUNT = 12
    # beyond-reference L0 introspection (VERDICT r1 #8): the reference's
    # /proc/self/status memory probe (src/adlb.c:3347-3369) and its
    # MPICH unexpected-message-queue depth (src/adlb.c:3645-3719), whose
    # TCP analogue is the endpoint's received-but-unhandled frame backlog
    RSS_KB = 13
    TRANSPORT_BACKLOG = 14
    # server-failover surface (Config(on_server_failure="failover")): how
    # many takeovers this server performed, units counted lost to
    # replication lag at takeover, and the last promotion's
    # detection->promoted time in ms (the recovery-cost row bench.py
    # records as failover_mttr_ms)
    NUM_FAILOVERS = 15
    FAILOVER_LOST = 16
    FAILOVER_MTTR_MS = 17
    # gray-failure surface: units moved to the per-server dead-letter
    # quarantine after exhausting Config(max_unit_retries) — counted
    # exactly-once under the same conservation contract as FAILOVER_LOST
    # (every unit is completed, re-executed, or counted here), and
    # retrievable via ctx.get_quarantined() / the ops /deadletter view
    QUARANTINED = 18


@dataclasses.dataclass(frozen=True)
class WorkHandle:
    """Opaque-ish handle returned by Reserve, consumed by Get_reserved.

    Mirrors the reference's 5-int handle {wqseqno, holding server rank,
    common_len, common_server_rank, common_seqno} (reference
    src/adlb.c:2935-2947) so a reserved unit can be fetched directly from
    whichever server holds it, and its batch-common prefix from wherever the
    prefix was stored.
    """

    seqno: int
    server_rank: int
    common_len: int = 0
    common_server_rank: int = -1
    common_seqno: int = -1

    def to_ints(self) -> list[int]:
        return [
            self.seqno,
            self.server_rank,
            self.common_len,
            self.common_server_rank,
            self.common_seqno,
        ]

    @staticmethod
    def from_ints(v: list[int]) -> "WorkHandle":
        return WorkHandle(v[0], v[1], v[2], v[3], v[4])


@dataclasses.dataclass(frozen=True)
class ReserveResult:
    """Everything a successful Reserve reports back to the app."""

    work_type: int
    work_prio: int
    handle: WorkHandle
    work_len: int
    answer_rank: int


@dataclasses.dataclass(frozen=True)
class GotWork:
    """A fused reserve+get result (this framework's extension): the unit is
    already consumed — no handle, no second round trip."""

    work_type: int
    work_prio: int
    payload: bytes
    answer_rank: int
    time_on_q: float


class AdlbError(RuntimeError):
    """Raised for API misuse (invalid type, invalid handle, ...)."""


class HomeServerLostError(AdlbError):
    """A protocol peer (home server, or any server this client must
    reach) became permanently unreachable mid-run.

    Under the rank-death fault model this ends the world either way, but
    the HARNESS needs the distinction: when some rank aborted the world,
    a server tearing down can close its clients' connections before
    their TA_ABORT frames arrive — those clients die with this error as
    abort COLLATERAL, and spawn_world classifies the world as aborted
    rather than failed. Without an abort in flight it is a genuine
    failure (server crash) and surfaces as an error."""


class AdlbAborted(RuntimeError):
    """Raised in every rank when some rank called Abort."""

    def __init__(self, code: int):
        super().__init__(f"ADLB aborted with code {code}")
        self.code = code
