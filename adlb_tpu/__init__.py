"""adlb_tpu — a TPU-native distributed task-queue framework.

A ground-up rebuild of the capabilities of ADLB (Asynchronous Dynamic
Load-Balancing library, reference: kc9jud/adlb — see /root/reference and
SURVEY.md): a typed, prioritized, globally load-balanced work pool for
master/worker applications, exposed through the classic
``Put / Reserve / Get_reserved`` API with targeting, answer-routing,
batch/common-prefix puts, blocking and non-blocking reserves, exhaustion
and explicit-termination protocols, a watchdog debug server, and
stats/observability.

Architecture (TPU-first, not a port):

* **Runtime / data plane** — message-passing ranks (threads in-process, TCP
  across processes/hosts) with a single-threaded server reactor per server
  rank; reproduces the semantics of the reference's MPI tag protocol
  (reference ``src/adlb.c:44-83``) without MPI.
* **Balancer brain** — the reference's 0.1 s qmstat gossip ring plus greedy
  per-server matching / RFR work stealing (reference ``src/adlb.c:806-822,
  1802-2070``) is *replaced* by a periodic batched global assignment solve in
  JAX: servers snapshot queued-task metadata into fixed-shape tensors, a
  jitted bipartite solve computes task->worker placement on TPU, and the plan
  is enacted through the work-transfer protocol.
* **Native core** (in progress) — the hot queue operations are additionally
  being implemented as a C++ library with ctypes bindings
  (``adlb_tpu/native/``), mirroring the reference's all-native data plane;
  the pure-Python queues remain the always-available fallback.
"""

from adlb_tpu.types import (  # noqa: F401
    ADLB_SUCCESS,
    ADLB_ERROR,
    ADLB_NO_MORE_WORK,
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_NO_CURRENT_WORK,
    ADLB_PUT_REJECTED,
    ADLB_LOWEST_PRIO,
    ADLB_RESERVE_REQUEST_ANY,
    ADLB_HANDLE_SIZE,
    InfoKey,
    WorkHandle,
)
from adlb_tpu.api import (  # noqa: F401
    AdlbContext,
    run_world,
)

__version__ = "0.1.0"
