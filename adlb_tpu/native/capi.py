"""Build + run harness for the native C client library.

``libadlb.so`` implements the public C API (include/adlb/adlb.h) over the
binary wire codec; this module compiles it (plain g++, same no-machinery
spirit as the wq core build) and runs mixed worlds: Python servers on the
TCP fabric + native client processes, rendezvousing through a file — the
moral equivalent of the reference's `mpiexec -n k ./a.out` launch
(reference examples/README-batcher.txt:57).
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_DIR))
_SRC = os.path.join(_DIR, "libadlb.cpp")
_FSRC = os.path.join(_DIR, "adlbf.c")
_LIB = os.path.join(_DIR, "libadlb.so")
_INCLUDE = os.path.join(_REPO, "include")

_lock = threading.Lock()


def build_libadlb() -> str:
    """Compile libadlb.so (cached by mtime); returns its path."""
    with _lock:
        srcs = [_SRC] + ([_FSRC] if os.path.exists(_FSRC) else [])
        deps = srcs + [os.path.join(_INCLUDE, "adlb", "adlb.h")]
        newest = max(os.path.getmtime(s) for s in deps)
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= newest:
            return _LIB
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            f"-I{_INCLUDE}", "-o", tmp, *srcs,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(f"libadlb build failed:\n{e.stderr}") from e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return _LIB


def build_example(src: str, out: Optional[str] = None) -> str:
    """Compile a C example against libadlb; returns the binary path."""
    build_libadlb()
    out = out or os.path.join(
        tempfile.gettempdir(),
        "adlb_" + os.path.splitext(os.path.basename(src))[0],
    )
    if os.path.exists(out) and os.path.getmtime(out) >= max(
        os.path.getmtime(src), os.path.getmtime(_LIB)
    ):
        return out
    cmd = [
        "gcc", "-O2", f"-I{_INCLUDE}", "-o", out, src,
        f"-L{_DIR}", "-ladlb", f"-Wl,-rpath,{_DIR}", "-lm",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(f"example build failed:\n{e.stderr}") from e
    return out


def run_native_probe(
    example: str,
    types,
    env_extra: dict,
    num_app_ranks: int,
    nservers: int,
    cfg=None,
    timeout: float = 300.0,
):
    """Shared bootstrap for the native benchmark probes
    (workloads/hotspot_native.py, workloads/trickle_native.py): force
    native servers, build ``examples/<example>``, run one C client per app
    rank, and raise on any nonzero client exit. Returns the per-rank
    (rc, stdout, stderr) list."""
    import dataclasses

    from adlb_tpu.runtime.world import Config

    base = cfg or Config()
    cfg = dataclasses.replace(
        base,
        server_impl="native",
        exhaust_check_interval=min(base.exhaust_check_interval, 0.2),
    )
    exe = build_example(os.path.join(_REPO, "examples", example))
    results, _stats = run_native_world(
        n_clients=num_app_ranks,
        nservers=nservers,
        types=list(types),
        exe=exe,
        cfg=cfg,
        env_extra=env_extra,
        timeout=timeout,
    )
    for rank, (rc, out, err) in enumerate(results):
        if rc != 0:
            raise RuntimeError(
                f"{example} rank {rank} exited {rc}\n"
                f"stdout:{out}\nstderr:{err}"
            )
    return results


def parse_probe_lines(results, prefix: str):
    """Parse the per-rank ``PREFIX k=v ...`` metric line each native probe
    client prints (hotspot_c/nq_c/tsp_c/trickle_c share the shape).
    Returns one dict per rank with ints where the value parses as int,
    floats otherwise."""
    rows = []
    for _rc, out, _err in results:
        line = next(
            ln for ln in out.splitlines() if ln.startswith(prefix + " ")
        )
        kv = {}
        for field in line.split()[1:]:
            k, v = field.split("=")
            try:
                kv[k] = int(v)
            except ValueError:
                try:
                    kv[k] = float(v)
                except ValueError:
                    kv[k] = v  # non-numeric marker (e.g. fetch=batch)
        rows.append(kv)
    return rows


def probe_makespan(rows):
    """(t_begin, t_end, elapsed) across parsed probe rows, with the
    division-safe elapsed floor applied in one place."""
    t_begin = min(r["t0"] for r in rows)
    t_end = max(r["t1"] for r in rows)
    return t_begin, t_end, max(t_end - t_begin, 1e-9)


def check_fetch_mode(rows, fetch: str, what: str, skip_first: bool = False):
    """Every consuming rank must report the REQUESTED fetch mode — a
    broken env plumbing falling back to single-unit would silently
    mislabel the bench's batch rows.  ``skip_first`` skips a rank-0
    producer/collector row that predates the field."""
    want = "batch" if fetch.startswith("batch") else "single"
    check = rows[1:] if skip_first else rows
    wrong = [r for r in check if r.get("fetch", "single") != want]
    if wrong:
        raise RuntimeError(
            f"{what} fetch mode mismatch: requested {fetch!r}, "
            f"ranks report {wrong[:2]}"
        )


def probe_aggregate(rows, tasks=None, done_key="done", wait_rows=None):
    """The aggregation every native probe harness repeats: total units,
    cross-process makespan, rate, and mean wait fraction.  ``tasks``
    overrides the default sum of ``done_key`` for probes whose unit count
    is assembled from several fields; ``wait_rows`` restricts the wait
    average to the ranks that actually consume (dedicated producers and
    collectors are blocked by design and would add a ~1/nranks floor
    that says nothing about balancing).  Returns
    (tasks, elapsed, tasks_per_sec, wait_pct)."""
    _t0, _t1, elapsed = probe_makespan(rows)
    if tasks is None:
        tasks = sum(r[done_key] for r in rows)
    wrows = rows if wait_rows is None else wait_rows
    wait = sum(r["wait"] / elapsed for r in wrows) / len(wrows)
    return tasks, elapsed, tasks / elapsed, 100.0 * wait


def run_native_world(
    n_clients: int,
    nservers: int,
    types: Sequence[int],
    exe: str,
    cfg=None,
    use_debug_server: bool = False,
    env_extra: Optional[dict] = None,
    timeout: float = 120.0,
):
    """Python servers (threads) + native client processes (one per app rank).

    Returns (results: list of (returncode, stdout, stderr) per client,
    server_stats: dict rank -> stats).
    """
    from adlb_tpu.runtime.debug_server import DebugServer
    from adlb_tpu.runtime.server import Server
    from adlb_tpu.runtime.transport_tcp import TcpEndpoint, local_addr_map
    from adlb_tpu.runtime.world import Config, WorldSpec

    cfg = cfg or Config()
    world = WorldSpec(
        nranks=n_clients + nservers + (1 if use_debug_server else 0),
        nservers=nservers,
        types=tuple(types),
        use_debug_server=use_debug_server,
    )
    all_native = cfg.server_impl == "native"
    addr_map = local_addr_map(world.nranks)
    binary = set(range(n_clients))  # native ranks speak the TLV codec
    abort_event = threading.Event()

    server_stats: dict[int, dict] = {}
    errors: list[BaseException] = []
    threads = []
    endpoints = {}
    daemons: dict[int, subprocess.Popen] = {}

    sidecar_thread = None
    if all_native:
        # all-native world: C clients + C++ server daemons. Daemons bind
        # their own ports, so the rendezvous map is completed from their
        # PORT hellos before any client starts. A failed bootstrap must not
        # leak the daemons already spawned.
        from adlb_tpu.native import daemon as daemon_mod

        sidecar_ep = None
        try:
            for rank in world.server_ranks:
                daemons[rank] = daemon_mod.spawn_daemon(world, cfg, rank)
            for rank, p in daemons.items():
                addr_map[rank] = ("127.0.0.1", daemon_mod.read_hello(p, rank))
            if cfg.balancer == "tpu":
                # JAX balancer sidecar thread at pseudo-rank world.nranks
                from adlb_tpu.balancer.sidecar import start_sidecar

                sidecar_ep, sidecar_thread = start_sidecar(
                    world, cfg, abort_event
                )
                addr_map[world.nranks] = ("127.0.0.1", sidecar_ep.port)
                sidecar_ep.addr_map.update(addr_map)
                endpoints[world.nranks] = sidecar_ep
                sidecar_thread.start()
            if use_debug_server:
                # the watchdog stays Python even in all-native worlds;
                # daemons heartbeat it with binary DS_LOG frames
                dbg_rank = world.debug_server_rank
                endpoints[dbg_rank] = TcpEndpoint(
                    dbg_rank, addr_map, binary_peers=set(world.server_ranks)
                )
                t = threading.Thread(
                    target=lambda: DebugServer(
                        world, cfg, endpoints[dbg_rank], abort_event
                    ).run(),
                    daemon=True,
                )
                threads.append(t)
                t.start()
            for p in daemons.values():
                daemon_mod.send_addrs(p, addr_map)
        except BaseException:
            for p in daemons.values():
                p.kill()
            abort_event.set()
            if sidecar_ep is not None:
                from adlb_tpu.balancer.sidecar import stop_sidecar

                endpoints.pop(world.nranks, None)
                stop_sidecar(sidecar_ep, sidecar_thread, abort_event)
            raise

    with tempfile.NamedTemporaryFile(
        "w", suffix=".adlb", delete=False
    ) as f:
        # world ranks only: the C client derives the world size from the
        # line count, so the balancer sidecar's pseudo-rank (world.nranks,
        # used by servers alone) must not appear here
        for r, (host, port) in sorted(addr_map.items()):
            if r < world.nranks:
                f.write(f"{r} {host} {port}\n")
        rendezvous = f.name

    if not all_native:
        # bind every Python listener BEFORE any rank starts sending: a
        # server's first DS_LOG can otherwise race the debug server's bind
        # and die on connection-refused
        endpoints = {
            rank: TcpEndpoint(rank, addr_map, binary_peers=binary)
            for rank in (
                list(world.server_ranks)
                + ([world.debug_server_rank] if use_debug_server else [])
            )
        }

        def server_main(rank: int) -> None:
            try:
                server = Server(world, cfg, endpoints[rank], abort_event)
                server.run()
                server_stats[rank] = server.finalize_stats()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                abort_event.set()

        def debug_main(rank: int) -> None:
            DebugServer(world, cfg, endpoints[rank], abort_event).run()

        for rank in world.server_ranks:
            t = threading.Thread(target=server_main, args=(rank,), daemon=True)
            threads.append(t)
            t.start()
        if use_debug_server:
            t = threading.Thread(
                target=debug_main, args=(world.debug_server_rank,), daemon=True
            )
            threads.append(t)
            t.start()

    env = dict(os.environ)
    env["ADLB_RENDEZVOUS"] = rendezvous
    env["ADLB_NUM_SERVERS"] = str(nservers)
    if use_debug_server:
        env["ADLB_USE_DEBUG_SERVER"] = "1"
    env.update(env_extra or {})

    procs = []
    for rank in range(n_clients):
        e = dict(env)
        e["ADLB_RANK"] = str(rank)
        procs.append(
            subprocess.Popen(
                [exe],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )

    import time as _time

    results = []
    deadline = _time.monotonic() + timeout  # shared wall-clock bound
    try:
        for p in procs:
            out, err = p.communicate(
                timeout=max(deadline - _time.monotonic(), 0.1)
            )
            results.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        abort_event.set()
        for p in procs:
            if p.poll() is None:
                p.kill()
                out, err = p.communicate()
                results.append((-9, out, err))
        raise TimeoutError(
            f"native world did not finish within {timeout}s; "
            f"client outputs: {results}"
        )
    finally:
        for t in threads:
            t.join(timeout=15.0)
        if any(t.is_alive() for t in threads):
            abort_event.set()
            for t in threads:
                t.join(timeout=5.0)
        if sidecar_thread is not None:
            sidecar_thread.join(timeout=10.0)  # exits on servers' DS_ENDs
        for ep in endpoints.values():
            ep.close()
        if daemons:
            from adlb_tpu.native import daemon as daemon_mod

            for rank, p in daemons.items():
                stats, abort_code, rc = daemon_mod.collect_stats(p)
                if stats is not None:
                    server_stats[rank] = stats
                elif abort_code is None and rc not in (-9, -15):
                    # crashed daemon (not one we killed on teardown):
                    # attribute it, parity with transport_tcp's
                    # 'exited without STATS'
                    errors.append(
                        RuntimeError(
                            f"native server rank {rank} exited {rc} "
                            f"without STATS"
                        )
                    )
        os.unlink(rendezvous)

    if errors:
        raise errors[0]
    return results, server_stats
