"""Build + load the native core.

Compiles ``wqcore.cpp`` into a shared library next to the source with the
system ``g++`` (cached by mtime), then loads it with ctypes. No
pip/pybind11/setuptools involvement — the reference's build layer is plain
CMake over C sources (reference ``CMakeLists.txt:44-56``); this is the same
spirit with less machinery.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wqcore.cpp")
_LIB = os.path.join(_DIR, "libadlbwq.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _compile() -> None:
    # compile to a private temp file and rename into place: concurrent
    # processes racing to build must never dlopen a half-written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
    i32p, i64p = ctypes.POINTER(i32), ctypes.POINTER(i64)
    sig = {
        "adlb_wq_new": (p, []),
        "adlb_wq_free": (None, [p]),
        "adlb_wq_add": (i32, [p, i64, i32, i32, i32, i32, i32, i64]),
        "adlb_wq_remove": (i32, [p, i64]),
        "adlb_wq_pin": (i32, [p, i64, i32]),
        "adlb_wq_unpin": (i32, [p, i64]),
        "adlb_wq_find_match": (i64, [p, i32, i32p, i32]),
        "adlb_wq_find_targeted": (i64, [p, i32, i32p, i32]),
        "adlb_wq_find_untargeted": (i64, [p, i32p, i32]),
        "adlb_wq_hi_prio_of_type": (i32, [p, i32, i32p]),
        "adlb_wq_count": (i64, [p]),
        "adlb_wq_max_count": (i64, [p]),
        "adlb_wq_total_bytes": (i64, [p]),
        "adlb_wq_num_unpinned": (i64, [p]),
        "adlb_wq_num_unpinned_untargeted": (i64, [p]),
        "adlb_wq_depth_sample": (None, [p, i64p]),
        "adlb_wq_snapshot_untargeted": (i64, [p, i64, i64p, i32p, i32p, i64p]),
        "adlb_wq_get": (i32, [p, i64, i32p, i32p, i32p, i32p, i64p]),
    }
    for name, (restype, argtypes) in sig.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    # The O(1) getters are ALSO bound through a PyDLL view of the same
    # library: CDLL releases the GIL around every call, and on a loaded
    # host each re-acquire can stall the calling (reactor) thread for
    # up to a scheduler switch interval — milliseconds — which made the
    # periodic tick's depth gauges a measurable slice of tpu-mode pop
    # latency. PyDLL keeps the GIL held: correct for these functions
    # (no I/O, no blocking, nanoseconds of C) and ~1000x cheaper under
    # thread contention. Heavy calls (snapshot sorts, matching) stay on
    # the GIL-releasing CDLL where parallelism pays.
    fast = ctypes.PyDLL(lib._name)
    for name in (
        "adlb_wq_count", "adlb_wq_max_count", "adlb_wq_total_bytes",
        "adlb_wq_num_unpinned", "adlb_wq_num_unpinned_untargeted",
        "adlb_wq_depth_sample", "adlb_wq_hi_prio_of_type",
    ):
        restype, argtypes = sig[name]
        fn = getattr(fast, name)
        fn.restype = restype
        fn.argtypes = argtypes
    lib._fast = fast
    return lib


def ensure_built() -> Optional[ctypes.CDLL]:
    """Build if stale and load; returns None (and records why) on failure."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        try:
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                _compile()
            _lib = _bind(ctypes.CDLL(_LIB))
            return _lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native core unavailable: {detail[:500]}"
            return None


def native_available() -> bool:
    return ensure_built() is not None


def build_error() -> Optional[str]:
    return _build_error


# ---------------------------------------------------------------- serverd

_SERVERD_SRC = os.path.join(_DIR, "serverd.cpp")
_SERVERD_HDR = os.path.join(_DIR, "wqcore.hpp")
_SERVERD_BIN = os.path.join(_DIR, "adlb_serverd")

_serverd_lock = threading.Lock()
_serverd_error: Optional[str] = None


def ensure_serverd() -> str:
    """Build (if stale) and return the path of the native server daemon.

    Raises RuntimeError when the toolchain is unavailable — callers asked
    for server_impl="native" explicitly, so there is no silent fallback.
    """
    global _serverd_error
    with _serverd_lock:
        if _serverd_error is not None:
            raise RuntimeError(_serverd_error)
        src_mtime = max(
            os.path.getmtime(_SERVERD_SRC), os.path.getmtime(_SERVERD_HDR)
        )
        if (
            not os.path.exists(_SERVERD_BIN)
            or os.path.getmtime(_SERVERD_BIN) < src_mtime
        ):
            tmp = f"{_SERVERD_BIN}.{os.getpid()}.tmp"
            cmd = [
                "g++", "-O2", "-std=c++17", "-pthread", "-o", tmp,
                _SERVERD_SRC,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, _SERVERD_BIN)
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                _serverd_error = f"native server unavailable: {detail[:800]}"
                raise RuntimeError(_serverd_error) from None
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return _SERVERD_BIN
