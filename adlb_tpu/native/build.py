"""Build + load the native core.

Compiles ``wqcore.cpp`` into a shared library next to the source with the
system ``g++`` (cached by mtime), then loads it with ctypes. No
pip/pybind11/setuptools involvement — the reference's build layer is plain
CMake over C sources (reference ``CMakeLists.txt:44-56``); this is the same
spirit with less machinery.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wqcore.cpp")
_LIB = os.path.join(_DIR, "libadlbwq.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _compile() -> None:
    # compile to a private temp file and rename into place: concurrent
    # processes racing to build must never dlopen a half-written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
    i32p, i64p = ctypes.POINTER(i32), ctypes.POINTER(i64)
    sig = {
        "adlb_wq_new": (p, []),
        "adlb_wq_free": (None, [p]),
        "adlb_wq_add": (i32, [p, i64, i32, i32, i32, i32, i32, i64]),
        "adlb_wq_remove": (i32, [p, i64]),
        "adlb_wq_pin": (i32, [p, i64, i32]),
        "adlb_wq_unpin": (i32, [p, i64]),
        "adlb_wq_find_match": (i64, [p, i32, i32p, i32]),
        "adlb_wq_find_targeted": (i64, [p, i32, i32p, i32]),
        "adlb_wq_find_untargeted": (i64, [p, i32p, i32]),
        "adlb_wq_hi_prio_of_type": (i32, [p, i32, i32p]),
        "adlb_wq_count": (i64, [p]),
        "adlb_wq_max_count": (i64, [p]),
        "adlb_wq_total_bytes": (i64, [p]),
        "adlb_wq_num_unpinned": (i64, [p]),
        "adlb_wq_num_unpinned_untargeted": (i64, [p]),
        "adlb_wq_depth_sample": (None, [p, i64p]),
        "adlb_wq_snapshot_untargeted": (i64, [p, i64, i64p, i32p, i32p, i64p]),
        "adlb_wq_get": (i32, [p, i64, i32p, i32p, i32p, i32p, i64p]),
    }
    for name, (restype, argtypes) in sig.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    # The O(1) getters are ALSO bound through a PyDLL view of the same
    # library: CDLL releases the GIL around every call, and on a loaded
    # host each re-acquire can stall the calling (reactor) thread for
    # up to a scheduler switch interval — milliseconds — which made the
    # periodic tick's depth gauges a measurable slice of tpu-mode pop
    # latency. PyDLL keeps the GIL held: correct for these functions
    # (no I/O, no blocking, nanoseconds of C) and ~1000x cheaper under
    # thread contention. Heavy calls (snapshot sorts, matching) stay on
    # the GIL-releasing CDLL where parallelism pays.
    fast = ctypes.PyDLL(lib._name)
    for name in (
        "adlb_wq_count", "adlb_wq_max_count", "adlb_wq_total_bytes",
        "adlb_wq_num_unpinned", "adlb_wq_num_unpinned_untargeted",
        "adlb_wq_depth_sample", "adlb_wq_hi_prio_of_type",
    ):
        restype, argtypes = sig[name]
        fn = getattr(fast, name)
        fn.restype = restype
        fn.argtypes = argtypes
    lib._fast = fast
    return lib


def ensure_built() -> Optional[ctypes.CDLL]:
    """Build if stale and load; returns None (and records why) on failure."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        try:
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                _compile()
            _lib = _bind(ctypes.CDLL(_LIB))
            return _lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native core unavailable: {detail[:500]}"
            return None


def native_available() -> bool:
    return ensure_built() is not None


def build_error() -> Optional[str]:
    return _build_error


# ------------------------------------------------------------------ codec

_CODEC_SRC = os.path.join(_DIR, "codec.cpp")
_CODEC_LIB = os.path.join(_DIR, "libadlbcodec.so")
_CODEC_ERRMARK = os.path.join(_DIR, "libadlbcodec.err")


def _errmark_paths() -> list:
    """Candidate failed-compile marker locations: the package dir, then
    a tempdir fallback keyed on the source path — a read-only
    site-packages must still be able to record "this compile is doomed"
    so every spawned rank doesn't re-pay the failed g++ at import."""
    import hashlib
    import tempfile

    h = hashlib.sha1(_CODEC_SRC.encode()).hexdigest()[:12]
    return [
        _CODEC_ERRMARK,
        os.path.join(tempfile.gettempdir(), f"adlbcodec.{h}.err"),
    ]

_codec_lock = threading.Lock()
_codec_lib = None  # the _adlbcodec module object once loaded
_codec_error: Optional[str] = None


def _compile_codec() -> None:
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    if not os.path.exists(os.path.join(inc, "Python.h")):
        raise OSError(f"Python.h not found under {inc}")
    tmp = f"{_CODEC_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", f"-I{inc}",
        "-o", tmp, _CODEC_SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _CODEC_LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _bind_codec(lib: ctypes.PyDLL):
    # PyDLL (the wqcore O(1)-getter discipline, GIL held throughout): the
    # ONE ctypes call asks the library for a fully-formed module object,
    # whose encode/decode are METH_FASTCALL builtins — per-frame calls
    # cost a builtin vector call, not a ctypes FFI marshal
    lib.adlb_codec_module.restype = ctypes.py_object
    lib.adlb_codec_module.argtypes = []
    return lib.adlb_codec_module()


def ensure_codec():
    """Build (if stale) and load the compiled TLV codec; returns the
    codec MODULE object, or None (recording why) when the toolchain or
    headers are unavailable.

    A failed compile writes a marker stamped with the source mtime so
    every subsequently spawned rank skips the doomed g++ attempt instead
    of paying it per process (spawn worlds fork dozens)."""
    global _codec_lib, _codec_error
    with _codec_lock:
        if _codec_lib is not None:
            return _codec_lib
        if _codec_error is not None:
            return None
        src_mtime = os.path.getmtime(_CODEC_SRC)
        try:
            if (
                not os.path.exists(_CODEC_LIB)
                or os.path.getmtime(_CODEC_LIB) < src_mtime
            ):
                for mark in _errmark_paths():
                    try:
                        with open(mark) as f:
                            if float(f.read().split("\n", 1)[0]) \
                                    == src_mtime:
                                _codec_error = (
                                    "codec build failed previously "
                                    f"(see {mark})"
                                )
                                return None
                    except (OSError, ValueError):
                        continue
                _compile_codec()
                for mark in _errmark_paths():
                    try:
                        os.unlink(mark)
                    except OSError:
                        pass
            _codec_lib = _bind_codec(ctypes.PyDLL(_CODEC_LIB))
            return _codec_lib
        except AttributeError as e:
            # a stale .so predating the module-object entrypoint
            _codec_error = f"compiled codec unavailable: {e}"
            return None
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _codec_error = f"compiled codec unavailable: {detail[:500]}"
            for mark in _errmark_paths():
                try:
                    with open(mark, "w") as f:
                        f.write(f"{src_mtime}\n{_codec_error}\n")
                    break  # first writable location wins
                except OSError:
                    continue
            return None


def codec_error() -> Optional[str]:
    return _codec_error


# ---------------------------------------------------------------- serverd

_SERVERD_SRC = os.path.join(_DIR, "serverd.cpp")
_SERVERD_HDR = os.path.join(_DIR, "wqcore.hpp")
_SERVERD_BIN = os.path.join(_DIR, "adlb_serverd")

_serverd_lock = threading.Lock()
_serverd_error: Optional[str] = None


def ensure_serverd() -> str:
    """Build (if stale) and return the path of the native server daemon.

    Raises RuntimeError when the toolchain is unavailable — callers asked
    for server_impl="native" explicitly, so there is no silent fallback.
    """
    global _serverd_error
    with _serverd_lock:
        if _serverd_error is not None:
            raise RuntimeError(_serverd_error)
        src_mtime = max(
            os.path.getmtime(_SERVERD_SRC), os.path.getmtime(_SERVERD_HDR)
        )
        if (
            not os.path.exists(_SERVERD_BIN)
            or os.path.getmtime(_SERVERD_BIN) < src_mtime
        ):
            tmp = f"{_SERVERD_BIN}.{os.getpid()}.tmp"
            cmd = [
                "g++", "-O2", "-std=c++17", "-pthread", "-o", tmp,
                _SERVERD_SRC,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, _SERVERD_BIN)
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                _serverd_error = f"native server unavailable: {detail[:800]}"
                raise RuntimeError(_serverd_error) from None
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return _SERVERD_BIN
