/* Fortran binding: thin by-reference shims over the C API, the native
 * equivalent of the reference's src/adlbf.c:6-103.  Name mangling follows
 * the GNU default (lowercase + trailing underscore); builds that need a
 * different convention define ADLB_FC_GLOBAL, which CMake wires up via
 * FortranCInterface when a Fortran compiler is present (reference
 * CMakeLists.txt:62-68).  Constants for Fortran programs live in
 * include/adlb/adlbf.h, generated from adlb.h by scripts/genfh.py.
 */
#include <adlb/adlb.h>

/* CMake defines ADLB_HAVE_FC_MANGLING when a Fortran compiler was found
 * and FortranCInterface generated adlb_fc_mangling.h with the compiler's
 * true convention (reference CMakeLists.txt:62-68). */
#ifdef ADLB_HAVE_FC_MANGLING
#include "adlb_fc_mangling.h"
#endif

#ifndef ADLB_FC_GLOBAL
#define ADLB_FC_GLOBAL(lc, UC) lc##_
#endif

/* the build compiles this file with g++ alongside libadlb.cpp; the shims
 * must keep unmangled Fortran-visible names either way */
#ifdef __cplusplus
extern "C" {
#endif

void ADLB_FC_GLOBAL(adlb_init, ADLB_INIT)(int *nservers, int *use_debug_server,
                                          int *aprintf_flag, int *ntypes,
                                          int type_vect[], int *am_server,
                                          int *am_debug_server,
                                          int *num_app_ranks, int *ierr) {
  *ierr = ADLB_Init(*nservers, *use_debug_server, *aprintf_flag, *ntypes,
                    type_vect, am_server, am_debug_server, num_app_ranks);
}

void ADLB_FC_GLOBAL(adlb_server, ADLB_SERVER)(double *hi_malloc,
                                              double *periodic_log_interval,
                                              int *ierr) {
  *ierr = ADLB_Server(*hi_malloc, *periodic_log_interval);
}

void ADLB_FC_GLOBAL(adlb_debug_server, ADLB_DEBUG_SERVER)(double *timeout,
                                                          int *ierr) {
  *ierr = ADLB_Debug_server(*timeout);
}

void ADLB_FC_GLOBAL(adlb_put, ADLB_PUT)(void *work_buf, int *work_len,
                                        int *target_rank, int *answer_rank,
                                        int *work_type, int *work_prio,
                                        int *ierr) {
  *ierr = ADLB_Put(work_buf, *work_len, *target_rank, *answer_rank,
                   *work_type, *work_prio);
}

void ADLB_FC_GLOBAL(adlb_reserve, ADLB_RESERVE)(int *req_types, int *work_type,
                                                int *work_prio,
                                                int *work_handle,
                                                int *work_len,
                                                int *answer_rank, int *ierr) {
  *ierr = ADLB_Reserve(req_types, work_type, work_prio, work_handle, work_len,
                       answer_rank);
}

void ADLB_FC_GLOBAL(adlb_ireserve, ADLB_IRESERVE)(int *req_types,
                                                  int *work_type,
                                                  int *work_prio,
                                                  int *work_handle,
                                                  int *work_len,
                                                  int *answer_rank,
                                                  int *ierr) {
  *ierr = ADLB_Ireserve(req_types, work_type, work_prio, work_handle,
                        work_len, answer_rank);
}

void ADLB_FC_GLOBAL(adlb_get_reserved, ADLB_GET_RESERVED)(void *work_buf,
                                                          int *work_handle,
                                                          int *ierr) {
  *ierr = ADLB_Get_reserved(work_buf, work_handle);
}

void ADLB_FC_GLOBAL(adlb_get_reserved_timed,
                    ADLB_GET_RESERVED_TIMED)(void *work_buf, int *work_handle,
                                             double *time_on_queue,
                                             int *ierr) {
  *ierr = ADLB_Get_reserved_timed(work_buf, work_handle, time_on_queue);
}

void ADLB_FC_GLOBAL(adlb_begin_batch_put,
                    ADLB_BEGIN_BATCH_PUT)(void *common_buf, int *len_common,
                                          int *ierr) {
  *ierr = ADLB_Begin_batch_put(common_buf, *len_common);
}

void ADLB_FC_GLOBAL(adlb_end_batch_put, ADLB_END_BATCH_PUT)(int *ierr) {
  *ierr = ADLB_End_batch_put();
}

void ADLB_FC_GLOBAL(adlb_set_problem_done, ADLB_SET_PROBLEM_DONE)(int *ierr) {
  *ierr = ADLB_Set_problem_done();
}

void ADLB_FC_GLOBAL(adlb_set_no_more_work, ADLB_SET_NO_MORE_WORK)(int *ierr) {
  *ierr = ADLB_Set_no_more_work();
}

void ADLB_FC_GLOBAL(adlb_info_get, ADLB_INFO_GET)(int *key, double *value,
                                                  int *ierr) {
  *ierr = ADLB_Info_get(*key, value);
}

void ADLB_FC_GLOBAL(adlb_info_num_work_units,
                    ADLB_INFO_NUM_WORK_UNITS)(int *work_type, int *num_units,
                                              int *num_bytes,
                                              int *max_wq_count, int *ierr) {
  *ierr = ADLB_Info_num_work_units(*work_type, num_units, num_bytes,
                                   max_wq_count);
}

void ADLB_FC_GLOBAL(adlb_finalize, ADLB_FINALIZE)(int *ierr) {
  *ierr = ADLB_Finalize();
}

void ADLB_FC_GLOBAL(adlb_abort, ADLB_ABORT)(int *code, int *ierr) {
  *ierr = ADLB_Abort(*code);
}

void ADLB_FC_GLOBAL(adlb_world_rank, ADLB_WORLD_RANK)(int *rank) {
  *rank = ADLB_World_rank();
}

void ADLB_FC_GLOBAL(adlb_world_size, ADLB_WORLD_SIZE)(int *size) {
  *size = ADLB_World_size();
}

#ifdef __cplusplus
} /* extern "C" */
#endif
